"""Brute-force kNN tests vs naive reference (reference test model:
cpp/internal/raft_internal/neighbors/naive_knn.cuh:82 + recall thresholds
in cpp/test/neighbors/ann_utils.cuh)."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.neighbors import brute_force


def naive_knn(x, y, k, metric="sqeuclidean", select_min=True):
    d = cdist(x, y, metric) if metric != "ip" else -(x @ y.T)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


def recall(got_idx, ref_idx):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got_idx, ref_idx))
    return hits / ref_idx.size


@pytest.mark.parametrize("metric,scipy_metric", [
    ("sqeuclidean", "sqeuclidean"),
    ("euclidean", "euclidean"),
    ("cosine", "cosine"),
])
def test_knn_exact(rng, metric, scipy_metric):
    x = rng.random((500, 32), dtype=np.float32)
    q = rng.random((40, 32), dtype=np.float32)
    idx = brute_force.build(jnp.asarray(x), metric=metric)
    dists, ids = brute_force.knn(idx, jnp.asarray(q), k=10)
    ref_d, ref_i = naive_knn(q, x, 10, scipy_metric)
    assert recall(np.asarray(ids), ref_i) >= 0.99
    np.testing.assert_allclose(np.sort(np.asarray(dists), 1),
                               np.sort(ref_d, 1), rtol=1e-3, atol=1e-4)


def test_knn_inner_product(rng):
    x = rng.random((300, 16), dtype=np.float32)
    q = rng.random((20, 16), dtype=np.float32)
    dists, ids = brute_force.knn_arrays(jnp.asarray(x), jnp.asarray(q), 5,
                                        metric="inner_product")
    sims = q @ x.T
    ref_i = np.argsort(-sims, axis=1)[:, :5]
    assert recall(np.asarray(ids), ref_i) >= 0.99


def test_knn_tiled_matches_untiled(rng, monkeypatch):
    """Force the scan-tiled path and check it agrees with one-shot."""
    from raft_tpu.neighbors import brute_force as bf

    x = rng.random((1000, 24), dtype=np.float32)
    q = rng.random((30, 24), dtype=np.float32)
    d1, i1 = bf.knn_arrays(jnp.asarray(x), jnp.asarray(q), 10)
    monkeypatch.setattr(bf, "_TILE_BUDGET_ELEMS", 30 * 128)
    d2, i2 = bf.knn_arrays(jnp.asarray(x), jnp.asarray(q), 10)
    np.testing.assert_allclose(np.sort(np.asarray(d1), 1),
                               np.sort(np.asarray(d2), 1), rtol=1e-5)
    assert recall(np.asarray(i2), np.asarray(i1)) >= 0.999


def test_knn_general_metric(rng):
    x = rng.random((200, 8), dtype=np.float32)
    q = rng.random((10, 8), dtype=np.float32)
    dists, ids = brute_force.knn_arrays(jnp.asarray(x), jnp.asarray(q), 5,
                                        metric="cityblock")
    ref_d, ref_i = naive_knn(q, x, 5, "cityblock")
    assert recall(np.asarray(ids), ref_i) >= 0.99


def test_validation(rng):
    x = jnp.zeros((10, 4))
    idx = brute_force.build(x)
    from raft_tpu.core import LogicError
    with pytest.raises(LogicError):
        brute_force.knn(idx, jnp.zeros((3, 5)), 2)  # dim mismatch
    with pytest.raises(LogicError):
        brute_force.knn(idx, jnp.zeros((3, 4)), 11)  # k > n


def test_tiled_bins_path_matches_exact(rng, monkeypatch):
    """Force the multi-tile scan (strided-bin cut) and compare with the
    guaranteed-exact per-tile selection and numpy."""
    from raft_tpu.neighbors import brute_force as bf

    monkeypatch.setattr(bf, "_TILE_BUDGET_ELEMS", 1 << 16)
    x = rng.random((3000, 24), dtype=np.float32)
    q = rng.random((40, 24), dtype=np.float32)
    idx = bf.build(jnp.asarray(x), metric="sqeuclidean")
    v1, i1 = bf.knn(idx, jnp.asarray(q), 10)
    v2, i2 = bf.knn(idx, jnp.asarray(q), 10, impl="sort")
    d = ((q[:, None, :] - x[None]) ** 2).sum(-1)
    gt = np.sort(d, axis=1)[:, :10]
    np.testing.assert_allclose(np.asarray(v1), gt, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(v2), gt, rtol=1e-4, atol=1e-4)
    # ip metric through the bins path too
    idx_ip = bf.build(jnp.asarray(x), metric="inner_product")
    vip, iip = bf.knn(idx_ip, jnp.asarray(q), 10)
    sip = q @ x.T
    np.testing.assert_allclose(np.asarray(vip),
                               -np.sort(-sip, axis=1)[:, :10],
                               rtol=1e-4, atol=1e-4)
