"""Lanczos + Boruvka MST vs scipy/numpy references
(reference tests: cpp/test/sparse/mst.cu, cpp/test/sparse/solver/lanczos.cu).
"""

import numpy as np
import pytest
import scipy.sparse as sp
import scipy.sparse.csgraph as csgraph

from raft_tpu import sparse
from raft_tpu.sparse import ops as sops
from raft_tpu.sparse.solver import lanczos_eigsh, mst


def _sym_graph(n, density, seed, unique=True):
    rs = np.random.RandomState(seed)
    a = sp.random(n, n, density=density, random_state=rs, format="coo", dtype=np.float32)
    a.data = np.abs(a.data) + 0.01
    if unique:
        a.data = a.data + rs.permutation(a.data.size).astype(np.float32) * 1e-4
    coo = sparse.make_coo(a.row, a.col, a.data, (n, n))
    return sops.symmetrize(coo, mode="max")


@pytest.mark.parametrize("n,density,seed", [(30, 0.3, 0), (100, 0.1, 1), (64, 0.5, 2)])
def test_mst_weight_matches_scipy(n, density, seed):
    adj = _sym_graph(n, density, seed)
    ref = csgraph.minimum_spanning_tree(sparse.to_scipy(adj))
    got = mst(adj)
    n_comp, _ = csgraph.connected_components(sparse.to_scipy(adj), directed=False)
    assert got.n_edges == n - n_comp
    np.testing.assert_allclose(got.weights.sum(), ref.sum(), rtol=1e-5)


def test_mst_tied_weights_acyclic():
    # all-equal weights: tie-break must still produce a spanning tree
    n = 40
    rs = np.random.RandomState(3)
    a = sp.random(n, n, density=0.3, random_state=rs, format="coo", dtype=np.float32)
    a.data = np.ones_like(a.data)
    adj = sops.symmetrize(sparse.make_coo(a.row, a.col, a.data, (n, n)), mode="max")
    n_comp, _ = csgraph.connected_components(sparse.to_scipy(adj), directed=False)
    got = mst(adj)
    assert got.n_edges == n - n_comp
    # spanning forest: selected edges must connect everything (same n_comp)
    forest = sp.coo_matrix((got.weights, (got.src, got.dst)), shape=(n, n))
    fc, _ = csgraph.connected_components(forest, directed=False)
    assert fc == n_comp


def test_mst_disconnected_forest():
    # two cliques, no bridge
    n = 20
    rows, cols = [], []
    for block in (range(0, 10), range(10, 20)):
        for i in block:
            for j in block:
                if i < j:
                    rows.append(i)
                    cols.append(j)
    w = np.arange(1, len(rows) + 1, dtype=np.float32)
    adj = sops.symmetrize(sparse.make_coo(rows, cols, w, (n, n)), mode="max")
    got = mst(adj)
    assert got.n_edges == n - 2
    ref = csgraph.minimum_spanning_tree(sparse.to_scipy(adj))
    np.testing.assert_allclose(got.weights.sum(), ref.sum(), rtol=1e-6)
    # colors: two components
    assert len(np.unique(got.color)) == 2


@pytest.mark.parametrize("which", ["smallest", "largest"])
def test_lanczos_eigsh(which):
    adj = _sym_graph(60, 0.2, 5)
    lap = sparse.linalg.laplacian(adj, normalized=True)
    dense = np.asarray(sparse.to_dense(lap), dtype=np.float64)
    want = np.linalg.eigvalsh(dense)
    k = 4
    vals, vecs = lanczos_eigsh(lap, k, which=which, max_iter=60)
    vals = np.asarray(vals, dtype=np.float64)
    if which == "smallest":
        np.testing.assert_allclose(vals, want[:k], atol=2e-3)
    else:
        np.testing.assert_allclose(vals, want[::-1][:k], atol=2e-3)
    # residual check ||Av - λv||
    for i in range(k):
        v = np.asarray(vecs[:, i], dtype=np.float64)
        r = dense @ v - vals[i] * v
        assert np.linalg.norm(r) < 5e-3


def test_lanczos_k_too_big():
    adj = _sym_graph(10, 0.5, 7)
    lap = sparse.linalg.laplacian(adj)
    with pytest.raises(ValueError):
        lanczos_eigsh(lap, 10)


def test_lanczos_deflation_complete_graph():
    """Krylov exhaustion (few distinct eigenvalues) must not yield
    spurious zero eigenpairs (review regression): normalized Laplacian of
    K_12 has eigenvalues {0, 13/12 x11}."""
    n = 12
    rows, cols = np.nonzero(~np.eye(n, dtype=bool))
    adj = sparse.coo_to_csr(sparse.make_coo(rows, cols, np.ones(rows.size, np.float32), (n, n)))
    lap = sparse.linalg.laplacian(adj, normalized=True)
    vals, vecs = lanczos_eigsh(lap, 4, which="smallest", max_iter=32)
    vals = np.asarray(vals, dtype=np.float64)
    want = np.linalg.eigvalsh(np.asarray(sparse.to_dense(lap), dtype=np.float64))[:4]
    np.testing.assert_allclose(vals, want, atol=5e-3)
    assert (np.linalg.norm(np.asarray(vecs), axis=0) > 0.9).all()
