"""select_k correctness vs reference sort (reference test model:
cpp/test/matrix/select_k.cu — compare against a host sort)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.matrix import select_k, merge_parts


def _ref_select(scores, k, select_min):
    order = np.argsort(scores, axis=1, kind="stable")
    if not select_min:
        order = order[:, ::-1]
    idx = order[:, :k]
    vals = np.take_along_axis(scores, idx, axis=1)
    return vals, idx


@pytest.mark.parametrize("batch,length,k", [(1, 10, 3), (7, 100, 10),
                                            (16, 1000, 32), (3, 257, 257)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_matches_sort(rng, batch, length, k, select_min):
    scores = rng.random((batch, length), dtype=np.float32)
    vals, idx = select_k(jnp.asarray(scores), k, select_min=select_min)
    ref_vals, _ = _ref_select(scores, k, select_min)
    np.testing.assert_allclose(np.sort(np.asarray(vals), 1),
                               np.sort(ref_vals, 1), rtol=1e-6)
    # returned indices must address the returned values
    np.testing.assert_allclose(
        np.take_along_axis(scores, np.asarray(idx), axis=1),
        np.asarray(vals), rtol=1e-6)


@pytest.mark.parametrize("length,tile", [(1000, 128), (513, 100), (2048, 2048)])
def test_select_k_tiled_matches(rng, length, tile):
    scores = rng.random((5, length), dtype=np.float32)
    v1, i1 = select_k(jnp.asarray(scores), 17, len_tile=tile)
    v2, i2 = select_k(jnp.asarray(scores), 17)
    np.testing.assert_allclose(np.sort(np.asarray(v1), 1),
                               np.sort(np.asarray(v2), 1), rtol=1e-6)


def test_select_k_input_indices(rng):
    scores = rng.random((4, 50), dtype=np.float32)
    ids = rng.integers(0, 10_000, (4, 50))
    vals, idx = select_k(jnp.asarray(scores), 5,
                         input_indices=jnp.asarray(ids))
    ref_vals, ref_pos = _ref_select(scores, 5, True)
    np.testing.assert_allclose(np.sort(np.asarray(vals), 1),
                               np.sort(ref_vals, 1), rtol=1e-6)
    ref_ids = np.take_along_axis(ids, ref_pos, axis=1)
    np.testing.assert_array_equal(np.sort(np.asarray(idx), 1),
                                  np.sort(ref_ids, 1))


def test_merge_parts(rng):
    # simulate 3 shards each holding local top-4 with global ids
    full = rng.random((2, 30), dtype=np.float32)
    parts_v, parts_i = [], []
    for s in range(3):
        chunk = full[:, s * 10:(s + 1) * 10]
        v, i = _ref_select(chunk, 4, True)
        parts_v.append(v)
        parts_i.append(i + s * 10)
    pv = jnp.asarray(np.stack(parts_v))
    pi = jnp.asarray(np.stack(parts_i))
    vals, idx = merge_parts(pv, pi, k=5)
    ref_vals, ref_idx = _ref_select(full, 5, True)
    np.testing.assert_allclose(np.sort(np.asarray(vals), 1),
                               np.sort(ref_vals, 1), rtol=1e-6)
    np.testing.assert_array_equal(np.sort(np.asarray(idx), 1),
                                  np.sort(ref_idx, 1))


def test_k_too_large_raises(rng):
    with pytest.raises(ValueError):
        select_k(jnp.zeros((2, 5)), 6)


def test_large_k_auto_tier_matches_sort(rng):
    """k > 64 on wide rows auto-dispatches to the tiled two-phase path
    (reference: the radix large-k tier, select_radix.cuh) — results must
    match the full sort."""
    from raft_tpu.matrix.select_k import select_k as sk

    s = rng.random((8, 1 << 17), dtype=np.float32)
    v1, i1 = sk(jnp.asarray(s), 128)
    ref = np.sort(s, axis=1)[:, :128]
    np.testing.assert_allclose(np.asarray(v1), ref, rtol=1e-6)
    got = np.take_along_axis(s, np.asarray(i1), axis=1)
    np.testing.assert_allclose(got, ref, rtol=1e-6)
