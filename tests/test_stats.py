"""stats tests vs sklearn/scipy (reference test model: cpp/test/stats/ +
pylibraft validations vs sklearn)."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats as sps
from sklearn import metrics as skm

from raft_tpu import stats


@pytest.fixture()
def labels(rng):
    a = rng.integers(0, 4, 200)
    b = rng.integers(0, 4, 200)
    return a, b


class TestDescriptive:
    def test_mean_var_std(self, rng):
        x = rng.random((50, 8), dtype=np.float32)
        mu, var = stats.meanvar(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(mu), x.mean(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(var), x.var(0, ddof=1), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(stats.stddev(jnp.asarray(x))),
                                   x.std(0, ddof=1), rtol=1e-4)

    def test_cov(self, rng):
        x = rng.random((100, 5), dtype=np.float32)
        np.testing.assert_allclose(np.asarray(stats.cov(jnp.asarray(x))),
                                   np.cov(x, rowvar=False), rtol=1e-3,
                                   atol=1e-5)

    def test_histogram(self, rng):
        x = rng.random(1000).astype(np.float32)
        got = np.asarray(stats.histogram(jnp.asarray(x), 10, 0.0, 1.0))
        ref, _ = np.histogram(x, bins=10, range=(0, 1))
        # edge-bin rounding can differ by ±1
        np.testing.assert_allclose(got, ref, atol=1)

    def test_weighted_mean_minmax(self, rng):
        x = rng.random((30, 4), dtype=np.float32)
        w = rng.random(30).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(stats.weighted_mean(jnp.asarray(x), jnp.asarray(w))),
            (x * w[:, None]).sum(0) / w.sum(), rtol=1e-4)
        lo, hi = stats.minmax(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(lo), x.min(0))
        np.testing.assert_allclose(np.asarray(hi), x.max(0))


class TestClusteringMetrics:
    def test_rand_and_adjusted_rand(self, labels):
        a, b = labels
        np.testing.assert_allclose(
            float(stats.adjusted_rand_index(jnp.asarray(a), jnp.asarray(b), 4)),
            skm.adjusted_rand_score(a, b), atol=1e-4)

    def test_mutual_info(self, labels):
        a, b = labels
        np.testing.assert_allclose(
            float(stats.mutual_info_score(jnp.asarray(a), jnp.asarray(b), 4)),
            skm.mutual_info_score(a, b), atol=1e-4)

    def test_entropy(self, labels):
        a, _ = labels
        counts = np.bincount(a)
        np.testing.assert_allclose(
            float(stats.entropy(jnp.asarray(a), 4)),
            sps.entropy(counts / counts.sum()), atol=1e-4)

    def test_homogeneity_completeness_v(self, labels):
        a, b = labels
        h = float(stats.homogeneity_score(jnp.asarray(a), jnp.asarray(b), 4))
        c = float(stats.completeness_score(jnp.asarray(a), jnp.asarray(b), 4))
        v = float(stats.v_measure(jnp.asarray(a), jnp.asarray(b), 4))
        hr, cr, vr = skm.homogeneity_completeness_v_measure(a, b)
        np.testing.assert_allclose([h, c, v], [hr, cr, vr], atol=1e-4)

    def test_silhouette(self, rng):
        from raft_tpu.random import make_blobs
        from raft_tpu.random.rng import RngState

        x, lbl = make_blobs(300, 6, n_clusters=4, cluster_std=0.5,
                            state=RngState(5))
        got = float(stats.silhouette_score(jnp.asarray(np.asarray(x)),
                                           jnp.asarray(np.asarray(lbl)), 4))
        ref = skm.silhouette_score(np.asarray(x), np.asarray(lbl))
        np.testing.assert_allclose(got, ref, atol=1e-3)

    def test_trustworthiness(self, rng):
        from sklearn.manifold import trustworthiness as sk_trust

        x = rng.random((80, 10), dtype=np.float32)
        emb = x[:, :2] + 0.01 * rng.random((80, 2)).astype(np.float32)
        got = float(stats.trustworthiness_score(jnp.asarray(x),
                                                jnp.asarray(emb), 5))
        ref = sk_trust(x, emb, n_neighbors=5)
        np.testing.assert_allclose(got, ref, atol=1e-2)


class TestModelMetrics:
    def test_accuracy_r2(self, rng):
        y = rng.random(50).astype(np.float32)
        yh = y + 0.1 * rng.random(50).astype(np.float32)
        np.testing.assert_allclose(
            float(stats.r2_score(jnp.asarray(y), jnp.asarray(yh))),
            skm.r2_score(y, yh), atol=1e-4)
        p = rng.integers(0, 2, 50)
        np.testing.assert_allclose(
            float(stats.accuracy(jnp.asarray(p), jnp.asarray(p))), 1.0)

    def test_regression_metrics(self, rng):
        y = rng.random(50).astype(np.float32)
        yh = y + rng.normal(0, 0.1, 50).astype(np.float32)
        mae, mse, medae = stats.regression_metrics(jnp.asarray(yh), jnp.asarray(y))
        np.testing.assert_allclose(float(mae), skm.mean_absolute_error(y, yh),
                                   atol=1e-5)
        np.testing.assert_allclose(float(mse), skm.mean_squared_error(y, yh),
                                   atol=1e-5)

    def test_information_criterion(self):
        ll = jnp.asarray(-120.0)
        aic = stats.information_criterion_batched(ll, 3, 100,
                                                  stats.InformationCriterion.AIC)
        np.testing.assert_allclose(float(aic), 246.0)
        bic = stats.information_criterion_batched(ll, 3, 100,
                                                  stats.InformationCriterion.BIC)
        np.testing.assert_allclose(float(bic), 240.0 + 3 * np.log(100), rtol=1e-5)

    def test_kl_divergence(self, rng):
        p = rng.random(20).astype(np.float32)
        q = rng.random(20).astype(np.float32)
        p, q = p / p.sum(), q / q.sum()
        from scipy.special import rel_entr

        np.testing.assert_allclose(
            float(stats.kl_divergence(jnp.asarray(p), jnp.asarray(q))),
            float(np.sum(rel_entr(p, q))), atol=1e-5)


class TestNeighborhoodRecall:
    def test_perfect_and_partial(self):
        ref = jnp.asarray([[0, 1, 2], [3, 4, 5]])
        got = jnp.asarray([[2, 1, 0], [3, 4, 9]])
        np.testing.assert_allclose(
            float(stats.neighborhood_recall(got, ref)), 5 / 6, atol=1e-6)

    def test_distance_ties_count(self):
        ref_i = jnp.asarray([[0, 1]])
        got_i = jnp.asarray([[0, 7]])
        ref_d = jnp.asarray([[0.0, 1.0]])
        got_d = jnp.asarray([[0.0, 1.0]])  # id 7 ties ref distance 1.0
        np.testing.assert_allclose(
            float(stats.neighborhood_recall(got_i, ref_i, got_d, ref_d)), 1.0)
