"""Distributed tests on the virtual 8-device CPU mesh (reference test model:
raft_dask/test/test_comms.py — collective self-checks per worker; here the
collectives run for real across 8 XLA host devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from raft_tpu.core.compat import shard_map
from jax.sharding import PartitionSpec as P
from scipy.spatial.distance import cdist

from raft_tpu.parallel import Comms, Op, make_mesh, replicated_knn, sharded_knn


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(axis_names=("shard",))


N_DEV = 8


class TestComms:
    """Collective correctness (reference: perform_test_comms_* trampolines,
    raft_dask/common/comms_utils.pyx:78+)."""

    def _run(self, fn, x, mesh, in_spec=P("shard"), out_spec=P("shard")):
        return shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                         out_specs=out_spec, check_vma=False)(x)

    def test_allreduce_sum(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.allreduce(v, Op.SUM), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, x.sum()))

    def test_allreduce_max_min(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.allreduce(v, Op.MAX), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, N_DEV - 1))
        out = self._run(lambda v: comms.allreduce(v, Op.MIN), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.zeros(N_DEV))

    def test_bcast(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.bcast(v, root=3), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, 3.0))

    def test_allgather(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = shard_map(lambda v: comms.allgather(v), mesh=mesh,
                        in_specs=(P("shard"),), out_specs=P("shard", None),
                        check_vma=False)(x)
        assert out.shape == (N_DEV * N_DEV, 1) or out.shape == (N_DEV, N_DEV)

    def test_reducescatter(self, mesh):
        comms = Comms("shard")
        x = jnp.ones((N_DEV * N_DEV,), jnp.float32)
        out = self._run(lambda v: comms.reducescatter(v, Op.SUM), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, N_DEV))

    def test_ring_permute(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.send_recv_ring(v, shift=1), x, mesh)
        expected = np.roll(np.arange(N_DEV, dtype=np.float32), 1)
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_allgatherv(self, mesh):
        # ragged shards: rank r holds (r % 3) + 1 valid rows in a cap-4
        # padded buffer; compacted output packs valid rows front, in
        # rank order (reference: comms_t::allgatherv, core/comms.hpp:423)
        comms = Comms("shard")
        cap = 4
        counts_h = np.array([(r % 3) + 1 for r in range(N_DEV)], np.int32)
        x = np.full((N_DEV * cap, 2), -1.0, np.float32)
        for r in range(N_DEV):
            for i in range(counts_h[r]):
                x[r * cap + i] = r * 10 + i
        out, cnts = shard_map(
            lambda v, c: comms.allgatherv(v, c[0]),
            mesh=mesh, in_specs=(P("shard"), P("shard")),
            out_specs=(P(None), P(None)), check_vma=False)(
                jnp.asarray(x), jnp.asarray(counts_h))
        total = int(counts_h.sum())
        expect = np.concatenate(
            [x[r * cap: r * cap + counts_h[r]] for r in range(N_DEV)])
        np.testing.assert_allclose(np.asarray(out)[:total], expect)
        np.testing.assert_array_equal(np.asarray(cnts), counts_h)
        # gatherv aliases the same packing
        out2, _ = shard_map(
            lambda v, c: comms.gatherv(v, c[0], root=2),
            mesh=mesh, in_specs=(P("shard"), P("shard")),
            out_specs=(P(None), P(None)), check_vma=False)(
                jnp.asarray(x), jnp.asarray(counts_h))
        np.testing.assert_allclose(np.asarray(out2)[:total], expect)

    def test_rank_size(self, mesh):
        comms = Comms("shard")
        x = jnp.zeros((N_DEV,), jnp.int32)
        out = self._run(lambda v: v + comms.get_rank(), x, mesh)
        np.testing.assert_array_equal(np.asarray(out), np.arange(N_DEV))


class TestCommsTelemetry:
    """ISSUE 5: every collective counts ops + per-rank payload bytes
    into ``comms.ops{op=...,axis=...}`` / ``comms.bytes{...}`` from
    static shape/dtype (once per trace, zero host syncs) — here run for
    real on the 8-device CPU mesh."""

    @pytest.fixture()
    def reg(self):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        yield reg
        obs.disable()
        obs.get_registry().reset()

    def _counters(self, reg):
        return reg.snapshot()["counters"]

    def test_allreduce_counts_ops_and_bytes(self, mesh, reg):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)  # [1] f32 per shard
        out = shard_map(lambda v: comms.allreduce(v, Op.SUM), mesh=mesh,
                        in_specs=(P("shard"),), out_specs=P("shard"),
                        check_vma=False)(x)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, x.sum()))
        c = self._counters(reg)
        assert c["comms.ops{axis=shard,op=allreduce}"] == 1.0
        assert c["comms.bytes{axis=shard,op=allreduce}"] == 4.0  # 1·f32

    def test_byte_totals_per_collective(self, mesh, reg):
        comms = Comms("shard")
        # [16, 2] f32 per shard = 128 payload bytes; fixed-size-result
        # verbs count the payload, the gather family counts the
        # size×payload table it materializes over the interconnect
        x = jnp.ones((N_DEV * 16, 2), jnp.float32)

        def body(v):
            g = comms.allgather(v)                       # 8 × 128 B
            r = comms.reducescatter(
                comms.alltoall(v) + v, Op.SUM)           # 128 B each
            s = comms.send_recv_ring(v)                  # 128 B
            return (jnp.sum(g) + jnp.sum(r) + jnp.sum(s))[None]

        shard_map(body, mesh=mesh, in_specs=(P("shard"),),
                  out_specs=P("shard"), check_vma=False)(x)
        c = self._counters(reg)
        for verb, want in (("allgather", N_DEV * 128.0),
                           ("alltoall", 128.0), ("reducescatter", 128.0),
                           ("send_recv_ring", 128.0)):
            assert c[f"comms.ops{{axis=shard,op={verb}}}"] == 1.0, (verb, c)
            assert c[f"comms.bytes{{axis=shard,op={verb}}}"] == want, \
                (verb, c)

    def test_allgatherv_counts_payload_plus_count(self, mesh, reg):
        comms = Comms("shard")
        cap = 4
        x = jnp.ones((N_DEV * cap, 2), jnp.float32)
        counts = jnp.ones((N_DEV,), jnp.int32)
        shard_map(lambda v, n: comms.allgatherv(v, n[0]), mesh=mesh,
                  in_specs=(P("shard"), P("shard")),
                  out_specs=(P(None), P(None)), check_vma=False)(x, counts)
        c = self._counters(reg)
        assert c["comms.ops{axis=shard,op=allgatherv}"] == 1.0
        # gather family counts the materialized table:
        # 8 × ([4, 2] f32 rows + one i32 count) = 8 × 36
        assert c["comms.bytes{axis=shard,op=allgatherv}"] == N_DEV * 36.0

    def test_counted_once_per_trace_not_per_execution(self, mesh, reg):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        fn = shard_map(lambda v: comms.allreduce(v), mesh=mesh,
                       in_specs=(P("shard"),), out_specs=P("shard"),
                       check_vma=False)
        jfn = jax.jit(fn)
        for _ in range(3):  # 2nd/3rd call hit the jit cache: no retrace
            jax.block_until_ready(jfn(x))
        c = self._counters(reg)
        assert c["comms.ops{axis=shard,op=allreduce}"] == 1.0, c

    def test_two_axis_mesh_attributes_per_axis(self, reg):
        # DCN×ICI-shaped mesh: sub-communicator traffic must label its
        # own axis, and a WORLD (tuple-axis) collective must decompose
        # into one counted stage per constituent axis instead of the
        # old lumped dcn+ici label (the per-axis attribution the
        # MULTICHIP record and the per-axis roofline need)
        mesh2 = make_mesh(shape=(2, N_DEV // 2), axis_names=("dcn", "ici"))
        world = Comms(("dcn", "ici"))
        ici, dcn = world.comm_split("ici"), world.comm_split("dcn")

        def hier(v):
            return dcn.allreduce(ici.allreduce(v)) + world.allreduce(v)

        out = shard_map(hier, mesh=mesh2, in_specs=(P(("dcn", "ici")),),
                        out_specs=P(("dcn", "ici")), check_vma=False)(
            jnp.arange(N_DEV, dtype=jnp.float32))
        expect = 2 * N_DEV * (N_DEV - 1) // 2
        np.testing.assert_allclose(np.asarray(out),
                                   np.full(N_DEV, float(expect)))
        c = self._counters(reg)
        # one explicit sub-axis allreduce each + one per-axis stage of
        # the world allreduce = 2 ops / 2×4 B per axis; no lumped key
        assert c["comms.ops{axis=ici,op=allreduce}"] == 2.0
        assert c["comms.ops{axis=dcn,op=allreduce}"] == 2.0
        for axis in ("ici", "dcn"):
            assert c[f"comms.bytes{{axis={axis},op=allreduce}}"] == 8.0
        assert not any("dcn+ici" in key for key in c), c

    def test_world_gather_family_charges_cumulative_stages(self, reg):
        # gather-family payload grows as it climbs the hierarchy: the
        # inner stage materializes size(inner)×payload, the outer stage
        # ships THAT times size(outer) — the byte model that keeps a
        # world allgather honest about what actually crosses DCN
        mesh2 = make_mesh(shape=(2, N_DEV // 2), axis_names=("dcn", "ici"))
        world = Comms(("dcn", "ici"))
        shard_map(lambda v: jnp.sum(world.allgather(v))[None],
                  mesh=mesh2, in_specs=(P(("dcn", "ici")),),
                  out_specs=P(("dcn", "ici")), check_vma=False)(
            jnp.arange(N_DEV, dtype=jnp.float32))
        c = self._counters(reg)
        # payload 4 B: ici stage 4×4 = 16, dcn stage 16×2 = 32
        assert c["comms.bytes{axis=ici,op=allgather}"] == 16.0
        assert c["comms.bytes{axis=dcn,op=allgather}"] == 32.0

    def test_sharded_knn_and_distributed_kmeans_count(self, mesh, reg,
                                                      rng):
        # the dryrun legs must leave nonzero comm counters (the
        # MULTICHIP acceptance): sharded kNN merges via allgather,
        # distributed kmeans merges via allreduce
        from raft_tpu.cluster import KMeansParams
        from raft_tpu.cluster import distributed as dkm

        x = jnp.asarray(rng.random((64, 8), dtype=np.float32))
        q = jnp.asarray(rng.random((4, 8), dtype=np.float32))
        sharded_knn(x, q, 3, mesh)
        dkm.fit(KMeansParams(n_clusters=4, max_iter=2, seed=0), x, mesh)
        c = self._counters(reg)
        assert c.get("comms.ops{axis=shard,op=allgather}", 0) >= 2.0, c
        assert c.get("comms.ops{axis=shard,op=allreduce}", 0) >= 3.0, c
        assert c.get("comms.bytes{axis=shard,op=allreduce}", 0) > 0, c


class TestCollectiveSchedule:
    """Distributed entry points gated by the collective-schedule
    checker (raft_tpu.obs.sanitize): the schedule each traced program
    commits every device to must be conditional-free-or-uniform, and
    must contain the collectives the telemetry attributes."""

    def _flat(self, sched):
        for e in sched:
            if len(e) == 2:  # ("while"|"scan", inner)
                yield from self._flat(e[1])
            else:
                yield e

    def test_sharded_knn_schedule_uniform(self, mesh, rng):
        from raft_tpu.obs import sanitize

        x = jnp.asarray(rng.random((64, 8), dtype=np.float32))
        q = jnp.asarray(rng.random((4, 8), dtype=np.float32))
        sched = sanitize.assert_uniform_collective_schedule(
            lambda: sharded_knn(x, q, 3, mesh))
        verbs = [e[0] for e in self._flat(sched)]
        assert verbs.count("all_gather") == 2, verbs  # vals + ids merge

    def test_distributed_kmeans_schedule_uniform(self, mesh, rng):
        from raft_tpu.cluster import KMeansParams
        from raft_tpu.cluster import distributed as dkm
        from raft_tpu.obs import sanitize

        x = jnp.asarray(rng.random((64, 8), dtype=np.float32))
        sched = sanitize.assert_uniform_collective_schedule(
            lambda: dkm.fit(KMeansParams(n_clusters=4, max_iter=2,
                                         seed=0), x, mesh))
        verbs = [e[0] for e in self._flat(sched)]
        # sums + counts + inertia psums per Lloyd iteration
        assert verbs.count("psum") >= 3, verbs


class TestShardedKnn:
    def test_sharded_matches_naive(self, mesh, rng):
        x = rng.random((803, 16), dtype=np.float32)  # non-divisible by 8
        q = rng.random((27, 16), dtype=np.float32)
        vals, ids = sharded_knn(jnp.asarray(x), jnp.asarray(q), 10, mesh)
        full = cdist(q, x, "sqeuclidean")
        ref_i = np.argsort(full, 1)[:, :10]
        hits = sum(len(set(g) & set(r)) for g, r in
                   zip(np.asarray(ids), ref_i))
        assert hits / ref_i.size >= 0.99
        np.testing.assert_allclose(
            np.sort(np.asarray(vals), 1),
            np.sort(np.take_along_axis(full, ref_i, 1), 1),
            rtol=1e-3, atol=1e-4)

    def test_replicated_matches_naive(self, mesh, rng):
        x = rng.random((200, 16), dtype=np.float32)
        q = rng.random((53, 16), dtype=np.float32)  # non-divisible by 8
        vals, ids = replicated_knn(jnp.asarray(x), jnp.asarray(q), 5, mesh)
        full = cdist(q, x, "sqeuclidean")
        ref_i = np.argsort(full, 1)[:, :5]
        hits = sum(len(set(g) & set(r)) for g, r in
                   zip(np.asarray(ids), ref_i))
        assert hits / ref_i.size >= 0.99


class TestHierMerge:
    """The ISSUE-19 two-level merge: per-pod ring over ICI, one sparse
    survivor exchange over DCN — identity with the flat tiers, the
    O(k·pods) DCN byte model, and the dispatch/validation surface."""

    @pytest.fixture
    def reg(self):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        yield reg
        obs.disable()

    def _counters(self, reg):
        return reg.snapshot()["counters"]

    @pytest.mark.parametrize("dcn_size,ici_size", [(2, 4), (4, 2)])
    def test_hier_matches_flat_bit_identically(self, mesh, rng,
                                               dcn_size, ici_size):
        from raft_tpu.parallel import hier_mesh

        x = jnp.asarray(rng.random((1024, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((96, 16), dtype=np.float32))
        fv, fi = sharded_knn(x, q, 10, mesh, merge="allgather")
        mesh2 = hier_mesh(ici_size, dcn_size)
        hv, hi = sharded_knn(x, q, 10, mesh2, axis=("dcn", "ici"))
        assert np.array_equal(np.asarray(fi), np.asarray(hi))
        assert np.array_equal(np.asarray(fv), np.asarray(hv))

    def test_hier_dcn_bytes_match_survivor_model(self, rng, reg):
        from raft_tpu.parallel import hier_chunk_rows, hier_mesh

        m, k, n_inner, n_outer = 96, 10, 4, 2
        x = jnp.asarray(rng.random((1024, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((m, 16), dtype=np.float32))
        mesh2 = hier_mesh(n_inner, n_outer)
        sharded_knn(x, q, k, mesh2, axis=("dcn", "ici"))
        c = self._counters(reg)
        assert c["parallel.merge.dispatch{impl=hier}"] == 1.0
        mc = hier_chunk_rows(m, n_inner, n_outer)
        # k survivors per pod × owned sub-chunk rows, f32 vals + i32 ids
        model = n_outer * (mc // n_outer) * k * 8
        dcn = sum(v for key, v in c.items()
                  if key.startswith("comms.bytes{") and "axis=dcn" in key)
        ici = sum(v for key, v in c.items()
                  if key.startswith("comms.bytes{") and "axis=ici" in key)
        assert dcn == model, (dcn, model, c)
        assert ici > 0, c

    def test_hier_dcn_bytes_below_flat_ring_cross_pod(self, rng):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry
        from raft_tpu.parallel import hier_mesh

        x = jnp.asarray(rng.random((1024, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((96, 16), dtype=np.float32))
        mesh2 = hier_mesh(4, 2)

        def dcn_bytes(**kw):
            reg = MetricsRegistry()
            obs.enable(registry=reg, hbm=False)
            try:
                sharded_knn(x, q, 10, mesh2, axis=("dcn", "ici"), **kw)
            finally:
                obs.disable()
            return sum(v for key, v in reg.snapshot()["counters"].items()
                       if key.startswith("comms.bytes{")
                       and "axis=dcn" in key)

        hier = dcn_bytes()
        # the topology-blind flat ring paces its whole stream cross-pod
        flat_ring = dcn_bytes(merge="ring")
        assert 0 < hier < flat_ring, (hier, flat_ring)

    def test_hier_env_off_falls_back_flat(self, rng, reg, monkeypatch):
        from raft_tpu.parallel import hier_mesh

        monkeypatch.setenv("RAFT_TPU_HIER_MERGE", "off")
        x = jnp.asarray(rng.random((256, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((16, 16), dtype=np.float32))
        mesh2 = hier_mesh(4, 2)
        fv, fi = sharded_knn(x, q, 5, mesh2, axis=("dcn", "ici"))
        c = self._counters(reg)
        assert "parallel.merge.dispatch{impl=hier}" not in c, c
        # explicit merge="hier" still overrides the env kill switch
        hv, hi = sharded_knn(x, q, 5, mesh2, axis=("dcn", "ici"),
                             merge="hier")
        assert np.array_equal(np.asarray(fi), np.asarray(hi))

    def test_merge_tier_dispatch_and_validation(self, reg):
        from raft_tpu.core.errors import LogicError
        from raft_tpu.parallel import merge_tier

        assert merge_tier(8, 256, 10,
                          hier_axes=("dcn", "ici", 2, 4)) == ("hier",
                                                              "hier")
        with pytest.raises(LogicError, match="hier"):
            merge_tier(8, 256, 10, explicit="hier")  # 1-D exchange
        c = self._counters(reg)
        assert c["parallel.merge.dispatch{impl=hier}"] == 1.0

    def test_merge_tier_env_on_without_axes_counts_fallback(
            self, reg, monkeypatch):
        from raft_tpu.parallel import merge_tier

        monkeypatch.setenv("RAFT_TPU_HIER_MERGE", "on")
        tier, _ = merge_tier(8, 256, 10)
        assert tier != "hier"
        c = self._counters(reg)
        assert c["parallel.merge.fallback{reason=no_hier_axes}"] == 1.0

    def test_hier_mesh_validates_axis_naming(self, mesh):
        from raft_tpu.core.errors import LogicError
        from raft_tpu.parallel import hier_mesh, submesh

        with pytest.raises(ValueError, match="slow axis must be outermost"):
            hier_mesh(4, 2, axis_names=("fast", "ici"))
        with pytest.raises(ValueError, match="DCN-labeled"):
            hier_mesh(4, 2, axis_names=("dcn", "pod2"))
        with pytest.raises(ValueError, match="slow axis must be outermost"):
            submesh(mesh, 8, ("ici", "dcn"), shape=(2, 4))
        with pytest.raises(ValueError, match="explicit shape"):
            submesh(mesh, 8, ("dcn", "ici"))
        m2 = submesh(mesh, 8, ("dcn", "ici"), shape=(2, 4))
        assert dict(zip(m2.axis_names, m2.devices.shape)) == \
            {"dcn": 2, "ici": 4}

    def test_non_dcn_outer_tuple_stays_flat(self, rng, reg):
        from raft_tpu.parallel import make_mesh as mk

        # a 2-D exchange whose outer axis is NOT DCN-labeled merges
        # flat (no hier auto-escalation, no hier dispatch counter)
        mesh2 = mk(shape=(2, 4), axis_names=("rows", "cols"))
        x = jnp.asarray(rng.random((256, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((16, 16), dtype=np.float32))
        sharded_knn(x, q, 5, mesh2, axis=("rows", "cols"))
        c = self._counters(reg)
        assert "parallel.merge.dispatch{impl=hier}" not in c, c
