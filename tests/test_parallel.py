"""Distributed tests on the virtual 8-device CPU mesh (reference test model:
raft_dask/test/test_comms.py — collective self-checks per worker; here the
collectives run for real across 8 XLA host devices)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from raft_tpu.core.compat import shard_map
from jax.sharding import PartitionSpec as P
from scipy.spatial.distance import cdist

from raft_tpu.parallel import Comms, Op, make_mesh, replicated_knn, sharded_knn


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(axis_names=("shard",))


N_DEV = 8


class TestComms:
    """Collective correctness (reference: perform_test_comms_* trampolines,
    raft_dask/common/comms_utils.pyx:78+)."""

    def _run(self, fn, x, mesh, in_spec=P("shard"), out_spec=P("shard")):
        return shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                         out_specs=out_spec, check_vma=False)(x)

    def test_allreduce_sum(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.allreduce(v, Op.SUM), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, x.sum()))

    def test_allreduce_max_min(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.allreduce(v, Op.MAX), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, N_DEV - 1))
        out = self._run(lambda v: comms.allreduce(v, Op.MIN), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.zeros(N_DEV))

    def test_bcast(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.bcast(v, root=3), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, 3.0))

    def test_allgather(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = shard_map(lambda v: comms.allgather(v), mesh=mesh,
                        in_specs=(P("shard"),), out_specs=P("shard", None),
                        check_vma=False)(x)
        assert out.shape == (N_DEV * N_DEV, 1) or out.shape == (N_DEV, N_DEV)

    def test_reducescatter(self, mesh):
        comms = Comms("shard")
        x = jnp.ones((N_DEV * N_DEV,), jnp.float32)
        out = self._run(lambda v: comms.reducescatter(v, Op.SUM), x, mesh)
        np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, N_DEV))

    def test_ring_permute(self, mesh):
        comms = Comms("shard")
        x = jnp.arange(N_DEV, dtype=jnp.float32)
        out = self._run(lambda v: comms.send_recv_ring(v, shift=1), x, mesh)
        expected = np.roll(np.arange(N_DEV, dtype=np.float32), 1)
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_allgatherv(self, mesh):
        # ragged shards: rank r holds (r % 3) + 1 valid rows in a cap-4
        # padded buffer; compacted output packs valid rows front, in
        # rank order (reference: comms_t::allgatherv, core/comms.hpp:423)
        comms = Comms("shard")
        cap = 4
        counts_h = np.array([(r % 3) + 1 for r in range(N_DEV)], np.int32)
        x = np.full((N_DEV * cap, 2), -1.0, np.float32)
        for r in range(N_DEV):
            for i in range(counts_h[r]):
                x[r * cap + i] = r * 10 + i
        out, cnts = shard_map(
            lambda v, c: comms.allgatherv(v, c[0]),
            mesh=mesh, in_specs=(P("shard"), P("shard")),
            out_specs=(P(None), P(None)), check_vma=False)(
                jnp.asarray(x), jnp.asarray(counts_h))
        total = int(counts_h.sum())
        expect = np.concatenate(
            [x[r * cap: r * cap + counts_h[r]] for r in range(N_DEV)])
        np.testing.assert_allclose(np.asarray(out)[:total], expect)
        np.testing.assert_array_equal(np.asarray(cnts), counts_h)
        # gatherv aliases the same packing
        out2, _ = shard_map(
            lambda v, c: comms.gatherv(v, c[0], root=2),
            mesh=mesh, in_specs=(P("shard"), P("shard")),
            out_specs=(P(None), P(None)), check_vma=False)(
                jnp.asarray(x), jnp.asarray(counts_h))
        np.testing.assert_allclose(np.asarray(out2)[:total], expect)

    def test_rank_size(self, mesh):
        comms = Comms("shard")
        x = jnp.zeros((N_DEV,), jnp.int32)
        out = self._run(lambda v: v + comms.get_rank(), x, mesh)
        np.testing.assert_array_equal(np.asarray(out), np.arange(N_DEV))


class TestShardedKnn:
    def test_sharded_matches_naive(self, mesh, rng):
        x = rng.random((803, 16), dtype=np.float32)  # non-divisible by 8
        q = rng.random((27, 16), dtype=np.float32)
        vals, ids = sharded_knn(jnp.asarray(x), jnp.asarray(q), 10, mesh)
        full = cdist(q, x, "sqeuclidean")
        ref_i = np.argsort(full, 1)[:, :10]
        hits = sum(len(set(g) & set(r)) for g, r in
                   zip(np.asarray(ids), ref_i))
        assert hits / ref_i.size >= 0.99
        np.testing.assert_allclose(
            np.sort(np.asarray(vals), 1),
            np.sort(np.take_along_axis(full, ref_i, 1), 1),
            rtol=1e-3, atol=1e-4)

    def test_replicated_matches_naive(self, mesh, rng):
        x = rng.random((200, 16), dtype=np.float32)
        q = rng.random((53, 16), dtype=np.float32)  # non-divisible by 8
        vals, ids = replicated_knn(jnp.asarray(x), jnp.asarray(q), 5, mesh)
        full = cdist(q, x, "sqeuclidean")
        ref_i = np.argsort(full, 1)[:, :5]
        hits = sum(len(set(g) & set(r)) for g, r in
                   zip(np.asarray(ids), ref_i))
        assert hits / ref_i.size >= 0.99
