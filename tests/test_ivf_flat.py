"""IVF-Flat tests: recall vs naive brute force (reference test model:
cpp/test/neighbors/ann_ivf_flat/ + naive_knn; recall thresholds as in
ann_utils.cuh eval_recall)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors.ivf_flat import IndexParams, SearchParams
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState


def recall_at_k(got_ids, ref_ids):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got_ids, ref_ids))
    return hits / ref_ids.size


@pytest.fixture(scope="module")
def corpus():
    x, _ = make_blobs(5000, 32, n_clusters=50, cluster_std=1.0,
                      state=RngState(3))
    q, _ = make_blobs(100, 32, n_clusters=50, cluster_std=1.0,
                      state=RngState(4))
    return np.asarray(x), np.asarray(q)


class TestIvfFlat:
    def test_recall_l2(self, corpus):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x),
                             IndexParams(n_lists=64, kmeans_n_iters=20, seed=0))
        dists, ids = ivf_flat.search(idx, jnp.asarray(q), 10,
                                     SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.95

    def test_recall_all_probes_is_exact(self, corpus):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        dists, ids = ivf_flat.search(idx, jnp.asarray(q), 10,
                                     SearchParams(n_probes=32))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        # probing every list = exact search (modulo capped overflow lists)
        assert recall_at_k(np.asarray(ids), ref) >= 0.999
        ref_d = np.sort(np.take_along_axis(full, ref, 1), 1)
        np.testing.assert_allclose(np.sort(np.asarray(dists), 1), ref_d,
                                   rtol=1e-3, atol=1e-3)

    def test_inner_product(self, corpus):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x),
                             IndexParams(n_lists=32, metric="inner_product"))
        _, ids = ivf_flat.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        ref = np.argsort(-(q @ x.T), 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.9

    def test_euclidean_sqrt(self, corpus):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x),
                             IndexParams(n_lists=32, metric="euclidean"))
        dists, ids = ivf_flat.search(idx, jnp.asarray(q), 5, SearchParams(n_probes=32))
        full = cdist(q, x, "euclidean")
        got_sorted = np.sort(np.asarray(dists), 1)
        ref_sorted = np.sort(np.take_along_axis(
            full, np.argsort(full, 1)[:, :5], 1), 1)
        np.testing.assert_allclose(got_sorted, ref_sorted, rtol=1e-3, atol=1e-3)

    def test_query_tiling_matches(self, corpus):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        d1, i1 = ivf_flat.search(idx, jnp.asarray(q), 10,
                                 SearchParams(n_probes=8, query_tile=512))
        d2, i2 = ivf_flat.search(idx, jnp.asarray(q), 10,
                                 SearchParams(n_probes=8, query_tile=16))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_extend(self, corpus):
        x, q = corpus
        half = len(x) // 2
        idx = ivf_flat.build(jnp.asarray(x[:half]),
                             IndexParams(n_lists=32, seed=0))
        idx = ivf_flat.extend(idx, jnp.asarray(x[half:]))
        assert idx.size == len(x)
        _, ids = ivf_flat.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=32))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.95

    def test_build_empty_then_extend(self, corpus):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x),
                             IndexParams(n_lists=32, add_data_on_build=False))
        assert idx.size == 0
        idx = ivf_flat.extend(idx, jnp.asarray(x))
        assert idx.size == len(x)

    def test_serialize_roundtrip(self, corpus, tmp_path):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        path = os.path.join(tmp_path, "ivf_flat.idx")
        ivf_flat.save(idx, path)
        idx2 = ivf_flat.load(path)
        d1, i1 = ivf_flat.search(idx, jnp.asarray(q), 5, SearchParams(n_probes=8))
        d2, i2 = ivf_flat.search(idx2, jnp.asarray(q), 5, SearchParams(n_probes=8))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)

    def test_int8_data(self, corpus):
        x, q = corpus
        x8 = np.clip(x * 10, -127, 127).astype(np.int8)
        q8 = np.clip(q * 10, -127, 127).astype(np.int8)
        idx = ivf_flat.build(jnp.asarray(x8), IndexParams(n_lists=16, seed=0))
        _, ids = ivf_flat.search(idx, jnp.asarray(q8.astype(np.float32)), 10,
                                 SearchParams(n_probes=16))
        full = cdist(q8.astype(np.float32), x8.astype(np.float32), "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.9

    def test_fit_list_size_rounding(self):
        """Tiny lists round to a multiple of 8, not 128 (padding is scan
        FLOPs); big lists keep the MXU-shaped 128 rounding."""
        fit = ivf_flat._fit_list_size
        assert fit(np.array([15, 3, 9]), avg=9, cap_factor=4.0) == 16
        assert fit(np.array([5, 2]), avg=3, cap_factor=4.0) == 8
        assert fit(np.array([130, 40]), avg=85, cap_factor=4.0) == 256
        assert fit(np.array([1000, 400]), avg=700, cap_factor=4.0) == 1024
        # cap clamps a skew-hot list
        assert fit(np.array([10_000, 10]), avg=100, cap_factor=4.0) == 512

class TestPallasGroupedScan:
    """The fused Pallas grouped-scan kernel (interpret mode off-TPU) must
    agree with the XLA grouped path on every metric."""

    @pytest.mark.parametrize("metric", ["sqeuclidean", "euclidean",
                                        "inner_product", "cosine"])
    def test_pallas_grouped_matches_xla(self, corpus, metric, monkeypatch):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x),
                             IndexParams(n_lists=32, metric=metric, seed=0))
        sp = SearchParams(n_probes=16, scan_mode="grouped")
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "never")
        dx, ix = ivf_flat.search(idx, jnp.asarray(q), 10, sp)
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "always")
        dp, ip_ = ivf_flat.search(idx, jnp.asarray(q), 10, sp)
        np.testing.assert_allclose(np.asarray(dp), np.asarray(dx),
                                   rtol=1e-4, atol=1e-4)
        same = np.mean([len(set(a) & set(b)) / 10.0
                        for a, b in zip(np.asarray(ip_), np.asarray(ix))])
        assert same >= 0.99

    def test_pallas_grouped_with_filter(self, corpus, monkeypatch):
        from raft_tpu.core import bitset as bs

        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        mask = np.zeros(len(x), bool); mask[1::2] = True
        bits = bs.from_mask(jnp.asarray(mask))
        sp = SearchParams(n_probes=32, scan_mode="grouped")
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "always")
        _, ids = ivf_flat.search(idx, jnp.asarray(q), 10, sp,
                                 filter_bitset=bits)
        got = np.asarray(ids)
        assert (got[got >= 0] % 2 == 1).all()


class TestGroupedScan:
    """The list-centric batch scan (ivf_common) must agree with the
    per-query gather path on every metric."""

    @pytest.mark.parametrize("metric,probes", [
        ("sqeuclidean", 16), ("euclidean", 16),
        ("inner_product", 16), ("cosine", 16)])
    def test_grouped_matches_per_query(self, corpus, metric, probes):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x),
                             IndexParams(n_lists=32, metric=metric, seed=0))
        dg, ig = ivf_flat.search(idx, jnp.asarray(q), 10,
                                 SearchParams(n_probes=probes,
                                              scan_mode="grouped"))
        dp, ip_ = ivf_flat.search(idx, jnp.asarray(q), 10,
                                  SearchParams(n_probes=probes,
                                               scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dg), 1),
                                   np.sort(np.asarray(dp), 1),
                                   rtol=1e-4, atol=1e-4)
        # id sets must agree except where distance ties permute order
        same = np.mean([len(set(a) & set(b)) / 10.0
                        for a, b in zip(np.asarray(ig), np.asarray(ip_))])
        assert same >= 0.99

    def test_grouped_recall_l2(self, corpus):
        x, q = corpus
        from scipy.spatial.distance import cdist as _cdist
        idx = ivf_flat.build(jnp.asarray(x),
                             IndexParams(n_lists=64, kmeans_n_iters=20, seed=0))
        _, ids = ivf_flat.search(idx, jnp.asarray(q), 10,
                                 SearchParams(n_probes=16, scan_mode="grouped"))
        full = _cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.95

    def test_grouped_with_filter(self, corpus):
        x, q = corpus
        from raft_tpu.core import bitset as bs
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        # filter out even dataset rows
        mask = np.zeros(len(x), bool); mask[1::2] = True
        bits = bs.from_mask(jnp.asarray(mask))
        _, ids = ivf_flat.search(idx, jnp.asarray(q), 10,
                                 SearchParams(n_probes=32, scan_mode="grouped"),
                                 filter_bitset=bits)
        got = np.asarray(ids)
        assert (got[got >= 0] % 2 == 1).all()

    def test_grouped_skewed_batch_dropfree(self, corpus):
        """Adversarial skew: every query probes the SAME lists, so a few
        hot lists own many segments. The segmented scan must still agree
        exactly with per_query (it is drop-free by construction)."""
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        qskew = jnp.asarray(np.repeat(q[:1], 256, axis=0) +
                            np.random.default_rng(7).normal(
                                0, 1e-3, (256, x.shape[1])).astype(np.float32))
        dg, ig = ivf_flat.search(idx, qskew, 10,
                                 SearchParams(n_probes=4,
                                              scan_mode="grouped"))
        dp, ip_ = ivf_flat.search(idx, qskew, 10,
                                  SearchParams(n_probes=4,
                                               scan_mode="per_query"))
        np.testing.assert_allclose(np.asarray(dg), np.asarray(dp),
                                   rtol=1e-4, atol=1e-4)
        same = np.mean([len(set(a) & set(b)) / 10.0
                        for a, b in zip(np.asarray(ig), np.asarray(ip_))])
        assert same >= 0.99

    def test_auto_dispatch_large_batch(self, corpus):
        x, _ = corpus
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=16, seed=0))
        # large batch -> grouped; must still return sane results
        qbig = jnp.asarray(x[:512])
        d, i = ivf_flat.search(idx, qbig, 1, SearchParams(n_probes=8))
        # nearest neighbor of a dataset row is itself
        hits = (np.asarray(i)[:, 0] == np.arange(512)).mean()
        assert hits >= 0.95


class TestApproxScanSelect:
    """scan_select="approx" (TPU hardware top-k) must stay close to the
    exact grouped path — it is the documented recall-targeted fast knob."""

    def test_approx_recall_close_to_exact(self, corpus):
        x, q = corpus
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        _, ie = ivf_flat.search(idx, jnp.asarray(q), 10,
                                SearchParams(n_probes=16, scan_mode="grouped"))
        _, ia = ivf_flat.search(idx, jnp.asarray(q), 10,
                                SearchParams(n_probes=16, scan_mode="grouped",
                                             scan_select="approx"))
        ie, ia = np.asarray(ie), np.asarray(ia)
        same = np.mean([len(set(a) & set(b)) / 10.0 for a, b in zip(ie, ia)])
        assert same >= 0.9, same


    @pytest.mark.slow  # interpret-mode kernel trace; the pq segk twin stays tier-1 (tier-1 budget)
    def test_segk_kernel_path_interpret(self, corpus, monkeypatch):
        """End-to-end through the scalar-prefetch kernel path (interpret
        mode off-TPU via RAFT_TPU_PALLAS_GROUPED=always), including a
        tiny-list index (L < 128 exercises the lane padding)."""
        x, q = corpus
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "always")
        for n_lists in (32, 256):   # 256 lists over 5000 rows -> L < 128
            idx = ivf_flat.build(jnp.asarray(x),
                                 IndexParams(n_lists=n_lists, seed=0))
            _, ie = ivf_flat.search(
                idx, jnp.asarray(q), 10,
                SearchParams(n_probes=16, scan_mode="grouped"))
            _, ia = ivf_flat.search(
                idx, jnp.asarray(q), 10,
                SearchParams(n_probes=16, scan_mode="grouped",
                             scan_select="approx"))
            ie, ia = np.asarray(ie), np.asarray(ia)
            same = np.mean([len(set(a) & set(b)) / 10.0
                            for a, b in zip(ie, ia)])
            assert same >= 0.9, (n_lists, same)

    def test_segk_k_exceeds_candidates(self, monkeypatch):
        """k > n_probes*kk exercises merge_bin_results' invalid padding."""
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "always")
        rng = np.random.default_rng(0)
        x = rng.standard_normal((256, 16)).astype(np.float32)
        q = rng.standard_normal((64, 16)).astype(np.float32)
        idx = ivf_flat.build(jnp.asarray(x), IndexParams(n_lists=32, seed=0))
        d, i = ivf_flat.search(idx, jnp.asarray(q), 12,
                               SearchParams(n_probes=1, scan_mode="grouped",
                                            scan_select="approx"))
        d, i = np.asarray(d), np.asarray(i)
        assert d.shape == (64, 12)
        # slots beyond the single probed list's capacity pad with -1/inf
        assert ((i >= -1) & (i < 256)).all()
        pad = i < 0
        assert np.isinf(d[pad]).all() or not pad.any()


class TestSpill:
    def test_spill_caps_capacity_and_keeps_rows(self, rng):
        """spill=True: padded capacity is the cap (not the skewed max)
        and overflow rows land in their second-nearest list instead of
        being dropped (ivf_common.spill_assignments)."""
        import raft_tpu.neighbors.ivf_common as ic

        # skewed blobs: one center holds ~40% of rows
        centers = rng.normal(0, 30, (16, 8)).astype(np.float32)
        assign = np.where(rng.random(8000) < 0.4, 0,
                          rng.integers(1, 16, 8000))
        x = (centers[assign]
             + rng.normal(0, 0.5, (8000, 8)).astype(np.float32))
        p = ivf_flat.IndexParams(n_lists=16, spill=True,
                                 list_size_cap_factor=1.5,
                                 kmeans_n_iters=8)
        idx = ivf_flat.build(jnp.asarray(x), p)
        avg = 8000 // 16
        from raft_tpu.neighbors.ivf_flat import _lane_round
        assert idx.max_list_size == _lane_round(int(avg * 1.5))
        got = np.sort(np.asarray(idx.packed_ids)[
            np.asarray(idx.packed_ids) >= 0])
        # a few rows may overflow both choices under extreme skew, but
        # nearly everything must survive
        assert len(got) >= 7990
        assert len(np.unique(got)) == len(got)
        # search still finds true neighbors
        q = x[rng.choice(8000, 100, replace=False)]
        d, i = ivf_flat.search(idx, jnp.asarray(q), 5,
                               ivf_flat.SearchParams(n_probes=8))
        assert float(np.asarray(d)[:, 0].max()) < 1.0  # self-ish hit

    def test_spill_assignments_exact(self):
        """Unit: capacity respected, overflow moves to l2, double
        overflow gets the drop marker."""
        import jax.numpy as jnp
        import raft_tpu.neighbors.ivf_common as ic

        # list 0 gets 5 first-choice rows at cap 3 -> 2 spill to l2=1;
        # list 1 has 2 natives + 2 spills at cap 3 -> 1 double-overflow
        l1 = jnp.asarray(np.array([0, 0, 0, 0, 0, 1, 1], np.int32))
        l2 = jnp.asarray(np.array([1, 1, 1, 1, 1, 0, 0], np.int32))
        lab = np.asarray(ic.spill_assignments(l1, l2, 2, 3))
        assert (lab[:3] == 0).all()          # kept natives of list 0
        assert (lab[5:] == 1).all()          # natives of list 1 kept
        moved = lab[3:5]
        assert sorted(moved.tolist()) == [1, 2]  # one fits, one dropped
        counts = np.bincount(lab[lab < 2], minlength=2)
        assert (counts <= 3).all()
