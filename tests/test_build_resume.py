"""Checkpointed resumable build_chunked (ISSUE 7 tentpole): manifest
validation, interrupted-then-resumed bit-identity, resume counters.
The SIGTERM-subprocess variant of the interruption lives in the CI
chaos lane (ci/test_python.sh); here the interruption is an injected
error at the same ``build.chunk_encode`` fault point, which leaves the
identical on-disk checkpoint state without paying a subprocess jax
import per test."""

import json
import os

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.core.errors import LogicError
from raft_tpu.neighbors import ivf_pq
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.robust import checkpoint as ckpt
from raft_tpu.robust import faults

CHUNK = 400


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_plan()
    yield
    faults.clear_plan()
    obs.disable()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    return rng.random((2200, 24), dtype=np.float32)


def _params(**kw):
    return ivf_pq.IndexParams(n_lists=8, pq_dim=8, seed=0,
                              cache_reconstruction="never", **kw)


def _index_arrays(idx):
    return {name: np.asarray(getattr(idx, name))
            for name in ("centers", "centers_rot", "rotation",
                         "codebooks", "packed_codes", "packed_ids",
                         "packed_norms", "list_sizes")}


def _assert_identical(a, b):
    fa, fb = _index_arrays(a), _index_arrays(b)
    for name in fa:
        assert np.array_equal(fa[name], fb[name]), name


def _interrupt_build(x, d, after=3, params=None):
    """Run a checkpointed build that dies (injected error) on the
    ``after``-th encode chunk; returns the manifest it left behind."""
    faults.install_plan({"faults": [
        {"site": "build.chunk_encode", "kind": "error", "after": after}]})
    with pytest.raises(faults.FaultInjected):
        ivf_pq.build_chunked(x, params or _params(), chunk_rows=CHUNK,
                             checkpoint_dir=str(d))
    faults.clear_plan()
    with open(os.path.join(str(d), "manifest.json")) as f:
        return json.load(f)


class TestCheckpointedBuild:
    def test_fresh_checkpointed_build_matches_plain(self, data, tmp_path):
        plain = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK)
        ck = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                  checkpoint_dir=str(tmp_path))
        _assert_identical(plain, ck)
        man = json.load(open(tmp_path / "manifest.json"))
        assert man["phase"] == "done"
        assert man["chunks_done"] == man["n_chunks"] == -(-2200 // CHUNK)
        shards = sorted(f for f in os.listdir(tmp_path)
                        if f.startswith("shard_"))
        assert len(shards) == man["n_chunks"]

    def test_interrupted_then_resumed_is_identical(self, data, tmp_path):
        man = _interrupt_build(data, tmp_path, after=3)
        assert man["phase"] == "encode"
        assert 0 < man["chunks_done"] < man["n_chunks"]
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        resumed = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                       checkpoint_dir=str(tmp_path),
                                       resume=True)
        obs.disable()
        clean = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK)
        _assert_identical(resumed, clean)
        c = reg.snapshot()["counters"]
        site = "{site=ivf_pq.build_chunked}"
        assert c[f"resume.attempts{site}"] == 1.0
        assert c[f"resume.chunks_replayed{site}"] == man["chunks_done"]

    def test_interrupted_spill_build_resumes_identical(self, tmp_path):
        rng = np.random.default_rng(3)
        x = rng.random((1600, 16), dtype=np.float32)
        p = _params(spill=True, list_size_cap_factor=1.5)
        _interrupt_build(x, tmp_path, after=2, params=p)
        resumed = ivf_pq.build_chunked(x, p, chunk_rows=CHUNK,
                                       checkpoint_dir=str(tmp_path),
                                       resume=True)
        clean = ivf_pq.build_chunked(x, p, chunk_rows=CHUNK)
        _assert_identical(resumed, clean)

    def test_resume_auto_without_manifest_builds_fresh(self, data,
                                                       tmp_path):
        idx = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                   checkpoint_dir=str(tmp_path),
                                   resume="auto")
        plain = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK)
        _assert_identical(idx, plain)

    def test_resume_auto_with_manifest_resumes(self, data, tmp_path):
        man = _interrupt_build(data, tmp_path, after=2)
        resumed = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                       checkpoint_dir=str(tmp_path),
                                       resume="auto")
        clean = ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK)
        _assert_identical(resumed, clean)
        assert man["chunks_done"] >= 1


class TestManifestValidation:
    """ISSUE 7 satellite: wrong dataset sha, wrong params, truncated
    manifest, missing shard — each a clear refusal, never a silent
    partial index."""

    def test_resume_needs_checkpoint_dir(self, data):
        with pytest.raises(LogicError, match="needs checkpoint_dir"):
            ivf_pq.build_chunked(data, _params(), resume=True)

    def test_resume_true_without_manifest_refuses(self, data, tmp_path):
        with pytest.raises(LogicError, match="no build manifest"):
            ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                 checkpoint_dir=str(tmp_path),
                                 resume=True)

    def test_wrong_dataset_refuses(self, data, tmp_path):
        _interrupt_build(data, tmp_path)
        other = np.random.default_rng(99).random((2200, 24),
                                                 dtype=np.float32)
        with pytest.raises(LogicError, match="different dataset"):
            ivf_pq.build_chunked(other, _params(), chunk_rows=CHUNK,
                                 checkpoint_dir=str(tmp_path),
                                 resume=True)

    def test_wrong_build_params_refuses(self, data, tmp_path):
        _interrupt_build(data, tmp_path)
        with pytest.raises(LogicError, match="different build parameters"):
            ivf_pq.build_chunked(data, _params(pq_bits=4),
                                 chunk_rows=CHUNK,
                                 checkpoint_dir=str(tmp_path),
                                 resume=True)

    def test_wrong_chunk_rows_refuses(self, data, tmp_path):
        # chunk_rows shapes the shard layout — it is part of the params
        # fingerprint, not silently reinterpretable
        _interrupt_build(data, tmp_path)
        with pytest.raises(LogicError, match="different build parameters"):
            ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK * 2,
                                 checkpoint_dir=str(tmp_path),
                                 resume=True)

    def test_truncated_manifest_refuses(self, data, tmp_path):
        _interrupt_build(data, tmp_path)
        with open(tmp_path / "manifest.json", "r+") as f:
            raw = f.read()
            f.seek(0)
            f.truncate()
            f.write(raw[: len(raw) // 2])  # torn write simulation
        with pytest.raises(LogicError, match="not valid JSON"):
            ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                 checkpoint_dir=str(tmp_path),
                                 resume=True)

    def test_missing_shard_refuses(self, data, tmp_path):
        man = _interrupt_build(data, tmp_path, after=3)
        assert man["chunks_done"] >= 2
        os.unlink(tmp_path / "shard_000000.npz")
        with pytest.raises(LogicError, match="shard_000000.npz is missing"):
            ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                 checkpoint_dir=str(tmp_path),
                                 resume=True)

    def test_missing_quantizer_state_refuses(self, data, tmp_path):
        _interrupt_build(data, tmp_path)
        os.unlink(tmp_path / "quantizers.npz")
        with pytest.raises(LogicError, match="missing quantizers.npz"):
            ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                                 checkpoint_dir=str(tmp_path),
                                 resume=True)

    def test_bad_resume_value_rejected(self, data, tmp_path):
        with pytest.raises(LogicError, match="resume must be"):
            ivf_pq.build_chunked(data, _params(),
                                 checkpoint_dir=str(tmp_path),
                                 resume="yes please")


class TestCheckpointPrimitives:
    def test_manifest_atomicity_leaves_no_tmp(self, tmp_path):
        ck = ckpt.BuildCheckpoint(str(tmp_path))
        ck.write_manifest({"dataset_sha": "a", "params_sha": "b",
                           "phase": "train"})
        files = os.listdir(tmp_path)
        assert files == ["manifest.json"], files
        man = ck.load_manifest()
        assert man["schema"] == ckpt.SCHEMA

    def test_manifest_stamps_fingerprint_elapsed(self, data, tmp_path):
        """ISSUE 13 satellite fix: the dataset/params fingerprint is
        computed ONCE per build (fingerprints_once) and its elapsed
        seconds are stamped into every manifest write."""
        ivf_pq.build_chunked(data, _params(), chunk_rows=CHUNK,
                             checkpoint_dir=str(tmp_path))
        man = json.load(open(tmp_path / "manifest.json"))
        assert man["phase"] == "done"
        assert isinstance(man["fingerprint_s"], float)
        assert man["fingerprint_s"] >= 0

    def test_fingerprints_once_matches_parts(self):
        ds = np.random.default_rng(0).random((64, 8), dtype=np.float32)
        sha, p_sha, fp_s = ckpt.fingerprints_once(ds, {"x": 1})
        assert sha == ckpt.dataset_fingerprint(ds)
        assert p_sha == ckpt.params_fingerprint({"x": 1})
        assert fp_s >= 0

    def test_fingerprints_are_content_sensitive(self):
        rng = np.random.default_rng(0)
        a = rng.random((100, 8), dtype=np.float32)
        b = a.copy()
        b[50, 3] += 1.0
        assert ckpt.dataset_fingerprint(a) == ckpt.dataset_fingerprint(
            a.copy())
        assert ckpt.dataset_fingerprint(a) != ckpt.dataset_fingerprint(b)
        assert ckpt.params_fingerprint({"x": 1}) != \
            ckpt.params_fingerprint({"x": 2})

    def test_provider_fingerprint_sees_the_seed(self):
        # a device-chunk provider's rows are a pure function of its
        # config: a same-shape different-seed provider must fingerprint
        # differently (content samples, not attribute inspection —
        # the seed lives inside PRNG-key arrays)
        from raft_tpu.bench.dataset import DeviceSyntheticChunks

        def fp(seed):
            return ckpt.dataset_fingerprint(DeviceSyntheticChunks(
                512, 8, n_centers=10, seed=seed, chunk_rows=128))

        assert fp(1) == fp(1)
        assert fp(1) != fp(2)

    def test_device_array_fingerprint_sees_content(self):
        import jax.numpy as jnp

        x = jnp.asarray(np.random.default_rng(0).random(
            (200, 8), dtype=np.float32))
        y = x.at[50, 3].add(1.0)
        assert ckpt.dataset_fingerprint(x) != ckpt.dataset_fingerprint(y)
