"""Cost attribution (raft_tpu.obs.prof): version-tolerant Compiled
accessors, the device peak table, roofline classification, gauge
recording, the programmatic profiler bracket, and the bench runner's
cost columns (ISSUE 9 tentpole)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tpu import obs
from raft_tpu.obs import prof
from raft_tpu.obs.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def _obs_clean():
    yield
    obs.disable()
    obs.get_registry().reset()


class TestPeakTable:
    def test_known_kinds_map_to_their_entries(self):
        assert prof.peak_for_kind("TPU v4").name == "v4"
        assert prof.peak_for_kind("TPU v5e").name == "v5e"
        assert prof.peak_for_kind("TPU v5 lite").name == "v5e"
        assert prof.peak_for_kind("TPU v5p").name == "v5p"
        assert prof.peak_for_kind("cpu").name == "cpu"

    def test_unknown_kind_degrades_to_cpu_placeholder(self):
        for kind in ("", None, "TPU v9 hyperpod", "gpu"):
            peak = prof.peak_for_kind(kind)
            assert peak.name == "cpu" and peak.placeholder

    def test_ridge_is_flops_over_bw(self):
        for peak in prof.DEVICE_PEAKS.values():
            assert peak.ridge == pytest.approx(peak.flops / peak.hbm_bw)

    def test_device_peak_never_raises(self):
        # real device 0 (CPU mesh) and a broken device object
        assert prof.device_peak().name in prof.DEVICE_PEAKS

        class Broken:
            @property
            def device_kind(self):
                raise RuntimeError("backend gone")

        assert prof.device_peak(Broken()).name == "cpu"


class TestVersionTolerantAccessors:
    def test_cost_analysis_dict_and_list_shapes(self):
        class AsDict:
            def cost_analysis(self):
                return {"flops": 10.0, "bytes accessed": 4.0, "other": "x"}

        class AsList:
            def cost_analysis(self):
                return [{"flops": 10.0, "bytes accessed": 4.0}]

        for compiled in (AsDict(), AsList()):
            ca = prof.cost_analysis(compiled)
            assert ca == {"flops": 10.0, "bytes accessed": 4.0}

    def test_cost_analysis_degrades_to_empty(self):
        class Raises:
            def cost_analysis(self):
                raise NotImplementedError

        class NoneShape:
            def cost_analysis(self):
                return None

        class EmptyList:
            def cost_analysis(self):
                return []

        for compiled in (Raises(), NoneShape(), EmptyList(), object()):
            assert prof.cost_analysis(compiled) == {}

    def test_memory_analysis_object_and_dict_shapes(self):
        class Stats:
            argument_size_in_bytes = 256
            output_size_in_bytes = 128
            temp_size_in_bytes = 64

        class Holder:
            def memory_analysis(self):
                return Stats()

        ma = prof.memory_analysis(Holder())
        assert ma["argument_size_in_bytes"] == 256
        assert ma["temp_size_in_bytes"] == 64

        class AsDict:
            def memory_analysis(self):
                return {"temp_size_in_bytes": 7}

        assert prof.memory_analysis(AsDict()) == {"temp_size_in_bytes": 7}
        assert prof.memory_analysis(object()) == {}


class TestAnalyze:
    def test_real_matmul_yields_roofline_fields(self):
        n = 256
        x = jnp.ones((n, n), jnp.float32)
        cost = prof.analyze_jit(lambda a: a @ a, x)
        assert cost is not None
        assert cost.flops and cost.flops > 0
        assert cost.bytes_accessed and cost.bytes_accessed > 0
        assert cost.arithmetic_intensity == pytest.approx(
            cost.flops / cost.bytes_accessed)
        assert cost.bound in ("memory", "compute")
        assert cost.ridge > 0 and cost.peak_bw > 0 and cost.peak_flops > 0

    def test_elapsed_attribution_sets_achieved_fracs(self):
        x = jnp.ones((64, 64), jnp.float32)
        cost = prof.analyze_jit(lambda a: a @ a, x, elapsed_s=1e-3)
        assert cost.achieved_bw_frac == pytest.approx(
            (cost.bytes_accessed / 1e-3) / cost.peak_bw)
        assert cost.achieved_flops_frac == pytest.approx(
            (cost.flops / 1e-3) / cost.peak_flops)
        # no elapsed → fracs stay None
        cost2 = prof.analyze_jit(lambda a: a @ a, x)
        assert cost2.achieved_bw_frac is None
        assert cost2.attribute_elapsed(None).achieved_bw_frac is None
        assert cost2.attribute_elapsed(0.0).achieved_bw_frac is None

    def test_untraceable_callable_returns_none(self):
        def hostile(a):
            if float(a[0, 0]) > 0:  # host sync on a tracer
                return a
            return -a

        assert prof.analyze_jit(hostile, jnp.ones((2, 2))) is None

    def test_as_row_columns(self):
        x = jnp.ones((64, 64), jnp.float32)
        row = prof.analyze_jit(lambda a: a @ a, x,
                               elapsed_s=1e-3).as_row()
        assert set(row) >= {"flops", "bytes_accessed", "bound",
                            "arith_intensity", "achieved_bw_frac"}
        assert row["bound"] in ("memory", "compute")

    def test_bound_classification_against_ridge(self):
        peak = prof.DEVICE_PEAKS["cpu"]

        class Fake:
            def __init__(self, flops, bts):
                self._c = {"flops": flops, "bytes accessed": bts}

            def cost_analysis(self):
                return self._c

            def memory_analysis(self):
                return None

        lo = prof.analyze_compiled(Fake(1.0, 1e6))   # AI « ridge
        hi = prof.analyze_compiled(Fake(1e12, 1.0))  # AI » ridge
        assert lo.bound == "memory" and hi.bound == "compute"
        assert lo.arithmetic_intensity < peak.ridge < \
            hi.arithmetic_intensity


class TestRecord:
    def test_gauges_land_with_program_label(self):
        reg = MetricsRegistry()
        cost = prof.ProgramCost(
            flops=100.0, bytes_accessed=50.0, arithmetic_intensity=2.0,
            bound="memory", peak_flops=1e9, peak_bw=1e8, ridge=10.0,
        ).attribute_elapsed(1e-3)
        prof.record(cost, registry=reg, program="p1")
        g = reg.snapshot()["gauges"]
        assert g["prof.flops{program=p1}"] == 100.0
        assert g["prof.bytes{program=p1}"] == 50.0
        assert g["prof.arith_intensity{program=p1}"] == 2.0
        assert g["prof.bound{bound=memory,program=p1}"] == 1.0
        assert g["prof.achieved_bw_frac{program=p1}"] == pytest.approx(
            (50.0 / 1e-3) / 1e8)

    def test_record_sanitizes_label_hostile_program_names(self):
        # the bench context embeds a search-param dict repr; the
        # registry's name{k=v,...} rendering has no escaping, so , { }
        # must be mapped out or parse_key chokes downstream
        from tools.obsdump import parse_key

        reg = MetricsRegistry()
        prof.record(prof.ProgramCost(flops=1.0), registry=reg,
                    program="ivf_pq.n1024 {'n_probes': 8, 'k': 10}")
        (key,) = reg.snapshot()["gauges"]
        name, labels = parse_key(key)
        assert name == "prof.flops"
        assert labels == {
            "program": "ivf_pq.n1024 ('n_probes': 8; 'k': 10)"}

    def test_record_skips_missing_fields(self):
        reg = MetricsRegistry()
        prof.record(prof.ProgramCost(), registry=reg, program="empty")
        assert reg.snapshot()["gauges"] == {}

    def test_record_defaults_to_live_obs_registry(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        prof.record(prof.ProgramCost(flops=1.0), program="d")
        assert reg.snapshot()["gauges"]["prof.flops{program=d}"] == 1.0


class TestCapture:
    @pytest.mark.slow  # full capture bracket; double_start keeps a tier-1 capture arm (tier-1 budget)
    def test_bracket_runs_and_degrades(self, tmp_path):
        cap = prof.capture(str(tmp_path / "xprof"))
        assert not cap.active
        with cap as c:
            # CPU backends may or may not support profiling — either
            # the capture armed, or it degraded with the error recorded
            assert c.active or c.error is not None
            jnp.ones((8, 8)).block_until_ready()
        assert not cap.active
        # stop() after stop is a no-op
        assert cap.stop() is None

    def test_double_start_is_idempotent(self, tmp_path):
        cap = prof.capture(str(tmp_path / "x"))
        cap.start()
        state = (cap.active, cap.error)
        cap.start()
        assert (cap.active, cap.error) == state
        cap.stop()

    def test_env_default_logdir(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_XPROF_DIR", "/tmp/envdir")
        assert prof.capture().logdir == "/tmp/envdir"


@pytest.mark.slow
class TestBenchRunnerCostColumns:
    """The acceptance shape: CPU smoke bench rows carry non-null
    flops/bytes_accessed/bound (and env provenance) when the OBS
    capture runs. Marked slow (a live build + OBS capture); the CI
    obs-smoke step asserts the same columns on the real smoke record,
    and the full pytest lane there includes slow tests."""

    @pytest.fixture()
    def rows(self, monkeypatch):
        from raft_tpu.bench import runner

        monkeypatch.setenv("RAFT_TPU_BENCH_OBS", "1")
        monkeypatch.setenv("RAFT_TPU_BENCH_OBS_REPS", "2")
        cfg = {
            "dataset": {"name": "prof-smoke", "n": 1500, "dim": 32,
                        "n_queries": 80, "metric": "sqeuclidean"},
            "k": 8, "batch_size": 10_000,
            "index": [{"name": "ivf_flat.n8", "algo": "ivf_flat",
                       "build_param": {"n_lists": 8},
                       "search_params": [{"n_probes": 4}]}],
        }
        return runner.run_config(cfg, verbose=False)

    def test_rows_carry_cost_and_env(self, rows):
        assert rows, "smoke config produced no rows"
        r = rows[0]
        assert r.cost is not None
        assert r.cost["flops"] and r.cost["flops"] > 0
        assert r.cost["bytes_accessed"] and r.cost["bytes_accessed"] > 0
        assert r.cost["bound"] in ("memory", "compute")
        assert r.cost["achieved_bw_frac"] > 0
        assert r.env is not None
        assert r.env["jax"] == jax.__version__
        assert r.env["device_count"] == len(jax.devices())
        assert r.env["device_kind"] is not None

    def test_environment_stamp_is_cached_and_complete(self):
        from raft_tpu.bench import runner

        env = runner.environment_stamp()
        assert env is runner.environment_stamp()  # cached
        for key in ("jax", "jaxlib", "libtpu", "backend", "device_kind",
                    "device_count", "local_device_count", "mesh_shape"):
            assert key in env
