"""graftlint self-test: every rule fires on a known-bad snippet, every
suppression form silences exactly what it claims, and the real tree
stays clean (the CI gate's contract — ci/test_python.sh runs
``python -m tools.graftlint raft_tpu`` as a blocking step).

Pure stdlib under test — no jax import needed; snippets are linted as
source strings.
"""

import json
import os
import subprocess
import sys

import pytest

from tools import graftlint


def rules_of(findings):
    return sorted({f.rule for f in findings})


def lint(src, path="raft_tpu/neighbors/fake.py", select=None):
    return graftlint.lint_source(src, path=path, select=select)


# ---------------------------------------------------------------------------
# GL01 — host syncs in hot bodies
# ---------------------------------------------------------------------------

GL01_BAD = """
import jax
import numpy as np

@jax.jit
def f(x):
    v = x.item()
    h = np.asarray(x)
    jax.device_get(x)
    x.block_until_ready()
    s = float(x)
    return v, h, s
"""


def test_gl01_fires_on_every_sync_kind():
    findings = [f for f in lint(GL01_BAD) if f.rule == "GL01"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 5
    for needle in (".item()", "np.asarray", "jax.device_get",
                   ".block_until_ready()", "float(x)"):
        assert needle in msgs


def test_gl01_traced_and_kernel_contexts():
    src = """
from raft_tpu.core.tracing import traced

@traced("raft_tpu.x")
def entry(x):
    return x.item()

def scan_kernel(a_ref, b_ref, o_ref):
    v = float(a_ref)
    o_ref[:] = v
"""
    findings = [f for f in lint(src) if f.rule == "GL01"]
    assert len(findings) == 2
    assert any("@traced function" in f.message for f in findings)
    assert any("Pallas kernel" in f.message for f in findings)


def test_gl01_quiet_on_eager_helpers():
    src = """
import numpy as np

def host_helper(x):
    return np.asarray(x).item()
"""
    assert not [f for f in lint(src) if f.rule == "GL01"]


# ---------------------------------------------------------------------------
# GL02 — raw env flag parsing
# ---------------------------------------------------------------------------

def test_gl02_fires_on_flag_vocab_compare():
    src = """
import os

def wanted():
    force = os.environ.get("RAFT_TPU_X", "auto")
    if force == "never":
        return False
    return force == "always"
"""
    assert rules_of(lint(src)) == ["GL02"]


def test_gl02_fires_on_inline_truth_test_and_chain():
    src = """
import os

def a():
    if os.environ.get("X"):
        return 1

def b():
    return os.environ.get("Y", "").strip().lower() not in ("", "0", "no")
"""
    findings = [f for f in lint(src) if f.rule == "GL02"]
    assert len(findings) == 2


def test_gl02_quiet_on_value_reads():
    src = """
import os

def paths():
    jsonl = os.environ.get("RAFT_TPU_BENCH_OBS_JSONL")
    if jsonl:
        open(jsonl)
    n = int(os.environ.get("RAFT_TPU_BENCH_N", 1000))
    return n
"""
    assert not [f for f in lint(src) if f.rule == "GL02"]


# ---------------------------------------------------------------------------
# GL03 — recompile hazards
# ---------------------------------------------------------------------------

def test_gl03_fires_on_tracer_branch_and_unhashable_static():
    src = """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k",))
def f(x, k, opts=[1, 2]):
    if x > 0:
        return x
    return -x

@functools.partial(jax.jit, static_argnames=("opts",))
def g(x, opts=[1, 2]):
    return x
"""
    findings = [f for f in lint(src) if f.rule == "GL03"]
    assert len(findings) == 2
    assert any("traced value" in f.message for f in findings)
    assert any("unhashable" in f.message for f in findings)


def test_gl03_quiet_on_static_and_structure_branches():
    src = """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def f(x, mask, k, interpret=False):
    if interpret:
        k = k + 1
    if mask is not None:
        x = x * 1.0
    if x.ndim == 2 and k > x.shape[0]:
        return x
    return x
"""
    assert not [f for f in lint(src) if f.rule == "GL03"]


# ---------------------------------------------------------------------------
# GL04 — observability contract on public entry points
# ---------------------------------------------------------------------------

GL04_BAD = """
def build(dataset):
    return dataset

def search(index, q, k):
    return index
"""

GL04_GOOD = """
from raft_tpu.core.tracing import traced, span

@traced("raft_tpu.fake.build")
def build(dataset):
    return dataset

def search(index, q, k):
    with span("scan"):
        return index

def _private_helper(x):
    return x

def not_an_entry_verb(x):
    return x
"""


def test_gl04_fires_only_in_entry_packages():
    assert len([f for f in lint(GL04_BAD) if f.rule == "GL04"]) == 2
    # same source outside neighbors/cluster/distance: no contract
    assert not lint(GL04_BAD, path="raft_tpu/sparse/fake.py")


def test_gl04_satisfied_by_traced_or_span():
    assert not [f for f in lint(GL04_GOOD) if f.rule == "GL04"]


# ---------------------------------------------------------------------------
# GL05 — Pallas kernel constraints
# ---------------------------------------------------------------------------

GL05_BAD = """
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128

def bad_kernel(x_ref, idx_ref, o_ref):
    o_ref[:] = jnp.take(x_ref[:], idx_ref[:], axis=1)

def caller(x, idx):
    return pl.pallas_call(
        bad_kernel,
        in_specs=[
            pl.BlockSpec((8, 100), lambda i: (i, 0)),
            pl.BlockSpec(),
            pl.BlockSpec((8, _LANES), lambda i: (i, 0)),
        ],
    )(x, idx)
"""


def test_gl05_fires_on_lane_tiling_memory_space_and_gather():
    findings = [f for f in lint(GL05_BAD) if f.rule == "GL05"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 3
    assert "not a multiple of 128" in msgs
    assert "memory_space" in msgs
    assert "lane-axis gather" in msgs
    # const-resolved _LANES block and the SMEM spec are fine
    src_ok = GL05_BAD.replace("(8, 100)", "(8, 256)") \
                     .replace("pl.BlockSpec(),",
                              "pl.BlockSpec(memory_space='smem'),") \
                     .replace("jnp.take(x_ref[:], idx_ref[:], axis=1)",
                              "x_ref[:]")
    assert not [f for f in lint(src_ok) if f.rule == "GL05"]


# ---------------------------------------------------------------------------
# GL06 — collective scope / axis consistency
# ---------------------------------------------------------------------------

GL06_BAD = """
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from raft_tpu.core.compat import shard_map
from raft_tpu.parallel.comms import Comms


def merge(x):
    comms = Comms("shards")          # typo: the mesh binds "shard"
    return comms.allreduce(x)


def helper(x, axis="shard"):
    return lax.psum(x, axis)         # right axis, never shard_mapped


def run(x, mesh, axis="shard"):
    fn = shard_map(lambda v: v, mesh=mesh, in_specs=(P(axis),),
                   out_specs=P(axis), check_vma=False)
    return fn(x)
"""

GL06_GOOD = """
from jax.sharding import PartitionSpec as P
from raft_tpu.core.compat import shard_map
from raft_tpu.parallel.comms import Comms


def merge(vals, axis):
    comms = Comms(axis)              # axis-generic helper: undecidable
    return comms.allgather(vals)


def run(x, mesh, axis="shard"):
    comms = Comms(axis)

    def local(v):
        return comms.allreduce(merge(v, axis))

    fn = shard_map(local, mesh=mesh, in_specs=(P(axis),),
                   out_specs=P(), check_vma=False)
    return fn(x)
"""


def test_gl06_fires_on_unbound_axis_and_unwrapped_collective():
    findings = [f for f in lint(GL06_BAD) if f.rule == "GL06"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 3
    assert "not bound by any mesh/axis declaration" in msgs
    assert "never wrapped in (or called from) shard_map" in msgs


def test_gl06_quiet_on_wrapped_and_axis_generic_code():
    assert not [f for f in lint(GL06_GOOD) if f.rule == "GL06"]


GL06_TUPLE = """
from jax.sharding import PartitionSpec as P
from raft_tpu.core.compat import shard_map
from raft_tpu.parallel.comms import Comms
from raft_tpu.parallel.mesh import hier_mesh

HIER_AXIS_NAMES = ("dcn", "ici")


def run(x, n_outer, n_inner):
    mesh = hier_mesh(n_inner, n_outer, axis_names=HIER_AXIS_NAMES)

    def local(v):
        inner = Comms("ici")
        outer = Comms("dcn")
        return outer.allgather(inner.allreduce(v))

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(("dcn", "ici"), None),),
                   out_specs=P(("dcn", "ici"), None), check_vma=False)
    return fn(x)
"""


def test_gl06_resolves_tuple_axis_consts():
    # the 2-D mesh idiom: axis names live in a module tuple constant
    # handed to the mesh constructor — both constituent axes are bound
    assert not [f for f in lint(GL06_TUPLE) if f.rule == "GL06"]
    typo = GL06_TUPLE.replace('Comms("ici")', 'Comms("icy")')
    findings = [f for f in lint(typo) if f.rule == "GL06"]
    assert len(findings) == 1
    assert "not bound" in findings[0].message


# ---------------------------------------------------------------------------
# GL07 — static ppermute perms
# ---------------------------------------------------------------------------

GL07_BAD = """
from jax import lax


def bad_src(x):
    return lax.ppermute(x, "shard", perm=[(0, 1), (0, 2), (1, 0)])


def bad_dup(x):
    return lax.ppermute(x, "shard", perm=[(0, 1), (1, 1), (2, 0)])


def bad_drop(x):
    # shift without wraparound: rank 0 silently receives zeros
    return lax.ppermute(x, "shard", perm=[(0, 1), (1, 2), (2, 3)])


def ring_exchange(x, comms):
    perm = [(0, 1), (1, 0), (2, 3), (3, 2)]  # two 2-cycles, no ring
    return comms.ppermute(x, perm)
"""

GL07_GOOD = """
from jax import lax


def ring_step(x, comms):
    return comms.ppermute(x, [(0, 1), (1, 2), (2, 3), (3, 0)])


def dynamic(x, comms, size):
    perm = [(i, (i + 1) % size) for i in range(size)]  # not static
    return comms.ppermute(x, perm)
"""


def test_gl07_fires_on_non_permutations_and_open_rings():
    findings = [f for f in lint(GL07_BAD) if f.rule == "GL07"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 4
    assert "duplicate source" in msgs
    assert "not injective" in msgs
    assert "ZERO-FILLS" in msgs
    assert "single cycle" in msgs


def test_gl07_quiet_on_closed_ring_and_dynamic_perms():
    assert not [f for f in lint(GL07_GOOD) if f.rule == "GL07"]


# ---------------------------------------------------------------------------
# GL08 — Pallas DMA lifetime
# ---------------------------------------------------------------------------

GL08_BAD = """
from jax.experimental.pallas import tpu as pltpu


def leak_kernel(hbm_ref, o_ref, sem):
    cp = pltpu.make_async_copy(hbm_ref, o_ref, sem)
    cp.start()
    o_ref[:] = o_ref[:] * 2.0


def race_kernel(hbm_ref, o_ref, sem):
    for i in range(4):
        cp = pltpu.make_async_copy(hbm_ref.at[i], o_ref, sem)
        cp.start()
    cp.wait()


def branch_kernel(hbm_ref, o_ref, sem, flag):
    cp = pltpu.make_async_copy(hbm_ref, o_ref, sem)
    cp.start()
    if flag:
        cp.wait()


def shared_sem_kernel(a_hbm, b_hbm, o_ref, sem):
    c1 = pltpu.make_async_copy(a_hbm, o_ref.at[0], sem)
    c2 = pltpu.make_async_copy(b_hbm, o_ref.at[1], sem)
    c1.start()
    c2.start()
    c1.wait()
    c2.wait()
"""

# the gather_refine factory/queue idiom (NBUF copies in flight, waits
# in the fori_loop body) must stay quiet
GL08_GOOD = """
import jax
from jax.experimental.pallas import tpu as pltpu


def good_kernel(ids_hbm, data_hbm, o_ref, ids_smem, rows, sem_ids, sems):
    cp = pltpu.make_async_copy(ids_hbm, ids_smem, sem_ids)
    cp.start()
    cp.wait()

    def row_copy(t):
        return pltpu.make_async_copy(
            data_hbm.at[t], rows.at[t], sems.at[t % 4])

    for t in range(4):
        row_copy(t).start()

    def stream(t, carry):
        row_copy(t).wait()
        return carry

    jax.lax.fori_loop(0, 16, stream, 0)
    o_ref[:] = rows[:]
"""


def test_gl08_fires_on_every_lifetime_violation():
    findings = [f for f in lint(GL08_BAD) if f.rule == "GL08"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 4
    assert "never waited" in msgs
    assert "restarted" in msgs
    assert "all control paths" in msgs
    assert "SAME semaphore" in msgs


def test_gl08_quiet_on_the_queue_idiom():
    assert not [f for f in lint(GL08_GOOD) if f.rule == "GL08"]


# the ISSUE 11 overlap-idiom extension: factory calls with statically
# stable arguments resolve to concrete semaphore slots

GL08_FACTORY_BAD = """
from jax.experimental.pallas import tpu as pltpu


def same_sem_kernel(hbm, out, sems):
    def cp(which):
        return pltpu.make_async_copy(hbm.at[which], out.at[which],
                                     sems.at[0])
    cp(0).start()
    cp(1).start()
    cp(0).wait()
    cp(1).wait()


def loop_restart_kernel(hbm, out, sems):
    def cp(i):
        return pltpu.make_async_copy(hbm.at[i], out.at[i], sems.at[i])
    for t in range(4):
        cp(0).start()
    cp(0).wait()


def exit_unwaited_kernel(hbm, out, sems):
    def cp(i):
        return pltpu.make_async_copy(hbm.at[i], out.at[i], sems.at[i])
    cp(0).start()
    cp(0).wait()
    cp(0).start()
"""

GL08_FACTORY_GOOD = """
from jax.experimental.pallas import tpu as pltpu


def overlap_kernel(hbm, out, sems):
    # two in-flight copies on DISTINCT semaphores: the legitimate
    # pipelined schedule (the ring kernel's overlap idiom)
    def cp(i):
        return pltpu.make_async_copy(hbm.at[i], out.at[i], sems.at[i])
    cp(0).start()
    cp(1).start()
    cp(0).wait()
    cp(1).wait()


def loop_carried_kernel(hbm, out, sems):
    # slot reuse across loop-carried hops, waited before restart
    def cp(i):
        return pltpu.make_async_copy(hbm.at[i], out.at[i], sems.at[i])
    cp(0).start()
    for s in range(4):
        cp(0).wait()
        cp(0).start()
    cp(0).wait()


def rotated_kernel(hbm, out, sems):
    # dynamically-rotated slots (loop-varying args) defer to the
    # whole-tree tally — the gather-refine prologue-fill idiom
    def cp(t):
        return pltpu.make_async_copy(hbm.at[t], out.at[t],
                                     sems.at[t % 2])
    cp(0).start()
    for t in range(1, 8):
        cp(t).start()
        cp(t - 1).wait()
    cp(7).wait()
"""


def test_gl08_factory_slot_violations_fire():
    findings = [f for f in lint(GL08_FACTORY_BAD) if f.rule == "GL08"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 3, msgs
    assert "SAME semaphore" in msgs
    assert "restarted" in msgs
    assert "all control paths" in msgs


def test_gl08_factory_overlap_idiom_quiet():
    assert not [f for f in lint(GL08_FACTORY_GOOD) if f.rule == "GL08"]


# ---------------------------------------------------------------------------
# GL09 — shard_map contract
# ---------------------------------------------------------------------------

GL09_BAD = """
from jax.sharding import Mesh, PartitionSpec as P
from raft_tpu.core.compat import shard_map


def local(a, b):
    return a + b


def run(x, y, mesh, axis="shard"):
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(axis, None), P(axis), P()),
                   out_specs=P("replicas"))
    return fn(x, y)
"""


def test_gl09_fires_on_arity_and_axis_mismatch():
    findings = [f for f in lint(GL09_BAD) if f.rule == "GL09"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 2
    assert "3 entries" in msgs and "2 positional" in msgs
    assert "'replicas'" in msgs
    src_ok = GL09_BAD.replace(", P()),", "),") \
                     .replace('P("replicas")', "P(axis)")
    assert not [f for f in lint(src_ok) if f.rule == "GL09"]
    # a bare P(...) in_specs is a valid pytree PREFIX broadcast over
    # every argument — never an arity finding
    src_prefix = GL09_BAD.replace("(P(axis, None), P(axis), P()),",
                                  "P(axis, None),") \
                         .replace('P("replicas")', "P(axis)")
    assert not [f for f in lint(src_prefix) if f.rule == "GL09"]


GL09_TUPLE = """
from jax.sharding import PartitionSpec as P
from raft_tpu.core.compat import shard_map
from raft_tpu.parallel.mesh import hier_mesh

HIER_AXIS_NAMES = ("dcn", "ici")
MESH = hier_mesh(4, 2, axis_names=HIER_AXIS_NAMES)


def local(v):
    return v


def run(x):
    fn = shard_map(local, mesh=MESH,
                   in_specs=(P(HIER_AXIS_NAMES, None),),
                   out_specs=P(("dcn", "ici"), None), check_vma=False)
    return fn(x)
"""


def test_gl09_resolves_tuple_axis_consts_via_mesh_binding():
    # mesh axes come from a module-level hier_mesh binding whose
    # axis_names is a tuple constant; P() joint-sharding over the tuple
    # (literal or via the same constant) resolves against them
    assert not [f for f in lint(GL09_TUPLE) if f.rule == "GL09"]
    typo = GL09_TUPLE.replace('out_specs=P(("dcn", "ici")',
                              'out_specs=P(("dcn", "icy")')
    findings = [f for f in lint(typo) if f.rule == "GL09"]
    assert len(findings) == 1
    assert "'icy'" in findings[0].message
    assert "'dcn'" in findings[0].message and "'ici'" in findings[0].message


# ---------------------------------------------------------------------------
# GL10 — facade bypass
# ---------------------------------------------------------------------------

GL10_BAD = """
from jax import lax


def merge(vals, axis_name):
    s = lax.psum(vals, axis_name)
    m = lax.pmax(vals, axis_name)
    return s + m
"""


def test_gl10_fires_outside_the_facade_module():
    findings = [f for f in lint(GL10_BAD, path="raft_tpu/parallel/fake.py")
                if f.rule == "GL10"]
    assert len(findings) == 2
    assert all("bypasses" in f.message for f in findings)
    # the facade itself and non-raft_tpu paths are exempt
    assert not [f for f in lint(GL10_BAD,
                                path="raft_tpu/parallel/comms.py")
                if f.rule == "GL10"]
    assert not [f for f in lint(GL10_BAD, path="tools/fake.py")
                if f.rule == "GL10"]
    # axis_index carries no payload: not a bypass
    src = GL10_BAD.replace("lax.psum", "lax.axis_index") \
                  .replace("lax.pmax", "lax.axis_index")
    assert not [f for f in lint(src, path="raft_tpu/parallel/fake.py")
                if f.rule == "GL10"]


# ---------------------------------------------------------------------------
# GL11 — int-overflow hazards in id arithmetic
# ---------------------------------------------------------------------------

GL11_BAD = """
import jax.numpy as jnp
import numpy as np


def remap(ids, rank, shard_rows):
    gids = ids.astype(jnp.int32) + rank.astype(jnp.int32) * shard_rows
    return gids


def iota(n):
    row_ids = jnp.arange(n)
    return row_ids


def host_math(shard, rows):
    offs = np.int32(shard * rows)
    return offs
"""

GL11_GOOD = """
import jax.numpy as jnp
from raft_tpu.core import ids as _ids


def remap(ids, rank, shard_rows, n_total):
    return _ids.global_ids(rank, shard_rows, ids, n_total=n_total)


def iota(n):
    row_ids = _ids.make_ids(n)
    return row_ids


def small_stuff(k, dim):
    mask = jnp.arange(k)          # not an id binding: no finding
    probes = jnp.arange(dim, dtype=jnp.int32) * 2
    return mask, probes
"""


def test_gl11_fires_on_id_overflow_hazards():
    findings = [f for f in lint(GL11_BAD) if f.rule == "GL11"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 3
    assert "global-id arithmetic" in msgs
    assert "default-dtype jnp.arange" in msgs
    assert "int32()" in msgs


def test_gl11_quiet_on_policy_helpers_and_small_iotas():
    assert not [f for f in lint(GL11_GOOD) if f.rule == "GL11"]
    # host np.arange building static tables is exempt by design
    src = """
import numpy as np

def sel(S):
    s_idx = np.arange(S)
    return s_idx
"""
    assert not [f for f in lint(src) if f.rule == "GL11"]


# ---------------------------------------------------------------------------
# GL12 — accumulator narrowing
# ---------------------------------------------------------------------------

GL12_BAD = """
import jax.numpy as jnp


def lut(q, cb):
    cbq = cb.astype(jnp.bfloat16)
    d1 = jnp.einsum("sp,skp->sk", q, cbq)
    d2 = jnp.dot(q, cb.astype(jnp.float8_e4m3fn))
    acc = jnp.sum(q.astype(jnp.bfloat16))
    return d1, d2, acc
"""

GL12_GOOD = """
import jax.numpy as jnp


def lut(q, cb):
    d = jnp.einsum("sp,skp->sk", q, cb.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    up = jnp.dot(q, cb.astype(jnp.bfloat16).astype(jnp.float32),
                 preferred_element_type=jnp.float32)
    s = jnp.sum(q.astype(jnp.bfloat16), dtype=jnp.float32)
    plain = jnp.dot(q, cb)       # f32 operands: no finding
    return d, up, s, plain
"""


def test_gl12_fires_on_narrowed_contractions():
    findings = [f for f in lint(GL12_BAD) if f.rule == "GL12"]
    assert len(findings) == 3
    assert all("preferred_element_type" in f.message for f in findings)


def test_gl12_quiet_on_pinned_accumulators():
    assert not [f for f in lint(GL12_GOOD) if f.rule == "GL12"]


# ---------------------------------------------------------------------------
# GL13 — sentinel safety
# ---------------------------------------------------------------------------

GL13_BAD = """
import jax.numpy as jnp


def bad_inf(mask, ids):
    return jnp.where(mask, jnp.inf, ids)


def bad_arith(mask, raw, base):
    ids = jnp.where(mask, raw, -1)
    offs = ids + base
    return offs
"""

GL13_GOOD = """
import jax.numpy as jnp


def guarded(mask, raw, base):
    ids = jnp.where(mask, raw, -1)
    return jnp.where(ids >= 0, ids + base, -1)


def float_sentinels(mask, dists):
    return jnp.where(mask, jnp.inf, dists)  # float array: fine
"""


def test_gl13_fires_on_sentinel_misuse():
    findings = [f for f in lint(GL13_BAD) if f.rule == "GL13"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 2
    assert "upcasts ids to float" in msgs
    assert "without a >= 0 guard" in msgs


def test_gl13_quiet_on_guarded_idioms():
    assert not [f for f in lint(GL13_GOOD) if f.rule == "GL13"]


# ---------------------------------------------------------------------------
# GL14 — Pallas per-grid-step resource budgets
# ---------------------------------------------------------------------------

GL14_BAD = """
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import jax.numpy as jnp

_FAT = 4096


def kern(x_ref, o_ref):
    o_ref[:] = x_ref[:]


def fat_caller(x):
    return pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec((_FAT, 2048), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        scratch_shapes=[pltpu.SMEM((1024, 1024), jnp.int32)],
    )(x)
"""


def test_gl14_fires_on_budget_breaches():
    findings = [f for f in lint(GL14_BAD, path="raft_tpu/ops/fake.py")
                if f.rule == "GL14"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 2
    assert "VMEM footprint" in msgs and "16 MB" in msgs
    assert "SMEM-resident" in msgs
    # dynamic block shapes defer to the runtime budget: no finding
    src_ok = GL14_BAD.replace("(_FAT, 2048)", "(bq, 2048)") \
                     .replace("pltpu.SMEM((1024, 1024), jnp.int32)",
                              "pltpu.SMEM((8, 128), jnp.int32)")
    assert not [f for f in lint(src_ok, path="raft_tpu/ops/fake.py")
                if f.rule == "GL14"]
    # an over-budget SMEM-resident BLOCK fires even with no SMEM
    # scratch allocation at all (regression: the check must run after
    # the whole-function sweep, not only inside the scratch branch)
    src_blk = GL14_BAD.replace("(_FAT, 2048)", "(8, 128)") \
                      .replace(
        "in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],",
        "in_specs=[pl.BlockSpec((1024, 1024), lambda i: (i, 0),\n"
        "                       memory_space=pltpu.SMEM)],") \
                      .replace(
        "scratch_shapes=[pltpu.SMEM((1024, 1024), jnp.int32)],", "")
    blk = [f for f in lint(src_blk, path="raft_tpu/ops/fake.py")
           if f.rule == "GL14"]
    assert len(blk) == 1 and "SMEM-resident" in blk[0].message


def test_gl14_quiet_on_the_existing_kernels():
    """The three shipped streaming kernels' BlockSpecs stay under the
    static budget check (their block shapes are parameter-dynamic and
    measured VMEM-safe — the satellite acceptance case)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = graftlint.lint_paths(
        [os.path.join(root, "raft_tpu", "ops", "pallas_kernels.py")],
        select={"GL14"})
    assert not findings, "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# GL15 — streaming-tier dispatch without an admission guard
# ---------------------------------------------------------------------------

GL15_BAD = """
from raft_tpu.ops import pallas_kernels as _pk


def refine(ds, q, cand, k):
    return _pk.gather_refine_topk(ds, q, cand, k, "l2")
"""

GL15_GOOD = """
from raft_tpu.neighbors import ivf_common as ic
from raft_tpu.ops import pallas_kernels as _pk


def refine(ds, q, cand, k):
    if not ic.gather_refine_mem_ok(ds.shape[0], ds.shape[1]):
        return None
    return _pk.gather_refine_topk(ds, q, cand, k, "l2")
"""


def test_gl15_fires_on_unguarded_kernel_dispatch():
    findings = [f for f in lint(GL15_BAD) if f.rule == "GL15"]
    assert len(findings) == 1
    assert "admission guard" in findings[0].message
    # guarded module: quiet
    assert not [f for f in lint(GL15_GOOD) if f.rule == "GL15"]
    # outside raft_tpu/ (tools, tests): no contract
    assert not [f for f in lint(GL15_BAD, path="tools/fake.py")
                if f.rule == "GL15"]
    # the defining module itself is exempt
    assert not [f for f in lint(GL15_BAD,
                                path="raft_tpu/ops/pallas_kernels.py")
                if f.rule == "GL15"]


# ISSUE 12: a FILTERED fused dispatch (filter_bytes operand) is still a
# streaming-kernel dispatch — without any admission guard it fires; the
# new filtered_scan_mem_ok guard satisfies the contract (_mem_ok suffix
# registration, same convention as every tier).
GL15_FILTERED_BAD = """
from raft_tpu.ops import pallas_kernels as _pk


def filtered_scan(seg_list, qv, codes, ids, norms, ctr, cb, fbytes):
    return _pk.ivfpq_lut_scan_topk(
        seg_list, qv, codes, ids, norms, ctr, cb, "l2",
        pq_bits=8, pq_dim=16, L=1024, filter_bytes=fbytes)
"""

GL15_FILTERED_GOOD = """
from raft_tpu.neighbors import ivf_common as ic
from raft_tpu.ops import pallas_kernels as _pk


def filtered_scan(seg_list, qv, codes, ids, norms, ctr, cb, fbytes,
                  n_lists, L):
    if not ic.filtered_scan_mem_ok(n_lists, L):
        return None
    return _pk.ivfpq_lut_scan_topk(
        seg_list, qv, codes, ids, norms, ctr, cb, "l2",
        pq_bits=8, pq_dim=16, L=L, filter_bytes=fbytes)
"""


def test_gl15_filtered_dispatch_snippets():
    findings = [f for f in lint(GL15_FILTERED_BAD) if f.rule == "GL15"]
    assert len(findings) == 1, findings
    assert not [f for f in lint(GL15_FILTERED_GOOD) if f.rule == "GL15"]


# ISSUE 12: the masked-sentinel epilogue — the filter mask joins the
# validity mask BEFORE the where that pours the -1 sentinel, and any
# downstream id arithmetic keeps the >= 0 guard. Folding the filter by
# OFFSETTING sentinel-bearing ids is the bug GL13 exists for.
GL13_FILTER_EPILOGUE_BAD = """
import jax.numpy as jnp


def fold_filter_by_offset(keep, raw, base):
    ids = jnp.where(keep, raw, -1)
    gids = ids + base
    return gids
"""

GL13_FILTER_EPILOGUE_GOOD = """
import jax.numpy as jnp


def masked_sentinel_epilogue(keep, raw, key):
    valid = (raw >= 0) & keep
    ids = jnp.where(valid, raw, -1)
    key = jnp.where(valid, key, jnp.inf)
    return key, ids


def guarded_offset(keep, raw, base):
    ids = jnp.where(keep, raw, -1)
    return jnp.where(ids >= 0, ids + base, -1)
"""


def test_gl13_filter_epilogue_snippets():
    findings = [f for f in lint(GL13_FILTER_EPILOGUE_BAD)
                if f.rule == "GL13"]
    assert len(findings) == 1, findings
    assert "without a >= 0 guard" in findings[0].message
    assert not [f for f in lint(GL13_FILTER_EPILOGUE_GOOD)
                if f.rule == "GL13"]


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_line_suppression_is_per_rule():
    src = """
import os

def wanted():
    force = os.environ.get("X", "auto")  # graftlint: disable=GL02
    return force == "always"
"""
    assert not lint(src)
    # wrong rule id on the line does NOT silence GL02
    assert lint(src.replace("disable=GL02", "disable=GL01"))


def test_fn_scope_suppression():
    src = """
import jax
import numpy as np

@jax.jit
def f(x):  # graftlint: disable-fn=GL01
    return np.asarray(x), x.item()

@jax.jit
def g(x):
    return np.asarray(x)
"""
    findings = [f for f in lint(src) if f.rule == "GL01"]
    assert len(findings) == 1  # only g's — f is scope-suppressed


def test_fn_suppression_anchors_to_decorated_def():
    """Regression: disable-fn above a decorator stack (or trailing on a
    decorator line) applies to the function it documents, not to the
    decorator expression."""
    src = """
import functools
import jax
import numpy as np


# graftlint: disable-fn=GL01
@functools.partial(jax.jit, static_argnames=("k",))
def f(x, k):
    return np.asarray(x)


@jax.jit  # graftlint: disable-fn=GL01
def g(x):
    return np.asarray(x)


@jax.jit
def h(x):
    return np.asarray(x)
"""
    findings = [f for f in lint(src) if f.rule == "GL01"]
    assert len(findings) == 1
    assert "(h)" in findings[0].message
    # a TRAILING comment on the statement above the stack must NOT leak
    # into the next function
    src2 = src.replace(
        "# graftlint: disable-fn=GL01\n@functools",
        "y = 1  # graftlint: disable-fn=GL01\n@functools")
    assert len([f for f in lint(src2) if f.rule == "GL01"]) == 2


def test_disable_all():
    src = """
import os

def wanted():
    return os.environ.get("X") == "always"  # graftlint: disable=all
"""
    assert not lint(src)


def test_every_rule_has_a_suppressible_finding():
    """Meta-check: each rule id observed above responds to its own
    line suppression (guards the Finding.line anchoring)."""
    cases = {
        "GL01": (GL01_BAD, "    v = x.item()",
                 "    v = x.item()  # graftlint: disable=GL01"),
        "GL04": (GL04_BAD, "def build(dataset):",
                 "def build(dataset):  # graftlint: disable=GL04"),
        "GL07": (GL07_BAD,
                 '    return lax.ppermute(x, "shard", '
                 "perm=[(0, 1), (1, 1), (2, 0)])",
                 '    return lax.ppermute(x, "shard", '
                 "perm=[(0, 1), (1, 1), (2, 0)])"
                 "  # graftlint: disable=GL07"),
        "GL08": (GL08_BAD,
                 "    cp.start()\n    o_ref[:] = o_ref[:] * 2.0",
                 "    cp.start()  # graftlint: disable=GL08\n"
                 "    o_ref[:] = o_ref[:] * 2.0"),
        "GL10": (GL10_BAD, "    s = lax.psum(vals, axis_name)",
                 "    s = lax.psum(vals, axis_name)"
                 "  # graftlint: disable=GL10"),
        "GL11": (GL11_BAD, "    row_ids = jnp.arange(n)",
                 "    row_ids = jnp.arange(n)"
                 "  # graftlint: disable=GL11"),
        "GL13": (GL13_BAD, "    offs = ids + base",
                 "    offs = ids + base  # graftlint: disable=GL13"),
        "GL15": (GL15_BAD,
                 '    return _pk.gather_refine_topk(ds, q, cand, k, "l2")',
                 '    return _pk.gather_refine_topk(ds, q, cand, k, "l2")'
                 "  # graftlint: disable=GL15"),
        "GL16": (GL16_BAD, "        return self._total",
                 "        return self._total"
                 "  # graftlint: disable=GL16"),
        "GL17": (GL17_BAD, "    t = threading.Thread(target=fn)",
                 "    t = threading.Thread(target=fn)"
                 "  # graftlint: disable=GL17"),
        "GL18": (GL18_BAD, "    _tls.tenant = name",
                 "    _tls.tenant = name  # graftlint: disable=GL18"),
        "GL19": (GL19_BAD, '    logging.error("dumped")',
                 '    logging.error("dumped")'
                 "  # graftlint: disable=GL19"),
        "GL20": (GL20_BAD,
                 "def run_one(job):\n    fut = Future()",
                 "def run_one(job):\n"
                 "    fut = Future()  # graftlint: disable=GL20"),
    }
    for rule, (src, old, new) in cases.items():
        before = [f for f in lint(src) if f.rule == rule]
        after = [f for f in lint(src.replace(old, new)) if f.rule == rule]
        assert len(after) == len(before) - 1, rule



# ---------------------------------------------------------------------------
# GL16 — lock discipline
# ---------------------------------------------------------------------------

GL16_BAD = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self._total = 0

    def add(self, k, v):
        with self._lock:
            self._items[k] = v
            self._total += 1

    def size(self):
        return self._total

    def drop(self, k):
        self._items.pop(k, None)
"""

GL16_GOOD = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self.budget = 100
        self._name = "r"

    def add(self, k, v):
        with self._lock:
            self._items[k] = v
            self._grow_locked(k)

    def _grow_locked(self, k):
        self._items[k] = k

    def describe(self):
        return self._name, self.budget

    def busiest(self):
        with self._lock:
            return max(self._items, key=lambda k: self._items[k])
"""


def test_gl16_fires_on_unlocked_access_to_guarded_state():
    findings = [f for f in lint(GL16_BAD) if f.rule == "GL16"]
    assert len(findings) == 2
    assert any("_total" in f.message and "size" in f.message
               for f in findings)
    assert any("_items" in f.message and "drop" in f.message
               for f in findings)


def test_gl16_quiet_on_locked_helpers_constants_and_lambdas():
    """Public attrs, read-only-after-__init__ attrs, the locked-helper
    fixpoint, and inline lambdas inside a locked scope all stay quiet."""
    assert not [f for f in lint(GL16_GOOD) if f.rule == "GL16"]


# ---------------------------------------------------------------------------
# GL17 — thread lifecycle
# ---------------------------------------------------------------------------

GL17_BAD = """
import queue
import threading

def fire_and_forget(fn):
    t = threading.Thread(target=fn)
    t.start()
    return t

class Pump:
    def __init__(self, q):
        self._q = q
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                break
"""

GL17_GOOD = """
import queue
import threading

class Prefetcher:
    def __init__(self):
        self._q = queue.Queue(4)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                continue

    def close(self):
        self._stop.set()
        self._thread.join(timeout=5.0)
"""


def test_gl17_fires_on_every_lifecycle_violation():
    findings = [f for f in lint(GL17_BAD) if f.rule == "GL17"]
    assert len(findings) == 3
    msgs = " | ".join(f.message for f in findings)
    assert "daemon=" in msgs
    assert "close()/stop()" in msgs
    assert "stop flag" in msgs


def test_gl17_quiet_on_the_prefetcher_idiom():
    """The shipped ChunkPrefetcher/RowPrefetcher shape: daemon reader,
    stop event checked per iteration, timeout on the blocking get, and
    an owner close() that sets + joins."""
    assert not [f for f in lint(GL17_GOOD) if f.rule == "GL17"]


# ---------------------------------------------------------------------------
# GL18 — thread-local context hygiene
# ---------------------------------------------------------------------------

GL18_BAD = """
import threading

_tls = threading.local()

def set_tenant(name):
    _tls.tenant = name
    do_work()
"""

GL18_GOOD = """
import threading

_tls = threading.local()

class tenant_scope:
    def __init__(self, name):
        self._name = name

    def __enter__(self):
        self._prev = getattr(_tls, "tenant", None)
        _tls.tenant = self._name
        return self

    def __exit__(self, *exc):
        _tls.tenant = self._prev

def install_tenant(name):
    prev = getattr(_tls, "tenant", None)
    _tls.tenant = name
    return prev

def bump():
    _tls.n = getattr(_tls, "n", 0) + 1

def scoped(name):
    prev = install_tenant(name)
    try:
        do_work()
    finally:
        _tls.tenant = prev
"""


def test_gl18_fires_on_unrestored_tls_write():
    findings = [f for f in lint(GL18_BAD) if f.rule == "GL18"]
    assert len(findings) == 1
    assert "restore" in findings[0].message


def test_gl18_quiet_on_the_bracket_idioms():
    """The four shipped shapes: a CM whose __exit__ restores, the
    save-and-return low-level setter (trace.set_request), a pure
    self-update counter, and install + try/finally restore in one
    function."""
    assert not [f for f in lint(GL18_GOOD) if f.rule == "GL18"]


# ---------------------------------------------------------------------------
# GL19 — signal-context safety
# ---------------------------------------------------------------------------

GL19_BAD = """
import logging
import signal
import threading

_lock = threading.Lock()

def _flush(path, payload):
    with _lock:
        with open(path, "w") as f:
            f.write(payload)
    logging.error("dumped")

def _handler(num, frame):
    _flush("/tmp/x", "payload")

signal.signal(signal.SIGTERM, _handler)
"""

GL19_GOOD = """
import logging
import os
import signal
import threading

_lock = threading.RLock()

def _flush(path, payload):
    with _lock:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

def _handler(num, frame):
    _flush("/tmp/x", "payload")

signal.signal(signal.SIGTERM, _handler)

def not_on_the_signal_path():
    logging.error("fine here")
"""


def test_gl19_fires_on_non_reentrant_calls_on_signal_paths():
    findings = [f for f in lint(GL19_BAD) if f.rule == "GL19"]
    assert len(findings) == 3
    msgs = " | ".join(f.message for f in findings)
    assert "plain Lock" in msgs
    assert "logging" in msgs
    assert "torn file" in msgs


def test_gl19_quiet_on_rlock_tmp_rename_and_unreachable_code():
    assert not [f for f in lint(GL19_GOOD) if f.rule == "GL19"]


# ---------------------------------------------------------------------------
# GL20 — future resolution
# ---------------------------------------------------------------------------

GL20_BAD = """
from concurrent.futures import Future

def run_one(job):
    fut = Future()
    if job.ready:
        fut.set_result(job.run())
    fut.result()

def run_two(job):
    fut = Future()
    try:
        fut.set_result(job.run())
    except KeyError:
        pass
    fut.result()
"""

GL20_GOOD = """
from concurrent.futures import Future

def handoff(work, q):
    fut = Future()
    q.put((work, fut))
    return fut

def branches(job):
    fut = Future()
    if job.ready:
        fut.set_result(job.run())
    else:
        fut.set_exception(RuntimeError("not ready"))
    return fut

def guarded(job):
    fut = Future()
    try:
        fut.set_result(job.run())
    except Exception as exc:
        fut.set_exception(exc)
    return fut
"""


def test_gl20_fires_on_paths_that_never_resolve():
    findings = [f for f in lint(GL20_BAD) if f.rule == "GL20"]
    assert len(findings) == 2
    assert all("every path" in f.message for f in findings)


def test_gl20_quiet_on_handoff_and_all_path_resolution():
    """A future that ESCAPES (queued/returned for a consumer to
    resolve — the server submit() handoff) is the consumer's contract;
    if/else and try/except shapes that resolve every path are quiet."""
    assert not [f for f in lint(GL20_GOOD) if f.rule == "GL20"]


# ---------------------------------------------------------------------------
# --jobs parallel analysis
# ---------------------------------------------------------------------------

def test_jobs_parallel_matches_sequential():
    """--jobs fans per-file analysis over a process pool; the merged,
    sorted finding set must be byte-identical to the sequential run."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    target = os.path.join(root, "tools", "graftlint")
    seq = graftlint.lint_paths([target])
    par = graftlint.lint_paths([target], jobs=2)
    assert [f.render() for f in par] == [f.render() for f in seq]


def test_cli_jobs_flag(tmp_path):
    bad = tmp_path / "raft_tpu_mod.py"
    bad.write_text(GL16_BAD)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(bad), "--jobs", "2",
         "--format", "json"],
        capture_output=True, text=True, cwd=root)
    assert p.returncode == 1
    rows = json.loads(p.stdout)
    assert [r["rule"] for r in rows] == ["GL16", "GL16"]
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(bad), "--jobs", "-2"],
        capture_output=True, text=True, cwd=root)
    assert p.returncode == 2

# ---------------------------------------------------------------------------
# engine / CLI
# ---------------------------------------------------------------------------

def test_select_filters_rules():
    findings = lint(GL01_BAD + GL04_BAD, select={"GL04"})
    assert rules_of(findings) == ["GL04"]


def test_repo_tree_is_clean():
    """The acceptance gate: zero unsuppressed findings on raft_tpu/."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = graftlint.lint_paths([os.path.join(root, "raft_tpu")])
    assert not findings, "\n".join(f.render() for f in findings)


def test_cli_json_and_exit_codes(tmp_path):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = tmp_path / "neighbors"
    bad.mkdir()
    (bad / "mod.py").write_text(GL04_BAD)
    env = dict(os.environ, PYTHONPATH=root)
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(bad), "--format",
         "json"], capture_output=True, text=True, cwd=root, env=env)
    assert p.returncode == 1
    payload = json.loads(p.stdout)
    assert {f["rule"] for f in payload} == {"GL04"}
    (bad / "mod.py").write_text(GL04_GOOD)
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(bad)],
        capture_output=True, text=True, cwd=root, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "clean" in p.stdout


def test_cli_report_artifact(tmp_path):
    """--report writes the CI JSON artifact beside the normal output."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = tmp_path / "neighbors"
    bad.mkdir()
    (bad / "mod.py").write_text(GL04_BAD)
    report = tmp_path / "report.json"
    env = dict(os.environ, PYTHONPATH=root)
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(bad),
         "--report", str(report)],
        capture_output=True, text=True, cwd=root, env=env)
    assert p.returncode == 1
    doc = json.loads(report.read_text())
    assert doc["count"] == 2
    assert {f["rule"] for f in doc["findings"]} == {"GL04"}
    assert set(doc["rules"]) >= {"GL01", "GL06", "GL10"}


def _git(repo, *args):
    p = subprocess.run(
        ["git", "-c", "user.email=ci@test", "-c", "user.name=ci",
         *args], capture_output=True, text=True, cwd=repo)
    assert p.returncode == 0, (args, p.stdout, p.stderr)
    return p.stdout


def test_cli_changed_lints_only_modified_files(tmp_path):
    """--changed scopes the run to files modified vs merge-base(HEAD,
    main): pre-existing findings elsewhere in the tree don't block."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = tmp_path / "repo"
    pkg = repo / "raft_tpu" / "neighbors"
    pkg.mkdir(parents=True)
    (pkg / "legacy.py").write_text(GL04_BAD)   # bad, but NOT changed
    (pkg / "mod.py").write_text("def helper(x):\n    return x\n")
    _git(repo, "init", "-q")
    _git(repo, "checkout", "-q", "-b", "main")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-q", "-m", "seed")
    _git(repo, "checkout", "-q", "-b", "feature")

    env = dict(os.environ, PYTHONPATH=root)

    def run_changed(*extra):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--changed",
             *extra], capture_output=True, text=True, cwd=repo, env=env)

    # clean edit on the feature branch → exit 0 despite legacy.py
    (pkg / "mod.py").write_text("def helper(x):\n    return x + 1\n")
    _git(repo, "commit", "-aqm", "clean edit")
    p = run_changed()
    assert p.returncode == 0, p.stdout + p.stderr
    assert "1 file(s) in scope" in p.stdout

    # bad UNCOMMITTED edit → exit 1, findings only in mod.py
    (pkg / "mod.py").write_text(GL04_BAD)
    p = run_changed("--format", "json")
    assert p.returncode == 1
    payload = json.loads(p.stdout)
    assert payload and all("mod.py" in f["path"] for f in payload)

    # a brand-new UNTRACKED bad file is in scope even when the CLI runs
    # from a subdirectory (git ls-files --others is cwd-relative)
    (pkg / "mod.py").write_text("def helper(x):\n    return x + 1\n")
    (pkg / "fresh.py").write_text(GL04_BAD)
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--changed",
         os.path.join(str(repo), "raft_tpu"), "--format", "json"],
        capture_output=True, text=True, cwd=str(pkg), env=env)
    assert p.returncode == 1, p.stdout + p.stderr
    assert all("fresh.py" in f["path"] for f in json.loads(p.stdout))

    # full run (no --changed) still sees legacy.py
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "raft_tpu", "--format",
         "json"], capture_output=True, text=True, cwd=repo, env=env)
    assert p.returncode == 1
    assert any("legacy.py" in f["path"] for f in json.loads(p.stdout))


def test_cli_baseline_gates_only_new_findings(tmp_path):
    """--baseline records current findings and gates only NEW ones —
    the mechanism that lets a future rule land blocking without blanket
    suppressions (same reporter/exit codes as the plain run)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = tmp_path / "repo" / "raft_tpu" / "neighbors"
    pkg.mkdir(parents=True)
    (pkg / "mod.py").write_text(GL04_BAD)   # two legacy GL04 findings
    bl = tmp_path / "baseline.json"
    env = dict(os.environ, PYTHONPATH=root)
    repo = str(tmp_path / "repo")

    def run(*extra):
        return subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "raft_tpu",
             "--baseline", str(bl), *extra],
            capture_output=True, text=True, cwd=repo, env=env)

    # a missing baseline file is an empty baseline: everything gates
    p = run()
    assert p.returncode == 1 and "NEW finding" in p.stdout

    # record, then the gated run is clean despite the legacy findings
    p = run("--update-baseline")
    assert p.returncode == 0, p.stdout + p.stderr
    doc = json.loads(bl.read_text())
    assert doc["count"] == 2
    p = run()
    assert p.returncode == 0, p.stdout + p.stderr
    assert "2 baseline finding(s) suppressed" in p.stdout

    # line drift above a legacy finding does NOT un-baseline it...
    (pkg / "mod.py").write_text("import os\n\n\n" + GL04_BAD)
    p = run()
    assert p.returncode == 0, p.stdout + p.stderr

    # ...but a brand-new finding still gates, and only IT is reported
    (pkg / "mod.py").write_text(GL04_BAD + "\n\ndef fit(x):\n    return x\n")
    report = tmp_path / "report.json"
    p = run("--format", "json", "--report", str(report))
    assert p.returncode == 1
    payload = json.loads(p.stdout)
    assert len(payload) == 1 and "fit" in payload[0]["message"]
    rep = json.loads(report.read_text())
    assert rep["count"] == 1 and rep["baseline_suppressed"] == 2

    # --update-baseline without --baseline is a usage error
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "raft_tpu",
         "--update-baseline"],
        capture_output=True, text=True, cwd=repo, env=env)
    assert p.returncode == 2

    # --update-baseline refuses the --changed scope (recording only the
    # changed files would ERASE unchanged files' baseline entries)
    p = run("--update-baseline", "--changed")
    assert p.returncode == 2
    assert "--changed" in p.stderr

    # an update run still writes the --report artifact (full finding set)
    rep2 = tmp_path / "update_report.json"
    p = run("--update-baseline", "--report", str(rep2))
    assert p.returncode == 0
    doc2 = json.loads(rep2.read_text())
    assert doc2["count"] == 3 and doc2["baseline_suppressed"] == 0
