"""graftlint self-test: every rule fires on a known-bad snippet, every
suppression form silences exactly what it claims, and the real tree
stays clean (the CI gate's contract — ci/test_python.sh runs
``python -m tools.graftlint raft_tpu`` as a blocking step).

Pure stdlib under test — no jax import needed; snippets are linted as
source strings.
"""

import json
import os
import subprocess
import sys

import pytest

from tools import graftlint


def rules_of(findings):
    return sorted({f.rule for f in findings})


def lint(src, path="raft_tpu/neighbors/fake.py", select=None):
    return graftlint.lint_source(src, path=path, select=select)


# ---------------------------------------------------------------------------
# GL01 — host syncs in hot bodies
# ---------------------------------------------------------------------------

GL01_BAD = """
import jax
import numpy as np

@jax.jit
def f(x):
    v = x.item()
    h = np.asarray(x)
    jax.device_get(x)
    x.block_until_ready()
    s = float(x)
    return v, h, s
"""


def test_gl01_fires_on_every_sync_kind():
    findings = [f for f in lint(GL01_BAD) if f.rule == "GL01"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 5
    for needle in (".item()", "np.asarray", "jax.device_get",
                   ".block_until_ready()", "float(x)"):
        assert needle in msgs


def test_gl01_traced_and_kernel_contexts():
    src = """
from raft_tpu.core.tracing import traced

@traced("raft_tpu.x")
def entry(x):
    return x.item()

def scan_kernel(a_ref, b_ref, o_ref):
    v = float(a_ref)
    o_ref[:] = v
"""
    findings = [f for f in lint(src) if f.rule == "GL01"]
    assert len(findings) == 2
    assert any("@traced function" in f.message for f in findings)
    assert any("Pallas kernel" in f.message for f in findings)


def test_gl01_quiet_on_eager_helpers():
    src = """
import numpy as np

def host_helper(x):
    return np.asarray(x).item()
"""
    assert not [f for f in lint(src) if f.rule == "GL01"]


# ---------------------------------------------------------------------------
# GL02 — raw env flag parsing
# ---------------------------------------------------------------------------

def test_gl02_fires_on_flag_vocab_compare():
    src = """
import os

def wanted():
    force = os.environ.get("RAFT_TPU_X", "auto")
    if force == "never":
        return False
    return force == "always"
"""
    assert rules_of(lint(src)) == ["GL02"]


def test_gl02_fires_on_inline_truth_test_and_chain():
    src = """
import os

def a():
    if os.environ.get("X"):
        return 1

def b():
    return os.environ.get("Y", "").strip().lower() not in ("", "0", "no")
"""
    findings = [f for f in lint(src) if f.rule == "GL02"]
    assert len(findings) == 2


def test_gl02_quiet_on_value_reads():
    src = """
import os

def paths():
    jsonl = os.environ.get("RAFT_TPU_BENCH_OBS_JSONL")
    if jsonl:
        open(jsonl)
    n = int(os.environ.get("RAFT_TPU_BENCH_N", 1000))
    return n
"""
    assert not [f for f in lint(src) if f.rule == "GL02"]


# ---------------------------------------------------------------------------
# GL03 — recompile hazards
# ---------------------------------------------------------------------------

def test_gl03_fires_on_tracer_branch_and_unhashable_static():
    src = """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k",))
def f(x, k, opts=[1, 2]):
    if x > 0:
        return x
    return -x

@functools.partial(jax.jit, static_argnames=("opts",))
def g(x, opts=[1, 2]):
    return x
"""
    findings = [f for f in lint(src) if f.rule == "GL03"]
    assert len(findings) == 2
    assert any("traced value" in f.message for f in findings)
    assert any("unhashable" in f.message for f in findings)


def test_gl03_quiet_on_static_and_structure_branches():
    src = """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def f(x, mask, k, interpret=False):
    if interpret:
        k = k + 1
    if mask is not None:
        x = x * 1.0
    if x.ndim == 2 and k > x.shape[0]:
        return x
    return x
"""
    assert not [f for f in lint(src) if f.rule == "GL03"]


# ---------------------------------------------------------------------------
# GL04 — observability contract on public entry points
# ---------------------------------------------------------------------------

GL04_BAD = """
def build(dataset):
    return dataset

def search(index, q, k):
    return index
"""

GL04_GOOD = """
from raft_tpu.core.tracing import traced, span

@traced("raft_tpu.fake.build")
def build(dataset):
    return dataset

def search(index, q, k):
    with span("scan"):
        return index

def _private_helper(x):
    return x

def not_an_entry_verb(x):
    return x
"""


def test_gl04_fires_only_in_entry_packages():
    assert len([f for f in lint(GL04_BAD) if f.rule == "GL04"]) == 2
    # same source outside neighbors/cluster/distance: no contract
    assert not lint(GL04_BAD, path="raft_tpu/sparse/fake.py")


def test_gl04_satisfied_by_traced_or_span():
    assert not [f for f in lint(GL04_GOOD) if f.rule == "GL04"]


# ---------------------------------------------------------------------------
# GL05 — Pallas kernel constraints
# ---------------------------------------------------------------------------

GL05_BAD = """
import jax.numpy as jnp
from jax.experimental import pallas as pl

_LANES = 128

def bad_kernel(x_ref, idx_ref, o_ref):
    o_ref[:] = jnp.take(x_ref[:], idx_ref[:], axis=1)

def caller(x, idx):
    return pl.pallas_call(
        bad_kernel,
        in_specs=[
            pl.BlockSpec((8, 100), lambda i: (i, 0)),
            pl.BlockSpec(),
            pl.BlockSpec((8, _LANES), lambda i: (i, 0)),
        ],
    )(x, idx)
"""


def test_gl05_fires_on_lane_tiling_memory_space_and_gather():
    findings = [f for f in lint(GL05_BAD) if f.rule == "GL05"]
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 3
    assert "not a multiple of 128" in msgs
    assert "memory_space" in msgs
    assert "lane-axis gather" in msgs
    # const-resolved _LANES block and the SMEM spec are fine
    src_ok = GL05_BAD.replace("(8, 100)", "(8, 256)") \
                     .replace("pl.BlockSpec(),",
                              "pl.BlockSpec(memory_space='smem'),") \
                     .replace("jnp.take(x_ref[:], idx_ref[:], axis=1)",
                              "x_ref[:]")
    assert not [f for f in lint(src_ok) if f.rule == "GL05"]


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

def test_line_suppression_is_per_rule():
    src = """
import os

def wanted():
    force = os.environ.get("X", "auto")  # graftlint: disable=GL02
    return force == "always"
"""
    assert not lint(src)
    # wrong rule id on the line does NOT silence GL02
    assert lint(src.replace("disable=GL02", "disable=GL01"))


def test_fn_scope_suppression():
    src = """
import jax
import numpy as np

@jax.jit
def f(x):  # graftlint: disable-fn=GL01
    return np.asarray(x), x.item()

@jax.jit
def g(x):
    return np.asarray(x)
"""
    findings = [f for f in lint(src) if f.rule == "GL01"]
    assert len(findings) == 1  # only g's — f is scope-suppressed


def test_disable_all():
    src = """
import os

def wanted():
    return os.environ.get("X") == "always"  # graftlint: disable=all
"""
    assert not lint(src)


def test_every_rule_has_a_suppressible_finding():
    """Meta-check: each rule id observed above responds to its own
    line suppression (guards the Finding.line anchoring)."""
    cases = {
        "GL01": (GL01_BAD, "    v = x.item()",
                 "    v = x.item()  # graftlint: disable=GL01"),
        "GL04": (GL04_BAD, "def build(dataset):",
                 "def build(dataset):  # graftlint: disable=GL04"),
    }
    for rule, (src, old, new) in cases.items():
        before = [f for f in lint(src) if f.rule == rule]
        after = [f for f in lint(src.replace(old, new)) if f.rule == rule]
        assert len(after) == len(before) - 1, rule


# ---------------------------------------------------------------------------
# engine / CLI
# ---------------------------------------------------------------------------

def test_select_filters_rules():
    findings = lint(GL01_BAD + GL04_BAD, select={"GL04"})
    assert rules_of(findings) == ["GL04"]


def test_repo_tree_is_clean():
    """The acceptance gate: zero unsuppressed findings on raft_tpu/."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = graftlint.lint_paths([os.path.join(root, "raft_tpu")])
    assert not findings, "\n".join(f.render() for f in findings)


def test_cli_json_and_exit_codes(tmp_path):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = tmp_path / "neighbors"
    bad.mkdir()
    (bad / "mod.py").write_text(GL04_BAD)
    env = dict(os.environ, PYTHONPATH=root)
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(bad), "--format",
         "json"], capture_output=True, text=True, cwd=root, env=env)
    assert p.returncode == 1
    payload = json.loads(p.stdout)
    assert {f["rule"] for f in payload} == {"GL04"}
    (bad / "mod.py").write_text(GL04_GOOD)
    p = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(bad)],
        capture_output=True, text=True, cwd=root, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "clean" in p.stdout
