"""K-means tests vs sklearn-style expectations (reference test model:
cpp/test/cluster/kmeans.cu + pylibraft test_kmeans.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cluster import KMeansParams, KMeansBalancedParams, kmeans, kmeans_balanced
from raft_tpu.cluster import distributed as dkm
from raft_tpu.parallel import make_mesh
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState
import jax


@pytest.fixture(scope="module")
def blobs():
    x, labels = make_blobs(1000, 8, n_clusters=5, cluster_std=0.4)
    return np.asarray(x), np.asarray(labels)


def _cluster_quality(x, labels_true, labels_pred, n_clusters):
    """Adjusted-rand-free sanity: majority label purity per cluster."""
    correct = 0
    for c in range(n_clusters):
        members = labels_true[labels_pred == c]
        if len(members):
            correct += np.bincount(members).max()
    return correct / len(labels_true)


class TestKMeans:
    def test_fit_recovers_blobs(self, blobs):
        x, true = blobs
        params = KMeansParams(n_clusters=5, max_iter=100, seed=1)
        centroids, inertia, n_iter = kmeans.fit(params, jnp.asarray(x))
        assert centroids.shape == (5, 8)
        assert int(n_iter) >= 1
        labels = np.asarray(kmeans.predict(centroids, jnp.asarray(x)))
        assert _cluster_quality(x, true, labels, 5) > 0.95

    def test_inertia_decreases_vs_random(self, blobs):
        x, _ = blobs
        params = KMeansParams(n_clusters=5, max_iter=100, seed=1)
        centroids, inertia, _ = kmeans.fit(params, jnp.asarray(x))
        rand_c = x[np.random.default_rng(0).choice(len(x), 5, replace=False)]
        rand_cost = float(kmeans.cluster_cost(jnp.asarray(rand_c), jnp.asarray(x)))
        assert float(inertia) <= rand_cost

    def test_transform_shape(self, blobs):
        x, _ = blobs
        params = KMeansParams(n_clusters=4, max_iter=20)
        centroids, _, _ = kmeans.fit(params, jnp.asarray(x))
        t = kmeans.transform(centroids, jnp.asarray(x))
        assert t.shape == (len(x), 4)
        # transform distances must agree with predict argmin
        labels = np.asarray(kmeans.predict(centroids, jnp.asarray(x)))
        np.testing.assert_array_equal(np.asarray(t).argmin(1), labels)

    def test_weighted_fit_ignores_zero_weight(self, blobs):
        x, _ = blobs
        # add junk rows with zero weight; fit must be unaffected
        junk = np.full((50, 8), 100.0, np.float32)
        xw = np.concatenate([x, junk])
        w = np.concatenate([np.ones(len(x), np.float32), np.zeros(50, np.float32)])
        params = KMeansParams(n_clusters=5, max_iter=100, seed=3)
        c1, _, _ = kmeans.fit(params, jnp.asarray(xw), sample_weights=jnp.asarray(w))
        assert np.abs(np.asarray(c1)).max() < 50  # junk never became a center

    def test_plus_plus_init_spreads(self, blobs):
        x, _ = blobs
        c = kmeans.init_plus_plus(jax.random.PRNGKey(0), jnp.asarray(x), 5)
        # all 5 seeds distinct
        d = np.asarray(c)
        assert len(np.unique(d.round(6), axis=0)) == 5

    def test_minibatch_fit_recovers_blobs(self, blobs):
        x, labels_true = blobs
        p = KMeansParams(n_clusters=5, seed=3)
        c, inertia, n_iters = kmeans.fit_minibatch(p, jnp.asarray(x),
                                                   batch_size=256)
        assert c.shape == (5, 8) and n_iters > 0
        pred = np.asarray(kmeans.predict(c, jnp.asarray(x)))
        assert _cluster_quality(x, labels_true, pred, 5) > 0.9
        # mini-batch inertia lands near the full-batch fit's
        _, full_inertia, _ = kmeans.fit(p, jnp.asarray(x))
        assert float(inertia) < 2.0 * float(full_inertia) + 1e-3

    def test_update_centroids_step(self, blobs):
        x, _ = blobs
        xj = jnp.asarray(x, jnp.float32)
        c0 = xj[:5]
        labels = kmeans.predict(c0, xj)
        w = jnp.ones((x.shape[0],), jnp.float32)
        counts, c1 = kmeans.update_centroids(xj, w, c0, labels)
        assert counts.shape == (5,) and c1.shape == c0.shape
        np.testing.assert_allclose(float(jnp.sum(counts)), x.shape[0])
        # one exact update step cannot increase the cost
        assert float(kmeans.cluster_cost(c1, xj)) <= float(
            kmeans.cluster_cost(c0, xj)) + 1e-3

    def test_find_k(self):
        x, _ = make_blobs(600, 4, n_clusters=3, cluster_std=0.2, state=RngState(7))
        best_k, inertias = kmeans.find_k(jnp.asarray(np.asarray(x)), k_max=8,
                                         params=KMeansParams(max_iter=50, seed=2))
        assert 2 <= best_k <= 5


class TestKMeansBalanced:
    def test_build_clusters_balance(self, blobs):
        x, _ = blobs
        centers, labels, sizes = kmeans_balanced.build_clusters(
            jnp.asarray(x), 16, KMeansBalancedParams(n_iters=25, seed=1))
        sizes = np.asarray(sizes)
        assert sizes.sum() == len(x)
        assert sizes.max() <= len(x) // 16 * 6  # no degenerate mega-cluster
        assert (sizes > 0).sum() >= 14          # nearly all clusters populated

    def test_hierarchical_fit(self, blobs):
        x, _ = blobs
        centers = kmeans_balanced.fit(jnp.asarray(x), 32,
                                      KMeansBalancedParams(n_iters=20, seed=1))
        assert centers.shape == (32, 8)
        labels = np.asarray(kmeans_balanced.predict(centers, jnp.asarray(x)))
        sizes = np.bincount(labels, minlength=32)
        assert (sizes > 0).sum() >= 28
        assert sizes.max() <= len(x) // 32 * 8

    def test_cosine_metric(self, blobs):
        x, _ = blobs
        p = KMeansBalancedParams(n_iters=15, metric="cosine", seed=2)
        centers = kmeans_balanced.fit(jnp.asarray(x), 8, p)
        norms = np.linalg.norm(np.asarray(centers), axis=1)
        np.testing.assert_allclose(norms, 1.0, rtol=1e-4)


class TestDistributedKMeans:
    def test_matches_single_device(self, blobs):
        x, true = blobs
        mesh = make_mesh(axis_names=("shard",))
        params = KMeansParams(n_clusters=5, max_iter=100, seed=1)
        c0 = kmeans.init_random(jax.random.PRNGKey(0), jnp.asarray(x), 5)
        c_dist, inertia_d, _ = dkm.fit(params, jnp.asarray(x), mesh,
                                       init_centroids=c0)
        c_single, inertia_s, _ = kmeans.fit(params, jnp.asarray(x),
                                            init_centroids=c0,
                                            )
        # same init → same fixpoint (up to fp reduction order)
        np.testing.assert_allclose(np.asarray(inertia_d), np.asarray(inertia_s),
                                   rtol=1e-3)
        # random init may hit a weaker optimum; equivalence with the
        # single-device fixpoint above is the real assertion
        labels = np.asarray(dkm.predict(c_dist, jnp.asarray(x), mesh))
        assert _cluster_quality(x, true, labels, 5) > 0.75

    def test_non_divisible_rows(self):
        x, _ = make_blobs(997, 6, n_clusters=3, cluster_std=0.3)
        mesh = make_mesh(axis_names=("shard",))
        params = KMeansParams(n_clusters=3, max_iter=60, seed=5)
        c, inertia, _ = dkm.fit(params, jnp.asarray(np.asarray(x)), mesh)
        assert np.isfinite(float(inertia))
        labels = dkm.predict(c, jnp.asarray(np.asarray(x)), mesh)
        assert labels.shape == (997,)


def test_balanced_level2_drop_warning():
    """Level-2 sampling truncation past the per-mesocluster cap must be
    surfaced as a warning above the threshold and stay silent below it
    (ADVICE r5) — silent sampling bias is otherwise invisible."""
    from raft_tpu.cluster.kmeans_balanced import _warn_level2_drop
    from raft_tpu.core import logging as rlog

    msgs = []
    rlog.set_callback(lambda lvl, msg: msgs.append(msg))
    try:
        _warn_level2_drop(1, 1000, 504)      # 0.1% — below threshold
        assert not msgs
        _warn_level2_drop(150, 1000, 504)    # 15% — must warn
    finally:
        rlog.set_callback(None)
    assert any("level-2 sampling dropped" in m for m in msgs), msgs


def test_balanced_fit_no_drop_warning_on_blobs():
    """A well-behaved dataset through the full hierarchical fit must not
    trigger the level-2 drop warning (wiring check)."""
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
    from raft_tpu.core import logging as rlog

    x, _ = make_blobs(2000, 8, n_clusters=16, cluster_std=1.0)
    msgs = []
    rlog.set_callback(lambda lvl, msg: msgs.append(msg))
    try:
        kmeans_balanced.fit(jnp.asarray(np.asarray(x)), 64,
                            KMeansBalancedParams(n_iters=4, seed=0))
    finally:
        rlog.set_callback(None)
    assert not any("level-2 sampling dropped" in m for m in msgs), msgs
