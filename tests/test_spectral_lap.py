"""Spectral partition + LAP vs scipy/numpy references
(reference tests: cpp/test/spectral_matrix.cu, cpp/test/lap/lap.cu)."""

import numpy as np
import pytest
import scipy.optimize as sopt

from raft_tpu import sparse, spectral
from raft_tpu.solver import lap_solve
from raft_tpu.sparse import ops as sops


def _two_cliques(n_per=15, bridge=1):
    """Two dense cliques joined by a weak bridge — an obvious balanced cut."""
    n = 2 * n_per
    rows, cols, w = [], [], []
    for base in (0, n_per):
        for i in range(n_per):
            for j in range(i + 1, n_per):
                rows.append(base + i)
                cols.append(base + j)
                w.append(1.0)
    for b in range(bridge):
        rows.append(b)
        cols.append(n_per + b)
        w.append(0.05)
    coo = sparse.make_coo(rows, cols, np.asarray(w, np.float32), (n, n))
    return sops.symmetrize(coo, mode="max"), n_per


@pytest.mark.slow  # modularity twin on the same cliques stays tier-1 (tier-1 budget)
def test_partition_two_cliques():
    adj, n_per = _two_cliques()
    labels, evals, evecs = spectral.partition(adj, 2, seed=1)
    lab = np.asarray(labels)
    assert len(set(lab[:n_per])) == 1
    assert len(set(lab[n_per:])) == 1
    assert lab[0] != lab[-1]
    stats = spectral.analyze_partition(adj, labels)
    assert stats.edge_cut == pytest.approx(0.05, rel=1e-4)


def test_modularity_maximization_two_cliques():
    adj, n_per = _two_cliques()
    labels, _, _ = spectral.modularity_maximization(adj, 2, seed=3)
    lab = np.asarray(labels)
    assert len(set(lab[:n_per])) == 1 and len(set(lab[n_per:])) == 1
    q = spectral.modularity(adj, labels)
    # near-perfect two-community structure → Q close to 0.5
    assert q > 0.4


@pytest.mark.parametrize("n,seed", [(10, 0), (25, 1), (50, 2)])
def test_lap_matches_scipy(n, seed):
    rs = np.random.RandomState(seed)
    cost = rs.randint(0, 100, size=(n, n)).astype(np.float32)
    assign, total = lap_solve(cost)
    assign = np.asarray(assign)
    # valid permutation
    assert sorted(assign.tolist()) == list(range(n))
    ri, ci = sopt.linear_sum_assignment(cost)
    assert float(total) == pytest.approx(cost[ri, ci].sum())


def test_lap_maximize():
    rs = np.random.RandomState(7)
    cost = rs.randint(0, 50, size=(12, 12)).astype(np.float32)
    assign, total = lap_solve(cost, maximize=True)
    ri, ci = sopt.linear_sum_assignment(cost, maximize=True)
    assert float(total) == pytest.approx(cost[ri, ci].sum())


def test_lap_rejects_nonsquare():
    with pytest.raises(ValueError):
        lap_solve(np.zeros((3, 4), np.float32))


def test_lap_wide_cost_range():
    """ε-scaling must keep shrinking for wide cost spans (review
    regression: fixed phase cap left ε too coarse).  f32 price
    resolution bounds exactness, so assert a tight relative gap."""
    rs = np.random.RandomState(11)
    cost = rs.randint(0, 1_000_000, size=(40, 40)).astype(np.float32)
    assign, total = lap_solve(cost)
    assert sorted(np.asarray(assign).tolist()) == list(range(40))
    ri, ci = sopt.linear_sum_assignment(cost)
    opt = cost[ri, ci].sum()
    assert float(total) <= opt * 1.001 + 40 * 2.0  # within n·eps of optimal


def test_lap_exact_mid_range():
    """span·(n+1) under 2^20 → exact optimum guaranteed."""
    rs = np.random.RandomState(13)
    cost = rs.randint(0, 20_000, size=(30, 30)).astype(np.float32)
    _, total = lap_solve(cost)
    ri, ci = sopt.linear_sum_assignment(cost)
    assert float(total) == pytest.approx(cost[ri, ci].sum())
