"""Refine dispatch-tier tests: fused Pallas gather-refine vs the XLA
einsum-gather path (interpret mode off-TPU), argument validation, and
the obs dispatch contract (ISSUE 4 acceptance: parity across all four
metrics × invalid-candidate patterns, atol-tiered by dtype)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.neighbors import refine

METRICS = ["sqeuclidean", "euclidean", "inner_product", "cosine"]


@pytest.fixture()
def corpus():
    rng = np.random.default_rng(7)
    n, d, m, C = 900, 48, 19, 300
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((m, d)).astype(np.float32)
    cand = rng.integers(0, n, (m, C)).astype(np.int32)
    return x, q, cand


def _both_tiers(monkeypatch, x, q, cand, k, metric):
    monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "never")
    d_x, i_x = refine.refine(jnp.asarray(x), jnp.asarray(q),
                             jnp.asarray(cand), k, metric)
    monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
    d_p, i_p = refine.refine(jnp.asarray(x), jnp.asarray(q),
                             jnp.asarray(cand), k, metric)
    return (np.asarray(d_x), np.asarray(i_x),
            np.asarray(d_p), np.asarray(i_p))


class TestFusedParity:
    @pytest.mark.parametrize("metric", METRICS)
    def test_parity_clean_candidates(self, corpus, monkeypatch, metric):
        x, q, cand = corpus
        d_x, i_x, d_p, i_p = _both_tiers(monkeypatch, x, q, cand, 10,
                                         metric)
        np.testing.assert_allclose(d_p, d_x, rtol=2e-4, atol=2e-4)
        np.testing.assert_array_equal(i_p, i_x)

    @pytest.mark.parametrize("metric", METRICS)
    def test_parity_invalid_patterns(self, corpus, monkeypatch, metric):
        """All-(-1) rows, duplicate ids, and ragged (-1) tails must
        survive both tiers identically — the kernel masks invalid ids
        to ±inf exactly like the XLA path."""
        x, q, cand = corpus
        cand = cand.copy()
        cand[0, :] = -1                    # fully invalid row
        cand[1, 5:40] = cand[1, 4]         # duplicate ids
        cand[2, -13:] = -1                 # ragged tail
        cand[3, : 300 - 4] = -1            # fewer valid than k
        d_x, i_x, d_p, i_p = _both_tiers(monkeypatch, x, q, cand, 10,
                                         metric)
        np.testing.assert_allclose(d_p, d_x, rtol=2e-4, atol=2e-4)
        assert (i_p[0] == -1).all() and (i_x[0] == -1).all()
        # duplicate ids rank as duplicates on both tiers
        np.testing.assert_array_equal(i_p[1], i_x[1])
        # the short row pads with -1 past its 4 valid candidates
        assert (i_p[3][4:] == -1).all() and (i_x[3][4:] == -1).all()
        np.testing.assert_array_equal(np.sort(i_p[2]), np.sort(i_x[2]))

    def test_parity_bf16_dataset(self, corpus, monkeypatch):
        """The recon-cache input shape: a bf16 dataset streams through
        the row DMAs dtype-preserved; parity vs the XLA path on the
        SAME bf16 rows, at the bf16 tolerance tier."""
        x, q, cand = corpus
        xb = jnp.asarray(x).astype(jnp.bfloat16)
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "never")
        d_x, i_x = refine.refine(xb, jnp.asarray(q), jnp.asarray(cand), 10)
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
        d_p, i_p = refine.refine(xb, jnp.asarray(q), jnp.asarray(cand), 10)
        np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_x),
                                   rtol=2e-2, atol=2e-2)
        # Jaccard, not /k: duplicate candidate ids legitimately repeat
        # in a top-k row, which shrinks the python set
        overlap = np.mean([len(set(a) & set(b)) / len(set(a) | set(b))
                           for a, b in zip(np.asarray(i_p),
                                           np.asarray(i_x))])
        assert overlap >= 0.9, overlap

    @pytest.mark.parametrize("metric", METRICS)
    def test_parity_filtered(self, corpus, monkeypatch, metric):
        """ISSUE 12: filter_bits excludes cleared-bit candidates on BOTH
        tiers identically — the fused kernel's in-DMA word test and the
        XLA tier's sentinel pre-mask agree bit-for-bit."""
        from raft_tpu.core import bitset

        x, q, cand = corpus
        rng = np.random.default_rng(23)
        keep = rng.random(len(x)) < 0.4
        bits = bitset.from_mask(jnp.asarray(keep))
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "never")
        d_x, i_x = refine.refine(jnp.asarray(x), jnp.asarray(q),
                                 jnp.asarray(cand), 10, metric,
                                 filter_bits=bits)
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
        d_p, i_p = refine.refine(jnp.asarray(x), jnp.asarray(q),
                                 jnp.asarray(cand), 10, metric,
                                 filter_bits=bits)
        d_x, i_x = np.asarray(d_x), np.asarray(i_x)
        d_p, i_p = np.asarray(d_p), np.asarray(i_p)
        np.testing.assert_allclose(d_p, d_x, rtol=2e-4, atol=2e-4)
        np.testing.assert_array_equal(i_p, i_x)
        assert keep[i_p[i_p >= 0]].all()
        assert keep[i_x[i_x >= 0]].all()

    def test_filtered_dispatch_counters(self, corpus, monkeypatch):
        """A filtered dispatch carries filtered=1 on both tiers."""
        from raft_tpu.core import bitset

        x, q, cand = corpus
        bits = bitset.create(len(x), default_value=True)
        reg = obs.MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
            refine.refine(jnp.asarray(x), jnp.asarray(q),
                          jnp.asarray(cand), 10, filter_bits=bits)
            monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "never")
            refine.refine(jnp.asarray(x), jnp.asarray(q),
                          jnp.asarray(cand), 10, filter_bits=bits)
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert c.get("refine.dispatch{filtered=1,impl=pallas_gather}",
                     0) >= 1, c
        assert c.get("refine.dispatch{filtered=1,impl=xla_gather}",
                     0) >= 1, c

    def test_fused_declines_oversized_k(self, corpus, monkeypatch):
        """k past the in-kernel merge budget must fall back to XLA, not
        error: the dispatch gate (not the kernel) owns the bound."""
        from raft_tpu.ops.pallas_kernels import GATHER_REFINE_MAX_K

        x, q, cand = corpus
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
        reg = obs.MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            refine.refine(jnp.asarray(x), jnp.asarray(q),
                          jnp.asarray(cand), GATHER_REFINE_MAX_K + 1)
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert c.get("refine.dispatch{impl=xla_gather}", 0) >= 1, c


def test_pad_copy_guard():
    """gather_refine_mem_ok: an unaligned dataset's PER-CALL pad copy
    must be weighed against the [m, C, d] buffer the tier replaces —
    a small re-rank against a huge d%128!=0 dataset stays on XLA."""
    from raft_tpu.neighbors.ivf_common import gather_refine_mem_ok

    assert gather_refine_mem_ok(10**6, 128, 4, m=10, C=256)  # aligned: free
    # 512 MB pad copy vs a ~1 MB gather buffer → decline
    assert not gather_refine_mem_ok(10**6, 96, 4, m=10, C=256)
    # the oversampled regime: the 7.7 GB buffer dwarfs the copy → engage
    assert gather_refine_mem_ok(10**6, 96, 4, m=10_000, C=2000)


class TestDispatchContract:
    def test_counters_and_span(self, corpus, monkeypatch):
        x, q, cand = corpus
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
        reg = obs.MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            refine.refine(jnp.asarray(x), jnp.asarray(q),
                          jnp.asarray(cand), 10)
        finally:
            obs.disable()
        snap = reg.snapshot()
        assert snap["counters"].get(
            "refine.dispatch{impl=pallas_gather}", 0) >= 1
        # the fused scan runs under the established span contract
        assert "span.refine.fused_scan" in snap["histograms"]

    def test_host_tiers_count(self, corpus, monkeypatch):
        x, q, cand = corpus
        reg = obs.MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            refine.refine_gathered(x, jnp.asarray(q), cand, 10)
        finally:
            obs.disable()
        assert reg.snapshot()["counters"].get(
            "refine.dispatch{impl=host_gather}", 0) >= 1


class TestValidation:
    """Satellite: oversized k / empty candidate axis fail with clear
    expects() messages on every entry point — not an opaque
    take_along_axis error from inside the jitted program."""

    def test_oversized_k(self, corpus):
        from raft_tpu.core.errors import LogicError

        x, q, cand = corpus
        with pytest.raises(LogicError, match="n_candidates"):
            refine.refine(jnp.asarray(x), jnp.asarray(q),
                          jnp.asarray(cand), cand.shape[1] + 1)
        with pytest.raises(LogicError, match="n_candidates"):
            refine.refine_gathered(x, jnp.asarray(q), cand,
                                   cand.shape[1] + 1)

    def test_empty_candidate_axis(self, corpus):
        from raft_tpu.core.errors import LogicError

        x, q, _ = corpus
        empty = np.zeros((q.shape[0], 0), np.int32)
        with pytest.raises(LogicError, match="non-empty"):
            refine.refine(jnp.asarray(x), jnp.asarray(q),
                          jnp.asarray(empty), 1)
        with pytest.raises(LogicError, match="non-empty"):
            refine.refine_gathered(x, jnp.asarray(q), empty, 1)

    def test_row_mismatch_still_checked(self, corpus):
        from raft_tpu.core.errors import LogicError

        x, q, cand = corpus
        with pytest.raises(LogicError, match="row mismatch"):
            refine.refine(jnp.asarray(x), jnp.asarray(q[:5]),
                          jnp.asarray(cand), 4)


def test_dataset_dim_mismatch(corpus):
    """Satellite follow-through: a wrong-dim re-rank base fails with a
    clear expects() message on every entry point, not an opaque einsum
    or Pallas block-shape error."""
    from raft_tpu.core.errors import LogicError

    x, q, cand = corpus
    wrong = jnp.asarray(x[:, :17])
    with pytest.raises(LogicError, match="feature-dim"):
        refine.refine(wrong, jnp.asarray(q), jnp.asarray(cand), 5)
    with pytest.raises(LogicError, match="feature-dim"):
        refine.refine_gathered(np.asarray(wrong), jnp.asarray(q), cand, 5)
