"""obs.cost + obs.capacity — the cost & capacity plane (ISSUE 20).

The attribution contract under test: batch device time prorated
equally across live members (shed members excluded by the caller;
cross-tenant batches split by member count), the conservation
invariant (Σ per-tenant device seconds == Σ measured batch wall, by
construction), HBM byte-second integration via the rectangle rule over
``index.bytes{tier=hbm}`` levels, describe() folding the registry's
tenant-labeled counters in, and the obs-off contract (accumulates for
unit tests, publishes nothing). The capacity half: DeltaRing
window-base selection, utilization/headroom accounting, the
least-squares saturation forecast (flat → inf, ramp → finite ttl,
already-over → 0), the alert counters, and the two closed loops —
``IndexRegistry.admit`` demoting raw tiers preemptively on a
forecasted saturation (BEFORE any pressure eviction), and
``FleetRouter`` placement steering by cost-share-weighted headroom.
"""

import pytest

from raft_tpu import obs
from raft_tpu.obs import capacity as capacity_mod
from raft_tpu.obs import cost as cost_mod
from raft_tpu.obs.capacity import (CapacityModel, CapacityPolicy,
                                   DeltaRing)
from raft_tpu.obs.cost import CostLedger
from raft_tpu.obs.metrics import MetricsRegistry, counter_sum


@pytest.fixture(autouse=True)
def _clean():
    cost_mod.clear_ledger()
    capacity_mod.clear_model()
    yield
    cost_mod.clear_ledger()
    capacity_mod.clear_model()
    obs.disable()


def _enable():
    obs.enable(registry=MetricsRegistry(), hbm=False)
    return obs.registry()


# ---------------------------------------------------------------------------
# CostLedger — proration + conservation
# ---------------------------------------------------------------------------

class TestProration:
    def test_single_member_batch_gets_full_time(self):
        led = CostLedger()
        led.note_batch(0.25, ["a"])
        assert led.device_seconds() == {"a": pytest.approx(0.25)}

    def test_coalesced_batch_splits_equally(self):
        led = CostLedger()
        led.note_batch(0.3, ["a", "a", "a"])
        assert led.device_seconds()["a"] == pytest.approx(0.3)
        cons = led.conservation()
        assert cons["attributed_device_s"] == pytest.approx(0.3)
        assert cons["rel_err"] == pytest.approx(0.0)

    def test_cross_tenant_batch_splits_by_member_count(self):
        # two of a, one of b sharing one dispatched bucket: a pays 2/3
        led = CostLedger()
        led.note_batch(0.3, ["a", "a", "b"])
        ds = led.device_seconds()
        assert ds["a"] == pytest.approx(0.2)
        assert ds["b"] == pytest.approx(0.1)
        assert led.shares()["a"] == pytest.approx(2.0 / 3.0)

    def test_shed_member_excluded_from_proration(self):
        # the batch coalesced 3 requests but one was deadline-shed
        # before dispatch: the caller hands only the 2 live members, so
        # the survivors split the whole batch and the shed request is
        # charged nothing — attribution follows work dispatched
        led = CostLedger()
        led.note_batch(0.2, ["a", "b"])          # 3rd member shed
        ds = led.device_seconds()
        assert ds == {"a": pytest.approx(0.1), "b": pytest.approx(0.1)}
        assert led.conservation()["attributed_device_s"] \
            == pytest.approx(0.2)

    def test_empty_or_negative_batches_ignored(self):
        led = CostLedger()
        led.note_batch(0.5, [])
        led.note_batch(-1.0, ["a"])
        assert led.device_seconds() == {}
        assert led.conservation()["batch_wall_s"] == 0.0

    def test_conservation_over_many_batches(self):
        led = CostLedger()
        total = 0.0
        for i in range(50):
            d = 0.001 * (i + 1)
            led.note_batch(d, [f"t{i % 3}"] * ((i % 4) + 1))
            total += d
        cons = led.conservation()
        assert cons["batch_wall_s"] == pytest.approx(total)
        assert cons["rel_err"] < 1e-9

    def test_disabled_obs_accumulates_but_publishes_nothing(self):
        # obs off: note_batch still books (unit-test contract) but no
        # cost.* series appear anywhere — the no-attribution half of
        # the zero-overhead contract (dispatch's tap additionally skips
        # the ledger entirely behind one spans.enabled() check)
        assert not obs.enabled()
        led = CostLedger()
        led.note_batch(0.1, ["a"])
        assert led.device_seconds()["a"] == pytest.approx(0.1)
        reg = _enable()
        assert not [k for k in reg.snapshot()["gauges"]
                    if k.startswith("cost.")]

    def test_enabled_obs_publishes_device_and_share_gauges(self):
        reg = _enable()
        led = CostLedger()
        led.note_batch(0.3, ["a", "b", "b"])
        g = reg.snapshot()["gauges"]
        assert g["cost.device_s{tenant=a}"] == pytest.approx(0.1)
        assert g["cost.device_s{tenant=b}"] == pytest.approx(0.2)
        assert g["cost.share{tenant=b}"] == pytest.approx(2.0 / 3.0)


# ---------------------------------------------------------------------------
# CostLedger — HBM byte-second integration + describe()
# ---------------------------------------------------------------------------

class TestHbmIntegration:
    def _mk(self):
        reg = _enable()
        clock = {"t": 0.0}
        led = CostLedger(clock=lambda: clock["t"])
        return led, clock, reg

    def test_rectangle_rule_integrates_previous_level(self):
        led, clock, reg = self._mk()
        reg.gauge("index.bytes",
                  labels={"index": "a", "tier": "hbm"}).set(1000.0)
        led.tick()                     # first sighting: integral += 0
        clock["t"] = 5.0
        led.tick()                     # 1000 B held for 5 s
        g = reg.snapshot()["gauges"]
        assert g["cost.hbm_byte_s{tenant=a}"] == pytest.approx(5000.0)
        # demotion drops the level; the interval BEFORE the tick that
        # observes it is still charged at the pre-move level
        reg.gauge("index.bytes",
                  labels={"index": "a", "tier": "hbm"}).set(0.0)
        clock["t"] = 7.0
        led.tick()                     # += 1000 * 2
        clock["t"] = 9.0
        led.tick()                     # += 0 * 2
        g = reg.snapshot()["gauges"]
        assert g["cost.hbm_byte_s{tenant=a}"] == pytest.approx(7000.0)

    def test_host_tier_levels_not_charged(self):
        led, clock, reg = self._mk()
        reg.gauge("index.bytes",
                  labels={"index": "a", "tier": "host"}).set(9999.0)
        led.tick()
        clock["t"] = 10.0
        led.tick()
        assert "cost.hbm_byte_s{tenant=a}" not in \
            reg.snapshot()["gauges"]

    def test_shares_fall_back_to_hbm_before_traffic(self):
        led, clock, reg = self._mk()
        reg.gauge("index.bytes",
                  labels={"index": "a", "tier": "hbm"}).set(3000.0)
        reg.gauge("index.bytes",
                  labels={"index": "b", "tier": "hbm"}).set(1000.0)
        led.tick()
        clock["t"] = 10.0
        led.tick()
        shares = led.shares()
        assert shares["a"] == pytest.approx(0.75)
        assert shares["b"] == pytest.approx(0.25)
        # the first batch flips the basis to device time
        led.note_batch(0.1, ["b"])
        assert led.shares() == {"b": pytest.approx(1.0)}

    def test_describe_folds_registry_counters(self):
        led, clock, reg = self._mk()
        led.note_batch(0.4, ["a"])
        reg.inc("serve.requests", 5, labels={"tenant": "a"})
        reg.inc("cost.io_bytes", 123.0, labels={"tenant": "a"})
        reg.inc("cost.comms_bytes", 64.0,
                labels={"tenant": "a", "axis": "ici"})
        reg.inc("serve.shed", 2, labels={"reason": "queue_full"})
        doc = led.describe()
        a = doc["tenants"]["a"]
        assert a["device_s"] == pytest.approx(0.4)
        assert a["requests"] == 5
        assert a["io_bytes"] == pytest.approx(123.0)
        assert a["comms_bytes"] == {"ici": pytest.approx(64.0),
                                    "dcn": 0.0}
        assert a["share"] == pytest.approx(1.0)
        assert doc["totals"]["batches"] == 1
        assert doc["totals"]["shed"] == 2
        assert doc["conservation"]["rel_err"] == pytest.approx(0.0)

    def test_counter_labeled_tenants_appear_without_batches(self):
        led, _, reg = self._mk()
        reg.inc("cost.io_bytes", 10.0, labels={"tenant": "io_only"})
        doc = led.describe()
        assert doc["tenants"]["io_only"]["io_bytes"] \
            == pytest.approx(10.0)


class TestGlobalLedger:
    def test_install_get_clear(self):
        led = CostLedger()
        assert cost_mod.set_ledger(led) is None
        assert cost_mod.get_ledger() is led
        cost_mod.clear_ledger(led)
        assert cost_mod.get_ledger() is None

    def test_stale_clear_keeps_newer_ledger(self):
        old, new = CostLedger(), CostLedger()
        cost_mod.set_ledger(old)
        cost_mod.set_ledger(new)
        cost_mod.clear_ledger(old)      # a late stop() must not win
        assert cost_mod.get_ledger() is new


# ---------------------------------------------------------------------------
# DeltaRing — the extracted multi-window machinery
# ---------------------------------------------------------------------------

class TestDeltaRing:
    def test_append_prunes_past_keep_window(self):
        ring = DeltaRing(keep_s=10.0)
        ring.append(0.0, {"x": 1.0})
        ring.append(5.0, {"x": 2.0})
        ring.append(20.0, {"x": 3.0})       # 0.0 and 5.0 both expire
        assert [ts for ts, _ in ring.snaps()] == [20.0]

    def test_window_base_picks_oldest_inside_window(self):
        snaps = [(0.0, {"x": 1.0}), (50.0, {"x": 2.0}),
                 (90.0, {"x": 3.0})]
        assert DeltaRing.window_base(snaps, 100.0, 60.0)["x"] == 2.0

    def test_window_base_falls_back_to_oldest_held(self):
        snaps = [(95.0, {"x": 2.0}), (100.0, {"x": 3.0})]
        # a 30 s window on a 5 s old ring sees everything there is
        assert DeltaRing.window_base(snaps, 100.0, 30.0)["x"] == 2.0
        assert DeltaRing.window_base([], 100.0, 30.0) == {}


# ---------------------------------------------------------------------------
# CapacityModel — utilization, forecast, alerts
# ---------------------------------------------------------------------------

class _Ramp:
    def __init__(self, v=0.0):
        self.v = float(v)

    def __call__(self):
        return self.v


class _FakeLedger:
    def __init__(self):
        self.dev = {}

    def device_seconds(self):
        return dict(self.dev)


def _model(resident, usable=1000.0, ledger=None, **policy_kw):
    clock = {"t": 0.0}
    model = CapacityModel(
        resident_bytes=resident, usable_bytes=lambda: usable,
        ledger=ledger,
        policy=CapacityPolicy(**policy_kw) if policy_kw else None,
        clock=lambda: clock["t"])
    return model, clock


class TestCapacityModel:
    def test_hbm_utilization_is_instantaneous_level(self):
        model, _ = _model(_Ramp(250.0))
        assert model.utilization()["hbm"] == pytest.approx(0.25)
        assert model.headroom_frac() == pytest.approx(0.75)

    def test_device_utilization_from_window_delta(self):
        led = _FakeLedger()
        model, clock = _model(_Ramp(0.0), ledger=led)
        model.tick()
        clock["t"] = 10.0
        led.dev = {"a": 4.0, "b": 1.0}
        model.tick()
        # 5 attributed device seconds over 10 wall seconds
        assert model.utilization()["device"] == pytest.approx(0.5)

    def test_flat_trend_never_saturates(self):
        model, clock = _model(_Ramp(500.0))
        for t in (0.0, 10.0, 20.0):
            clock["t"] = t
            model.tick()
        assert model.ttl_saturation_s() == float("inf")
        assert model.projected_growth_bytes() == 0.0
        assert not model.would_saturate(extra_bytes=100.0)

    def test_ramp_forecasts_finite_ttl(self):
        ramp = _Ramp(100.0)
        model, clock = _model(ramp)
        for t, v in ((0.0, 100.0), (10.0, 200.0), (20.0, 300.0)):
            clock["t"] = t
            ramp.v = v
            model.tick()
        # slope 10 B/s, 700 B of headroom left -> 70 s to saturation
        assert model.ttl_saturation_s() == pytest.approx(70.0)
        # an admission candidate burns headroom up front
        assert model.ttl_saturation_s(extra_bytes=200.0) \
            == pytest.approx(50.0)
        assert model.would_saturate(horizon_s=600.0)
        assert not model.would_saturate(horizon_s=60.0)
        assert model.projected_growth_bytes(horizon_s=30.0) \
            == pytest.approx(300.0)

    def test_already_over_budget_is_ttl_zero(self):
        model, _ = _model(_Ramp(1200.0))
        assert model.ttl_saturation_s() == 0.0

    def test_min_points_gates_the_trend_fit(self):
        ramp = _Ramp(100.0)
        model, clock = _model(ramp, min_points=3)
        for t, v in ((0.0, 100.0), (10.0, 200.0)):
            clock["t"] = t
            ramp.v = v
            model.tick()
        # two points make a line, not a trend
        assert model.ttl_saturation_s() == float("inf")

    def test_tick_publishes_gauges_and_alerts(self):
        reg = _enable()
        ramp = _Ramp(900.0)
        model, clock = _model(ramp)
        for t, v in ((0.0, 900.0), (10.0, 910.0), (20.0, 920.0)):
            clock["t"] = t
            ramp.v = v
            model.tick()
        snap = reg.snapshot()
        g = snap["gauges"]
        assert g["capacity.utilization{resource=hbm}"] \
            == pytest.approx(0.92)
        assert g["capacity.headroom_frac"] == pytest.approx(0.08)
        # slope 1 B/s, 80 B headroom -> 80 s, well inside the horizon
        assert g["capacity.ttl_saturation_s"] == pytest.approx(80.0)
        # util > 0.85 on every tick; ttl < horizon once trend is live
        assert snap["counters"]["capacity.alert{resource=hbm}"] >= 4

    def test_flat_ttl_gauge_encodes_inf_as_negative(self):
        reg = _enable()
        model, clock = _model(_Ramp(100.0))
        for t in (0.0, 10.0, 20.0):
            clock["t"] = t
            model.tick()
        g = reg.snapshot()["gauges"]
        assert g["capacity.ttl_saturation_s"] == -1.0
        assert "capacity.alert{resource=hbm}" not in \
            reg.snapshot()["counters"]

    def test_arrival_rates_split_by_tenant_proportion(self):
        reg = _enable()
        model, clock = _model(_Ramp(100.0))
        reg.inc("serve.requests", 30, labels={"tenant": "a"})
        reg.inc("serve.requests", 10, labels={"tenant": "b"})
        model.tick()
        clock["t"] = 10.0
        reg.inc("serve.requests", 30, labels={"tenant": "a"})
        model.tick()
        rates = model.arrival_rates()
        # 30 new requests over 10 s, split 60:10 by lifetime proportion
        assert rates["a"] == pytest.approx(3.0 * 60.0 / 70.0)
        assert rates["b"] == pytest.approx(3.0 * 10.0 / 70.0)

    def test_forecast_payload_is_json_ready(self):
        import json

        model, clock = _model(_Ramp(100.0))
        model.tick()
        doc = model.forecast()
        assert doc["ttl_saturation_s"] is None      # inf -> None
        assert doc["utilization"]["hbm"] == pytest.approx(0.1)
        json.dumps(doc)

    def test_global_model_install_and_stale_clear(self):
        m1, _ = _model(_Ramp(0.0))
        m2, _ = _model(_Ramp(0.0))
        capacity_mod.set_model(m1)
        capacity_mod.set_model(m2)
        capacity_mod.clear_model(m1)
        assert capacity_mod.get_model() is m2
        capacity_mod.clear_model()
        assert capacity_mod.get_model() is None


# ---------------------------------------------------------------------------
# closed loop ① — admission consults the forecast, demotes preemptively
# ---------------------------------------------------------------------------

class TestPreemptiveDemotion:
    def test_forecasted_saturation_demotes_before_the_cliff(self):
        import jax.numpy as jnp

        from raft_tpu import serve

        reg = _enable()
        registry = serve.IndexRegistry(budget_bytes=10_000,
                                       headroom_frac=0.0)
        data = jnp.ones((100, 4), dtype=jnp.float32)   # 1600 B raw
        registry.admit("cold", object(), dataset=data, default_k=4)
        # a capacity model whose resident trend ramps toward the
        # budget: 100 B/s over three synthetic ticks
        ramp = _Ramp(1000.0)
        model, clock = _model(ramp, usable=10_000.0)
        for t, v in ((0.0, 1000.0), (10.0, 2000.0), (20.0, 3000.0)):
            clock["t"] = t
            ramp.v = v
            model.tick()
        capacity_mod.set_model(model)
        # "new" fits trivially (100 B under a 10 kB budget): no
        # pressure demotion, no eviction — only the forecast acts
        registry.admit("new", object(), size_bytes=100, default_k=4)
        snap = reg.snapshot()["counters"]
        assert snap["serve.registry.preemptive_demote{tenant=cold}"] \
            == 1.0
        cold = registry.peek("cold")
        assert cold.demoted                      # raw moved to host
        assert cold.state in ("warming", "serving")   # NOT evicted
        assert registry.peek("new") is not None

    def test_flat_forecast_leaves_admission_untouched(self):
        import jax.numpy as jnp

        from raft_tpu import serve

        reg = _enable()
        registry = serve.IndexRegistry(budget_bytes=10_000,
                                       headroom_frac=0.0)
        data = jnp.ones((100, 4), dtype=jnp.float32)
        registry.admit("cold", object(), dataset=data, default_k=4)
        model, clock = _model(_Ramp(1000.0), usable=10_000.0)
        for t in (0.0, 10.0, 20.0):
            clock["t"] = t
            model.tick()
        capacity_mod.set_model(model)
        registry.admit("new", object(), size_bytes=100, default_k=4)
        assert "serve.registry.preemptive_demote{tenant=cold}" not in \
            reg.snapshot()["counters"]
        assert not registry.peek("cold").demoted


# ---------------------------------------------------------------------------
# closed loop ② — placement by cost-share-weighted headroom
# ---------------------------------------------------------------------------

class _FakeTenant:
    def __init__(self, name):
        self.name = name


class _FakePodRegistry:
    def __init__(self, tenants, resident_bytes=100.0,
                 usable_bytes=1000.0):
        self._tenants = [_FakeTenant(t) for t in tenants]
        self._resident_bytes = resident_bytes
        self.usable_bytes = usable_bytes
        self.admitted = []

    def resident(self):
        return list(self._tenants)

    def resident_bytes(self):
        return self._resident_bytes

    def admit(self, name, index, **kw):
        self.admitted.append(name)


class TestCapacityPlacement:
    def _fleet(self):
        from raft_tpu.serve.router import FleetRouter, Pod

        pod_a = Pod("a", registry=_FakePodRegistry(["hog"]))
        pod_b = Pod("b", registry=_FakePodRegistry(["t1", "t2"]))
        return FleetRouter([pod_a, pod_b]), pod_a, pod_b

    def test_no_ledger_falls_back_to_fewest_tenants(self):
        reg = _enable()
        router, pod_a, pod_b = self._fleet()
        assert router.place("new", object()) == ["a"]
        assert pod_a.registry.admitted == ["new"]
        assert not [k for k in reg.snapshot()["counters"]
                    if "reason=capacity" in k]

    def test_share_weighted_headroom_overrides_tenant_count(self):
        reg = _enable()
        router, pod_a, pod_b = self._fleet()
        led = CostLedger()
        # pod a's single tenant burns 90% of fleet device time: its
        # "emptiness" by tenant count is a lie the ledger corrects
        led.note_batch(0.90, ["hog"])
        led.note_batch(0.05, ["t1"])
        led.note_batch(0.05, ["t2"])
        cost_mod.set_ledger(led)
        assert router.place("new", object()) == ["b"]
        assert pod_b.registry.admitted == ["new"]
        c = reg.snapshot()["counters"]
        assert c["serve.router.steer{away_from=a,reason=capacity}"] \
            == 1.0

    def test_unattributed_ledger_falls_back_to_fewest_tenants(self):
        _enable()
        router, pod_a, _ = self._fleet()
        cost_mod.set_ledger(CostLedger())   # installed, nothing booked
        assert router.place("new", object()) == ["a"]
        assert pod_a.registry.admitted == ["new"]
