"""Thread-fuzz for the serving plane under the lock-order tracker.

The runtime half of graftlint's concurrency pass (GL16–GL20, ISSUE 18):
eight threads hammer the registry's admit/evict/demote/promote surface
and the micro-batch server's submit/stop path while every lock in the
plane is a ``sanitize.monitored_*`` wrapper recording per-thread
acquisition order. The assertions are the ones single-threaded tests
cannot make: the observed order graph stays acyclic (no interleaving of
these operations can deadlock), no blocking call ran while a plane lock
was held, every submitted future resolves, and resident-bytes
accounting matches the surviving tenants exactly. Seeded AB/BA and
blocking-while-held negatives prove the detectors actually fire — a
tracker that never trips is indistinguishable from one that never
looks.

``test_zz_no_lock_cycles_after_suite`` is the CI lane's closer: the
sanitize lane lists this module LAST so the assertion covers every edge
the serve/quality/tiered modules recorded before it.
"""

import random
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import serve
from raft_tpu.neighbors import ivf_flat
from raft_tpu.obs import sanitize
from raft_tpu.serve.errors import AdmissionError, ShedError, TenantUnknown

N, DIM = 512, 16
THREADS = 8
SEED = 20250806


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    return rng.random((N, DIM), dtype=np.float32)


@pytest.fixture(scope="module")
def flat_index(data):
    return ivf_flat.build(jnp.asarray(data),
                          ivf_flat.IndexParams(n_lists=4))


FLAT_PARAMS = ivf_flat.SearchParams(n_probes=4)


def _run_threads(workers):
    threads = [threading.Thread(target=w, daemon=True) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "fuzz worker hung"


# ---------------------------------------------------------------------------
# registry fuzz
# ---------------------------------------------------------------------------

class TestRegistryFuzz:
    def test_admit_evict_demote_promote_cycle_free(self, flat_index,
                                                   data):
        """8 threads × 120 seeded ops against one registry: typed
        refusals only, acyclic lock order, honest accounting."""
        with sanitize.force_lock_tracking():
            reg = serve.IndexRegistry(budget_bytes=8 << 20)
            names = [f"t{i}" for i in range(6)]
            errors = []

            def worker(seed):
                rng = random.Random(seed)
                dev = jnp.asarray(data)
                for _ in range(120):
                    name = rng.choice(names)
                    op = rng.random()
                    try:
                        if op < 0.40:
                            # half the admissions carry a device
                            # dataset so pressure demotions and
                            # re-promotions are real tier moves
                            ds = dev if rng.random() < 0.5 else None
                            reg.admit(name, flat_index,
                                      params=FLAT_PARAMS, default_k=10,
                                      size_bytes=1 << 20, dataset=ds)
                        elif op < 0.55:
                            reg.evict(name)
                        elif op < 0.70:
                            reg.demote_raw(name)
                        elif op < 0.85:
                            reg.promote_when_clear()
                        else:
                            reg.resident_bytes()
                            reg.describe()
                    except (AdmissionError, TenantUnknown):
                        pass  # typed refusals are the contract
                    except Exception as exc:  # noqa: BLE001
                        errors.append(exc)
                        return
                    if rng.random() < 0.25:
                        time.sleep(0)  # seeded yield point

            _run_threads([lambda s=i: worker(SEED + s)
                          for i in range(THREADS)])
            assert not errors, errors
            sanitize.assert_no_lock_cycles()
            sanitize.assert_no_held_lock_blocking()
            # accounting invariant: the gauge the evictor trusts equals
            # the surviving residents' bytes, via the public surface
            resident = [t for t in reg.tenants()
                        if t.state in ("warming", "serving", "degraded")]
            assert reg.resident_bytes() == sum(t.size_bytes
                                               for t in resident)
            assert reg.resident_bytes() <= reg.usable_bytes


# ---------------------------------------------------------------------------
# server fuzz
# ---------------------------------------------------------------------------

class TestServerFuzz:
    def test_submit_stop_leaves_no_unresolved_future(self, flat_index):
        """Submitters race a drain-stop: every future handed out is
        resolved (result or typed shed), and the lock order across
        batcher/registry/metrics stays acyclic."""
        with sanitize.force_lock_tracking():
            reg = serve.IndexRegistry(budget_bytes=1 << 30)
            reg.admit("t", flat_index, params=FLAT_PARAMS, default_k=10)
            server = serve.MicroBatchServer(reg, serve.ServerConfig(
                max_batch=4, queue_depth=64, linger_s=0.001,
                drain_s=2.0))
            server.start(warmup=True)
            futures = []
            fut_lock = threading.Lock()
            rng0 = np.random.default_rng(SEED)
            queries = rng0.random((THREADS, 24, DIM), dtype=np.float32)

            def submitter(idx):
                rng = random.Random(SEED + idx)
                for j in range(24):
                    try:
                        fut = server.submit("t", queries[idx, j])
                    except ShedError:
                        continue  # typed refusal, nothing dangling
                    with fut_lock:
                        futures.append(fut)
                    if rng.random() < 0.3:
                        time.sleep(0)

            threads = [threading.Thread(target=submitter, args=(i,),
                                        daemon=True)
                       for i in range(THREADS)]
            for t in threads:
                t.start()
            # stop mid-flood: drain resolves queued work, the post-join
            # sweep sheds the rest — zero unresolved futures either way
            time.sleep(0.05)
            server.stop(drain=True)
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
            # anything submitted after stop() was shed at submit();
            # everything that got a future must be resolved
            unresolved = [f for f in futures if not f.done()]
            assert not unresolved, f"{len(unresolved)} unresolved"
            ok = sum(1 for f in futures if f.exception() is None)
            assert ok > 0, "drain resolved nothing — fuzz proved nothing"
            sanitize.assert_no_lock_cycles()
            sanitize.assert_no_held_lock_blocking()


# ---------------------------------------------------------------------------
# the detectors themselves (negative controls)
# ---------------------------------------------------------------------------

class TestLockOrderTracker:
    def test_seeded_ab_ba_deadlock_is_caught(self):
        """The CI-lane negative control: an AB/BA inversion that never
        actually deadlocks in this run still raises, with both witness
        stacks in the message."""
        with sanitize.force_lock_tracking():
            a = sanitize.monitored_lock("seeded.A")
            b = sanitize.monitored_lock("seeded.B")
            with a:
                with b:
                    pass

            def inverted():
                with b:
                    with a:
                        pass

            t = threading.Thread(target=inverted, daemon=True)
            t.start()
            t.join()
            with pytest.raises(sanitize.LockOrderViolation) as ei:
                sanitize.assert_no_lock_cycles()
            msg = str(ei.value)
            assert "seeded.A" in msg and "seeded.B" in msg
            assert "held at" in msg and "acquired at" in msg

    def test_blocking_while_held_is_caught(self):
        with sanitize.force_lock_tracking():
            lock = sanitize.monitored_lock("seeded.registry")
            with lock:
                with sanitize.blocking_region("queue.get"):
                    pass
            with pytest.raises(sanitize.HeldLockBlockingCall) as ei:
                sanitize.assert_no_held_lock_blocking()
            assert "queue.get" in str(ei.value)
            assert "seeded.registry" in str(ei.value)

    def test_blocking_with_nothing_held_is_quiet(self):
        with sanitize.force_lock_tracking():
            with sanitize.blocking_region("queue.get"):
                pass
            sanitize.assert_no_held_lock_blocking()

    def test_rlock_reentrancy_is_not_an_edge(self):
        with sanitize.force_lock_tracking():
            r = sanitize.monitored_rlock("seeded.R")
            with r:
                with r:
                    pass
            assert sanitize.lock_order_edges() == {}
            sanitize.assert_no_lock_cycles()

    def test_condition_wait_strips_held_entries(self):
        """A waiter parked in cond.wait() does not 'hold' its lock: the
        notifier's acquisitions inside the wait window record no edge
        against the waiter."""
        with sanitize.force_lock_tracking():
            cond = sanitize.monitored_condition("seeded.C")
            other = sanitize.monitored_lock("seeded.other")
            woke = []

            def waiter():
                with cond:
                    while not woke:
                        cond.wait(timeout=5)

            t = threading.Thread(target=waiter, daemon=True)
            t.start()
            time.sleep(0.05)
            with other:
                pass  # no monitored lock held here → no edge
            with cond:
                woke.append(1)
                cond.notify_all()
            t.join(timeout=10)
            assert not t.is_alive()
            sanitize.assert_no_lock_cycles()

    def test_counters_and_edges_are_observable(self):
        with sanitize.force_lock_tracking():
            a = sanitize.monitored_lock("seeded.outer")
            b = sanitize.monitored_lock("seeded.inner")
            with a:
                with b:
                    pass
            edges = sanitize.lock_order_edges()
            assert ("seeded.outer", "seeded.inner") in edges
            held_at, got_at = edges[("seeded.outer", "seeded.inner")]
            assert "test_concurrency" in held_at
            assert "test_concurrency" in got_at
            counts = sanitize.lock_tracker_counts()
            assert counts["sanitize.lock.acquire"] == 2
            sanitize.reset_lock_tracker()
            assert sanitize.lock_order_edges() == {}
            assert sanitize.lock_tracker_counts() == {}

    def test_factories_match_lane(self):
        """Off the sanitize lane the factories return plain stdlib
        primitives (zero wrapper); on it, monitored wrappers."""
        lock = sanitize.monitored_lock("lane.check")
        if sanitize.lock_tracking_enabled():
            assert type(lock).__name__ == "_MonitoredLock"
        else:
            assert isinstance(lock, type(threading.Lock()))
        with sanitize.force_lock_tracking():
            forced = sanitize.monitored_lock("lane.forced")
            assert type(forced).__name__ == "_MonitoredLock"


# ---------------------------------------------------------------------------
# lane closer — keep this test LAST in the module (and list this module
# last on the sanitize lane's pytest command line)
# ---------------------------------------------------------------------------

def test_zz_no_lock_cycles_after_suite():
    """Asserts over the PROCESS-WIDE tracker: in the sanitize lane every
    serve/quality/tiered test before this point recorded its real lock
    acquisitions here, and none of them may have produced a cycle or a
    blocking-while-held. Off the lane the graph is empty and this is
    vacuously green."""
    sanitize.assert_no_lock_cycles()
    sanitize.assert_no_held_lock_blocking()
