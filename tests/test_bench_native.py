"""Native components + bench harness
(reference: cpp/bench/ann dataset/driver; refine_host-inl.hpp).
"""

import os

import numpy as np
import pytest

from raft_tpu import native
from raft_tpu.bench import dataset as ds_mod
from raft_tpu.bench import runner


def test_bin_roundtrip(tmp_path, rng):
    a = rng.random((50, 9), dtype=np.float32)
    p = str(tmp_path / "x.fbin")
    native.bin_write(p, a)
    assert native.bin_header(p) == (50, 9)
    np.testing.assert_array_equal(native.bin_read(p, np.float32), a)
    np.testing.assert_array_equal(native.bin_read(p, np.float32, offset=7, count=11), a[7:18])


def test_bin_read_out_of_range(tmp_path, rng):
    p = str(tmp_path / "y.fbin")
    native.bin_write(p, rng.random((10, 4), dtype=np.float32))
    with pytest.raises(IOError):
        native.bin_read(p, np.float32, offset=5, count=20)


@pytest.mark.skipif(not native.available(), reason="native lib unavailable")
def test_refine_host_matches_numpy(rng):
    x = rng.random((300, 12), dtype=np.float32)
    q = rng.random((15, 12), dtype=np.float32)
    cand = rng.integers(0, 300, (15, 40)).astype(np.int32)
    cand[0, :5] = -1  # invalid slots
    d, i = native.refine_host(x, q, cand, k=6, metric="sqeuclidean")
    full = ((q[:, None, :] - x[np.maximum(cand, 0)]) ** 2).sum(-1)
    full[cand < 0] = np.inf
    pos = np.argsort(full, axis=1)[:, :6]
    want_i = np.take_along_axis(cand, pos, 1)
    want_d = np.take_along_axis(full, pos, 1)
    np.testing.assert_allclose(np.sort(d, 1), np.sort(want_d, 1), rtol=1e-5)
    assert np.array_equal(np.sort(i, 1), np.sort(want_i, 1))


@pytest.mark.skipif(not native.available(), reason="native lib unavailable")
def test_refine_host_inner_product(rng):
    x = rng.random((100, 8), dtype=np.float32)
    q = rng.random((5, 8), dtype=np.float32)
    cand = np.tile(np.arange(100, dtype=np.int32), (5, 1))
    d, i = native.refine_host(x, q, cand, k=3, metric="inner_product")
    full = q @ x.T
    want_i = np.argsort(-full, axis=1)[:, :3]
    assert np.array_equal(np.sort(i, 1), np.sort(want_i, 1))
    assert (np.diff(d, axis=1) <= 1e-6).all()  # descending similarity


def test_dataset_write_load(tmp_path, rng):
    ds = ds_mod.make_synthetic("t", 200, 8, 20, seed=1)
    ds_mod.compute_groundtruth(ds, k=10)
    ds_mod.write_dataset(str(tmp_path), ds)
    back = ds_mod.load_dataset(str(tmp_path), "t")
    np.testing.assert_array_equal(back.base, ds.base)
    np.testing.assert_array_equal(back.groundtruth, ds.groundtruth)
    sub = ds_mod.load_dataset(str(tmp_path), "t", max_rows=50)
    assert sub.base.shape == (50, 8)


def test_runner_end_to_end():
    config = {
        "dataset": {"name": "tiny", "n": 2000, "dim": 16, "n_queries": 100},
        "k": 5,
        "batch_size": 100,
        "index": [
            {"name": "bf", "algo": "brute_force", "build_param": {},
             "search_params": [{}]},
            {"name": "ivf", "algo": "ivf_flat",
             "build_param": {"n_lists": 8},
             "search_params": [{"n_probes": 4}, {"n_probes": 8}]},
        ],
    }
    results = runner.run_config(config, verbose=False)
    assert len(results) == 3
    bf = results[0]
    assert bf.recall == pytest.approx(1.0)
    assert bf.qps > 0 and bf.build_s >= 0
    # full-probe ivf over clustered data must be near-exact
    assert results[2].recall > 0.95
    front = runner.pareto_frontier(results)
    assert front and all(front[i].qps <= front[i + 1].qps for i in range(len(front) - 1))


def test_runner_memmap_dir_chunked_build(tmp_path):
    """The DEEP-100M-shaped path at subset scale: on-disk dataset dir,
    memmapped base, chunked IVF-PQ build (reference: run/conf/deep-1B.json
    + dataset.hpp subsets)."""
    # no groundtruth on disk: the runner recomputes it on the subset
    ds = ds_mod.make_synthetic("deep-shaped", 4000, 32, 100, seed=3)
    ds_mod.write_dataset(str(tmp_path), ds)
    config = {
        "dataset": {"dir": str(tmp_path), "name": "deep-shaped",
                    "metric": "sqeuclidean", "mmap": True, "max_rows": 3000},
        "k": 10,
        "batch_size": 100,
        "index": [
            {"name": "ivf_pq.chunked", "algo": "ivf_pq",
             "build_param": {"n_lists": 16, "pq_dim": 16,
                             "chunked_build": True, "chunk_rows": 512},
             "search_params": [{"n_probes": 16}]},
        ],
    }
    results = runner.run_config(config, verbose=False)
    assert len(results) == 1
    assert results[0].qps > 0
    assert results[0].recall >= 0.5


def test_subset_load_drops_full_groundtruth(tmp_path, rng):
    """GT computed over the full base is unreachable on a subset — it must
    be dropped so callers recompute, not silently deflate recall."""
    ds = ds_mod.make_synthetic("g", 300, 8, 10, seed=2)
    ds_mod.compute_groundtruth(ds, k=5)
    ds_mod.write_dataset(str(tmp_path), ds)
    full = ds_mod.load_dataset(str(tmp_path), "g")
    assert full.groundtruth is not None
    sub = ds_mod.load_dataset(str(tmp_path), "g", max_rows=100)
    assert sub.groundtruth is None


def test_refine_gathered_matches_device(rng):
    """Host-gather refine (memmap path) must equal the device refine."""
    import jax.numpy as jnp
    from raft_tpu.neighbors import refine

    x = rng.random((500, 16), dtype=np.float32)
    q = rng.random((20, 16), dtype=np.float32)
    cand = rng.integers(0, 500, (20, 30)).astype(np.int32)
    cand[0, 5] = -1  # invalid slot
    d1, i1 = refine.refine(jnp.asarray(x), jnp.asarray(q),
                           jnp.asarray(cand), 10)
    d2, i2 = refine.refine_gathered(x, jnp.asarray(q), cand, 10)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


def test_deep100m_conf_parses():
    import json, os
    conf = os.path.join(os.path.dirname(runner.__file__), "conf",
                        "deep-100m.json")
    with open(conf) as f:
        cfg = json.load(f)
    assert cfg["dataset"]["mmap"] is True
    assert cfg["index"][0]["build_param"]["chunked_build"] is True


def test_runner_rejects_unknown_algo():
    with pytest.raises(ValueError):
        runner.run_config(
            {"dataset": {"name": "x", "n": 100, "dim": 4, "n_queries": 5},
             "index": [{"algo": "hnsw"}]},
            verbose=False,
        )


def test_export_csv(tmp_path):
    rows = [runner.BenchResult("bf", "bf", "d", 10, 100, 1.0, 0.1, 1000.0, 0.99)]
    p = str(tmp_path / "out.csv")
    runner.export_csv(rows, p)
    text = open(p).read()
    assert "qps" in text and "1000.0" in text


def test_hdf5_ingest_roundtrip(tmp_path, rng):
    """convert_hdf5 writes a loadable dataset dir (reference:
    get_dataset/__main__.py:34 convert_hdf5_to_fbin)."""
    import h5py

    from raft_tpu.bench import ingest

    base = rng.random((100, 8), dtype=np.float32)
    q = rng.random((10, 8), dtype=np.float32)
    nb = rng.integers(0, 100, (10, 5)).astype(np.int32)
    h5 = tmp_path / "toy-8-angular.hdf5"
    with h5py.File(h5, "w") as f:
        f["train"] = base
        f["test"] = q
        f["neighbors"] = nb
        f["distances"] = rng.random((10, 5), dtype=np.float32)
    d = ingest.convert_hdf5(str(h5), str(tmp_path), normalize=True)
    assert d.endswith("toy-8-inner")  # angular → inner rename
    ds = ds_mod.load_dataset(str(tmp_path), "toy-8-inner")
    norm = base / np.linalg.norm(base, axis=1, keepdims=True)
    np.testing.assert_allclose(ds.base, norm, rtol=1e-6)
    np.testing.assert_array_equal(ds.groundtruth, nb)


def test_split_groundtruth(tmp_path, rng):
    """big-ann gt binary → ibin/fbin pair (reference: split_groundtruth)."""
    import struct

    from raft_tpu.bench import ingest

    ids = rng.integers(0, 1000, (20, 10)).astype(np.int32)
    dist = rng.random((20, 10), dtype=np.float32)
    gt = tmp_path / "gt.bin"
    with open(gt, "wb") as f:
        f.write(struct.pack("<ii", 20, 10))
        f.write(ids.tobytes())
        f.write(dist.tobytes())
    out = ingest.split_groundtruth(str(gt))
    got_ids = native.bin_read(os.path.join(out, "groundtruth.ibin"), np.int32)
    got_d = native.bin_read(os.path.join(out, "groundtruth_dist.fbin"),
                            np.float32)
    np.testing.assert_array_equal(got_ids, ids)
    np.testing.assert_allclose(got_d, dist)


def test_plot_outputs(tmp_path):
    """plot module renders search + build figures from a results CSV
    (reference: plot/__main__.py)."""
    from raft_tpu.bench import plot as plot_mod

    rows = [runner.BenchResult(
        algo="ivf_flat", index_name=f"ivf.{i}", dataset="toy", k=10,
        batch_size=100, build_s=1.0 + i, search_s=0.1, qps=1000.0 * (i + 1),
        recall=0.9 + 0.03 * i, search_param={"n_probes": 2 ** i})
        for i in range(3)]
    csv_path = tmp_path / "res.csv"
    runner.export_csv(rows, str(csv_path))
    back = plot_mod.read_csv(str(csv_path))
    assert len(back) == 3 and back[0].search_param == {"n_probes": 1}
    out = plot_mod.plot_search(back, str(tmp_path / "s.png"))
    assert os.path.getsize(out) > 1000
    out2 = plot_mod.plot_build(back, str(tmp_path / "b.png"))
    assert os.path.getsize(out2) > 1000


def test_chunked_groundtruth_matches_exact(rng):
    """The streaming GT path (memmap-scale bases) must agree with the
    in-HBM brute force path."""
    base = rng.random((5000, 16), dtype=np.float32)
    q = rng.random((300, 16), dtype=np.float32)
    ds = ds_mod.Dataset(name="t", base=base, queries=q)
    ds_mod.compute_groundtruth(ds, k=10, device_budget=1, chunk_rows=1024,
                               max_queries=200)
    d = ((q[:200, :, None] - base.T[None]) ** 2).sum(1)
    exact = np.argsort(d, axis=1)[:, :10]
    got = ds.groundtruth
    assert got.shape == (200, 10)
    # allow distance ties to permute ids: compare via distances
    dg = np.take_along_axis(d, got, axis=1)
    de = np.take_along_axis(d, exact, axis=1)
    np.testing.assert_allclose(np.sort(dg, 1), np.sort(de, 1),
                               rtol=1e-4, atol=1e-4)
