"""Driver-contract tests for bench.py's record machinery (no device
work): headline selection, stamp verification, and the
always-emits-JSON property under SIGTERM. Round 4's record was lost to
exactly this machinery not existing (BENCH_r04: rc=124, parsed=null).
"""
import importlib.util
import hashlib
import json
import os
import signal
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _row(dataset, algo, qps, recall, index="i"):
    return {"dataset": dataset, "algo": algo, "index": index,
            "qps": qps, "recall": recall, "build_s": 1.0,
            "search_param": {}}


def test_headline_prefers_recall_bar(bench):
    bench.STATE["detail"] = [
        _row("sift-1m-hard-synth", "ivf_flat", 200_000, 0.90, "fast"),
        _row("sift-1m-hard-synth", "ivf_flat", 70_000, 0.96, "good"),
        _row("sift-1m-hard-synth", "brute_force", 20_000, 1.0),
    ]
    p = bench._payload()
    assert p["metric"].startswith("ann_qps_at_recall95")
    assert p["value"] == 70_000 and p["best_algo"] == "good"


def test_headline_flags_missed_bar(bench):
    bench.STATE["detail"] = [
        _row("sift-1m-hard-synth", "ivf_flat", 200_000, 0.90)]
    assert bench._payload()["metric"] == \
        "ann_qps_below_recall_bar_hard1m_b10000_k10"


def test_headline_brute_force_only_is_not_ann(bench):
    bench.STATE["detail"] = [
        _row("sift-1m-hard-synth", "brute_force", 20_000, 1.0)]
    assert bench._payload()["metric"] == "brute_force_qps_hard1m_b10000_k10"


def test_stamp_verification(bench, tmp_path):
    idx = tmp_path / "pq.idx"
    idx.write_bytes(b"x" * 4096)
    st = os.stat(idx)
    h = hashlib.sha256(b"x" * 4096).hexdigest()[:16]
    good = {"index_bytes": st.st_size, "index_mtime": int(st.st_mtime),
            "index_sha16m": h}
    assert bench._verify_stamp(str(tmp_path), good)
    assert not bench._verify_stamp(str(tmp_path), None)
    assert not bench._verify_stamp(
        str(tmp_path), {**good, "index_bytes": 1})
    assert not bench._verify_stamp(
        str(tmp_path), {**good, "index_sha16m": "0" * 16})


def test_sigterm_emits_record():
    # a real subprocess: SIGTERM mid-run must still print a JSON line
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import bench, time\n"
        "import signal\n"
        "signal.signal(signal.SIGTERM, bench._die)\n"
        "bench.STATE['detail'].append({'dataset': 'sift-1m-hard-synth',"
        " 'algo': 'ivf_flat', 'index': 'i', 'qps': 5.0, 'recall': 0.99,"
        " 'build_s': 1.0, 'search_param': {}})\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n" % ROOT
    )
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE, text=True)
    assert p.stdout.readline().strip() == "ready"
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=30)
    payload = json.loads(out.strip().splitlines()[-1])
    assert payload["detail"][0]["qps"] == 5.0
    assert any("signal" in n for n in payload["notes"])


def test_sigterm_stamps_flight_dump_path(tmp_path):
    """ISSUE 5 acceptance: a bench run killed by SIGTERM leaves a
    flight dump whose path appears in the partial record's notes (the
    recorder only arms once raft_tpu is imported — as the runner legs
    do — so the child imports it before waiting)."""
    code = (
        "import sys, os; sys.path.insert(0, %r)\n"
        "os.environ['RAFT_TPU_FLIGHT_DIR'] = %r\n"
        "import bench, time, signal\n"
        "import raft_tpu  # the runner legs would have imported it\n"
        "bench._install_flight()\n"
        "signal.signal(signal.SIGTERM, bench._die)\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n" % (ROOT, str(tmp_path))
    )
    p = subprocess.Popen([sys.executable, "-c", code],
                         stdout=subprocess.PIPE, text=True)
    for line in p.stdout:
        if line.strip() == "ready":
            break
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=60)
    payload = json.loads(out.strip().splitlines()[-1])
    stamped = [n for n in payload["notes"] if n.startswith("flight dump: ")]
    assert stamped, payload["notes"]
    dump_path = stamped[0][len("flight dump: "):]
    assert os.path.dirname(dump_path) == str(tmp_path)
    with open(dump_path) as f:
        doc = json.load(f)
    assert doc["reason"].startswith("signal")
    assert "metrics" in doc and "events" in doc


class TestGistConf:
    """GIST-960 leg wiring (ISSUE 4 satellite: BASELINE config 4 has
    recorded zero rows in five rounds — the conf now lives in
    raft_tpu/bench/conf and this CPU-shaped smoke proves the wiring
    produces rows every CI round)."""

    CONF = os.path.join(ROOT, "raft_tpu", "bench", "conf", "gist-960.json")

    def _load(self):
        with open(self.CONF) as f:
            return json.load(f)

    def test_conf_schema(self):
        cfg = self._load()
        assert cfg["dataset"]["name"] == "gist-960-euclidean"
        assert cfg["k"] == 10
        algos = {i["algo"] for i in cfg["index"]}
        assert algos == {"cagra", "ivf_flat", "ivf_pq"}
        # BASELINE config 4: CAGRA graph_degree=64 on GIST-1M
        cagra = next(i for i in cfg["index"] if i["algo"] == "cagra")
        assert cagra["build_param"]["graph_degree"] == 64
        # ISSUE 11: the fp8-QLUT recall-delta legs — the lut_dtype
        # triple at FIXED search params, per dataset
        pq = next(i for i in cfg["index"] if i["algo"] == "ivf_pq")
        dtype_legs = [sp for sp in pq["search_params"]
                      if "lut_dtype" in sp]
        triple = [sp["lut_dtype"] for sp in dtype_legs]
        assert triple == ["float32", "bfloat16", "float8_e4m3"]
        fixed = [{k: v for k, v in sp.items() if k != "lut_dtype"}
                 for sp in dtype_legs]
        assert all(f == fixed[0] for f in fixed)
        # ISSUE 12: the filtered-search legs — the selectivity sweep on
        # the fused tier plus the 10% forced-fallback twin (leg_env
        # pins the pre-ISSUE-12 tier for the cliff comparison)
        filt = [sp for sp in pq["search_params"]
                if "filter_selectivity" in sp]
        fused = sorted(sp["filter_selectivity"] for sp in filt
                       if "leg_env" not in sp)
        assert fused == [0.01, 0.1, 0.5], filt
        forced = [sp for sp in filt if "leg_env" in sp]
        assert len(forced) == 1 and forced[0]["filter_selectivity"] == 0.1
        assert forced[0]["leg_env"] == {
            "RAFT_TPU_PALLAS_LUTSCAN": "never"}, forced

    @pytest.mark.slow  # full runner pass over every conf entry; the CI bench legs run the same smoke (tier-1 budget)
    def test_cpu_shaped_smoke(self):
        """Run the conf's index entries through the real runner on a
        tiny 960-d synthetic (the dataset dir is absent on CI): every
        entry must produce rows — the exact property the leg lacked."""
        from raft_tpu.bench import runner

        cfg = self._load()
        cfg["dataset"] = {"name": "gist-960-smoke", "n": 600, "dim": 960,
                          "n_queries": 40,
                          "metric": cfg["dataset"]["metric"]}
        cfg["batch_size"] = 40
        # CPU-shaped shrink of the build/search params only — the
        # wiring (algos, refine_ratio leg, runner plumbing) is what the
        # smoke exercises, not 1M-scale QPS
        for entry in cfg["index"]:
            if entry["algo"] == "cagra":
                entry["build_param"]["graph_degree"] = 8
                entry["search_params"] = [{"itopk_size": 16,
                                           "search_width": 4}]
                continue
            entry["build_param"]["n_lists"] = 8
            entry["build_param"].pop("spill", None)
            entry["build_param"].pop("list_size_cap_factor", None)
            if entry["algo"] == "ivf_pq":
                # keep the lut_dtype triple (the legs under test),
                # shrink everything else to CPU shape
                entry["build_param"]["pq_dim"] = 16
                entry["search_params"] = [
                    {"n_probes": 4, "scan_select": "approx",
                     "refine_ratio": 4, "lut_dtype": dt}
                    for dt in ("float32", "bfloat16", "float8_e4m3")]
            else:
                entry["search_params"] = [
                    {"n_probes": 4, "scan_select": "approx"},
                    {"n_probes": 4, "scan_select": "approx",
                     "refine_ratio": 4}]
        rows = runner.run_config(cfg, verbose=False)
        by_algo = {}
        for r in rows:
            by_algo.setdefault(r.algo, []).append(r)
        assert set(by_algo) == {"cagra", "ivf_flat", "ivf_pq"}, \
            by_algo.keys()
        assert len(by_algo["ivf_flat"]) == 2
        # one row per lut_dtype leg, recall recorded on each (the
        # recall-delta rows the fp8 default is judged by)
        assert len(by_algo["ivf_pq"]) == 3
        assert all(r.qps > 0 and 0.0 <= r.recall <= 1.0 for r in rows)
