"""obs.index_stats — index-health introspection (ISSUE 16 tentpole b).

The structural-quality contract under test: list skew / dead-centroid
stats from a size vector, the host code unpack agrees bit-for-bit with
the build's ``pack_bits_np`` layout, centroid drift is ~zero right
after a build and grows when centers are displaced, the PQ
per-subspace error is computed through the index's own
rotation/codebooks and bounded by the residual energy, ``describe_index``
never raises, and ``note_index_stats`` publishes ``index.*{index=}``
gauges only while obs is recording.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.obs import index_stats
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.neighbors import ivf_flat, ivf_pq


@pytest.fixture(autouse=True)
def _quiet_obs():
    yield
    obs.disable()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    return rng.random((2000, 16), dtype=np.float32)


@pytest.fixture(scope="module")
def skewed_data():
    # deliberately skewed: a dense blob plus a thin uniform background,
    # so k-means lists end up visibly uneven
    rng = np.random.default_rng(1)
    blob = rng.normal(0.5, 0.01, size=(1800, 16)).astype(np.float32)
    bg = rng.random((200, 16), dtype=np.float32)
    return np.concatenate([blob, bg])


@pytest.fixture(scope="module")
def flat_index(data):
    return ivf_flat.build(jnp.asarray(data),
                          ivf_flat.IndexParams(n_lists=16))


@pytest.fixture(scope="module")
def pq_index(data):
    return ivf_pq.build(jnp.asarray(data), ivf_pq.IndexParams(
        n_lists=16, pq_dim=8, seed=0, cache_reconstruction="never"))


class TestListStats:
    def test_known_vector(self):
        st = index_stats.list_stats([4, 0, 8, 4])
        assert st["n_lists"] == 4 and st["size"] == 16
        assert st["dead"] == 1 and st["max"] == 8
        assert st["max_mean"] == pytest.approx(2.0)
        assert st["cv"] == pytest.approx(np.std([4, 0, 8, 4]) / 4.0)

    def test_uniform_has_zero_skew(self):
        st = index_stats.list_stats([5, 5, 5, 5])
        assert st["cv"] == 0.0 and st["max_mean"] == 1.0
        assert st["dead"] == 0

    def test_empty(self):
        st = index_stats.list_stats(np.zeros((0,), np.int32))
        assert st["n_lists"] == 0 and st["size"] == 0

    def test_skewed_build_shows_skew(self, skewed_data, data):
        skewed = ivf_flat.build(jnp.asarray(skewed_data),
                                ivf_flat.IndexParams(n_lists=16))
        even = ivf_flat.build(jnp.asarray(data),
                              ivf_flat.IndexParams(n_lists=16))
        st_skew = index_stats.list_stats(skewed.list_sizes)
        st_even = index_stats.list_stats(even.list_sizes)
        assert st_skew["cv"] > st_even["cv"]


class TestUnpack:
    @pytest.mark.parametrize("pq_bits", [4, 5, 8])
    def test_roundtrips_pack_bits_np(self, pq_bits):
        rng = np.random.default_rng(pq_bits)
        codes = rng.integers(0, 1 << pq_bits,
                             size=(192, 10)).astype(np.uint8)
        packed = ivf_pq.pack_bits_np(codes, pq_bits)
        got = index_stats._unpack_codes_np(packed, 10, pq_bits)
        np.testing.assert_array_equal(got, codes)
        # and through an extra leading (list) axis, the layout the
        # introspection actually reads
        stacked = packed.reshape(6, 32, -1)
        got3 = index_stats._unpack_codes_np(stacked, 10, pq_bits)
        np.testing.assert_array_equal(got3.reshape(192, 10), codes)


class TestDrift:
    def test_fresh_flat_build_low_drift(self, flat_index):
        d = index_stats.centroid_drift(flat_index)
        assert d["lists_sampled"] > 0
        # k-means centers ARE (near) their members' means
        assert d["rel_mean"] < 0.25

    def test_displaced_centers_raise_drift(self, flat_index):
        base = index_stats.centroid_drift(flat_index)
        shifted = flat_index.replace(
            centers=flat_index.centers + 0.5)
        moved = index_stats.centroid_drift(shifted)
        assert moved["mean"] > base["mean"] * 2

    def test_pq_drift_from_decoded_residuals(self, pq_index):
        d = index_stats.centroid_drift(pq_index)
        assert d is not None and d["lists_sampled"] > 0
        assert np.isfinite(d["mean"]) and d["mean"] >= 0.0

    def test_non_index_object_is_none(self):
        class Bare:
            list_sizes = np.array([1, 1])

        assert index_stats.centroid_drift(Bare()) is None


class TestPqError:
    def test_error_bounded_by_residual_energy(self, pq_index, data):
        st = index_stats.pq_subspace_error(pq_index, data, sample_rows=512)
        assert st["rows_sampled"] == 512
        assert len(st["per_subspace_mse"]) == pq_index.pq_dim
        assert all(e >= 0.0 for e in st["per_subspace_mse"])
        # quantization can only lose a FRACTION of residual energy
        assert 0.0 < st["rel_error"] < 1.0

    def test_flat_index_is_none(self, flat_index, data):
        assert index_stats.pq_subspace_error(flat_index, data) is None

    def test_no_dataset_is_none(self, pq_index):
        assert index_stats.pq_subspace_error(pq_index, None) is None


class TestDescribe:
    def test_full_snapshot(self, pq_index, data):
        st = index_stats.describe_index(pq_index, data, sample_rows=256)
        assert st["kind"] == "IvfPqIndex"
        assert st["lists"]["n_lists"] == 16
        assert st["tombstone_density"] == 0.0
        assert st["drift"]["lists_sampled"] > 0
        assert st["pq"]["rows_sampled"] == 256
        assert "error" not in st

    def test_never_raises_on_garbage(self):
        st = index_stats.describe_index(object())
        assert "error" in st and st["kind"] == "object"


class TestNoteIndexStats:
    def test_publishes_gauges_when_recording(self, flat_index):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        st = index_stats.note_index_stats(flat_index, name="acme",
                                          cheap=True)
        assert st is not None
        g = obs.registry().snapshot()["gauges"]
        assert g["index.n_lists{index=acme}"] == 16.0
        assert g["index.size{index=acme}"] == 2000.0
        assert "index.tombstone_density{index=acme}" in g
        assert "index.list_cv{index=acme}" in g

    def test_noop_when_obs_off(self, flat_index):
        obs.disable()
        assert index_stats.note_index_stats(flat_index, name="acme",
                                            cheap=True) is None

    def test_precomputed_stats_publish_even_with_full_describe(
            self, pq_index, data):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        stats = index_stats.describe_index(pq_index, data,
                                           sample_rows=128)
        index_stats.note_index_stats(pq_index, name="pq", stats=stats)
        g = obs.registry().snapshot()["gauges"]
        assert "index.pq_err_rel{index=pq}" in g
        assert "index.drift_rel{index=pq}" in g

    def test_build_paths_emit_gauges(self, data):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        ivf_flat.build(jnp.asarray(data),
                       ivf_flat.IndexParams(n_lists=8))
        g = obs.registry().snapshot()["gauges"]
        assert g["index.n_lists{index=ivf_flat.build}"] == 8.0

    def test_extend_emits_gauges(self, data, flat_index):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        ivf_flat.extend(flat_index, jnp.asarray(data[:64]))
        g = obs.registry().snapshot()["gauges"]
        assert g["index.size{index=ivf_flat.extend}"] == 2064.0
