"""raft_tpu.robust — fault injection, retry policy, degradation ladder
(ISSUE 7 tentpole; docs/developer_guide.md "Robustness")."""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.robust import degrade, faults, retry


@pytest.fixture(autouse=True)
def _clean_plan():
    """Fault plans are process-global — leave none behind."""
    faults.clear_plan()
    yield
    faults.clear_plan()
    obs.disable()


def _counters(reg):
    return reg.snapshot()["counters"]


# ---------------------------------------------------------------------------
# faults
# ---------------------------------------------------------------------------

class TestFaults:
    def test_no_plan_is_a_noop(self):
        assert faults.faultpoint("anything") is None
        assert faults.fires() == {}

    def test_error_kind_raises_transient(self):
        faults.install_plan({"faults": [{"site": "s", "kind": "error"}]})
        with pytest.raises(faults.FaultInjected) as ei:
            faults.faultpoint("s")
        assert ei.value.transient is True
        assert ei.value.site == "s"

    def test_oom_kind_matches_resource_exhausted(self):
        faults.install_plan({"faults": [{"site": "s", "kind": "oom"}]})
        with pytest.raises(faults.InjectedResourceExhausted) as ei:
            faults.faultpoint("s")
        assert degrade.is_resource_exhausted(ei.value)
        assert ei.value.transient is False  # never blind-retried
        assert not retry.default_retryable(ei.value)

    def test_after_and_times_semantics(self):
        faults.install_plan({"faults": [
            {"site": "s", "kind": "error", "after": 3, "times": 2}]})
        assert faults.faultpoint("s") is None  # hit 1
        assert faults.faultpoint("s") is None  # hit 2
        for _ in range(2):                     # hits 3, 4 fire
            with pytest.raises(faults.FaultInjected):
                faults.faultpoint("s")
        assert faults.faultpoint("s") is None  # times cap reached
        assert faults.fires() == {"s": 2}

    def test_probability_is_deterministic_by_seed(self):
        spec = {"seed": 42, "faults": [
            {"site": "s", "kind": "nan", "p": 0.5, "times": 0}]}
        runs = []
        for _ in range(2):
            faults.install_plan(dict(spec))
            runs.append([faults.faultpoint("s") for _ in range(20)])
        assert runs[0] == runs[1]
        assert "nan" in runs[0] and None in runs[0]  # both outcomes occur

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.install_plan({"faults": [{"site": "s",
                                             "kind": "explode"}]})

    def test_corrupt_nan_poisons_floats(self):
        faults.install_plan({"faults": [{"site": "s", "kind": "nan"}]})
        out = faults.corrupt("s", np.ones((3,), np.float32))
        assert np.isnan(out).all()
        assert np.array_equal(faults.corrupt("s", np.ones(3)),
                              np.ones(3))  # times=1 consumed

    def test_forced(self):
        faults.install_plan({"faults": [{"site": "g", "kind": "force"}]})
        assert faults.forced("g") is True
        assert faults.forced("g") is False  # consumed
        assert faults.forced("other") is False

    def test_env_inline_plan_arms_lazily(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FAULT_PLAN_JSON",
                           '{"faults": [{"site": "e", "kind": "force"}]}')
        monkeypatch.setattr(faults, "_plan", None)
        monkeypatch.setattr(faults, "_env_checked", False)
        assert faults.forced("e") is True
        faults.clear_plan()

    def test_fired_counter(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [{"site": "c", "kind": "force"}]})
        assert faults.forced("c")
        assert _counters(reg)["faults.fired{kind=force,site=c}"] == 1.0


# ---------------------------------------------------------------------------
# retry
# ---------------------------------------------------------------------------

class _Flaky:
    def __init__(self, fail_times, exc_factory):
        self.calls = 0
        self.fail_times = fail_times
        self.exc_factory = exc_factory

    def __call__(self):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise self.exc_factory()
        return "ok"


class TestRetry:
    def test_first_try_success_never_sleeps(self):
        slept = []
        st = {}
        out = retry.retry_call(lambda: 7, site="s", stats=st,
                               sleep=slept.append)
        assert out == 7 and st["attempts"] == 1 and not slept
        assert st["outcome"] == "ok"

    def test_transient_then_success_recovers(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        fn = _Flaky(2, lambda: OSError("read hiccup"))
        slept = []
        st = {}
        policy = retry.RetryPolicy(max_attempts=3, base_delay_s=0.1,
                                   multiplier=2.0, jitter=0.0)
        assert retry.retry_call(fn, site="io", policy=policy, stats=st,
                                sleep=slept.append) == "ok"
        assert st["attempts"] == 3 and st["outcome"] == "recovered"
        assert slept == [0.1, 0.2]  # exponential, jitter off
        c = _counters(reg)
        assert c["retry.attempts{site=io}"] == 3.0
        assert c["retry.recovered{site=io}"] == 1.0

    def test_exhausted_raises_with_cause_and_counter(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        fn = _Flaky(99, lambda: TimeoutError("still down"))
        policy = retry.RetryPolicy(max_attempts=2, base_delay_s=0.0)
        with pytest.raises(retry.RetryExhausted) as ei:
            retry.retry_call(fn, site="s", policy=policy,
                             sleep=lambda d: None)
        assert ei.value.attempts == 2
        assert isinstance(ei.value.last, TimeoutError)
        assert _counters(reg)["retry.exhausted{site=s}"] == 1.0

    def test_non_retryable_propagates_unwrapped(self):
        st = {}
        with pytest.raises(ValueError):
            retry.retry_call(_Flaky(9, lambda: ValueError("logic bug")),
                             site="s", stats=st, sleep=lambda d: None)
        assert st["attempts"] == 1 and st["outcome"] == "fatal"

    def test_oom_is_never_retried(self):
        fn = _Flaky(9, lambda: RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 7 bytes"))
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            retry.retry_call(fn, site="s", sleep=lambda d: None)
        assert fn.calls == 1

    def test_jitter_bounds(self):
        slept = []
        fn = _Flaky(1, lambda: OSError("x"))
        policy = retry.RetryPolicy(max_attempts=2, base_delay_s=1.0,
                                   jitter=0.5)
        retry.retry_call(fn, site="s", policy=policy, sleep=slept.append)
        assert len(slept) == 1 and 0.5 <= slept[0] <= 1.5

    def test_deadline_budget_stops_early(self, monkeypatch):
        clock = [0.0]
        monkeypatch.setattr(retry.time, "monotonic", lambda: clock[0])
        fn = _Flaky(9, lambda: OSError("x"))
        policy = retry.RetryPolicy(max_attempts=10, base_delay_s=5.0,
                                   jitter=0.0, deadline_s=4.0)
        with pytest.raises(retry.RetryExhausted) as ei:
            retry.retry_call(fn, site="s", policy=policy,
                             sleep=lambda d: None)
        assert ei.value.attempts == 1  # a 5s backoff can't fit 4s budget

    def test_injected_fault_is_retryable(self):
        faults.install_plan({"faults": [
            {"site": "r", "kind": "error", "times": 1}]})

        def body():
            faults.faultpoint("r")
            return "done"

        st = {}
        assert retry.retry_call(body, site="r", stats=st,
                                sleep=lambda d: None) == "done"
        assert st["outcome"] == "recovered"

    def test_decorator(self):
        calls = []

        @retry.retrying("deco", retry.RetryPolicy(max_attempts=2,
                                                  base_delay_s=0.0))
        def fn():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("x")
            return len(calls)

        assert fn() == 2

    def test_policy_describe_mentions_knobs(self):
        s = retry.RetryPolicy(base_delay_s=15.0, jitter=0.25).describe()
        assert "15" in s and "25%" in s


# ---------------------------------------------------------------------------
# the shared request Deadline (ISSUE 14 satellite)
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestDeadline:
    def test_remaining_and_expiry(self):
        clk = _FakeClock()
        d = retry.Deadline(2.0, clock=clk)
        assert d.remaining() == pytest.approx(2.0) and not d.expired
        clk.t = 1.5
        assert d.remaining() == pytest.approx(0.5)
        clk.t = 2.5
        assert d.expired and d.remaining() == pytest.approx(-0.5)
        assert "deadline" in repr(d)

    def test_unbounded_never_expires(self):
        d = retry.Deadline(None)
        assert d.remaining() == float("inf") and not d.expired
        assert "unbounded" in d.describe()

    def test_deadline_exceeded_is_never_retryable(self):
        # the message must not collide with the grpc DEADLINE_EXCEEDED
        # transient marker: transient=False pins the classification
        e = retry.DeadlineExceeded("site", retry.Deadline(0.0))
        assert e.transient is False
        assert not retry.default_retryable(e)

    def test_expired_deadline_refuses_first_attempt(self):
        clk = _FakeClock()
        d = retry.Deadline(1.0, clock=clk)
        clk.t = 2.0
        calls = []
        st = {}
        with pytest.raises(retry.DeadlineExceeded) as ei:
            retry.retry_call(lambda: calls.append(1), site="s",
                             deadline=d, stats=st, sleep=lambda s: None)
        assert not calls and st["outcome"] == "deadline"
        assert ei.value.site == "s" and ei.value.deadline is d

    def test_exhaustion_mid_backoff(self):
        """The satellite's named case: a backoff sleep that would
        outlive the shared budget gives up instead of sleeping past
        the SLO — never actually sleeps, and surfaces the DEADLINE
        type (the request's budget ran out, not the site's policy) so
        the serving layer counts an SLO shed, not a tenant error."""
        clk = _FakeClock()
        d = retry.Deadline(1.0, clock=clk)
        clk.t = 0.9  # 0.1 s left; the next backoff wants 5 s
        fn = _Flaky(9, lambda: OSError("x"))
        slept = []
        st = {}
        policy = retry.RetryPolicy(max_attempts=10, base_delay_s=5.0,
                                   jitter=0.0)
        with pytest.raises(retry.DeadlineExceeded) as ei:
            retry.retry_call(fn, site="s", policy=policy, deadline=d,
                             stats=st, sleep=slept.append)
        assert ei.value.deadline is d and not slept
        assert st["attempts"] == 1 and st["outcome"] == "deadline"
        # the per-site policy budget alone still reads as exhausted
        fn2 = _Flaky(9, lambda: OSError("x"))
        with pytest.raises(retry.RetryExhausted):
            retry.retry_call(
                fn2, site="s", sleep=slept.append,
                policy=retry.RetryPolicy(max_attempts=10,
                                         base_delay_s=5.0, jitter=0.0,
                                         deadline_s=4.0))

    def test_shared_budget_spans_sites(self):
        """Two nested retry sites draw down ONE budget: the first
        site's backoff spend removes headroom from the second — no
        per-site deadline stacking."""
        clk = _FakeClock()
        d = retry.Deadline(1.0, clock=clk)

        def sleeper(s):
            clk.t += s

        policy = retry.RetryPolicy(max_attempts=5, base_delay_s=0.4,
                                   multiplier=1.0, jitter=0.0)
        # site A: one failure + one 0.4 s backoff, then success
        assert retry.retry_call(_Flaky(1, lambda: OSError("x")),
                                site="a", policy=policy, deadline=d,
                                sleep=sleeper) == "ok"
        assert d.remaining() == pytest.approx(0.6)
        # site B alone would retry 4 times under its per-site policy,
        # but only one more 0.4 s backoff fits the shared budget —
        # whose exhaustion surfaces as the DEADLINE type
        fn = _Flaky(9, lambda: OSError("x"))
        st = {}
        with pytest.raises(retry.DeadlineExceeded):
            retry.retry_call(fn, site="b", policy=policy, deadline=d,
                             stats=st, sleep=sleeper)
        assert st["attempts"] == 2 and st["outcome"] == "deadline"

    def test_ladder_aborts_on_expired_deadline(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        clk = _FakeClock()
        d = retry.Deadline(1.0, clock=clk)

        def call(knobs):
            clk.t += 2.0  # the attempt itself burns the budget
            raise RuntimeError("RESOURCE_EXHAUSTED: oom")

        ladder = degrade.Ladder([degrade.Step(
            "shrink", lambda kn: dict(kn, shrunk=True))])
        with pytest.raises(retry.DeadlineExceeded):
            degrade.run_with_degradation(call, {}, ladder, site="s",
                                         deadline=d)
        c = _counters(reg)
        assert c["degrade.deadline_abort{site=s}"] == 1.0
        # the rung was NEVER taken: the budget died first
        assert "degrade.steps{from=native,reason=resource_exhausted," \
            "site=s,to=shrink}" not in c

    def test_batched_call_abandons_split_past_deadline(self):
        clk = _FakeClock()
        d = retry.Deadline(1.0, clock=clk)
        seen = []

        def search_fn(index, q, k, p, fb, ds):
            seen.append(q.shape[0])
            clk.t += 1.1  # the first sub-batch overruns the budget
            return jnp.zeros((q.shape[0], k)), jnp.zeros(
                (q.shape[0], k), jnp.int32)

        queries = jnp.zeros((8, 4))
        call = degrade.batched_search_call(search_fn, None, queries, 3,
                                           None, deadline=d, site="s")
        with pytest.raises(retry.DeadlineExceeded):
            call({"params": None, "max_batch": 4})
        assert seen == [4]  # second sub-batch abandoned, not computed

    def test_unbounded_deadline_changes_nothing(self, pq_index=None):
        fn = _Flaky(1, lambda: OSError("x"))
        assert retry.retry_call(fn, site="s",
                                deadline=retry.Deadline(None),
                                sleep=lambda s: None) == "ok"


# ---------------------------------------------------------------------------
# degrade
# ---------------------------------------------------------------------------

def _oom():
    raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")


class TestDegrade:
    def test_classifier(self):
        assert degrade.is_resource_exhausted(
            RuntimeError("RESOURCE_EXHAUSTED: ..."))
        assert degrade.is_resource_exhausted(
            RuntimeError("Resource exhausted: Out of memory"))
        assert not degrade.is_resource_exhausted(ValueError("nope"))

    def test_ladder_walk_records_path_and_recovers(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        fails = [2]

        def call(knobs):
            if fails[0]:
                fails[0] -= 1
                _oom()
            return knobs

        ladder = degrade.Ladder([
            degrade.Step("a", lambda kn: {**kn, "a": 1}),
            degrade.Step("b", lambda kn: {**kn, "b": 1}),
        ])
        out = degrade.run_with_degradation(call, {}, ladder, site="t")
        assert out == {"a": 1, "b": 1}
        c = _counters(reg)
        assert c["degrade.steps{from=native,reason=resource_exhausted,"
                 "site=t,to=a}"] == 1.0
        assert c["degrade.steps{from=a,reason=resource_exhausted,"
                 "site=t,to=b}"] == 1.0
        assert c["degrade.recovered{site=t}"] == 1.0

    def test_exhausted_ladder_raises_with_path(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        ladder = degrade.Ladder([degrade.Step("only",
                                              lambda kn: {**kn, "x": 1})])
        with pytest.raises(degrade.DegradationExhausted) as ei:
            degrade.run_with_degradation(lambda kn: _oom(), {}, ladder,
                                         site="t")
        assert ei.value.path == ["only"]
        assert degrade.is_resource_exhausted(ei.value.last)
        assert _counters(reg)["degrade.exhausted{site=t}"] == 1.0

    def test_non_oom_propagates(self):
        ladder = degrade.Ladder([degrade.Step("a", lambda kn: kn)])

        def call(knobs):
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            degrade.run_with_degradation(call, {}, ladder, site="t")

    def test_repeatable_terminal_rung(self):
        fails = [3]

        def call(knobs):
            if fails[0]:
                fails[0] -= 1
                _oom()
            return knobs

        ladder = degrade.Ladder([
            degrade.Step("halve", degrade._halve_batch(8),
                         repeatable=True)])
        out = degrade.run_with_degradation(call, {}, ladder, site="t")
        assert out["max_batch"] == 1  # 8 → 4 → 2 → 1

    def test_standard_ladder_order(self):
        from raft_tpu.neighbors import ivf_pq

        ladder = degrade.standard_search_ladder(64, has_lut=True)
        knobs = {"params": ivf_pq.SearchParams(scan_select="pallas"),
                 "dataset": jnp.ones((8, 4))}
        names = []
        for _ in range(7):
            adv = ladder.advance(knobs)
            if adv is None:
                break
            step, knobs = adv
            names.append(step.name)
        # two LUT-footprint halvings (bf16 then fp8), then pallas→approx
        # and →per_query as two decline_fused moves; host_gather skipped
        # (refine off); terminal halving repeats
        assert names[:3] == ["halve_batch", "bf16_lut", "fp8_lut"]
        assert names[3:5] == ["decline_fused", "decline_fused"]
        assert set(names[5:]) == {"halve_batch"}
        assert knobs["params"].scan_select == "approx"
        assert knobs["params"].scan_mode == "per_query"
        assert knobs["params"].lut_dtype == "float8_e4m3"

    def test_host_gather_rung_moves_dataset(self):
        from raft_tpu.neighbors import ivf_pq

        params = ivf_pq.SearchParams(refine="f32_regen")
        knobs = {"params": params, "dataset": jnp.ones((8, 4))}
        out = degrade._host_gather(dict(knobs))
        assert isinstance(out["dataset"], np.ndarray)
        # already host-side → rung not applicable
        assert degrade._host_gather(dict(out)) is None


# ---------------------------------------------------------------------------
# entry-point wiring: search_resilient, mem-guard declines, comms
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pq_index():
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((2000, 32), dtype=np.float32))
    idx = ivf_pq.build(x, ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, seed=0, cache_reconstruction="never"))
    return idx, x


class TestSearchResilient:
    def test_injected_oom_completes_with_identical_results(self, pq_index):
        from raft_tpu.neighbors import ivf_pq

        idx, x = pq_index
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")
        d0, i0 = ivf_pq.search(idx, x[:64], 10, sp)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "ivf_pq.search", "kind": "oom", "times": 1}]})
        d1, i1 = ivf_pq.search_resilient(idx, x[:64], 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d0),
                                   rtol=1e-6, atol=1e-6)
        c = _counters(reg)
        assert c["degrade.steps{from=native,reason=resource_exhausted,"
                 "site=ivf_pq.search,to=halve_batch}"] == 1.0
        assert c["degrade.recovered{site=ivf_pq.search}"] == 1.0

    def test_two_injected_ooms_walk_two_rungs(self, pq_index):
        from raft_tpu.neighbors import ivf_pq

        idx, x = pq_index
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "ivf_pq.search", "kind": "oom", "times": 2}]})
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")
        d1, i1 = ivf_pq.search_resilient(idx, x[:32], 10, sp)
        assert i1.shape == (32, 10)
        c = _counters(reg)
        assert c["degrade.steps{from=halve_batch,"
                 "reason=resource_exhausted,site=ivf_pq.search,"
                 "to=bf16_lut}"] == 1.0

    def test_three_ooms_reach_the_fp8_rung(self, pq_index):
        """ISSUE 11: the fp8-LUT rung between bf16 and decline_fused —
        an injected-OOM walk lands on it (counted as
        ``degrade.steps{to=fp8_lut}``), results equal the undegraded
        search (exact top-k stable under LUT quantization at this
        scale), and the flight recorder's robust section shows the
        move."""
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu.obs import flight

        idx, x = pq_index
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")
        d0, i0 = ivf_pq.search(idx, x[:32], 10, sp)
        # what the fp8 rung's configuration produces WITHOUT any fault:
        # the degraded run must reproduce exactly this (batch splitting
        # is exact; the fp8-LUT rung is the documented precision trade,
        # so equality to the native f32 run is a recall bound, not
        # bit-equality — same contract as the bf16 rung)
        sp8 = dataclasses.replace(sp, lut_dtype="float8_e4m3")
        d8, i8 = ivf_pq.search(idx, x[:16], 10, sp8)
        d8b, i8b = ivf_pq.search(idx, x[16:32], 10, sp8)
        d8 = jnp.concatenate([d8, d8b])
        i8 = jnp.concatenate([i8, i8b])
        degrade.clear_recent()
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "ivf_pq.search", "kind": "oom", "times": 3}]})
        d1, i1 = ivf_pq.search_resilient(idx, x[:32], 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i8))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d8),
                                   rtol=1e-6, atol=1e-6)
        overlap = np.mean([len(set(a) & set(b)) / 10.0 for a, b in
                           zip(np.asarray(i1), np.asarray(i0))])
        assert overlap >= 0.9, overlap
        c = _counters(reg)
        assert c["degrade.steps{from=bf16_lut,"
                 "reason=resource_exhausted,site=ivf_pq.search,"
                 "to=fp8_lut}"] == 1.0
        assert c["degrade.recovered{site=ivf_pq.search}"] == 1.0
        # the flight recorder's black box records the walk
        recent = degrade.recent_steps()
        assert any(s["to"] == "fp8_lut" for s in recent), recent
        moves = flight._robust_state()["degrade_recent"]
        assert any(s["to"] == "fp8_lut" for s in moves), moves

    def test_filtered_fused_search_walks_the_ladder(self, pq_index,
                                                    monkeypatch):
        """ISSUE 12 chaos leg: the degrade ladder still works when the
        degrading search is a FILTERED FUSED one — an injected OOM on a
        scan_select="pallas" + filter_bitset search walks halve_batch,
        recovers, returns exactly the fault-free filtered results, and
        never leaks a filtered id."""
        from raft_tpu.core import bitset
        from raft_tpu.neighbors import ivf_pq

        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        idx, x = pq_index
        rng = np.random.default_rng(3)
        keep = rng.random(x.shape[0]) < 0.5
        bits = bitset.from_mask(jnp.asarray(keep))
        sp = ivf_pq.SearchParams(n_probes=8, scan_select="pallas")
        d0, i0 = ivf_pq.search(idx, x[:64], 10, sp, filter_bitset=bits)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "ivf_pq.search", "kind": "oom", "times": 1}]})
        d1, i1 = ivf_pq.search_resilient(idx, x[:64], 10, sp,
                                         filter_bitset=bits)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d0),
                                   rtol=1e-6, atol=1e-6)
        i1 = np.asarray(i1)
        assert keep[i1[i1 >= 0]].all()
        c = _counters(reg)
        assert c["degrade.steps{from=native,reason=resource_exhausted,"
                 "site=ivf_pq.search,to=halve_batch}"] == 1.0
        assert c["degrade.recovered{site=ivf_pq.search}"] == 1.0
        # the filtered halves re-dispatched the fused tier, and the
        # retired fallback reason stayed silent
        assert any(k.startswith("ivf_pq.scan.dispatch{filtered=1,"
                                "impl=pallas_lut}") for k in c), c
        assert c.get("ivf_pq.scan.fallback{reason=filter_bitset}",
                     0) == 0, c

    def test_no_fault_means_no_counters(self, pq_index):
        from raft_tpu.neighbors import ivf_pq

        idx, x = pq_index
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        ivf_pq.search_resilient(idx, x[:16], 5)
        assert not [k for k in _counters(reg) if k.startswith("degrade.")]

    def test_ivf_flat_resilient(self):
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.random((1500, 16), dtype=np.float32))
        idx = ivf_flat.build(x, ivf_flat.IndexParams(n_lists=8, seed=0))
        sp = ivf_flat.SearchParams(n_probes=4, scan_mode="per_query")
        d0, i0 = ivf_flat.search(idx, x[:48], 10, sp)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "ivf_flat.search", "kind": "oom", "times": 1}]})
        d1, i1 = ivf_flat.search_resilient(idx, x[:48], 10, sp)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        assert _counters(reg)[
            "degrade.steps{from=native,reason=resource_exhausted,"
            "site=ivf_flat.search,to=halve_batch}"] == 1.0


class TestMemGuardDeclines:
    def test_refine_forced_decline_counts_degrade_step(self):
        from raft_tpu.neighbors import refine as rf

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.random((500, 24), dtype=np.float32))
        q = jnp.asarray(rng.random((8, 24), dtype=np.float32))
        cand = jnp.asarray(rng.integers(0, 500, (8, 32)).astype(np.int32))
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "refine.mem_guard", "kind": "force", "times": 1}]})
        rf.refine(x, q, cand, 5)
        c = _counters(reg)
        assert c["degrade.steps{from=pallas_gather,reason=mem_guard,"
                 "site=refine,to=xla_gather}"] == 1.0
        assert c["refine.dispatch{impl=xla_gather}"] >= 1.0

    def test_lut_scan_forced_mem_guard_decline(self, pq_index):
        from raft_tpu.neighbors import ivf_pq

        idx, x = pq_index
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "ivf_pq.scan.mem_guard", "kind": "force",
             "times": 1}]})
        # an explicit pallas request forces the grouped path through
        # the mem guard; the forced decline must land on approx with
        # both the fallback reason and the degrade step recorded
        ivf_pq.search(idx, x[:64], 10, ivf_pq.SearchParams(
            n_probes=8, scan_select="pallas"))
        c = _counters(reg)
        assert c["ivf_pq.scan.fallback{reason=mem_guard}"] == 1.0
        assert c["degrade.steps{from=pallas_lut,reason=mem_guard,"
                 "site=ivf_pq.search,to=grouped_approx}"] == 1.0


class TestCommsFaultpoint:
    def test_collective_fault_fires_at_trace_time(self):
        from raft_tpu.parallel import comms as cm

        faults.install_plan({"faults": [
            {"site": "comms.allreduce", "kind": "error"}]})
        with pytest.raises(faults.FaultInjected, match="comms.allreduce"):
            cm.Comms("shard").allreduce(jnp.ones((4,)))

    def test_build_chunk_read_fault_is_retried(self):
        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(2)
        x = rng.random((1200, 16), dtype=np.float32)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "build.chunk_read", "kind": "error", "times": 1}]})
        idx = ivf_pq.build_chunked(
            x, ivf_pq.IndexParams(n_lists=8, pq_dim=8, seed=0,
                                  cache_reconstruction="never"),
            chunk_rows=400)
        assert idx.size > 0
        c = _counters(reg)
        assert c["retry.recovered{site=build.chunk_read}"] == 1.0
        assert c["retry.attempts{site=build.chunk_read}"] >= 2.0
