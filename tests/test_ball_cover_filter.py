"""Ball cover, epsilon-neighborhood, and sample filtering
(reference tests: cpp/test/neighbors/ball_cover.cu,
epsilon_neighborhood.cu, and the *_filter variants of ann tests).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import (
    ball_cover,
    brute_force,
    cagra,
    epsilon_neighborhood,
    ivf_flat,
    ivf_pq,
    sample_filter,
)


def _data(rng, n=500, d=8):
    return rng.random((n, d), dtype=np.float32)


def _truth_l2(x, q, k):
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    ids = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.sqrt(np.take_along_axis(d, ids, axis=1)), ids


# ---------------------------------------------------------------------------
# ball cover
# ---------------------------------------------------------------------------

def test_ball_cover_exact_euclidean(rng):
    x = _data(rng)
    q = _data(rng, n=40)
    index = ball_cover.build(x, metric="euclidean", seed=0)
    d, i = ball_cover.knn(index, q, k=7)
    want_d, want_i = _truth_l2(x, q, 7)
    # exact: distances must match the brute-force truth
    np.testing.assert_allclose(np.sort(np.asarray(d), 1), np.sort(want_d, 1), rtol=1e-4, atol=1e-5)
    recall = np.mean([len(set(np.asarray(i)[r]) & set(want_i[r])) / 7 for r in range(40)])
    assert recall > 0.999


def test_ball_cover_haversine(rng):
    # lat/lon radians
    pts = np.stack(
        [rng.uniform(-np.pi / 2, np.pi / 2, 300), rng.uniform(-np.pi, np.pi, 300)], axis=1
    ).astype(np.float32)
    q = pts[:20] + 0.01
    index = ball_cover.build(pts, metric="haversine")
    d, i = ball_cover.knn(index, q, k=5)
    # haversine truth
    lat1, lon1 = q[:, None, 0], q[:, None, 1]
    lat2, lon2 = pts[None, :, 0], pts[None, :, 1]
    h = (
        np.sin((lat2 - lat1) / 2) ** 2
        + np.cos(lat1) * np.cos(lat2) * np.sin((lon2 - lon1) / 2) ** 2
    )
    full = 2 * np.arcsin(np.sqrt(np.clip(h, 0, 1)))
    want_i = np.argsort(full, axis=1, kind="stable")[:, :5]
    want_d = np.take_along_axis(full, want_i, axis=1)
    np.testing.assert_allclose(np.sort(np.asarray(d), 1), np.sort(want_d, 1), rtol=1e-3, atol=1e-5)


def test_ball_cover_eps_nn(rng):
    x = _data(rng, n=200, d=4)
    q = _data(rng, n=10, d=4)
    index = ball_cover.build(x, metric="euclidean")
    eps = 0.5
    mask, ids = ball_cover.eps_nn(index, q, eps)
    mask = np.asarray(mask)
    ids = np.asarray(ids)
    # reconstruct neighbor sets and compare with truth
    full = np.sqrt(((q[:, None, :] - x[None, :, :]) ** 2).sum(-1))
    for r in range(10):
        got = set(ids[mask[r]].tolist())
        want = set(np.nonzero(full[r] <= eps)[0].tolist())
        assert got == want


def test_ball_cover_rejects_bad_metric(rng):
    with pytest.raises(Exception):
        ball_cover.build(_data(rng, n=50), metric="cosine")


# ---------------------------------------------------------------------------
# epsilon neighborhood
# ---------------------------------------------------------------------------

def test_eps_neighbors(rng):
    x = _data(rng, n=60, d=5)
    y = _data(rng, n=80, d=5)
    adj, vd = epsilon_neighborhood.eps_neighbors_l2sq(x, y, eps_sq=0.3)
    full = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(adj), full < 0.3)
    np.testing.assert_array_equal(np.asarray(vd), (full < 0.3).sum(1))


# ---------------------------------------------------------------------------
# sample filtering
# ---------------------------------------------------------------------------

def test_filter_brute_force(rng):
    x = _data(rng, n=300)
    q = _data(rng, n=25)
    # remove the true top-1 of each query; it must never be returned
    _, top1 = _truth_l2(x, q, 1)
    removed = np.unique(top1.ravel())
    bits = sample_filter.make_filter(len(x), remove=removed)
    index = brute_force.build(jnp.asarray(x), metric="euclidean")
    _, ids = brute_force.knn(index, jnp.asarray(q), 5, filter_bitset=bits)
    assert not np.isin(np.asarray(ids), removed).any()
    # and equals brute force over the kept subset
    keep = np.setdiff1d(np.arange(len(x)), removed)
    want_d, want_sub = _truth_l2(x[keep], q, 5)
    np.testing.assert_array_equal(np.asarray(ids), keep[want_sub])


def test_filter_keep_semantics(rng):
    x = _data(rng, n=100)
    q = _data(rng, n=5)
    keep = np.arange(0, 100, 7)
    bits = sample_filter.make_filter(100, keep=keep)
    index = brute_force.build(jnp.asarray(x), metric="sqeuclidean")
    _, ids = brute_force.knn(index, jnp.asarray(q), 3, filter_bitset=bits)
    assert np.isin(np.asarray(ids), keep).all()


def test_filter_ivf_flat(rng):
    x = _data(rng, n=400)
    q = _data(rng, n=20)
    _, top1 = _truth_l2(x, q, 1)
    removed = np.unique(top1.ravel())
    bits = sample_filter.make_filter(len(x), remove=removed)
    index = ivf_flat.build(jnp.asarray(x), ivf_flat.IndexParams(n_lists=8))
    _, ids = ivf_flat.search(index, jnp.asarray(q), 5,
                             ivf_flat.SearchParams(n_probes=8), filter_bitset=bits)
    assert not np.isin(np.asarray(ids), removed).any()


def test_filter_ivf_pq(rng):
    x = _data(rng, n=2000, d=16)
    q = _data(rng, n=10, d=16)
    removed = np.arange(0, 2000, 3)
    bits = sample_filter.make_filter(2000, remove=removed)
    index = ivf_pq.build(jnp.asarray(x), ivf_pq.IndexParams(n_lists=8, pq_dim=4))
    _, ids = ivf_pq.search(index, jnp.asarray(q), 5,
                           ivf_pq.SearchParams(n_probes=8), filter_bitset=bits)
    ids = np.asarray(ids)
    assert not np.isin(ids[ids >= 0], removed).any()


@pytest.mark.slow  # filter semantics proved on brute/ivf_flat/ivf_pq above; CI lanes run the cagra leg (tier-1 budget)
def test_filter_cagra(rng):
    x = _data(rng, n=2000, d=8)
    q = _data(rng, n=10, d=8)
    removed = np.arange(0, 2000, 2)  # remove half the dataset
    bits = sample_filter.make_filter(2000, remove=removed)
    index = cagra.build(jnp.asarray(x), cagra.IndexParams(graph_degree=16))
    _, ids = cagra.search(index, jnp.asarray(q), 5, filter_bitset=bits)
    ids = np.asarray(ids)
    assert not np.isin(ids[ids >= 0], removed).any()
