"""Distributed billion-scale build (ISSUE 13) on the 8-device CPU mesh.

The load-bearing claim is BIT-IDENTITY: the sharded assign+encode pass
(each shard walking only its slice, different chunk shapes, different
walk order) must assemble into exactly the index the single-host
``build_chunked`` produces — quantizers, packed codes, ids, norms,
sizes, byte for byte. Plus: the prefetcher's accounting/shutdown/error
contracts, the allgatherv-only comms story, the collective-schedule
checker over the build's two collectives, and per-shard checkpointed
resume. The heaviest parity variants are slow-marked (PR-10/12
precedent); the core pq8 + flat parities stay tier-1.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.parallel import (
    ChunkPrefetcher,
    assemble_ivf_flat,
    assemble_ivf_pq,
    build_ivf_pq_distributed,
    index_sha16,
    make_mesh,
    search_ivf_pq,
)
from raft_tpu.parallel import build as dbuild
from raft_tpu.robust import faults

CHUNK = 100


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_plan()
    yield
    faults.clear_plan()
    obs.disable()


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(11)
    # NOT divisible by 8 and not by CHUNK: ragged last shard AND a
    # ragged final chunk inside every shard walk
    return rng.random((1043, 16), dtype=np.float32)


def _pq_params(**kw):
    kw.setdefault("n_lists", 8)
    kw.setdefault("pq_dim", 8)
    kw.setdefault("kmeans_n_iters", 4)
    kw.setdefault("seed", 0)
    kw.setdefault("cache_reconstruction", "never")
    return ivf_pq.IndexParams(**kw)


def _assert_identical(a, b):
    for name in ("centers", "centers_rot", "rotation", "codebooks",
                 "packed_codes", "packed_ids", "packed_norms",
                 "list_sizes"):
        if not hasattr(a, name):
            continue
        fa, fb = np.asarray(getattr(a, name)), np.asarray(getattr(b, name))
        assert fa.dtype == fb.dtype, name
        assert np.array_equal(fa, fb), name


class TestDistributedBuildParity:
    @pytest.mark.slow  # the flat bit-identical twin stays tier-1; CI distributed legs run this one (tier-1 budget)
    def test_ivf_pq_bit_identical_to_build_chunked(self, mesh, data):
        """The acceptance bar: 8-shard distributed build, assembled,
        equals the single-host build_chunked byte for byte — even with
        DIFFERENT chunk sizes (chunk boundaries are not part of the
        result)."""
        params = _pq_params()
        sharded = ivf_pq.build_distributed(data, params, mesh=mesh,
                                           chunk_rows=CHUNK)
        single = ivf_pq.build_chunked(data, params, chunk_rows=4 * CHUNK)
        asm = assemble_ivf_pq(sharded)
        _assert_identical(asm, single)
        assert index_sha16(asm) == index_sha16(single)
        # the sharded layout invariant: global ids carry the shard
        # offset (rank·shard_rows + local), every stored id owned by
        # its shard's contiguous slice
        ids = np.asarray(sharded.packed_ids)
        sr = sharded.shard_rows
        for s in range(sharded.n_shards):
            own = ids[s][ids[s] >= 0]
            assert own.size and (own // sr == s).all()

    @pytest.mark.slow  # second full pq build pair; CI lanes run it
    def test_pq4_parity(self, mesh, data):
        params = _pq_params(pq_bits=4, seed=2)
        sharded = ivf_pq.build_distributed(data, params, mesh=mesh,
                                           chunk_rows=CHUNK)
        single = ivf_pq.build_chunked(data, params, chunk_rows=CHUNK)
        assert index_sha16(assemble_ivf_pq(sharded)) == \
            index_sha16(single)

    @pytest.mark.slow  # cosine normalization path; CI lanes run it
    def test_cosine_parity(self, mesh, data):
        params = _pq_params(metric="cosine", seed=3)
        sharded = ivf_pq.build_distributed(data, params, mesh=mesh,
                                           chunk_rows=CHUNK)
        single = ivf_pq.build_chunked(data, params, chunk_rows=4 * CHUNK)
        assert index_sha16(assemble_ivf_pq(sharded)) == \
            index_sha16(single)

    def test_ivf_flat_bit_identical_to_build(self, mesh, data):
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=4,
                                      seed=1)
        sharded = ivf_flat.build_distributed(data, params, mesh=mesh,
                                             chunk_rows=CHUNK)
        single = ivf_flat.build(jnp.asarray(data), params)
        asm = assemble_ivf_flat(sharded)
        _assert_identical(asm, single)
        assert index_sha16(asm) == index_sha16(single)

    def test_search_consumes_per_shard_output_directly(self, mesh, data):
        """ISSUE 13 (c): the per-shard output IS a ShardedIvfPq — the
        PR-8 searcher takes it with no conversion, through both the
        parallel entry and the neighbors pod dispatch, and returns
        valid global ids."""
        params = _pq_params()
        sharded = ivf_pq.build_distributed(data, params, mesh=mesh,
                                           chunk_rows=CHUNK)
        q = jnp.asarray(data[:16])
        sp = ivf_pq.SearchParams(n_probes=8)
        vals, ids = search_ivf_pq(sp, sharded, q, 5, mesh)
        ids = np.asarray(ids)
        assert ids.shape == (16, 5) and (ids >= 0).any()
        assert ids.max() < len(data)
        # self-queries find themselves through the pod dispatch
        _, ids2 = ivf_pq.search(sharded, q, 1, sp, mesh=mesh)
        assert (np.asarray(ids2)[:, 0] == np.arange(16)).mean() >= 0.8

    @pytest.mark.slow  # own distributed build for a refusal path; CI lanes run it (tier-1 budget)
    def test_assemble_refuses_unknown_capacity(self, mesh, data):
        from raft_tpu.parallel import build_ivf_pq as spmd_build

        params = _pq_params()
        # the SPMD device-resident builder doesn't stamp the global
        # capacity — assembly cannot reproduce a single-host pack
        sharded = spmd_build(params, jnp.asarray(data[:512]), mesh)
        with pytest.raises(Exception, match="global_list_cap"):
            assemble_ivf_pq(sharded)

    def test_spill_not_supported(self, mesh, data):
        with pytest.raises(Exception, match="spill"):
            ivf_pq.build_distributed(data, _pq_params(spill=True),
                                     mesh=mesh)


class TestChunkPrefetcher:
    """The prefetcher's contracts: hit/stall accounting, reader-thread
    exception propagation, clean shutdown mid-stream."""

    def _counters(self, reg):
        return {k: v for k, v in reg.snapshot()["counters"].items()
                if k.startswith("build.prefetch.")}

    def test_hit_and_stall_accounting(self):
        import time

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        pf = ChunkPrefetcher(lambda a, b: np.arange(a, b),
                             [(0, 4), (4, 8), (8, 12)],
                             counter_site="t")
        try:
            # first get may stall (the reader just started); give the
            # reader time to park the rest -> hits
            first = pf.get()
            time.sleep(0.3)
            rest = [pf.get(), pf.get()]
        finally:
            pf.close()
            obs.disable()
        assert np.array_equal(first, np.arange(0, 4))
        assert np.array_equal(rest[1], np.arange(8, 12))
        c = self._counters(reg)
        assert c.get("build.prefetch.hit{site=t}", 0) >= 2
        total = sum(c.values())
        assert total == 3  # every get counted exactly once

    def test_serial_mode_counts_stalls_only(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        pf = ChunkPrefetcher(lambda a, b: np.arange(a, b),
                             [(0, 2), (2, 4)], prefetch=False,
                             counter_site="t")
        try:
            pf.get(), pf.get()
        finally:
            pf.close()
            obs.disable()
        c = self._counters(reg)
        assert c == {"build.prefetch.stall{site=t}": 2.0}

    def test_reader_exception_propagates(self):
        def boom(a, b):
            if a >= 2:
                raise IOError("disk gone")
            return np.arange(a, b)

        pf = ChunkPrefetcher(boom, [(0, 2), (2, 4), (4, 6)])
        try:
            assert np.array_equal(pf.get(), np.arange(0, 2))
            with pytest.raises(IOError, match="disk gone"):
                pf.get()
                pf.get()
        finally:
            pf.close()

    def test_exhausted_raises(self):
        pf = ChunkPrefetcher(lambda a, b: np.arange(a, b), [(0, 1)])
        try:
            pf.get()
            with pytest.raises(IndexError):
                pf.get()
        finally:
            pf.close()

    def test_clean_shutdown_mid_stream(self):
        import threading

        n_before = threading.active_count()
        pf = ChunkPrefetcher(lambda a, b: np.zeros(b - a),
                             [(i, i + 1) for i in range(64)], depth=2)
        pf.get()
        pf.close()
        pf.close()  # idempotent
        assert pf._thread is None
        assert threading.active_count() <= n_before + 1

    def test_faulted_read_retries_under_io_policy(self):
        """An injected IO error on a chunk read recovers under
        IO_POLICY and counts retry.recovered{site=build.chunk_read} —
        the chaos contract, exercised at the prefetcher level."""
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "build.chunk_read", "kind": "error", "times": 1}]})
        rng_data = np.arange(40, dtype=np.float32).reshape(10, 4)
        read = dbuild._make_read_chunk(rng_data, normalize=False)
        pf = ChunkPrefetcher(read, [(0, 5), (5, 10)])
        try:
            a, b = np.asarray(pf.get()), np.asarray(pf.get())
        finally:
            pf.close()
            faults.clear_plan()
            obs.disable()
        assert np.array_equal(np.concatenate([a, b]), rng_data)
        c = reg.snapshot()["counters"]
        assert c.get("retry.recovered{site=build.chunk_read}", 0) == 1


class TestBuildComms:
    """ISSUE 13 (c): the build's collective story is allgatherv-only —
    one trainset gather, one per-list-count gather; codes/ids/norms
    never cross the interconnect."""

    def test_allgatherv_only_and_counts(self, mesh, data):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            ivf_pq.build_distributed(data, _pq_params(), mesh=mesh,
                                     chunk_rows=CHUNK)
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        comm = {k: v for k, v in c.items() if k.startswith("comms.")}
        assert comm, "build recorded no collective traffic"
        assert all("op=allgatherv" in k for k in comm), comm
        # exactly two collectives: trainset rows + per-list counts
        assert comm.get("comms.ops{axis=shard,op=allgatherv}") == 2.0
        # prefetch accounting rode along
        assert any(k.startswith("build.prefetch.") for k in c), c

    def test_collective_schedule_uniform(self, mesh):
        """Both build collectives pass the runtime collective-schedule
        checker, with the facade recorder attributing the allgatherv
        verbs (the GL10 completeness pair)."""
        from raft_tpu.obs import sanitize

        counts = np.tile(np.arange(8, dtype=np.int64), (8, 1))
        stacked = jnp.zeros((8, 4, 8), jnp.float32)
        ns = jnp.full((8,), 4, jnp.int32)
        with sanitize.record_comms_schedule() as rec:
            sanitize.assert_uniform_collective_schedule(
                lambda: dbuild.gather_list_counts(counts, mesh, "shard"))
            sanitize.assert_uniform_collective_schedule(
                lambda: dbuild.gather_trainset_rows(stacked, ns, 32,
                                                    mesh, "shard"))
        verbs = [v for v, _, _ in rec]
        assert verbs == ["allgatherv", "allgatherv"], rec
        assert all(a == "shard" for _, a, _ in rec)


class TestDistributedResume:
    """Per-shard checkpointed resume (the PR-7 layer grown a shard
    axis): an interrupted pod build replays to a sha-identical sharded
    index, with resume.* counters and the once-computed fingerprint
    stamped in the manifest."""

    @pytest.mark.slow  # three full distributed builds; CI lanes run it
    def test_interrupted_then_resumed_is_identical(self, mesh, data,
                                                   tmp_path):
        params = _pq_params()
        faults.install_plan({"faults": [
            {"site": "build.chunk_encode", "kind": "error",
             "after": 6}]})
        with pytest.raises(faults.FaultInjected):
            ivf_pq.build_distributed(data, params, mesh=mesh,
                                     chunk_rows=CHUNK,
                                     checkpoint_dir=str(tmp_path))
        faults.clear_plan()
        man = json.load(open(tmp_path / "manifest.json"))
        assert man["phase"] == "encode"
        assert man["n_shards"] == 8 and man["shard_rows"] == 131
        assert man["fingerprint_s"] >= 0
        done = man["shard_chunks_done"]
        assert len(done) == 8 and 0 < sum(done) < 8 * 2
        # the shard-axis file layout: s000_shard_000000.npz etc.
        assert any(f.startswith("s000_shard_") for f in
                   os.listdir(tmp_path))
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        resumed = ivf_pq.build_distributed(data, params, mesh=mesh,
                                           chunk_rows=CHUNK,
                                           checkpoint_dir=str(tmp_path),
                                           resume=True)
        obs.disable()
        clean = ivf_pq.build_distributed(data, params, mesh=mesh,
                                         chunk_rows=CHUNK)
        assert index_sha16(resumed) == index_sha16(clean)
        c = reg.snapshot()["counters"]
        site = "{site=ivf_pq.build_distributed}"
        assert c[f"resume.attempts{site}"] == 1.0
        assert c[f"resume.chunks_replayed{site}"] == sum(done)

    def test_wrong_dataset_refuses(self, mesh, data, tmp_path):
        params = _pq_params()
        # die on the first encode chunk — the manifest is already on
        # disk, and the refusal matrix doesn't need a complete build
        faults.install_plan({"faults": [
            {"site": "build.chunk_encode", "kind": "error",
             "after": 1}]})
        with pytest.raises(faults.FaultInjected):
            ivf_pq.build_distributed(data, params, mesh=mesh,
                                     chunk_rows=CHUNK,
                                     checkpoint_dir=str(tmp_path))
        faults.clear_plan()
        other = np.random.default_rng(99).random(data.shape,
                                                 dtype=np.float32)
        with pytest.raises(Exception, match="different dataset"):
            ivf_pq.build_distributed(other, params, mesh=mesh,
                                     chunk_rows=CHUNK,
                                     checkpoint_dir=str(tmp_path),
                                     resume=True)

    def test_resume_needs_checkpoint_dir(self, mesh, data):
        with pytest.raises(Exception, match="checkpoint_dir"):
            ivf_pq.build_distributed(data, _pq_params(), mesh=mesh,
                                     resume=True)


class TestDistributedCoarseMode:
    """coarse='distributed' routes the coarse trainer through the
    psum-Lloyd MNMG path (cluster.distributed.fit) — sha-parity is
    waived, the index must still search."""

    @pytest.mark.slow  # an extra full build; CI lanes run it
    def test_distributed_coarse_searches(self, mesh, data):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            sharded = ivf_pq.build_distributed(
                data, _pq_params(), mesh=mesh, chunk_rows=CHUNK,
                coarse="distributed")
        finally:
            obs.disable()
        # the mode's reason to exist: the coarse fit rode the psum
        # Lloyd (allreduce traffic), the full sample was never
        # allgatherv'd — only the small codebook subsample was
        c = reg.snapshot()["counters"]
        assert c.get("comms.ops{axis=shard,op=allreduce}", 0) > 0, c
        # the codebooks must be trained against the DISTRIBUTED
        # centers: self-queries quantize well enough to find
        # themselves (a center/codebook mismatch tanks this)
        q = jnp.asarray(data[:16])
        _, ids = search_ivf_pq(ivf_pq.SearchParams(n_probes=8), sharded,
                               q, 3, mesh)
        ids = np.asarray(ids)
        assert ids.max() < len(data)
        assert (ids[:, 0] == np.arange(16)).mean() >= 0.7

    def test_bad_coarse_mode_rejected(self, mesh, data):
        with pytest.raises(Exception, match="coarse"):
            build_ivf_pq_distributed(data, _pq_params(), mesh,
                                     coarse="nope")
