"""NN-Descent tests (reference test model: cpp/test/neighbors/ann_nn_descent/
— graph recall vs exact knn)."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.neighbors import cagra, nn_descent
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState


def graph_recall(got, ref):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got, ref))
    return hits / ref.size


@pytest.fixture(scope="module")
def corpus():
    x, _ = make_blobs(2000, 16, n_clusters=20, cluster_std=1.0,
                      state=RngState(31))
    return np.asarray(x)


def test_graph_recall(corpus):
    x = corpus
    ids = np.asarray(nn_descent.build_knn_graph(jnp.asarray(x), 10,
                                                n_iters=30))
    full = cdist(x, x, "sqeuclidean")
    np.fill_diagonal(full, np.inf)
    ref = np.argsort(full, 1)[:, :10]
    assert graph_recall(ids, ref) >= 0.85


def test_no_self_edges_no_dups(corpus):
    x = corpus
    ids = np.asarray(nn_descent.build_knn_graph(jnp.asarray(x), 8, n_iters=10))
    assert (ids != np.arange(len(x))[:, None]).all()
    for row in ids[:100]:
        assert len(set(row)) == len(row)


def test_distances_match_ids(corpus):
    x = corpus
    ids, dists = nn_descent.build_knn_graph_with_distances(
        jnp.asarray(x), 8, n_iters=10)
    full = cdist(x, x, "sqeuclidean")
    exact = np.take_along_axis(full, np.asarray(ids), axis=1)
    np.testing.assert_allclose(np.asarray(dists), exact, rtol=1e-3, atol=1e-3)


def test_more_iters_improves(corpus):
    x = corpus
    full = cdist(x, x, "sqeuclidean")
    np.fill_diagonal(full, np.inf)
    ref = np.argsort(full, 1)[:, :10]
    r1 = graph_recall(np.asarray(
        nn_descent.build_knn_graph(jnp.asarray(x), 10, n_iters=2)), ref)
    r2 = graph_recall(np.asarray(
        nn_descent.build_knn_graph(jnp.asarray(x), 10, n_iters=25)), ref)
    assert r2 >= r1


def test_cagra_with_nn_descent_backend(corpus):
    x = corpus
    q = x[:50] + 0.05
    idx = cagra.build(jnp.asarray(x),
                      cagra.IndexParams(intermediate_graph_degree=32,
                                        graph_degree=16,
                                        build_algo="nn_descent"))
    _, ids = cagra.search(idx, jnp.asarray(q), 10,
                          cagra.SearchParams(itopk_size=64))
    full = cdist(q, x, "sqeuclidean")
    ref = np.argsort(full, 1)[:, :10]
    assert graph_recall(np.asarray(ids), ref) >= 0.85
