"""raft_tpu.serve — resilient online serving (ISSUE 14 tentpole).

The chaos-lane contract under test: injected OOM mid-batch walks the
degrade ladder and returns exact results; a full queue rejects with a
typed shed error (never a hang); registry eviction under synthetic HBM
pressure picks the LRU cold tenant; an injected SIGTERM leaves a
parseable flight dump carrying the serve counters; and steady-state
serving triggers ZERO recompiles under ``recompile_budget(0)``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.obs import sanitize
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.robust import degrade, faults, retry
from raft_tpu import serve
from raft_tpu.serve import loadgen

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N, DIM = 3000, 32


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_plan()
    degrade.clear_recent()
    yield
    faults.clear_plan()
    obs.disable()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    return rng.random((N, DIM), dtype=np.float32)


@pytest.fixture(scope="module")
def pq_index(data):
    return ivf_pq.build(jnp.asarray(data), ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, seed=0, cache_reconstruction="never"))


@pytest.fixture(scope="module")
def flat_index(data):
    return ivf_flat.build(jnp.asarray(data),
                          ivf_flat.IndexParams(n_lists=16))


PQ_PARAMS = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")


def _registry_with(pq_index, flat_index=None):
    reg = serve.IndexRegistry(budget_bytes=1 << 30)
    reg.admit("pq", pq_index, params=PQ_PARAMS, default_k=10)
    if flat_index is not None:
        reg.admit("flat", flat_index,
                  params=ivf_flat.SearchParams(n_probes=8), default_k=10)
    return reg


def _counters(reg):
    return reg.snapshot()["counters"]


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_bucket_sizes_are_powers_of_two(self):
        assert serve.bucket_sizes(8) == (1, 2, 4, 8)
        assert serve.bucket_sizes(1) == (1,)
        assert serve.bucket_sizes(5) == (1, 2, 4, 8)  # rounded up

    def test_bucket_for_picks_smallest_fit(self):
        b = serve.bucket_sizes(16)
        assert serve.bucket_for(1, b) == 1
        assert serve.bucket_for(3, b) == 4
        assert serve.bucket_for(16, b) == 16

    def test_invalid_max_batch(self):
        with pytest.raises(ValueError):
            serve.bucket_sizes(0)


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_admit_get_touch_and_peek(self):
        reg = serve.IndexRegistry(budget_bytes=1000)
        reg.admit("a", object(), size_bytes=100)
        t = reg.get("a")
        assert t.state == "warming"
        before = t.last_used
        time.sleep(0.005)
        # peek validates without heating the LRU clock; get touches it
        assert reg.peek("a").last_used == before
        assert reg.get("a").last_used > before
        with pytest.raises(serve.TenantUnknown):
            reg.peek("nope")

    def test_index_device_bytes_counts_leaves(self, pq_index):
        nbytes = serve.index_device_bytes(pq_index)
        # at minimum the packed codes + ids + norms are in there
        assert nbytes > int(pq_index.packed_codes.nbytes)

    def test_unknown_and_terminal_tenants_are_typed(self):
        reg = serve.IndexRegistry(budget_bytes=1000)
        with pytest.raises(serve.TenantUnknown):
            reg.get("nope")
        reg.admit("a", object(), size_bytes=10)
        reg.evict("a")
        with pytest.raises(serve.TenantUnknown) as ei:
            reg.get("a")
        assert ei.value.state == "evicted"

    def test_eviction_under_pressure_picks_lru_cold_tenant(self):
        """The ISSUE's named chaos case: synthetic HBM pressure (tight
        byte budget) must evict the LEAST-recently-used tenant, not the
        hottest one."""
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = serve.IndexRegistry(budget_bytes=400, headroom_frac=0.0)
        for name in ("t1", "t2", "t3"):
            reg.admit(name, object(), size_bytes=100)
        time.sleep(0.002)
        reg.get("t1")  # t1 and t3 are hot, t2 is the cold one
        reg.get("t3")
        reg.admit("t4", object(), size_bytes=150)  # needs one eviction
        states = {t.name: t.state for t in reg.tenants()}
        assert states == {"t1": "warming", "t2": "evicted",
                          "t3": "warming", "t4": "warming"}
        c = _counters(mreg)
        assert c["serve.registry.evict{reason=pressure,tenant=t2}"] == 1.0
        assert c["serve.registry.admit{tenant=t4}"] == 1.0
        assert reg.resident_bytes() == 350

    def test_pinned_tenants_survive_pressure(self):
        reg = serve.IndexRegistry(budget_bytes=300, headroom_frac=0.0)
        reg.admit("pinned", object(), size_bytes=200, pinned=True)
        with pytest.raises(serve.AdmissionError):
            reg.admit("big", object(), size_bytes=200)
        assert reg.get("pinned").state == "warming"

    def test_oversized_tenant_refused_outright(self):
        reg = serve.IndexRegistry(budget_bytes=100, headroom_frac=0.1)
        with pytest.raises(serve.AdmissionError, match="usable budget"):
            reg.admit("big", object(), size_bytes=95)

    def test_readmit_replaces(self):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = serve.IndexRegistry(budget_bytes=1000, headroom_frac=0.0)
        reg.admit("a", object(), size_bytes=900)
        reg.admit("a", object(), size_bytes=800)  # replaces, must fit
        assert reg.resident_bytes() == 800
        c = _counters(mreg)
        assert c["serve.registry.evict{reason=replaced,tenant=a}"] == 1.0

    def test_failed_hot_swap_keeps_the_serving_tenant(self):
        """Review hardening: a replacement that cannot fit must refuse
        WITHOUT destroying the tenant it would have replaced — and
        without evicting any bystander."""
        reg = serve.IndexRegistry(budget_bytes=1000, headroom_frac=0.0)
        prod = object()
        reg.admit("prod", prod, size_bytes=600)
        reg.admit("pinned_other", object(), size_bytes=300, pinned=True)
        with pytest.raises(serve.AdmissionError):
            reg.admit("prod", object(), size_bytes=1100)  # > budget
        with pytest.raises(serve.AdmissionError):
            # fits the budget alone, but not beside the pinned
            # bystander even after the prior's bytes come back
            reg.admit("prod", object(), size_bytes=800)
        t = reg.get("prod")
        assert t.state in ("warming", "serving") and t.index is prod
        assert reg.get("pinned_other").state == "warming"

    def test_mark_evicted_releases_residency(self):
        """Review hardening: mark(name, 'evicted') must drop the index
        and count the eviction exactly like evict() — a terminal
        tenant must never pin HBM that resident_bytes() stopped
        counting."""
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = serve.IndexRegistry(budget_bytes=1000, headroom_frac=0.0)
        t = reg.admit("a", object(), size_bytes=400)
        reg.mark("a", "evicted")
        assert t.index is None and t.state == "evicted"
        assert reg.resident_bytes() == 0
        c = _counters(mreg)
        assert c["serve.registry.evict{reason=manual,tenant=a}"] == 1.0

    def test_failed_tenant_drops_index_and_refuses(self):
        reg = serve.IndexRegistry(budget_bytes=1000)
        reg.admit("a", object(), size_bytes=10)
        reg.mark("a", "failed")
        assert reg.resident_bytes() == 0
        with pytest.raises(serve.TenantUnknown):
            reg.get("a")

    def test_admit_faultpoint_is_armed(self):
        faults.install_plan({"faults": [
            {"site": "serve.registry.admit", "kind": "error",
             "times": 1}]})
        reg = serve.IndexRegistry(budget_bytes=1000)
        with pytest.raises(faults.FaultInjected):
            reg.admit("a", object(), size_bytes=10)
        reg.admit("a", object(), size_bytes=10)  # plan exhausted

    def test_describe_snapshot(self):
        reg = serve.IndexRegistry(budget_bytes=1000)
        reg.admit("a", object(), size_bytes=10)
        d = reg.describe()
        assert d["resident_bytes"] == 10
        assert d["tenants"][0]["name"] == "a"


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class TestServer:
    def test_single_query_parity_with_direct_search(self, data, pq_index):
        """A served result equals the direct search's: padding to a
        bucket must not change any real row (per-query independence)."""
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=8, linger_s=0.001, default_slo_s=None))
        with srv:
            d, i = srv.search("pq", data[7], 10)
        d_ref, i_ref = ivf_pq.search(pq_index, jnp.asarray(data[7:8]),
                                     10, PQ_PARAMS)
        np.testing.assert_array_equal(i, np.asarray(i_ref)[0])
        np.testing.assert_allclose(d, np.asarray(d_ref)[0], rtol=1e-5,
                                   atol=1e-5)
        assert reg.get("pq").state == "serving"  # warmup marked it

    def test_coalesced_batch_matches_per_query(self, data, pq_index):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=8, linger_s=0.05, default_slo_s=None))
        with srv:
            futs = [srv.submit("pq", data[j], 10) for j in range(8)]
            got = [f.result(timeout=30) for f in futs]
        d_ref, i_ref = ivf_pq.search(pq_index, jnp.asarray(data[:8]),
                                     10, PQ_PARAMS)
        for j, (d, i) in enumerate(got):
            np.testing.assert_array_equal(i, np.asarray(i_ref)[j])
        c = _counters(mreg)
        assert c["serve.requests{tenant=pq}"] == 8.0
        snap = mreg.snapshot()["histograms"]
        assert snap["serve.batch_fill"]["count"] >= 1
        assert snap["serve.latency_s"]["count"] == 8

    def test_full_queue_sheds_typed_never_hangs(self, pq_index):
        """The load-shedding contract: a bounded queue full of stalled
        work REJECTS new arrivals with ShedError(queue_full) — and
        every accepted request still terminates."""
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        # stall every dispatch so the queue cannot drain
        faults.install_plan({"faults": [
            {"site": "serve.dispatch", "kind": "sleep", "sleep_s": 0.2,
             "times": 0}]})
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=2, queue_depth=4, linger_s=0.0,
            default_slo_s=None, drain_s=10.0))
        q = np.zeros(DIM, np.float32)
        shed = []
        futs = []
        with srv:
            for _ in range(12):
                try:
                    futs.append(srv.submit("pq", q, 10))
                except serve.ShedError as e:
                    shed.append(e)
            # accepted work must terminate (results, not hangs)
            for f in futs:
                f.result(timeout=30)
        assert shed and all(e.reason == "queue_full" for e in shed)
        c = _counters(mreg)
        assert c["serve.shed{reason=queue_full}"] == len(shed)

    def test_expired_queue_deadline_is_shed_not_dispatched(self, pq_index):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=1, linger_s=0.0, default_slo_s=None,
            drain_s=10.0))
        q = np.zeros(DIM, np.float32)
        with srv:
            # armed AFTER warmup so the one-shot stall hits the first
            # real dispatch; max_batch=1 serializes the two requests —
            # the second's 10 ms budget dies in the queue behind it
            faults.install_plan({"faults": [
                {"site": "serve.dispatch", "kind": "sleep",
                 "sleep_s": 0.25, "times": 1}]})
            slow = srv.submit("pq", q, 10, slo_s=None)
            doomed = srv.submit("pq", q, 10, slo_s=0.01)
            with pytest.raises(serve.DeadlineExceeded):
                doomed.result(timeout=30)
            slow.result(timeout=30)
        c = _counters(mreg)
        assert c["serve.shed{reason=deadline}"] >= 1.0
        assert c["serve.deadline_missed"] >= 1.0

    def test_injected_oom_mid_batch_walks_ladder_exact_results(
            self, data, pq_index):
        """The ISSUE's named chaos case: an OOM mid-batch walks the
        degrade ladder (halve_batch) and the served results are EXACT
        — identical to the same batch served without any fault."""
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=8, linger_s=0.05, default_slo_s=None))
        with srv:
            futs = [srv.submit("pq", data[j], 10) for j in range(8)]
            clean = [f.result(timeout=30) for f in futs]
            mreg = MetricsRegistry()
            obs.enable(registry=mreg, hbm=False)
            faults.install_plan({"faults": [
                {"site": "ivf_pq.search", "kind": "oom", "times": 1}]})
            futs = [srv.submit("pq", data[j], 10) for j in range(8)]
            degraded = [f.result(timeout=30) for f in futs]
        for (dc, ic), (dd, idg) in zip(clean, degraded):
            np.testing.assert_array_equal(ic, idg)
            np.testing.assert_allclose(dc, dd, rtol=1e-5, atol=1e-5)
        c = _counters(mreg)
        assert c.get("degrade.steps{from=native,"
                     "reason=resource_exhausted,site=ivf_pq.search,"
                     "to=halve_batch}", 0) >= 1, c
        assert c.get("faults.fired{kind=oom,site=ivf_pq.search}",
                     0) >= 1, c
        # the ladder fired during dispatch: health says so
        assert reg.get("pq").state == "degraded"

    def test_transient_dispatch_fault_is_retried(self, data, pq_index):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=2, linger_s=0.0, default_slo_s=None))
        with srv:
            faults.install_plan({"faults": [
                {"site": "ivf_pq.search", "kind": "error", "times": 1}]})
            d, i = srv.search("pq", data[0], 10, timeout_s=30)
        assert i.shape == (10,)
        c = _counters(mreg)
        assert c.get("retry.recovered{site=serve.dispatch}", 0) >= 1, c

    def test_unknown_tenant_is_typed(self, pq_index):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg)
        with pytest.raises(serve.TenantUnknown):
            srv.submit("ghost", np.zeros(DIM, np.float32))
        # review hardening: a bogus client-supplied name must not mint
        # a permanent labeled counter series (unbounded cardinality)
        assert "serve.requests{tenant=ghost}" not in _counters(mreg)

    def test_warmup_failure_marks_failed_and_serves_the_rest(
            self, data, pq_index, flat_index):
        """Review hardening: one tenant that cannot warm (every dispatch
        OOMs through an exhausted ladder) is marked failed — residency
        released, submits typed — while the healthy tenant warms and
        serves."""
        reg = _registry_with(pq_index, flat_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=2, linger_s=0.001, default_slo_s=None))
        faults.install_plan({"faults": [
            {"site": "ivf_pq.search", "kind": "oom", "times": 0}]})
        try:
            srv.start()
        finally:
            faults.clear_plan()
        try:
            assert reg.tenants()[0].state == "failed"  # pq
            assert reg.get("flat").state == "serving"
            with pytest.raises(serve.TenantUnknown) as ei:
                srv.submit("pq", data[0], 10)
            assert ei.value.state == "failed"
            _, ids = srv.search("flat", data[0], 10)
            assert ids.shape == (10,)
        finally:
            srv.stop()

    def test_steps_seen_is_thread_local(self):
        """Review hardening: another thread's ladder moves must not
        bump this thread's bracket counter (a concurrent tenant's
        degradation would falsely mark THIS dispatch's tenant)."""
        import threading

        before = degrade.steps_seen()
        t = threading.Thread(target=lambda: degrade.note_step(
            "other-thread", "native", "halve_batch", "test"))
        t.start()
        t.join()
        assert degrade.steps_seen() == before
        degrade.note_step("this-thread", "native", "halve_batch", "test")
        assert degrade.steps_seen() == before + 1

    def test_submit_before_start_sheds_not_running(self, pq_index):
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg)
        with pytest.raises(serve.ShedError) as ei:
            srv.submit("pq", np.zeros(DIM, np.float32))
        assert ei.value.reason == "not_running"

    def test_stop_sheds_queued_as_draining(self, pq_index):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "serve.dispatch", "kind": "sleep", "sleep_s": 0.3,
             "times": 0}]})
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=1, queue_depth=32, linger_s=0.0,
            default_slo_s=None, drain_s=0.0))
        srv.start()
        q = np.zeros(DIM, np.float32)
        futs = [srv.submit("pq", q, 10) for _ in range(6)]
        srv.stop(drain=False)
        outcomes = []
        for f in futs:
            try:
                f.result(timeout=30)
                outcomes.append("ok")
            except serve.ShedError as e:
                outcomes.append(e.reason)
        assert "draining" in outcomes  # queued work shed, typed
        assert all(o in ("ok", "draining") for o in outcomes)

    def test_unwarmed_k_is_rejected_and_declared_ks_serve(self, data,
                                                          pq_index):
        """Review hardening: the k surface is closed at admission —
        submit() with an un-warmed k is a typed client error (it would
        recompile on the serving path), and every declared k serves."""
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = serve.IndexRegistry(budget_bytes=1 << 30)
        reg.admit("pq", pq_index, params=PQ_PARAMS, default_k=10,
                  ks=[5, 10])
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=4, linger_s=0.001, default_slo_s=None))
        with srv:
            with pytest.raises(ValueError, match="warmed surface"):
                srv.submit("pq", data[0], k=7)
            d5, i5 = srv.search("pq", data[0], 5)
            d10, i10 = srv.search("pq", data[0], 10)
        assert i5.shape == (5,) and i10.shape == (10,)
        np.testing.assert_array_equal(i5, i10[:5])
        # every (bucket x k) shape warmed: 3 buckets x 2 ks
        c = _counters(mreg)
        assert c["serve.warmup{tenant=pq}"] == 6.0

    def test_degraded_marking_survives_recent_ring_saturation(
            self, data, pq_index):
        """Review hardening: the degraded-health signal compares the
        MONOTONIC degrade.steps_seen(), not the bounded recent ring —
        after 64+ process-wide ladder moves the ring saturates, and
        a dispatch-time walk must still mark the tenant."""
        for _ in range(70):  # saturate the ≤64-entry recent ring
            degrade.note_step("sat", "native", "halve_batch", "test")
        assert len(degrade.recent_steps()) == 64
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=4, linger_s=0.01, default_slo_s=None))
        with srv:
            faults.install_plan({"faults": [
                {"site": "ivf_pq.search", "kind": "oom", "times": 1}]})
            futs = [srv.submit("pq", data[j], 10) for j in range(4)]
            for f in futs:
                f.result(timeout=30)
        assert reg.get("pq").state == "degraded"

    def test_bad_query_shapes_rejected(self, pq_index):
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg).start(warmup=False)
        try:
            with pytest.raises(ValueError, match="one query vector"):
                srv.submit("pq", np.zeros((2, DIM), np.float32))
            with pytest.raises(ValueError, match="dim"):
                srv.submit("pq", np.zeros(DIM + 1, np.float32))
        finally:
            srv.stop()

    def test_mixed_tenants_coalesce_separately(self, data, pq_index,
                                               flat_index):
        reg = _registry_with(pq_index, flat_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=4, linger_s=0.02, default_slo_s=None))
        with srv:
            fp = [srv.submit("pq", data[j], 10) for j in range(4)]
            ff = [srv.submit("flat", data[j], 10) for j in range(4)]
            got_p = [f.result(timeout=30) for f in fp]
            got_f = [f.result(timeout=30) for f in ff]
        i_ref = np.asarray(ivf_flat.search(
            flat_index, jnp.asarray(data[:4]), 10,
            ivf_flat.SearchParams(n_probes=8))[1])
        for j, (_, i) in enumerate(got_f):
            np.testing.assert_array_equal(i, i_ref[j])
        assert all(i.shape == (10,) for _, i in got_p)


# ---------------------------------------------------------------------------
# zero steady-state recompiles (the AOT-warmup contract)
# ---------------------------------------------------------------------------

class TestSteadyStateCompiles:
    def test_steady_state_is_recompile_free(self, data, pq_index):
        """After start(warmup=True), serving traffic across every
        bucket shape triggers ZERO backend compiles — the PR-3
        sanitizer turns an accidental retrace into a failure."""
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=8, linger_s=0.01, default_slo_s=None))
        with srv:
            # one extra settling pass: anything warmup's zeros-shaped
            # queries missed compiles here, outside the budget scope
            for j in range(3):
                srv.search("pq", data[j], 10)
            with sanitize.recompile_budget(0, what="steady-state serve"):
                for size in (1, 3, 8, 5, 2):
                    futs = [srv.submit("pq", data[j], 10)
                            for j in range(size)]
                    for f in futs:
                        f.result(timeout=30)


# ---------------------------------------------------------------------------
# loadgen
# ---------------------------------------------------------------------------

class TestLoadgen:
    def test_open_loop_step_records_curve_row(self, data, pq_index):
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=8, linger_s=0.002, default_slo_s=5.0))
        with srv:
            rows = loadgen.sweep(srv, "pq", data[:64], 10,
                                 offered_steps=[40.0], duration_s=0.4)
        (row,) = rows
        assert row["sent"] > 0 and row["completed"] > 0
        assert row["qps"] > 0
        assert row["latency_p50_s"] is not None
        assert row["latency_p99_s"] >= row["latency_p50_s"]
        assert row["errors"] == 0

    def test_record_stamps_provenance(self, data, pq_index):
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=8, default_slo_s=5.0))
        with srv:
            rows = loadgen.sweep(srv, "pq", data[:32], 10, [30.0],
                                 duration_s=0.3)
        rec = loadgen.record(rows, dataset="serve-test", tenant="pq",
                             k=10)
        (d,) = rec["detail"]
        assert d["dataset"] == "serve-test" and d["algo"] == "serve"
        assert d["search_param"] == {"offered_qps": 30.0, "k": 10}
        assert d["batch_size"] == 1
        assert d["env"]["jax"] and d["measured_at"]
        # benchdiff must be able to key the rows (the self-compare gate
        # in CI joins the committed baseline on exactly this)
        from tools import benchdiff

        keys = {benchdiff.row_key(r) for r in rec["detail"]}
        assert len(keys) == len(rec["detail"])

    def test_overload_step_sheds_and_says_so(self, data, pq_index):
        """Offered load far past capacity: the open-loop generator must
        SEE the shedding (a closed-loop one never would)."""
        reg = _registry_with(pq_index)
        srv = serve.MicroBatchServer(reg, serve.ServerConfig(
            max_batch=4, queue_depth=8, linger_s=0.0,
            default_slo_s=None, drain_s=10.0))
        faults.install_plan({"faults": [
            {"site": "serve.dispatch", "kind": "sleep", "sleep_s": 0.05,
             "times": 0}]})
        with srv:
            row = loadgen.run_step(srv, "pq", data[:32], 10,
                                   offered_qps=500.0, duration_s=0.4)
        assert row["shed"] > 0
        assert row["shed_reasons"].get("queue_full", 0) > 0
        assert row["sent"] >= row["completed"] + row["shed"]


# ---------------------------------------------------------------------------
# flight-dump chaos (SIGTERM mid-serving)
# ---------------------------------------------------------------------------

class TestServeFlightDump:
    @pytest.mark.slow  # subprocess builds its own index (~7 s); the CI
    # pytest + sanitize lanes run it — tier-1 keeps its 870 s headroom
    def test_sigterm_leaves_dump_with_serve_counters(self, tmp_path):
        """The ISSUE's named chaos case: a SIGTERM'd serving process
        leaves a parseable flight dump whose metrics snapshot carries
        the serve.* counter family."""
        code = f"""
import os, sys, time
sys.path.insert(0, {ROOT!r})
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np, jax.numpy as jnp
from raft_tpu import obs, serve
from raft_tpu.obs import flight
from raft_tpu.neighbors import ivf_pq

obs.enable(hbm=False)
flight.install({str(tmp_path)!r}, every_s=0)
rng = np.random.default_rng(0)
x = rng.random((800, 16), dtype=np.float32)
idx = ivf_pq.build(jnp.asarray(x), ivf_pq.IndexParams(
    n_lists=8, pq_dim=8, seed=0, cache_reconstruction="never"))
reg = serve.IndexRegistry(budget_bytes=1 << 30)
reg.admit("t", idx, params=ivf_pq.SearchParams(
    n_probes=4, scan_mode="per_query"), default_k=5)
srv = serve.MicroBatchServer(reg, serve.ServerConfig(
    max_batch=4, linger_s=0.001, default_slo_s=5.0)).start()
srv.search("t", x[0], 5)
print("armed", flush=True)
while True:
    srv.search("t", x[0], 5)
    time.sleep(0.005)
"""
        p = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE, text=True)
        assert p.stdout.readline().strip() == "armed"
        time.sleep(0.3)
        p.send_signal(signal.SIGTERM)
        p.wait(timeout=60)
        docs = []
        for name in sorted(os.listdir(tmp_path)):
            if name.startswith("flight_") and name.endswith(".json"):
                with open(os.path.join(str(tmp_path), name)) as f:
                    docs.append(json.load(f))
        dumps = [d for d in docs if d["reason"].startswith("signal")]
        assert dumps, [d["reason"] for d in docs]
        counters = dumps[0]["metrics"]["counters"]
        req = [k for k in counters if k.startswith("serve.requests")]
        assert req and counters[req[0]] >= 1, sorted(counters)
        assert any(k.startswith("serve.registry.admit")
                   for k in counters), sorted(counters)
        hists = dumps[0]["metrics"]["histograms"]
        assert "serve.latency_s" in hists


# ---------------------------------------------------------------------------
# ISSUE 15: request-scoped tracing + exposition through the server
# ---------------------------------------------------------------------------

class TestRequestTracing:
    def test_future_carries_trace_id(self, pq_index):
        from raft_tpu.obs import trace

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        with server:
            fut = server.submit("pq", np.zeros(DIM, np.float32), 10)
            fut.result(timeout=30)
        assert isinstance(fut.trace_id, str) and len(fut.trace_id) == 16

    def test_latency_exemplars_resolve_to_timelines(self, pq_index, data):
        from raft_tpu.obs import trace
        from raft_tpu.obs.metrics import exemplars_for_quantile

        prev = trace.set_buffer(trace.EventBuffer())
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        try:
            with server:
                for j in range(12):
                    server.search("pq", data[j], 10)
            lat = reg.snapshot()["histograms"]["serve.latency_s"]
            assert lat["count"] == 12
            ex = exemplars_for_quantile(lat, 0.99)
            assert ex, "p99 resolved to no exemplars"
            events = trace.get_buffer().snapshot()
            for e in ex:
                tid = e["trace_id"]
                mine = [ev for ev in events
                        if trace.event_matches_trace(ev, tid)]
                names = {ev["name"] for ev in mine}
                # the anchor event + the coalesced dispatch stages
                assert "serve.request" in names, names
                assert "serve.dispatch" in names, names
        finally:
            trace.set_buffer(prev)

    def test_request_event_details(self, pq_index, data):
        from raft_tpu.obs import trace

        prev = trace.set_buffer(trace.EventBuffer())
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        try:
            with server:
                fut = server.submit("pq", data[0], 10)
                fut.result(timeout=30)
            (ev,) = [e for e in trace.get_buffer().snapshot()
                     if e["name"] == "serve.request"
                     and e.get("args", {}).get("trace_id")
                     == fut.trace_id]
            args = ev["args"]
            assert args["outcome"] == "ok"
            assert args["tenant"] == "pq" and args["k"] == 10
            assert args["bucket"] >= 1 and 0 < args["fill"] <= 1.0
            assert args["queue_s"] >= 0.0
            assert ev["dur"] > 0
        finally:
            trace.set_buffer(prev)

    def test_ladder_walk_attributed_to_request(self, pq_index, data):
        from raft_tpu.obs import trace

        prev = trace.set_buffer(trace.EventBuffer())
        degrade.clear_recent()
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        try:
            with server:
                faults.install_plan({"faults": [
                    {"site": "ivf_pq.search", "kind": "oom",
                     "times": 1}]})
                fut = server.submit("pq", data[0], 10)
                fut.result(timeout=30)
                faults.clear_plan()
            steps = [s for s in degrade.recent_steps()
                     if s.get("site") == "ivf_pq.search"]
            assert steps, "no ladder move recorded"
            assert fut.trace_id in steps[-1].get("trace_ids", []), steps
            # and the zero-dur marker joined the request's timeline
            markers = [e for e in trace.get_buffer().snapshot()
                       if e["name"] == "degrade.step"
                       and trace.event_matches_trace(e, fut.trace_id)]
            assert markers
        finally:
            trace.set_buffer(prev)

    def test_shed_deadline_records_event(self, pq_index, data):
        from raft_tpu.obs import trace
        from raft_tpu.robust.retry import Deadline

        prev = trace.set_buffer(trace.EventBuffer())
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        try:
            with server:
                fut = server.submit("pq", data[0], 10, slo_s=1e-9)
                with pytest.raises(retry.DeadlineExceeded):
                    fut.result(timeout=30)
            evs = [e for e in trace.get_buffer().snapshot()
                   if e["name"] == "serve.request"
                   and trace.event_matches_trace(e, fut.trace_id)]
            assert evs and evs[0]["args"]["outcome"] == "shed_deadline"
        finally:
            trace.set_buffer(prev)


class TestServerExposition:
    def test_endpoint_lives_and_dies_with_server(self, pq_index, data):
        import urllib.request

        from raft_tpu.obs.expo import parse_prometheus

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001,
                               expo_port=0))
        with server:
            assert server.expo is not None and server.expo.port > 0
            url = server.expo.url
            for j in range(3):
                server.search("pq", data[j], 10)
            text = urllib.request.urlopen(
                url + "/metrics", timeout=10).read().decode()
            fams = parse_prometheus(text)
            assert "raft_tpu_serve_requests" in fams
            assert "raft_tpu_serve_latency_s" in fams
            assert "raft_tpu_hbm_bytes_limit" in fams
            health = json.loads(urllib.request.urlopen(
                url + "/healthz", timeout=10).read())
            assert health["tenants"]["pq"] == "serving"
        assert server.expo is None  # stopped with the server
        with pytest.raises(Exception):
            urllib.request.urlopen(url + "/metrics", timeout=2)

    def test_budget_mirrored_even_when_obs_enabled_late(self, pq_index):
        """Registry built BEFORE obs.enable (the reverse of the CI
        smoke's order) must still expose hbm.bytes_limit once the
        server starts — the mirror re-fires at start()."""
        obs.disable()
        registry = _registry_with(pq_index)  # obs off: no init mirror
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        server = serve.MicroBatchServer(
            registry, serve.ServerConfig(max_batch=4, linger_s=0.001))
        with server:
            pass
        g = reg.snapshot()["gauges"]
        assert g.get("hbm.bytes_limit{source=admission}") == \
            float(registry.budget_bytes)

    def test_not_running_shed_records_anchor_event(self, pq_index,
                                                   data):
        from raft_tpu.obs import trace

        prev = trace.set_buffer(trace.EventBuffer())
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        try:
            with pytest.raises(serve.ShedError):
                server.submit("pq", data[0], 10)  # never started
            evs = [e for e in trace.get_buffer().snapshot()
                   if e["name"] == "serve.request"]
            assert evs and evs[-1]["args"]["outcome"] == \
                "shed_not_running"
        finally:
            trace.set_buffer(prev)

    def test_failed_bind_leaves_server_stopped(self, pq_index):
        """An expo port already in use must not leave a half-started
        server (live batcher, registered flight section, no endpoint,
        unrestartable) — start() tears back down and raises."""
        import socket

        from raft_tpu.obs import flight

        taken = socket.socket()
        taken.bind(("127.0.0.1", 0))
        port = taken.getsockname()[1]
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001,
                               expo_port=port))
        try:
            with pytest.raises(OSError):
                server.start()
            assert server.expo is None
            assert not server._running
            rec = flight.FlightRecorder("/tmp/raft_tpu_test_bind")
            body = rec.payload("test")
            rec.close()
            assert "serve_registry" not in body  # section cleared
            # the port freed -> the SAME server starts cleanly
            taken.close()
            with server:
                assert server.expo is not None
                assert server.expo.port == port
        finally:
            taken.close()
            flight.uninstall()

    def test_no_port_no_endpoint(self, pq_index):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        with server:
            assert server.expo is None

    def test_flight_section_registered_while_serving(self, pq_index):
        from raft_tpu.obs import flight

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=4, linger_s=0.001))
        flight.uninstall()
        try:
            with server:
                rec = flight.FlightRecorder("/tmp/raft_tpu_test_sect")
                body = rec.payload("test")
                rec.close()
                tenants = {t["name"]: t["state"]
                           for t in body["serve_registry"]["tenants"]}
                assert tenants == {"pq": "serving"}
            rec = flight.FlightRecorder("/tmp/raft_tpu_test_sect")
            body = rec.payload("test")
            rec.close()
            assert "serve_registry" not in body  # cleared on stop
        finally:
            flight.uninstall()


class TestLoadgenExemplars:
    def test_run_step_returns_slow_trace_ids(self, pq_index, data):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=8, linger_s=0.001))
        with server:
            row = loadgen.run_step(server, "pq", data[:64], 10,
                                   offered_qps=200.0, duration_s=0.5)
        assert row["completed"] > 0
        assert row["slow_trace_ids"], row
        assert all(len(t) == 16 for t in row["slow_trace_ids"])

    def test_record_notes_name_worst_p99_offenders(self):
        rows = [
            {"offered_qps": 100.0, "duration_s": 1.0, "sent": 10,
             "completed": 10, "shed": 0, "shed_reasons": {},
             "deadline_missed": 0, "errors": 0, "qps": 10.0,
             "latency_p50_s": 0.002, "latency_p99_s": 0.004,
             "latency_mean_s": 0.002, "slow_trace_ids": ["a" * 16]},
            {"offered_qps": 400.0, "duration_s": 1.0, "sent": 40,
             "completed": 40, "shed": 0, "shed_reasons": {},
             "deadline_missed": 0, "errors": 0, "qps": 40.0,
             "latency_p50_s": 0.004, "latency_p99_s": 0.090,
             "latency_mean_s": 0.01,
             "slow_trace_ids": ["b" * 16, "c" * 16]},
        ]
        rec = loadgen.record(rows, "ds", "pq", 10, note="base")
        assert "offered_qps=400.0" in rec["baseline_note"]
        assert "b" * 16 in rec["baseline_note"]
        assert rec["detail"][1]["slow_trace_ids"] == ["b" * 16, "c" * 16]

    def test_obsdump_slowest_renders_loadgen_offender(
            self, pq_index, data, tmp_path):
        from raft_tpu.obs import flight, trace

        prev = trace.set_buffer(trace.EventBuffer())
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        server = serve.MicroBatchServer(
            _registry_with(pq_index),
            serve.ServerConfig(max_batch=8, linger_s=0.001))
        flight.uninstall()
        try:
            with server:
                row = loadgen.run_step(server, "pq", data[:64], 10,
                                       offered_qps=200.0,
                                       duration_s=0.5)
                rec = flight.FlightRecorder(str(tmp_path))
                path = rec.dump("test")
                rec.close()
            from tools import obsdump

            out = obsdump.render(path, top=5, slowest=3)
            assert "slowest 3 requests" in out
            assert "serve.request" in out
            # the loadgen's named offenders appear in the drill-down
            assert any(t in out for t in row["slow_trace_ids"])
        finally:
            trace.set_buffer(prev)
            flight.uninstall()


class TestQualityPlane:
    """ISSUE 16: the online recall verifier wired through the server —
    sampled replays feed quality gauges off the hot path, the flight
    dump grows a "quality" section, /healthz carries the SLO doc, and
    /indexz serves per-tenant index health."""

    def _quality_server(self, flat_index, data, **cfg):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        registry = serve.IndexRegistry(budget_bytes=1 << 30)
        registry.admit("flat", flat_index,
                       params=ivf_flat.SearchParams(n_probes=16),
                       default_k=10, dataset=data, recall_floor=0.2)
        server = serve.MicroBatchServer(
            registry, serve.ServerConfig(
                max_batch=8, linger_s=0.001, verify_sample=1.0,
                verify_rate_per_s=1e9, **cfg))
        return server, reg

    def _wait_gauge(self, reg, key, timeout=15.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            g = reg.snapshot()["gauges"]
            if key in g:
                return g
            time.sleep(0.02)
        raise AssertionError(
            f"{key} never appeared; gauges: "
            f"{sorted(reg.snapshot()['gauges'])}")

    def test_verifier_feeds_recall_gauges(self, flat_index, data):
        server, reg = self._quality_server(flat_index, data)
        with server:
            assert server.verifier is not None
            for j in range(24):
                server.search("flat", data[j], 10)
            g = self._wait_gauge(reg, "quality.recall{k=10,tenant=flat}")
            recall = g["quality.recall{k=10,tenant=flat}"]
            lo = g["quality.recall_ci_low{k=10,tenant=flat}"]
            hi = g["quality.recall_ci_high{k=10,tenant=flat}"]
            assert 0.0 <= lo <= recall <= hi <= 1.0
            # exact self-queries over the admitted dataset: n_probes=16
            # of 16 lists is exhaustive, recall must be perfect
            assert recall == pytest.approx(1.0)
            snap = reg.snapshot()
            assert snap["counters"][
                "quality.verified{tenant=flat}"] >= 1.0
            hkey = [k for k in snap["histograms"]
                    if k.startswith("quality.recall_loss{")]
            assert hkey, sorted(snap["histograms"])
        assert server.verifier is None  # stopped with the server

    def test_flight_quality_section_while_serving(self, flat_index,
                                                  data):
        from raft_tpu.obs import flight

        server, reg = self._quality_server(flat_index, data)
        flight.uninstall()
        try:
            with server:
                for j in range(8):
                    server.search("flat", data[j], 10)
                self._wait_gauge(reg,
                                 "quality.recall{k=10,tenant=flat}")
                rec = flight.FlightRecorder("/tmp/raft_tpu_test_qsect")
                body = rec.payload("test")
                rec.close()
                q = body["quality"]
                assert q["verified_total"] >= 1
                assert "flat" in q["tenants"]
                assert q["verdicts"][0]["trace_id"]
            rec = flight.FlightRecorder("/tmp/raft_tpu_test_qsect")
            body = rec.payload("test")
            rec.close()
            assert "quality" not in body  # cleared on stop
        finally:
            flight.uninstall()

    def test_healthz_and_indexz_over_http(self, flat_index, data):
        import urllib.request

        server, reg = self._quality_server(flat_index, data,
                                           expo_port=0)
        with server:
            for j in range(8):
                server.search("flat", data[j], 10)
            self._wait_gauge(reg, "quality.recall{k=10,tenant=flat}")
            url = server.expo.url
            health = json.loads(urllib.request.urlopen(
                url + "/healthz", timeout=10).read())
            assert health["status"] == "ok"       # floor 0.2 well met
            assert "recall_floor_breached" in health["slo"]
            assert health["slo"]["recall_floor_breached"] == []
            idx = json.loads(urllib.request.urlopen(
                url + "/indexz", timeout=10).read())
            ten = idx["tenants"]["flat"]
            assert ten["recall_floor"] == 0.2
            assert ten["stats"]["lists"]["n_lists"] == 16
            assert "cv" in ten["stats"]["lists"]

    def test_no_verify_sample_no_verifier(self, flat_index, data):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        registry = serve.IndexRegistry(budget_bytes=1 << 30)
        registry.admit("flat", flat_index,
                       params=ivf_flat.SearchParams(n_probes=8),
                       default_k=10, dataset=data)
        server = serve.MicroBatchServer(
            registry, serve.ServerConfig(max_batch=8, linger_s=0.001))
        with server:
            assert server.verifier is None
            assert server.slo is not None   # guardrails run regardless
            server.search("flat", data[0], 10)
        assert "quality.recall{k=10,tenant=flat}" not in \
            reg.snapshot()["gauges"]


# ---------------------------------------------------------------------------
# fleet router (ISSUE 19)
# ---------------------------------------------------------------------------

class TestFleetRouter:
    """Straggler-steered cross-pod routing: placement, the one Deadline
    across the hop, the chaos pod-kill leg with exact shed/degrade
    accounting, and the steering control loop over the PR-15 straggler
    table feed."""

    def _capture_pod(self, name, hosts=()):
        calls = []

        def fn(tenant, queries, k, deadline):
            calls.append((tenant, deadline))
            return np.zeros((len(queries), k)), np.zeros((len(queries), k),
                                                         np.int64)

        return serve.Pod(name, hosts=hosts, dispatch_fn=fn), calls

    def test_placement_modes(self, pq_index):
        regs = [serve.IndexRegistry(budget_bytes=1 << 30) for _ in range(2)]
        router = serve.FleetRouter([
            serve.Pod("a", registry=regs[0]),
            serve.Pod("b", registry=regs[1])])
        assert sorted(router.place("hot", pq_index, hot=True,
                                   params=PQ_PARAMS)) == ["a", "b"]
        assert len(router.place("big", pq_index, sharded=True,
                                params=PQ_PARAMS)) == 1
        # single placement balances onto the emptier pod
        single = router.place("small", pq_index, params=PQ_PARAMS)
        assert len(single) == 1
        counts = {p.name: len(p.registry.resident()) for p in router.pods}
        assert abs(counts["a"] - counts["b"]) <= 1

    def test_straggler_feed_steers_dispatch(self):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        pod_a, calls_a = self._capture_pod("a", hosts=("hostA",))
        pod_b, calls_b = self._capture_pod("b", hosts=("hostB",))
        router = serve.FleetRouter([pod_a, pod_b])
        q = np.zeros((2, 4), np.float32)
        router.dispatch("t", q, 3)
        router.dispatch("t", q, 3)
        assert calls_a and calls_b  # round-robin while both clean
        # PR-15 straggler-table shape: hostB lags 50% over fleet mean
        n = router.note_stragglers([
            {"collective": "comms.ring_topk", "slowest": "hostB",
             "skew_frac": 0.50},
            {"collective": "comms.allreduce", "slowest": "hostB",
             "skew_frac": 0.01}])   # below threshold: ignored
        assert n == 1
        before = len(calls_b)
        for _ in range(6):
            router.dispatch("t", q, 3)
        assert len(calls_b) == before   # steered away from hostB's pod
        c = _counters(mreg)
        assert c["serve.router.straggler{host=hostB}"] == 1.0
        assert c["serve.router.steer{away_from=hostB,reason=straggler}"] \
            >= 1.0
        assert router.describe()["pods"][1]["straggling"] is True

    def test_straggler_sighting_expires(self):
        now = [0.0]
        pod_a, calls_a = self._capture_pod("a", hosts=("hostA",))
        pod_b, calls_b = self._capture_pod("b", hosts=("hostB",))
        router = serve.FleetRouter(
            [pod_a, pod_b], serve.RouterPolicy(lag_window_s=60.0),
            clock=lambda: now[0])
        router.note_stragglers([{"slowest": "hostB", "skew_frac": 0.9}])
        assert router.straggling_hosts() == ["hostB"]
        now[0] = 61.0
        assert router.straggling_hosts() == []  # recovered host wins back
        q = np.zeros((1, 4), np.float32)
        for _ in range(4):
            router.dispatch("t", q, 3)
        assert calls_b

    def test_one_deadline_object_crosses_the_hop(self):
        pod, calls = self._capture_pod("a")
        router = serve.FleetRouter([pod])
        dl = retry.Deadline(5.0)
        router.dispatch("t", np.zeros((1, 4), np.float32), 3, deadline=dl)
        assert calls[0][1] is dl    # the ONE request budget, untouched

    def test_pod_kill_mid_storm_degraded_but_correct(self, data):
        # the ISSUE-19 chaos leg: two simulated pods on 4-device halves
        # of the 8-dev CPU mesh serve a replicated tenant; the DCN hop
        # to pod b dies mid-query-storm; every answered request must
        # equal the fault-free reference (degraded-but-correct from the
        # surviving pod) with exact failover accounting
        import jax
        from raft_tpu.parallel import make_mesh, sharded_knn

        devs = jax.devices()
        assert len(devs) >= 8, "CPU CI mesh must present 8 devices"
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        x = jnp.asarray(data[:1024])
        queries = np.asarray(data[:16], np.float32)
        k = 5

        def pod_fn(mesh):
            def fn(tenant, q, k_, deadline):
                v, i = sharded_knn(x, jnp.asarray(q), k_, mesh)
                return np.asarray(v), np.asarray(i)
            return fn

        mesh_a = make_mesh(devices=devs[:4])
        mesh_b = make_mesh(devices=devs[4:8])
        ref_v, ref_i = pod_fn(mesh_a)("t", queries, k, None)
        router = serve.FleetRouter([
            serve.Pod("a", hosts=("hostA",), dispatch_fn=pod_fn(mesh_a)),
            serve.Pod("b", hosts=("hostB",), dispatch_fn=pod_fn(mesh_b))])
        # pod b's DCN hop dies permanently at its 3rd crossing
        faults.install_plan({"faults": [
            {"site": "serve.router.hop.b", "kind": "error",
             "after": 3, "times": 0}]})
        answers = [router.dispatch("t", queries, k) for _ in range(10)]
        for v, i in answers:    # degraded-but-correct: every request
            np.testing.assert_array_equal(i, ref_i)
            np.testing.assert_allclose(v, ref_v, rtol=1e-5)
        assert not router.pods[1].healthy
        c = _counters(mreg)
        assert c["serve.router.pod_down{pod=b}"] == 1.0
        assert c["serve.router.degraded{reason=pod_lost}"] == 1.0
        assert c["serve.router.requests{tenant=t}"] == 10.0
        assert "serve.router.shed{reason=pod_unhealthy}" not in c
        # now the whole fleet dies: the refusal is typed, counted once
        faults.install_plan({"faults": [
            {"site": "serve.router.hop.a", "kind": "error", "times": 0}]})
        with pytest.raises(serve.ShedError) as exc:
            router.dispatch("t", queries, k)
        assert exc.value.reason == "pod_unhealthy"
        c = _counters(mreg)
        assert c["serve.router.shed{reason=pod_unhealthy}"] == 1.0
        assert c["serve.router.pod_down{pod=a}"] == 1.0

    def test_request_scoped_refusals_propagate_not_pod_down(self):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)

        def fn(tenant, q, k, deadline):
            raise retry.DeadlineExceeded("serve.dispatch",
                                         retry.Deadline(0.0))

        router = serve.FleetRouter([serve.Pod("a", dispatch_fn=fn)])
        with pytest.raises(retry.DeadlineExceeded):
            router.dispatch("t", np.zeros((1, 4), np.float32), 3)
        assert router.pods[0].healthy    # the request's problem
        assert "serve.router.pod_down{pod=a}" not in _counters(mreg)

    def test_global_install_clear_races(self):
        pod, _ = self._capture_pod("a")
        r1 = serve.FleetRouter([pod])
        r2 = serve.FleetRouter([pod])
        assert serve.set_router(r1) is None
        assert serve.get_router() is r1
        serve.clear_router(r2)            # stale teardown: no-op
        assert serve.get_router() is r1
        serve.clear_router(r1)
        assert serve.get_router() is None
