"""Event recording, Chrome-trace export, flight recorder, and the
obsdump renderer (ISSUE 5 tentpole; see docs/observability.md)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.core import tracing
from raft_tpu.neighbors import ivf_pq
from raft_tpu.obs import flight, trace
from raft_tpu.obs.metrics import MetricsRegistry, quantile_from_state

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _obs_clean():
    """Spans/registries/buffers are process-global — leave none behind."""
    prev_buf = trace.set_buffer(trace.EventBuffer())
    yield
    obs.disable()
    obs.get_registry().reset()
    trace.set_buffer(prev_buf)
    flight.uninstall()


class TestEventBuffer:
    def test_ring_evicts_oldest_and_counts_drops(self):
        buf = trace.EventBuffer(capacity=4)
        for i in range(7):
            buf.record_span(f"s{i}", ts=float(i), dur=0.1)
        assert len(buf) == 4
        assert buf.dropped == 3
        names = [e["name"] for e in buf.snapshot()]
        assert names == ["s3", "s4", "s5", "s6"]  # oldest evicted
        buf.clear()
        assert len(buf) == 0 and buf.dropped == 0

    def test_rejects_nonpositive_capacity(self):
        with pytest.raises(ValueError):
            trace.EventBuffer(capacity=0)

    def test_thread_safety(self):
        buf = trace.EventBuffer(capacity=10_000)

        def work(tag):
            for i in range(500):
                buf.record_span(f"{tag}.{i}", ts=0.0, dur=0.0)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(buf) == 4000

    def test_counter_events(self):
        buf = trace.EventBuffer()
        buf.record_counter("hbm.bytes_in_use{device=0}", 123.0, ts=1.0)
        (ev,) = buf.snapshot()
        assert ev["ph"] == "C" and ev["value"] == 123.0 and ev["ts"] == 1.0


class TestSpanEvents:
    def test_spans_append_events_when_enabled(self):
        buf = trace.get_buffer()
        obs.enable(registry=MetricsRegistry(), hbm=False, events=True)
        with tracing.span("search", labels={"leg": "hard"}):
            with tracing.span("scan") as sp:
                sp.annotate(probe=3)
                time.sleep(0.002)
        obs.disable()
        events = buf.snapshot()
        by_name = {e["name"]: e for e in events}
        assert set(by_name) == {"search", "search.scan"}
        scan = by_name["search.scan"]
        assert scan["ph"] == "X"
        assert scan["dur"] > 0 and scan["dur"] <= by_name["search"]["dur"]
        assert scan["tid"] == threading.get_ident()
        assert scan["args"] == {"probe": 3}
        assert by_name["search"]["args"] == {"leg": "hard"}
        # wall-clock begin ordering: outer starts before inner
        assert by_name["search"]["ts"] <= scan["ts"] + 1e-6

    def test_no_events_without_events_mode(self):
        buf = trace.get_buffer()
        obs.enable(registry=MetricsRegistry(), hbm=False)  # events OFF
        with tracing.span("quiet"):
            pass
        obs.disable()
        assert len(buf) == 0

    def test_no_event_on_exception(self):
        buf = trace.get_buffer()
        obs.enable(registry=MetricsRegistry(), hbm=False, events=True)
        with pytest.raises(RuntimeError):
            with tracing.span("boom"):
                raise RuntimeError("x")
        obs.disable()
        assert len(buf) == 0


class TestChromeExport:
    def _search_and_export(self, path):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.random((2000, 32), dtype=np.float32))
        idx = ivf_pq.build(x, ivf_pq.IndexParams(
            n_lists=16, pq_dim=16, seed=0, cache_reconstruction="never"))
        obs.enable(registry=MetricsRegistry(), hbm=False, events=True)
        try:
            ivf_pq.search(idx, x[:32], 5,
                          ivf_pq.SearchParams(n_probes=4,
                                              scan_mode="per_query"))
        finally:
            obs.disable()
        return trace.export_chrome(str(path))

    def test_schema_shape(self, tmp_path):
        """Acceptance: the exported JSON is valid Chrome-trace schema
        (loads in Perfetto): a traceEvents array of complete events with
        name/ph/ts/dur/pid/tid, µs timestamps, per-thread metadata."""
        out = tmp_path / "trace.json"
        n = self._search_and_export(out)
        assert n >= 1
        with open(out) as f:
            doc = json.load(f)
        assert isinstance(doc["traceEvents"], list)
        assert doc["displayTimeUnit"] == "ms"
        phases = {e["ph"] for e in doc["traceEvents"]}
        assert "X" in phases and "M" in phases
        for e in doc["traceEvents"]:
            assert isinstance(e["name"], str) and e["name"]
            assert e["ph"] in ("X", "C", "M")
            assert isinstance(e["pid"], int)
            if e["ph"] == "X":
                assert isinstance(e["ts"], float) and e["ts"] > 0
                assert isinstance(e["dur"], float) and e["dur"] >= 0
                assert isinstance(e["tid"], int)
        names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert "ivf_pq.search" in names, names
        # one thread_name metadata track per tid seen
        meta = [e for e in doc["traceEvents"]
                if e["ph"] == "M" and e["name"] == "thread_name"]
        assert meta and all(e["args"]["name"] for e in meta)

    def test_merge_remaps_colliding_pids(self, tmp_path):
        p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
        buf = trace.EventBuffer()
        buf.record_span("w", ts=1.0, dur=0.5)
        trace.export_chrome(str(p1), buf)
        trace.export_chrome(str(p2), buf)  # same pid in both files
        out = tmp_path / "merged.json"
        doc = trace.merge([str(p1), str(p2)], out_path=str(out))
        pids = {e["pid"] for e in doc["traceEvents"]}
        assert len(pids) == 2, pids  # collision resolved
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 2
        with open(out) as f:  # written file round-trips
            assert json.load(f)["traceEvents"]

    def test_obsdump_renders_tables(self, tmp_path):
        """Acceptance: `python -m tools.obsdump <trace>` renders the
        top-spans/comm-bytes/HBM tables from an instrumented search."""
        out = tmp_path / "trace.json"
        self._search_and_export(out)
        p = subprocess.run(
            [sys.executable, "-m", "tools.obsdump", str(out)],
            capture_output=True, text=True, cwd=ROOT, timeout=120)
        assert p.returncode == 0, p.stderr
        assert "top spans by total time" in p.stdout
        assert "ivf_pq.search" in p.stdout
        assert "comm traffic by op x axis" in p.stdout
        assert "HBM" in p.stdout


class TestFlightRecorder:
    def test_dump_contains_events_metrics_logs(self, tmp_path):
        rec = flight.install(str(tmp_path), signals=(), use_atexit=False)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        try:
            with tracing.span("leg"):
                pass
            reg.inc("comms.ops", 2, labels={"op": "allreduce",
                                            "axis": "shard"})
            from raft_tpu.core import logging as _log

            _log.warn("flight test line %d", 7)
            path = rec.dump(reason="unit")
        finally:
            obs.disable()
        with open(path) as f:
            doc = json.load(f)
        assert doc["schema"] == flight.SCHEMA
        assert doc["reason"] == "unit"
        assert doc["pid"] == os.getpid()
        assert any(e["name"] == "leg" for e in doc["events"])
        assert doc["metrics"]["counters"][
            "comms.ops{axis=shard,op=allreduce}"] == 2.0
        assert any("flight test line 7" in line for line in doc["logs"])
        assert doc["uptime_s"] >= 0

    def test_install_is_idempotent_and_dump_now_works(self, tmp_path):
        rec = flight.install(str(tmp_path), signals=(), use_atexit=False)
        assert flight.install("/elsewhere") is rec  # singleton wins
        p = flight.dump_now(reason="now")
        assert p and os.path.dirname(p) == str(tmp_path)
        with open(p) as f:
            assert json.load(f)["reason"] == "now"

    def test_periodic_checkpoint(self, tmp_path):
        rec = flight.FlightRecorder(str(tmp_path))
        rec.start_periodic(0.05)
        try:
            latest = os.path.join(
                str(tmp_path), f"flight_{os.getpid()}_latest.json")
            deadline = time.time() + 5
            while not os.path.exists(latest) and time.time() < deadline:
                time.sleep(0.02)
            assert os.path.exists(latest), "no periodic checkpoint in 5s"
            with open(latest) as f:
                assert json.load(f)["reason"] == "periodic"
        finally:
            rec.close()

    def test_sigterm_leaves_parseable_dump_and_chains(self, tmp_path):
        """Acceptance-shaped: a SIGTERM'd process leaves a parseable
        flight_*.json, and the prior signal handler still runs (exit
        path preserved)."""
        code = (
            "import sys, os, signal, time\n"
            f"sys.path.insert(0, {ROOT!r})\n"
            "def prior(num, frame):\n"
            "    print('prior-handler', flush=True)\n"
            "    os._exit(7)\n"
            "signal.signal(signal.SIGTERM, prior)\n"
            "from raft_tpu.obs import flight\n"
            # every_s=0: an inherited RAFT_TPU_FLIGHT_EVERY_S would add
            # periodic _latest.json dumps beside the signal one
            f"flight.install({str(tmp_path)!r}, every_s=0)\n"
            "print('armed', flush=True)\n"
            "time.sleep(60)\n"
        )
        p = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE, text=True)
        assert p.stdout.readline().strip() == "armed"
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=30)
        assert "prior-handler" in out  # chained to the previous handler
        assert p.returncode == 7
        docs = []
        for name in sorted(os.listdir(tmp_path)):
            if name.startswith("flight_") and name.endswith(".json"):
                with open(os.path.join(str(tmp_path), name)) as f:
                    docs.append(json.load(f))
        signal_dumps = [d for d in docs
                        if d["reason"].startswith("signal")]
        assert signal_dumps, [d["reason"] for d in docs]
        assert signal_dumps[0]["schema"] == flight.SCHEMA

    def test_sigint_leaves_parseable_dump_and_chains(self, tmp_path):
        """ISSUE 14 satellite, mirroring the SIGTERM test: a Ctrl-C'd
        serving process must keep its flight dump — SIGINT is now in
        DEFAULT_SIGNALS — and the prior handler (the app's own, or
        Python's default KeyboardInterrupt) still runs after it."""
        assert "SIGINT" in flight.DEFAULT_SIGNALS
        code = (
            "import sys, os, signal, time\n"
            f"sys.path.insert(0, {ROOT!r})\n"
            "def prior(num, frame):\n"
            "    print('prior-handler', flush=True)\n"
            "    os._exit(8)\n"
            "signal.signal(signal.SIGINT, prior)\n"
            "from raft_tpu.obs import flight\n"
            f"flight.install({str(tmp_path)!r}, every_s=0)\n"
            "print('armed', flush=True)\n"
            "time.sleep(60)\n"
        )
        p = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE, text=True)
        assert p.stdout.readline().strip() == "armed"
        p.send_signal(signal.SIGINT)
        out, _ = p.communicate(timeout=30)
        assert "prior-handler" in out  # Ctrl-C semantics preserved
        assert p.returncode == 8
        docs = []
        for name in sorted(os.listdir(tmp_path)):
            if name.startswith("flight_") and name.endswith(".json"):
                with open(os.path.join(str(tmp_path), name)) as f:
                    docs.append(json.load(f))
        signal_dumps = [d for d in docs
                        if d["reason"].startswith("signal")]
        assert signal_dumps, [d["reason"] for d in docs]
        assert signal_dumps[0]["schema"] == flight.SCHEMA

    def test_sigint_default_disposition_raises_keyboardinterrupt(
            self, tmp_path):
        """Without an app handler, the chained SIGINT must still land
        as KeyboardInterrupt (the recorder observes the death, it does
        not change it)."""
        code = (
            "import sys, time\n"
            f"sys.path.insert(0, {ROOT!r})\n"
            "from raft_tpu.obs import flight\n"
            f"flight.install({str(tmp_path)!r}, every_s=0)\n"
            # 'armed' is printed INSIDE the try: the parent fires
            # SIGINT the moment it reads the line, and under load the
            # interrupt can land before the child reaches the sleep —
            # any point after the print must already be covered.
            "try:\n"
            "    print('armed', flush=True)\n"
            "    time.sleep(60)\n"
            "except KeyboardInterrupt:\n"
            "    print('kbd-interrupt', flush=True)\n"
            "    raise SystemExit(9)\n"
        )
        p = subprocess.Popen([sys.executable, "-c", code],
                             stdout=subprocess.PIPE, text=True)
        assert p.stdout.readline().strip() == "armed"
        p.send_signal(signal.SIGINT)
        out, _ = p.communicate(timeout=30)
        assert "kbd-interrupt" in out
        assert p.returncode == 9


class TestFlightDumpDurability:
    """ISSUE 7 satellite: the dump path must never expose a partial
    file — fsync BEFORE the atomic rename, and a failed write leaves
    neither the target nor tmp litter."""

    def test_fsync_happens_before_rename(self, tmp_path, monkeypatch):
        rec = flight.FlightRecorder(str(tmp_path))
        order = []
        real_fsync, real_replace = os.fsync, os.replace
        monkeypatch.setattr(
            os, "fsync", lambda fd: (order.append("fsync"),
                                     real_fsync(fd))[1])
        monkeypatch.setattr(
            os, "replace",
            lambda a, b: (order.append("rename"), real_replace(a, b))[1])
        path = rec.dump(reason="durability")
        assert os.path.exists(path)
        assert "fsync" in order and "rename" in order
        assert order.index("fsync") < order.index("rename"), order
        rec.close()

    def test_failed_dump_exposes_nothing(self, tmp_path, monkeypatch):
        rec = flight.FlightRecorder(str(tmp_path))
        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(json, "dump", boom)
        with pytest.raises(OSError):
            rec.dump(reason="boom")
        leftovers = [f for f in os.listdir(str(tmp_path))
                     if f.startswith("flight_")]
        assert not leftovers, leftovers  # no final file, no tmp litter
        rec.close()

    def test_watchdog_kill_info_rides_the_dump(self, tmp_path,
                                               monkeypatch):
        info = tmp_path / "kill.json"
        info.write_text(json.dumps({"reason": "stall", "stalled_min": 5,
                                    "elapsed_s": 301, "attempt": 0}))
        monkeypatch.setenv("WATCHDOG_KILL_INFO", str(info))
        rec = flight.FlightRecorder(str(tmp_path))
        with open(rec.dump(reason="killed")) as f:
            doc = json.load(f)
        assert doc["watchdog"] == {"reason": "stall", "stalled_min": 5,
                                   "elapsed_s": 301, "attempt": 0}
        rec.close()

    def test_watchdog_sidecar_absent_or_broken_is_ignored(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("WATCHDOG_KILL_INFO",
                           str(tmp_path / "nope.json"))
        rec = flight.FlightRecorder(str(tmp_path))
        with open(rec.dump(reason="x")) as f:
            assert "watchdog" not in json.load(f)
        broken = tmp_path / "broken.json"
        broken.write_text("{truncated")
        monkeypatch.setenv("WATCHDOG_KILL_INFO", str(broken))
        with open(rec.dump(reason="y")) as f:
            assert "watchdog" not in json.load(f)
        rec.close()

    def test_robust_state_rides_the_dump(self, tmp_path):
        # the robust↔obs cross-link (ISSUE 9): a dump taken while a
        # fault plan is armed and the degradation ladder has moved
        # says WHAT was injected and how far the run had degraded
        from raft_tpu.robust import degrade, faults

        faults.install_plan({"faults": [
            {"site": "ivf_pq.search", "kind": "sleep",
             "sleep_s": 0.0, "times": 2}]})
        degrade.clear_recent()
        try:
            assert faults.faultpoint("ivf_pq.search") == "sleep"
            degrade.note_step("ivf_pq.search", "native", "halve_batch",
                              "resource_exhausted")
            rec = flight.FlightRecorder(str(tmp_path))
            with open(rec.dump(reason="chaos")) as f:
                doc = json.load(f)
            rec.close()
        finally:
            faults.clear_plan()
            degrade.clear_recent()
        robust = doc["robust"]
        (rule,) = robust["fault_plan"]
        assert rule["site"] == "ivf_pq.search"
        assert rule["kind"] == "sleep"
        assert rule["fired"] == 1
        assert robust["fault_fires"] == {"ivf_pq.search": 1}
        (step,) = robust["degrade_recent"]
        assert step["site"] == "ivf_pq.search"
        assert step["from"] == "native"
        assert step["to"] == "halve_batch"
        assert step["reason"] == "resource_exhausted"
        assert step["ts"] > 0

    def test_no_robust_section_when_nothing_armed(self, tmp_path):
        from raft_tpu.robust import degrade, faults

        faults.clear_plan()
        degrade.clear_recent()
        rec = flight.FlightRecorder(str(tmp_path))
        with open(rec.dump(reason="calm")) as f:
            doc = json.load(f)
        rec.close()
        assert "robust" not in doc


class TestQuantiles:
    def test_histogram_quantile_interpolates(self):
        h = obs.Histogram("lat", buckets=[0.01, 0.1, 1.0])
        for v in (0.005, 0.02, 0.05, 0.5):
            h.observe(v)
        assert h.quantile(0.0) == pytest.approx(0.005)  # clamped to min
        assert h.quantile(1.0) == pytest.approx(0.5)    # clamped to max
        p50 = h.quantile(0.5)
        assert 0.01 <= p50 <= 0.1  # rank 2 falls in the (0.01, 0.1] bucket
        assert quantile_from_state(h.state(), 0.5) == pytest.approx(p50)

    def test_quantile_empty_and_tail(self):
        h = obs.Histogram("lat", buckets=[1.0])
        assert h.quantile(0.5) is None
        h.observe(5.0)  # lands in +inf bucket
        assert h.quantile(0.99) == pytest.approx(5.0)

    def test_quantile_from_jsonl_round_trip(self, tmp_path):
        r = MetricsRegistry()
        for v in (0.1, 0.2, 0.3, 4.0):
            r.observe("lat", v)
        path = str(tmp_path / "m.jsonl")
        r.dump_jsonl(path)
        (row,) = [x for x in obs.load_jsonl(path)
                  if x["kind"] == "histogram"]
        assert quantile_from_state(row, 0.99) == pytest.approx(4.0)


class TestObsdumpFlight:
    def test_renders_flight_dump_with_comms_and_hbm(self, tmp_path):
        reg = MetricsRegistry()
        reg.inc("comms.ops", 3, labels={"op": "allgather", "axis": "ici"})
        reg.inc("comms.bytes", 4096,
                labels={"op": "allgather", "axis": "ici"})
        reg.gauge("hbm.bytes_in_use", {"device": "0"}).set(1 << 30)
        reg.histogram("span.ivf_pq.search").observe(0.25)
        rec = flight.install(str(tmp_path), signals=(), use_atexit=False)
        obs.enable(registry=reg, hbm=False)
        try:
            path = rec.dump(reason="render")
        finally:
            obs.disable()
        p = subprocess.run(
            [sys.executable, "-m", "tools.obsdump", path, "--top", "5"],
            capture_output=True, text=True, cwd=ROOT, timeout=120)
        assert p.returncode == 0, p.stderr
        assert "allgather" in p.stdout and "ici" in p.stdout
        assert "4.0 KiB" in p.stdout
        assert "ivf_pq.search" in p.stdout
        assert "bytes_in_use" in p.stdout and "1.0 GiB" in p.stdout

    def test_renders_prof_roofline_and_robust_sections(self, tmp_path):
        from raft_tpu.obs import prof
        from raft_tpu.robust import degrade, faults
        from tools import obsdump

        reg = MetricsRegistry()
        cost = prof.ProgramCost(
            flops=2e9, bytes_accessed=1e9, arithmetic_intensity=2.0,
            bound="memory", peak_flops=1e12, peak_bw=1e11, ridge=10.0,
        ).attribute_elapsed(0.05)
        prof.record(cost, registry=reg, program="ivf_pq.n1024 b10000")
        faults.install_plan({"faults": [
            {"site": "ivf_flat.search", "kind": "sleep",
             "sleep_s": 0.5, "times": 0}]})
        degrade.clear_recent()
        degrade.note_step("s", "native", "halve_batch", "mem_guard")
        rec = flight.FlightRecorder(str(tmp_path))
        obs.enable(registry=reg, hbm=False)
        try:
            path = rec.dump(reason="prof-render")
        finally:
            obs.disable()
            rec.close()
            faults.clear_plan()
            degrade.clear_recent()
        out = obsdump.render(path, top=10)
        assert "cost / roofline attribution" in out
        assert "ivf_pq.n1024 b10000" in out
        assert "memory" in out
        assert "2e+09" in out or "2.000e+09" in out or "2e+9" in out
        assert "ivf_flat.search:sleep" in out
        assert "native->halve_batch [mem_guard]" in out

    def test_renders_serve_family_and_shed_tables(self, tmp_path):
        """ISSUE 14 satellite: a serving run's flight dump leads with
        the serve.* tables — per-tenant traffic, shed-by-reason +
        deadline misses, and the served latency quantiles."""
        from tools import obsdump

        reg = MetricsRegistry()
        reg.inc("serve.requests", 41, labels={"tenant": "acme"})
        reg.inc("serve.warmup", 4, labels={"tenant": "acme"})
        reg.inc("serve.registry.admit", 1, labels={"tenant": "acme"})
        reg.inc("serve.registry.evict", 1,
                labels={"tenant": "acme", "reason": "pressure"})
        reg.inc("serve.shed", 7, labels={"reason": "queue_full"})
        reg.inc("serve.shed", 2, labels={"reason": "deadline"})
        reg.inc("serve.deadline_missed", 3)
        h = reg.histogram("serve.latency_s",
                          buckets=[0.001, 0.01, 0.1, 1.0])
        for v in (0.004, 0.006, 0.05):
            h.observe(v)
        reg.histogram("serve.batch_fill", buckets=[0.5, 1.0]).observe(0.75)
        rec = flight.FlightRecorder(str(tmp_path))
        obs.enable(registry=reg, hbm=False)
        try:
            path = rec.dump(reason="serve-render")
        finally:
            obs.disable()
            rec.close()
        out = obsdump.render(path, top=5)
        assert "serving (serve.*)" in out
        assert "acme" in out and "41" in out
        assert "shed / deadline" in out
        assert "queue_full" in out and "7" in out
        assert "deadline_missed" in out and "3" in out
        assert "0.75" in out  # mean batch fill
        # a dump with no serve activity renders no serve section
        reg2 = MetricsRegistry()
        reg2.histogram("span.x").observe(0.1)
        rec2 = flight.FlightRecorder(str(tmp_path))
        obs.enable(registry=reg2, hbm=False)
        try:
            path2 = rec2.dump(reason="no-serve")
        finally:
            obs.disable()
            rec2.close()
        assert "serving (serve.*)" not in obsdump.render(path2, top=5)


# ---------------------------------------------------------------------------
# ISSUE 15: request-scoped trace propagation + exemplars
# ---------------------------------------------------------------------------

class TestRequestContext:
    def test_trace_ids_are_unique_hex(self):
        ids = {trace.new_trace_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids)

    def test_event_labels_single_and_batch(self):
        ctx = trace.RequestContext(tenant="acme")
        assert ctx.event_labels() == {"trace_id": ctx.trace_id,
                                      "tenant": "acme"}
        batch = trace.RequestContext(tenant="acme",
                                     trace_ids=["a", "b", "c"])
        assert batch.event_labels()["trace_ids"] == ["a", "b", "c"]
        assert batch.matches("b") and not batch.matches("z")

    def test_use_request_nests_and_restores(self):
        assert trace.current_request() is None
        outer = trace.RequestContext()
        inner = trace.RequestContext()
        with trace.use_request(outer):
            assert trace.current_request() is outer
            with trace.use_request(inner):
                assert trace.current_request() is inner
            assert trace.current_request() is outer
        assert trace.current_request() is None

    def test_context_is_thread_local(self):
        ctx = trace.RequestContext()
        seen = {}

        def other():
            seen["ctx"] = trace.current_request()

        with trace.use_request(ctx):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["ctx"] is None

    def test_spans_stamp_current_request(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        ctx = trace.RequestContext(tenant="t9")
        with trace.use_request(ctx):
            with tracing.span("stagex", labels={"k": 1}):
                pass
        with tracing.span("unstamped"):
            pass
        events = {e["name"]: e for e in trace.get_buffer().snapshot()}
        assert events["stagex"]["args"] == {
            "k": 1, "trace_id": ctx.trace_id, "tenant": "t9"}
        assert "args" not in events["unstamped"]
        assert trace.event_matches_trace(events["stagex"], ctx.trace_id)

    def test_batch_context_matches_every_member(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        ctx = trace.RequestContext(trace_ids=["m1", "m2"])
        with trace.use_request(ctx):
            with tracing.span("batchstage"):
                pass
        (ev,) = [e for e in trace.get_buffer().snapshot()
                 if e["name"] == "batchstage"]
        assert trace.event_matches_trace(ev, "m1")
        assert trace.event_matches_trace(ev, "m2")
        assert not trace.event_matches_trace(ev, "m3")

    def test_degrade_steps_carry_trace_ids(self):
        from raft_tpu.robust import degrade

        degrade.clear_recent()
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        ctx = trace.RequestContext(tenant="t1")
        with trace.use_request(ctx):
            degrade.note_step("site.x", "native", "bf16_lut", "test")
        degrade.note_step("site.y", "native", "fp8_lut", "test")
        steps = degrade.recent_steps()
        assert steps[-2]["trace_id"] == ctx.trace_id
        assert "trace_id" not in steps[-1]
        # the move also landed in the event ring as a zero-dur marker
        markers = [e for e in trace.get_buffer().snapshot()
                   if e["name"] == "degrade.step"]
        assert any(trace.event_matches_trace(e, ctx.trace_id)
                   for e in markers)
        degrade.clear_recent()

    def test_retry_attempts_land_in_timeline(self):
        from raft_tpu.robust import retry

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        ctx = trace.RequestContext()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        with trace.use_request(ctx):
            out = retry.retry_call(flaky, site="test.site",
                                   policy=retry.RetryPolicy(
                                       max_attempts=5, base_delay_s=0.0,
                                       jitter=0.0))
        assert out == "ok"
        markers = [e for e in trace.get_buffer().snapshot()
                   if e["name"] == "retry.attempt"]
        assert len(markers) == 2  # attempts 2 and 3, never the first
        assert all(trace.event_matches_trace(e, ctx.trace_id)
                   for e in markers)
        assert [e["args"]["attempt"] for e in markers] == [2, 3]


class TestExemplars:
    def test_reservoir_bounded_and_keeps_worst(self):
        from raft_tpu.obs import metrics as m

        h = m.Histogram("h", buckets=[1.0, 10.0])
        for i in range(50):
            h.observe(0.1 + i * 0.01, exemplar=f"t{i}")
        st = h.state()
        res = st["exemplars"]["1.0"]
        assert len(res) == m.EXEMPLARS_PER_BUCKET
        # the largest values in the bucket are retained, worst first
        vals = [e["value"] for e in res]
        assert vals == sorted(vals, reverse=True)
        assert res[0]["trace_id"] == "t49"

    def test_no_exemplars_no_state_key(self):
        from raft_tpu.obs import metrics as m

        h = m.Histogram("h")
        h.observe(0.5)
        assert "exemplars" not in h.state()

    def test_exemplars_for_quantile_picks_right_bucket(self):
        from raft_tpu.obs import metrics as m

        h = m.Histogram("h", buckets=[0.01, 0.1, 1.0])
        for i in range(99):
            h.observe(0.005, exemplar=f"fast{i}")
        h.observe(0.5, exemplar="slow")
        ex99 = m.exemplars_for_quantile(h.state(), 0.997)
        assert ex99[0]["trace_id"] == "slow"
        ex50 = m.exemplars_for_quantile(h.state(), 0.5)
        assert ex50 and ex50[0]["trace_id"].startswith("fast")

    def test_quantile_falls_back_to_nearest_bucket(self):
        from raft_tpu.obs import metrics as m

        h = m.Histogram("h", buckets=[0.01, 0.1, 1.0])
        # samples land in the tail bucket WITHOUT exemplars; exemplars
        # exist only below — the p99 must still resolve
        for i in range(5):
            h.observe(0.005, exemplar=f"e{i}")
        for _ in range(95):
            h.observe(0.5)  # no exemplar
        ex = m.exemplars_for_quantile(h.state(), 0.99)
        assert ex and ex[0]["trace_id"].startswith("e")

    def test_empty_histogram(self):
        from raft_tpu.obs import metrics as m

        assert m.exemplars_for_quantile(m.Histogram("h").state(),
                                        0.99) == []

    def test_exemplars_roundtrip_jsonl(self, tmp_path):
        from raft_tpu.obs import metrics as m

        reg = MetricsRegistry()
        reg.observe("lat", 0.2, exemplar="tid0")
        path = str(tmp_path / "x.jsonl")
        reg.dump_jsonl(path)
        (row,) = [r for r in m.load_jsonl(path)
                  if r["kind"] == "histogram"]
        assert row["exemplars"]["1.0"][0]["trace_id"] == "tid0"


class TestExportUnderConcurrentLoad:
    """ISSUE 15 satellite: export_chrome racing ring eviction and a
    mid-export dump_now must produce schema-valid output — no torn
    events, eviction accounting consistent."""

    def test_export_races_eviction_and_flight_dump(self, tmp_path):
        buf = trace.EventBuffer(capacity=512)  # small ring: constant
        trace.set_buffer(buf)                  # eviction under load
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False, events=True)
        stop = threading.Event()
        errors = []

        def hammer(tag):
            i = 0
            while not stop.is_set():
                buf.record_span(f"load.{tag}", ts=time.time(),
                                dur=0.001, args={"i": i})
                i += 1

        def dumper():
            while not stop.is_set():
                p = flight.dump_now("race",
                                    dump_dir=str(tmp_path / "flight"))
                if p is None:
                    errors.append("dump_now failed")

        writers = [threading.Thread(target=hammer, args=(t,))
                   for t in range(4)]
        dump_thread = threading.Thread(target=dumper)
        for t in writers:
            t.start()
        dump_thread.start()
        export_paths = []
        try:
            for j in range(10):
                p = str(tmp_path / f"trace_{j}.json")
                trace.export_chrome(p, buf)
                export_paths.append(p)
        finally:
            stop.set()
            for t in writers:
                t.join()
            dump_thread.join()
        assert not errors
        for p in export_paths:
            doc = json.load(open(p))  # parses: no torn file
            evs = doc["traceEvents"]
            assert all(
                {"name", "ph", "pid", "tid", "ts"} <= set(e) or
                e["ph"] in ("M", "C") for e in evs), "torn event"
            xs = [e for e in evs if e["ph"] == "X"]
            assert all("dur" in e and "ts" in e for e in xs)
            # eviction accounting: dropped is reported and consistent
            # with a bounded ring (retained <= capacity)
            assert len(xs) <= 512
            assert doc["otherData"]["dropped_events"] >= 0
        # the racing flight dumps are each valid JSON with event lists
        fdir = tmp_path / "flight"
        dumps = list(fdir.glob("flight_*.json")) if fdir.exists() else []
        for p in dumps:
            doc = json.load(open(p))
            assert isinstance(doc["events"], list)
            assert doc["dropped_events"] >= 0

    def test_eviction_counter_monotonic_under_race(self):
        buf = trace.EventBuffer(capacity=64)
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                buf.record_span("x", ts=0.0, dur=0.0)

        t = threading.Thread(target=hammer)
        t.start()
        try:
            last = 0
            for _ in range(200):
                d = buf.dropped
                assert d >= last
                last = d
        finally:
            stop.set()
            t.join()
        assert buf.dropped + len(buf) == buf._total
