"""refine_provider + DeviceSyntheticChunks contract tests.

The billion-scale refine path re-ranks candidates against rows
REGENERATED on device from the seed-deterministic provider
(refine.refine_provider) — these pin its agreement with the plain
device refine, the provider's block determinism across chunkings, and
the query/base key separation (ADVICE r4: a fold_in-keyed query set
could collide bit-identically with a base block).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tpu.bench import dataset as dsm
from raft_tpu.neighbors import refine


@pytest.fixture(scope="module")
def prov():
    return dsm.DeviceSyntheticChunks(6_000, 16, n_centers=40, seed=3,
                                     chunk_rows=1024)


def test_refine_provider_matches_dense_refine(prov):
    base = np.asarray(prov[0:6_000])
    q = jnp.asarray(np.asarray(prov.queries(24)))
    rng = np.random.default_rng(0)
    cand = rng.integers(0, 6_000, (24, 32)).astype(np.int32)
    cand[0, :4] = -1  # invalid markers must stay excluded
    d1, i1 = refine.refine(jnp.asarray(base), q, jnp.asarray(cand), 8)
    d2, i2 = refine.refine_provider(prov, q, jnp.asarray(cand), 8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-4,
                               atol=1e-3)


def test_provider_blocks_stable_across_chunkings(prov):
    # slicing with any window must reproduce the same rows: block
    # content is a function of the block index alone
    a = np.asarray(prov[1000:3000])
    b = np.concatenate([np.asarray(prov[1000:1500]),
                        np.asarray(prov[1500:3000])])
    np.testing.assert_array_equal(a, b)


def test_queries_disjoint_from_base_blocks():
    # chunk_rows divides the old fold_in offset (n+1): the regression
    # ADVICE r4 flagged — queries must come from a separate key branch
    n, c = 2047, 256  # c divides n+1
    p = dsm.DeviceSyntheticChunks(n, 8, n_centers=10, seed=5, chunk_rows=c)
    qq = np.asarray(p.queries(c))
    base = np.asarray(p[0:n])
    eq = (qq[:, None, :] == base[None, :, :]).all(-1)
    assert not eq.any(), "query rows bit-identical to base rows"


def test_refine_provider_multi_chunk_callers(prov):
    # callers chunk queries to bound the row buffer; results must agree
    q = jnp.asarray(np.asarray(prov.queries(32)))
    rng = np.random.default_rng(1)
    cand = rng.integers(0, 6_000, (32, 16)).astype(np.int32)
    d_full, i_full = refine.refine_provider(prov, q, jnp.asarray(cand), 5)
    parts = [refine.refine_provider(prov, q[a:a + 16],
                                    jnp.asarray(cand[a:a + 16]), 5)
             for a in (0, 16)]
    np.testing.assert_array_equal(
        np.asarray(i_full),
        np.concatenate([np.asarray(p[1]) for p in parts]))


def test_refine_provider_validates_row_mismatch(prov):
    # refine() validated the queries/candidates row match; the provider
    # and host-gather variants must too (ADVICE r5)
    from raft_tpu.core.errors import LogicError

    q = jnp.asarray(np.asarray(prov.queries(8)))
    cand = jnp.asarray(np.zeros((4, 16), np.int32))  # 4 != 8 rows
    with pytest.raises(LogicError):
        refine.refine_provider(prov, q, cand, 5)
    with pytest.raises(LogicError):
        refine.refine_gathered(np.zeros((100, 16), np.float32), q, cand, 5)


def test_search_level_f32_regen_routes_to_provider(prov):
    """ivf_flat/ivf_pq search(refine="f32_regen", dataset=<provider>)
    must route the re-rank through refine_provider (a provider's
    __getitem__ rejects the fancy-index refine_gathered would issue)."""
    from raft_tpu import obs
    from raft_tpu.neighbors import ivf_flat

    base = np.asarray(prov[0:6_000])
    q = jnp.asarray(np.asarray(prov.queries(16)))
    idx = ivf_flat.build(jnp.asarray(base), ivf_flat.IndexParams(n_lists=16))
    reg = obs.MetricsRegistry()
    obs.enable(registry=reg, hbm=False)
    try:
        dv, iv = ivf_flat.search(
            idx, q, 5,
            ivf_flat.SearchParams(n_probes=8, refine="f32_regen",
                                  refine_ratio=4.0),
            dataset=prov)
    finally:
        obs.disable()
    assert reg.snapshot()["counters"].get(
        "refine.dispatch{impl=provider_regen}", 0) >= 1
    # the provider regenerates the SAME rows the index was built from,
    # so the re-rank is exact — top-1 must be each query's true nearest
    # among its candidates
    assert np.asarray(dv).shape == (16, 5)


def test_refine_provider_dim_mismatch_message(prov):
    from raft_tpu.core.errors import LogicError

    q = jnp.asarray(np.random.default_rng(0).standard_normal(
        (8, 32)).astype(np.float32))  # provider is 16-dim
    cand = np.zeros((8, 4), np.int32)
    with pytest.raises(LogicError, match="feature-dim"):
        refine.refine_provider(prov, q, jnp.asarray(cand), 2)
