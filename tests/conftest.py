"""Test configuration: run the suite on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): "multi-node" is
emulated on a single host — the reference uses LocalCUDACluster
(raft_dask/test/test_comms.py:21); here XLA's host-platform device count
gives N fake devices so every sharded code path executes for real.
Must set env vars before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's axon site-hook pins JAX_PLATFORMS; the config update
# after import is what actually lands the CPU platform here.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
