"""Test configuration: run the suite on a virtual 8-device CPU mesh.

Mirrors the reference's test strategy (SURVEY.md §4): "multi-node" is
emulated on a single host — the reference uses LocalCUDACluster
(raft_dask/test/test_comms.py:21); here XLA's host-platform device count
gives N fake devices so every sharded code path executes for real.
Must set env vars before the first jax import.

Sanitizer mode (``RAFT_TPU_SANITIZE=1``, docs/developer_guide.md): the
suite additionally runs under ``jax_numpy_rank_promotion="raise"`` and
``jax_debug_nans`` (the compute-sanitizer analog — RAFT's CI runs its
tests under exactly such a lane), and tests marked
``@pytest.mark.recompile_budget(n)`` assert their body triggers at most
``n`` backend compiles via the jax.monitoring jit-cache-miss counter —
an unexpected retrace fails the test instead of silently costing
seconds per call in production.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's axon site-hook pins JAX_PLATFORMS; the config update
# after import is what actually lands the CPU platform here.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

# Persistent XLA compile cache: the suite is compile-bound on a 1-core
# CI box, and most of the wall clock is backend_compile of the same
# programs every run. A warm on-disk cache skips only the XLA compile —
# tracing still happens (span/comms counters are trace-time) and the
# recompile_budget listener counts backend compiles, so a cache hit can
# only relax an upper-bound budget, never break one. Respect an
# explicit JAX_COMPILATION_CACHE_DIR; default to a repo-local dir so a
# wiped /tmp cannot silently turn every CI run cold.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".cache", "jax"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
    except Exception:  # pragma: no cover - jax without the cache knobs
        pass

from raft_tpu.obs import sanitize as _sanitize  # noqa: E402

if _sanitize.sanitize_enabled():
    _sanitize.apply_sanitize_config()
    # install before any compiles so budget deltas see every cache miss
    _sanitize.install_compile_counter()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _recompile_budget(request):
    """Enforce ``@pytest.mark.recompile_budget(n)`` in sanitizer mode.

    Outside sanitizer mode the marker is inert — budgets depend on a
    cold, deterministic jit cache, which only the dedicated
    ``RAFT_TPU_SANITIZE=1`` CI lane guarantees."""
    marker = request.node.get_closest_marker("recompile_budget")
    if marker is None or not _sanitize.sanitize_enabled():
        yield
        return
    with _sanitize.recompile_budget(int(marker.args[0]),
                                    what=request.node.nodeid):
        yield
