"""Sparse pairwise distances vs the dense engine / scipy references.

Mirrors the reference's sparse distance tests (cpp/test/sparse/dist_*.cu):
sparse results must match dense pairwise on the densified inputs.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from raft_tpu import sparse
from raft_tpu.distance.pairwise import pairwise_distance as dense_pw
from raft_tpu.sparse import distance as sdist

METRICS = [
    "sqeuclidean",
    "euclidean",
    "l2_unexpanded",
    "l2_sqrt_unexpanded",
    "inner_product",
    "cosine",
    "hellinger",
    "jaccard",
    "dice",
    "russelrao",
    "correlation",
    "l1",
    "linf",
    "canberra",
    "lp",
    "hamming",
    "jensenshannon",
    "kl_divergence",
]


def _rand_pair(seed, m=33, n=27, d=40, density=0.3, nonneg=False):
    rs = np.random.RandomState(seed)
    a = sp.random(m, d, density=density, random_state=rs, format="csr", dtype=np.float32)
    b = sp.random(n, d, density=density, random_state=rs, format="csr", dtype=np.float32)
    if nonneg:
        a.data = np.abs(a.data)
        b.data = np.abs(b.data)
    return a, b


@pytest.mark.parametrize("metric", METRICS)
def test_sparse_matches_dense(metric):
    nonneg = metric in ("hellinger", "jensenshannon", "kl_divergence")
    a_sp, b_sp = _rand_pair(3, nonneg=nonneg)
    if metric in ("hellinger", "jensenshannon", "kl_divergence"):
        # probability-like rows
        a_sp = sp.csr_matrix(a_sp / np.maximum(a_sp.sum(axis=1), 1e-9))
        b_sp = sp.csr_matrix(b_sp / np.maximum(b_sp.sum(axis=1), 1e-9))
    a, b = sparse.from_scipy(a_sp), sparse.from_scipy(b_sp)
    kwargs = {"metric_arg": 1.5} if metric == "lp" else {}
    got = np.asarray(sdist.pairwise_distance(a, b, metric=metric, **kwargs))
    want = np.asarray(
        dense_pw(
            jnp.asarray(a_sp.toarray()), jnp.asarray(b_sp.toarray()), metric=metric, **kwargs
        )
    )
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_sparse_tiling_matches_untiled():
    a_sp, b_sp = _rand_pair(5, m=50)
    a, b = sparse.from_scipy(a_sp), sparse.from_scipy(b_sp)
    full = np.asarray(sdist.pairwise_distance(a, b, metric="sqeuclidean"))
    tiled = np.asarray(sdist.pairwise_distance(a, b, metric="sqeuclidean", tile_rows=16))
    np.testing.assert_allclose(full, tiled, rtol=1e-5, atol=1e-5)


def test_sparse_knn_recall():
    a_sp, b_sp = _rand_pair(7, m=64, n=200, d=32, density=0.4)
    index = sparse.from_scipy(b_sp)
    queries = sparse.from_scipy(a_sp)
    dists, ids = sdist.brute_force_knn(index, queries, k=5, metric="sqeuclidean")
    # exact reference on dense
    full = ((a_sp.toarray()[:, None, :] - b_sp.toarray()[None, :, :]) ** 2).sum(-1)
    want_ids = np.argsort(full, axis=1, kind="stable")[:, :5]
    want_d = np.take_along_axis(full, want_ids, axis=1)
    np.testing.assert_allclose(np.sort(np.asarray(dists), axis=1), np.sort(want_d, axis=1), rtol=1e-3, atol=1e-4)


def test_knn_graph():
    from raft_tpu.sparse.neighbors import knn_graph

    rng = np.random.default_rng(0)
    x = rng.random((40, 8), dtype=np.float32)
    g = knn_graph(x, n_neighbors=4)
    rows = np.asarray(g.rows)
    assert g.shape == (40, 40)
    # every vertex has exactly 4 out-edges, none self
    counts = np.bincount(rows, minlength=40)
    assert (counts == 4).all()
    assert (np.asarray(g.rows) != np.asarray(g.cols)).all()


def test_jaccard_explicit_zeros_and_duplicates():
    """Non-canonical input (stored zeros, duplicate coords) must match the
    dense reference — from_scipy canonicalizes (review regression)."""
    a = sp.csr_matrix(np.array([[1.0, 0.0, 2.0]], dtype=np.float32))
    b = sp.csr_matrix(
        (np.array([0.0, 3.0, 4.0], dtype=np.float32), np.array([0, 1, 2]), np.array([0, 3])),
        shape=(1, 3),
    )
    got = float(
        sdist.pairwise_distance(sparse.from_scipy(a), sparse.from_scipy(b), metric="jaccard")[0, 0]
    )
    want = float(
        dense_pw(jnp.asarray(a.toarray()), jnp.asarray(b.toarray()), metric="jaccard")[0, 0]
    )
    assert abs(got - want) < 1e-6
