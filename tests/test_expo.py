"""obs.expo — live telemetry exposition (ISSUE 15 tentpole b).

The exposition contract under test: the full registry renders as
parseable Prometheus text format (HELP/TYPE per family, labeled
counters/gauges, cumulative histogram buckets), the stdlib HTTP server
serves it on an ephemeral port, /healthz reflects serving-registry
tenant health (200 while anything is resident, 503 when everything is
terminal), and /flightz triggers an on-demand flight dump. Device-free
— nothing here touches jax.
"""

import json
import os
import urllib.error
import urllib.request

import pytest

from raft_tpu.obs import hbm
from raft_tpu.obs.expo import (ExpoServer, parse_prometheus, prom_name,
                               render_prometheus)
from raft_tpu.obs.metrics import MetricsRegistry


def _reg():
    reg = MetricsRegistry()
    reg.inc("serve.requests", 3, labels={"tenant": "acme"})
    reg.inc("serve.requests", 1, labels={"tenant": "other"})
    reg.set("serve.queue_depth", 7)
    reg.observe("serve.latency_s", 0.004, labels=None, exemplar="t1")
    reg.observe("serve.latency_s", 0.2, labels=None, exemplar="t2")
    return reg


class TestRender:
    def test_name_sanitization(self):
        assert prom_name("serve.latency_s") == "raft_tpu_serve_latency_s"
        assert prom_name("a.b-c d") == "raft_tpu_a_b_c_d"

    def test_families_help_type_and_labels(self):
        text = render_prometheus(_reg().collect())
        assert "# HELP raft_tpu_serve_requests" in text
        assert "# TYPE raft_tpu_serve_requests counter" in text
        assert 'raft_tpu_serve_requests{tenant="acme"} 3' in text
        assert "# TYPE raft_tpu_serve_queue_depth gauge" in text
        assert "# TYPE raft_tpu_serve_latency_s histogram" in text

    def test_histogram_buckets_cumulative_and_closed(self):
        fams = parse_prometheus(render_prometheus(_reg().collect()))
        lat = fams["raft_tpu_serve_latency_s"]
        buckets = [s for s in lat if s["series"].endswith("_bucket")]
        assert buckets, lat
        # cumulative: values never decrease with rising le, +Inf == count
        les = [(float("inf") if s["labels"]["le"] == "+Inf"
                else float(s["labels"]["le"]), s["value"])
               for s in buckets]
        les.sort()
        vals = [v for _, v in les]
        assert vals == sorted(vals)
        count = [s for s in lat if s["series"].endswith("_count")][0]
        assert les[-1][1] == count["value"] == 2

    def test_parse_rejects_malformed(self):
        with pytest.raises(ValueError):
            parse_prometheus("this is { not a metric line")

    def test_help_carries_original_dotted_name(self):
        text = render_prometheus(_reg().collect())
        assert "serve.latency_s" in text  # the HELP line names the source

    def test_label_escaping(self):
        reg = MetricsRegistry()
        reg.inc("x", labels={"p": 'say "hi"\nthere'})
        text = render_prometheus(reg.collect())
        assert r'\"hi\"' in text and r"\n" in text
        fams = parse_prometheus(text)  # still parses
        (series,) = fams["raft_tpu_x"]
        assert series["labels"]["p"] == 'say "hi"\nthere'  # round-trips

    def test_label_values_with_commas_round_trip(self):
        # a comma (or brace) inside a quoted label VALUE must not be
        # split into bogus extra labels by the parser
        reg = MetricsRegistry()
        reg.inc("y", labels={"t": 'a,b"q', "u": "c{d}e"})
        fams = parse_prometheus(render_prometheus(reg.collect()))
        (series,) = fams["raft_tpu_y"]
        assert series["labels"] == {"t": 'a,b"q', "u": "c{d}e"}

    def test_malformed_label_body_raises(self):
        with pytest.raises(ValueError):
            parse_prometheus('m{bad-key="v"} 1')
        with pytest.raises(ValueError):
            parse_prometheus('m{k="v" extra} 1')


class TestServer:
    def _get(self, url, timeout=10):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()

    def test_metrics_roundtrip_on_ephemeral_port(self):
        with ExpoServer(port=0, registry=_reg()) as expo:
            assert expo.port and expo.port > 0
            status, body = self._get(expo.url + "/metrics")
            assert status == 200
            fams = parse_prometheus(body.decode())
            assert "raft_tpu_serve_requests" in fams
        assert expo.port is None  # stopped

    def test_healthz_without_provider_is_ok(self):
        with ExpoServer(port=0, registry=_reg()) as expo:
            status, body = self._get(expo.url + "/healthz")
            assert status == 200
            assert json.loads(body)["status"] == "ok"

    def test_healthz_reflects_tenant_states(self):
        desc = {"tenants": [{"name": "a", "state": "serving"},
                            {"name": "b", "state": "evicted"}],
                "resident_bytes": 10, "budget_bytes": 100}
        with ExpoServer(port=0, registry=_reg(),
                        health=lambda: desc) as expo:
            status, body = self._get(expo.url + "/healthz")
            doc = json.loads(body)
            assert status == 200
            assert doc["tenants"] == {"a": "serving", "b": "evicted"}
            # everything terminal -> 503
            desc["tenants"] = [{"name": "a", "state": "failed"},
                               {"name": "b", "state": "evicted"}]
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(expo.url + "/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["status"] == "unavailable"

    def test_flightz_triggers_dump(self, tmp_path):
        marker = tmp_path / "dumped.json"

        def fake_dump():
            marker.write_text("{}")
            return str(marker)

        with ExpoServer(port=0, registry=_reg(),
                        flight_dump=fake_dump) as expo:
            status, body = self._get(expo.url + "/flightz")
            assert status == 200
            assert json.loads(body)["path"] == str(marker)
            assert marker.exists()

    def test_unknown_path_404(self):
        with ExpoServer(port=0, registry=_reg()) as expo:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(expo.url + "/nope")
            assert ei.value.code == 404

    def test_callable_registry_resolves_per_scrape(self):
        regs = {"cur": MetricsRegistry()}
        regs["cur"].inc("gen", 1)
        with ExpoServer(port=0, registry=lambda: regs["cur"]) as expo:
            _, body = self._get(expo.url + "/metrics")
            assert "raft_tpu_gen 1" in body.decode()
            regs["cur"] = MetricsRegistry()
            regs["cur"].inc("gen", 5)
            _, body = self._get(expo.url + "/metrics")
            assert "raft_tpu_gen 5" in body.decode()


class TestNoteBudget:
    def test_budget_mirrors_into_hbm_family(self):
        reg = MetricsRegistry()
        hbm.note_budget(1 << 20, reg)
        g = reg.snapshot()["gauges"]
        # its OWN labeled series: the allocator's unlabeled/{device=i}
        # readings (hbm.sample) must never be clobbered by a
        # capacity-capped admission budget
        assert g["hbm.bytes_limit{source=admission}"] == float(1 << 20)
        assert "hbm.bytes_limit" not in g
        assert "hbm.bytes_limit{device=0}" not in g


class TestJsonlRotation:
    def _fill(self, reg, n=40):
        for i in range(n):
            reg.inc(f"series.{i}", i + 1, labels={"idx": str(i)})

    def test_unbounded_by_default(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        reg = MetricsRegistry()
        self._fill(reg)
        for _ in range(5):
            reg.dump_jsonl(path)
        assert not os.path.exists(path + ".1")

    def test_rotates_at_cap_and_keeps_n(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        reg = MetricsRegistry()
        self._fill(reg)
        one_dump = reg.dump_jsonl(path)
        assert one_dump == 40
        size = os.path.getsize(path)
        cap_mb = (size * 2) / (1 << 20)  # rotate every ~2 dumps
        for _ in range(12):
            reg.dump_jsonl(path, max_mb=cap_mb, keep=2)
        assert os.path.exists(path + ".1")
        assert os.path.exists(path + ".2")
        assert not os.path.exists(path + ".3")  # keep=2 prunes
        # every retained file is valid JSONL (atomic renames: a reader
        # never sees a torn file)
        from raft_tpu.obs.metrics import load_jsonl

        for p in (path, path + ".1", path + ".2"):
            rows = load_jsonl(p)
            assert rows and all("kind" in r for r in rows)
        # the live file stays under ~cap + one dump
        assert os.path.getsize(path) <= size * 3

    def test_env_knobs(self, tmp_path, monkeypatch):
        path = str(tmp_path / "m.jsonl")
        reg = MetricsRegistry()
        self._fill(reg)
        reg.dump_jsonl(path)
        cap_mb = os.path.getsize(path) / (1 << 20)
        monkeypatch.setenv("RAFT_TPU_OBS_JSONL_MAX_MB", repr(cap_mb))
        monkeypatch.setenv("RAFT_TPU_OBS_JSONL_KEEP", "1")
        reg.dump_jsonl(path)  # at cap -> rotates
        reg.dump_jsonl(path)
        assert os.path.exists(path + ".1")
        assert not os.path.exists(path + ".2")


class TestHelpEscaping:
    """Text-format 0.0.4 compliance (ISSUE 16 satellite): HELP escapes
    ONLY backslash and newline; label values additionally escape the
    double quote. A ``\\"`` in HELP would be a literal
    backslash-quote to a compliant parser — promtool flags it."""

    def test_help_keeps_quotes_but_escapes_backslash_newline(self):
        reg = MetricsRegistry()
        reg.inc('weird"name\\x\ny')
        text = render_prometheus(reg.collect())
        help_line = [l for l in text.splitlines()
                     if l.startswith("# HELP")][0]
        assert '"' in help_line          # quote NOT escaped in HELP
        assert r"\\x" in help_line       # backslash doubled
        assert r"\ny" in help_line       # newline escaped
        assert "\n" not in help_line     # one physical line

    def test_bench_param_repr_label_round_trips(self):
        # the bench harness labels series with search-param dict reprs
        # — quotes, commas, braces and backslashes all at once
        tricky = repr({"n_probes": 32, "lut": "fp8", "p": "a\\b"})
        reg = MetricsRegistry()
        reg.inc("bench.qps", 7, labels={"params": tricky})
        text = render_prometheus(reg.collect())
        fams = parse_prometheus(text)
        (series,) = fams["raft_tpu_bench_qps"]
        assert series["labels"]["params"] == tricky
        assert series["value"] == 7

    def test_label_backslash_alone_survives(self):
        reg = MetricsRegistry()
        reg.set("g", 1, labels={"path": "C:\\tmp\\x"})
        fams = parse_prometheus(render_prometheus(reg.collect()))
        (series,) = fams["raft_tpu_g"]
        assert series["labels"]["path"] == "C:\\tmp\\x"


class TestIndexz:
    def _get(self, url, timeout=10):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()

    def test_indexz_serves_provider_payload(self):
        doc = {"tenants": {"acme": {"lists": {"cv": 0.5, "dead": 1}}}}
        with ExpoServer(port=0, registry=_reg(),
                        indexz=lambda: doc) as expo:
            status, body = self._get(expo.url + "/indexz")
            assert status == 200
            assert json.loads(body) == doc

    def test_indexz_404_without_provider(self):
        with ExpoServer(port=0, registry=_reg()) as expo:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(expo.url + "/indexz")
            assert ei.value.code == 404

    def test_indexz_500_when_provider_throws(self):
        def boom():
            raise RuntimeError("stats race")

        with ExpoServer(port=0, registry=_reg(), indexz=boom) as expo:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(expo.url + "/indexz")
            assert ei.value.code == 500
            assert "stats race" in json.loads(ei.value.read())["error"]

    def test_healthz_degraded_on_recall_floor_breach(self):
        # quality trouble flips the STATUS STRING but keeps HTTP 200 —
        # results still flow; orchestration reads the body
        desc = {"tenants": [{"name": "a", "state": "serving"}],
                "slo": {"recall_floor_breached": ["a"],
                        "burn_rates": {"30s": 0.0},
                        "burn_threshold": 2.0}}
        with ExpoServer(port=0, registry=_reg(),
                        health=lambda: desc) as expo:
            status, body = self._get(expo.url + "/healthz")
            doc = json.loads(body)
            assert status == 200
            assert doc["status"] == "degraded"
            assert doc["slo"]["recall_floor_breached"] == ["a"]
            # breach clears -> plain ok again
            desc["slo"] = {"recall_floor_breached": [],
                           "burn_rates": {"30s": 0.0},
                           "burn_threshold": 2.0}
            _, body = self._get(expo.url + "/healthz")
            assert json.loads(body)["status"] == "ok"


class TestCostz:
    def _get(self, url, timeout=10):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()

    def test_costz_serves_provider_payload(self):
        doc = {"ledger": {"tenants": {"a": {"device_s": 0.5}}},
               "capacity": {"headroom_frac": 0.9}}
        with ExpoServer(port=0, registry=_reg(),
                        costz=lambda: doc) as expo:
            status, body = self._get(expo.url + "/costz")
            assert status == 200
            assert json.loads(body) == doc

    def test_costz_404_without_provider(self):
        with ExpoServer(port=0, registry=_reg()) as expo:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(expo.url + "/costz")
            assert ei.value.code == 404

    def test_costz_500_when_provider_throws(self):
        def boom():
            raise RuntimeError("ledger gone")

        with ExpoServer(port=0, registry=_reg(), costz=boom) as expo:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(expo.url + "/costz")
            assert ei.value.code == 500
            assert "ledger gone" in json.loads(ei.value.read())["error"]


class TestProcessSelfTelemetry:
    def test_process_rows_cover_the_standard_family(self):
        from raft_tpu.obs.expo import process_rows

        rows = {r["name"]: r for r in process_rows()}
        # Linux CI has /proc and resource: the full set must be there
        assert rows["process_cpu_seconds_total"]["kind"] == "counter"
        assert rows["process_cpu_seconds_total"]["value"] >= 0.0
        assert rows["process_resident_memory_bytes"]["value"] > 1 << 20
        assert rows["process_open_fds"]["value"] >= 3  # stdio at least
        assert rows["process_uptime_seconds"]["value"] >= 0.0

    def test_process_text_parses_round_trip_unprefixed(self):
        from raft_tpu.obs.expo import process_text

        fams = parse_prometheus(process_text())
        # the Prometheus-conventional names: NO raft_tpu_ namespace
        for name in ("process_cpu_seconds_total",
                     "process_resident_memory_bytes",
                     "process_open_fds", "process_uptime_seconds"):
            (series,) = fams[name]
            assert series["labels"] == {}
            assert isinstance(series["value"], float)

    def test_metrics_endpoint_appends_process_family(self):
        with ExpoServer(port=0, registry=_reg()) as expo:
            with urllib.request.urlopen(expo.url + "/metrics",
                                        timeout=10) as r:
                fams = parse_prometheus(r.read().decode())
        assert "raft_tpu_serve_requests" in fams
        assert "process_cpu_seconds_total" in fams
        assert "process_resident_memory_bytes" in fams
