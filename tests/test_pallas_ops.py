"""Pallas kernels in interpreter mode vs references (the CPU-side
equivalent of the reference's kernel unit tests; on real TPU the same
kernels run compiled — see bench.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.ops import fused_l2_argmin, select_k_pallas


def test_fused_l2_argmin_interpret(rng):
    x = rng.random((100, 40), dtype=np.float32)
    y = rng.random((1000, 40), dtype=np.float32)
    d, i = fused_l2_argmin(jnp.asarray(x), jnp.asarray(y), interpret=True)
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))
    np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-4, atol=1e-5)


def test_fused_l2_argmin_ragged_shapes(rng):
    # shapes not multiples of the block sizes exercise the padding masks
    x = rng.random((33, 7), dtype=np.float32)
    y = rng.random((517, 7), dtype=np.float32)
    d, i = fused_l2_argmin(jnp.asarray(x), jnp.asarray(y), bm=32, bn=256, interpret=True)
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_pallas_interpret(rng, select_min):
    s = rng.random((37, 5000), dtype=np.float32)
    v, ix = select_k_pallas(jnp.asarray(s), 10, select_min=select_min, interpret=True)
    order = np.argsort(s if select_min else -s, 1)[:, :10]
    want_v = np.take_along_axis(s, order, 1)
    np.testing.assert_allclose(np.asarray(v), want_v, rtol=1e-6)
    np.testing.assert_array_equal(np.sort(np.asarray(ix), 1), np.sort(order, 1))


def test_select_k_pallas_duplicates(rng):
    # ties: every extracted index must be distinct
    s = np.zeros((4, 300), np.float32)
    v, ix = select_k_pallas(jnp.asarray(s), 8, interpret=True)
    ix = np.asarray(ix)
    for r in range(4):
        assert len(set(ix[r].tolist())) == 8
    np.testing.assert_allclose(np.asarray(v), 0.0)


def test_select_k_pallas_k_too_big(rng):
    with pytest.raises(ValueError):
        select_k_pallas(jnp.zeros((2, 5)), 6, interpret=True)


def test_fused_dispatch_cpu_falls_back(rng):
    # on the CPU test backend the auto dispatch must take the XLA path
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin

    x = rng.random((20, 8), dtype=np.float32)
    y = rng.random((50, 8), dtype=np.float32)
    d, i = fused_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y))
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))
