"""Pallas kernels in interpreter mode vs references (the CPU-side
equivalent of the reference's kernel unit tests; on real TPU the same
kernels run compiled — see bench.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.ops import fused_l2_argmin, select_k_pallas


def test_fused_l2_argmin_interpret(rng):
    x = rng.random((100, 40), dtype=np.float32)
    y = rng.random((1000, 40), dtype=np.float32)
    d, i = fused_l2_argmin(jnp.asarray(x), jnp.asarray(y), interpret=True)
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))
    np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-4, atol=1e-5)


def test_fused_l2_argmin_ragged_shapes(rng):
    # shapes not multiples of the block sizes exercise the padding masks
    x = rng.random((33, 7), dtype=np.float32)
    y = rng.random((517, 7), dtype=np.float32)
    d, i = fused_l2_argmin(jnp.asarray(x), jnp.asarray(y), bm=32, bn=256, interpret=True)
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_pallas_interpret(rng, select_min):
    s = rng.random((37, 5000), dtype=np.float32)
    v, ix = select_k_pallas(jnp.asarray(s), 10, select_min=select_min, interpret=True)
    order = np.argsort(s if select_min else -s, 1)[:, :10]
    want_v = np.take_along_axis(s, order, 1)
    np.testing.assert_allclose(np.asarray(v), want_v, rtol=1e-6)
    np.testing.assert_array_equal(np.sort(np.asarray(ix), 1), np.sort(order, 1))


def test_select_k_pallas_duplicates(rng):
    # ties: every extracted index must be distinct
    s = np.zeros((4, 300), np.float32)
    v, ix = select_k_pallas(jnp.asarray(s), 8, interpret=True)
    ix = np.asarray(ix)
    for r in range(4):
        assert len(set(ix[r].tolist())) == 8
    np.testing.assert_allclose(np.asarray(v), 0.0)


def test_select_k_pallas_k_too_big(rng):
    with pytest.raises(ValueError):
        select_k_pallas(jnp.zeros((2, 5)), 6, interpret=True)


def test_fused_dispatch_cpu_falls_back(rng):
    # on the CPU test backend the auto dispatch must take the XLA path
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin

    x = rng.random((20, 8), dtype=np.float32)
    y = rng.random((50, 8), dtype=np.float32)
    d, i = fused_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y))
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))


class TestSegmentedScan:
    """segmented_scan_topk (interpret mode off-TPU) vs numpy reference:
    per-strided-bin mins (bin = position mod 128) of each segment's
    distance row."""

    def test_bin_mins_match_numpy(self):
        from raft_tpu.ops.pallas_kernels import segmented_scan_topk

        rng = np.random.default_rng(0)
        n_lists, L, d, n_seg, S = 8, 1408, 64, 12, 16
        packed = rng.standard_normal((n_lists, L, d)).astype(np.float32)
        ids = rng.integers(-1, 10_000, (n_lists, L)).astype(np.int32)
        seg_list = rng.integers(0, n_lists, n_seg).astype(np.int32)
        qv = rng.standard_normal((n_seg, S, d)).astype(np.float32)

        keys, pos = segmented_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), "l2", interpret=True)
        keys, pos = np.asarray(keys), np.asarray(pos)
        T = L // 128
        assert keys.shape == (n_seg, S, 256)

        for s in (0, 5, n_seg - 1):
            li = seg_list[s]
            dist = ((qv[s][:, None, :] - packed[li][None, :, :]) ** 2).sum(-1)
            dist[:, ids[li] < 0] = np.inf
            d3 = dist.reshape(S, T, 128)
            m1 = d3.min(axis=1)                            # [S, 128] bins
            a1 = d3.argmin(axis=1)
            d3b = d3.copy()
            d3b[np.arange(S)[:, None], a1, np.arange(128)[None, :]] = np.inf
            m2 = d3b.min(axis=1)
            a2 = d3b.argmin(axis=1)
            ref_min = np.concatenate([m1, m2], axis=1)
            np.testing.assert_allclose(keys[s], ref_min, rtol=1e-4, atol=1e-4)
            lanes = np.arange(128)[None, :]
            ref_pos = np.concatenate([a1 * 128 + lanes, a2 * 128 + lanes], 1)
            ref_ids = ids[li][ref_pos]                     # kernel emits ids
            okmask = np.isfinite(ref_min)
            assert (pos[s][okmask] == ref_ids[okmask]).all()
            assert (pos[s][~okmask] == -1).all()

    def test_ip_metric(self):
        from raft_tpu.ops.pallas_kernels import segmented_scan_topk

        rng = np.random.default_rng(1)
        packed = rng.standard_normal((4, 256, 32)).astype(np.float32)
        ids = np.where(rng.random((4, 256)) < 0.1, -1, 1).astype(np.int32)
        seg_list = np.array([2, 0, 3], np.int32)
        qv = rng.standard_normal((3, 8, 32)).astype(np.float32)
        keys, pos = segmented_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), "ip", interpret=True)
        keys, pos = np.asarray(keys), np.asarray(pos)
        s = 0
        score = -(qv[s] @ packed[2].T)
        score[:, ids[2] < 0] = np.inf
        ref = score.reshape(8, 2, 128).min(axis=1)
        np.testing.assert_allclose(keys[s][:, :128], ref, rtol=1e-4, atol=1e-4)
