"""Pallas kernels in interpreter mode vs references (the CPU-side
equivalent of the reference's kernel unit tests; on real TPU the same
kernels run compiled — see bench.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.ops import fused_l2_argmin, select_k_pallas


def test_fused_l2_argmin_interpret(rng):
    x = rng.random((100, 40), dtype=np.float32)
    y = rng.random((1000, 40), dtype=np.float32)
    d, i = fused_l2_argmin(jnp.asarray(x), jnp.asarray(y), interpret=True)
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))
    np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-4, atol=1e-5)


def test_fused_l2_argmin_ragged_shapes(rng):
    # shapes not multiples of the block sizes exercise the padding masks
    x = rng.random((33, 7), dtype=np.float32)
    y = rng.random((517, 7), dtype=np.float32)
    d, i = fused_l2_argmin(jnp.asarray(x), jnp.asarray(y), bm=32, bn=256, interpret=True)
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))


@pytest.mark.parametrize("select_min", [True, False])
def test_select_k_pallas_interpret(rng, select_min):
    s = rng.random((37, 5000), dtype=np.float32)
    v, ix = select_k_pallas(jnp.asarray(s), 10, select_min=select_min, interpret=True)
    order = np.argsort(s if select_min else -s, 1)[:, :10]
    want_v = np.take_along_axis(s, order, 1)
    np.testing.assert_allclose(np.asarray(v), want_v, rtol=1e-6)
    np.testing.assert_array_equal(np.sort(np.asarray(ix), 1), np.sort(order, 1))


def test_select_k_pallas_duplicates(rng):
    # ties: every extracted index must be distinct
    s = np.zeros((4, 300), np.float32)
    v, ix = select_k_pallas(jnp.asarray(s), 8, interpret=True)
    ix = np.asarray(ix)
    for r in range(4):
        assert len(set(ix[r].tolist())) == 8
    np.testing.assert_allclose(np.asarray(v), 0.0)


def test_select_k_pallas_k_too_big(rng):
    with pytest.raises(ValueError):
        select_k_pallas(jnp.zeros((2, 5)), 6, interpret=True)


def test_fused_dispatch_cpu_falls_back(rng):
    # on the CPU test backend the auto dispatch must take the XLA path
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin

    x = rng.random((20, 8), dtype=np.float32)
    y = rng.random((50, 8), dtype=np.float32)
    d, i = fused_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y))
    full = ((x[:, None, :] - y[None]) ** 2).sum(-1)
    np.testing.assert_array_equal(np.asarray(i), np.argmin(full, 1))


class TestSegmentedScan:
    """segmented_scan_topk (interpret mode off-TPU) vs numpy reference:
    per-strided-bin mins (bin = position mod 128) of each segment's
    distance row."""

    def test_bin_mins_match_numpy(self):
        from raft_tpu.ops.pallas_kernels import segmented_scan_topk

        rng = np.random.default_rng(0)
        n_lists, L, d, n_seg, S = 8, 1408, 64, 12, 16
        packed = rng.standard_normal((n_lists, L, d)).astype(np.float32)
        ids = rng.integers(-1, 10_000, (n_lists, L)).astype(np.int32)
        seg_list = rng.integers(0, n_lists, n_seg).astype(np.int32)
        qv = rng.standard_normal((n_seg, S, d)).astype(np.float32)

        keys, pos = segmented_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), "l2", interpret=True)
        keys, pos = np.asarray(keys), np.asarray(pos)
        T = L // 128
        assert keys.shape == (n_seg, S, 256)

        for s in (0, 5, n_seg - 1):
            li = seg_list[s]
            dist = ((qv[s][:, None, :] - packed[li][None, :, :]) ** 2).sum(-1)
            dist[:, ids[li] < 0] = np.inf
            d3 = dist.reshape(S, T, 128)
            m1 = d3.min(axis=1)                            # [S, 128] bins
            a1 = d3.argmin(axis=1)
            d3b = d3.copy()
            d3b[np.arange(S)[:, None], a1, np.arange(128)[None, :]] = np.inf
            m2 = d3b.min(axis=1)
            a2 = d3b.argmin(axis=1)
            ref_min = np.concatenate([m1, m2], axis=1)
            np.testing.assert_allclose(keys[s], ref_min, rtol=1e-4, atol=1e-4)
            lanes = np.arange(128)[None, :]
            ref_pos = np.concatenate([a1 * 128 + lanes, a2 * 128 + lanes], 1)
            ref_ids = ids[li][ref_pos]                     # kernel emits ids
            okmask = np.isfinite(ref_min)
            assert (pos[s][okmask] == ref_ids[okmask]).all()
            assert (pos[s][~okmask] == -1).all()

    def test_ip_metric(self):
        from raft_tpu.ops.pallas_kernels import segmented_scan_topk

        rng = np.random.default_rng(1)
        packed = rng.standard_normal((4, 256, 32)).astype(np.float32)
        ids = np.where(rng.random((4, 256)) < 0.1, -1, 1).astype(np.int32)
        seg_list = np.array([2, 0, 3], np.int32)
        qv = rng.standard_normal((3, 8, 32)).astype(np.float32)
        keys, pos = segmented_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), "ip", interpret=True)
        keys, pos = np.asarray(keys), np.asarray(pos)
        s = 0
        score = -(qv[s] @ packed[2].T)
        score[:, ids[2] < 0] = np.inf
        ref = score.reshape(8, 2, 128).min(axis=1)
        np.testing.assert_allclose(keys[s][:, :128], ref, rtol=1e-4, atol=1e-4)


class TestIvfPqLutScan:
    """ivfpq_lut_scan_topk (interpret mode) vs a numpy ADC reference:
    in-kernel unpack of packed pq_bits codes, Σ_s QLUT[s, code_s]
    accumulation, masked list tails, and the 2-deep bin running merge."""

    def _mk(self, rng, n_lists, L, S, pq_bits, P, n_seg, seg, sizes=None,
            fold=False):
        from raft_tpu.neighbors.ivf_pq import pack_bits_np

        K = 1 << pq_bits
        rot = S * P
        codes = rng.integers(0, K, (n_lists, L, S)).astype(np.uint8)
        packed = np.stack([pack_bits_np(codes[li], pq_bits)
                           for li in range(n_lists)])
        if fold:
            nb = packed.shape[-1]
            assert (L * nb) % 128 == 0
            packed = packed.reshape(n_lists, -1, 128)
        cb = rng.standard_normal((S, K, P)).astype(np.float32)
        ids = np.full((n_lists, L), -1, np.int32)
        if sizes is None:
            sizes = [L] * n_lists
        for li, sz in enumerate(sizes):
            # unique ids per list: the parity checks key by id
            ids[li, :sz] = li * L + rng.permutation(L)[:sz]
        norms = rng.random((n_lists, L)).astype(np.float32) + 0.5
        ctr = rng.standard_normal((n_lists, rot)).astype(np.float32)
        qv = rng.standard_normal((n_seg, seg, rot)).astype(np.float32)
        seg_list = rng.integers(0, n_lists, n_seg).astype(np.int32)
        return codes, packed, cb, ids, norms, ctr, qv, seg_list

    def _ref_keys(self, codes, cb, ids, norms, ctr, qv, li, s, metric):
        """All-candidate reference: {id: key} for segment s over list li."""
        S = codes.shape[-1]
        dec = cb[np.arange(S)[:, None], codes[li].T].transpose(1, 0, 2)
        dec = dec.reshape(codes.shape[1], -1)             # [L, rot]
        qd = qv[s] @ dec.T                                # [seg, L]
        qc = qv[s] @ ctr[li]                              # [seg]
        if metric == "ip":
            key = -(qc[:, None] + qd)
        else:
            key = norms[li][None, :] - 2.0 * (qc[:, None] + qd)
        return key

    @pytest.mark.parametrize("pq_bits", [4, 5, 6, 8])
    def test_unpack_and_adc_parity(self, pq_bits):
        """L ≤ bins → the emitted candidate set is LOSSLESS: every valid
        candidate appears exactly once with its exact ADC key."""
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(3 + pq_bits)
        n_lists, L, S, P, n_seg, seg = 4, 256, 16, 2, 5, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, pq_bits, P, n_seg, seg,
            sizes=[L, L - 37, 3, 0])
        keys, kids = ivfpq_lut_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), jnp.asarray(norms), jnp.asarray(ctr),
            jnp.asarray(cb), "l2", pq_bits=pq_bits, pq_dim=S, L=L,
            lut_dtype="float32", interpret=True)
        keys, kids = np.asarray(keys), np.asarray(kids)
        assert keys.shape == (n_seg, seg, 256)
        for s in (0, 2, n_seg - 1):
            li = seg_list[s]
            ref = self._ref_keys(codes, cb, ids, norms, ctr, qv, li, s,
                                 "l2")
            for q in range(seg):
                got = {int(i): k for i, k in zip(kids[s, q], keys[s, q])
                       if i >= 0}
                want = {int(ids[li, l]): ref[q, l]
                        for l in range(L) if ids[li, l] >= 0}
                assert set(got) == set(want)
                for i in want:
                    np.testing.assert_allclose(got[i], want[i],
                                               rtol=1e-4, atol=1e-4)

    def test_folded_layout_parity(self):
        """Lane-folded packed codes (codes_folded storage) decode
        identically — the fold-group strided unpack and bin spreading."""
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(7)
        # S=16, pq_bits=8 → nb=16 → G=8 fold groups per 128-byte row
        n_lists, L, S, P, n_seg, seg = 3, 240, 16, 2, 4, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, 8, P, n_seg, seg,
            sizes=[L, 100, 17], fold=True)
        assert packed.shape[-1] == 128
        keys, kids = ivfpq_lut_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), jnp.asarray(norms), jnp.asarray(ctr),
            jnp.asarray(cb), "l2", pq_bits=8, pq_dim=S, L=L,
            lut_dtype="float32", interpret=True)
        keys, kids = np.asarray(keys), np.asarray(kids)
        for s in range(n_seg):
            li = seg_list[s]
            ref = self._ref_keys(codes, cb, ids, norms, ctr, qv, li, s,
                                 "l2")
            for q in (0, seg - 1):
                got = {int(i): k for i, k in zip(kids[s, q], keys[s, q])
                       if i >= 0}
                want = {int(ids[li, l]): ref[q, l]
                        for l in range(L) if ids[li, l] >= 0}
                assert set(got) == set(want)
                for i in want:
                    np.testing.assert_allclose(got[i], want[i],
                                               rtol=1e-4, atol=1e-4)

    def test_two_deep_bins_lossy_tail(self):
        """L > bins: each bin keeps the TWO smallest of its strided
        candidates (unfolded mapping: bin = position mod 128)."""
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(11)
        n_lists, L, S, P, n_seg, seg = 2, 512, 16, 2, 3, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, 8, P, n_seg, seg)
        keys, kids = ivfpq_lut_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), jnp.asarray(norms), jnp.asarray(ctr),
            jnp.asarray(cb), "l2", pq_bits=8, pq_dim=S, L=L,
            lut_dtype="float32", interpret=True)
        keys, kids = np.asarray(keys), np.asarray(kids)
        s = 1
        li = seg_list[s]
        ref = self._ref_keys(codes, cb, ids, norms, ctr, qv, li, s, "l2")
        for q in (0, 3):
            for lane in (0, 17, 127):
                cand = sorted(ref[q, lane::128])
                got = sorted([keys[s, q, lane], keys[s, q, 128 + lane]])
                np.testing.assert_allclose(got, cand[:2],
                                           rtol=1e-4, atol=1e-4)

    def test_ip_metric_keys(self):
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(13)
        n_lists, L, S, P, n_seg, seg = 3, 128, 8, 4, 3, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, 8, P, n_seg, seg, sizes=[L, 60, L])
        keys, kids = ivfpq_lut_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), jnp.asarray(norms), jnp.asarray(ctr),
            jnp.asarray(cb), "ip", pq_bits=8, pq_dim=S, L=L,
            lut_dtype="float32", interpret=True)
        keys, kids = np.asarray(keys), np.asarray(kids)
        s, q = 1, 2
        li = seg_list[s]
        ref = self._ref_keys(codes, cb, ids, norms, ctr, qv, li, s, "ip")
        got = {int(i): k for i, k in zip(kids[s, q], keys[s, q]) if i >= 0}
        want = {int(ids[li, l]): ref[q, l]
                for l in range(L) if ids[li, l] >= 0}
        assert set(got) == set(want)
        for i in want:
            np.testing.assert_allclose(got[i], want[i], rtol=1e-4,
                                       atol=1e-4)

    def test_lut_dtype_tolerance_tiers(self):
        """bf16 keys track f32 keys loosely; fp8 more loosely — the
        quantization ladder the lut_dtype knob buys."""
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(17)
        n_lists, L, S, P, n_seg, seg = 2, 128, 16, 2, 2, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, 8, P, n_seg, seg)
        outs = {}
        for dt in ("float32", "bfloat16", "float8_e4m3"):
            k_, _ = ivfpq_lut_scan_topk(
                jnp.asarray(seg_list), jnp.asarray(qv),
                jnp.asarray(packed), jnp.asarray(ids), jnp.asarray(norms),
                jnp.asarray(ctr), jnp.asarray(cb), "l2", pq_bits=8,
                pq_dim=S, L=L, lut_dtype=dt, interpret=True)
            outs[dt] = np.asarray(k_)
        fin = np.isfinite(outs["float32"])
        assert (np.isfinite(outs["bfloat16"]) == fin).all()
        scale = np.abs(outs["float32"][fin]).max()
        bf16_err = np.abs(outs["bfloat16"][fin]
                          - outs["float32"][fin]).max() / scale
        fp8_err = np.abs(outs["float8_e4m3"][fin]
                         - outs["float32"][fin]).max() / scale
        assert bf16_err < 0.05, bf16_err
        assert fp8_err < 0.30, fp8_err
        assert bf16_err <= fp8_err

    def test_dispatch_heuristic(self, monkeypatch):
        from raft_tpu.ops.pallas_kernels import pallas_lut_scan_wanted

        monkeypatch.delenv("RAFT_TPU_PALLAS_LUTSCAN", raising=False)
        # off-TPU, no force → not wanted
        assert not pallas_lut_scan_wanted(64, 256, 2, 64, 64, 1024, 128)
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        assert pallas_lut_scan_wanted(64, 256, 2, 64, 64, 1024, 128)
        # folded deep-100m shape: nb=64 inside 128-byte rows (G=2)
        assert pallas_lut_scan_wanted(64, 256, 2, 64, 128, 18312, 128)
        # a filter adds its byte stream + unpack operands to the VMEM
        # model without disqualifying the workhorse shapes
        assert pallas_lut_scan_wanted(64, 256, 2, 64, 64, 1024, 128,
                                      filtered=True)
        assert pallas_lut_scan_wanted(64, 256, 2, 64, 128, 18312, 128,
                                      filtered=True)
        # byte width not dividing the stored row width → unsupported
        assert not pallas_lut_scan_wanted(96, 256, 1, 96, 128, 1024, 96)
        # fold group too deep (G=16)
        assert not pallas_lut_scan_wanted(8, 256, 2, 8, 128, 1024, 16)
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "never")
        assert not pallas_lut_scan_wanted(64, 256, 2, 64, 64, 1024, 128)

    def _filter_bytes(self, ids, keep_global):
        """Packed per-list filter bytes over a [n_lists, L] GLOBAL id
        table — the host-side operand prep the dispatchers run
        (sample_filter.list_filter_bytes), built here via the same
        public helpers the tier uses."""
        from raft_tpu.core import bitset
        from raft_tpu.neighbors import sample_filter

        bits = bitset.from_mask(jnp.asarray(keep_global))
        return np.asarray(sample_filter.list_filter_bytes(
            bits, jnp.asarray(ids)))

    def _filtered_want(self, codes, cb, ids, norms, ctr, qv, li, s,
                       keep_global, L):
        ref = self._ref_keys(codes, cb, ids, norms, ctr, qv, li, s, "l2")
        return ref, {int(ids[li, l]): ref[:, l] for l in range(L)
                     if ids[li, l] >= 0 and keep_global[ids[li, l]]}

    @pytest.mark.parametrize("sel", [0.01, 0.1, 0.5])
    def test_filtered_parity_selectivity(self, sel):
        """Streamed filter mask: at every selectivity the emitted
        candidate set is exactly the KEPT subset of the unfiltered
        lossless set (L ≤ bins), keys exact, filtered ids absent."""
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(int(sel * 1000) + 29)
        n_lists, L, S, P, n_seg, seg = 4, 256, 16, 2, 5, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, 8, P, n_seg, seg,
            sizes=[L, L - 37, 3, 0])
        keep = rng.random(n_lists * L) < sel
        fbytes = self._filter_bytes(ids, keep)
        keys, kids = ivfpq_lut_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), jnp.asarray(norms), jnp.asarray(ctr),
            jnp.asarray(cb), "l2", pq_bits=8, pq_dim=S, L=L,
            lut_dtype="float32", filter_bytes=jnp.asarray(fbytes),
            interpret=True)
        keys, kids = np.asarray(keys), np.asarray(kids)
        for s in range(n_seg):
            li = seg_list[s]
            ref, want_by_id = self._filtered_want(
                codes, cb, ids, norms, ctr, qv, li, s, keep, L)
            for q in (0, seg - 1):
                got = {int(i): k for i, k in zip(kids[s, q], keys[s, q])
                       if i >= 0}
                assert set(got) == set(want_by_id), (s, q, sel)
                for i, kv in got.items():
                    np.testing.assert_allclose(kv, want_by_id[i][q],
                                               rtol=1e-4, atol=1e-4)

    def test_filtered_edge_masks(self):
        """all-pass == unfiltered bit-for-bit; all-fail emits only
        sentinels; a single survivor is found wherever it hides."""
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(31)
        n_lists, L, S, P, n_seg, seg = 3, 256, 16, 2, 4, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, 4, P, n_seg, seg, sizes=[L, 100, 17])

        def run(fbytes):
            k_, i_ = ivfpq_lut_scan_topk(
                jnp.asarray(seg_list), jnp.asarray(qv),
                jnp.asarray(packed), jnp.asarray(ids),
                jnp.asarray(norms), jnp.asarray(ctr), jnp.asarray(cb),
                "l2", pq_bits=4, pq_dim=S, L=L, lut_dtype="float32",
                filter_bytes=(None if fbytes is None
                              else jnp.asarray(fbytes)),
                interpret=True)
            return np.asarray(k_), np.asarray(i_)

        base_k, base_i = run(None)
        # all-pass: identical to no filter
        allpass = np.ones(n_lists * L, bool)
        k1, i1 = run(self._filter_bytes(ids, allpass))
        np.testing.assert_array_equal(i1, base_i)
        np.testing.assert_allclose(k1, base_k, rtol=0, atol=0)
        # all-fail: nothing but sentinels
        k0, i0 = run(self._filter_bytes(ids, np.zeros(n_lists * L, bool)))
        assert (i0 == -1).all()
        assert not np.isfinite(k0).any()
        # single survivor: exactly that id, everywhere its list is probed
        surv = np.zeros(n_lists * L, bool)
        li0 = int(seg_list[1])
        lane = int(np.where(ids[li0] >= 0)[0].max())  # last valid slot
        gid = int(ids[li0, lane])
        assert gid >= 0
        surv[gid] = True
        ks, is_ = run(self._filter_bytes(ids, surv))
        for s in range(n_seg):
            got = set(int(i) for i in is_[s].ravel() if i >= 0)
            assert got == ({gid} if int(seg_list[s]) == li0 else set()), s

    def test_filtered_ragged_tail_and_folded(self):
        """Filter bytes pad to whole code tiles with 0 (= filtered): a
        ragged list tail plus lane-folded storage must not admit any
        OOB candidate, and kept candidates survive exactly."""
        from raft_tpu.ops.pallas_kernels import ivfpq_lut_scan_topk

        rng = np.random.default_rng(37)
        n_lists, L, S, P, n_seg, seg = 3, 240, 16, 2, 4, 8
        codes, packed, cb, ids, norms, ctr, qv, seg_list = self._mk(
            rng, n_lists, L, S, 8, P, n_seg, seg,
            sizes=[L, 100, 17], fold=True)
        keep = rng.random(n_lists * L) < 0.4
        fbytes = self._filter_bytes(ids, keep)
        keys, kids = ivfpq_lut_scan_topk(
            jnp.asarray(seg_list), jnp.asarray(qv), jnp.asarray(packed),
            jnp.asarray(ids), jnp.asarray(norms), jnp.asarray(ctr),
            jnp.asarray(cb), "l2", pq_bits=8, pq_dim=S, L=L,
            lut_dtype="float32", filter_bytes=jnp.asarray(fbytes),
            interpret=True)
        keys, kids = np.asarray(keys), np.asarray(kids)
        for s in range(n_seg):
            li = seg_list[s]
            ref, want_by_id = self._filtered_want(
                codes, cb, ids, norms, ctr, qv, li, s, keep, L)
            for q in (0, seg - 1):
                got = {int(i): k for i, k in zip(kids[s, q], keys[s, q])
                       if i >= 0}
                assert set(got) == set(want_by_id), (s, q)
                for i, kv in got.items():
                    np.testing.assert_allclose(kv, want_by_id[i][q],
                                               rtol=1e-4, atol=1e-4)


class TestGatherRefine:
    """gather_refine_topk (interpret mode off-TPU) vs numpy reference:
    streamed candidate-row gather + exact metric epilogue + running
    top-k, with no [m, C, d] buffer (ISSUE 4 acceptance)."""

    def _ref(self, data, q, cand, metric):
        rows = data[np.clip(cand, 0, data.shape[0] - 1)].astype(np.float32)
        s = np.einsum("md,mcd->mc", q, rows)
        if metric == "ip":
            key = -s
        elif metric == "cos":
            qn = np.sqrt(np.maximum((q * q).sum(1), 1e-30))
            cn = np.sqrt(np.maximum((rows ** 2).sum(-1), 1e-30))
            key = 1.0 - s / (qn[:, None] * cn)
        else:
            key = np.maximum((q * q).sum(1)[:, None]
                             + (rows ** 2).sum(-1) - 2.0 * s, 0.0)
        return np.where(cand >= 0, key, np.inf)

    def _check(self, data, q, cand, k, metric, **kw):
        from raft_tpu.ops import gather_refine_topk

        keys, ids = gather_refine_topk(jnp.asarray(data), jnp.asarray(q),
                                       jnp.asarray(cand), k, metric,
                                       interpret=True)
        keys, ids = np.asarray(keys), np.asarray(ids)
        ref = self._ref(np.asarray(data, np.float32), q, cand, metric)
        order = np.argsort(ref, axis=1, kind="stable")[:, :k]
        want_v = np.take_along_axis(ref, order, 1)
        np.testing.assert_allclose(keys, want_v, **kw)
        want_i = np.where(np.isinf(want_v), -1,
                          np.take_along_axis(cand, order, 1))
        # ids must agree wherever keys are strictly ordered (ties may
        # legally reorder between the buffer merge and a full argsort)
        strict = np.ones_like(keys, dtype=bool)
        strict[:, 1:] &= want_v[:, 1:] != want_v[:, :-1]
        strict[:, :-1] &= want_v[:, :-1] != want_v[:, 1:]
        np.testing.assert_array_equal(ids[strict], want_i[strict])

    def test_metrics_match_numpy(self, rng):
        data = rng.standard_normal((700, 96)).astype(np.float32)
        q = rng.standard_normal((21, 96)).astype(np.float32)
        cand = rng.integers(0, 700, (21, 300)).astype(np.int32)
        for metric in ("l2", "ip", "cos"):
            self._check(data, q, cand, 10, metric, rtol=1e-4, atol=1e-4)

    def test_invalid_and_ragged(self, rng):
        data = rng.standard_normal((500, 40)).astype(np.float32)
        q = rng.standard_normal((9, 40)).astype(np.float32)
        cand = rng.integers(0, 500, (9, 270)).astype(np.int32)
        cand[0, :] = -1            # fully invalid row
        cand[1, -31:] = -1         # ragged tail
        cand[2, 10:30] = cand[2, 9]  # duplicates
        self._check(data, q, cand, 8, "l2", rtol=1e-4, atol=1e-4)

    def test_bf16_recon_rows(self, rng):
        """bf16 dataset rows (the recon-cache input) stream through the
        row DMAs dtype-preserved; keys computed in f32 against the
        bf16-quantized values."""
        data = rng.standard_normal((400, 64)).astype(np.float32)
        data_bf = jnp.asarray(data).astype(jnp.bfloat16)
        q = rng.standard_normal((10, 64)).astype(np.float32)
        cand = rng.integers(0, 400, (10, 256)).astype(np.int32)
        self._check(np.asarray(data_bf.astype(jnp.float32)), q, cand, 8,
                    "l2", rtol=1e-4, atol=1e-4)

    def test_short_rows_pad_with_invalid(self, rng):
        from raft_tpu.ops import gather_refine_topk

        data = rng.standard_normal((100, 16)).astype(np.float32)
        q = rng.standard_normal((3, 16)).astype(np.float32)
        cand = np.full((3, 200), -1, np.int32)
        cand[:, :4] = rng.integers(0, 100, (3, 4))
        keys, ids = gather_refine_topk(jnp.asarray(data), jnp.asarray(q),
                                       jnp.asarray(cand), 10, "l2",
                                       interpret=True)
        keys, ids = np.asarray(keys), np.asarray(ids)
        assert np.isfinite(keys[:, :4]).all()
        assert np.isinf(keys[:, 4:]).all() and (ids[:, 4:] == -1).all()

    def test_k_over_merge_budget_raises(self, rng):
        from raft_tpu.ops import gather_refine_topk
        from raft_tpu.ops.pallas_kernels import GATHER_REFINE_MAX_K

        with pytest.raises(ValueError):
            gather_refine_topk(jnp.zeros((10, 16)), jnp.zeros((2, 16)),
                               jnp.zeros((2, 300), jnp.int32),
                               GATHER_REFINE_MAX_K + 1, "l2",
                               interpret=True)

    def test_dispatch_heuristic(self, monkeypatch):
        from raft_tpu.ops.pallas_kernels import pallas_gather_refine_wanted

        monkeypatch.delenv("RAFT_TPU_PALLAS_REFINE", raising=False)
        # off-TPU, no force → not wanted
        assert not pallas_gather_refine_wanted(10_000, 2000, 96, 10)
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
        assert pallas_gather_refine_wanted(10_000, 2000, 96, 10)
        # a filter adds the per-candidate word scratch without
        # disqualifying the acceptance shape
        assert pallas_gather_refine_wanted(10_000, 2000, 96, 10,
                                           filtered=True)
        # k past the merge budget / tiny candidate sets stay on XLA
        assert not pallas_gather_refine_wanted(10_000, 2000, 96, 65)
        assert not pallas_gather_refine_wanted(10_000, 100, 96, 10)
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "never")
        assert not pallas_gather_refine_wanted(10_000, 2000, 96, 10)

    def _check_filtered(self, data, q, cand, k, metric, keep, **kw):
        from raft_tpu.core import bitset
        from raft_tpu.ops import gather_refine_topk

        bits = bitset.from_mask(jnp.asarray(keep))
        keys, ids = gather_refine_topk(jnp.asarray(data), jnp.asarray(q),
                                       jnp.asarray(cand), k, metric,
                                       filter_bits=bits, interpret=True)
        keys, ids = np.asarray(keys), np.asarray(ids)
        ref = self._ref(np.asarray(data, np.float32), q, cand, metric)
        # the filter joins the invalid-id mask: cleared bits → +inf/-1
        kept = (cand >= 0) & keep[np.clip(cand, 0, len(keep) - 1)]
        ref = np.where(kept, ref, np.inf)
        order = np.argsort(ref, axis=1, kind="stable")[:, :k]
        want_v = np.take_along_axis(ref, order, 1)
        np.testing.assert_allclose(keys, want_v, **kw)
        want_i = np.where(np.isinf(want_v), -1,
                          np.take_along_axis(cand, order, 1))
        strict = np.ones_like(keys, dtype=bool)
        strict[:, 1:] &= want_v[:, 1:] != want_v[:, :-1]
        strict[:, :-1] &= want_v[:, :-1] != want_v[:, 1:]
        np.testing.assert_array_equal(ids[strict], want_i[strict])
        got = ids[ids >= 0]
        assert keep[got].all() if got.size else True

    def test_filtered_metrics_match_numpy(self, rng):
        """Per-candidate bitset-word fetch through the row-DMA queue:
        cleared bits poison rows to +inf/-1 across every metric."""
        data = rng.standard_normal((700, 96)).astype(np.float32)
        q = rng.standard_normal((21, 96)).astype(np.float32)
        cand = rng.integers(0, 700, (21, 300)).astype(np.int32)
        keep = rng.random(700) < 0.5
        for metric in ("l2", "ip", "cos"):
            self._check_filtered(data, q, cand, 10, metric, keep,
                                 rtol=1e-4, atol=1e-4)

    def test_filtered_edge_masks(self, rng):
        """all-pass == unfiltered; all-fail → all sentinels; a single
        surviving candidate is returned alone; ragged/invalid tails
        compose with the filter."""
        from raft_tpu.core import bitset
        from raft_tpu.ops import gather_refine_topk

        data = rng.standard_normal((500, 40)).astype(np.float32)
        q = rng.standard_normal((9, 40)).astype(np.float32)
        cand = rng.integers(0, 500, (9, 270)).astype(np.int32)
        cand[1, -31:] = -1         # ragged tail composes with the filter

        base_k, base_i = gather_refine_topk(
            jnp.asarray(data), jnp.asarray(q), jnp.asarray(cand), 8,
            "l2", interpret=True)
        allpass = bitset.from_mask(jnp.ones(500, bool))
        k1, i1 = gather_refine_topk(
            jnp.asarray(data), jnp.asarray(q), jnp.asarray(cand), 8,
            "l2", filter_bits=allpass, interpret=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(base_i))
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(base_k))

        allfail = bitset.from_mask(jnp.zeros(500, bool))
        k0, i0 = gather_refine_topk(
            jnp.asarray(data), jnp.asarray(q), jnp.asarray(cand), 8,
            "l2", filter_bits=allfail, interpret=True)
        assert (np.asarray(i0) == -1).all()
        assert np.isinf(np.asarray(k0)).all()

        surv = np.zeros(500, bool)
        gid = int(cand[4, 100])
        surv[gid] = True
        ks, is_ = gather_refine_topk(
            jnp.asarray(data), jnp.asarray(q), jnp.asarray(cand), 8,
            "l2", filter_bits=bitset.from_mask(jnp.asarray(surv)),
            interpret=True)
        is_ = np.asarray(is_)
        for m in range(9):
            got = set(is_[m][is_[m] >= 0].tolist())
            want = {gid} if gid in set(cand[m].tolist()) else set()
            assert got == want, m
