"""Core layer tests (reference test model: cpp/test/core/)."""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import (
    DeviceResources,
    LogicError,
    Resources,
    bitset,
    expects,
    get_device_resources,
    serialize,
)


class TestResources:
    def test_factory_lazy(self):
        r = Resources()
        calls = []
        r.add_resource_factory("x", lambda: calls.append(1) or 42)
        assert calls == []
        assert r.get_resource("x") == 42
        assert r.get_resource("x") == 42
        assert calls == [1]

    def test_missing_factory_raises(self):
        r = Resources()
        with pytest.raises(LogicError):
            r.get_resource("nope")

    def test_device_resources_rng(self):
        h = DeviceResources(seed=7)
        k1 = h.next_rng_key()
        k2 = h.next_rng_key()
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))

    def test_handle_pool(self):
        h1 = get_device_resources()
        h2 = get_device_resources()
        assert h1 is h2

    def test_expects(self):
        expects(True, "fine")
        with pytest.raises(LogicError, match="bad thing 3"):
            expects(False, "bad thing %d", 3)


class TestBitset:
    def test_roundtrip(self, rng):
        mask = rng.random(100) < 0.5
        bits = bitset.from_mask(jnp.asarray(mask))
        out = np.asarray(bitset.to_mask(bits, 100))
        np.testing.assert_array_equal(out, mask)

    def test_set_test_flip_count(self):
        bits = bitset.create(70, default_value=False)
        bits = bitset.set_bits(bits, jnp.array([0, 33, 69]))
        assert bool(bitset.test(bits, 33))
        assert not bool(bitset.test(bits, 34))
        assert int(bitset.count(bits, 70)) == 3
        flipped = bitset.flip(bits)
        assert int(bitset.count(flipped, 70)) == 67

    def test_word_at_gather(self, rng):
        """word_at: the bitset word covering each id, for arbitrary id
        arrays — the shared primitive behind test()/passes() and the
        fused kernels' operand prep."""
        mask = rng.random(200) < 0.5
        bits = bitset.from_mask(jnp.asarray(mask))
        ids = jnp.asarray([0, 31, 32, 63, 64, 199])
        words = np.asarray(bitset.word_at(bits, ids))
        np.testing.assert_array_equal(
            words, np.asarray(bits)[np.asarray(ids) // 32])

    def test_word_at_and_test_sentinel_preserving(self):
        """Negative ids (the -1 pad sentinel, either id width) never
        wrap to a live word: word_at reads word 0, test() returns
        False (core/ids policy)."""
        bits = bitset.create(64, default_value=True)
        # int32 here; the int64 width (ids past 2³¹) is proven by the
        # filtered capacity proof (tools/capacity_prove.py, GL11)
        ids = jnp.asarray([-1, 5, -7], dtype=jnp.int32)
        words = np.asarray(bitset.word_at(bits, ids))
        np.testing.assert_array_equal(words, np.asarray(bits)[[0, 0, 0]])
        out = np.asarray(bitset.test(bits, ids))
        np.testing.assert_array_equal(out, [False, True, False])

    def test_density(self, rng):
        mask = rng.random(320) < 0.25
        bits = bitset.from_mask(jnp.asarray(mask))
        got = float(bitset.density(bits))
        assert abs(got - mask.mean()) < 1e-6
        assert float(bitset.density(bitset.create(320, True))) == 1.0
        assert float(bitset.density(bitset.create(320, False))) == 0.0


class TestSampleFilterPacking:
    """pack_mask_bytes / list_filter_bytes — the fused kernels'
    host-side filter-operand prep (ISSUE 12)."""

    def test_pack_mask_bytes_layout(self):
        from raft_tpu.neighbors import sample_filter

        keep = jnp.asarray(np.array([1, 0, 0, 0, 0, 0, 0, 0,   # byte 0 = 1
                                     1, 1, 0, 0, 0, 0, 0, 1],  # byte 1
                                    bool))
        b = np.asarray(sample_filter.pack_mask_bytes(keep))
        np.testing.assert_array_equal(b, [1, 0b10000011])

    def test_pack_mask_bytes_pads_with_zero(self):
        from raft_tpu.neighbors import sample_filter

        keep = jnp.ones(11, bool)  # 3 pad bits must pack as 0
        b = np.asarray(sample_filter.pack_mask_bytes(keep))
        np.testing.assert_array_equal(b, [0xFF, 0b00000111])

    def test_list_filter_bytes_matches_passes(self, rng):
        """bit j of byte b in list l == passes(filter, ids[l, 8b+j]);
        pad slots (id -1) pack as 0."""
        from raft_tpu.neighbors import sample_filter

        n = 500
        mask = rng.random(n) < 0.5
        bits = bitset.from_mask(jnp.asarray(mask))
        ids = np.full((4, 64), -1, np.int32)
        ids[0] = rng.permutation(n)[:64]
        ids[1, :10] = rng.permutation(n)[:10]
        ids[3] = rng.integers(0, n, 64)
        fbytes = np.asarray(sample_filter.list_filter_bytes(
            bits, jnp.asarray(ids)))
        assert fbytes.shape == (4, 8) and fbytes.dtype == np.uint8
        unpacked = np.unpackbits(fbytes, axis=1, bitorder="little")
        want = (ids >= 0) & mask[np.clip(ids, 0, n - 1)]
        np.testing.assert_array_equal(unpacked.astype(bool), want)


class TestSerialize:
    def test_scalar_roundtrip(self, tmp_path):
        import io

        buf = io.BytesIO()
        for v in [True, 17, 3.5, "hello"]:
            serialize.serialize_scalar(buf, v)
        buf.seek(0)
        assert serialize.deserialize_scalar(buf) is True
        assert serialize.deserialize_scalar(buf) == 17
        assert serialize.deserialize_scalar(buf) == 3.5
        assert serialize.deserialize_scalar(buf) == "hello"

    def test_container_roundtrip(self, tmp_path, rng):
        path = os.path.join(tmp_path, "idx.bin")
        arrays = {
            "data": jnp.asarray(rng.random((10, 4), dtype=np.float32)),
            "ids": jnp.arange(10, dtype=jnp.int32),
        }
        serialize.save_arrays(path, "test_index", 3, {"metric": "l2"}, arrays)
        version, meta, loaded = serialize.load_arrays(path, "test_index")
        assert version == 3
        assert meta == {"metric": "l2"}
        np.testing.assert_allclose(loaded["data"], np.asarray(arrays["data"]))
        np.testing.assert_array_equal(loaded["ids"], np.arange(10))

    def test_kind_mismatch(self, tmp_path):
        path = os.path.join(tmp_path, "idx.bin")
        serialize.save_arrays(path, "a", 1, {}, {})
        with pytest.raises(ValueError, match="expected"):
            serialize.load_arrays(path, "b")


class TestMatrixMiscOps:
    """Reference matrix/*.cuh long-tail surfaces."""

    def test_diagonal_ops(self):
        import jax.numpy as jnp
        from raft_tpu import matrix as M

        m = jnp.asarray([[2.0, 1.0], [3.0, 4.0]])
        np.testing.assert_array_equal(np.asarray(M.get_diagonal(m)), [2.0, 4.0])
        m2 = M.set_diagonal(m, jnp.asarray([9.0, 8.0]))
        np.testing.assert_array_equal(np.asarray(M.get_diagonal(m2)), [9.0, 8.0])
        m3 = M.invert_diagonal(m)
        np.testing.assert_allclose(np.asarray(M.get_diagonal(m3)), [0.5, 0.25])

    def test_math_ops(self):
        import jax.numpy as jnp
        from raft_tpu import matrix as M

        m = jnp.asarray([[4.0, 0.01], [1.0, 9.0]])
        np.testing.assert_allclose(np.asarray(M.sqrt(m))[0, 0], 2.0)
        np.testing.assert_allclose(np.asarray(M.power(m, 2))[1, 1], 81.0)
        r = M.reciprocal(m, thres=0.1)
        assert np.asarray(r)[0, 1] == 0.0 and np.asarray(r)[0, 0] == 0.25
        np.testing.assert_allclose(float(np.asarray(M.ratio(m)).sum()), 1.0,
                                   rtol=1e-6)
        z = M.zero_small_values(m, 0.5)
        assert np.asarray(z)[0, 1] == 0.0
        assert np.asarray(M.eye(3)).trace() == 3.0
        assert np.asarray(M.fill((2, 2), 7.0)).sum() == 28.0


def test_multi_variable_gaussian():
    import jax.numpy as jnp
    from raft_tpu.random import multi_variable_gaussian
    from raft_tpu.random.rng import RngState

    mean = jnp.asarray([1.0, -2.0, 0.5])
    cov = jnp.asarray([[2.0, 0.6, 0.0], [0.6, 1.0, 0.3], [0.0, 0.3, 0.5]])
    for method in ("cholesky", "eig"):
        s = np.asarray(multi_variable_gaussian(RngState(0), mean, cov,
                                               20000, method=method))
        np.testing.assert_allclose(s.mean(0), np.asarray(mean), atol=0.05)
        np.testing.assert_allclose(np.cov(s.T), np.asarray(cov), atol=0.08)
