"""Ring reduce-scatter-of-top-k merge tier (ISSUE 8) on the virtual
8-device CPU mesh: the Pallas kernel's interpret-mode remote-DMA ring
vs numpy, the ppermute fallback's parity with the allgather tier,
exact per-hop ``comms.ops/bytes{op=ring_topk}`` accounting, and
collective-schedule uniformity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from raft_tpu.core.compat import shard_map
from raft_tpu.obs import sanitize
from raft_tpu.ops import pallas_kernels as pk
from raft_tpu.parallel import (
    Comms,
    make_mesh,
    merge_out_spec,
    merge_tier,
    merge_topk,
    merged_rows,
    sharded_knn,
)
from raft_tpu.parallel.merge import ring_auto_wanted

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(axis_names=("shard",))


def numpy_merge(vals, ids, k, select_min):
    """Reference merge: per query, stable top-k over every device's
    candidates (ids < 0 are invalid regardless of their key)."""
    n_dev, m, _ = vals.shape
    cat_v = np.concatenate([vals[d] for d in range(n_dev)], axis=1)
    cat_i = np.concatenate([ids[d] for d in range(n_dev)], axis=1)
    key = np.where(cat_i < 0, np.inf, cat_v if select_min else -cat_v)
    order = np.argsort(key, axis=1, kind="stable")[:, :k]
    return (np.take_along_axis(cat_v, order, 1),
            np.take_along_axis(cat_i, order, 1))


def make_tables(rng, m, k, select_min, dup_ids=False, sentinels=False):
    """Per-device local top-k tables, sorted the way a local search
    emits them (ascending keys for min-select, descending for max)."""
    vals = rng.random((N_DEV, m, k)).astype(np.float32)
    ids = rng.integers(0, 100_000, (N_DEV, m, k)).astype(np.int32)
    if dup_ids:  # the same candidate surviving twice is kept twice
        ids[:, :, 1] = ids[:, :, 0]
    order = np.argsort(vals if select_min else -vals, axis=-1)
    vals = np.take_along_axis(vals, order, -1)
    ids = np.take_along_axis(ids, order, -1)
    if sentinels:  # short tables pad their tails with ±inf sentinels
        pad = np.inf if select_min else -np.inf
        vals[2, :, -2:] = pad
        ids[2, :, -2:] = -1
        vals[5, :, :] = pad  # a whole shard with no candidates
        ids[5, :, :] = -1
    return vals, ids


class TestRingKernelParity:
    """The ACTUAL Pallas kernel (remote DMAs run by the interpreter
    across the 8 CPU devices) vs numpy."""

    def _run_kernel(self, mesh, vals, ids, k, select_min):
        m = vals.shape[1]

        def body(v, i):
            return pk.ring_topk_merge(v[0], i[0], k, "shard", N_DEV,
                                      select_min, interpret=True)

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P("shard", None, None), P("shard", None, None)),
            out_specs=(P("shard", None), P("shard", None)),
            check_vma=False)
        gv, gi = fn(jnp.asarray(vals), jnp.asarray(ids))
        return np.asarray(gv)[:m], np.asarray(gi)[:m]

    @pytest.mark.slow  # k1/max_select keep kernel parity tier-1 (tier-1 budget)
    def test_ragged_m_min_select(self, mesh, rng):
        # m=27: chunks pad to 8 sublane rows, pad rows must not leak
        vals, ids = make_tables(rng, 27, 10, True)
        gv, gi = self._run_kernel(mesh, vals, ids, 10, True)
        rv, ri = numpy_merge(vals, ids, 10, True)
        np.testing.assert_array_equal(gv, rv)
        np.testing.assert_array_equal(gi, ri)

    def test_max_select(self, mesh, rng):
        # ip-style keys: bigger is better, −inf sentinels
        vals, ids = make_tables(rng, 16, 4, False, sentinels=True)
        gv, gi = self._run_kernel(mesh, vals, ids, 4, False)
        rv, ri = numpy_merge(vals, ids, 4, False)
        np.testing.assert_array_equal(gv, rv)
        np.testing.assert_array_equal(gi, ri)

    @pytest.mark.slow  # k1/max_select keep kernel parity tier-1 (tier-1 budget)
    def test_duplicate_ids_and_sentinels(self, mesh, rng):
        vals, ids = make_tables(rng, 8, 6, True, dup_ids=True,
                                sentinels=True)
        gv, gi = self._run_kernel(mesh, vals, ids, 6, True)
        rv, ri = numpy_merge(vals, ids, 6, True)
        np.testing.assert_array_equal(gv, rv)
        np.testing.assert_array_equal(gi, ri)

    def test_k1(self, mesh, rng):
        vals, ids = make_tables(rng, 9, 1, True)
        gv, gi = self._run_kernel(mesh, vals, ids, 1, True)
        rv, ri = numpy_merge(vals, ids, 1, True)
        np.testing.assert_array_equal(gv, rv)
        np.testing.assert_array_equal(gi, ri)

    def test_kernel_guards(self):
        with pytest.raises(ValueError, match="extraction rounds"):
            pk.ring_topk_merge(jnp.zeros((8, 128)),
                               jnp.zeros((8, 128), jnp.int32),
                               pk.RING_TOPK_MAX_K + 1, "shard", 8)
        assert not pk.ring_topk_kernel_ok(64, pk.RING_TOPK_MAX_K + 1, 8)
        assert not pk.ring_topk_kernel_ok(64, 8, 1)
        assert pk.ring_topk_kernel_ok(64, 8, 8)


class TestRingOverlapSchedule:
    """ISSUE 11 tentpole: the compute/comms-overlapped (half-pipelined)
    hop schedule is exact-parity with the PR-8 serialized schedule —
    kernel-vs-numpy across both schedules at shapes where the overlap
    actually splits (mc ≥ 16), plus the split/env plumbing."""

    def _run_kernel(self, mesh, vals, ids, k, select_min, schedule):
        m = vals.shape[1]

        def body(v, i):
            return pk.ring_topk_merge(v[0], i[0], k, "shard", N_DEV,
                                      select_min, interpret=True,
                                      schedule=schedule)

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P("shard", None, None), P("shard", None, None)),
            out_specs=(P("shard", None), P("shard", None)),
            check_vma=False)
        gv, gi = fn(jnp.asarray(vals), jnp.asarray(ids))
        return np.asarray(gv)[:m], np.asarray(gi)[:m]

    # the serial leg re-proves the PR-8 schedule (already covered by
    # TestRingKernelParity) — slow lane; the overlap leg stays tier-1
    @pytest.mark.parametrize("schedule", [
        pytest.param("serial", marks=pytest.mark.slow), "overlap"])
    def test_two_half_parity_min_select(self, mesh, rng, schedule):
        # m=200 → mc=32 → the overlap schedule really splits (16+16)
        vals, ids = make_tables(rng, 200, 10, True, dup_ids=True)
        gv, gi = self._run_kernel(mesh, vals, ids, 10, True, schedule)
        rv, ri = numpy_merge(vals, ids, 10, True)
        np.testing.assert_array_equal(gv, rv)
        np.testing.assert_array_equal(gi, ri)

    @pytest.mark.parametrize("schedule", ["serial", "overlap"])
    @pytest.mark.slow  # heavy interpret-mode kernel traces; CI lanes run it
    def test_uneven_halves_max_select(self, mesh, rng, schedule):
        # m=129 → mc=24 → uneven (8, 16) halves; −inf sentinels ride
        vals, ids = make_tables(rng, 129, 6, False, sentinels=True)
        gv, gi = self._run_kernel(mesh, vals, ids, 6, False, schedule)
        rv, ri = numpy_merge(vals, ids, 6, False)
        np.testing.assert_array_equal(gv, rv)
        np.testing.assert_array_equal(gi, ri)

    @pytest.mark.slow  # k=64 extraction rounds x 7 hops x 2 schedules
    def test_overlap_matches_serial(self, mesh, rng):
        vals, ids = make_tables(rng, 256, pk.RING_TOPK_MAX_K, True,
                                sentinels=True)
        so = self._run_kernel(mesh, vals, ids, pk.RING_TOPK_MAX_K, True,
                              "overlap")
        ss = self._run_kernel(mesh, vals, ids, pk.RING_TOPK_MAX_K, True,
                              "serial")
        np.testing.assert_array_equal(so[0], ss[0])
        np.testing.assert_array_equal(so[1], ss[1])

    def test_splits(self):
        # serial: one block; overlap: two sublane-aligned halves that
        # tile the chunk exactly (the byte model is rows-preserving)
        assert pk.ring_topk_splits(32, "serial") == ((0, 32),)
        assert pk.ring_topk_splits(32, "overlap") == ((0, 16), (16, 16))
        assert pk.ring_topk_splits(24, "overlap") == ((0, 8), (8, 16))
        # chunks too short to split degenerate to one block
        assert pk.ring_topk_splits(8, "overlap") == ((0, 8),)
        for mc in (8, 16, 24, 32, 104):
            for sched in ("serial", "overlap"):
                splits = pk.ring_topk_splits(mc, sched)
                assert sum(r for _, r in splits) == mc
                assert all(r % 8 == 0 and o % 8 == 0 for o, r in splits)

    def test_schedule_env(self, monkeypatch):
        assert pk.ring_schedule("serial") == "serial"
        assert pk.ring_schedule("overlap") == "overlap"
        monkeypatch.setenv("RAFT_TPU_RING_OVERLAP", "off")
        assert pk.ring_schedule("auto") == "serial"
        monkeypatch.setenv("RAFT_TPU_RING_OVERLAP", "on")
        assert pk.ring_schedule("auto") == "overlap"
        monkeypatch.delenv("RAFT_TPU_RING_OVERLAP")
        assert pk.ring_schedule("auto") == "overlap"  # the default

    def test_overlap_schedule_uniform_and_counted(self, mesh, rng,
                                                  monkeypatch):
        # the overlapped kernel under the collective-schedule checker +
        # facade hop accounting: byte model identical to serial
        monkeypatch.setenv("RAFT_TPU_RING_OVERLAP", "on")
        x = jnp.asarray(rng.random((2048, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((256, 16), dtype=np.float32))
        with sanitize.record_comms_schedule() as rec:
            sanitize.assert_uniform_collective_schedule(
                lambda: sharded_knn(x, q, 4, mesh, merge="ring"))
        hops = [e for e in rec if e[0] == "ring_topk"]
        assert len(hops) == N_DEV - 1, rec
        mc = pk.ring_chunk_rows(256, N_DEV)
        assert all(b == mc * 4 * 8 for _, _, b in hops), rec


class TestRingFallbackParity:
    """The ppermute fallback inside real sharded searches: identical
    results to the allgather tier (same candidates, same selection)."""

    @pytest.mark.slow  # impl-twin parity; the CI ring smoke + pytest lane re-assert it (tier-1 budget)
    def test_sharded_knn_ring_matches_allgather(self, mesh, rng):
        x = jnp.asarray(rng.random((803, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((27, 16), dtype=np.float32))
        va, ia = sharded_knn(x, q, 10, mesh, merge="allgather")
        vr, ir = sharded_knn(x, q, 10, mesh, merge="ring")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ir))
        np.testing.assert_allclose(np.asarray(va), np.asarray(vr))

    @pytest.mark.slow  # one more full sharded trace; CI lanes run it
    def test_sharded_knn_ring_inner_product(self, mesh, rng):
        # max-select end to end (negated keys through the ring)
        x = jnp.asarray(rng.random((256, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((16, 16), dtype=np.float32))
        va, ia = sharded_knn(x, q, 5, mesh, metric="inner_product",
                             merge="allgather")
        vr, ir = sharded_knn(x, q, 5, mesh, metric="inner_product",
                             merge="ring")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ir))
        np.testing.assert_allclose(np.asarray(va), np.asarray(vr))

    @pytest.mark.slow  # two full impl traces; CI lanes run it
    def test_kernel_impl_matches_fallback(self, mesh, rng):
        # the merge_topk dispatch's two ring impls agree hop for hop
        m, k = 40, 8
        vals, ids = make_tables(rng, m, k, True)

        def run(impl):
            def body(v, i):
                return merge_topk(v[0], i[0], "shard", m, k, N_DEV, True,
                                  tier="ring", impl=impl, interpret=True)

            fn = shard_map(
                body, mesh=mesh,
                in_specs=(P("shard", None, None), P("shard", None, None)),
                out_specs=(P("shard", None), P("shard", None)),
                check_vma=False)
            gv, gi = fn(jnp.asarray(vals), jnp.asarray(ids))
            return np.asarray(gv)[:m], np.asarray(gi)[:m]

        kv, ki = run("ring_kernel")
        fv, fi = run("ring_ppermute")
        np.testing.assert_array_equal(ki, fi)
        np.testing.assert_allclose(kv, fv)

    def test_int64_ids_decline_the_kernel(self, mesh, rng):
        """The id-width admission (PR-10 capacity pass): the ring
        KERNEL is int32-only by construction, so an int64 billion-scale
        id table must reroute to the identical-schedule ppermute
        fallback (fallback{reason=id_width}) instead of silently
        truncating. Trace-only under scoped x64 — the branch is a
        trace-time dtype check."""
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        m, k = 40, 8
        vals, ids = make_tables(rng, m, k, True)

        def body(v, i):
            return merge_topk(v[0], i[0].astype(jnp.int64), "shard", m,
                              k, N_DEV, True, tier="ring",
                              impl="ring_kernel", interpret=True)

        fn = shard_map(
            body, mesh=mesh,
            in_specs=(P("shard", None, None), P("shard", None, None)),
            out_specs=(P("shard", None), P("shard", None)),
            check_vma=False)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            with sanitize.scoped_x64(True):
                closed = jax.make_jaxpr(fn)(jnp.asarray(vals),
                                            jnp.asarray(ids))
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert c.get("parallel.merge.fallback{reason=id_width}") == 1.0
        # merged ids keep their 64-bit width end to end
        assert "int64" in str(closed.jaxpr.outvars[1].aval)


class TestMergeTierDispatch:
    def test_env_tristate(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_RING_TOPK", "off")
        assert merge_tier(8, 64, 10)[0] == "allgather"
        monkeypatch.setenv("RAFT_TPU_RING_TOPK", "on")
        tier, impl = merge_tier(8, 64, 10)
        assert tier == "ring"
        assert impl == "ring_ppermute"  # CPU: the kernel needs a TPU
        monkeypatch.setenv("RAFT_TPU_RING_TOPK", "auto")
        assert merge_tier(8, 64, 10)[0] == "allgather"  # auto off-TPU

    def test_explicit_overrides_env(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_RING_TOPK", "off")
        assert merge_tier(8, 64, 10, explicit="ring")[0] == "ring"
        with pytest.raises(Exception, match="merge tier"):
            merge_tier(8, 64, 10, explicit="bogus")

    def test_dispatch_counter(self, monkeypatch):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            merge_tier(8, 64, 10, explicit="ring")
            merge_tier(8, 64, 10, explicit="allgather")
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert c["parallel.merge.dispatch{impl=ring_ppermute}"] == 1.0
        assert c["parallel.merge.dispatch{impl=allgather}"] == 1.0

    def test_ring_auto_shape_gate(self):
        # tiny batches: mc pads to 8 rows, the ring would ship MORE
        # bytes over n_dev−1 serial hops — auto must keep allgather
        assert not ring_auto_wanted(4, 10, 8)
        assert not ring_auto_wanted(8, 10, 8)
        # bandwidth-bound batches: the ring's counted bytes are ≤ half
        # the allgather's (the scaling CI's ≥2× bar)
        assert ring_auto_wanted(256, 10, 8)
        assert ring_auto_wanted(64, 10, 2)

    @pytest.mark.slow  # full sharded trace for a validation path; CI lanes run it (tier-1 budget)
    def test_sharded_search_validates_queries(self, mesh, rng):
        # the sharded entry keeps the single-chip contract: bad query
        # dims fail the clear expects, not a shape error in shard_map
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.parallel import build_ivf_flat, search_ivf_flat

        x = jnp.asarray(rng.random((512, 16), dtype=np.float32))
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=2)
        sharded = build_ivf_flat(params, x, mesh)
        with pytest.raises(Exception, match=r"queries must be \[m, 16\]"):
            search_ivf_flat(ivf_flat.SearchParams(n_probes=4), sharded,
                            jnp.zeros((4, 7)), 3, mesh)

    def test_out_spec_and_rows(self):
        assert merge_out_spec("allgather", "shard") == P()
        assert merge_out_spec("ring", "shard") == P("shard", None)
        assert merged_rows("allgather", 27, 8) == 27
        assert merged_rows("ring", 27, 8) == pk.ring_chunk_rows(27, 8) * 8


class TestRingBytes:
    """Exact per-hop accounting: n_dev−1 ops, one surviving-block
    payload per hop, for BOTH ring impls — and the allgather tier's
    materialized-table model beside them."""

    @pytest.fixture()
    def reg(self):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        yield reg
        obs.disable()

    @pytest.mark.slow  # exact hop-byte model; CI lanes + the dryrun byte assertions cover it (tier-1 budget)
    def test_ring_hop_bytes_exact(self, mesh, reg, rng):
        m, k = 27, 10
        x = jnp.asarray(rng.random((803, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((m, 16), dtype=np.float32))
        sharded_knn(x, q, k, mesh, merge="ring")
        c = reg.snapshot()["counters"]
        mc = pk.ring_chunk_rows(m, N_DEV)
        hop = mc * k * (4 + 4)  # f32 vals + i32 ids per surviving block
        assert c["comms.ops{axis=shard,op=ring_topk}"] == N_DEV - 1, c
        assert c["comms.bytes{axis=shard,op=ring_topk}"] == \
            (N_DEV - 1) * hop, c
        assert "comms.ops{axis=shard,op=allgather}" not in c, c

    def test_allgather_merge_bytes_exact(self, mesh, reg, rng):
        m, k = 27, 10
        x = jnp.asarray(rng.random((803, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((m, 16), dtype=np.float32))
        sharded_knn(x, q, k, mesh, merge="allgather")
        c = reg.snapshot()["counters"]
        # two gathers (vals + ids), each materializing size × [m, k]
        assert c["comms.bytes{axis=shard,op=allgather}"] == \
            N_DEV * m * k * 4 * 2, c

    @pytest.mark.slow  # ratio re-proved by the dryrun byte model + exact-byte twins above; CI lanes run it (tier-1 budget)
    def test_ring_beats_allgather_2x(self, mesh, reg, rng):
        # the ISSUE 8 acceptance ratio at n_dev=8, in the counters
        m, k = 256, 10
        x = jnp.asarray(rng.random((2048, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((m, 16), dtype=np.float32))
        sharded_knn(x, q, k, mesh, merge="allgather")
        sharded_knn(x, q, k, mesh, merge="ring")
        c = reg.snapshot()["counters"]
        ag = c["comms.bytes{axis=shard,op=allgather}"]
        ring = c["comms.bytes{axis=shard,op=ring_topk}"]
        assert 2 * ring <= ag, (ring, ag)

    @pytest.mark.slow  # two full impl traces; CI lanes run it
    def test_kernel_impl_counts_like_fallback(self, mesh, reg, rng):
        # count_ring_topk (kernel path) == per-hop ring_topk_hop counts
        m, k = 40, 8
        vals, ids = make_tables(rng, m, k, True)

        def run(impl):
            def body(v, i):
                return merge_topk(v[0], i[0], "shard", m, k, N_DEV, True,
                                  tier="ring", impl=impl, interpret=True)

            fn = shard_map(
                body, mesh=mesh,
                in_specs=(P("shard", None, None), P("shard", None, None)),
                out_specs=(P("shard", None), P("shard", None)),
                check_vma=False)
            jax.block_until_ready(fn(jnp.asarray(vals), jnp.asarray(ids)))

        run("ring_kernel")
        kc = dict(reg.snapshot()["counters"])
        reg.reset()
        run("ring_ppermute")
        fc = reg.snapshot()["counters"]
        for key in ("comms.ops{axis=shard,op=ring_topk}",
                    "comms.bytes{axis=shard,op=ring_topk}"):
            assert kc[key] == fc[key], (key, kc, fc)


class TestRingSchedule:
    """The ring merge under the collective-schedule checker: one
    device-uniform schedule, with the facade recorder attributing
    exactly n_dev−1 ring_topk hops."""

    def test_ring_knn_schedule_uniform(self, mesh, rng):
        x = jnp.asarray(rng.random((256, 16), dtype=np.float32))
        q = jnp.asarray(rng.random((16, 16), dtype=np.float32))
        with sanitize.record_comms_schedule() as rec:
            sched = sanitize.assert_uniform_collective_schedule(
                lambda: sharded_knn(x, q, 4, mesh, merge="ring"))
        hops = [e for e in rec if e[0] == "ring_topk"]
        assert len(hops) == N_DEV - 1, rec
        mc = pk.ring_chunk_rows(16, N_DEV)
        assert all(a == "shard" and b == mc * 4 * 8
                   for _, a, b in hops), rec
        verbs = [e[0] for e in _flat(sched)]
        # vals + ids move per hop: 2(n_dev−1) ppermutes, no all_gather
        assert verbs.count("ppermute") == 2 * (N_DEV - 1), verbs
        assert verbs.count("all_gather") == 0, verbs


def _flat(sched):
    for e in sched:
        if len(e) == 2:  # ("while"|"scan", inner)
            yield from _flat(e[1])
        else:
            yield e


@pytest.fixture(scope="module")
def pq_sharded(mesh):
    """A small sharded IVF-PQ index + its build data (module-scoped:
    the distributed build is the expensive part)."""
    from raft_tpu.neighbors import ivf_pq as _pq
    from raft_tpu.parallel import build_ivf_pq

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.random((1024, 32), dtype=np.float32))
    params = _pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=4,
                             kmeans_n_iters=3)
    return build_ivf_pq(params, x, mesh), x


class TestRingFusedScan:
    """ISSUE 11 tentpole, second half: the fused scan-in-ring tier —
    per-shard LUT scan folded into the ring exchange, exact parity with
    the unfused sharded search, unchanged byte model, every decline
    rung preserved."""

    def _search(self, idx, q, k, mesh, merge="ring", n_probes=4,
                lut_dtype="float32", scan_select="pallas",
                filter_bitset=None):
        from raft_tpu.neighbors import ivf_pq as _pq
        from raft_tpu.parallel import search_ivf_pq

        # scan_select="pallas": the fused tier carries the LUT-bin
        # tier's selection semantics, so it only serves searches the
        # single-chip dispatch would route there (default "exact"
        # declines with reason=scan_select)
        sp = _pq.SearchParams(n_probes=n_probes, lut_dtype=lut_dtype,
                              scan_select=scan_select)
        return search_ivf_pq(sp, idx, q, k, mesh, merge=merge,
                             filter_bitset=filter_bitset)

    @pytest.mark.slow  # parity twin re-asserted by the dryrun fused-identity leg; CI runs it (tier-1 budget)
    def test_fused_matches_unfused(self, mesh, rng, pq_sharded,
                                   monkeypatch):
        idx, _ = pq_sharded
        q = jnp.asarray(rng.random((77, 32), dtype=np.float32))  # ragged
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "off")
        va, ia = self._search(idx, q, 8, mesh, merge="allgather")
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        vf, iff = self._search(idx, q, 8, mesh, merge="ring")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(iff))
        np.testing.assert_allclose(np.asarray(va), np.asarray(vf),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow  # two sharded searches + fused trace; CI runs it
    def test_fused_filtered_matches_unfused(self, mesh, rng, pq_sharded,
                                            monkeypatch):
        """ISSUE 12: filtered pod-scale search rides the ring kernel —
        the per-shard bitset slice streams beside the codes, results
        identical to the unfused filtered allgather path, no filtered
        id ever crossing the ring, and the fused dispatch counted with
        filtered=1 while the retired filter_bitset reason stays zero."""
        from raft_tpu import obs
        from raft_tpu.core import bitset
        from raft_tpu.obs.metrics import MetricsRegistry

        idx, x = pq_sharded
        n = x.shape[0]
        keep = np.asarray(rng.random(n) < 0.4)
        bits = bitset.from_mask(jnp.asarray(keep))
        q = jnp.asarray(rng.random((64, 32), dtype=np.float32))
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "off")
        va, ia = self._search(idx, q, 8, mesh, merge="allgather",
                              filter_bitset=bits)
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            vf, iff = self._search(idx, q, 8, mesh, merge="ring",
                                   filter_bitset=bits)
            jax.block_until_ready((vf, iff))
        finally:
            obs.disable()
        ia, iff = np.asarray(ia), np.asarray(iff)
        assert keep[ia[ia >= 0]].all() and keep[iff[iff >= 0]].all()
        np.testing.assert_array_equal(ia, iff)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vf),
                                   rtol=1e-4, atol=1e-4)
        c = reg.snapshot()["counters"]
        assert c.get(
            "ivf_pq.scan.dispatch{filtered=1,impl=ring_lut_fused}",
            0) == 1.0, c
        assert c.get("ivf_pq.scan.fallback{reason=filter_bitset}",
                     0) == 0, c

    @pytest.mark.slow  # sole tier-1 user of the pq_sharded build; the fused CI legs exercise admission (tier-1 budget)
    def test_fused_filtered_admission(self, pq_sharded, monkeypatch):
        """_ring_fused_wanted(filtered=True) admits the workhorse shape
        (the filter slots fit the VMEM model and the byte rows pass
        filtered_scan_mem_ok) — filtered searches stay on the tier."""
        from raft_tpu.distance.types import DistanceType
        from raft_tpu.parallel.ivf import _ring_fused_wanted

        idx, _ = pq_sharded
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        args = dict(m=64, k=8, n_probes=4, n_dev=N_DEV, whole_mesh=True,
                    merge="ring", mt=DistanceType.L2Expanded,
                    lut_dtype="float32", scan_select="pallas")
        take, reason = _ring_fused_wanted(idx, filtered=True, **args)
        assert (take, reason) == (True, "")

    @pytest.mark.slow  # own sharded build + fused kernel trace
    def test_fused_inner_product(self, mesh, rng, monkeypatch):
        from raft_tpu.neighbors import ivf_pq as _pq
        from raft_tpu.parallel import build_ivf_pq

        x = jnp.asarray(rng.random((768, 32), dtype=np.float32))
        q = jnp.asarray(rng.random((40, 32), dtype=np.float32))
        idx = build_ivf_pq(
            _pq.IndexParams(n_lists=8, pq_dim=8, pq_bits=4,
                            kmeans_n_iters=2, metric="inner_product"),
            x, mesh)
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "off")
        va, ia = self._search(idx, q, 5, mesh, merge="allgather")
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        vf, iff = self._search(idx, q, 5, mesh, merge="ring")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(iff))
        np.testing.assert_allclose(np.asarray(va), np.asarray(vf),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow  # two more full sharded traces; CI lanes run it
    def test_fused_dispatch_counters_and_bytes(self, mesh, rng,
                                               pq_sharded, monkeypatch):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        idx, _ = pq_sharded
        q = jnp.asarray(rng.random((64, 32), dtype=np.float32))

        def run(fused):
            monkeypatch.setenv("RAFT_TPU_RING_FUSED", fused)
            reg = MetricsRegistry()
            obs.enable(registry=reg, hbm=False)
            try:
                jax.block_until_ready(
                    self._search(idx, q, 8, mesh, merge="ring"))
            finally:
                obs.disable()
            return reg.snapshot()["counters"]

        cf = run("on")
        assert cf["parallel.merge.dispatch{impl=ring_fused_scan}"] == 1.0
        assert cf["ivf_pq.scan.dispatch{impl=ring_lut_fused}"] == 1.0
        cu = run("off")
        # the fusion moves compute, not bytes: identical ring hop model
        key_ops = "comms.ops{axis=shard,op=ring_topk}"
        key_b = "comms.bytes{axis=shard,op=ring_topk}"
        assert cf[key_ops] == cu[key_ops] == N_DEV - 1
        assert cf[key_b] == cu[key_b] > 0

    @pytest.mark.slow  # one more full fused-kernel trace
    def test_fused_schedule_uniform(self, mesh, rng, pq_sharded,
                                    monkeypatch):
        idx, _ = pq_sharded
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        q = jnp.asarray(rng.random((32, 32), dtype=np.float32))
        with sanitize.record_comms_schedule() as rec:
            sanitize.assert_uniform_collective_schedule(
                lambda: self._search(idx, q, 4, mesh, merge="ring"))
        hops = [e for e in rec if e[0] == "ring_topk"]
        assert len(hops) == N_DEV - 1, rec

    @pytest.mark.slow  # x64 retrace of the whole sharded search
    def test_int64_ids_decline_fused(self, mesh, rng, pq_sharded,
                                     monkeypatch):
        """The id-width admission is preserved through the fused tier:
        an int64 id table declines the fused kernel (int32-only) AND
        the plain ring kernel, landing on the identical-schedule
        ppermute fallback — counted, never truncated."""
        from raft_tpu import obs
        from raft_tpu.obs import sanitize as _san
        from raft_tpu.obs.metrics import MetricsRegistry

        idx, _ = pq_sharded
        q = jnp.asarray(rng.random((64, 32), dtype=np.float32))
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            # trace-only under scoped x64, like the plain-ring id-width
            # test: the declines are trace-time dtype checks
            with _san.scoped_x64(True):
                idx64 = idx.replace(
                    packed_ids=idx.packed_ids.astype(jnp.int64))
                closed = jax.make_jaxpr(
                    lambda qq: self._search(idx64, qq, 8, mesh,
                                            merge="ring"))(q)
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert c.get("parallel.merge.fallback{reason=id_width}", 0) >= 1.0
        assert "ivf_pq.scan.dispatch{impl=ring_lut_fused}" not in c
        # merged ids keep their 64-bit width end to end
        assert "int64" in str(closed.jaxpr.outvars[1].aval)

    @pytest.mark.slow  # own sharded build
    def test_cosine_declines_fused(self, mesh, rng, monkeypatch):
        from raft_tpu import obs
        from raft_tpu.neighbors import ivf_pq as _pq
        from raft_tpu.obs.metrics import MetricsRegistry
        from raft_tpu.parallel import build_ivf_pq

        x = jnp.asarray(rng.random((512, 32), dtype=np.float32))
        q = jnp.asarray(rng.random((40, 32), dtype=np.float32))
        idx = build_ivf_pq(
            _pq.IndexParams(n_lists=8, pq_dim=8, pq_bits=4,
                            kmeans_n_iters=2, metric="cosine"),
            x, mesh)
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            jax.block_until_ready(
                self._search(idx, q, 5, mesh, merge="ring"))
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert c.get("parallel.merge.fallback{reason=metric}", 0) == 1.0
        assert "ivf_pq.scan.dispatch{impl=ring_lut_fused}" not in c

    def test_exact_scan_select_declines(self, pq_sharded, monkeypatch):
        """The default scan_select="exact" must never be silently
        swapped for the bin tier's recall-targeted selection — even
        under env force the fused tier declines (reason=scan_select)
        unless the single-chip dispatch would have picked the LUT
        tier."""
        from raft_tpu.distance.types import DistanceType
        from raft_tpu.parallel.ivf import _ring_fused_wanted

        idx, _ = pq_sharded
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "on")
        args = dict(m=64, k=8, n_probes=4, n_dev=N_DEV, whole_mesh=True,
                    merge="ring", mt=DistanceType.L2Expanded,
                    lut_dtype="float32")
        take, reason = _ring_fused_wanted(idx, scan_select="exact",
                                          **args)
        assert (take, reason) == (False, "scan_select")
        take, reason = _ring_fused_wanted(idx, scan_select="pallas",
                                          **args)
        assert (take, reason) == (True, "")
        # "approx" only at the oversampled auto-upgrade shape
        take, reason = _ring_fused_wanted(idx, scan_select="approx",
                                          **args)
        assert (take, reason) == (False, "scan_select")

    @pytest.mark.slow  # one more sharded trace; CI lanes run it
    def test_env_off_keeps_plain_path(self, mesh, rng, pq_sharded,
                                      monkeypatch):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        idx, _ = pq_sharded
        q = jnp.asarray(rng.random((64, 32), dtype=np.float32))
        monkeypatch.setenv("RAFT_TPU_RING_FUSED", "off")
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            jax.block_until_ready(
                self._search(idx, q, 8, mesh, merge="ring"))
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert "parallel.merge.dispatch{impl=ring_fused_scan}" not in c
        assert c["parallel.merge.dispatch{impl=ring_ppermute}"] == 1.0
