"""Sparse formats / ops / linalg vs scipy references.

Mirrors the reference's test strategy (SURVEY.md §4): device results
compared against host reference implementations (cpp/test/sparse/*).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from raft_tpu import sparse
from raft_tpu.sparse import linalg as slinalg
from raft_tpu.sparse import ops as sops


def _random_csr(rng, n, m, density=0.1):
    mat = sp.random(n, m, density=density, random_state=np.random.RandomState(7), format="csr", dtype=np.float32)
    return sparse.from_scipy(mat), mat


def test_dense_roundtrip(rng):
    a = rng.random((13, 9), dtype=np.float32)
    a[a < 0.6] = 0.0
    csr = sparse.csr_from_dense(a)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(csr)), a, rtol=1e-6)
    coo = sparse.coo_from_dense(a)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(coo)), a, rtol=1e-6)


def test_coo_csr_roundtrip(rng):
    csr, ref = _random_csr(rng, 20, 15)
    coo = sparse.csr_to_coo(csr)
    back = sparse.coo_to_csr(coo)
    np.testing.assert_allclose(sparse.to_scipy(back).toarray(), ref.toarray(), rtol=1e-6)


def test_row_ids_jittable(rng):
    csr, ref = _random_csr(rng, 10, 10)
    rids = jax.jit(lambda c: c.row_ids)(csr)
    expected = ref.tocoo().row
    np.testing.assert_array_equal(np.asarray(rids), expected)


def test_sum_duplicates():
    coo = sparse.make_coo([0, 0, 1, 2, 2], [1, 1, 0, 2, 2], [1.0, 2.0, 3.0, 4.0, 5.0], (3, 3))
    out = sops.sum_duplicates(coo)
    assert out.nnz == 3
    dense = np.asarray(sparse.to_dense(out))
    np.testing.assert_allclose(dense[0, 1], 3.0)
    np.testing.assert_allclose(dense[2, 2], 9.0)


def test_remove_zeros():
    coo = sparse.make_coo([0, 1, 2], [0, 1, 2], [0.0, 2.0, 0.0], (3, 3))
    out = sops.remove_zeros(coo)
    assert out.nnz == 1
    assert float(out.data[0]) == 2.0


def test_slice_rows(rng):
    csr, ref = _random_csr(rng, 30, 12)
    sl = sops.slice_rows(csr, 5, 17)
    np.testing.assert_allclose(sparse.to_scipy(sl).toarray(), ref[5:17].toarray(), rtol=1e-6)


def test_degree(rng):
    csr, ref = _random_csr(rng, 25, 25)
    np.testing.assert_array_equal(np.asarray(sops.degree(csr)), np.diff(ref.indptr))


def test_symmetrize_max():
    coo = sparse.make_coo([0, 1], [1, 2], [3.0, 1.0], (3, 3))
    out = sops.symmetrize(coo, mode="max")
    dense = np.asarray(sparse.to_dense(out))
    assert dense[0, 1] == dense[1, 0] == 3.0
    assert dense[1, 2] == dense[2, 1] == 1.0


@pytest.mark.parametrize("norm", ["l1", "l2", "linf"])
def test_row_norm(rng, norm):
    csr, ref = _random_csr(rng, 18, 11)
    got = np.asarray(slinalg.row_norm(csr, norm))
    dense = ref.toarray()
    if norm == "l1":
        want = np.abs(dense).sum(axis=1)
    elif norm == "l2":
        want = (dense**2).sum(axis=1)
    else:
        want = np.abs(dense).max(axis=1, initial=0)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_spmv_spmm(rng):
    csr, ref = _random_csr(rng, 22, 17)
    x = rng.random(17, dtype=np.float32)
    b = rng.random((17, 5), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(slinalg.spmv(csr, jnp.asarray(x))), ref @ x, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(jax.jit(slinalg.spmm)(csr, jnp.asarray(b))), ref @ b, rtol=1e-4)


def test_transpose(rng):
    csr, ref = _random_csr(rng, 9, 14)
    np.testing.assert_allclose(sparse.to_scipy(slinalg.transpose(csr)).toarray(), ref.T.toarray(), rtol=1e-6)


def test_add(rng):
    a, ra = _random_csr(rng, 12, 12, 0.15)
    b_sp = sp.random(12, 12, density=0.15, random_state=np.random.RandomState(11), format="csr", dtype=np.float32)
    b = sparse.from_scipy(b_sp)
    np.testing.assert_allclose(
        sparse.to_scipy(slinalg.add(a, b)).toarray(), (ra + b_sp).toarray(), rtol=1e-5
    )


def test_row_normalize(rng):
    csr, ref = _random_csr(rng, 10, 10)
    out = slinalg.row_normalize(csr, "l1")
    sums = np.abs(sparse.to_scipy(out).toarray()).sum(axis=1)
    nz = np.diff(ref.indptr) > 0
    np.testing.assert_allclose(sums[nz], 1.0, rtol=1e-5)


def test_laplacian_normalized(rng):
    adj_coo = sparse.csr_to_coo(_random_csr(rng, 15, 15, 0.2)[0])
    sym = sops.symmetrize(adj_coo, mode="max")
    lap = slinalg.laplacian(sym, normalized=True)
    dense = np.asarray(sparse.to_dense(lap), dtype=np.float64)
    np.testing.assert_allclose(dense, dense.T, atol=1e-6)
    evals = np.linalg.eigvalsh(dense)
    assert evals.min() > -1e-5  # PSD
    assert abs(evals.min()) < 1e-4  # 0 eigenvalue exists


def test_row_op(rng):
    csr, ref = _random_csr(rng, 8, 8)
    out = sops.row_op(csr, lambda rid, vals: vals * (rid + 1).astype(vals.dtype))
    want = ref.toarray() * (np.arange(8) + 1)[:, None]
    np.testing.assert_allclose(np.asarray(sparse.to_dense(out)), want, rtol=1e-5)
