"""Sanitizer-mode coverage: bitset + sample_filter under the runtime
guards (ISSUE 3 satellite — the transfer guard exposed host round-trips
in the ``set_bits`` paths, fixed by jitting the packing ops), plus the
jit-cache-miss budget contract on a search hot path.

Every test here passes in the normal tier-1 lane too — the guards are
scoped explicitly via :mod:`raft_tpu.obs.sanitize`; only the
``recompile_budget`` markers need the ``RAFT_TPU_SANITIZE=1`` lane (the
conftest fixture enforces them there and ignores them elsewhere).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import bitset
from raft_tpu.neighbors import brute_force, sample_filter
from raft_tpu.obs import sanitize


def _rank_promotion_raise():
    """Context: jax_numpy_rank_promotion='raise' (restores prior value)."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        prev = jax.config.jax_numpy_rank_promotion
        jax.config.update("jax_numpy_rank_promotion", "raise")
        try:
            yield
        finally:
            jax.config.update("jax_numpy_rank_promotion", prev)

    return ctx()


class TestBitsetSanitized:
    def test_roundtrip_under_guard_and_rank_raise(self, rng):
        mask_h = rng.random(301) < 0.5
        mask = jnp.asarray(mask_h)
        with _rank_promotion_raise():
            bits = bitset.from_mask(mask)
            with sanitize.no_host_transfers():
                back = bitset.to_mask(bits, 301)
                cnt = bitset.count(bits, 301)
                flipped = bitset.flip(bits)
                jax.block_until_ready((back, cnt, flipped))
        np.testing.assert_array_equal(np.asarray(back), mask_h)
        assert int(cnt) == int(mask_h.sum())
        np.testing.assert_array_equal(
            np.asarray(bitset.to_mask(flipped, 301)), ~mask_h)

    def test_set_bits_word_collisions_under_guard(self):
        # several indices landing in the same uint32 word — the
        # segment-reduction path must keep every write
        idx = jnp.asarray([0, 1, 31, 32, 33, 64, 95, 99])
        idx3 = jnp.asarray([0, 1, 31])  # device-resident BEFORE the guard
        bits0 = bitset.create(100, default_value=False)
        with _rank_promotion_raise(), sanitize.no_host_transfers():
            bits = bitset.set_bits(bits0, idx, True)
            cleared = bitset.set_bits(bits, idx3, False)
            jax.block_until_ready((bits, cleared))
        expect = np.zeros(100, bool)
        expect[np.asarray(idx)] = True
        np.testing.assert_array_equal(np.asarray(bitset.to_mask(bits, 100)),
                                      expect)
        expect[np.asarray(idx[:3])] = False
        np.testing.assert_array_equal(
            np.asarray(bitset.to_mask(cleared, 100)), expect)

    def test_test_and_passes_under_guard(self):
        remove = np.asarray([2, 7, 40])
        bits = sample_filter.make_filter(64, remove=remove)
        ids = jnp.asarray([[0, 2, 63], [7, -1, 40]])
        probe = jnp.asarray([2, 3, 40])
        with _rank_promotion_raise(), sanitize.no_host_transfers():
            ok = sample_filter.passes(bits, ids)
            t = bitset.test(bits, probe)
            none_ok = sample_filter.passes(None, ids)
            jax.block_until_ready((ok, t, none_ok))
        np.testing.assert_array_equal(
            np.asarray(ok), [[True, False, True], [False, False, False]])
        np.testing.assert_array_equal(np.asarray(t), [False, True, False])
        # None filter is the allow-all shortcut: pads included (callers
        # mask padding separately — this is the established contract)
        np.testing.assert_array_equal(np.asarray(none_ok),
                                      np.ones((2, 3), bool))

    def test_make_filter_keep_semantics(self):
        keep = np.asarray([1, 5, 9])
        bits = sample_filter.make_filter(32, keep=keep)
        mask = np.asarray(bitset.to_mask(bits, 32))
        expect = np.zeros(32, bool)
        expect[keep] = True
        np.testing.assert_array_equal(mask, expect)
        with pytest.raises(ValueError):
            sample_filter.make_filter(8, remove=[1], keep=[2])


@pytest.fixture(scope="module")
def warm_filtered_knn(request):
    """Build + warm a filtered brute-force search so the steady-state
    test below measures a hot jit cache (module-scope: the warmup
    compiles land OUTSIDE the function-scoped budget fixture)."""
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.random((500, 32), dtype=np.float32))
    q = jnp.asarray(rng.random((16, 32), dtype=np.float32))
    index = brute_force.build(x)
    fbits = sample_filter.make_filter(500, remove=np.arange(0, 500, 7))
    jax.block_until_ready(brute_force.knn(index, q, 10, fbits))
    return index, q, fbits


@pytest.mark.recompile_budget(0)
def test_filtered_knn_steady_state(warm_filtered_knn):
    """The serving contract on a hot path: a warm, same-shape filtered
    search triggers ZERO backend compiles and ZERO implicit host
    transfers. In RAFT_TPU_SANITIZE=1 mode the budget marker turns any
    retrace into a failure."""
    index, q, fbits = warm_filtered_knn
    with sanitize.no_host_transfers():
        d, i = brute_force.knn(index, q, 10, fbits)
        jax.block_until_ready((d, i))
    ids = np.asarray(i)
    # filtered rows (multiples of 7) must never be returned
    assert not np.isin(ids, np.arange(0, 500, 7)).any()
    assert ids.shape == (16, 10)


class TestCollectiveSchedule:
    """The runtime half of the SPMD correctness pass (graftlint
    GL06–GL10): per traced program, every device's collective schedule
    must be identical — a collective gated on ``axis_index`` deadlocks
    (or silently zero-fills) a real mesh while single-device tests stay
    green. Runs on the 8-device CPU mesh."""

    @pytest.fixture(scope="class")
    def mesh(self):
        from raft_tpu.parallel import make_mesh

        return make_mesh(axis_names=("shard",))

    def test_axis_gated_psum_is_caught(self, mesh):
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from raft_tpu.core.compat import shard_map

        def prog(x):
            def local(v):
                rank = lax.axis_index("shard")
                return lax.cond(rank == 0,
                                lambda u: lax.psum(u, "shard"),
                                lambda u: u, v)
            return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P("shard"), check_vma=False)(x)

        with pytest.raises(sanitize.CollectiveScheduleDivergence) as e:
            sanitize.assert_uniform_collective_schedule(
                prog, jnp.ones((8, 4), jnp.float32))
        assert "diverges" in str(e.value)

    def test_uniform_branches_pass(self, mesh):
        # both branches committing to the SAME schedule is safe: every
        # device executes a psum regardless of the predicate
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from raft_tpu.core.compat import shard_map

        def prog(x):
            def local(v):
                rank = lax.axis_index("shard")
                return lax.cond(rank == 0,
                                lambda u: lax.psum(u, "shard"),
                                lambda u: lax.psum(u * 2.0, "shard"), v)
            return shard_map(local, mesh=mesh, in_specs=(P("shard"),),
                             out_specs=P("shard"), check_vma=False)(x)

        sched = sanitize.collective_schedule(
            prog, jnp.ones((8, 4), jnp.float32))
        assert [e[0] for e in sched] == ["psum"]

    def test_comms_schedule_recorder(self, mesh):
        from jax.sharding import PartitionSpec as P
        from raft_tpu.core.compat import shard_map
        from raft_tpu.parallel import Comms

        comms = Comms("shard")

        def body(v):
            return comms.send_recv_ring(comms.allreduce(v))

        fn = shard_map(body, mesh=mesh, in_specs=(P("shard"),),
                       out_specs=P("shard"), check_vma=False)
        with sanitize.record_comms_schedule() as rec:
            jax.block_until_ready(jax.jit(fn)(jnp.ones((8,))))
        assert [(v, a) for v, a, _ in rec] == \
            [("allreduce", "shard"), ("send_recv_ring", "shard")]
        assert all(b > 0 for _, _, b in rec)
        # recording is scoped: outside the context nothing records
        assert not sanitize.comms_schedule_recording()


class TestScopedX64:
    """The capacity prover's x64 scoping (PR-10 satellite): proofs
    trace int64 id paths, but ``jax_enable_x64`` is process-global and
    silently changes every later test's dtypes — the scope must
    save/restore, including on exceptions."""

    def test_scope_enables_and_restores(self):
        assert not jax.config.jax_enable_x64  # conftest pins it off
        with sanitize.scoped_x64(True):
            assert jax.config.jax_enable_x64
            assert jnp.arange(3, dtype=jnp.int64).dtype == jnp.int64
        assert not jax.config.jax_enable_x64
        assert jnp.asarray([1]).dtype == jnp.int32

    def test_scope_restores_on_exception(self):
        with pytest.raises(RuntimeError):
            with sanitize.scoped_x64(True):
                raise RuntimeError("boom")
        assert not jax.config.jax_enable_x64

    def test_prover_never_leaks_x64(self):
        """A full capacity proof (which traces int64 ids under the
        scope) leaves the process exactly as it found it — whether the
        proof passes or raises."""
        import tools.capacity_prove as cp

        cp.prove_ivf_flat()
        assert not jax.config.jax_enable_x64
        with pytest.raises(sanitize.CapacityError):
            sanitize.assert_billion_safe(
                lambda q: jnp.arange(cp.DEFAULT_N, dtype=jnp.int32)[:2] + q,
                jax.ShapeDtypeStruct((2,), jnp.int32), what="seeded")
        assert not jax.config.jax_enable_x64
        assert jnp.asarray([1]).dtype == jnp.int32


def test_recompile_budget_fires():
    """The budget context itself: a fresh shape inside a 0-budget scope
    must raise RecompileBudgetExceeded."""
    sanitize.install_compile_counter()

    @jax.jit
    def f(v):
        return v * 2.0 + 1.0

    with pytest.raises(sanitize.RecompileBudgetExceeded):
        with sanitize.recompile_budget(0, what="fresh shape"):
            jax.block_until_ready(f(jnp.arange(173, dtype=jnp.float32)))
    # warm now → budget 0 holds
    with sanitize.recompile_budget(0, what="warm shape"):
        jax.block_until_ready(f(jnp.arange(173, dtype=jnp.float32)))
