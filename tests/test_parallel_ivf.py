"""Distributed IVF build+search on the virtual 8-device CPU mesh.

Mirrors the reference's raft-dask strategy (SURVEY.md §4): "multi-node" is
emulated as multi-device on one host; quality is asserted as recall vs
exact ground truth, same thresholds as the single-device suites.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.parallel import (
    build_ivf_flat,
    build_ivf_pq,
    make_mesh,
    search_ivf_flat,
    search_ivf_pq,
)


def exact_knn(dataset, queries, k, metric="sqeuclidean"):
    if metric in ("inner_product",):
        d = -queries @ dataset.T
    elif metric == "cosine":
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        dn = dataset / np.linalg.norm(dataset, axis=1, keepdims=True)
        d = 1.0 - qn @ dn.T
    else:
        d = ((queries[:, None, :] - dataset[None, :, :]) ** 2).sum(-1)
    return np.argsort(d, axis=1)[:, :k]


def recall(ids, gt):
    hits = sum(len(np.intersect1d(ids[i], gt[i])) for i in range(len(gt)))
    return hits / gt.size


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    dataset = rng.standard_normal((4096, 32), dtype=np.float32)
    queries = rng.standard_normal((64, 32), dtype=np.float32)
    return dataset, queries


class TestShardedIvfPq:
    def test_recall_matches_single_device(self, mesh, data):
        """Sharded recall ≈ single-device recall on the same data."""
        dataset, queries = data
        k, n_probes = 10, 16
        gt = exact_knn(dataset, queries, k)

        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                    kmeans_n_iters=8, seed=3)
        sp = ivf_pq.SearchParams(n_probes=n_probes)

        single = ivf_pq.build(jnp.asarray(dataset), params)
        _, ids_1 = ivf_pq.search(single, jnp.asarray(queries), k, sp)
        r1 = recall(np.asarray(ids_1), gt)

        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        vals, ids_8 = search_ivf_pq(sp, sharded, jnp.asarray(queries), k,
                                    mesh)
        r8 = recall(np.asarray(ids_8), gt)

        assert r8 >= 0.7, f"sharded recall {r8:.3f} too low"
        assert r8 >= r1 - 0.08, f"sharded {r8:.3f} vs single {r1:.3f}"
        # distances ascend, ids are valid global rows
        v = np.asarray(vals)
        assert (np.diff(v, axis=1) >= -1e-4).all()
        assert (np.asarray(ids_8) >= 0).all()
        assert (np.asarray(ids_8) < len(dataset)).all()

    def test_all_shards_contribute(self, mesh, data):
        """Returned global ids span several shards — the merge really
        mixes per-shard candidates (ids are global at build)."""
        dataset, queries = data
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, kmeans_n_iters=4)
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        _, ids = search_ivf_pq(ivf_pq.SearchParams(n_probes=32), sharded,
                               jnp.asarray(queries), 10, mesh)
        shard_n = -(-len(dataset) // 8)
        shards_hit = np.unique(np.asarray(ids) // shard_n)
        assert len(shards_hit) >= 4

    def test_index_size_counts_all_rows(self, mesh, data):
        dataset, _ = data
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, kmeans_n_iters=4)
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        # capacity overflow may drop a few rows; the bulk must be packed
        assert sharded.size >= int(0.98 * len(dataset))

    def test_inner_product_metric(self, mesh, data):
        dataset, queries = data
        k = 10
        gt = exact_knn(dataset, queries, k, metric="inner_product")
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, kmeans_n_iters=8,
                                    metric="inner_product")
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        _, ids = search_ivf_pq(ivf_pq.SearchParams(n_probes=16), sharded,
                               jnp.asarray(queries), k, mesh)
        assert recall(np.asarray(ids), gt) >= 0.6


class TestShardedIvfFlat:
    def test_recall_matches_single_device(self, mesh, data):
        dataset, queries = data
        k, n_probes = 10, 16
        gt = exact_knn(dataset, queries, k)

        params = ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=8, seed=3)
        sp = ivf_flat.SearchParams(n_probes=n_probes)

        single = ivf_flat.build(jnp.asarray(dataset), params)
        _, ids_1 = ivf_flat.search(single, jnp.asarray(queries), k, sp)
        r1 = recall(np.asarray(ids_1), gt)

        sharded = build_ivf_flat(params, jnp.asarray(dataset), mesh)
        vals, ids_8 = search_ivf_flat(sp, sharded, jnp.asarray(queries), k,
                                      mesh)
        r8 = recall(np.asarray(ids_8), gt)

        assert r8 >= 0.8, f"sharded recall {r8:.3f} too low"
        assert r8 >= r1 - 0.08, f"sharded {r8:.3f} vs single {r1:.3f}"
        assert (np.asarray(ids_8) >= 0).all()

    def test_exact_within_probed_lists(self, mesh, data):
        """With n_probes = n_lists the sharded scan is exhaustive → recall
        1.0 (IVF-Flat stores raw vectors; no quantization error)."""
        dataset, queries = data
        k = 10
        gt = exact_knn(dataset, queries[:16], k)
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4,
                                      list_size_cap_factor=32.0)
        sharded = build_ivf_flat(params, jnp.asarray(dataset), mesh)
        _, ids = search_ivf_flat(ivf_flat.SearchParams(n_probes=16), sharded,
                                 jnp.asarray(queries[:16]), k, mesh)
        assert recall(np.asarray(ids), gt) >= 0.999


class TestCollectiveSchedule:
    """Sharded IVF search programs under the collective-schedule checker
    (raft_tpu.obs.sanitize) — the merge's cross-shard gathers must form
    one device-uniform schedule, with the facade recorder attributing
    the same verbs the comms counters see."""

    def test_sharded_ivf_flat_search_schedule(self, mesh, data):
        from raft_tpu.obs import sanitize

        dataset, queries = data
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=2)
        sharded = build_ivf_flat(params, jnp.asarray(dataset[:512]), mesh)
        sp = ivf_flat.SearchParams(n_probes=8)
        q = jnp.asarray(queries[:8])
        with sanitize.record_comms_schedule() as rec:
            sched = sanitize.assert_uniform_collective_schedule(
                lambda: search_ivf_flat(sp, sharded, q, 5, mesh))
        verbs = [e[0] for e in sched if len(e) == 3]
        assert verbs.count("all_gather") == 2, verbs  # vals + ids merge
        assert [v for v, _, _ in rec] == ["allgather", "allgather"], rec
        assert all(a == "shard" for _, a, _ in rec)
