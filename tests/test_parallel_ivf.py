"""Distributed IVF build+search on the virtual 8-device CPU mesh.

Mirrors the reference's raft-dask strategy (SURVEY.md §4): "multi-node" is
emulated as multi-device on one host; quality is asserted as recall vs
exact ground truth, same thresholds as the single-device suites.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.parallel import (
    build_ivf_flat,
    build_ivf_pq,
    make_mesh,
    search_ivf_flat,
    search_ivf_pq,
)


def exact_knn(dataset, queries, k, metric="sqeuclidean"):
    if metric in ("inner_product",):
        d = -queries @ dataset.T
    elif metric == "cosine":
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        dn = dataset / np.linalg.norm(dataset, axis=1, keepdims=True)
        d = 1.0 - qn @ dn.T
    else:
        d = ((queries[:, None, :] - dataset[None, :, :]) ** 2).sum(-1)
    return np.argsort(d, axis=1)[:, :k]


def recall(ids, gt):
    hits = sum(len(np.intersect1d(ids[i], gt[i])) for i in range(len(gt)))
    return hits / gt.size


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    dataset = rng.standard_normal((4096, 32), dtype=np.float32)
    queries = rng.standard_normal((64, 32), dtype=np.float32)
    return dataset, queries


class TestShardedIvfPq:
    @pytest.mark.slow  # heaviest sharded-pq twin; all_shards_contribute keeps the class tier-1 (tier-1 budget)
    def test_recall_matches_single_device(self, mesh, data):
        """Sharded recall ≈ single-device recall on the same data."""
        dataset, queries = data
        k, n_probes = 10, 16
        gt = exact_knn(dataset, queries, k)

        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                    kmeans_n_iters=8, seed=3)
        sp = ivf_pq.SearchParams(n_probes=n_probes)

        single = ivf_pq.build(jnp.asarray(dataset), params)
        _, ids_1 = ivf_pq.search(single, jnp.asarray(queries), k, sp)
        r1 = recall(np.asarray(ids_1), gt)

        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        vals, ids_8 = search_ivf_pq(sp, sharded, jnp.asarray(queries), k,
                                    mesh)
        r8 = recall(np.asarray(ids_8), gt)

        assert r8 >= 0.7, f"sharded recall {r8:.3f} too low"
        assert r8 >= r1 - 0.08, f"sharded {r8:.3f} vs single {r1:.3f}"
        # distances ascend, ids are valid global rows
        v = np.asarray(vals)
        assert (np.diff(v, axis=1) >= -1e-4).all()
        assert (np.asarray(ids_8) >= 0).all()
        assert (np.asarray(ids_8) < len(dataset)).all()

    def test_all_shards_contribute(self, mesh, data):
        """Returned global ids span several shards — the merge really
        mixes per-shard candidates (ids are global at build)."""
        dataset, queries = data
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, kmeans_n_iters=4)
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        _, ids = search_ivf_pq(ivf_pq.SearchParams(n_probes=32), sharded,
                               jnp.asarray(queries), 10, mesh)
        shard_n = -(-len(dataset) // 8)
        shards_hit = np.unique(np.asarray(ids) // shard_n)
        assert len(shards_hit) >= 4

    @pytest.mark.slow  # heavy sharded-build twin; CI lanes run it (tier-1 budget)
    def test_index_size_counts_all_rows(self, mesh, data):
        dataset, _ = data
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, kmeans_n_iters=4)
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        # capacity overflow may drop a few rows; the bulk must be packed
        assert sharded.size >= int(0.98 * len(dataset))

    @pytest.mark.slow  # heavy sharded-build twin; CI lanes run it (tier-1 budget)
    def test_inner_product_metric(self, mesh, data):
        dataset, queries = data
        k = 10
        gt = exact_knn(dataset, queries, k, metric="inner_product")
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, kmeans_n_iters=8,
                                    metric="inner_product")
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        _, ids = search_ivf_pq(ivf_pq.SearchParams(n_probes=16), sharded,
                               jnp.asarray(queries), k, mesh)
        assert recall(np.asarray(ids), gt) >= 0.6


class TestShardedIvfFlat:
    def test_recall_matches_single_device(self, mesh, data):
        dataset, queries = data
        k, n_probes = 10, 16
        gt = exact_knn(dataset, queries, k)

        params = ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=8, seed=3)
        sp = ivf_flat.SearchParams(n_probes=n_probes)

        single = ivf_flat.build(jnp.asarray(dataset), params)
        _, ids_1 = ivf_flat.search(single, jnp.asarray(queries), k, sp)
        r1 = recall(np.asarray(ids_1), gt)

        sharded = build_ivf_flat(params, jnp.asarray(dataset), mesh)
        vals, ids_8 = search_ivf_flat(sp, sharded, jnp.asarray(queries), k,
                                      mesh)
        r8 = recall(np.asarray(ids_8), gt)

        assert r8 >= 0.8, f"sharded recall {r8:.3f} too low"
        assert r8 >= r1 - 0.08, f"sharded {r8:.3f} vs single {r1:.3f}"
        assert (np.asarray(ids_8) >= 0).all()

    @pytest.mark.slow  # heavy sharded-build twin; CI lanes run it (tier-1 budget)
    def test_exact_within_probed_lists(self, mesh, data):
        """With n_probes = n_lists the sharded scan is exhaustive → recall
        1.0 (IVF-Flat stores raw vectors; no quantization error)."""
        dataset, queries = data
        k = 10
        gt = exact_knn(dataset, queries[:16], k)
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4,
                                      list_size_cap_factor=32.0)
        sharded = build_ivf_flat(params, jnp.asarray(dataset), mesh)
        _, ids = search_ivf_flat(ivf_flat.SearchParams(n_probes=16), sharded,
                                 jnp.asarray(queries[:16]), k, mesh)
        assert recall(np.asarray(ids), gt) >= 0.999


class TestRingMergeTier:
    """ISSUE 8: sharded searches through the ring reduce-scatter-of-
    top-k tier return results identical to the allgather tier on the
    8-device CPU mesh (same per-shard candidates, same selection)."""

    # the two ring-vs-allgather builds below are the module's heaviest
    # programs (~20 s each on the CPU mesh): slow-marked so the tier-1
    # lane (-m 'not slow') keeps its 870 s budget — the CI pytest lane
    # and the RAFT_TPU_SANITIZE=1 lane (no -m filter) still run them
    @pytest.mark.slow
    def test_sharded_ivf_pq_ring_matches_allgather(self, mesh, data):
        dataset, queries = data
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                    kmeans_n_iters=4, seed=3)
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        sp = ivf_pq.SearchParams(n_probes=16)
        va, ia = search_ivf_pq(sp, sharded, jnp.asarray(queries), 10,
                               mesh, merge="allgather")
        vr, ir = search_ivf_pq(sp, sharded, jnp.asarray(queries), 10,
                               mesh, merge="ring")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ir))
        np.testing.assert_allclose(np.asarray(va), np.asarray(vr))

    @pytest.mark.slow
    def test_sharded_ivf_flat_ring_matches_allgather(self, mesh, data):
        dataset, queries = data
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4)
        sharded = build_ivf_flat(params, jnp.asarray(dataset), mesh)
        sp = ivf_flat.SearchParams(n_probes=8)
        # neighbors-level dispatch: the single-chip entry routes a
        # sharded index + mesh to the parallel tier
        va, ia = ivf_flat.search(sharded, jnp.asarray(queries), 10, sp,
                                 mesh=mesh, merge="allgather")
        vr, ir = ivf_flat.search(sharded, jnp.asarray(queries), 10, sp,
                                 mesh=mesh, merge="ring")
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ir))
        np.testing.assert_allclose(np.asarray(va), np.asarray(vr))

    def test_mesh_dispatch_validates(self, mesh, data):
        dataset, queries = data
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                    kmeans_n_iters=2)
        single = ivf_pq.build(jnp.asarray(dataset[:512]), params)
        with pytest.raises(Exception, match="ShardedIvfPq"):
            ivf_pq.search(single, jnp.asarray(queries), 5,
                          ivf_pq.SearchParams(n_probes=4), mesh=mesh)

    @pytest.mark.slow  # two sharded traces; CI lanes run it
    def test_sharded_filtered_ring_matches_allgather(self, mesh, data):
        """ISSUE 12: a filter_bitset rides the sharded tier — each
        shard composes the replicated global bitset with its own
        global-id tables; ring and allgather merges agree exactly and
        no filtered id is ever returned."""
        from raft_tpu.core import bitset

        dataset, queries = data
        rng = np.random.default_rng(13)
        keep = rng.random(len(dataset)) < 0.3
        bits = bitset.from_mask(jnp.asarray(keep))
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                    kmeans_n_iters=4, seed=3)
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        sp = ivf_pq.SearchParams(n_probes=16)
        va, ia = search_ivf_pq(sp, sharded, jnp.asarray(queries), 10,
                               mesh, merge="allgather",
                               filter_bitset=bits)
        vr, ir = search_ivf_pq(sp, sharded, jnp.asarray(queries), 10,
                               mesh, merge="ring", filter_bitset=bits)
        ia, ir = np.asarray(ia), np.asarray(ir)
        assert keep[ia[ia >= 0]].all() and keep[ir[ir >= 0]].all()
        np.testing.assert_array_equal(ia, ir)
        np.testing.assert_allclose(np.asarray(va), np.asarray(vr))

    @pytest.mark.slow  # own sharded flat build; CI lanes run it
    def test_sharded_ivf_flat_filtered(self, mesh, data):
        """The flat sharded tier masks each shard's scan through the
        same global-id composition; the neighbors entry routes the
        filter through the pod dispatch."""
        from raft_tpu.core import bitset

        dataset, queries = data
        rng = np.random.default_rng(17)
        keep = rng.random(len(dataset)) < 0.5
        bits = bitset.from_mask(jnp.asarray(keep))
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=4)
        sharded = build_ivf_flat(params, jnp.asarray(dataset), mesh)
        sp = ivf_flat.SearchParams(n_probes=8)
        _, ia = ivf_flat.search(sharded, jnp.asarray(queries), 10, sp,
                                mesh=mesh, filter_bitset=bits)
        ia = np.asarray(ia)
        assert (ia >= 0).any()
        assert keep[ia[ia >= 0]].all()


class TestShardedFusedPipeline:
    """The end-to-end sharded oversampled pipeline: per-shard scan +
    per-shard exact refine against the shard's own rows, only refined
    survivors entering the merge (BASELINE config 5's shape)."""

    @pytest.mark.slow  # ~24 s: see the tier-1-budget note above
    def test_refined_sharded_search(self, mesh, data):
        dataset, queries = data
        k = 10
        gt = exact_knn(dataset, queries, k)
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                    kmeans_n_iters=8, seed=3)
        sharded = build_ivf_pq(params, jnp.asarray(dataset), mesh)
        plain = ivf_pq.SearchParams(n_probes=16)
        _, ids_plain = search_ivf_pq(plain, sharded, jnp.asarray(queries),
                                     k, mesh)
        sp = ivf_pq.SearchParams(n_probes=16, refine="f32_regen",
                                 refine_ratio=4.0)
        va, ia = ivf_pq.search(sharded, jnp.asarray(queries), k, sp,
                               dataset=jnp.asarray(dataset), mesh=mesh,
                               merge="allgather")
        vr, ir = ivf_pq.search(sharded, jnp.asarray(queries), k, sp,
                               dataset=jnp.asarray(dataset), mesh=mesh,
                               merge="ring")
        # ring tier identical to allgather tier on the refined pipeline
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ir))
        np.testing.assert_allclose(np.asarray(va), np.asarray(vr))
        # exact re-rank must not lose recall vs the unrefined search
        r_plain = recall(np.asarray(ids_plain), gt)
        r_ref = recall(np.asarray(ia), gt)
        assert r_ref >= r_plain - 0.02, (r_ref, r_plain)
        assert r_ref >= 0.8, r_ref
        # refined distances are exact squared L2 of the returned rows
        ia_np, va_np = np.asarray(ia), np.asarray(va)
        row = dataset[ia_np[0, 0]]
        d0 = float(((queries[0] - row) ** 2).sum())
        np.testing.assert_allclose(va_np[0, 0], d0, rtol=1e-4)

    @pytest.mark.slow  # heavy sharded-build twin; CI lanes run it (tier-1 budget)
    def test_refined_needs_dataset(self, mesh, data):
        dataset, _ = data
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                    kmeans_n_iters=2)
        sharded = build_ivf_pq(params, jnp.asarray(dataset[:512]), mesh)
        sp = ivf_pq.SearchParams(n_probes=4, refine="f32_regen")
        with pytest.raises(Exception, match="dataset"):
            search_ivf_pq(sp, sharded, jnp.asarray(dataset[:8]), 5, mesh)


class TestCollectiveSchedule:
    """Sharded IVF search programs under the collective-schedule checker
    (raft_tpu.obs.sanitize) — the merge's cross-shard gathers must form
    one device-uniform schedule, with the facade recorder attributing
    the same verbs the comms counters see."""

    def test_sharded_ivf_flat_search_schedule(self, mesh, data):
        from raft_tpu.obs import sanitize

        dataset, queries = data
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=2)
        sharded = build_ivf_flat(params, jnp.asarray(dataset[:512]), mesh)
        sp = ivf_flat.SearchParams(n_probes=8)
        q = jnp.asarray(queries[:8])
        with sanitize.record_comms_schedule() as rec:
            sched = sanitize.assert_uniform_collective_schedule(
                lambda: search_ivf_flat(sp, sharded, q, 5, mesh))
        verbs = [e[0] for e in sched if len(e) == 3]
        assert verbs.count("all_gather") == 2, verbs  # vals + ids merge
        assert [v for v, _, _ in rec] == ["allgather", "allgather"], rec
        assert all(a == "shard" for _, a, _ in rec)
