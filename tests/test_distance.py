"""Pairwise distance correctness vs scipy (reference test model:
cpp/test/distance/ — device kernels vs naive host loops; pylibraft
test_distance.py validates vs scipy.spatial.distance.cdist)."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial import distance as spd
from scipy.spatial.distance import cdist

from raft_tpu.distance import (
    DistanceType,
    fused_l2_nn_argmin,
    masked_l2_nn_argmin,
    gram_matrix,
    KernelParams,
    KernelType,
    pairwise_distance,
)

M, N, D = 33, 47, 19


def _data(rng, positive=False, binary=False):
    x = rng.random((M, D), dtype=np.float32)
    y = rng.random((N, D), dtype=np.float32)
    if binary:
        return (x > 0.5).astype(np.float32), (y > 0.5).astype(np.float32)
    if positive:
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
    return x, y


SCIPY_METRICS = [
    ("euclidean", "euclidean", {}),
    ("sqeuclidean", "sqeuclidean", {}),
    ("cityblock", "cityblock", {}),
    ("chebyshev", "chebyshev", {}),
    ("canberra", "canberra", {}),
    ("cosine", "cosine", {}),
    ("correlation", "correlation", {}),
    ("braycurtis", "braycurtis", {}),
    ("minkowski", "minkowski", {"p": 3.0}),
]


@pytest.mark.parametrize("ours,scipy_name,kw", SCIPY_METRICS)
def test_vs_scipy(rng, ours, scipy_name, kw):
    x, y = _data(rng)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                       metric=ours, metric_arg=kw.get("p", 2.0)))
    ref = cdist(x, y, metric=scipy_name, **kw)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_inner_product(rng):
    x, y = _data(rng)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                       metric="inner_product"))
    np.testing.assert_allclose(got, x @ y.T, rtol=1e-5)


def test_hellinger(rng):
    x, y = _data(rng, positive=True)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                       metric="hellinger"))
    ref = np.sqrt(np.maximum(1.0 - np.sqrt(x)[:, None, :] @ np.sqrt(y)[None].transpose(0, 2, 1), 0)).squeeze()
    ref = np.sqrt(np.maximum(1.0 - np.einsum("id,jd->ij", np.sqrt(x), np.sqrt(y)), 0))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_jensenshannon(rng):
    x, y = _data(rng, positive=True)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                       metric="jensenshannon"))
    ref = cdist(x, y, metric="jensenshannon")
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-5)


def test_kl_divergence(rng):
    x, y = _data(rng, positive=True)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                       metric="kl_divergence"))
    ref = np.einsum("ijd->ij", x[:, None, :] * np.log(x[:, None, :] / y[None, :, :]))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_hamming(rng):
    x, y = _data(rng, binary=True)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                       metric="hamming"))
    ref = cdist(x, y, metric="hamming")
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_jaccard_dice_russelrao(rng):
    x, y = _data(rng, binary=True)
    for ours, scipy_name in [("jaccard", "jaccard"), ("dice", "dice"),
                             ("russelrao", "russellrao")]:
        got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                           metric=ours))
        ref = cdist(x.astype(bool), y.astype(bool), metric=scipy_name)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5,
                                   err_msg=ours)


def test_haversine(rng):
    x = (rng.random((10, 2)).astype(np.float32) - 0.5) * np.array([np.pi, 2 * np.pi], np.float32)
    y = (rng.random((8, 2)).astype(np.float32) - 0.5) * np.array([np.pi, 2 * np.pi], np.float32)
    got = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y),
                                       metric="haversine"))

    def hav(a, b):
        sdlat = np.sin(0.5 * (b[0] - a[0]))
        sdlon = np.sin(0.5 * (b[1] - a[1]))
        return 2 * np.arcsin(np.sqrt(sdlat**2 + np.cos(a[0]) * np.cos(b[0]) * sdlon**2))

    ref = np.array([[hav(a, b) for b in y] for a in x])
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_l2_unexpanded_matches_expanded(rng):
    x, y = _data(rng)
    e = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y), metric="sqeuclidean"))
    u = np.asarray(pairwise_distance(jnp.asarray(x), jnp.asarray(y), metric="l2_unexpanded"))
    np.testing.assert_allclose(e, u, rtol=1e-4, atol=1e-5)


class TestFusedL2NN:
    def test_matches_naive(self, rng):
        x, y = _data(rng)
        d, i = fused_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y))
        full = cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(i), full.argmin(1))
        np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-4, atol=1e-5)

    def test_tiled_path(self, rng):
        x = rng.random((20, 8), dtype=np.float32)
        y = rng.random((1000, 8), dtype=np.float32)
        d, i = fused_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y), tile=128)
        full = cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(i), full.argmin(1))
        np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-4, atol=1e-5)

    def test_sqrt(self, rng):
        x, y = _data(rng)
        d, _ = fused_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y), sqrt=True)
        full = cdist(x, y, "euclidean")
        np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-4, atol=1e-5)


def test_masked_l2_nn(rng):
    x, y = _data(rng)
    adj = rng.random((M, N)) < 0.3
    adj[:, 0] = True  # every row has at least one admitted column
    d, i = masked_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y), jnp.asarray(adj))
    full = cdist(x, y, "sqeuclidean")
    full[~adj] = np.inf
    np.testing.assert_array_equal(np.asarray(i), full.argmin(1))


def test_masked_l2_nn_tiled(rng):
    """The scanned (tile < n) path must match the single-block path and
    never pick a masked or padded column."""
    x = rng.random((37, 16), dtype=np.float32)
    y = rng.random((301, 16), dtype=np.float32)
    adj = rng.random((37, 301)) < 0.2
    adj[:, 5] = True
    d, i = masked_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y),
                               jnp.asarray(adj), tile=64)
    full = cdist(x, y, "sqeuclidean")
    full[~adj] = np.inf
    np.testing.assert_array_equal(np.asarray(i), full.argmin(1))
    np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-4,
                               atol=1e-5)


def test_masked_l2_nn_tiled_groups(rng):
    """Group-indexed adjacency on the tiled path (reference: masked_nn's
    group semantics, detail/masked_distance_base.cuh)."""
    x = rng.random((20, 8), dtype=np.float32)
    y = rng.random((150, 8), dtype=np.float32)
    n_groups = 6
    gidx = rng.integers(0, n_groups, 150).astype(np.int32)
    adj = rng.random((20, n_groups)) < 0.5
    adj[:, 0] = True
    col_mask = adj[:, gidx]
    d, i = masked_l2_nn_argmin(jnp.asarray(x), jnp.asarray(y),
                               jnp.asarray(adj), jnp.asarray(gidx), tile=64)
    full = cdist(x, y, "sqeuclidean")
    full[~col_mask] = np.inf
    np.testing.assert_array_equal(np.asarray(i), full.argmin(1))


class TestGram:
    def test_linear(self, rng):
        x, y = _data(rng)
        got = np.asarray(gram_matrix(jnp.asarray(x), jnp.asarray(y),
                                     KernelParams(KernelType.LINEAR)))
        np.testing.assert_allclose(got, x @ y.T, rtol=1e-5)

    def test_rbf(self, rng):
        x, y = _data(rng)
        gamma = 0.5
        got = np.asarray(gram_matrix(jnp.asarray(x), jnp.asarray(y),
                                     KernelParams(KernelType.RBF, gamma=gamma)))
        ref = np.exp(-gamma * cdist(x, y, "sqeuclidean"))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_poly_tanh(self, rng):
        x, y = _data(rng)
        p = KernelParams(KernelType.POLYNOMIAL, degree=2, gamma=0.1, coef0=1.0)
        got = np.asarray(gram_matrix(jnp.asarray(x), jnp.asarray(y), p))
        np.testing.assert_allclose(got, (0.1 * (x @ y.T) + 1.0) ** 2, rtol=1e-4)
        p = KernelParams(KernelType.TANH, gamma=0.1, coef0=0.5)
        got = np.asarray(gram_matrix(jnp.asarray(x), jnp.asarray(y), p))
        np.testing.assert_allclose(got, np.tanh(0.1 * (x @ y.T) + 0.5), rtol=1e-4)
