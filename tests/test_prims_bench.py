"""Micro-benchmark harness smoke tests (reference: cpp/bench/prims)."""

import numpy as np

from raft_tpu.bench import prims


def test_select_k_bench_rows(tmp_path):
    rows = prims.bench_select_k(grid=[(32, 512, 5)], iters=2)
    assert {r.impl for r in rows} >= {"lax.top_k", "select_k.auto"}
    assert all(r.ms > 0 and np.isfinite(r.throughput) for r in rows)
    out = str(tmp_path / "prims.csv")
    prims.export_csv(rows, out)
    with open(out) as f:
        assert len(f.readlines()) == len(rows) + 1


def test_run_rejects_unknown():
    import pytest

    with pytest.raises(ValueError):
        prims.run(["nope"])


def test_ivf_scan_crossover_smoke():
    rows = prims.bench_ivf_scan(batches=(16, 128), n=4000, d=32,
                                n_lists=32, n_probes=8, iters=1)
    modes = {(r.params["batch"], r.impl) for r in rows}
    assert (16, "grouped") in modes and (128, "per_query") in modes


def test_pq_scan_bench_rows(monkeypatch):
    """The scan-kernel microbench must emit a one-hot row and, with the
    interpret-mode force on, a pallas_lut row (ISSUE 2 acceptance) —
    plus the ISSUE 12 filtered pair: the fused filtered scan vs the
    forced-fallback tier on the same shape at 10% selectivity."""
    import os

    monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
    rows = prims.bench_pq_scan(grid=[(2000, 32, 16, 8, 40, 64)], iters=1)
    impls = {r.impl for r in rows}
    assert impls == {"one_hot", "pallas_lut", "filtered_pallas_lut",
                     "filtered_fallback"}, impls
    measured = [r for r in rows if not r.impl.endswith("skipped")]
    assert all(r.ms > 0 and np.isfinite(r.throughput) for r in measured)
    filt = [r for r in rows if r.impl.startswith("filtered_")]
    assert all(r.params["filter_selectivity"] == 0.1 for r in filt)
    # the forced-fallback row's env pin must be restored, not leaked
    assert os.environ.get("RAFT_TPU_PALLAS_LUTSCAN") == "always"


import pytest


@pytest.mark.slow  # three real builds (~11 s); the CI pytest lane runs it
def test_build_encode_bench_rows():
    """ISSUE 13 satellite: the build_encode microbench must emit the
    serial build_chunked row plus, on a multi-device host, the
    distributed serialized/prefetch pair (vectors/s/chip) — with the
    roofline columns of the per-chunk encode program attached."""
    rows = prims.bench_build_encode(grid=[(4000, 16, 8, 512)])
    impls = {r.impl for r in rows}
    assert "build_chunked" in impls, impls
    import jax

    if len(jax.devices()) >= 2:
        assert {"distributed_serial", "distributed_prefetch"} <= impls
    else:
        assert "distributed_skipped" in impls  # skip recorded, not silent
    measured = [r for r in rows if not r.impl.endswith("skipped")]
    assert all(r.ms > 0 and np.isfinite(r.throughput) for r in measured)
    assert all(r.params.get("flops") for r in measured)


def test_refine_bench_rows(monkeypatch):
    """The refine microbench must emit an einsum row and, with the
    interpret-mode force on, a pallas_gather row forced through the env
    override (ISSUE 4 acceptance: the bench/prims refine row)."""
    monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
    rows = prims.bench_refine(grid=[(1500, 32, 32, 256, 8)], iters=1)
    impls = {r.impl for r in rows}
    assert impls == {"einsum_gather", "pallas_gather"}, impls
    assert all(r.ms > 0 and np.isfinite(r.throughput) for r in rows)
    assert all(r.params["gather_buffer_gib"] >= 0 for r in rows)
    # the override must be restored, not leaked
    import os
    assert os.environ.get("RAFT_TPU_PALLAS_REFINE") == "always"


def test_tiered_refine_bench_rows(monkeypatch):
    """The tiered-refine microbench (ISSUE 17) must emit all three
    residency legs, with the tiered row carrying its hit/stall split
    and the host rows their implied h2d bandwidth."""
    monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "16")
    rows = prims.bench_tiered_refine(grid=[(4_000, 32, 64, 8)], iters=1)
    impls = {r.impl for r in rows}
    assert impls == {"hbm_resident", "tiered_prefetch",
                     "serialized_gather"}, impls
    assert all(r.ms > 0 and np.isfinite(r.throughput) for r in rows)
    by = {r.impl: r for r in rows}
    t = by["tiered_prefetch"].params
    assert t["prefetch_hits"] + t["prefetch_stalls"] == 4  # 64/16
    assert by["serialized_gather"].params["h2d_gibps"] > 0
    assert "h2d_gibps" not in by["hbm_resident"].params
