"""Notebooks parity (reference: notebooks/*.ipynb, SURVEY §2.17).

Full execution is exercised manually / in docs builds; here we keep the
cheap invariants: valid nbformat JSON and code cells that compile.
"""
import glob
import json
import os

import pytest

NB_DIR = os.path.join(os.path.dirname(__file__), "..", "notebooks")


@pytest.mark.parametrize("path", sorted(glob.glob(os.path.join(NB_DIR, "*.ipynb"))))
def test_notebook_wellformed(path):
    nb = json.load(open(path))
    assert nb["nbformat"] == 4
    assert any(c["cell_type"] == "markdown" for c in nb["cells"])
    for i, cell in enumerate(nb["cells"]):
        if cell["cell_type"] == "code":
            compile("".join(cell["source"]), f"{path}#cell{i}", "exec")


def test_notebooks_exist():
    names = {os.path.basename(p) for p in glob.glob(os.path.join(NB_DIR, "*.ipynb"))}
    assert {"ivf_flat_example.ipynb", "tutorial_ivf_pq.ipynb"} <= names
