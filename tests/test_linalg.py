"""linalg tests vs numpy/scipy (reference test model: cpp/test/linalg/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg


@pytest.fixture()
def mats(rng):
    a = rng.random((20, 12), dtype=np.float32)
    b = rng.random((12, 9), dtype=np.float32)
    return a, b


class TestBlas:
    def test_gemm(self, mats):
        a, b = mats
        np.testing.assert_allclose(
            np.asarray(linalg.gemm(jnp.asarray(a), jnp.asarray(b))),
            a @ b, rtol=1e-5)

    def test_gemm_trans_beta(self, mats, rng):
        a, b = mats
        c = rng.random((12, 12), dtype=np.float32)
        out = linalg.gemm(jnp.asarray(a), jnp.asarray(a), alpha=2.0,
                          beta=0.5, c=jnp.asarray(c), trans_a=True)
        np.testing.assert_allclose(np.asarray(out), 2 * a.T @ a + 0.5 * c,
                                   rtol=1e-5)

    def test_gemv_axpy_dot(self, mats, rng):
        a, _ = mats
        x = rng.random(12, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(linalg.gemv(jnp.asarray(a), jnp.asarray(x))),
                                   a @ x, rtol=1e-5)
        y = rng.random(12, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(linalg.axpy(2.0, jnp.asarray(x), jnp.asarray(y))),
                                   2 * x + y, rtol=1e-6)
        np.testing.assert_allclose(float(linalg.dot(jnp.asarray(x), jnp.asarray(y))),
                                   x @ y, rtol=1e-5)


class TestSolvers:
    def test_eig(self, rng):
        a = rng.random((10, 10), dtype=np.float32)
        s = (a + a.T) / 2
        w, v = linalg.eig_dc(jnp.asarray(s))
        np.testing.assert_allclose(np.asarray(s @ np.asarray(v)),
                                   np.asarray(v) * np.asarray(w)[None, :],
                                   atol=1e-4)

    def test_svd_reconstruct(self, mats):
        a, _ = mats
        u, s, vt = linalg.svd(jnp.asarray(a))
        np.testing.assert_allclose(
            np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(vt), a,
            atol=1e-4)

    def test_rsvd_top_singular_values(self, rng):
        # low-rank + noise: rsvd should recover the top singular values
        u = rng.random((50, 5), dtype=np.float32)
        v = rng.random((5, 30), dtype=np.float32)
        a = u @ v
        _, s_r, _ = linalg.rsvd(jnp.asarray(a), k=5, n_iter=3)
        s_full = np.linalg.svd(a, compute_uv=False)[:5]
        np.testing.assert_allclose(np.asarray(s_r), s_full, rtol=1e-3)

    def test_qr(self, mats):
        a, _ = mats
        q, r = linalg.qr(jnp.asarray(a))
        np.testing.assert_allclose(np.asarray(q) @ np.asarray(r), a, atol=1e-4)
        np.testing.assert_allclose(np.asarray(q).T @ np.asarray(q),
                                   np.eye(12), atol=1e-4)

    def test_lstsq(self, rng):
        a = rng.random((30, 5), dtype=np.float32)
        x_true = rng.random(5, dtype=np.float32)
        b = a @ x_true
        x = linalg.lstsq(jnp.asarray(a), jnp.asarray(b))
        np.testing.assert_allclose(np.asarray(x), x_true, atol=1e-3)

    def test_cholesky_r1_update(self, rng):
        a = rng.random((6, 6), dtype=np.float32)
        spd = a @ a.T + 6 * np.eye(6, dtype=np.float32)
        l = np.linalg.cholesky(spd)
        v = rng.random(6, dtype=np.float32)
        l_up = linalg.cholesky_r1_update(jnp.asarray(l), jnp.asarray(v))
        expected = np.linalg.cholesky(spd + np.outer(v, v))
        np.testing.assert_allclose(np.asarray(l_up), expected, atol=1e-3)


class TestMapReduce:
    def test_normalize_rows(self, mats):
        a, _ = mats
        out = np.asarray(linalg.normalize_rows(jnp.asarray(a)))
        np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)

    def test_reduce_rows_by_key(self, rng):
        m = rng.random((10, 4), dtype=np.float32)
        keys = np.array([0, 1, 0, 2, 1, 0, 2, 2, 1, 0])
        out = np.asarray(linalg.reduce_rows_by_key(
            jnp.asarray(m), jnp.asarray(keys), 3))
        for k in range(3):
            np.testing.assert_allclose(out[k], m[keys == k].sum(0), rtol=1e-5)

    def test_reduce_cols_by_key(self, rng):
        m = rng.random((4, 6), dtype=np.float32)
        keys = np.array([0, 1, 1, 0, 2, 2])
        out = np.asarray(linalg.reduce_cols_by_key(
            jnp.asarray(m), jnp.asarray(keys), 3))
        for k in range(3):
            np.testing.assert_allclose(out[:, k], m[:, keys == k].sum(1),
                                       rtol=1e-5)

    def test_reduce_with_main_op(self, mats):
        a, _ = mats
        out = np.asarray(linalg.reduce_op(jnp.asarray(a), axis=1, op="sum",
                                          main_op=lambda x: x * x))
        np.testing.assert_allclose(out, (a * a).sum(1), rtol=1e-5)

    def test_mse_map_offset(self, mats):
        a, _ = mats
        b = a + 0.1
        np.testing.assert_allclose(
            float(linalg.mean_squared_error(jnp.asarray(a), jnp.asarray(b))),
            0.01, rtol=1e-3)
        out = np.asarray(linalg.map_offset(lambda i: i * 2, (3, 4)))
        np.testing.assert_array_equal(out, (np.arange(12) * 2).reshape(3, 4))
