"""benchdiff (tools/benchdiff.py): record loading, the join, the
noise-aware thresholds, environment-provenance refusal, rendering,
exit codes — and the gate's self-test: a slowdown injected through the
PR-7 ``faults`` ``sleep`` kind must trip it (ISSUE 9 acceptance)."""

import copy
import json
import subprocess
import sys

import pytest

from tools import benchdiff


ENV = {"jax": "0.4.37", "jaxlib": "0.4.36", "libtpu": None,
       "backend": "cpu", "device_kind": "cpu", "device_count": 8,
       "mesh_shape": [8]}


def _row(qps=1000.0, recall=0.99, index="ivf_flat.n1024",
         sp=None, p50=0.010, p99=0.011, reps=5, env=ENV, **extra):
    r = {"dataset": "sift-hard", "algo": "ivf_flat", "index": index,
         "qps": qps, "recall": recall, "batch_size": 10_000,
         "search_param": sp if sp is not None else {"n_probes": 32},
         "latency_p50_s": p50, "latency_p99_s": p99,
         "latency_reps": reps}
    if env is not None:
        r["env"] = dict(env)
    r.update(extra)
    return r


def _record(rows, path=None, tmp_path=None, name="r.json"):
    doc = {"detail": rows}
    if tmp_path is not None:
        path = tmp_path / name
        path.write_text(json.dumps(doc))
        return str(path)
    return {"path": "<mem>", "rows": rows, "meta": {}}


class TestLoading:
    def test_payload_driver_wrap_and_bare_list(self, tmp_path):
        rows = [_row()]
        shapes = {
            "payload.json": {"detail": rows},
            "wrapped.json": {"parsed": {"detail": rows}, "rc": 0},
            "bare.json": rows,
        }
        for name, doc in shapes.items():
            p = tmp_path / name
            p.write_text(json.dumps(doc))
            rec = benchdiff.load_record(str(p))
            assert len(rec["rows"]) == 1, name

    def test_rowless_record_raises(self, tmp_path):
        p = tmp_path / "x.json"
        p.write_text(json.dumps({"metric": "x"}))
        with pytest.raises(ValueError):
            benchdiff.load_record(str(p))

    def test_baseline_name_resolution(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            benchdiff.resolve_record_path("no-such-baseline-xyz")
        # the committed baseline resolves by bare name
        assert benchdiff.resolve_record_path("cpu_smoke").endswith(
            "baselines/cpu_smoke.json")

    def test_row_key_joins_on_identity_not_measurement(self):
        a = _row(qps=1.0, recall=0.5)
        b = _row(qps=9.0, recall=0.9)
        assert benchdiff.row_key(a) == benchdiff.row_key(b)
        assert benchdiff.row_key(_row(sp={"n_probes": 64})) != \
            benchdiff.row_key(a)


class TestCompare:
    def test_identical_records_pass(self):
        doc = benchdiff.diff_records(_record([_row()]), _record([_row()]))
        assert doc["verdict"] == "pass"
        assert doc["rows"][0]["status"] == "ok"

    def test_qps_regression_trips(self):
        doc = benchdiff.diff_records(
            _record([_row(qps=1000)]), _record([_row(qps=700)]))
        assert doc["verdict"] == "regression"
        assert "qps" in doc["rows"][0]["reasons"][0]

    def test_twenty_percent_drop_always_trips_despite_noise(self):
        # rep spread at the clamp (noise 1.0) still cannot widen the
        # threshold past the cap — the acceptance bar's 20 % regression
        # must trip no matter how noisy the reps were
        base = _row(qps=1000, p50=0.01, p99=0.05)
        new = _row(qps=799, p50=0.01, p99=0.05)
        doc = benchdiff.diff_records(_record([base]), _record([new]))
        assert doc["verdict"] == "regression"

    def test_noise_widens_threshold_below_cap(self):
        # 12 % drop: trips at tight noise, tolerated under wide spread
        tight = benchdiff.diff_records(
            _record([_row(qps=1000, p99=0.0101)]),
            _record([_row(qps=880, p99=0.0101)]))
        assert tight["verdict"] == "regression"
        wide = benchdiff.diff_records(
            _record([_row(qps=1000, p99=0.0108)]),
            _record([_row(qps=880, p99=0.0108)]))
        assert wide["verdict"] == "pass"
        assert wide["rows"][0]["qps_threshold"] > \
            tight["rows"][0]["qps_threshold"]

    def test_explicit_floor_wins_over_the_cap(self):
        # --qps-drop 0.30 must tolerate a 25 % drop even though the
        # (noise-widening) cap sits at 0.18
        doc = benchdiff.diff_records(
            _record([_row(qps=1000)]), _record([_row(qps=750)]),
            thresholds={"qps_drop": 0.30})
        assert doc["verdict"] == "pass"
        assert doc["rows"][0]["qps_threshold"] == pytest.approx(0.30)

    def test_recall_regression_trips(self):
        doc = benchdiff.diff_records(
            _record([_row(recall=0.95)]), _record([_row(recall=0.90)]))
        assert doc["verdict"] == "regression"
        assert any("recall" in r for r in doc["rows"][0]["reasons"])

    def test_p99_rise_flags(self):
        doc = benchdiff.diff_records(
            _record([_row(p99=0.011)]), _record([_row(p99=0.030)]))
        assert doc["rows"][0]["status"] == "regression"
        assert any("p99" in r for r in doc["rows"][0]["reasons"])

    def test_improvement_is_flagged_not_gated(self):
        doc = benchdiff.diff_records(
            _record([_row(qps=1000)]), _record([_row(qps=1500)]))
        assert doc["verdict"] == "pass"
        assert doc["rows"][0]["status"] == "improved"

    def test_single_rep_rows_fall_back_to_floor(self):
        base = _row(qps=1000, reps=1, p99=0.05)
        assert benchdiff.row_noise(base) is None
        doc = benchdiff.diff_records(
            _record([base]), _record([_row(qps=880, reps=1, p99=0.05)]))
        assert doc["verdict"] == "regression"  # floor 10 % < 12 % drop

    def test_unmatched_rows_counted_not_gated(self):
        doc = benchdiff.diff_records(
            _record([_row(), _row(index="only-in-base")]),
            _record([_row(), _row(index="only-in-new")]))
        assert doc["verdict"] == "pass"
        assert doc["counts"]["base_only"] == 1
        assert doc["counts"]["new_only"] == 1

    def test_zero_join_refuses(self):
        doc = benchdiff.diff_records(
            _record([_row(index="a")]), _record([_row(index="b")]))
        assert doc["verdict"] == "refused"


class TestEnvProvenance:
    def test_mismatch_refuses_with_named_keys(self):
        other = dict(ENV, device_kind="TPU v5e", device_count=4)
        doc = benchdiff.diff_records(
            _record([_row()]), _record([_row(env=other)]))
        assert doc["verdict"] == "refused"
        assert "device_kind" in doc["refusal"]
        assert set(doc["env"]["mismatched_keys"]) == {"device_kind",
                                                      "device_count"}

    def test_mismatch_override(self):
        other = dict(ENV, jax="9.9.9")
        doc = benchdiff.diff_records(
            _record([_row()]), _record([_row(env=other)]),
            allow_env_mismatch=True)
        assert doc["verdict"] == "pass"

    def test_pre_provenance_records_compare_as_unknown(self):
        doc = benchdiff.diff_records(
            _record([_row(env=None)]), _record([_row()]))
        assert doc["env"]["status"] == "unknown"
        assert doc["verdict"] == "pass"


class TestRenderAndCli:
    def test_markdown_scoreboard(self):
        doc = benchdiff.diff_records(
            _record([_row(qps=1000)]), _record([_row(qps=700)]))
        md = benchdiff.render_markdown(doc)
        assert "REGRESSION" in md and "ivf_flat.n1024" in md
        assert "Environment: identical" in md

    def test_cli_exit_codes_and_artifacts(self, tmp_path):
        base = _record([_row(qps=1000)], tmp_path=tmp_path, name="b.json")
        slow = _record([_row(qps=600)], tmp_path=tmp_path, name="s.json")
        out_md = tmp_path / "score.md"
        out_json = tmp_path / "verdict.json"
        rc = benchdiff.main([base, base])
        assert rc == 0
        rc = benchdiff.main([base, slow, "--md", str(out_md),
                             "--json", str(out_json)])
        assert rc == 1
        assert "REGRESSION" in out_md.read_text()
        verdict = json.loads(out_json.read_text())
        assert verdict["schema"] == benchdiff.SCHEMA
        assert verdict["verdict"] == "regression"
        assert benchdiff.main([base, slow, "--report-only"]) == 0

    def test_cli_env_mismatch_exit_2(self, tmp_path):
        base = _record([_row()], tmp_path=tmp_path, name="b.json")
        rows = [_row(env=dict(ENV, jaxlib="0.0.1"))]
        other = _record(rows, tmp_path=tmp_path, name="o.json")
        assert benchdiff.main([base, other]) == 2
        assert benchdiff.main([base, other, "--allow-env-mismatch"]) == 0

    def test_cli_missing_file_exit_2(self):
        assert benchdiff.main(["/no/such.json", "/no/such2.json"]) == 2

    def test_obsdump_renders_verdict_json(self, tmp_path, capsys):
        from tools import obsdump

        doc = benchdiff.diff_records(
            _record([_row(qps=1000)]), _record([_row(qps=700)]))
        p = tmp_path / "verdict.json"
        p.write_text(json.dumps(doc))
        out = obsdump.render(str(p), top=20)
        assert "benchdiff" in out and "REGRESSION" in out


class TestCommittedBaseline:
    def test_cpu_smoke_baseline_loads_and_self_compares_clean(self):
        path = benchdiff.resolve_record_path("cpu_smoke")
        rec = benchdiff.load_record(path)
        assert rec["rows"], "committed baseline has no rows"
        env = benchdiff.record_env(rec)
        assert env and env["backend"] == "cpu"
        # acceptance: rows carry the roofline columns
        assert all(r.get("flops") and r.get("bytes_accessed")
                   and r.get("bound") in ("memory", "compute")
                   for r in rec["rows"])
        doc = benchdiff.diff_records(rec, rec)
        assert doc["verdict"] == "pass"
        assert doc["counts"]["regressions"] == 0


@pytest.mark.slow
class TestSleepInjectedSelfTest:
    """The gate's reason to exist: a slowdown injected through the PR-7
    fault harness (``sleep`` kind at the ``ivf_flat.search`` fault
    point) must show up as a qps regression and trip the exit code.
    Marked slow (two live bench measurements); the CI gate re-runs the
    same scenario end-to-end in ``ci/test_python.sh``, and the full
    pytest lane there includes slow tests."""

    @pytest.fixture(scope="class")
    def records(self, tmp_path_factory):
        from raft_tpu.bench import runner
        from raft_tpu.robust import faults

        cfg = {
            "dataset": {"name": "gate-smoke", "n": 1200, "dim": 16,
                        "n_queries": 64, "metric": "sqeuclidean"},
            "k": 8, "batch_size": 10_000,
            "index": [{"name": "ivf_flat.n8", "algo": "ivf_flat",
                       "build_param": {"n_lists": 8},
                       "search_params": [{"n_probes": 4}]}],
        }

        def measure():
            rows = runner.run_config(copy.deepcopy(cfg), verbose=False)
            return {"detail": [
                {"dataset": r.dataset, "algo": r.algo,
                 "index": r.index_name, "qps": r.qps,
                 "recall": r.recall, "batch_size": r.batch_size,
                 "search_param": r.search_param, "env": r.env}
                for r in rows]}

        base = measure()
        faults.install_plan({"faults": [
            {"site": "ivf_flat.search", "kind": "sleep",
             "sleep_s": 0.05, "times": 0}]})
        try:
            slow = measure()
        finally:
            faults.clear_plan()
        d = tmp_path_factory.mktemp("gate")
        pb, ps = d / "base.json", d / "slow.json"
        pb.write_text(json.dumps(base))
        ps.write_text(json.dumps(slow))
        return str(pb), str(ps), base, slow

    def test_injected_sleep_is_a_real_slowdown(self, records):
        _, _, base, slow = records
        b, s = base["detail"][0]["qps"], slow["detail"][0]["qps"]
        assert s < 0.8 * b, (b, s)  # ≥20 % regression, the gate's bar

    def test_gate_trips_on_injected_slowdown(self, records):
        pb, ps, _, _ = records
        assert benchdiff.main([pb, pb]) == 0   # unchanged record passes
        assert benchdiff.main([pb, ps]) == 1   # injected slowdown trips

    def test_gate_trips_from_the_cli_entry(self, records):
        pb, ps, _, _ = records
        p = subprocess.run(
            [sys.executable, "-m", "tools.benchdiff", pb, ps],
            capture_output=True, text=True)
        assert p.returncode == 1, p.stdout + p.stderr
        assert "REGRESSION" in p.stdout
