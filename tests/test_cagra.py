"""CAGRA tests: graph quality + search recall vs naive (reference test
model: cpp/test/neighbors/ann_cagra/ recall thresholds)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.neighbors import cagra
from raft_tpu.neighbors.cagra import IndexParams, SearchParams
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState


def recall_at_k(got_ids, ref_ids):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got_ids, ref_ids))
    return hits / ref_ids.size


@pytest.fixture(scope="module")
def corpus():
    x, _ = make_blobs(3000, 24, n_clusters=30, cluster_std=1.2,
                      state=RngState(21))
    q, _ = make_blobs(80, 24, n_clusters=30, cluster_std=1.2,
                      state=RngState(22))
    return np.asarray(x), np.asarray(q)


@pytest.fixture(scope="module")
def built_index(corpus):
    x, _ = corpus
    return cagra.build(jnp.asarray(x),
                       IndexParams(intermediate_graph_degree=48,
                                   graph_degree=24, seed=0))


class TestCagraBuild:
    def test_graph_shape_and_validity(self, built_index, corpus):
        x, _ = corpus
        g = np.asarray(built_index.graph)
        assert g.shape == (len(x), 24)
        assert (g >= 0).all() and (g < len(x)).all()
        # no self-loops in the forward half
        assert (g[:, :12] != np.arange(len(x))[:, None]).all()

    def test_knn_graph_quality(self, corpus):
        """The intermediate knn graph must mostly agree with exact knn."""
        x, _ = corpus
        knn = np.asarray(cagra.build_knn_graph(jnp.asarray(x), 10))
        full = cdist(x, x, "sqeuclidean")
        np.fill_diagonal(full, np.inf)
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(knn, ref) >= 0.9

    def test_optimize_graph_connectivity(self, built_index, corpus):
        """Reverse-edge augmentation keeps in-degree spread reasonable."""
        x, _ = corpus
        g = np.asarray(built_index.graph)
        indeg = np.bincount(g.reshape(-1), minlength=len(x))
        assert (indeg > 0).mean() > 0.95  # nearly every node reachable


class TestCagraSearch:
    def test_recall(self, built_index, corpus):
        x, q = corpus
        dists, ids = cagra.search(built_index, jnp.asarray(q), 10,
                                  SearchParams(itopk_size=64, search_width=4))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.9

    def test_distances_are_exact_for_found_ids(self, built_index, corpus):
        x, q = corpus
        dists, ids = cagra.search(built_index, jnp.asarray(q), 5,
                                  SearchParams(itopk_size=32))
        full = cdist(q, x, "sqeuclidean")
        exact = np.take_along_axis(full, np.asarray(ids), axis=1)
        np.testing.assert_allclose(np.asarray(dists), exact, rtol=1e-3,
                                   atol=1e-3)

    def test_wider_search_improves_recall(self, built_index, corpus):
        x, q = corpus
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        _, ids_small = cagra.search(built_index, jnp.asarray(q), 10,
                                    SearchParams(itopk_size=16, max_iterations=4))
        _, ids_big = cagra.search(built_index, jnp.asarray(q), 10,
                                  SearchParams(itopk_size=96, search_width=8))
        assert (recall_at_k(np.asarray(ids_big), ref)
                >= recall_at_k(np.asarray(ids_small), ref))

    def test_query_tiling_matches(self, built_index, corpus):
        x, q = corpus
        d1, i1 = cagra.search(built_index, jnp.asarray(q), 5,
                              SearchParams(itopk_size=32, query_tile=512))
        d2, i2 = cagra.search(built_index, jnp.asarray(q), 5,
                              SearchParams(itopk_size=32, query_tile=16))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_serialize_roundtrip(self, built_index, corpus, tmp_path):
        x, q = corpus
        path = os.path.join(tmp_path, "cagra.idx")
        cagra.save(built_index, path)
        idx2 = cagra.load(path)
        d1, i1 = cagra.search(built_index, jnp.asarray(q), 5)
        d2, i2 = cagra.search(idx2, jnp.asarray(q), 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_serialize_to_hnswlib_layout(self, built_index, corpus, tmp_path):
        """Structural parse of the exported file following hnswlib's
        saveIndex layout (hnswlib itself isn't in the image): header
        fields, per-element link/data/label blocks, level-list zeros."""
        import struct

        x, _ = corpus
        path = os.path.join(tmp_path, "cagra.hnsw")
        cagra.serialize_to_hnswlib(built_index, path, ef_construction=150)
        n, dim = x.shape
        degree = built_index.graph.shape[1]
        with open(path, "rb") as f:
            raw = f.read()
        hdr_fmt = "<QQQQQQiIQQQdQ"
        hdr = struct.unpack_from(hdr_fmt, raw, 0)
        (off0, max_el, cur, size_pe, label_off, off_data,
         maxlevel, entry, maxm, maxm0, m, mult, efc) = hdr
        assert (off0, max_el, cur, maxlevel) == (0, n, n, 0)
        assert maxm0 == degree and efc == 150
        assert size_pe == (degree * 4 + 4) + dim * 4 + 8
        base = struct.calcsize(hdr_fmt)
        blocks = np.frombuffer(
            raw, np.uint8, n * size_pe, base).reshape(n, size_pe)
        # vectors roundtrip exactly
        vecs = blocks[:, off_data:off_data + dim * 4].copy().view(
            np.float32).reshape(n, dim)
        np.testing.assert_array_equal(vecs, np.asarray(built_index.dataset))
        # labels are 0..n-1
        labels = blocks[:, label_off:label_off + 8].copy().view(np.uint64)
        np.testing.assert_array_equal(labels.reshape(-1), np.arange(n))
        # link lists: count, then that many valid neighbor ids compacted
        # to the front in graph order
        counts = blocks[:, 0:2].copy().view(np.uint16).reshape(-1)
        links = blocks[:, 4:4 + degree * 4].copy().view(np.uint32).reshape(
            n, degree)
        g = np.asarray(built_index.graph)
        np.testing.assert_array_equal(counts, (g >= 0).sum(1))
        for row in (0, n // 2, n - 1):
            np.testing.assert_array_equal(
                links[row, :counts[row]], g[row][g[row] >= 0])
        # trailing: one zero u32 per element (no upper levels)
        tail = np.frombuffer(raw, np.uint32, n, base + n * size_pe)
        assert (tail == 0).all()
        assert len(raw) == base + n * size_pe + n * 4

    def test_serialize_without_dataset(self, built_index, corpus, tmp_path):
        x, q = corpus
        path = os.path.join(tmp_path, "cagra_nods.idx")
        cagra.save(built_index, path, include_dataset=False)
        idx2 = cagra.load(path, dataset=jnp.asarray(x))
        _, i2 = cagra.search(idx2, jnp.asarray(q), 5)
        _, i1 = cagra.search(built_index, jnp.asarray(q), 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestClusterKnnGraph:
    @pytest.mark.slow  # the overflow-rows twin keeps cluster-graph parity tier-1 (tier-1 budget)
    def test_matches_exact_graph(self):
        """Cluster-blocked graph (n>16384 path) edges vs exact 32-NN."""
        from scipy.spatial.distance import cdist
        rng = np.random.default_rng(3)
        centers = rng.normal(0, 10, (64, 16)).astype(np.float32)
        x = (centers[rng.integers(0, 64, 20_000)]
             + rng.normal(0, 0.5, (20_000, 16)).astype(np.float32))
        g = cagra.cluster_knn_graph(jnp.asarray(x), 16, rows_per_list=512,
                                    neighborhood=8)
        g = np.asarray(g)
        assert g.shape == (20_000, 16)
        # spot-check recall of graph edges against exact kNN on a sample
        sample = rng.choice(20_000, 200, replace=False)
        d = cdist(x[sample], x, "sqeuclidean")
        d[np.arange(200), sample] = np.inf
        exact = np.argsort(d, axis=1)[:, :16]
        rec = np.mean([len(set(exact[i]) & set(g[s])) / 16
                       for i, s in enumerate(sample)])
        assert rec >= 0.85, rec
        # no self edges
        assert not (g[sample] == sample[:, None]).any()

    def test_overflow_rows_get_own_neighbors(self):
        """Rows dropped by list overflow must get THEIR OWN cluster-local
        neighbors, not another row's edges (ADVICE r3: cagra.py:267)."""
        from scipy.spatial.distance import cdist
        rng = np.random.default_rng(5)
        # a third of the rows sit in one tiny ball: nearest-center
        # assignment sends them all to one list, which must overflow the
        # 4x-mean capacity cap no matter how balanced the centers are
        centers = rng.normal(0, 50, (40, 8)).astype(np.float32)
        assign = np.where(rng.random(20_000) < 0.35, 0,
                          rng.integers(1, 40, 20_000))
        x = (centers[assign]
             + rng.normal(0, 0.5, (20_000, 8)).astype(np.float32))
        x[assign == 0] = centers[0] + rng.normal(
            0, 1e-3, (int((assign == 0).sum()), 8)).astype(np.float32)
        import raft_tpu.neighbors.cagra as cagra_mod
        hits = {}
        orig = cagra_mod._overflow_knn
        cagra_mod._overflow_knn = (
            lambda *a, **k: (hits.setdefault("y", True), orig(*a, **k))[1])
        try:
            g = np.asarray(cagra.cluster_knn_graph(
                jnp.asarray(x), 8, rows_per_list=512, neighborhood=8))
        finally:
            cagra_mod._overflow_knn = orig
        assert hits.get("y"), "overflow patch path was not exercised"
        # sample rows of the fat cluster (where overflow lands) and check
        # their edges point at genuinely near vectors
        fat = np.nonzero(assign == 0)[0]
        sample = rng.choice(fat, 100, replace=False)
        d = cdist(x[sample], x, "sqeuclidean")
        near = np.partition(d, 200, axis=1)[:, 200]  # generous near bar
        for i, s in enumerate(sample):
            dist_of_edges = d[i, g[s]]
            assert (dist_of_edges <= max(near[i], 1.0)).mean() >= 0.5, (
                f"row {s}: edges are not local")
