"""Memory-tiered serving (ISSUE 17): host-resident raw vectors with
candidate-row prefetch overlapped under the LUT scan.

The acceptance contract under test: the tiered path's results are
BIT-EQUAL to the HBM-resident path across metrics × pq_bits including
a composed filter_bitset; the :class:`RowPrefetcher` honours the PR-13
prefetcher lifecycle (exception at the next get(), clean mid-stream
close, hit/stall accounting, ``serve.row_read`` faults recovering
under ``retry.IO_POLICY``); the overlap is real (prefetched wall <
serialized wall with a calibrated synthetic delay); the registry
demotes raw vectors to host under HBM pressure instead of evicting
(counted ``demote_raw`` rung, re-promotion when pressure clears); and
mixed-residency byte accounting only charges HBM for device leaves.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu import obs, serve
from raft_tpu.core import bitset
from raft_tpu.neighbors import ivf_flat, ivf_pq, tiered
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.robust import degrade, faults
from raft_tpu.serve import placement
from tools.obsdump import parse_key

N, DIM = 2000, 32
METRICS = ["sqeuclidean", "inner_product", "cosine"]


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_plan()
    degrade.clear_recent()
    yield
    faults.clear_plan()
    degrade.clear_recent()
    obs.disable()


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(17)
    return rng.random((N, DIM), dtype=np.float32)


@pytest.fixture(scope="module")
def queries(data):
    return jnp.asarray(data[:32] + 0.01)


def _pq(data, **kw):
    kw.setdefault("n_lists", 16)
    kw.setdefault("pq_dim", 16)
    kw.setdefault("seed", 0)
    kw.setdefault("cache_reconstruction", "never")
    return ivf_pq.build(jnp.asarray(data), ivf_pq.IndexParams(**kw))


@pytest.fixture(scope="module")
def pq_index(data):
    return _pq(data)


REFINE_PARAMS = ivf_pq.SearchParams(
    n_probes=16, scan_mode="per_query", lut_dtype="float32",
    refine="f32_regen", refine_ratio=4.0)


def _label_sum(reg, name, **want):
    """Sum counters named ``name`` whose labels include ``want`` —
    label-render-order-proof counter matching."""
    total = 0.0
    for key, v in reg.snapshot()["counters"].items():
        kname, labels = parse_key(key)
        if kname == name and all(labels.get(k) == w
                                 for k, w in want.items()):
            total += v
    return total


# ---------------------------------------------------------------------------
# RowPrefetcher lifecycle (the PR-13 ChunkPrefetcher contract, serving twin)
# ---------------------------------------------------------------------------

class TestRowPrefetcher:
    def test_submit_order_and_hit_stall_accounting(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        pf = tiered.RowPrefetcher(lambda c: c * 10, depth=2, tenant="t")
        try:
            pf.submit(1)
            pf.submit(2)
            time.sleep(0.3)            # both land before anyone asks
            assert pf.get() == 10      # hit
            assert pf.get() == 20      # hit

            slow_started = threading.Event()

            def slow(c):
                slow_started.set()
                time.sleep(0.2)
                return c * 10

            pf._fetch = slow
            pf.submit(3)
            slow_started.wait(timeout=5.0)
            assert pf.get() == 30      # consumer waited: stall
        finally:
            pf.close()
        assert _label_sum(reg, "serve.prefetch.hit", tenant="t") == 2
        assert _label_sum(reg, "serve.prefetch.stall", tenant="t") == 1

    def test_serialized_mode_every_get_is_a_stall(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        pf = tiered.RowPrefetcher(lambda c: c + 1, tenant="s",
                                  prefetch=False)
        try:
            pf.submit(1)
            pf.submit(2)
            assert pf.get() == 2
            assert pf.get() == 3
        finally:
            pf.close()
        assert pf._thread is None      # no reader in serialized mode
        assert _label_sum(reg, "serve.prefetch.hit", tenant="s") == 0
        assert _label_sum(reg, "serve.prefetch.stall", tenant="s") == 2

    def test_reader_exception_raised_at_next_get(self):
        calls = []

        def fetch(c):
            calls.append(c)
            if c == 2:
                raise ValueError("disk gone")
            return c

        pf = tiered.RowPrefetcher(fetch, depth=2)
        pf.submit(1)
        pf.submit(2)
        pf.submit(3)
        assert pf.get() == 1
        with pytest.raises(ValueError, match="disk gone"):
            pf.get()
        # the reader exits after queueing the error: block 3 never reads
        assert calls == [1, 2]
        pf.close()   # idempotent after the error path already closed
        pf.close()

    def test_get_past_last_submit_is_typed(self):
        pf = tiered.RowPrefetcher(lambda c: c)
        try:
            pf.submit(1)
            assert pf.get() == 1
            with pytest.raises(IndexError, match="past the last submit"):
                pf.get()
        finally:
            pf.close()

    def test_close_mid_stream_is_clean_and_fast(self):
        def slowish(c):
            time.sleep(0.05)
            return c

        pf = tiered.RowPrefetcher(slowish, depth=2)
        for i in range(8):
            pf.submit(i)
        t0 = time.monotonic()
        pf.close()   # unconsumed blocks in flight: must not hang
        assert time.monotonic() - t0 < 5.0
        assert pf._thread is None or not pf._thread.is_alive()
        pf.close()   # idempotent


# ---------------------------------------------------------------------------
# host_row_reader: gather semantics + IO fault recovery under IO_POLICY
# ---------------------------------------------------------------------------

class TestHostRowReader:
    def test_gather_matches_refine_gathered_semantics(self, data):
        fetch = tiered.host_row_reader(data)
        cand = np.array([[0, 5, -3], [N - 1, N + 7, 2]], np.int32)
        rows = np.asarray(fetch(jnp.asarray(cand)))
        assert rows.shape == (2, 3, DIM)
        assert rows.dtype == np.float32
        # out-of-range ids clip exactly like refine_gathered (the
        # refine epilogue masks id<0 rows out of the ranking anyway)
        np.testing.assert_array_equal(rows[0, 2], data[0])
        np.testing.assert_array_equal(rows[1, 1], data[N - 1])

    def test_row_read_fault_recovers_counted(self, data):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "serve.row_read", "kind": "error", "times": 1}]})
        fetch = tiered.host_row_reader(data)
        rows = np.asarray(fetch(np.array([[1, 2]], np.int32)))
        np.testing.assert_array_equal(rows[0, 0], data[1])
        assert _label_sum(reg, "retry.recovered",
                          site="serve.row_read") >= 1

    def test_row_read_fault_through_the_pipeline(self, data, queries,
                                                 monkeypatch):
        """The whole-path chaos case: an injected serve.row_read fault
        inside the PREFETCH READER recovers under IO_POLICY and the
        search still returns the exact tiered results."""
        monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "8")
        idx = _pq(data)
        clean = ivf_pq.search(idx, queries, 10, REFINE_PARAMS,
                              dataset=data)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "serve.row_read", "kind": "error", "times": 2}]})
        d, i = ivf_pq.search(idx, queries, 10, REFINE_PARAMS,
                             dataset=data)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(clean[1]))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(clean[0]))
        assert _label_sum(reg, "retry.recovered",
                          site="serve.row_read") >= 1


# ---------------------------------------------------------------------------
# routing guard
# ---------------------------------------------------------------------------

class TestTieredWanted:
    def test_ineligible_bases_decline(self, data):
        p = REFINE_PARAMS
        assert not tiered.tiered_refine_wanted(None, 64, 40, DIM, p)
        assert not tiered.tiered_refine_wanted(jnp.asarray(data), 64, 40,
                                               DIM, p)
        assert not tiered.tiered_refine_wanted(data[0], 64, 40, DIM, p)

        class Provider:
            shape = (N, DIM)
            _block = True

        assert not tiered.tiered_refine_wanted(Provider(), 64, 40, DIM, p)

    def test_pins_and_env(self, data, monkeypatch):
        import dataclasses

        serial = dataclasses.replace(REFINE_PARAMS,
                                     refine_transfer="serial")
        assert not tiered.tiered_refine_wanted(data, 256, 40, DIM, serial)
        monkeypatch.setenv("RAFT_TPU_TIERED_REFINE", "0")
        assert not tiered.tiered_refine_wanted(data, 256, 40, DIM,
                                               REFINE_PARAMS)
        monkeypatch.setenv("RAFT_TPU_TIERED_REFINE", "1")
        # env "on" forces even a single-sub-batch search
        assert tiered.tiered_refine_wanted(data, 8, 40, DIM,
                                           REFINE_PARAMS)
        monkeypatch.delenv("RAFT_TPU_TIERED_REFINE")
        # auto declines when the whole batch fits one pipeline stage
        assert not tiered.tiered_refine_wanted(data, 8, 40, DIM,
                                               REFINE_PARAMS)
        assert tiered.tiered_refine_wanted(data, 256, 40, DIM,
                                           REFINE_PARAMS)

    def test_mem_guard_decline_is_a_counted_degrade_step(self, data):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "tiered.mem_guard", "kind": "force", "times": 1}]})
        assert not tiered.tiered_refine_wanted(data, 256, 40, DIM,
                                               REFINE_PARAMS)
        assert _label_sum(reg, "degrade.steps", site="refine",
                          to="host_gather", reason="mem_guard") >= 1

    def test_mem_ok_bound(self):
        from raft_tpu.neighbors.ivf_common import (GROUPED_BYTES_CAP,
                                                   tiered_refine_mem_ok)

        # (depth+1) in-flight [m_b, C, d] f32 blocks vs the shared cap
        assert tiered_refine_mem_ok(64, 400, 128)
        c_huge = GROUPED_BYTES_CAP // (3 * 64 * 128 * 4) + 1
        assert not tiered_refine_mem_ok(64, c_huge, 128)

    def test_pipeline_batch(self, monkeypatch):
        assert tiered.pipeline_batch(1000) == 250
        assert tiered.pipeline_batch(64) == 32   # floor
        monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "8")
        assert tiered.pipeline_batch(1000) == 8


# ---------------------------------------------------------------------------
# parity: the acceptance core — bit-equal to the HBM-resident path
# ---------------------------------------------------------------------------

class TestTieredParity:
    def _parity(self, data, queries, idx, monkeypatch, metric,
                with_filter=False):
        monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "8")
        bits = None
        if with_filter:
            rng = np.random.default_rng(3)
            bits = bitset.from_mask(jnp.asarray(rng.random(N) < 0.5))
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            d_dev, i_dev = ivf_pq.search(idx, queries, 10, REFINE_PARAMS,
                                         dataset=jnp.asarray(data),
                                         filter_bitset=bits)
            d_t, i_t = ivf_pq.search(idx, queries, 10, REFINE_PARAMS,
                                     dataset=data, filter_bitset=bits)
        finally:
            obs.disable()
        np.testing.assert_array_equal(np.asarray(i_t), np.asarray(i_dev))
        np.testing.assert_array_equal(np.asarray(d_t), np.asarray(d_dev))
        # the host leg really served on the prefetch tier
        assert _label_sum(reg, "refine.dispatch",
                          impl="tiered_prefetch") >= 1
        hits = _label_sum(reg, "serve.prefetch.hit")
        stalls = _label_sum(reg, "serve.prefetch.stall")
        assert hits + stalls == 4    # 32 queries / sub-batch 8

    @pytest.mark.parametrize("metric", METRICS)
    def test_bit_equal_across_metrics(self, data, queries, monkeypatch,
                                      metric):
        idx = _pq(data, metric=metric)
        self._parity(data, queries, idx, monkeypatch, metric)

    def test_bit_equal_pq4_with_filter(self, data, queries, monkeypatch):
        idx = _pq(data, pq_bits=4)
        self._parity(data, queries, idx, monkeypatch, "sqeuclidean",
                     with_filter=True)

    def test_bit_equal_pq8_with_filter(self, data, queries, pq_index,
                                       monkeypatch):
        self._parity(data, queries, pq_index, monkeypatch,
                     "sqeuclidean", with_filter=True)

    def test_serial_equals_tiered(self, data, queries, pq_index,
                                  monkeypatch):
        """refine_transfer="serial" (the ladder's host_gather pin / the
        bench's comparison leg) and the prefetch pipeline agree
        bit-for-bit — the overlap is a schedule change, not a math
        change."""
        import dataclasses

        monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "8")
        serial = dataclasses.replace(REFINE_PARAMS,
                                     refine_transfer="serial")
        d_s, i_s = ivf_pq.search(pq_index, queries, 10, serial,
                                 dataset=data)
        forced = dataclasses.replace(REFINE_PARAMS,
                                     refine_transfer="tiered")
        d_t, i_t = ivf_pq.search(pq_index, queries, 10, forced,
                                 dataset=data)
        np.testing.assert_array_equal(np.asarray(i_t), np.asarray(i_s))
        np.testing.assert_array_equal(np.asarray(d_t), np.asarray(d_s))

    def test_ivf_flat_bit_equal(self, data, queries, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "8")
        idx = ivf_flat.build(jnp.asarray(data),
                             ivf_flat.IndexParams(n_lists=16))
        params = ivf_flat.SearchParams(n_probes=16, refine="f32_regen",
                                       refine_ratio=4.0)
        d_dev, i_dev = ivf_flat.search(idx, queries, 10, params,
                                       dataset=jnp.asarray(data))
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            d_t, i_t = ivf_flat.search(idx, queries, 10, params,
                                       dataset=data)
        finally:
            obs.disable()
        np.testing.assert_array_equal(np.asarray(i_t), np.asarray(i_dev))
        np.testing.assert_array_equal(np.asarray(d_t), np.asarray(d_dev))
        assert _label_sum(reg, "refine.dispatch",
                          impl="tiered_prefetch") >= 1


# ---------------------------------------------------------------------------
# overlap: the perf claim, with a calibrated synthetic delay
# ---------------------------------------------------------------------------

class TestOverlap:
    def _drive(self, prefetch, n=5, fetch_s=0.06, compute_s=0.06):
        """The search loop's schedule against a synthetic slow fetch:
        submit stage i, then consume stage i-1 with ``compute_s`` of
        'refine' work. Prefetched, the fetch hides under the compute;
        serialized, they add."""
        pf = tiered.RowPrefetcher(
            lambda c: time.sleep(fetch_s) or c, prefetch=prefetch)
        t0 = time.monotonic()
        try:
            pending = 0
            for i in range(n):
                pf.submit(i)
                pending += 1
                if pending > 1:
                    pf.get()
                    pending -= 1
                    time.sleep(compute_s)
            while pending:
                pf.get()
                pending -= 1
                time.sleep(compute_s)
        finally:
            pf.close()
        return time.monotonic() - t0

    def test_prefetch_beats_serialized(self):
        wall_serial = self._drive(prefetch=False)
        wall_pf = self._drive(prefetch=True)
        # serialized pays fetch+compute per stage (~0.60 s); prefetched
        # hides the fetch under the compute (~0.36 s). The 0.85 factor
        # absorbs scheduler noise while still proving real overlap.
        assert wall_pf < wall_serial * 0.85, (wall_pf, wall_serial)


# ---------------------------------------------------------------------------
# registry: placement, mixed-residency accounting, demote before evict
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_validation(self):
        with pytest.raises(ValueError):
            placement.Placement(codes="host")
        with pytest.raises(ValueError):
            placement.Placement(raw="disk")
        p = placement.Placement(raw="host")
        assert p.describe() == {"codes": "hbm", "raw": "host"}

    def test_tier_probes(self, data):
        assert placement.dataset_tier(None) == "none"
        assert placement.dataset_tier(jnp.asarray(data)) == "hbm"
        assert placement.dataset_tier(data) == "host"
        assert placement.placement_for(data).raw == "host"
        host = placement.to_host(jnp.asarray(data))
        assert isinstance(host, np.ndarray)
        dev = placement.to_device(data)
        assert isinstance(dev, jax.Array)


class TestRegistryTiers:
    def test_index_device_bytes_mixed_residency(self, data):
        dev = jnp.asarray(data)              # N*DIM*4 device bytes
        host = np.ones((10, 8), np.float32)  # host leaf: zero HBM
        mixed = {"codes": dev, "raw": host}
        assert serve.index_device_bytes(mixed) == dev.nbytes
        by = serve.index_bytes_by_tier(mixed)
        assert by == {"hbm": dev.nbytes, "host": host.nbytes}
        # dataset rides into the same split
        by2 = serve.index_bytes_by_tier({"codes": dev}, dataset=host)
        assert by2 == {"hbm": dev.nbytes, "host": host.nbytes}

    def test_admit_placement_contract(self, data):
        reg = serve.IndexRegistry(budget_bytes=1 << 30)
        # raw="hbm" demanded but the dataset is host-resident: typed
        with pytest.raises(serve.AdmissionError, match="raw"):
            reg.admit("a", object(), dataset=data,
                      placement=serve.Placement(raw="hbm"))
        # raw="host" with a device dataset: demoted at the door
        t = reg.admit("b", object(), dataset=jnp.asarray(data),
                      placement=serve.Placement(raw="host"))
        assert isinstance(t.dataset, np.ndarray)
        assert t.placement.raw == "host"
        # raw tier declared but no dataset to place
        with pytest.raises(serve.AdmissionError, match="dataset"):
            reg.admit("c", object(),
                      placement=serve.Placement(raw="host"))
        # default placement is inferred from the dataset's residency
        t2 = reg.admit("d", object(), dataset=jnp.asarray(data))
        assert t2.placement.raw == "hbm"

    def _tiered_registry(self):
        reg = serve.IndexRegistry(budget_bytes=300_000,
                                  headroom_frac=0.0)
        for name in ("a", "b"):
            reg.admit(name, object(),
                      dataset=jnp.ones((1000, 32), jnp.float32))
        return reg  # 2 × 128 kB resident of 300 kB

    def test_pressure_demotes_raw_before_evicting(self):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = self._tiered_registry()
        # 256 kB incoming against 44 kB free: BOTH residents must shed
        # their raw tier — and neither may be evicted
        reg.admit("c", object(),
                  dataset=jnp.ones((2000, 32), jnp.float32))
        for name in ("a", "b"):
            t = reg.peek(name)
            assert t.state != "evicted"
            assert t.demoted and t.placement.raw == "host"
            assert isinstance(t.dataset, np.ndarray)
            assert _label_sum(mreg, "serve.registry.demote",
                              tenant=name) == 1
        assert _label_sum(mreg, "degrade.steps", to="demote_raw",
                          site="serve.registry") == 2
        assert _label_sum(mreg, "serve.registry.evict") == 0
        # the tier gauges show the move: raw bytes now on the host side
        g = mreg.snapshot()["gauges"]
        assert g.get("index.bytes{index=a,tier=host}") == 128_000
        assert g.get("index.bytes{index=a,tier=hbm}") == 0

    def test_promote_when_pressure_clears(self):
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg = self._tiered_registry()
        reg.admit("c", object(),
                  dataset=jnp.ones((2000, 32), jnp.float32))
        assert reg.peek("a").demoted and reg.peek("b").demoted
        reg.evict("c")
        for name in ("a", "b"):
            t = reg.peek(name)
            assert not t.demoted
            assert t.placement.raw == "hbm"
            assert isinstance(t.dataset, jax.Array)
            assert _label_sum(mreg, "serve.registry.promote",
                              tenant=name) == 1

    def test_deliberate_host_placement_is_never_promoted(self, data):
        reg = serve.IndexRegistry(budget_bytes=1 << 30)
        t = reg.admit("h", object(), dataset=data,
                      placement=serve.Placement(raw="host"))
        assert not t.demoted         # chosen, not pressured
        assert reg.promote_when_clear() == []
        assert isinstance(reg.peek("h").dataset, np.ndarray)

    def test_demoted_tenant_serves_bit_exact(self, data, pq_index,
                                             monkeypatch):
        """End to end through dispatch: a demoted tenant's results are
        identical to its HBM-resident twin's, and the prefetch counters
        carry its tenant label (the serving_tenant bracket)."""
        from raft_tpu.serve.dispatch import dispatch_batch

        monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "8")
        q = jnp.asarray(data[:32])
        reg = serve.IndexRegistry(budget_bytes=1 << 30)
        reg.admit("pq", pq_index, params=REFINE_PARAMS, default_k=10,
                  dataset=jnp.asarray(data))
        d_dev, i_dev = dispatch_batch(reg.get("pq"), q, 10)
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        reg.demote_raw("pq", reason="test")
        t = reg.peek("pq")
        assert t.demoted and isinstance(t.dataset, np.ndarray)
        d_h, i_h = dispatch_batch(t, q, 10)
        np.testing.assert_array_equal(np.asarray(i_h), np.asarray(i_dev))
        np.testing.assert_array_equal(np.asarray(d_h), np.asarray(d_dev))
        assert (_label_sum(mreg, "serve.prefetch.hit", tenant="pq")
                + _label_sum(mreg, "serve.prefetch.stall",
                             tenant="pq")) == 4


# ---------------------------------------------------------------------------
# degrade ladder: the demote_raw rung
# ---------------------------------------------------------------------------

class TestDemoteRawRung:
    def test_rung_order_and_quality_exemption(self):
        names = [s.name for s in
                 degrade.standard_search_ladder(64, has_lut=True).steps]
        assert names.index("demote_raw") > names.index("fp8_lut")
        assert names.index("demote_raw") < names.index("decline_fused")
        # exact results: demote_raw must never be quality-gated
        assert "demote_raw" not in degrade.QUALITY_RUNGS

    def test_ladder_walks_to_demote_raw_exact_results(self, data,
                                                      pq_index,
                                                      monkeypatch):
        monkeypatch.setenv("RAFT_TPU_TIERED_BATCH", "8")
        q = jnp.asarray(data[:32])
        clean = ivf_pq.search(pq_index, q, 10, REFINE_PARAMS,
                              dataset=jnp.asarray(data))
        mreg = MetricsRegistry()
        obs.enable(registry=mreg, hbm=False)
        faults.install_plan({"faults": [
            {"site": "ivf_pq.search", "kind": "oom", "times": 4}]})
        d, i = ivf_pq.search_resilient(pq_index, q, 10, REFINE_PARAMS,
                                       dataset=jnp.asarray(data))
        np.testing.assert_array_equal(np.asarray(i), np.asarray(clean[1]))
        np.testing.assert_array_equal(np.asarray(d), np.asarray(clean[0]))
        assert _label_sum(mreg, "degrade.steps", to="demote_raw",
                          site="ivf_pq.search") >= 1


# ---------------------------------------------------------------------------
# observability surfaces: /indexz + obsdump
# ---------------------------------------------------------------------------

class TestObsSurfaces:
    def test_indexz_payload_shows_tiers(self, data, pq_index):
        reg = serve.IndexRegistry(budget_bytes=1 << 30)
        reg.admit("pq", pq_index, params=REFINE_PARAMS, default_k=10,
                  dataset=jnp.asarray(data))
        srv = serve.MicroBatchServer(reg)
        body = srv._indexz_payload()
        ten = body["tenants"]["pq"]
        assert ten["placement"] == {"codes": "hbm", "raw": "hbm"}
        assert ten["bytes"]["hbm"] > 0
        reg.demote_raw("pq", reason="test")
        ten = srv._indexz_payload()["tenants"]["pq"]
        assert ten["placement"]["raw"] == "host"
        assert ten["demoted"] is True
        assert ten["bytes"]["host"] == data.nbytes

    def test_obsdump_index_table_renders_tier_split(self):
        from tools.obsdump import index_table

        reg = MetricsRegistry()
        reg.gauge("index.bytes",
                  labels={"index": "a", "tier": "hbm"}).set(1 << 20)
        reg.gauge("index.bytes",
                  labels={"index": "a", "tier": "host"}).set(2 << 20)
        out = index_table(reg.snapshot())
        assert "hbm" in out and "host" in out
        row = [ln for ln in out.splitlines() if ln.strip().
               startswith("a")][0]
        assert "1.0 MiB" in row and "2.0 MiB" in row
