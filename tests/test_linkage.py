"""label / connected-components / single-linkage vs scipy references
(reference tests: cpp/test/label/label.cu, cpp/test/cluster/linkage.cu).
"""

import numpy as np
import pytest
import scipy.cluster.hierarchy as sch
import scipy.sparse as sp
import scipy.sparse.csgraph as csgraph

from raft_tpu import label as rlabel
from raft_tpu import sparse
from raft_tpu.cluster import single_linkage
from raft_tpu.sparse import ops as sops
from raft_tpu.stats.metrics import adjusted_rand_index as _ari


def adjusted_rand_index(a, b):
    n_classes = int(max(a.max(), b.max())) + 1
    return _ari(np.asarray(a), np.asarray(b), n_classes)


def test_make_monotonic():
    lab, k = rlabel.make_monotonic([5, 9, 5, 3, 9])
    assert k == 3
    np.testing.assert_array_equal(np.asarray(lab), [1, 2, 1, 0, 2])


def test_make_monotonic_ignore():
    lab, k = rlabel.make_monotonic([7, -1, 7, 2, -1], ignore=-1)
    assert k == 2
    np.testing.assert_array_equal(np.asarray(lab), [1, -1, 1, 0, -1])


def test_connected_components_vs_scipy():
    # sparse enough that multiple components exist
    rs = np.random.RandomState(0)
    a = sp.random(80, 80, density=0.006, random_state=rs, format="coo", dtype=np.float32)
    a.data[:] = 1.0
    adj = sops.symmetrize(sparse.make_coo(a.row, a.col, a.data, (80, 80)), mode="max")
    want_k, want = csgraph.connected_components(sparse.to_scipy(adj), directed=False)
    got, k = rlabel.connected_components(adj)
    assert k == want_k
    assert float(adjusted_rand_index(np.asarray(got), want)) == pytest.approx(1.0)


def test_merge_labels():
    # two labelings in vertex-id space: merging {0,1} with {1,2} unions all
    a = np.array([0, 0, 2, 3], dtype=np.int32)
    b = np.array([0, 1, 1, 3], dtype=np.int32)
    got = np.asarray(rlabel.merge_labels(a, b))
    assert got[0] == got[1] == got[2]
    assert got[3] != got[0]


def _blobs(rng, n=60, d=2, c=3, spread=0.05):
    centers = rng.random((c, d)).astype(np.float32) * 10
    pts = np.concatenate(
        [centers[i] + spread * rng.standard_normal((n // c, d)).astype(np.float32) for i in range(c)]
    )
    truth = np.repeat(np.arange(c), n // c)
    return pts, truth


def test_single_linkage_exact_vs_scipy(rng):
    pts, _ = _blobs(rng)
    # exact pairwise construction (n_neighbors >= n-1) must match scipy
    out = single_linkage(pts, n_clusters=3, metric="euclidean", n_neighbors=len(pts) - 1)
    z = sch.linkage(pts, method="single", metric="euclidean")
    want = sch.fcluster(z, t=3, criterion="maxclust")
    assert float(adjusted_rand_index(np.asarray(out.labels), want)) == pytest.approx(1.0)
    assert out.children.shape == (len(pts) - 1, 2)
    assert (np.diff(out.distances) >= -1e-6).all()  # monotone merge heights


def test_single_linkage_knn_graph(rng):
    pts, truth = _blobs(rng, n=90, c=3)
    out = single_linkage(pts, n_clusters=3, metric="euclidean", n_neighbors=8)
    assert float(adjusted_rand_index(np.asarray(out.labels), truth)) == pytest.approx(1.0)


def test_single_linkage_validates():
    with pytest.raises(ValueError):
        single_linkage(np.zeros((5, 2), np.float32), n_clusters=9)
