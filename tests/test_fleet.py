"""obs.fleet — pod-wide aggregation & straggler attribution (ISSUE 15
tentpole c).

The aggregation contract under test: per-host flight dumps carry fleet
identity (shared run_id, rank, clock anchor); ``aggregate`` merges them
onto one run-relative, pid-collision-free timeline; the straggler table
names the slowest host per collective with the right skew fraction; and
``obsdump --fleet`` / ``--slowest`` render it all. Synthetic dumps —
device-free and fast; the real end-to-end (subprocess-per-host over a
live distributed build) runs in the dryrun's MULTICHIP fleet leg.
"""

import json
import os

import pytest

from raft_tpu.obs import fleet


def _span(name, ts, dur, args=None, tid=1):
    e = {"ph": "X", "name": name, "ts": ts, "dur": dur, "tid": tid,
         "tname": "MainThread"}
    if args:
        e["args"] = args
    return e


def _dump(path, rank, pid, anchor, t0, comms_dur, run_id="runA",
          extra_events=(), counters=None):
    """One synthetic per-host flight dump: a couple of comms.allgatherv
    spans at host-local wall times (anchor + t0 …) plus extras."""
    events = [
        _span("ivf_pq.build_distributed.comms.allgatherv",
              anchor + t0, comms_dur, {"op": "allgatherv"}),
        _span("ivf_pq.build_distributed.comms.allgatherv",
              anchor + t0 + 1.0, comms_dur, {"op": "allgatherv"}),
        _span("ivf_pq.build_distributed.encode", anchor + t0 + 2.0, 0.5),
    ] + list(extra_events)
    doc = {
        "schema": "raft_tpu.flight/1",
        "reason": "fleet-test",
        "pid": pid,
        "host": f"host{rank}",
        "uptime_s": 5.0,
        "fleet": {"run_id": run_id, "host": f"host{rank}", "pid": pid,
                  "rank": rank, "anchor_wall_s": anchor,
                  "wall_s": anchor + 10.0, "mono_s": 1000.0 + rank},
        "metrics": {"counters": counters or
                    {"comms.ops{axis=shard,op=allgatherv,rank=%d}" % rank:
                     2.0},
                    "gauges": {}, "histograms": {}},
        "events": events,
        "dropped_events": 0,
        "logs": [],
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


@pytest.fixture()
def dumps(tmp_path):
    anchor = 1_000_000.0
    paths = []
    for rank in range(3):
        dur = 0.9 if rank == 2 else 0.3  # rank2 is the straggler
        paths.append(_dump(str(tmp_path / f"flight_r{rank}.json"),
                           rank, pid=500, anchor=anchor,
                           t0=0.5 + rank * 0.01, comms_dur=dur))
    return paths


class TestIdentity:
    def test_run_id_env_wins(self, monkeypatch):
        monkeypatch.setenv(fleet.RUN_ID_ENV, "shared-42")
        assert fleet.run_id() == "shared-42"
        ident = fleet.identity()
        assert ident["run_id"] == "shared-42"
        assert ident["pid"] == os.getpid()

    def test_run_id_minted_once_per_process(self, monkeypatch):
        monkeypatch.delenv(fleet.RUN_ID_ENV, raising=False)
        assert fleet.run_id() == fleet.run_id()

    def test_rank_and_anchor_parse(self, monkeypatch):
        monkeypatch.setenv(fleet.RANK_ENV, "3")
        monkeypatch.setenv(fleet.ANCHOR_ENV, "123.5")
        assert fleet.rank() == 3
        assert fleet.anchor_wall_s() == 123.5
        monkeypatch.setenv(fleet.RANK_ENV, "junk")
        assert fleet.rank() is None

    def test_host_tag(self):
        assert fleet.host_tag({"rank": 2}) == "rank2"
        assert fleet.host_tag({"host": "h", "pid": 9}) == "h:9"

    def test_flight_dump_carries_identity(self, tmp_path, monkeypatch):
        from raft_tpu.obs import flight

        monkeypatch.setenv(fleet.RUN_ID_ENV, "dump-id-1")
        flight.uninstall()
        try:
            p = flight.dump_now("t", dump_dir=str(tmp_path))
            doc = json.load(open(p))
            assert doc["fleet"]["run_id"] == "dump-id-1"
            assert doc["fleet"]["pid"] == os.getpid()
            assert doc["fleet"]["mono_s"] > 0
        finally:
            flight.uninstall()


class TestCollectiveFamily:
    def test_suffix_from_dotted_stack(self):
        assert fleet.collective_family(
            "ivf_pq.build_distributed.comms.allgatherv") \
            == "comms.allgatherv"
        assert fleet.collective_family("comms.ring_topk") \
            == "comms.ring_topk"

    def test_non_collectives_skipped(self):
        assert fleet.collective_family("serve.dispatch") is None
        assert fleet.collective_family("telecomms.foo") is None


class TestAggregate:
    def test_one_run_clock_aligned(self, dumps):
        view = fleet.aggregate(dumps)
        assert view["run_id"] == "runA"
        assert {h["tag"] for h in view["hosts"]} == \
            {"rank0", "rank1", "rank2"}
        ts = [e["ts"] for e in view["events"]]
        assert ts == sorted(ts)
        # anchor-relative: events land at ~0.5..3s, not at wall epoch
        assert all(0.0 <= t < 10.0 for t in ts), (min(ts), max(ts))

    def test_pid_collisions_remapped(self, dumps):
        view = fleet.aggregate(dumps)  # all three dumps claim pid 500
        merged = {h["merged_pid"] for h in view["hosts"]}
        assert len(merged) == 3
        assert 500 in merged

    def test_counters_sum_and_per_host_preserved(self, dumps):
        view = fleet.aggregate(dumps)
        assert sum(v for k, v in view["counters"].items()
                   if k.startswith("comms.ops")) == 6.0
        r2 = [h for h in view["hosts"] if h["tag"] == "rank2"][0]
        assert any("rank=2" in k for k in r2["counters"])

    def test_straggler_table_names_slowest(self, dumps):
        view = fleet.aggregate(dumps)
        rows = view["stragglers"]
        assert rows
        ag = rows[0]
        assert ag["collective"] == "comms.allgatherv"
        assert ag["slowest"] == "rank2"
        assert ag["hosts"] == 3 and ag["count"] == 6
        # means: (0.3, 0.3, 0.9) -> fleet 0.5, skew (0.9-0.5)/0.5 = 0.8
        assert ag["slowest_mean_s"] == pytest.approx(0.9)
        assert ag["fleet_mean_s"] == pytest.approx(0.5)
        assert ag["skew_frac"] == pytest.approx(0.8, abs=1e-3)

    def test_same_host_multiple_dumps_extend_not_replace(
            self, dumps, tmp_path):
        """A process that dumped more than once (periodic checkpoints +
        final dump) contributes ALL its events to the straggler
        computation — the second file must not replace the first."""
        extra = _dump(str(tmp_path / "flight_r2_again.json"), 2,
                      pid=501, anchor=1_000_000.0, t0=3.5,
                      comms_dur=0.9)
        view = fleet.aggregate(dumps + [extra])
        ag = view["stragglers"][0]
        assert ag["count"] == 8  # 2 per original dump x3 + 2 extra
        # rank2's mean still reflects BOTH its dumps (all 0.9s)
        assert ag["per_host_mean_s"]["rank2"] == pytest.approx(0.9)
        assert len(view["hosts"]) == 4  # one row per dump file

    def test_same_process_cumulative_dumps_dedupe(self, dumps,
                                                  tmp_path):
        """Periodic + final dumps of ONE process are cumulative
        snapshots of the same registry and ring: overlapping events
        count once, the process keeps one merged pid, and the LATEST
        counters stand in for the process (no ~2x fleet totals)."""
        # rank0's "final" dump: same host/pid as dumps[0], a superset
        # ring (its 3 events again + 1 newer) and grown counters
        anchor = 1_000_000.0
        later = str(tmp_path / "flight_r0_final.json")
        _dump(later, 0, pid=500, anchor=anchor, t0=0.5,
              comms_dur=0.3,
              extra_events=[_span(
                  "ivf_pq.build_distributed.comms.allgatherv",
                  anchor + 4.0, 0.3, {"op": "allgatherv"})],
              counters={"comms.ops{axis=shard,op=allgatherv,rank=0}":
                        3.0})
        doc = json.load(open(later))
        doc["fleet"]["wall_s"] = anchor + 20.0  # later than dumps[0]
        json.dump(doc, open(later, "w"))
        view = fleet.aggregate(dumps + [later])
        # events: 3 hosts x 3 + 1 genuinely-new = 10 (no duplicates)
        assert len(view["events"]) == 10
        r0 = [h for h in view["hosts"] if h["tag"] == "rank0"]
        assert len(r0) == 2
        assert r0[0]["merged_pid"] == r0[1]["merged_pid"]
        # counters: rank0 contributes its LATEST snapshot (3.0), not
        # the 2.0 + 3.0 double count
        assert view["counters"][
            "comms.ops{axis=shard,op=allgatherv,rank=0}"] == 3.0
        # straggler means fold the extra (deduped) allgatherv span
        ag = view["stragglers"][0]
        assert ag["count"] == 7  # 2+2+2 originals + 1 new

    def test_mixed_run_ids_surface(self, dumps, tmp_path):
        other = _dump(str(tmp_path / "flight_other.json"), 7, 900,
                      anchor=1_000_000.0, t0=0.1, comms_dur=0.1,
                      run_id="runB")
        view = fleet.aggregate(dumps + [other])
        assert view["run_id"] is None
        assert view["run_ids"] == ["runA", "runB"]

    def test_fleetless_dump_merges_without_skewing_origin(self, tmp_path):
        """A pre-ISSUE-15 dump (no fleet stamp) must neither crash the
        merge nor shift its siblings' fallback origin: the (wall −
        uptime) pairing is per dump, never positional across a
        filtered list."""
        anchor = 3_000_000.0
        new = _dump(str(tmp_path / "new.json"), 0, 1, anchor=anchor,
                    t0=0.5, comms_dur=0.2)
        doc = json.load(open(new))
        doc["fleet"]["anchor_wall_s"] = None
        doc["uptime_s"] = 1.0
        json.dump(doc, open(new, "w"))
        old = str(tmp_path / "old.json")
        json.dump({"schema": "raft_tpu.flight/1", "reason": "legacy",
                   "pid": 77, "host": "oldhost", "uptime_s": 500.0,
                   "metrics": {"counters": {}, "gauges": {},
                               "histograms": {}},
                   "events": [], "dropped_events": 0, "logs": []},
                  open(old, "w"))
        view = fleet.aggregate([old, new])
        assert len(view["hosts"]) == 2
        ts = [e["ts"] for e in view["events"]]
        # origin = new dump's (wall − uptime) = anchor + 9; events at
        # anchor + 0.5.. land slightly NEGATIVE of it — never ~500 s
        # off (the mismatched-zip bug this guards against)
        assert all(abs(t) < 30.0 for t in ts), ts

    def test_anchorless_dump_falls_back(self, tmp_path):
        p = _dump(str(tmp_path / "f.json"), 0, 1, anchor=2_000_000.0,
                  t0=0.5, comms_dur=0.2)
        doc = json.load(open(p))
        doc["fleet"]["anchor_wall_s"] = None
        json.dump(doc, open(p, "w"))
        view = fleet.aggregate([p])
        ts = [e["ts"] for e in view["events"]]
        # aligned against (wall - uptime): small nonnegative offsets
        assert all(-10.0 <= t <= 20.0 for t in ts), ts

    def test_empty(self):
        view = fleet.aggregate([])
        assert view["hosts"] == [] and view["stragglers"] == []


class TestExportChrome:
    def test_perfetto_loadable(self, dumps, tmp_path):
        view = fleet.aggregate(dumps)
        out = str(tmp_path / "pod.json")
        n = fleet.export_chrome(view, out)
        doc = json.load(open(out))
        assert n == len(doc["traceEvents"])
        names = {e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        assert names == {"rank0", "rank1", "rank2"}
        assert all("ts" in e for e in doc["traceEvents"]
                   if e.get("ph") == "X")


class TestObsdumpFleet:
    def test_fleet_render_and_merge(self, dumps, tmp_path, capsys):
        from tools import obsdump

        out = str(tmp_path / "merged.json")
        rc = obsdump.main(["--fleet", *dumps, "--merge", out])
        assert rc == 0
        text = capsys.readouterr().out
        assert "stragglers" in text
        assert "rank2" in text and "comms.allgatherv" in text
        assert os.path.exists(out)

    def test_flight_header_shows_fleet_identity(self, dumps, capsys):
        from tools import obsdump

        assert obsdump.main([dumps[2]]) == 0
        text = capsys.readouterr().out
        assert "run_id=runA" in text and "rank=2" in text
