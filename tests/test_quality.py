"""obs.quality — online recall estimation (ISSUE 16 tentpole a).

The shadow-verifier contract under test: Wilson intervals behave at the
edges, the exact host replay agrees with brute force per metric,
half-filled answers count against recall, the sampling pattern replays
deterministically from the seed (crc32 tenant seeding — never salted
str hash), a burst hits the token bucket and the bounded reservoir
instead of growing memory, an admission-declined replay NEVER touches
the dataset, and the verifier's ``state()`` feeds the flight dump's
``"quality"`` section with trace-id-carrying verdicts. Device-free —
nothing here imports jax.
"""

import threading

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.obs.quality import (RecallVerifier, VerifierConfig,
                                  exact_topk_ids, recall_at_k,
                                  wilson_interval)


class _FakeTenant:
    def __init__(self, name, dataset, metric="sqeuclidean",
                 recall_floor=None):
        self.name = name
        self.dataset = dataset
        self.index = type("I", (), {"metric": metric})()
        self.recall_floor = recall_floor


class _FakeRegistry:
    """Duck-typed stand-in: peek / usable_bytes / resident_bytes."""

    def __init__(self, tenants, usable=1 << 30, resident=0):
        self._tenants = {t.name: t for t in tenants}
        self.usable_bytes = usable
        self._resident = resident

    def peek(self, name):
        if name not in self._tenants:
            raise KeyError(name)
        return self._tenants[name]

    def resident_bytes(self):
        return self._resident

    def resident(self):
        return list(self._tenants.values())


@pytest.fixture(autouse=True)
def _quiet_obs():
    yield
    obs.disable()


class TestWilson:
    def test_degenerate_total(self):
        assert wilson_interval(0, 0) == (0.0, 1.0)

    def test_contains_p_hat_and_stays_in_unit(self):
        lo, hi = wilson_interval(9, 10)
        assert 0.0 <= lo < 0.9 < hi <= 1.0

    def test_perfect_recall_interval_below_one(self):
        # the reason for Wilson over normal approx: p̂=1 still yields a
        # non-degenerate lower bound that tightens with n
        lo10, hi10 = wilson_interval(10, 10)
        lo100, _ = wilson_interval(100, 100)
        assert hi10 == 1.0 and 0.0 < lo10 < 1.0
        assert lo100 > lo10

    def test_more_evidence_tightens(self):
        lo1, hi1 = wilson_interval(8, 10)
        lo2, hi2 = wilson_interval(80, 100)
        assert (hi2 - lo2) < (hi1 - lo1)


class TestExactTopK:
    @pytest.fixture(scope="class")
    def data(self):
        rng = np.random.default_rng(7)
        return rng.normal(size=(500, 16)).astype(np.float32)

    def test_l2_matches_bruteforce(self, data):
        q = data[3] + 0.01
        d = np.sum((data - q) ** 2, axis=1)
        expect = np.argsort(d, kind="stable")[:10]
        got = exact_topk_ids(data, q, 10, "sqeuclidean")
        assert set(got.tolist()) == set(expect.tolist())
        assert got[0] == 3  # the (near-)self row wins

    def test_l2_flavors_share_ordering(self, data):
        q = data[11]
        a = exact_topk_ids(data, q, 8, "sqeuclidean")
        b = exact_topk_ids(data, q, 8, "l2_expanded")
        c = exact_topk_ids(data, q, 8, "l2_sqrt_expanded")
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)

    def test_inner_product_maximizes(self, data):
        q = data[0]
        scores = data @ q
        expect = np.argsort(-scores, kind="stable")[:5]
        got = exact_topk_ids(data, q, 5, "inner_product")
        np.testing.assert_array_equal(got, expect)

    def test_cosine_normalizes_rows(self, data):
        # scale one row hugely: inner product would rank it first,
        # cosine must not care
        x = data.copy()
        x[42] *= 1e4
        q = data[17]
        ip = exact_topk_ids(x, q, 5, "inner_product")
        cos = exact_topk_ids(x, q, 5, "cosine")
        norm = x / np.linalg.norm(x, axis=1, keepdims=True)
        expect = np.argsort(-(norm @ q), kind="stable")[:5]
        assert 42 == ip[0]
        np.testing.assert_array_equal(cos, expect)

    def test_k_clamped_to_rows(self, data):
        got = exact_topk_ids(data[:3], data[0], 10, "sqeuclidean")
        assert got.shape == (3,)


class TestRecallAtK:
    def test_exact_match(self):
        assert recall_at_k(np.array([1, 2, 3]), np.array([3, 2, 1]), 3) \
            == 1.0

    def test_partial_overlap(self):
        assert recall_at_k(np.array([1, 2, 9]), np.array([1, 2, 3]), 3) \
            == pytest.approx(2 / 3)

    def test_pad_counts_against_recall(self):
        # a half-filled answer IS a quality failure: -1 pads never match
        assert recall_at_k(np.array([1, -1, -1]),
                           np.array([1, 2, 3]), 3) == pytest.approx(1 / 3)

    def test_served_longer_than_k_is_truncated(self):
        # only the first k served ids count: 9, 8, 1 vs {1, 2, 3}
        assert recall_at_k(np.array([9, 8, 1, 2, 3]),
                           np.array([1, 2, 3]), 3) == pytest.approx(1 / 3)


class TestSampling:
    def _pattern(self, seed, n=200, fraction=0.25):
        reg = _FakeRegistry([])
        v = RecallVerifier(reg, VerifierConfig(
            sample_fraction=fraction, rate_limit_per_s=0.0,
            reservoir_depth=1 << 20, seed=seed))
        q = np.zeros(4, np.float32)
        ids = np.arange(3)
        return [v.maybe_sample("acme", q, 3, ids, f"t{i}")
                for i in range(n)]

    def test_deterministic_per_seed(self):
        # crc32 tenant seeding: the accept pattern replays exactly —
        # str hash() is process-salted and would break this
        a = self._pattern(seed=5)
        b = self._pattern(seed=5)
        assert a == b
        assert any(a) and not all(a)

    def test_different_seed_different_pattern(self):
        assert self._pattern(seed=5) != self._pattern(seed=6)

    def test_zero_fraction_never_samples(self):
        assert not any(self._pattern(seed=0, fraction=0.0))

    def test_rate_limit_bounds_a_burst(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        v = RecallVerifier(_FakeRegistry([]), VerifierConfig(
            sample_fraction=1.0, rate_limit_per_s=1.0,
            reservoir_depth=1 << 20, seed=0))
        q = np.zeros(4, np.float32)
        taken = sum(v.maybe_sample("acme", q, 3, np.arange(3), f"t{i}")
                    for i in range(100))
        # one token of burst capacity, negligible refill in-loop
        assert taken <= 2
        c = obs.registry().snapshot()["counters"]
        assert c["quality.skipped{reason=rate_limit,tenant=acme}"] >= 98

    def test_reservoir_bounds_memory_under_burst(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        v = RecallVerifier(_FakeRegistry([]), VerifierConfig(
            sample_fraction=1.0, rate_limit_per_s=0.0,
            reservoir_depth=8, seed=0))
        q = np.zeros(4, np.float32)
        for i in range(100):
            v.maybe_sample("acme", q, 3, np.arange(3), f"t{i}")
        assert len(v._pending) == 8
        c = obs.registry().snapshot()["counters"]
        assert c["quality.skipped{reason=reservoir,tenant=acme}"] == 92

    def test_sample_copies_not_views(self):
        # the serving loop reuses its buffers: the sample must hold its
        # own copies, not views that mutate under the worker
        v = RecallVerifier(_FakeRegistry([]), VerifierConfig(
            sample_fraction=1.0, rate_limit_per_s=0.0, seed=0))
        q = np.ones(4, np.float32)
        ids = np.arange(3)
        assert v.maybe_sample("acme", q, 3, ids, "t0")
        q[:] = -1.0
        ids[:] = -1
        item = v._pending[0]
        assert item["query"].tolist() == [1.0] * 4
        assert item["ids"].tolist() == [0, 1, 2]


class _Poison:
    """A dataset stand-in that explodes if anything materializes it."""

    nbytes = 1 << 40

    def __array__(self, *a, **kw):
        raise AssertionError("admission-declined replay touched the "
                             "dataset")


class TestVerify:
    def _mk(self, dataset, usable=1 << 30, resident=0, metric="sqeuclidean"):
        tenant = _FakeTenant("acme", dataset, metric=metric)
        reg = _FakeRegistry([tenant], usable=usable, resident=resident)
        return RecallVerifier(reg, VerifierConfig(
            sample_fraction=1.0, rate_limit_per_s=0.0, seed=0))

    def test_verify_publishes_gauges_and_verdicts(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(200, 8)).astype(np.float32)
        v = self._mk(x)
        true = exact_topk_ids(x, x[5], 4, "sqeuclidean")
        served = true.copy()
        served[-1] = 199 if true[-1] != 199 else 198  # one wrong answer
        v._verify({"tenant": "acme", "k": 4, "query": x[5],
                   "ids": served, "trace_id": "trace-1"})
        g = obs.registry().snapshot()["gauges"]
        assert g["quality.recall{k=4,tenant=acme}"] == pytest.approx(0.75)
        assert g["quality.recall_ci_low{k=4,tenant=acme}"] < 0.75
        assert g["quality.recall_ci_high{k=4,tenant=acme}"] > 0.75
        assert g["quality.samples{k=4,tenant=acme}"] == 1.0
        # the worst-recall exemplar ride: the loss histogram retains
        # the verdict's trace id
        h = obs.registry().snapshot()["histograms"][
            "quality.recall_loss{tenant=acme}"]
        tids = [e["trace_id"] for res in h["exemplars"].values()
                for e in res]
        assert "trace-1" in tids
        assert v.recall_summary("acme")[4]["n"] == 1.0

    def test_admission_declined_never_touches_dataset(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        v = self._mk(_Poison(), usable=1 << 20, resident=0)
        v._verify({"tenant": "acme", "k": 3,
                   "query": np.zeros(4, np.float32),
                   "ids": np.arange(3), "trace_id": "t"})
        c = obs.registry().snapshot()["counters"]
        assert c["quality.skipped{reason=admission,tenant=acme}"] == 1.0
        assert v.recall_summary("acme") == {}

    def test_numpy_dataset_needs_no_headroom(self):
        # host-resident datasets transfer nothing: a full chip must not
        # block their replays
        obs.enable(registry=MetricsRegistry(), hbm=False)
        x = np.zeros((50, 4), np.float32)
        v = self._mk(x, usable=0, resident=0)
        v._verify({"tenant": "acme", "k": 3, "query": x[0],
                   "ids": np.array([0, 1, 2]), "trace_id": "t"})
        assert v.recall_summary("acme")[3]["n"] == 1.0

    def test_missing_tenant_and_dataset_count_skips(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        v = self._mk(None)
        v._verify({"tenant": "ghost", "k": 3,
                   "query": np.zeros(4, np.float32),
                   "ids": np.arange(3), "trace_id": "t"})
        v._verify({"tenant": "acme", "k": 3,
                   "query": np.zeros(4, np.float32),
                   "ids": np.arange(3), "trace_id": "t"})
        c = obs.registry().snapshot()["counters"]
        assert c["quality.skipped{reason=tenant_gone,tenant=ghost}"] == 1.0
        assert c["quality.skipped{reason=no_dataset,tenant=acme}"] == 1.0

    def test_worker_drains_in_background(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(100, 8)).astype(np.float32)
        v = self._mk(x)
        v.start()
        try:
            done = threading.Event()
            v.on_verdict = lambda t: done.set()
            assert v.maybe_sample(
                "acme", x[3], 5,
                exact_topk_ids(x, x[3], 5, "sqeuclidean"), "t0")
            assert done.wait(timeout=5.0), "worker never verified"
        finally:
            v.stop()
        assert v.recall_summary("acme")[5]["recall"] == 1.0

    def test_state_feeds_flight_section(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        rng = np.random.default_rng(2)
        x = rng.normal(size=(80, 8)).astype(np.float32)
        v = self._mk(x)
        for i in range(3):
            v._verify({"tenant": "acme", "k": 4, "query": x[i],
                       "ids": exact_topk_ids(x, x[i], 4, "sqeuclidean"),
                       "trace_id": f"trace-{i}"})
        st = v.state()
        assert st["verified_total"] == 3
        assert st["tenants"]["acme"]["4"]["recall"] == 1.0
        assert [d["trace_id"] for d in st["verdicts"]] \
            == ["trace-0", "trace-1", "trace-2"]
        assert st["config"]["sample_fraction"] == 1.0
        import json

        json.dumps(st)  # flight dumps serialize it verbatim
