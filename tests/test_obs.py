"""Observability layer: metrics registry, span timers, HBM telemetry,
staged search, and the no-overhead-when-disabled contract
(ISSUE 1 acceptance; see docs/observability.md)."""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.core import tracing
from raft_tpu.neighbors import ivf_pq
from raft_tpu.obs import hbm
from raft_tpu.obs.metrics import MetricsRegistry


@pytest.fixture(autouse=True)
def _obs_clean():
    """Spans/registries are process-global state — leave none behind."""
    yield
    obs.disable()
    obs.get_registry().reset()


@pytest.fixture(scope="module")
def pq_index():
    rng = np.random.default_rng(0)
    x = rng.random((4000, 32), dtype=np.float32)
    q = rng.random((200, 32), dtype=np.float32)
    idx = ivf_pq.build(x, ivf_pq.IndexParams(
        n_lists=32, pq_dim=16, seed=0, cache_reconstruction="never"))
    return idx, jnp.asarray(q)


class TestMetricsRegistry:
    def test_counter_math_and_labels(self):
        r = MetricsRegistry()
        r.inc("reqs")
        r.inc("reqs", 2.5)
        r.inc("reqs", 1, labels={"algo": "ivf_pq"})
        r.inc("reqs", 2, labels={"algo": "ivf_pq"})
        snap = r.snapshot()
        assert snap["counters"]["reqs"] == 3.5
        assert snap["counters"]["reqs{algo=ivf_pq}"] == 3.0
        with pytest.raises(ValueError):
            r.counter("reqs").inc(-1)

    def test_label_order_is_canonical(self):
        r = MetricsRegistry()
        r.inc("c", 1, labels={"a": "1", "b": "2"})
        r.inc("c", 1, labels={"b": "2", "a": "1"})  # same series
        assert r.snapshot()["counters"]["c{a=1,b=2}"] == 2.0

    def test_gauge_set_and_max(self):
        r = MetricsRegistry()
        r.set("g", 5)
        r.set("g", 3)
        assert r.snapshot()["gauges"]["g"] == 3.0
        r.gauge("peak").max(10)
        r.gauge("peak").max(7)  # high-water keeps 10
        assert r.snapshot()["gauges"]["peak"] == 10.0

    def test_histogram_math(self):
        r = MetricsRegistry()
        h = r.histogram("lat", buckets=[0.01, 0.1, 1.0])
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        st = h.state()
        assert st["count"] == 4
        assert st["sum"] == pytest.approx(5.555)
        assert st["min"] == 0.005 and st["max"] == 5.0
        assert st["mean"] == pytest.approx(5.555 / 4)
        # cumulative buckets: ≤0.01 → 1, ≤0.1 → 2, ≤1.0 → 3, +inf → 4
        assert st["buckets"]["0.01"] == 1
        assert st["buckets"]["0.1"] == 2
        assert st["buckets"]["1.0"] == 3
        assert st["buckets"]["+inf"] == 4

    def test_counter_thread_safety(self):
        r = MetricsRegistry()

        def work():
            for _ in range(1000):
                r.inc("n")

        threads = [threading.Thread(target=work) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert r.snapshot()["counters"]["n"] == 8000.0

    def test_jsonl_round_trip(self, tmp_path):
        r = MetricsRegistry()
        r.inc("c", 2, labels={"x": "1"})
        r.set("g", 7.5)
        r.observe("h", 0.02)
        path = str(tmp_path / "metrics.jsonl")
        n = r.dump_jsonl(path, extra={"run": "t0"})
        assert n == 3
        rows = obs.load_jsonl(path)
        by = {(row["kind"], row["name"]): row for row in rows}
        assert by[("counter", "c")]["value"] == 2.0
        assert by[("counter", "c")]["labels"] == {"x": "1"}
        assert by[("gauge", "g")]["value"] == 7.5
        assert by[("histogram", "h")]["count"] == 1
        assert by[("histogram", "h")]["sum"] == pytest.approx(0.02)
        assert all(row["run"] == "t0" for row in rows)
        # appends (the bench writes one block per measured row)
        r.dump_jsonl(path)
        assert len(obs.load_jsonl(path)) == 6
        # every line is self-contained JSON
        with open(path) as f:
            for line in f:
                json.loads(line)

    def test_reset_and_set_registry(self):
        r = MetricsRegistry()
        r.inc("a")
        r.reset()
        assert r.snapshot() == {"counters": {}, "gauges": {},
                                "histograms": {}}
        prev = obs.set_registry(r)
        try:
            assert obs.get_registry() is r
        finally:
            obs.set_registry(prev)


class TestHistogramQuantileEdges:
    """Edge cases of ``Histogram.quantile`` / ``quantile_from_state`` —
    the values benchdiff's noise model reads off the recorded bench
    reps, so the degenerate shapes (empty, single sample, single
    bucket) must degrade predictably instead of interpolating junk."""

    def test_empty_histogram_returns_none(self):
        h = MetricsRegistry().histogram("h")
        assert h.quantile(0.5) is None
        assert obs.quantile_from_state(h.state(), 0.5) is None

    def test_single_sample_every_q_is_the_sample(self):
        h = MetricsRegistry().histogram("h", buckets=[1.0, 10.0])
        h.observe(3.5)
        for q in (0.0, 0.25, 0.5, 0.99, 1.0):
            # min/max clamping pins every quantile to the one value
            assert h.quantile(q) == pytest.approx(3.5)

    def test_single_bucket_histogram_clamps_to_observed_range(self):
        h = MetricsRegistry().histogram("h", buckets=[100.0])
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        # one giant bucket: interpolation alone would sweep [0, 100];
        # the min/max clamps keep estimates inside the data
        assert 1.0 <= h.quantile(0.5) <= 4.0
        assert h.quantile(0.0) == pytest.approx(1.0)
        assert h.quantile(1.0) == pytest.approx(4.0)

    def test_q_clamping_outside_unit_interval(self):
        h = MetricsRegistry().histogram("h", buckets=[1.0, 2.0, 4.0])
        for v in (0.5, 1.5, 3.0):
            h.observe(v)
        # q outside [0, 1] clamps to the endpoints rather than raising
        assert h.quantile(-0.5) == h.quantile(0.0)
        assert h.quantile(2.0) == h.quantile(1.0)
        assert h.quantile(0.0) == pytest.approx(0.5)
        assert h.quantile(1.0) == pytest.approx(3.0)

    def test_all_samples_in_overflow_bucket(self):
        h = MetricsRegistry().histogram("h", buckets=[1.0])
        h.observe(5.0)
        h.observe(7.0)
        # +inf bucket has no upper bound to interpolate against: the
        # estimate falls back to the observed max
        assert h.quantile(0.99) == pytest.approx(7.0)
        assert h.quantile(0.5) == pytest.approx(7.0)

    def test_state_without_buckets_degrades(self):
        # hand-built state (a flight dump from a foreign process might
        # carry a truncated histogram): no buckets → max fallback
        st = {"count": 3, "sum": 6.0, "min": 1.0, "max": 3.0,
              "mean": 2.0, "buckets": {}}
        assert obs.quantile_from_state(st, 0.5) == 3.0


class TestSpans:
    def test_nested_spans_dot_join(self):
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        with tracing.span("search"):
            with tracing.span("scan"):
                pass
            with tracing.span("scan"):
                pass
        obs.disable()
        h = reg.snapshot()["histograms"]
        assert h["span.search"]["count"] == 1
        assert h["span.search.scan"]["count"] == 2
        assert h["span.search.scan"]["sum"] >= 0

    def test_no_record_on_exception(self):
        # a raising block yields a truncated duration — it must not mix
        # into the same series as successful samples, and the stack must
        # still unwind
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        with pytest.raises(RuntimeError):
            with tracing.span("boom"):
                raise RuntimeError("x")
        obs.disable()
        assert "span.boom" not in reg.snapshot()["histograms"]
        assert obs.current_name() == ""

    def test_disabled_spans_record_nothing(self):
        assert not obs.enabled()
        with tracing.span("ghost") as sp:
            sp.attach(jnp.arange(3))
        assert "span.ghost" not in obs.get_registry().snapshot()["histograms"]

    def test_sync_mode_blocks_on_attached(self, monkeypatch):
        blocked = []
        real = jax.block_until_ready
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda x: blocked.append(x) or real(x))
        reg = MetricsRegistry()
        obs.enable(sync=True, registry=reg, hbm=False)
        with tracing.span("s") as sp:
            sp.attach(jnp.arange(8) * 2)
        obs.disable()
        assert len(blocked) == 1
        assert reg.snapshot()["histograms"]["span.s"]["count"] == 1

    def test_spans_skip_under_jit_trace(self):
        reg = MetricsRegistry()
        obs.enable(sync=True, registry=reg, hbm=False)

        @jax.jit
        def f(x):
            # a span inside a traced function must not record (it would
            # measure trace time once) nor block on tracers
            with tracing.span("inside_jit") as sp:
                y = x * 2
                sp.attach(y)
                return y

        np.testing.assert_array_equal(np.asarray(f(jnp.arange(4))),
                                      [0, 2, 4, 6])
        obs.disable()
        assert "span.inside_jit" not in reg.snapshot()["histograms"]

    def test_traced_records_span_when_enabled(self):
        reg = MetricsRegistry()

        @tracing.traced("raft_tpu.test.traced_span")
        def f(x):
            return x + 1

        assert f(1) == 2  # disabled: no record
        obs.enable(registry=reg, hbm=False)
        assert f(1) == 2
        obs.disable()
        h = reg.snapshot()["histograms"]
        assert h["span.test.traced_span"]["count"] == 1


class TestHbm:
    def test_helpers_degrade_without_allocator_stats(self):
        # CPU backend reports nothing; all helpers must not raise
        stats = hbm.device_memory_stats()
        assert isinstance(stats, dict)
        assert hbm.bytes_limit(default=123) == (
            123 if "bytes_limit" not in stats else int(stats["bytes_limit"]))
        biu = hbm.bytes_in_use()
        assert biu is None or isinstance(biu, int)

    def test_sample_writes_gauges_only_when_reported(self):
        reg = MetricsRegistry()
        stats = hbm.sample(reg)
        gauges = reg.snapshot()["gauges"]
        if stats.get("bytes_in_use") is not None:
            assert gauges["hbm.bytes_in_use"] == stats["bytes_in_use"]
        else:
            assert "hbm.bytes_in_use" not in gauges

    def test_sample_covers_all_local_devices(self, monkeypatch):
        # sharded runs must see EVERY chip's HBM, not just device 0 —
        # fake a 2-device backend that reports allocator stats
        class FakeDev:
            def __init__(self, n):
                self._n = n

            def memory_stats(self):
                return {"bytes_in_use": 100 * self._n,
                        "peak_bytes_in_use": 200 * self._n,
                        "bytes_limit": 1000}

        monkeypatch.setattr(hbm, "_local_devices",
                            lambda: [FakeDev(1), FakeDev(2)])
        reg = MetricsRegistry()
        stats = hbm.sample(reg)
        assert stats["bytes_in_use"] == 100  # device 0's dict returned
        gauges = reg.snapshot()["gauges"]
        assert gauges["hbm.bytes_in_use{device=0}"] == 100
        assert gauges["hbm.bytes_in_use{device=1}"] == 200
        assert gauges["hbm.peak_bytes{device=1}"] == 400
        # unlabeled back-compat series mirrors device 0 (bench peak col)
        assert gauges["hbm.bytes_in_use"] == 100
        assert gauges["hbm.peak_bytes"] == 200

    def test_sample_mixed_reporting_devices(self, monkeypatch):
        # a device mid-outage (stats -> {}) must not hide the others
        class Dead:
            def memory_stats(self):
                raise RuntimeError("transport down")

        class Live:
            def memory_stats(self):
                return {"bytes_in_use": 7}

        monkeypatch.setattr(hbm, "_local_devices", lambda: [Dead(), Live()])
        reg = MetricsRegistry()
        stats = hbm.sample(reg)
        assert stats == {}  # device 0 degraded
        gauges = reg.snapshot()["gauges"]
        assert "hbm.bytes_in_use{device=0}" not in gauges
        assert gauges["hbm.bytes_in_use{device=1}"] == 7
        assert "hbm.bytes_in_use" not in gauges  # unlabeled = device 0

    def test_sample_records_counter_events(self, monkeypatch):
        from raft_tpu.obs import trace

        class Dev:
            def memory_stats(self):
                return {"bytes_in_use": 11, "peak_bytes_in_use": 13}

        monkeypatch.setattr(hbm, "_local_devices", lambda: [Dev()])
        buf = trace.EventBuffer()
        hbm.sample(MetricsRegistry(), events=buf)
        names = {e["name"] for e in buf.snapshot()}
        assert "hbm.bytes_in_use{device=0}" in names


class TestDeviceResourcesMetrics:
    def test_handle_hands_out_global_registry(self):
        from raft_tpu.core.resources import DeviceResources

        h = DeviceResources()
        assert h.metrics is obs.get_registry()
        mine = MetricsRegistry()
        h.set_metrics(mine)
        assert h.metrics is mine
        assert isinstance(h.memory_stats(), dict)

    def test_handle_follows_enable_registry_override(self):
        # handle metrics must land in the same sink spans record into,
        # including a temporary obs.enable(registry=...) override (the
        # bench's per-row capture)
        from raft_tpu.core.resources import DeviceResources

        h = DeviceResources()
        mine = MetricsRegistry()
        obs.enable(registry=mine, hbm=False)
        try:
            assert h.metrics is mine
        finally:
            obs.disable()
        assert h.metrics is obs.get_registry()

    def test_handle_follows_global_registry_swap(self):
        # regression: the handle must resolve the global registry per
        # access, not cache the one current at first read — otherwise
        # h.metrics goes stale after the bench swaps in a fresh registry
        from raft_tpu.core.resources import DeviceResources

        h = DeviceResources()
        assert h.metrics is obs.get_registry()  # read once (would cache)
        fresh = MetricsRegistry()
        prev = obs.set_registry(fresh)
        try:
            assert h.metrics is fresh
        finally:
            obs.set_registry(prev)


class TestEnvFlag:
    def test_falsy_strings_mean_off(self, monkeypatch):
        for v in ("0", "false", "False", "off", "no", ""):
            monkeypatch.setenv("RAFT_TPU_TEST_FLAG", v)
            assert not obs.env_flag("RAFT_TPU_TEST_FLAG"), v
        for v in ("1", "true", "yes", "on"):
            monkeypatch.setenv("RAFT_TPU_TEST_FLAG", v)
            assert obs.env_flag("RAFT_TPU_TEST_FLAG"), v
        monkeypatch.delenv("RAFT_TPU_TEST_FLAG")
        assert not obs.env_flag("RAFT_TPU_TEST_FLAG")


class TestSelectKDispatchCounter:
    def test_counts_dispatch_decisions(self):
        from raft_tpu.matrix.select_k import select_k

        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        select_k(jnp.arange(100.0).reshape(2, 50), 5)
        obs.disable()
        counters = reg.snapshot()["counters"]
        assert any(n.startswith("select_k.dispatch{") for n in counters), \
            counters


class TestStagedSearch:
    def test_staged_matches_per_query(self, pq_index):
        idx, q = pq_index
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")
        d0, i0 = ivf_pq.search(idx, q, 10, sp)
        d1, i1 = ivf_pq.search_staged(idx, q, 10, sp)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1),
                                   rtol=1e-5, atol=1e-5)

    def test_stage_mode_routes_search_and_records_stages(self, pq_index):
        idx, q = pq_index
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")
        d0, i0 = ivf_pq.search(idx, q, 10, sp)
        reg = MetricsRegistry()
        obs.enable(sync=True, stages=True, registry=reg)
        d1, i1 = ivf_pq.search(idx, q, 10, sp)
        obs.disable()
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        h = reg.snapshot()["histograms"]
        for stage in ("span.ivf_pq.search.coarse_quantize",
                      "span.ivf_pq.search.lut",
                      "span.ivf_pq.search.scan",
                      "span.ivf_pq.search"):
            assert h[stage]["count"] == 1, stage
            assert h[stage]["sum"] > 0

    def test_stage_mode_not_baked_into_outer_jit(self, pq_index):
        # regression: inside a user's jax.jit trace, stage mode must NOT
        # route to search_staged — the staged path would be baked into
        # the caller's jit cache and outlive obs.disable()
        idx, q = pq_index
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")
        d0, i0 = ivf_pq.search(idx, q, 10, sp)
        reg = MetricsRegistry()
        obs.enable(sync=True, stages=True, registry=reg)
        d1, i1 = jax.jit(lambda qq: ivf_pq.search(idx, qq, 10, sp))(q)
        obs.disable()
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        assert "span.ivf_pq.search.scan" not in reg.snapshot()["histograms"]

    def test_staged_rejects_per_cluster(self, rng):
        x = rng.random((600, 16), dtype=np.float32)
        idx = ivf_pq.build(x, ivf_pq.IndexParams(
            n_lists=8, pq_dim=8, codebook_kind="per_cluster", seed=0,
            cache_reconstruction="never"))
        from raft_tpu.core.errors import LogicError

        with pytest.raises(LogicError):
            ivf_pq.search_staged(idx, jnp.asarray(x[:4]), 5)
        # ...but stage-mode search() still works (falls back to fused)
        obs.enable(stages=True, hbm=False)
        d, i = ivf_pq.search(idx, jnp.asarray(x[:4]), 5,
                             ivf_pq.SearchParams(n_probes=4))
        obs.disable()
        assert np.asarray(i).shape == (4, 5)


class TestNoOverheadWhenDisabled:
    """ISSUE 1 acceptance (extended by ISSUE 5 to the event recorder
    and the instrumented collectives): with observability disabled, the
    instrumented paths add no sync points, record no events, count no
    comm traffic, and cost <2% wall time."""

    def test_disabled_search_records_no_events(self, pq_index):
        # ISSUE 5: the event-recording hook in span.__exit__ must stay
        # behind the enable flag — a disabled search leaves the ring
        # buffer untouched
        from raft_tpu.obs import trace

        idx, q = pq_index
        assert not obs.enabled()
        buf = trace.EventBuffer()
        prev = trace.set_buffer(buf)
        try:
            ivf_pq.search(idx, q, 10,
                          ivf_pq.SearchParams(n_probes=8,
                                              scan_mode="per_query"))
        finally:
            trace.set_buffer(prev)
        assert len(buf) == 0

    def test_disabled_collectives_count_nothing(self):
        # ISSUE 5: instrumented comms must be free when obs is off —
        # no comms.* series appear anywhere, no events recorded
        from jax.sharding import PartitionSpec as P

        from raft_tpu.core.compat import shard_map
        from raft_tpu.obs import trace
        from raft_tpu.parallel import Comms, make_mesh

        assert not obs.enabled()
        mesh = make_mesh(axis_names=("shard",))
        comms = Comms("shard")
        buf = trace.EventBuffer()
        prev = trace.set_buffer(buf)
        try:
            out = shard_map(
                lambda v: comms.allgather(comms.allreduce(v)),
                mesh=mesh, in_specs=(P("shard"),),
                out_specs=P("shard", None), check_vma=False,
            )(jnp.arange(8, dtype=jnp.float32))
            jax.block_until_ready(out)
        finally:
            trace.set_buffer(prev)
        counters = obs.get_registry().snapshot()["counters"]
        assert not any(n.startswith("comms.") for n in counters), counters
        assert len(buf) == 0

    def test_no_block_until_ready_from_span_code(self, monkeypatch,
                                                 pq_index):
        idx, q = pq_index
        assert not obs.enabled()
        calls = []
        monkeypatch.setattr(jax, "block_until_ready",
                            lambda x: calls.append(type(x)) or x)
        d, i = ivf_pq.search(idx, q, 10,
                             ivf_pq.SearchParams(n_probes=8,
                                                 scan_mode="per_query"))
        np.asarray(i)  # consume without block_until_ready
        assert calls == [], "span code introduced a sync point"

    def test_disabled_overhead_under_2pct(self, pq_index):
        idx, q = pq_index
        assert not obs.enabled()
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="per_query")
        ivf_pq.search(idx, q, 10, sp)  # warm the jit cache

        # cost of one disabled span enter/exit
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with tracing.span("overhead_probe"):
                pass
        per_span = (time.perf_counter() - t0) / n

        reps = 10
        t0 = time.perf_counter()
        for _ in range(reps):
            d, i = ivf_pq.search(idx, q, 10, sp)
        jax.block_until_ready(i)
        per_search = (time.perf_counter() - t0) / reps

        # the instrumented path opens a handful of spans per search;
        # 32 is a generous over-estimate
        assert 32 * per_span < 0.02 * per_search, (
            f"disabled span cost {per_span * 1e6:.2f}µs × 32 exceeds 2% "
            f"of a {per_search * 1e3:.2f}ms search")
