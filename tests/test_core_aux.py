"""Core auxiliary subsystems: tracing, interruptible, resources manager
(reference: core/nvtx.hpp, core/interruptible.hpp,
core/device_resources_manager.hpp)."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import interruptible
from raft_tpu.core.resources import DeviceResourcesManager
from raft_tpu.core.tracing import traced


class TestTracing:
    def test_traced_preserves_behavior(self):
        @traced("raft_tpu.test.double")
        def double(x):
            return x * 2

        out = double(jnp.asarray([1.0, 2.0]))
        np.testing.assert_array_equal(np.asarray(out), [2.0, 4.0])
        assert double.__name__ == "double"

    def test_traced_works_bare_and_with_parens(self):
        # regression: @traced without parentheses must behave like
        # @traced() (the name falls back to the qualname)
        @traced
        def bare(x):
            return x + 1

        @traced()
        def empty_parens(x):
            return x + 2

        assert bare(1) == 2
        assert empty_parens(1) == 3
        assert bare.__name__ == "bare"
        assert hasattr(bare, "__wrapped__")
        assert hasattr(empty_parens, "__wrapped__")

    def test_public_apis_are_traced(self):
        from raft_tpu.matrix import select_k
        from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq

        # the decorator keeps wrappers' metadata; presence is visible via
        # __wrapped__ (functools.wraps sets it)
        for fn in (select_k, brute_force.knn, ivf_flat.search,
                   ivf_pq.search, ivf_pq.build, ivf_pq.build_chunked):
            assert hasattr(fn, "__wrapped__"), fn


class TestLoggingCallback:
    """core/logging.set_callback replacement semantics (reference:
    callback_sink.hpp — one sink, re-set replaces)."""

    @pytest.fixture(autouse=True)
    def _restore_logging(self):
        from raft_tpu.core import logging as rlog

        prev_level = rlog.get_logger().level
        yield
        rlog.set_callback(None)
        rlog.set_level(prev_level)

    def test_callback_receives_level_and_message(self):
        from raft_tpu.core import logging as rlog

        seen = []
        rlog.set_level(rlog.TRACE)
        rlog.set_callback(lambda lvl, msg: seen.append((lvl, msg)))
        rlog.info("hello %d", 7)
        assert len(seen) == 1
        lvl, msg = seen[0]
        assert lvl == 20 and "hello 7" in msg

    def test_second_callback_replaces_first(self):
        from raft_tpu.core import logging as rlog

        first, second = [], []
        rlog.set_level(rlog.TRACE)
        rlog.set_callback(lambda lvl, msg: first.append(msg))
        rlog.warn("one")
        rlog.set_callback(lambda lvl, msg: second.append(msg))
        rlog.warn("two")
        assert [m for m in first] == ["one"]  # NOT also "two"
        assert [m for m in second] == ["two"]

    def test_none_uninstalls(self):
        from raft_tpu.core import logging as rlog

        seen = []
        rlog.set_level(rlog.TRACE)
        rlog.set_callback(lambda lvl, msg: seen.append(msg))
        rlog.error("before")
        rlog.set_callback(None)
        rlog.error("after")
        assert seen == ["before"]
        rlog.set_callback(None)  # idempotent


class TestInterruptible:
    def test_cancel_self_raises_at_point(self):
        interruptible.cancel()
        with pytest.raises(interruptible.interrupted_exception):
            interruptible.cancellation_point()
        # token cleared: next point passes
        interruptible.cancellation_point()

    def test_cancel_other_thread(self):
        state = {}
        started = threading.Event()
        release = threading.Event()

        def worker():
            started.set()
            release.wait(5)
            try:
                for _ in range(100):
                    interruptible.cancellation_point()
            except interruptible.interrupted_exception:
                state["cancelled"] = True

        t = threading.Thread(target=worker)
        t.start()
        started.wait(5)
        interruptible.cancel(t.ident)
        release.set()
        t.join(5)
        assert state.get("cancelled")

    def test_synchronize_blocks_and_checks(self):
        x = jnp.arange(8) * 2
        interruptible.synchronize(x)  # no cancel → no raise
        interruptible.cancel()
        with pytest.raises(interruptible.interrupted_exception):
            interruptible.synchronize(x)

    def test_cancelled_chunked_build_aborts(self):
        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(0)
        x = rng.random((2000, 16), dtype=np.float32)
        interruptible.cancel()
        with pytest.raises(interruptible.interrupted_exception):
            ivf_pq.build_chunked(x, ivf_pq.IndexParams(n_lists=8, pq_dim=8,
                                                       seed=0),
                                 chunk_rows=256)


def test_pallas_grouped_vmem_bound(monkeypatch):
    """Auto-dispatch must refuse list blocks whose VMEM working set
    exceeds the per-program budget and keep accepting normal shapes."""
    from raft_tpu.ops.pallas_kernels import pallas_grouped_wanted

    monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "always")
    assert pallas_grouped_wanted(10, L=768, d=128)
    assert pallas_grouped_wanted(10, L=4096, d=128)
    assert not pallas_grouped_wanted(10, L=16384, d=128)  # ~16 MB block
    assert not pallas_grouped_wanted(65, L=768, d=128)    # kk cap


class TestResourcesManager:
    def test_pool_round_robin(self):
        m = DeviceResourcesManager()
        m.set_pool_size(3)
        m.set_seed(42)
        h1, h2, h3, h4 = (m.get_resources() for _ in range(4))
        assert h1 is not h2 and h2 is not h3
        assert h4 is h1  # round-robin wraps

    def test_options_frozen_after_first_get(self):
        m = DeviceResourcesManager()
        m.set_pool_size(2)
        first = m.get_resources()
        m.set_pool_size(5)  # ignored with a warning
        seen = {id(first), id(m.get_resources()), id(m.get_resources())}
        assert len(seen) == 2  # still the 2-handle pool

    def test_handles_have_distinct_rng_streams(self):
        m = DeviceResourcesManager()
        m.set_pool_size(2)
        h1 = m.get_resources()
        h2 = m.get_resources()
        k1 = np.asarray(h1.next_rng_key())
        k2 = np.asarray(h2.next_rng_key())
        assert not np.array_equal(k1, k2)
