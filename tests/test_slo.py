"""serve.slo — SLO guardrails (ISSUE 16 tentpole c).

The policy contract under test: multi-window burn rates computed from
snapshot deltas (not lifetime totals), the latency-over-SLO resolution
against cumulative histogram buckets, the recall-floor state machine
(insufficient evidence holds state; breach demotes + arms the quality
gate; fresh evidence recovers + disarms), the degrade ladder actually
skipping refused quality rungs with the ``degrade.refused`` counter,
and the process-global monitor install/clear discipline dispatch relies
on. Device-free — no jax import.
"""

import dataclasses

import pytest

from raft_tpu import obs
from raft_tpu.obs.metrics import MetricsRegistry
from raft_tpu.robust import degrade
from raft_tpu.serve import slo
from raft_tpu.serve.slo import SLOMonitor, SLOPolicy


class _FakeTenant:
    def __init__(self, name, recall_floor=None):
        self.name = name
        self.recall_floor = recall_floor


class _FakeRegistry:
    def __init__(self, tenants):
        self._tenants = tenants
        self.degraded = []
        self.recovered = []

    def resident(self):
        return list(self._tenants)

    def note_degraded(self, name):
        self.degraded.append(name)

    def note_recovered(self, name):
        self.recovered.append(name)


class _FakeVerifier:
    """recall_summary is the only surface the monitor reads."""

    def __init__(self):
        self.summaries = {}

    def recall_summary(self, tenant):
        return self.summaries.get(tenant, {})


def _summary(recall, n, z=1.96):
    from raft_tpu.obs.quality import wilson_interval

    lo, hi = wilson_interval(recall * n, n, z)
    return {10: {"recall": recall, "ci_low": lo, "ci_high": hi,
                 "n": float(n)}}


@pytest.fixture(autouse=True)
def _clean():
    slo.clear_monitor()
    yield
    slo.clear_monitor()
    obs.disable()


class TestBurnRates:
    def _mk(self, policy=None):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        clock = {"t": 0.0}
        mon = SLOMonitor(_FakeRegistry([]), policy=policy or SLOPolicy(
            windows_s=(30.0, 300.0), availability_target=0.999),
            clock=lambda: clock["t"])
        return mon, clock, obs.registry()

    def test_no_traffic_is_zero_burn(self):
        mon, _, _ = self._mk()
        assert mon.burn_rates() == {30.0: 0.0, 300.0: 0.0}

    def test_burn_from_deltas_not_lifetime(self):
        mon, clock, reg = self._mk()
        # a historic bad period outside the window must not burn now
        reg.inc("serve.requests", 1000, labels={"tenant": "a"})
        reg.inc("serve.shed", 500, labels={"reason": "queue_full"})
        mon.tick()
        clock["t"] = 1000.0                     # old snap pruned
        mon.tick()
        clock["t"] = 1010.0
        reg.inc("serve.requests", 100, labels={"tenant": "a"})
        burns = mon.burn_rates()
        assert burns[30.0] == 0.0

    def test_bad_fraction_over_budget(self):
        mon, clock, reg = self._mk()
        reg.inc("serve.requests", 100, labels={"tenant": "a"})
        mon.tick()
        clock["t"] = 10.0
        reg.inc("serve.requests", 100, labels={"tenant": "a"})
        reg.inc("serve.shed", 30, labels={"reason": "queue_full"})
        burns = mon.burn_rates()
        # 30 bad / 100 total over a 0.001 budget = 300x burn
        assert burns[30.0] == pytest.approx(300.0)
        snap = obs.registry().snapshot()
        assert snap["gauges"]["slo.burn_rate{window=30s}"] \
            == pytest.approx(300.0)
        assert snap["counters"]["slo.burn_alert{window=30s}"] >= 1.0

    def test_latency_slo_counts_slow_completions(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        clock = {"t": 0.0}
        mon = SLOMonitor(_FakeRegistry([]), policy=SLOPolicy(
            windows_s=(30.0,), availability_target=0.9,
            latency_slo_s=0.1), clock=lambda: clock["t"])
        reg = obs.registry()
        mon.tick()
        clock["t"] = 5.0
        for v in (0.01, 0.02, 0.5, 0.9):  # 2 of 4 over the 0.1 s SLO
            reg.observe("serve.latency_s", v)
        reg.inc("serve.requests", 4, labels={"tenant": "a"})
        burns = mon.burn_rates()
        # 2 slow / 4 requests over a 0.1 budget = 5x burn
        assert burns[30.0] == pytest.approx(5.0)


class TestRecallFloor:
    def _mk(self, floor=0.8, min_samples=8):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        tenant = _FakeTenant("acme", recall_floor=floor)
        registry = _FakeRegistry([tenant, _FakeTenant("other")])
        verifier = _FakeVerifier()
        mon = SLOMonitor(registry, verifier=verifier,
                         policy=SLOPolicy(min_samples=min_samples))
        return mon, registry, verifier

    def test_insufficient_evidence_holds_state(self):
        mon, registry, verifier = self._mk()
        verifier.summaries["acme"] = _summary(0.1, n=3)  # n < min_samples
        mon.evaluate()
        assert mon.breached() == [] and registry.degraded == []

    def test_breach_demotes_and_arms_gate(self):
        mon, registry, verifier = self._mk()
        verifier.summaries["acme"] = _summary(0.3, n=20)
        mon.evaluate()
        assert mon.breached() == ["acme"]
        assert registry.degraded == ["acme"]
        gate = mon.quality_gate_for("acme")
        assert gate is not None and gate("fp8_lut")
        assert mon.quality_gate_for("other") is None
        c = obs.registry().snapshot()["counters"]
        assert c["slo.recall_floor_breach{tenant=acme}"] == 1.0
        g = obs.registry().snapshot()["gauges"]
        assert g["slo.recall_floor_ok{tenant=acme}"] == 0.0
        # re-evaluating an unchanged breach is idempotent
        mon.evaluate()
        assert registry.degraded == ["acme"]

    def test_recovery_promotes_and_disarms(self):
        mon, registry, verifier = self._mk()
        verifier.summaries["acme"] = _summary(0.3, n=20)
        mon.evaluate()
        verifier.summaries["acme"] = _summary(1.0, n=50)
        mon.evaluate()
        assert mon.breached() == []
        assert registry.recovered == ["acme"]
        assert mon.quality_gate_for("acme") is None
        snap = obs.registry().snapshot()
        assert snap["counters"][
            "slo.recall_floor_recovered{tenant=acme}"] == 1.0
        assert snap["gauges"]["slo.recall_floor_ok{tenant=acme}"] == 1.0

    def test_floorless_tenant_never_breaches(self):
        mon, registry, verifier = self._mk(floor=None)
        verifier.summaries["acme"] = _summary(0.0, n=50)
        mon.evaluate()
        assert mon.breached() == []

    def test_marginal_recall_breaches_via_ci_not_point(self):
        # point estimate ABOVE the floor but CI lower bound below it
        # with thin evidence: the floor trips on the bound — the SLO is
        # about what we can PROVE, not the lucky sample mean
        mon, registry, verifier = self._mk(floor=0.8, min_samples=8)
        verifier.summaries["acme"] = _summary(0.85, n=10)
        from raft_tpu.obs.quality import wilson_interval

        assert wilson_interval(8.5, 10)[0] < 0.8
        mon.evaluate()
        assert mon.breached() == ["acme"]

    def test_healthz_payload(self):
        mon, registry, verifier = self._mk()
        verifier.summaries["acme"] = _summary(0.2, n=20)
        doc = mon.healthz()
        assert doc["recall_floor_breached"] == ["acme"]
        assert "30s" in doc["burn_rates"]
        assert doc["burn_threshold"] == 2.0


@dataclasses.dataclass
class _Params:
    # the knob surface the standard ladder's rungs mutate
    lut_dtype: str = "float32"
    scan_select: str = "pallas"
    scan_mode: str = "grouped"
    refine: str = "none"


def _knobs():
    return {"params": _Params()}


class TestQualityGateLadder:
    def test_refused_rungs_skipped_and_counted(self):
        obs.enable(registry=MetricsRegistry(), hbm=False)
        ladder = degrade.standard_search_ladder(batch=1, has_lut=True)
        with degrade.quality_gate(lambda rung: True):
            taken = []
            knobs = _knobs()
            while True:
                step = ladder.advance(knobs)
                if step is None:
                    break
                taken.append(step[0].name)
                knobs = step[1]
        assert "bf16_lut" not in taken and "fp8_lut" not in taken
        assert "decline_fused" not in taken
        c = obs.registry().snapshot()["counters"]
        assert c["degrade.refused{reason=recall_floor,rung=bf16_lut}"] \
            >= 1.0
        assert c["degrade.refused{reason=recall_floor,rung=fp8_lut}"] \
            >= 1.0

    def test_ungated_walk_takes_quality_rungs(self):
        ladder = degrade.standard_search_ladder(batch=1, has_lut=True)
        taken = []
        knobs = _knobs()
        while True:
            step = ladder.advance(knobs)
            if step is None:
                break
            taken.append(step[0].name)
            knobs = step[1]
        assert "bf16_lut" in taken and "fp8_lut" in taken

    def test_cursor_untouched_by_refusal(self):
        # a refused rung must come back after the gate drops: refuse
        # everything once, then walk un-gated — quality rungs reappear
        ladder = degrade.standard_search_ladder(batch=2, has_lut=True)
        step = ladder.advance(_knobs())       # halve_batch applies
        assert step[0].name == "halve_batch"
        with degrade.quality_gate(lambda rung: True):
            nxt = ladder.advance(step[1])
        # gated: bf16/fp8/decline refused; host_gather (or the terminal
        # halve) taken instead
        assert nxt is None or nxt[0].name not in degrade.QUALITY_RUNGS
        ladder2 = degrade.standard_search_ladder(batch=2, has_lut=True)
        with degrade.quality_gate(lambda rung: True):
            s = ladder2.advance(_knobs())
        nxt2 = ladder2.advance(s[1])          # un-gated follow-up
        assert nxt2[0].name == "bf16_lut"

    def test_raising_gate_fails_open(self):
        def boom(rung):
            raise RuntimeError("policy backend down")

        ladder = degrade.standard_search_ladder(batch=1, has_lut=True)
        with degrade.quality_gate(boom):
            step = ladder.advance(_knobs())
        assert step[0].name == "bf16_lut"  # degraded answers beat a crash

    def test_none_gate_is_noop(self):
        with degrade.quality_gate(None):
            ladder = degrade.standard_search_ladder(batch=1, has_lut=True)
            step = ladder.advance(_knobs())
            assert step[0].name == "bf16_lut"

    def test_gate_is_thread_local(self):
        import threading

        seen = {}

        def other_thread():
            ladder = degrade.standard_search_ladder(batch=1, has_lut=True)
            step = ladder.advance(_knobs())
            seen["name"] = step[0].name

        with degrade.quality_gate(lambda rung: True):
            t = threading.Thread(target=other_thread)
            t.start()
            t.join()
        assert seen["name"] == "bf16_lut"  # gate never leaked across


class TestGlobalMonitor:
    def test_install_and_clear(self):
        mon = SLOMonitor(_FakeRegistry([]))
        assert slo.set_monitor(mon) is None
        assert slo.get_monitor() is mon
        slo.clear_monitor(mon)
        assert slo.get_monitor() is None

    def test_stale_clear_keeps_newer_monitor(self):
        old = SLOMonitor(_FakeRegistry([]))
        new = SLOMonitor(_FakeRegistry([]))
        slo.set_monitor(old)
        slo.set_monitor(new)
        slo.clear_monitor(old)  # a stop() racing a newer start()
        assert slo.get_monitor() is new
