"""IVF-PQ tests: recall vs naive + refine recovery (reference test model:
cpp/test/neighbors/ann_ivf_pq.cuh:193 — recall vs naive_knn thresholds)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.neighbors import ivf_pq, refine
from raft_tpu.neighbors.ivf_pq import IndexParams, SearchParams
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState


def recall_at_k(got_ids, ref_ids):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got_ids, ref_ids))
    return hits / ref_ids.size


@pytest.fixture(scope="module")
def corpus():
    x, _ = make_blobs(5000, 32, n_clusters=40, cluster_std=1.0,
                      state=RngState(11))
    q, _ = make_blobs(100, 32, n_clusters=40, cluster_std=1.0,
                      state=RngState(12))
    return np.asarray(x), np.asarray(q)


class TestIvfPq:
    def test_recall_l2(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                       kmeans_n_iters=20, seed=0))
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.8  # PQ is lossy

    def test_full_dim_codebooks_near_exact(self, corpus):
        """pq_dim == dim (pq_len=1, 256 entries/subspace) ≈ fine scalar
        quantization → near-exact recall with all probes."""
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=32, pq_bits=8, seed=0))
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.93

    def test_refine_recovers_recall(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=8, pq_bits=8, seed=0))
        # low pq_dim → lossy; search 5x candidates then refine to k
        _, cand = ivf_pq.search(idx, jnp.asarray(q), 50, SearchParams(n_probes=16))
        d_ref, ids_ref = refine.refine(jnp.asarray(x), jnp.asarray(q),
                                       cand, 10, metric="sqeuclidean")
        _, ids_raw = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        r_raw = recall_at_k(np.asarray(ids_raw), ref)
        r_ref = recall_at_k(np.asarray(ids_ref), ref)
        assert r_ref >= r_raw
        assert r_ref >= 0.85

    def test_approx_distance_error_bounded(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16, pq_bits=8, seed=0))
        dists, ids = ivf_pq.search(idx, jnp.asarray(q), 5, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        exact = np.take_along_axis(full, np.asarray(ids), axis=1)
        got = np.asarray(dists)
        rel_err = np.abs(got - exact) / np.maximum(exact, 1e-6)
        assert np.median(rel_err) < 0.15

    def test_inner_product(self, corpus):
        x, q = corpus
        # MIPS top-k has many near-ties; full-dim codebooks keep the
        # quantization error below the tie margin
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=32,
                                       metric="inner_product", seed=0))
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        ref = np.argsort(-(q @ x.T), 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.75

    def test_cosine(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=32,
                                       metric="cosine", seed=0))
        dists, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        ref = np.argsort(cdist(q, x, "cosine"), 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.75
        assert np.asarray(dists).min() >= -0.01  # cosine distances ≥ 0

    def test_query_tiling_matches(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x), IndexParams(n_lists=16, pq_dim=16, seed=0))
        d1, i1 = ivf_pq.search(idx, jnp.asarray(q), 5,
                               SearchParams(n_probes=8, query_tile=256))
        d2, i2 = ivf_pq.search(idx, jnp.asarray(q), 5,
                               SearchParams(n_probes=8, query_tile=16))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_extend(self, corpus):
        x, q = corpus
        half = len(x) // 2
        idx = ivf_pq.build(jnp.asarray(x[:half]),
                           IndexParams(n_lists=16, pq_dim=16, seed=0))
        idx = ivf_pq.extend(idx, jnp.asarray(x[half:]))
        assert idx.size == len(x)
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.75

    def test_serialize_roundtrip(self, corpus, tmp_path):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x), IndexParams(n_lists=16, pq_dim=16, seed=0))
        path = os.path.join(tmp_path, "ivf_pq.idx")
        ivf_pq.save(idx, path)
        idx2 = ivf_pq.load(path)
        d1, i1 = ivf_pq.search(idx, jnp.asarray(q), 5, SearchParams(n_probes=8))
        d2, i2 = ivf_pq.search(idx2, jnp.asarray(q), 5, SearchParams(n_probes=8))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)

    def test_rotation_orthonormal(self):
        import jax

        from raft_tpu.neighbors.ivf_pq import make_rotation_matrix

        r = make_rotation_matrix(jax.random.PRNGKey(0), 40, 32)
        np.testing.assert_allclose(np.asarray(r.T @ r), np.eye(32),
                                   atol=1e-5)

class TestCodebookKindsAndPacking:
    """per_cluster codebooks, n-bit code packing, fp8 LUT (reference:
    ivf_pq_types.hpp:43,68,83; detail/ivf_pq_fp_8bit.cuh)."""

    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(0)
        for bits in (4, 5, 6, 7, 8):
            codes = rng.integers(0, 1 << bits, (37, 24)).astype(np.uint8)
            packed = ivf_pq.pack_bits_np(codes, bits)
            assert packed.shape[1] == ivf_pq.packed_nbytes(24, bits)
            out = np.asarray(ivf_pq.unpack_bits(jnp.asarray(packed), 24, bits))
            np.testing.assert_array_equal(out, codes)
            # device pack agrees with the host pack
            packed_dev = np.asarray(ivf_pq.pack_bits(jnp.asarray(codes), bits))
            np.testing.assert_array_equal(packed_dev, packed)

    def test_pq_bits4_halves_code_bytes(self, corpus):
        x, q = corpus
        i8 = ivf_pq.build(jnp.asarray(x),
                          IndexParams(n_lists=16, pq_dim=16, pq_bits=8, seed=0))
        i4 = ivf_pq.build(jnp.asarray(x),
                          IndexParams(n_lists=16, pq_dim=16, pq_bits=4, seed=0))
        assert i4.packed_codes.shape[2] * 2 == i8.packed_codes.shape[2]
        # 4-bit ADC is very lossy (measured exact-over-reconstruction
        # ceiling ≈ 0.29 on this corpus) — the search must hit its
        # ceiling, and refine must recover high recall from candidates
        ref = np.argsort(cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, ids = ivf_pq.search(i4, jnp.asarray(q), 10, SearchParams(n_probes=16))
        assert recall_at_k(np.asarray(ids), ref) >= 0.25
        _, cand = ivf_pq.search(i4, jnp.asarray(q), 100, SearchParams(n_probes=16))
        _, rids = refine.refine(jnp.asarray(x), jnp.asarray(q), cand, 10,
                                metric="sqeuclidean")
        assert recall_at_k(np.asarray(rids), ref) >= 0.8

    @pytest.mark.parametrize("bits", [4, 6])
    def test_nbit_grouped_matches_per_query(self, corpus, bits):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=16, pq_bits=bits,
                                       seed=0, cache_reconstruction="never"))
        dg, _ = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        dp, _ = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dg), 1),
                                   np.sort(np.asarray(dp), 1),
                                   rtol=1e-3, atol=1e-3)

    @pytest.mark.slow  # own per-cluster build; serialize/recon-cache twins keep the kind tier-1 (tier-1 budget)
    def test_per_cluster_recall(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16,
                                       codebook_kind="per_cluster", seed=0))
        assert idx.codebooks.shape[0] == 16  # one codebook per list
        assert idx.pq_dim == 16
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        ref = np.argsort(cdist(q, x, "sqeuclidean"), 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.7

    # sqeuclidean is the heavy leg; inner_product keeps the parity tier-1 (tier-1 budget)
    @pytest.mark.parametrize("metric", [
        pytest.param("sqeuclidean", marks=pytest.mark.slow),
        "inner_product"])
    def test_per_cluster_grouped_matches_per_query(self, corpus, metric):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=16, metric=metric,
                                       codebook_kind="per_cluster", seed=0,
                                       cache_reconstruction="never"))
        dg, _ = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        dp, _ = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dg), 1),
                                   np.sort(np.asarray(dp), 1),
                                   rtol=1e-3, atol=1e-3)

    def test_per_cluster_recon_cache_and_extend(self, corpus):
        x, q = corpus
        half = len(x) // 2
        idx = ivf_pq.build(jnp.asarray(x[:half]),
                           IndexParams(n_lists=16, pq_dim=16, seed=0,
                                       codebook_kind="per_cluster",
                                       cache_reconstruction="always"))
        assert idx.packed_recon is not None
        idx = ivf_pq.extend(idx, jnp.asarray(x[half:]))
        assert idx.size == len(x)
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        ref = np.argsort(cdist(q, x, "sqeuclidean"), 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.65

    def test_per_cluster_serialize_roundtrip(self, corpus, tmp_path):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16, pq_bits=6,
                                       codebook_kind="per_cluster", seed=0))
        path = os.path.join(tmp_path, "pq_pc.idx")
        ivf_pq.save(idx, path)
        idx2 = ivf_pq.load(path)
        assert idx2.codebook_kind == "per_cluster"
        assert idx2.pq_bits == 6 and idx2.pq_dim == 16
        d1, i1 = ivf_pq.search(idx, jnp.asarray(q), 5, SearchParams(n_probes=8))
        d2, i2 = ivf_pq.search(idx2, jnp.asarray(q), 5, SearchParams(n_probes=8))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)

    @pytest.mark.parametrize("lut", ["bfloat16", "float8_e4m3"])
    def test_lut_dtype_quantization(self, corpus, lut):
        """Quantized LUTs trade a little distance precision, not ids en
        masse — top-10 agreement with the f32 LUT stays high."""
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16, seed=0))
        _, i32 = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=16, scan_mode="per_query"))
        _, iq = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="per_query",
                                           lut_dtype=lut))
        agree = recall_at_k(np.asarray(iq), np.asarray(i32))
        assert agree >= (0.9 if lut == "bfloat16" else 0.8)


class TestChunkedBuild:
    """Streaming build (bounded host/device working set) must match the
    in-memory build's quality (reference: memmapped billion-scale builds,
    cpp/bench/ann/src/common/dataset.hpp)."""

    def test_chunked_matches_regular_recall(self, corpus):
        x, q = corpus
        p = IndexParams(n_lists=32, pq_dim=16, seed=0)
        ref_idx = ivf_pq.build(jnp.asarray(x), p)
        chk_idx = ivf_pq.build_chunked(x, p, chunk_rows=777)
        assert chk_idx.size == len(x)
        ref = np.argsort(cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, i1 = ivf_pq.search(ref_idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        _, i2 = ivf_pq.search(chk_idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        r1 = recall_at_k(np.asarray(i1), ref)
        r2 = recall_at_k(np.asarray(i2), ref)
        assert r2 >= r1 - 0.05  # same algorithm, different trainset sample

    def test_chunked_from_memmap(self, corpus, tmp_path):
        from raft_tpu.bench import dataset as ds
        x, q = corpus
        path = os.path.join(tmp_path, "base.fbin")
        from raft_tpu import native
        native.bin_write(path, x.astype(np.float32))
        mm = ds.bin_memmap(path, np.float32)
        assert mm.shape == x.shape
        idx = ivf_pq.build_chunked(mm, IndexParams(n_lists=32, pq_dim=16,
                                                   seed=0), chunk_rows=1024)
        assert idx.size == len(x)
        ref = np.argsort(cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        assert recall_at_k(np.asarray(ids), ref) >= 0.7

    def test_chunked_ids_complete(self, corpus):
        """Every dataset row lands in exactly one list slot with its own
        global id (no duplicates, no loss when lists don't overflow)."""
        x, _ = corpus
        idx = ivf_pq.build_chunked(x, IndexParams(n_lists=16, pq_dim=8,
                                                  seed=0), chunk_rows=999)
        got = np.asarray(idx.packed_ids)
        got = np.sort(got[got >= 0])
        np.testing.assert_array_equal(got, np.arange(len(x)))


class TestPallasGroupedScanPq:
    """Fused Pallas grouped scan over the bf16 recon cache (interpret
    mode off-TPU) must agree with the XLA recon-cache path."""

    # sqeuclidean is the heavy leg; inner_product keeps the parity tier-1 (tier-1 budget)
    @pytest.mark.parametrize("metric", [
        pytest.param("sqeuclidean", marks=pytest.mark.slow),
        "inner_product"])
    def test_pallas_matches_xla(self, metric, monkeypatch):
        from raft_tpu.random import make_blobs
        from raft_tpu.random.rng import RngState
        x, _ = make_blobs(4000, 32, n_clusters=40, cluster_std=1.0,
                          state=RngState(5))
        q, _ = make_blobs(80, 32, n_clusters=40, cluster_std=1.0,
                          state=RngState(6))
        idx = ivf_pq.build(jnp.asarray(np.asarray(x)),
                           IndexParams(n_lists=32, pq_dim=8, metric=metric,
                                       seed=0, cache_reconstruction="always"))
        sp = SearchParams(n_probes=16, scan_mode="grouped")
        qj = jnp.asarray(np.asarray(q))
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "never")
        dx, ix = ivf_pq.search(idx, qj, 10, sp)
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "always")
        dp, ip_ = ivf_pq.search(idx, qj, 10, sp)
        # the Pallas path recomputes ‖c+d‖² from bf16 recon: small drift
        np.testing.assert_allclose(np.asarray(dp), np.asarray(dx),
                                   rtol=2e-2, atol=2e-2)
        same = np.mean([len(set(a) & set(b)) / 10.0
                        for a, b in zip(np.asarray(ip_), np.asarray(ix))])
        assert same >= 0.95


class TestGroupedScanPq:
    """List-centric batch scan must agree with the per-query path."""

    def _corpus(self):
        from raft_tpu.random import make_blobs
        from raft_tpu.random.rng import RngState
        x, _ = make_blobs(5000, 32, n_clusters=50, cluster_std=1.0,
                          state=RngState(3))
        q, _ = make_blobs(100, 32, n_clusters=50, cluster_std=1.0,
                          state=RngState(4))
        return np.asarray(x), np.asarray(q)

    @pytest.mark.parametrize(
        "metric", ["sqeuclidean", "euclidean", "inner_product", "cosine"])
    def test_grouped_matches_per_query(self, metric):
        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=8, metric=metric,
                                       seed=0, cache_reconstruction="never"))
        dg, ig = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=16, scan_mode="grouped"))
        dp, ip_ = ivf_pq.search(idx, jnp.asarray(q), 10,
                                SearchParams(n_probes=16, scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dg), 1),
                                   np.sort(np.asarray(dp), 1),
                                   rtol=1e-3, atol=1e-3)

    def test_recon_cache_matches_decode(self):
        x, q = self._corpus()
        idx_n = ivf_pq.build(jnp.asarray(x),
                             IndexParams(n_lists=32, pq_dim=8, seed=0,
                                         cache_reconstruction="never"))
        idx_c = idx_n.replace(packed_recon=ivf_pq._build_recon_cache(idx_n))
        dn, _ = ivf_pq.search(idx_n, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        dc, _ = ivf_pq.search(idx_c, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        # bf16 cache vs f32 decode: small numeric drift allowed
        np.testing.assert_allclose(np.sort(np.asarray(dn), 1),
                                   np.sort(np.asarray(dc), 1),
                                   rtol=2e-2, atol=2e-2)

    def test_grouped_recall_with_refine(self):
        from raft_tpu.neighbors import refine as rf
        from scipy.spatial.distance import cdist
        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=16, seed=0))
        _, i0 = ivf_pq.search(idx, jnp.asarray(q), 40,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        _, ids = rf.refine(jnp.asarray(x), jnp.asarray(q), i0, 10,
                           metric="sqeuclidean")
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        hits = sum(len(set(g) & set(r)) for g, r in zip(np.asarray(ids), ref))
        assert hits / ref.size >= 0.9


class TestApproxScanSelect:
    def test_approx_recall_close_to_exact(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16, seed=0))
        _, ie = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=8, scan_mode="grouped"))
        _, ia = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=8, scan_mode="grouped",
                                           scan_select="approx"))
        ie, ia = np.asarray(ie), np.asarray(ia)
        same = np.mean([len(set(a) & set(b)) / 10.0 for a, b in zip(ie, ia)])
        assert same >= 0.85, same


    def test_segk_kernel_path_interpret(self, corpus, monkeypatch):
        """End-to-end PQ through the scalar-prefetch kernel over the
        recon cache (interpret mode off-TPU)."""
        x, q = corpus
        monkeypatch.setenv("RAFT_TPU_PALLAS_GROUPED", "always")
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16, seed=0,
                                       cache_reconstruction="always"))
        assert idx.packed_recon is not None
        _, ia = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=8, scan_mode="grouped",
                                           scan_select="approx"))
        _, ie = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=8, scan_mode="grouped"))
        ia, ie = np.asarray(ia), np.asarray(ie)
        same = np.mean([len(set(a) & set(b)) / 10.0 for a, b in zip(ie, ia)])
        assert same >= 0.8, same


class TestPallasLutScanTier:
    """scan_select="pallas" — the fused LUT-scan kernel over packed
    codes (interpret mode off-TPU) must agree with the exact per_query
    LUT path, across pq_bits, metrics, folded storage, and filters."""

    def _corpus(self, d=32):
        from raft_tpu.random import make_blobs
        from raft_tpu.random.rng import RngState
        x, _ = make_blobs(3000, d, n_clusters=30, cluster_std=1.0,
                          state=RngState(21))
        q, _ = make_blobs(60, d, n_clusters=30, cluster_std=1.0,
                          state=RngState(22))
        return np.asarray(x), np.asarray(q)

    def _build(self, x, **kw):
        kw.setdefault("n_lists", 16)
        kw.setdefault("pq_dim", 16)
        kw.setdefault("seed", 0)
        kw.setdefault("cache_reconstruction", "never")
        return ivf_pq.build(jnp.asarray(x), IndexParams(**kw))

    @pytest.mark.parametrize("bits", [4, 5, 6, 8])
    def test_matches_per_query_nbit(self, bits, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, q = self._corpus()
        idx = self._build(x, pq_bits=bits)
        dp, ip_ = ivf_pq.search(idx, jnp.asarray(q), 20,
                                SearchParams(n_probes=8,
                                             scan_select="pallas"))
        de, ie = ivf_pq.search(idx, jnp.asarray(q), 20,
                               SearchParams(n_probes=8,
                                            scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dp), 1),
                                   np.sort(np.asarray(de), 1),
                                   rtol=1e-3, atol=1e-3)
        same = np.mean([len(set(a) & set(b)) / 20.0 for a, b in
                        zip(np.asarray(ip_), np.asarray(ie))])
        assert same >= 0.99, same

    @pytest.mark.parametrize(
        "metric", ["euclidean", "inner_product", "cosine"])
    def test_matches_per_query_metrics(self, metric, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, q = self._corpus()
        idx = self._build(x, metric=metric)
        dp, ip_ = ivf_pq.search(idx, jnp.asarray(q), 10,
                                SearchParams(n_probes=8,
                                             scan_select="pallas"))
        de, ie = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=8,
                                            scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dp), 1),
                                   np.sort(np.asarray(de), 1),
                                   rtol=1e-3, atol=1e-3)

    def test_folded_storage_matches(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, q = self._corpus()
        idx = self._build(x)
        n_lists, L, nb = idx.packed_codes.shape
        assert (L * nb) % 128 == 0, "fixture must be foldable"
        folded = idx.replace(
            packed_codes=idx.packed_codes.reshape(n_lists, -1, 128))
        folded = ivf_pq.IvfPqIndex(
            centers=folded.centers, centers_rot=folded.centers_rot,
            rotation=folded.rotation, codebooks=folded.codebooks,
            packed_codes=folded.packed_codes,
            packed_ids=folded.packed_ids,
            packed_norms=folded.packed_norms,
            list_sizes=folded.list_sizes, metric=folded.metric,
            codebook_kind=folded.codebook_kind, pq_bits=folded.pq_bits,
            pq_dim_static=idx.pq_dim, codes_folded=True)
        sp = SearchParams(n_probes=8, scan_select="pallas")
        du, iu = ivf_pq.search(idx, jnp.asarray(q), 10, sp)
        df, if_ = ivf_pq.search(folded, jnp.asarray(q), 10, sp)
        np.testing.assert_array_equal(np.asarray(iu), np.asarray(if_))
        np.testing.assert_allclose(np.asarray(du), np.asarray(df),
                                   rtol=1e-5, atol=1e-5)

    def test_lut_dtype_tiers_through_search(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, q = self._corpus()
        idx = self._build(x)
        de, ie = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=8,
                                            scan_mode="per_query"))
        overlaps = {}
        for lut, bar in (("bfloat16", 0.9), ("float8_e4m3", 0.7)):
            _, il = ivf_pq.search(idx, jnp.asarray(q), 10,
                                  SearchParams(n_probes=8,
                                               scan_select="pallas",
                                               lut_dtype=lut))
            same = np.mean([len(set(a) & set(b)) / 10.0 for a, b in
                            zip(np.asarray(il), np.asarray(ie))])
            overlaps[lut] = same
            assert same >= bar, (lut, same)

    @pytest.mark.slow  # oversampled build + 2 searches; CI lanes + the
    # CI LUT smoke assert the same dispatch property
    def test_filter_bitset_rides_the_tier(self, monkeypatch):
        """ISSUE 12: a filter_bitset no longer disqualifies the LUT tier
        — the kernel streams the packed per-candidate keep bits beside
        the codes and masks filtered candidates to the sentinel BEFORE
        bin selection. The dispatch counter carries filtered=1, the
        retired filter_bitset fallback reason stays at zero, and no
        filtered id is ever returned."""
        from raft_tpu import obs
        from raft_tpu.core import bitset
        from raft_tpu.obs.metrics import MetricsRegistry
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, q = self._corpus()
        idx = self._build(x)
        mask = np.ones(len(x), bool)
        mask[::3] = False  # exclude a third of the corpus
        bits = bitset.from_mask(jnp.asarray(mask))
        sp = SearchParams(n_probes=8, scan_select="pallas")
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, sp,
                                   filter_bitset=bits)
        finally:
            obs.disable()
        counters = reg.snapshot()["counters"]
        assert counters.get(
            "ivf_pq.scan.dispatch{filtered=1,impl=pallas_lut}",
            0) >= 1, counters
        assert counters.get(
            "ivf_pq.scan.fallback{reason=filter_bitset}", 0) == 0, counters
        ids = np.asarray(ids)
        got = ids[ids >= 0]
        assert got.size and not np.any(got % 3 == 0)

    @pytest.mark.parametrize("bits", [
        4, pytest.param(5, marks=pytest.mark.slow),
        pytest.param(6, marks=pytest.mark.slow), 8])
    def test_filtered_matches_per_query_nbit(self, bits, monkeypatch):
        """Filtered fused == unfused parity across pq_bits: same kept-
        neighbor sets, same sorted distances."""
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        from raft_tpu.core import bitset
        x, q = self._corpus()
        idx = self._build(x, pq_bits=bits)
        mask = np.random.default_rng(bits).random(len(x)) < 0.3
        fbits = bitset.from_mask(jnp.asarray(mask))
        dp, ip_ = ivf_pq.search(idx, jnp.asarray(q), 20,
                                SearchParams(n_probes=8,
                                             scan_select="pallas"),
                                filter_bitset=fbits)
        de, ie = ivf_pq.search(idx, jnp.asarray(q), 20,
                               SearchParams(n_probes=8,
                                            scan_mode="per_query"),
                               filter_bitset=fbits)
        ip_, ie = np.asarray(ip_), np.asarray(ie)
        assert mask[ip_[ip_ >= 0]].all()
        for a, b in zip(ip_, ie):
            assert set(a[a >= 0]) == set(b[b >= 0])
        np.testing.assert_allclose(np.sort(np.asarray(dp), 1),
                                   np.sort(np.asarray(de), 1),
                                   rtol=1e-3, atol=1e-3)

    @pytest.mark.parametrize("metric", [
        pytest.param("euclidean", marks=pytest.mark.slow),
        "inner_product", "cosine"])
    def test_filtered_matches_per_query_metrics(self, metric, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        from raft_tpu.core import bitset
        x, q = self._corpus()
        idx = self._build(x, metric=metric)
        mask = np.random.default_rng(9).random(len(x)) < 0.3
        fbits = bitset.from_mask(jnp.asarray(mask))
        dp, ip_ = ivf_pq.search(idx, jnp.asarray(q), 10,
                                SearchParams(n_probes=8,
                                             scan_select="pallas"),
                                filter_bitset=fbits)
        de, ie = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=8,
                                            scan_mode="per_query"),
                               filter_bitset=fbits)
        ip_ = np.asarray(ip_)
        assert mask[ip_[ip_ >= 0]].all()
        np.testing.assert_allclose(np.sort(np.asarray(dp), 1),
                                   np.sort(np.asarray(de), 1),
                                   rtol=1e-3, atol=1e-3)

    @pytest.mark.parametrize("sel", [0.01, 0.1, 0.5])
    def test_filtered_matches_unfused_selectivity(self, sel, monkeypatch):
        """Filtered fused == unfused parity across the selectivity sweep
        (1%/10%/50%): the LUT tier's streamed mask and the per_query
        tier's in-scan filter must agree on the kept-neighbor set."""
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        from raft_tpu.core import bitset
        x, q = self._corpus()
        idx = self._build(x)
        rng = np.random.default_rng(5)
        mask = rng.random(len(x)) < sel
        mask[0] = True  # never empty
        bits = bitset.from_mask(jnp.asarray(mask))
        k = 10
        dp, ip_ = ivf_pq.search(idx, jnp.asarray(q), k,
                                SearchParams(n_probes=8,
                                             scan_select="pallas"),
                                filter_bitset=bits)
        de, ie = ivf_pq.search(idx, jnp.asarray(q), k,
                               SearchParams(n_probes=8,
                                            scan_mode="per_query"),
                               filter_bitset=bits)
        ip_, ie = np.asarray(ip_), np.asarray(ie)
        assert mask[ip_[ip_ >= 0]].all() and mask[ie[ie >= 0]].all()
        # identical kept-neighbor sets per query (tie order may differ
        # between scan algorithms; the SET is the contract)
        for a, b in zip(ip_, ie):
            assert set(a[a >= 0]) == set(b[b >= 0])
        np.testing.assert_allclose(np.sort(np.asarray(dp), 1),
                                   np.sort(np.asarray(de), 1),
                                   rtol=1e-3, atol=1e-3)

    def test_falls_back_gracefully_off_tpu(self, monkeypatch):
        """Without the env force, scan_select="pallas" off-TPU downgrades
        to the approx grouped tier — same results (approx select is
        exact on CPU), no crash, and a once-per-process warning."""
        from raft_tpu.core import logging as rlog
        monkeypatch.delenv("RAFT_TPU_PALLAS_LUTSCAN", raising=False)
        monkeypatch.setattr(ivf_pq, "_lut_fallback_warned", False)
        x, q = self._corpus()
        idx = self._build(x)
        msgs = []
        rlog.set_callback(lambda lvl, msg: msgs.append(msg))
        try:
            dp, _ = ivf_pq.search(idx, jnp.asarray(q), 10,
                                  SearchParams(n_probes=8,
                                               scan_select="pallas"))
        finally:
            rlog.set_callback(None)
        assert any("scan_select='pallas' requested" in m for m in msgs)
        # satellite (ISSUE 12): the warning names the CONCRETE reason +
        # the env override, and never the retired filter_bitset reason
        warned = [m for m in msgs if "scan_select='pallas'" in m]
        assert any("reason=kernel_ineligible" in m for m in warned), warned
        assert any("RAFT_TPU_PALLAS_LUTSCAN" in m for m in warned), warned
        assert not any("filter_bitset" in m for m in warned), warned
        assert "filter_bitset" not in ivf_pq._LUT_FALLBACK_DETAIL
        de, _ = ivf_pq.search(idx, jnp.asarray(q), 10,
                              SearchParams(n_probes=8,
                                           scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dp), 1),
                                   np.sort(np.asarray(de), 1),
                                   rtol=1e-3, atol=1e-3)

    def test_no_upgrade_when_bins_cannot_cover_k(self, monkeypatch):
        """k beyond n_probes·256 must NOT upgrade to the LUT tier — its
        bin cap would pad the tail with -1s where the approx tier
        returns real neighbors."""
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, q = self._corpus()
        idx = self._build(x)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            _, ids = ivf_pq.search(
                idx, jnp.asarray(q), 400,
                SearchParams(n_probes=1, scan_mode="grouped",
                             scan_select="approx"))
        finally:
            obs.disable()
        counters = reg.snapshot()["counters"]
        assert counters.get("ivf_pq.scan.dispatch{impl=pallas_lut}",
                            0) == 0, counters
        # the approx tier serves every real candidate the probed list
        # holds (well beyond the LUT tier's 256-per-probe bin cap)
        assert (np.asarray(ids) >= 0).sum(1).max() > 256

    def test_approx_auto_upgrades_on_oversampled_shapes(self, monkeypatch):
        """The DEEP-100M regime (k_cand ≥ 400, no recon cache) upgrades
        scan_select="approx" to the LUT kernel; the dispatch counter
        records the decision."""
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, q = self._corpus()
        idx = self._build(x)
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            ivf_pq.search(idx, jnp.asarray(q), 400,
                          SearchParams(n_probes=8, scan_mode="grouped",
                                       scan_select="approx"))
        finally:
            obs.disable()
        counters = reg.snapshot()["counters"]
        assert counters.get("ivf_pq.scan.dispatch{impl=pallas_lut}", 0) \
            >= 1, counters


class TestFp8LutDispatchDefault:
    """ISSUE 11: SearchParams.lut_dtype defaults to "auto" and
    :func:`ivf_pq.resolve_lut_dtype` makes fp8 QLUTs the measured
    default for oversampled dispatch — fp8 when the candidate slack
    absorbs the quantization noise, declining to bf16 when it can't,
    exact f32 everywhere else (and everywhere off-TPU unless forced)."""

    def test_default_is_auto(self):
        assert SearchParams().lut_dtype == "auto"

    def test_explicit_passthrough(self):
        for dt in ("float32", "bfloat16", "float8_e4m3"):
            assert ivf_pq.resolve_lut_dtype(dt, 128, 10) == dt

    def test_auto_off_tpu_is_f32(self, monkeypatch):
        monkeypatch.delenv("RAFT_TPU_FP8_LUT", raising=False)
        # oversampled shape, but this host is a CPU: exact f32
        assert ivf_pq.resolve_lut_dtype("auto", 128, 10) == "float32"

    def test_auto_forced_picks_fp8_with_slack(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FP8_LUT", "on")
        # oversampled + slack ≥ FP8_LUT_MIN_SLACK·k → fp8
        assert ivf_pq.resolve_lut_dtype("auto", 64, 10) == "float8_e4m3"
        # oversampled via k ≥ 400 but slack too thin for fp8 → bf16
        # (the documented recall-floor decline)
        n_probes = 4
        k = 500
        assert n_probes * 256 < ivf_pq.FP8_LUT_MIN_SLACK * k
        assert ivf_pq.resolve_lut_dtype("auto", n_probes, k) == "bfloat16"
        # not oversampled → exact f32 even when forced
        assert ivf_pq.resolve_lut_dtype("auto", 8, 10) == "float32"

    def test_env_off_pins_f32(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FP8_LUT", "off")
        assert ivf_pq.resolve_lut_dtype("auto", 128, 500) == "float32"

    def test_resolution_counter(self, monkeypatch):
        from raft_tpu import obs
        from raft_tpu.obs.metrics import MetricsRegistry

        monkeypatch.setenv("RAFT_TPU_FP8_LUT", "on")
        reg = MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            ivf_pq.resolve_lut_dtype("auto", 64, 10)
            ivf_pq.resolve_lut_dtype("auto", 4, 500)
            ivf_pq.resolve_lut_dtype("auto", 8, 10)
        finally:
            obs.disable()
        c = reg.snapshot()["counters"]
        assert c["ivf_pq.lut.dispatch{dtype=float8_e4m3}"] == 1.0
        assert c["ivf_pq.lut.dispatch{dtype=bfloat16}"] == 1.0
        assert c["ivf_pq.lut.dispatch{dtype=float32}"] == 1.0

    @pytest.mark.slow  # 64-list build + two searches; CI lanes run it
    def test_search_resolves_auto_before_the_scan(self, rng,
                                                  monkeypatch):
        """An "auto" params object runs end-to-end (no tier ever sees
        the unresolved token) and a forced-fp8 oversampled search stays
        within the documented recall envelope of the f32 run."""
        x = rng.random((2000, 32), dtype=np.float32)
        q = rng.random((32, 32), dtype=np.float32)
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=64, pq_dim=8,
                                       kmeans_n_iters=2))
        de, ie = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=64,
                                            lut_dtype="float32"))
        monkeypatch.setenv("RAFT_TPU_FP8_LUT", "on")
        da, ia = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=64))  # auto → fp8
        overlap = np.mean([len(set(a) & set(b)) / 10.0 for a, b in
                           zip(np.asarray(ia), np.asarray(ie))])
        assert overlap >= 1.0 - ivf_pq.FP8_LUT_RECALL_FLOOR - 0.05, \
            overlap


def test_folded_codes_storage_matches(rng):
    """Lane-folded code storage (codes_folded=True) must search
    identically — it is the same bytes reshaped to a [*, 128] trailing
    dim (u8 trailing dims < 128 pad to 128 lanes on TPU: 2x HBM)."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_pq

    x = rng.random((4000, 32), dtype=np.float32)
    q = rng.random((64, 32), dtype=np.float32)
    idx = ivf_pq.build(jnp.asarray(x), ivf_pq.IndexParams(
        n_lists=16, pq_dim=16, kmeans_n_iters=4))
    L, nb = idx.packed_codes.shape[1], idx.packed_codes.shape[2]
    assert (L * nb) % 128 == 0
    folded = idx.replace(
        packed_codes=idx.packed_codes.reshape(16, -1, 128),
        codes_folded=True)
    d1, i1 = ivf_pq.search(idx, jnp.asarray(q), 10,
                           ivf_pq.SearchParams(n_probes=8))
    d2, i2 = ivf_pq.search(folded, jnp.asarray(q), 10,
                           ivf_pq.SearchParams(n_probes=8))
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)
    # per_query path too
    d3, i3 = ivf_pq.search(folded, jnp.asarray(q), 10,
                           ivf_pq.SearchParams(n_probes=8,
                                               scan_mode="per_query"))
    d4, i4 = ivf_pq.search(idx, jnp.asarray(q), 10,
                           ivf_pq.SearchParams(n_probes=8,
                                               scan_mode="per_query"))
    np.testing.assert_array_equal(np.asarray(i3), np.asarray(i4))


@pytest.mark.slow  # full C=1 rescan twin; capacity_prove + CI lanes re-assert it (tier-1 budget)
def test_slice_scan_matches_gather_scan(rng, monkeypatch):
    """The billion-scale dynamic_slice scan (C=1) must return the same
    results as the gather scan."""
    import jax.numpy as jnp

    import raft_tpu.neighbors.ivf_pq as pq

    x = rng.random((6000, 32), dtype=np.float32)
    q = rng.random((300, 32), dtype=np.float32)
    idx = pq.build(jnp.asarray(x), pq.IndexParams(
        n_lists=16, pq_dim=16, kmeans_n_iters=4,
        cache_reconstruction="never"))
    sp = pq.SearchParams(n_probes=8, scan_mode="grouped",
                         scan_select="approx")
    d1, i1 = pq.search(idx, jnp.asarray(q), 10, sp)
    monkeypatch.setattr(pq, "_SLICE_SCAN_BYTES", 0)
    pq._search_grouped.clear_cache()  # force a re-trace under the patch
    d2, i2 = pq.search(idx, jnp.asarray(q), 10, sp)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)


class TestRefinedSearch:
    """search(refine="f32_regen") — the streamed scan→refine pipeline
    (ISSUE 4): the end-to-end fused path (Pallas LUT scan + Pallas
    gather-refine, interpret mode off-TPU) must match the recall of the
    unfused XLA path, and the routing must honor dataset residency."""

    def _corpus(self):
        x, _ = make_blobs(4000, 32, n_clusters=30, cluster_std=1.0,
                          state=RngState(31))
        q, _ = make_blobs(80, 32, n_clusters=30, cluster_std=1.0,
                          state=RngState(32))
        return np.asarray(x), np.asarray(q)

    def test_matches_manual_oversample_plus_refine(self):
        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x), IndexParams(n_lists=16,
                                                       pq_dim=16, seed=0))
        sp = SearchParams(n_probes=8, refine="f32_regen", refine_ratio=4)
        dv, iv = ivf_pq.search(idx, jnp.asarray(q), 10, sp,
                               dataset=jnp.asarray(x))
        _, i0 = ivf_pq.search(idx, jnp.asarray(q), 40,
                              SearchParams(n_probes=8))
        dm, im = refine.refine(jnp.asarray(x), jnp.asarray(q), i0, 10)
        np.testing.assert_allclose(np.asarray(dv), np.asarray(dm),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(iv), np.asarray(im))

    def test_fused_pipeline_recall_parity(self, monkeypatch):
        """Oversampled end-to-end: fused scan (LUT kernel) + fused
        refine (gather kernel) vs the unfused XLA pipeline — recall
        against exact neighbors must match within the approx-bin
        tolerance (the refine half is exact; only the scan's 2-deep
        bin pre-selection is lossy)."""
        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16, seed=0,
                                       cache_reconstruction="never"))
        k, k_cand = 10, 400  # the oversampled regime (k_cand >= 400)
        sp = SearchParams(n_probes=8, scan_mode="grouped",
                          refine="f32_regen", refine_ratio=k_cand / k)
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "always")
        _, i_f = ivf_pq.search(idx, jnp.asarray(q), k, sp,
                               dataset=jnp.asarray(x))
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "never")
        monkeypatch.setenv("RAFT_TPU_PALLAS_REFINE", "never")
        _, i_x = ivf_pq.search(idx, jnp.asarray(q), k, sp,
                               dataset=jnp.asarray(x))
        ref = np.argsort(cdist(q, x, "sqeuclidean"), 1)[:, :k]
        r_f = recall_at_k(np.asarray(i_f), ref)
        r_x = recall_at_k(np.asarray(i_x), ref)
        assert r_f >= r_x - 0.02, (r_f, r_x)
        assert r_f >= 0.9, r_f

    def test_refine_validation(self):
        from raft_tpu.core.errors import LogicError

        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x), IndexParams(n_lists=16,
                                                       pq_dim=16, seed=0))
        with pytest.raises(LogicError, match="dataset"):
            ivf_pq.search(idx, jnp.asarray(q), 10,
                          SearchParams(refine="f32_regen"))
        with pytest.raises(LogicError, match="refine mode"):
            ivf_pq.search(idx, jnp.asarray(q), 10,
                          SearchParams(refine="sq8"),
                          dataset=jnp.asarray(x))

    def test_host_dataset_routes_to_host_tier(self):
        from raft_tpu import obs

        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x), IndexParams(n_lists=16,
                                                       pq_dim=16, seed=0))
        reg = obs.MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            # numpy dataset, enough queries to pipeline → tiered prefetch
            ivf_pq.search(idx, jnp.asarray(q), 10,
                          SearchParams(n_probes=8, refine="f32_regen"),
                          dataset=x)
            # pinned serial transfer → the plain host gather tier
            ivf_pq.search(idx, jnp.asarray(q), 10,
                          SearchParams(n_probes=8, refine="f32_regen",
                                       refine_transfer="serial"),
                          dataset=x)
        finally:
            obs.disable()
        counters = reg.snapshot()["counters"]
        assert counters.get(
            "refine.dispatch{impl=tiered_prefetch}", 0) >= 1
        assert counters.get(
            "refine.dispatch{impl=host_gather}", 0) >= 1


class TestScanFallbackCounter:
    """ivf_pq.scan.fallback{reason=...} (ISSUE 4 satellite): declined
    LUT-tier dispatches must be visible with their losing reason, not
    just the winning impl."""

    def _setup(self, **kw):
        x, _ = make_blobs(3000, 32, n_clusters=20, cluster_std=1.0,
                          state=RngState(41))
        kw.setdefault("n_lists", 16)
        kw.setdefault("pq_dim", 16)
        kw.setdefault("seed", 0)
        kw.setdefault("cache_reconstruction", "never")
        idx = ivf_pq.build(jnp.asarray(np.asarray(x)), IndexParams(**kw))
        return np.asarray(x), idx

    def _count(self, fn):
        from raft_tpu import obs

        reg = obs.MetricsRegistry()
        obs.enable(registry=reg, hbm=False)
        try:
            fn()
        finally:
            obs.disable()
        return reg.snapshot()["counters"]

    def test_filter_bitset_reason_retired(self, monkeypatch):
        """ISSUE 12: the filter_bitset fallback reason is RETIRED — a
        filtered search on an eligible shape dispatches the LUT tier
        (filtered=1) and the old reason stays at zero (the CI obs-smoke
        step asserts the same invariant over the filtered legs)."""
        from raft_tpu.core import bitset

        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, idx = self._setup()
        bits = bitset.create(x.shape[0], default_value=True)
        c = self._count(lambda: ivf_pq.search(
            idx, jnp.asarray(x[:64]), 10,
            SearchParams(n_probes=8, scan_mode="grouped",
                         scan_select="pallas"),
            filter_bitset=bits))
        assert c.get("ivf_pq.scan.fallback{reason=filter_bitset}", 0) == 0, c
        assert c.get(
            "ivf_pq.scan.dispatch{filtered=1,impl=pallas_lut}", 0) >= 1, c

    def test_bin_capacity_reason(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, idx = self._setup()
        # k > n_probes·256: the bin output cannot carry enough candidates
        c = self._count(lambda: ivf_pq.search(
            idx, jnp.asarray(x[:64]), 600,
            SearchParams(n_probes=2, scan_mode="grouped",
                         scan_select="pallas")))
        assert c.get("ivf_pq.scan.fallback{reason=bin_capacity}", 0) >= 1, c

    def test_kernel_ineligible_reason(self, monkeypatch):
        monkeypatch.delenv("RAFT_TPU_PALLAS_LUTSCAN", raising=False)
        x, idx = self._setup()
        # explicit pallas request off-TPU without the env force
        c = self._count(lambda: ivf_pq.search(
            idx, jnp.asarray(x[:64]), 10,
            SearchParams(n_probes=8, scan_mode="grouped",
                         scan_select="pallas")))
        assert c.get("ivf_pq.scan.fallback{reason=kernel_ineligible}",
                     0) >= 1, c

    def test_per_cluster_reason(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PALLAS_LUTSCAN", "always")
        x, idx = self._setup(codebook_kind="per_cluster")
        c = self._count(lambda: ivf_pq.search(
            idx, jnp.asarray(x[:64]), 10,
            SearchParams(n_probes=8, scan_mode="grouped",
                         scan_select="pallas")))
        assert c.get("ivf_pq.scan.fallback{reason=per_cluster}", 0) >= 1, c
