"""IVF-PQ tests: recall vs naive + refine recovery (reference test model:
cpp/test/neighbors/ann_ivf_pq.cuh:193 — recall vs naive_knn thresholds)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial.distance import cdist

from raft_tpu.neighbors import ivf_pq, refine
from raft_tpu.neighbors.ivf_pq import IndexParams, SearchParams
from raft_tpu.random import make_blobs
from raft_tpu.random.rng import RngState


def recall_at_k(got_ids, ref_ids):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got_ids, ref_ids))
    return hits / ref_ids.size


@pytest.fixture(scope="module")
def corpus():
    x, _ = make_blobs(5000, 32, n_clusters=40, cluster_std=1.0,
                      state=RngState(11))
    q, _ = make_blobs(100, 32, n_clusters=40, cluster_std=1.0,
                      state=RngState(12))
    return np.asarray(x), np.asarray(q)


class TestIvfPq:
    def test_recall_l2(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                       kmeans_n_iters=20, seed=0))
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.8  # PQ is lossy

    def test_full_dim_codebooks_near_exact(self, corpus):
        """pq_dim == dim (pq_len=1, 256 entries/subspace) ≈ fine scalar
        quantization → near-exact recall with all probes."""
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=32, pq_bits=8, seed=0))
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.93

    def test_refine_recovers_recall(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=8, pq_bits=8, seed=0))
        # low pq_dim → lossy; search 5x candidates then refine to k
        _, cand = ivf_pq.search(idx, jnp.asarray(q), 50, SearchParams(n_probes=16))
        d_ref, ids_ref = refine.refine(jnp.asarray(x), jnp.asarray(q),
                                       cand, 10, metric="sqeuclidean")
        _, ids_raw = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        r_raw = recall_at_k(np.asarray(ids_raw), ref)
        r_ref = recall_at_k(np.asarray(ids_ref), ref)
        assert r_ref >= r_raw
        assert r_ref >= 0.85

    def test_approx_distance_error_bounded(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=16, pq_bits=8, seed=0))
        dists, ids = ivf_pq.search(idx, jnp.asarray(q), 5, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        exact = np.take_along_axis(full, np.asarray(ids), axis=1)
        got = np.asarray(dists)
        rel_err = np.abs(got - exact) / np.maximum(exact, 1e-6)
        assert np.median(rel_err) < 0.15

    def test_inner_product(self, corpus):
        x, q = corpus
        # MIPS top-k has many near-ties; full-dim codebooks keep the
        # quantization error below the tie margin
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=32,
                                       metric="inner_product", seed=0))
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        ref = np.argsort(-(q @ x.T), 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.75

    def test_cosine(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=16, pq_dim=32,
                                       metric="cosine", seed=0))
        dists, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        ref = np.argsort(cdist(q, x, "cosine"), 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.75
        assert np.asarray(dists).min() >= -0.01  # cosine distances ≥ 0

    def test_query_tiling_matches(self, corpus):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x), IndexParams(n_lists=16, pq_dim=16, seed=0))
        d1, i1 = ivf_pq.search(idx, jnp.asarray(q), 5,
                               SearchParams(n_probes=8, query_tile=256))
        d2, i2 = ivf_pq.search(idx, jnp.asarray(q), 5,
                               SearchParams(n_probes=8, query_tile=16))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_extend(self, corpus):
        x, q = corpus
        half = len(x) // 2
        idx = ivf_pq.build(jnp.asarray(x[:half]),
                           IndexParams(n_lists=16, pq_dim=16, seed=0))
        idx = ivf_pq.extend(idx, jnp.asarray(x[half:]))
        assert idx.size == len(x)
        _, ids = ivf_pq.search(idx, jnp.asarray(q), 10, SearchParams(n_probes=16))
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        assert recall_at_k(np.asarray(ids), ref) >= 0.75

    def test_serialize_roundtrip(self, corpus, tmp_path):
        x, q = corpus
        idx = ivf_pq.build(jnp.asarray(x), IndexParams(n_lists=16, pq_dim=16, seed=0))
        path = os.path.join(tmp_path, "ivf_pq.idx")
        ivf_pq.save(idx, path)
        idx2 = ivf_pq.load(path)
        d1, i1 = ivf_pq.search(idx, jnp.asarray(q), 5, SearchParams(n_probes=8))
        d2, i2 = ivf_pq.search(idx2, jnp.asarray(q), 5, SearchParams(n_probes=8))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)

    def test_rotation_orthonormal(self):
        import jax

        from raft_tpu.neighbors.ivf_pq import make_rotation_matrix

        r = make_rotation_matrix(jax.random.PRNGKey(0), 40, 32)
        np.testing.assert_allclose(np.asarray(r.T @ r), np.eye(32),
                                   atol=1e-5)

class TestGroupedScanPq:
    """List-centric batch scan must agree with the per-query path."""

    def _corpus(self):
        from raft_tpu.random import make_blobs
        from raft_tpu.random.rng import RngState
        x, _ = make_blobs(5000, 32, n_clusters=50, cluster_std=1.0,
                          state=RngState(3))
        q, _ = make_blobs(100, 32, n_clusters=50, cluster_std=1.0,
                          state=RngState(4))
        return np.asarray(x), np.asarray(q)

    @pytest.mark.parametrize(
        "metric", ["sqeuclidean", "euclidean", "inner_product", "cosine"])
    def test_grouped_matches_per_query(self, metric):
        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=8, metric=metric,
                                       seed=0, cache_reconstruction="never"))
        dg, ig = ivf_pq.search(idx, jnp.asarray(q), 10,
                               SearchParams(n_probes=16, scan_mode="grouped"))
        dp, ip_ = ivf_pq.search(idx, jnp.asarray(q), 10,
                                SearchParams(n_probes=16, scan_mode="per_query"))
        np.testing.assert_allclose(np.sort(np.asarray(dg), 1),
                                   np.sort(np.asarray(dp), 1),
                                   rtol=1e-3, atol=1e-3)

    def test_recon_cache_matches_decode(self):
        x, q = self._corpus()
        idx_n = ivf_pq.build(jnp.asarray(x),
                             IndexParams(n_lists=32, pq_dim=8, seed=0,
                                         cache_reconstruction="never"))
        idx_c = idx_n.replace(packed_recon=ivf_pq._build_recon_cache(idx_n))
        dn, _ = ivf_pq.search(idx_n, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        dc, _ = ivf_pq.search(idx_c, jnp.asarray(q), 10,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        # bf16 cache vs f32 decode: small numeric drift allowed
        np.testing.assert_allclose(np.sort(np.asarray(dn), 1),
                                   np.sort(np.asarray(dc), 1),
                                   rtol=2e-2, atol=2e-2)

    def test_grouped_recall_with_refine(self):
        from raft_tpu.neighbors import refine as rf
        from scipy.spatial.distance import cdist
        x, q = self._corpus()
        idx = ivf_pq.build(jnp.asarray(x),
                           IndexParams(n_lists=32, pq_dim=16, seed=0))
        _, i0 = ivf_pq.search(idx, jnp.asarray(q), 40,
                              SearchParams(n_probes=16, scan_mode="grouped"))
        _, ids = rf.refine(jnp.asarray(x), jnp.asarray(q), i0, 10,
                           metric="sqeuclidean")
        full = cdist(q, x, "sqeuclidean")
        ref = np.argsort(full, 1)[:, :10]
        hits = sum(len(set(g) & set(r)) for g, r in zip(np.asarray(ids), ref))
        assert hits / ref.size >= 0.9
