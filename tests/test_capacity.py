"""Capacity-prover tests — the runtime half of the billion-scale pass.

``assert_billion_safe`` (obs.sanitize) must hold over every public
search entry, the sharded merge tier, and build_chunked's
assignment/encode pass at n = 2.2e9 synthetic shapes (all device-free:
``jax.ShapeDtypeStruct`` operands, ``eval_shape``/``make_jaxpr``
semantics, zero bytes allocated) — and must CATCH a seeded int32
overflow regression. The x64 scoping satellite (the prover never leaks
``jax_enable_x64``) is regression-tested in tests/test_sanitize.py
alongside the other sanitize-lane tests.
"""

import jax
import jax.numpy as jnp
import pytest

from raft_tpu.core import ids as _ids
from raft_tpu.obs import sanitize as _san
import tools.capacity_prove as cp

N = cp.DEFAULT_N  # 2.2e9 — comfortably past 2³¹


# ---------------------------------------------------------------------------
# prover unit behavior
# ---------------------------------------------------------------------------

class TestCapacityReport:
    def test_int32_iota_over_big_axis_is_a_violation(self):
        def bad(q):
            return jnp.arange(N, dtype=jnp.int32)[:4] + q

        rep = _san.capacity_report(bad, jax.ShapeDtypeStruct((4,),
                                                             jnp.int32))
        assert len(rep["violations"]) == 1
        v = rep["violations"][0]
        assert v["primitive"] == "iota"
        assert "make_ids" in v["message"]
        # provenance points at the OFFENDING line (this file), not the
        # prover's call site (jax tracebacks are innermost-first)
        assert "test_capacity.py" in v["where"]

    def test_int32_gather_into_big_axis_is_a_violation(self):
        def bad(ds, idx):
            return jnp.take(ds, jnp.clip(idx, 0, 100), axis=0)

        rep = _san.capacity_report(
            bad, jax.ShapeDtypeStruct((N, 4), jnp.float32),
            jax.ShapeDtypeStruct((8,), jnp.int32))
        assert [v["primitive"] for v in rep["violations"]] == ["gather"]

    def test_trace_time_index_overflow_is_reported_not_raised(self):
        """jnp-level int32 indexing into a ≥2³¹ axis dies inside jax's
        index normalization (OverflowError) — the prover converts that
        into a violation with the user frame instead of crashing."""
        def bad(ds, idx):
            return ds[idx]

        rep = _san.capacity_report(
            bad, jax.ShapeDtypeStruct((N, 4), jnp.float32),
            jax.ShapeDtypeStruct((8,), jnp.int32))
        assert len(rep["violations"]) == 1
        assert rep["violations"][0]["primitive"] == "trace"
        assert "test_capacity.py" in rep["violations"][0]["where"]

    def test_int64_id_path_is_clean_and_reports_peak_bytes(self):
        def good(ds):
            ids = _ids.make_ids(8, start=N - 8, n_total=N)
            return ds[ids]

        rep = _san.assert_billion_safe(
            good, jax.ShapeDtypeStruct((N, 4), jnp.float32), what="good")
        assert not rep["violations"]
        # the [N, 4] f32 operand alone is > 32 GB of (abstract) bytes
        assert rep["peak_intermediate_bytes"] > 32 * 2**30

    def test_small_shapes_never_violate(self):
        """int32 everything is FINE below 2³¹ — the policy keeps int32
        when provably safe, and the prover must not cry wolf."""
        def fn(ds, idx):
            return ds[jnp.clip(idx.astype(jnp.int32), 0,
                               ds.shape[0] - 1)]

        rep = _san.assert_billion_safe(
            fn, jax.ShapeDtypeStruct((1 << 20, 4), jnp.float32),
            jax.ShapeDtypeStruct((8,), jnp.int32), what="small")
        assert not rep["violations"]

    def test_assert_raises_with_eqn_provenance(self):
        def bad(q):
            return jnp.arange(N, dtype=jnp.int32)[:4] + q

        with pytest.raises(_san.CapacityError) as ei:
            _san.assert_billion_safe(
                bad, jax.ShapeDtypeStruct((4,), jnp.int32), what="seeded")
        msg = str(ei.value)
        assert "seeded" in msg and "iota" in msg and "at " in msg


# ---------------------------------------------------------------------------
# the acceptance proofs: all public entries at n = 2.2e9
# ---------------------------------------------------------------------------

class TestBillionScaleProofs:
    def test_brute_force_search(self):
        assert not cp.prove_brute_force(N)["violations"]

    def test_ivf_pq_search(self):
        assert not cp.prove_ivf_pq(N)["violations"]

    def test_ivf_flat_search(self):
        assert not cp.prove_ivf_flat(N)["violations"]

    def test_filtered_search(self):
        """ISSUE 12: the filtered path (word-index divide in bitset.
        word_at + the fused tiers' list_filter_bytes operand prep) at
        n = 2.2e9 — int32 word math cannot sneak back in (GL11)."""
        assert not cp.prove_filtered_search(N)["violations"]

    def test_word_at_keeps_id_width(self):
        """The word-index divide in bitset.word_at runs in the INCOMING
        id dtype: `ids.astype(int32) // 32` would wrap negative past
        2³¹ and silently read a live word for an id that should have
        been masked — a wrong-RESULT bug the ≥ 2³¹-axis gather check
        cannot see (the word axis itself is < 2³¹), so the divide's
        dtype in the traced jaxpr is the proof. (jax may narrow the
        final in-bounds gather index AFTER the i64 divide — benign.)"""
        from raft_tpu.core import bitset as _bitset

        n_words = -(-N // 32)
        with _san.scoped_x64(True):
            closed = jax.make_jaxpr(_bitset.word_at.__wrapped__)(
                jax.ShapeDtypeStruct((n_words,), jnp.uint32),
                jax.ShapeDtypeStruct((4,), jnp.int64))
        divs = [e for e in closed.jaxpr.eqns
                if "floor_divide" in str(e.params.get("name", ""))
                or e.primitive.name == "div"]
        assert divs, closed.jaxpr
        for e in divs:
            assert str(e.invars[0].aval.dtype) == "int64", closed.jaxpr
            assert str(e.outvars[0].aval.dtype) == "int64", closed.jaxpr

    def test_cagra_search(self):
        assert not cp.prove_cagra(N)["violations"]

    def test_sharded_merge_ring(self):
        assert not cp.prove_sharded_merge(N, "ring")["violations"]

    def test_sharded_merge_allgather(self):
        assert not cp.prove_sharded_merge(N, "allgather")["violations"]

    def test_sharded_knn_pad_rows_widen_ids(self):
        """Boundary regression (code-review find): when the REAL row
        count still fits int32 but the padded total does not, gids must
        ride the padded width — otherwise pad-row gids wrap negative
        and escape the `gids < n` mask."""
        import numpy as np
        from jax.sharding import Mesh
        from raft_tpu.parallel import knn as _pknn

        n = 2**31 - 1  # int32-safe real rows; padded-to-8 total is not
        mesh = Mesh(np.array(jax.devices()[:8]), ("shard",))

        def fn(ds, q):
            return _pknn.sharded_knn(ds, q, 4, mesh, merge="allgather")

        with _san.scoped_x64(True):
            closed = jax.make_jaxpr(fn)(
                jax.ShapeDtypeStruct((n, 8), jnp.float32),
                jax.ShapeDtypeStruct((4, 8), jnp.float32))
        assert "int64" in str(closed.jaxpr.outvars[1].aval)

    def test_build_chunked_assign_encode(self):
        assert not cp.prove_build_chunked_pass(N)["violations"]

    def test_build_distributed_assign_encode(self):
        """ISSUE 13: the distributed build's per-shard assign+encode on
        the 8-device mesh — the ``rank·shard_rows + local`` global-id
        stamp at the last chunk's offset plus the per-list-count
        allgatherv must stay billion-safe."""
        assert not cp.prove_build_distributed_pass(N)["violations"]

    def test_seeded_int32_regression_fails(self):
        """The negative control: the OLD hard-int32 global-id remap
        (pre-core.ids parallel/knn.py) must fail the prover."""
        def old_remap(lids, marker):
            gids = lids.astype(jnp.int32) \
                + jnp.int32(3) * jnp.int32(N // 8)
            return cp._address_rows(marker, gids)

        with pytest.raises(_san.CapacityError):
            _san.assert_billion_safe(
                old_remap, jax.ShapeDtypeStruct((4, 4), jnp.int32),
                jax.ShapeDtypeStruct((N, 1), jnp.int8),
                what="old-remap")

    def test_seeded_policy_regression_fails_an_entry_proof(self):
        """Re-pinning the id policy to int32 (simulating a reverted
        core/ids.py) must break a real entry's proof — the proofs
        depend on the policy, not on hand-built indexes."""
        orig = _ids.id_dtype
        _ids.id_dtype = lambda n_rows: jnp.int32
        try:
            with pytest.raises(_san.CapacityError):
                cp.prove_cagra(N)
        finally:
            _ids.id_dtype = orig

    def test_cagra_optimize_graph_preserves_id_width(self):
        """Build-side regression (code-review find): the reverse-edge
        table must follow the graph's id width — a hard int32 table
        silently truncates int64 node ids through the .at[].set scatter
        (jnp casts, it doesn't error), dropping every reverse edge from
        the upper half of a ≥2³¹-row dataset."""
        from raft_tpu.neighbors import cagra as _cagra

        def fn(g):
            return _cagra.optimize_graph(g, 8)

        with _san.scoped_x64(True):
            closed = jax.make_jaxpr(fn)(
                jax.ShapeDtypeStruct((128, 16), jnp.int64))
        assert "int64" in str(closed.jaxpr.outvars[0].aval)

    def test_cli_report(self, tmp_path):
        """The CI entry point: all proofs clean, report artifact
        written."""
        import json

        report = tmp_path / "capacity.json"
        rc = cp.main(["--report", str(report),
                      "--only", "ivf_pq.search,merge.ring"])
        assert rc == 0
        doc = json.loads(report.read_text())
        assert doc["version"] == "raft_tpu.capacity_prove/1"
        assert all(p["ok"] for p in doc["proofs"].values())


# ---------------------------------------------------------------------------
# the id-dtype policy itself
# ---------------------------------------------------------------------------

class TestIdPolicy:
    def test_id_dtype_threshold(self):
        assert _ids.id_dtype(2**31 - 1) == jnp.int32
        assert _ids.id_dtype(2**31) == jnp.int64
        import numpy as np

        assert _ids.np_id_dtype(10) == np.int32
        assert _ids.np_id_dtype(N) == np.int64

    def test_make_ids_small_is_int32(self):
        ids = _ids.make_ids(16, start=4)
        assert ids.dtype == jnp.int32
        assert int(ids[0]) == 4 and int(ids[-1]) == 19

    def test_global_local_roundtrip_preserves_sentinels(self):
        import numpy as np

        local = jnp.asarray([0, 5, -1, 7], jnp.int32)
        g = _ids.global_ids(jnp.int32(3), 100, local, n_total=800)
        np.testing.assert_array_equal(np.asarray(g), [300, 305, -1, 307])
        back = _ids.local_ids(g, jnp.int32(3), 100)
        np.testing.assert_array_equal(np.asarray(back), [0, 5, -1, 7])

    def test_id_dtype_like_never_narrows(self):
        with _san.scoped_x64(True):
            wide = jnp.asarray([1, 2], jnp.int64)
            assert _ids.id_dtype_like(wide) == jnp.int64
        narrow = jnp.asarray([1, 2], jnp.int32)
        assert _ids.id_dtype_like(narrow) == jnp.int32
