"""Pairwise distances — expanded (MXU) and generic tiled (VPU) engines.

TPU-native counterpart of the reference's distance layer
(distance/distance-inl.cuh:67 ``distance()``, :238 ``pairwise_distance()``;
per-metric ops distance/detail/distance_ops/*.cuh; tiled engine
distance/detail/pairwise_matrix/). Design mapping:

- *expanded* metrics (L2/cosine/IP/correlation/hellinger/jaccard/dice/
  russelrao) decompose into one ``dot_general`` Gram matrix plus a cheap
  norm epilogue → pure XLA, runs on the MXU, fused by the compiler. This
  replaces the reference's CUTLASS sm80 path.
- *unexpanded* metrics (L1/Linf/Canberra/Lp/BrayCurtis/JS/Hamming/KL) run
  through a generic row-tiled engine: per-element ``core`` accumulated over
  the feature axis, mirroring the reference's distance_ops functor design
  (pairwise_matrix/kernel_sm60.cuh) with XLA doing the tiling/fusion.

Row tiling bounds peak memory exactly like the reference's tile-size
heuristic (knn_brute_force.cuh:80) — tile count is computed at trace time
from static shapes.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.utils.precision import get_precision

# Peak elements per broadcast block in the generic engine (~256 MB f32).
_GENERIC_BUDGET_ELEMS = 1 << 26


# ---------------------------------------------------------------------------
# expanded family: Gram matmul + epilogue (MXU path)
# ---------------------------------------------------------------------------

def _gram(x: jax.Array, y: jax.Array, precision=None) -> jax.Array:
    return lax.dot_general(
        x, y, (((1,), (1,)), ((), ())),
        precision=get_precision(precision), preferred_element_type=jnp.float32,
    )


def _sq_norms(x: jax.Array) -> jax.Array:
    return jnp.sum(x.astype(jnp.float32) ** 2, axis=1)


def l2_expanded(x, y, sqrt: bool, precision=None):
    """||x-y||² = ||x||² + ||y||² − 2⟨x,y⟩ (distance_ops/l2_exp.cuh)."""
    d2 = _sq_norms(x)[:, None] + _sq_norms(y)[None, :] - 2.0 * _gram(x, y, precision)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.sqrt(d2) if sqrt else d2


def cosine_expanded(x, y, precision=None):
    """1 − ⟨x,y⟩ / (‖x‖‖y‖) (distance_ops/cosine.cuh)."""
    nx = jnp.sqrt(jnp.maximum(_sq_norms(x), 1e-30))
    ny = jnp.sqrt(jnp.maximum(_sq_norms(y), 1e-30))
    return 1.0 - _gram(x, y, precision) / (nx[:, None] * ny[None, :])


def inner_product(x, y, precision=None):
    """Raw inner product — a similarity; select with ``select_min=False``
    (distance_ops/ip.cuh)."""
    return _gram(x, y, precision)


def correlation_expanded(x, y, precision=None):
    """1 − Pearson correlation = cosine of row-centered data
    (distance_ops/correlation.cuh)."""
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    yc = y - jnp.mean(y, axis=1, keepdims=True)
    return cosine_expanded(xc, yc, precision)


def hellinger_expanded(x, y, precision=None):
    """sqrt(1 − Σ √(xᵢyᵢ)) via the Gram of √x (distance_ops/hellinger.cuh)."""
    g = _gram(jnp.sqrt(jnp.maximum(x, 0.0)), jnp.sqrt(jnp.maximum(y, 0.0)), precision)
    return jnp.sqrt(jnp.maximum(1.0 - g, 0.0))


def jaccard_expanded(x, y, precision=None):
    """1 − |x∩y| / |x∪y| on non-zero supports (distance_ops/jaccard… via
    binarized Gram)."""
    xb = (x != 0).astype(jnp.float32)
    yb = (y != 0).astype(jnp.float32)
    inter = _gram(xb, yb, precision)
    union = jnp.sum(xb, 1)[:, None] + jnp.sum(yb, 1)[None, :] - inter
    return jnp.where(union > 0, 1.0 - inter / jnp.maximum(union, 1.0), 0.0)


def dice_expanded(x, y, precision=None):
    """1 − 2|x∩y| / (|x|+|y|) on non-zero supports (distance_ops/dice.cuh)."""
    xb = (x != 0).astype(jnp.float32)
    yb = (y != 0).astype(jnp.float32)
    inter = _gram(xb, yb, precision)
    denom = jnp.sum(xb, 1)[:, None] + jnp.sum(yb, 1)[None, :]
    return jnp.where(denom > 0, 1.0 - 2.0 * inter / jnp.maximum(denom, 1.0), 0.0)


def russelrao_expanded(x, y, precision=None):
    """(d − Σ xᵢyᵢ) / d for binary data (distance_ops/russel_rao.cuh)."""
    d = x.shape[1]
    return (d - _gram(x, y, precision)) / d


# ---------------------------------------------------------------------------
# generic tiled engine (unexpanded metrics)
# ---------------------------------------------------------------------------

def _row_tile(m: int, n: int, d: int) -> int:
    per_row = max(n * d, 1)
    bm = max(1, _GENERIC_BUDGET_ELEMS // per_row)
    bm = min(m, bm)
    if bm >= 8:
        bm -= bm % 8
    return max(bm, 1)


def _tiled_over_rows(x: jax.Array, y: jax.Array, block_fn) -> jax.Array:
    """Apply block_fn(x_block[bm,d], y[n,d]) -> [bm,n] over row tiles of x,
    bounding the broadcast intermediate (the reference's tiling heuristic,
    knn_brute_force.cuh:80)."""
    m, d = x.shape
    n = y.shape[0]
    bm = _row_tile(m, n, d)
    n_tiles = -(-m // bm)
    if n_tiles == 1:
        return block_fn(x, y)
    pad = n_tiles * bm - m
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    blocks = xp.reshape(n_tiles, bm, d)
    out = lax.map(lambda xb: block_fn(xb, y), blocks)
    return out.reshape(n_tiles * bm, n)[:m]


def _core_l1(a, b):
    return jnp.sum(jnp.abs(a - b), axis=-1)


def _core_l2(a, b):
    diff = a - b
    return jnp.sum(diff * diff, axis=-1)


def _core_linf(a, b):
    return jnp.max(jnp.abs(a - b), axis=-1)


def _core_canberra(a, b):
    num = jnp.abs(a - b)
    den = jnp.abs(a) + jnp.abs(b)
    return jnp.sum(jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0), axis=-1)


def _core_lp(a, b, p):
    return jnp.sum(jnp.abs(a - b) ** p, axis=-1) ** (1.0 / p)


def _core_braycurtis(a, b):
    num = jnp.sum(jnp.abs(a - b), axis=-1)
    den = jnp.sum(jnp.abs(a + b), axis=-1)
    return jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0)


def _xlogx_over(p, q):
    """p·log(p/q) with the 0·log0 → 0 convention."""
    safe = (p > 0) & (q > 0)
    return jnp.where(safe, p * jnp.log(jnp.maximum(p, 1e-30) / jnp.maximum(q, 1e-30)), 0.0)


def _core_jensenshannon(a, b):
    m = 0.5 * (a + b)
    s = jnp.sum(_xlogx_over(a, m) + _xlogx_over(b, m), axis=-1)
    return jnp.sqrt(jnp.maximum(0.5 * s, 0.0))


def _core_hamming(a, b):
    return jnp.mean((a != b).astype(jnp.float32), axis=-1)


def _core_kl(a, b):
    return jnp.sum(_xlogx_over(a, b), axis=-1)


def _make_block(core):
    def block_fn(xb, y):
        return core(xb[:, None, :].astype(jnp.float32), y[None, :, :].astype(jnp.float32))
    return block_fn


def haversine(x, y):
    """Great-circle distance on (lat, lon) radians pairs
    (spatial/knn/detail/haversine_distance.cuh). Feature dim must be 2."""
    expects(x.shape[1] == 2 and y.shape[1] == 2, "haversine requires 2-D points")
    lat1, lon1 = x[:, 0][:, None], x[:, 1][:, None]
    lat2, lon2 = y[:, 0][None, :], y[:, 1][None, :]
    sdlat = jnp.sin(0.5 * (lat2 - lat1))
    sdlon = jnp.sin(0.5 * (lon2 - lon1))
    a = sdlat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sdlon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@traced("raft_tpu.pairwise_distance")
def pairwise_distance(
    x: jax.Array,
    y: jax.Array,
    metric="euclidean",
    metric_arg: float = 2.0,
    precision: Optional[str] = None,
) -> jax.Array:
    """All-pairs distance matrix [m, n] between rows of x [m,d] and y [n,d].

    Counterpart of ``raft::distance::pairwise_distance``
    (distance/distance-inl.cuh:238) with runtime metric dispatch. ``metric``
    accepts a :class:`DistanceType` or a friendly alias ("euclidean",
    "cosine", …). ``metric_arg`` is the Minkowski p for the "lp" metric.
    """
    mt = resolve_metric(metric)
    expects(x.ndim == 2 and y.ndim == 2, "inputs must be 2-D [rows, features]")
    expects(x.shape[1] == y.shape[1], "feature dims differ: %d vs %d", x.shape[1], y.shape[1])

    if mt == DistanceType.L2Expanded:
        return l2_expanded(x, y, sqrt=False, precision=precision)
    if mt == DistanceType.L2SqrtExpanded:
        return l2_expanded(x, y, sqrt=True, precision=precision)
    if mt == DistanceType.CosineExpanded:
        return cosine_expanded(x, y, precision)
    if mt == DistanceType.InnerProduct:
        return inner_product(x, y, precision)
    if mt == DistanceType.CorrelationExpanded:
        return correlation_expanded(x, y, precision)
    if mt == DistanceType.HellingerExpanded:
        return hellinger_expanded(x, y, precision)
    if mt == DistanceType.JaccardExpanded:
        return jaccard_expanded(x, y, precision)
    if mt == DistanceType.DiceExpanded:
        return dice_expanded(x, y, precision)
    if mt == DistanceType.RusselRaoExpanded:
        return russelrao_expanded(x, y, precision)
    if mt == DistanceType.Haversine:
        return haversine(x, y)
    if mt == DistanceType.Precomputed:
        raise ValueError("Precomputed is a marker metric; pass distances directly")

    cores = {
        DistanceType.L1: _core_l1,
        DistanceType.L2Unexpanded: _core_l2,
        DistanceType.L2SqrtUnexpanded: lambda a, b: jnp.sqrt(_core_l2(a, b)),
        DistanceType.Linf: _core_linf,
        DistanceType.Canberra: _core_canberra,
        DistanceType.LpUnexpanded: partial(_core_lp, p=metric_arg),
        DistanceType.BrayCurtis: _core_braycurtis,
        DistanceType.JensenShannon: _core_jensenshannon,
        DistanceType.HammingUnexpanded: _core_hamming,
        DistanceType.KLDivergence: _core_kl,
    }
    return _tiled_over_rows(x, y, _make_block(cores[mt]))


@traced("raft_tpu.distance")
def distance(x, y, metric="euclidean", metric_arg: float = 2.0):
    """Alias matching the reference's ``raft::distance::distance``
    (distance/distance-inl.cuh:67)."""
    return pairwise_distance(x, y, metric=metric, metric_arg=metric_arg)
