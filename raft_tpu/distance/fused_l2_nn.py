"""Fused L2 nearest-neighbor (1-NN argmin) — kmeans' inner loop.

TPU-native counterpart of ``raft::distance::fused_l2_nn``
(distance/fused_l2_nn.cuh, detail/fused_distance_nn/): the L2 distance and
the argmin reduce are fused so the full [m, n] distance matrix is never
materialized in HBM. Here the fusion is expressed as a ``lax.scan`` over
column tiles of ``y`` with a running (min, argmin) carry — XLA fuses the
Gram matmul, epilogue, and reduction per tile; HBM cost is O(m·tile).
Also provides the masked variant (reference: distance/masked_nn.cuh).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.tracing import traced
from raft_tpu.utils.precision import get_precision

# Column-tile width of the running-argmin scan: large enough to keep the MXU
# busy, small enough that m×tile stays cheap in HBM.
_DEFAULT_TILE = 4096


def _dist_block(x, yb, x_sq, yb_sq, sqrt):
    d2 = x_sq[:, None] + yb_sq[None, :] - 2.0 * lax.dot_general(
        x, yb, (((1,), (1,)), ((), ())), precision=get_precision(),
        preferred_element_type=jnp.float32)
    d2 = jnp.maximum(d2, 0.0)
    return jnp.sqrt(d2) if sqrt else d2


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover
        return False


@traced("raft_tpu.fused_l2_nn_argmin")
def fused_l2_nn_argmin(
    x: jax.Array,
    y: jax.Array,
    sqrt: bool = False,
    tile: int = _DEFAULT_TILE,
    impl: Optional[str] = None,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of x, the L2 distance and index of its nearest row of y.

    Counterpart of ``fused_l2_nn``/``fused_l2_nn_min_reduce``
    (distance/fused_l2_nn.cuh). Returns (min_dists [m], argmins [m]).

    ``impl``: "pallas" | "xla" | None (auto: the Pallas kernel on TPU —
    the fusion is explicit there and ~100× the scanned XLA path — XLA
    elsewhere)."""
    m, d = x.shape
    n = y.shape[0]
    if impl is None:
        impl = "pallas" if _on_tpu() else "xla"
    if impl == "pallas":
        from raft_tpu.ops import fused_l2_argmin as _pallas_argmin

        dist, idx = _pallas_argmin(x, y)
        return (jnp.sqrt(dist) if sqrt else dist), idx
    xf = x.astype(jnp.float32)
    x_sq = jnp.sum(xf * xf, axis=1)

    if n <= tile:
        dists = _dist_block(xf, y.astype(jnp.float32), x_sq,
                            jnp.sum(y.astype(jnp.float32) ** 2, axis=1), sqrt)
        return jnp.min(dists, axis=1), jnp.argmin(dists, axis=1).astype(jnp.int32)

    n_tiles = -(-n // tile)
    pad = n_tiles * tile - n
    yp = jnp.pad(y.astype(jnp.float32), ((0, pad), (0, 0)))
    y_blocks = yp.reshape(n_tiles, tile, d)
    y_sq = jnp.sum(y_blocks * y_blocks, axis=2)
    # mask out padded rows so they never win the argmin
    valid = (jnp.arange(n_tiles * tile).reshape(n_tiles, tile) < n)

    def step(carry, inp):
        best_d, best_i = carry
        yb, yb_sq, vmask, base = inp
        dblk = _dist_block(xf, yb, x_sq, yb_sq, sqrt)
        dblk = jnp.where(vmask[None, :], dblk, jnp.inf)
        blk_min = jnp.min(dblk, axis=1)
        blk_arg = jnp.argmin(dblk, axis=1).astype(jnp.int32) + base
        take = blk_min < best_d
        return (jnp.where(take, blk_min, best_d), jnp.where(take, blk_arg, best_i)), None

    init = (jnp.full((m,), jnp.inf, jnp.float32), jnp.zeros((m,), jnp.int32))
    bases = (jnp.arange(n_tiles) * tile).astype(jnp.int32)
    (best_d, best_i), _ = lax.scan(step, init, (y_blocks, y_sq, valid, bases))
    return best_d, best_i


@traced("raft_tpu.masked_l2_nn_argmin")
def masked_l2_nn_argmin(
    x: jax.Array,
    y: jax.Array,
    adj: jax.Array,
    group_idx: Optional[jax.Array] = None,
    sqrt: bool = False,
    tile: int = _DEFAULT_TILE,
) -> Tuple[jax.Array, jax.Array]:
    """Masked L2 argmin (reference: distance/masked_nn.cuh).

    ``adj`` is a [m, n_groups] boolean adjacency: row i may only match
    columns whose group is admitted. ``group_idx`` maps each y row to its
    group (default: one group per y row, i.e. adj is [m, n]).

    Tiled like :func:`fused_l2_nn_argmin` — a ``lax.scan`` over column
    tiles of ``y`` with a running (min, argmin) carry, so HBM cost is
    O(m·tile), never the full [m, n] matrix (the point of the
    reference's masked fusion, detail/masked_distance_base.cuh).
    """
    xf = x.astype(jnp.float32)
    x_sq = jnp.sum(xf * xf, axis=1)
    m = x.shape[0]
    n, d = y.shape
    if group_idx is None:
        group_idx = jnp.arange(n, dtype=jnp.int32)

    if n <= tile:
        dists = _dist_block(xf, y.astype(jnp.float32), x_sq,
                            jnp.sum(y.astype(jnp.float32) ** 2, axis=1), sqrt)
        dists = jnp.where(jnp.take(adj, group_idx, axis=1), dists, jnp.inf)
        return jnp.min(dists, axis=1), jnp.argmin(dists, axis=1).astype(jnp.int32)

    n_tiles = -(-n // tile)
    pad = n_tiles * tile - n
    yp = jnp.pad(y.astype(jnp.float32), ((0, pad), (0, 0)))
    y_blocks = yp.reshape(n_tiles, tile, d)
    y_sq = jnp.sum(y_blocks * y_blocks, axis=2)
    g_blocks = jnp.pad(group_idx.astype(jnp.int32), (0, pad)).reshape(
        n_tiles, tile)
    valid = (jnp.arange(n_tiles * tile).reshape(n_tiles, tile) < n)

    def step(carry, inp):
        best_d, best_i = carry
        yb, yb_sq, gb, vmask, base = inp
        dblk = _dist_block(xf, yb, x_sq, yb_sq, sqrt)
        mask = jnp.take(adj, gb, axis=1) & vmask[None, :]  # [m, tile]
        dblk = jnp.where(mask, dblk, jnp.inf)
        blk_min = jnp.min(dblk, axis=1)
        blk_arg = jnp.argmin(dblk, axis=1).astype(jnp.int32) + base
        take = blk_min < best_d
        return (jnp.where(take, blk_min, best_d),
                jnp.where(take, blk_arg, best_i)), None

    init = (jnp.full((m,), jnp.inf, jnp.float32), jnp.zeros((m,), jnp.int32))
    bases = (jnp.arange(n_tiles) * tile).astype(jnp.int32)
    (best_d, best_i), _ = lax.scan(
        step, init, (y_blocks, y_sq, g_blocks, valid, bases))
    return best_d, best_i
