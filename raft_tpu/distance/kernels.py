"""Gram / kernel matrices (SVM-style kernels).

TPU-native counterpart of the reference's Gram kernel layer
(distance/kernels.cuh, detail/kernels/{gram_matrix,kernel_factory}.cuh):
linear, polynomial, RBF, and tanh kernels over row-major data. All are a
single MXU Gram matmul plus elementwise epilogue — XLA fuses the epilogue.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp
from jax import lax


class KernelType(enum.Enum):
    LINEAR = "linear"
    POLYNOMIAL = "polynomial"
    RBF = "rbf"
    TANH = "tanh"


@dataclasses.dataclass
class KernelParams:
    """Reference: ``raft::distance::kernels::KernelParams``."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0


from raft_tpu.core.tracing import traced
from raft_tpu.utils.precision import get_precision


def _gram(x, y):
    return lax.dot_general(x, y, (((1,), (1,)), ((), ())),
                           precision=get_precision(),
                           preferred_element_type=jnp.float32)


@traced("raft_tpu.gram_matrix")
def gram_matrix(x: jax.Array, y: jax.Array, params: KernelParams) -> jax.Array:
    """Evaluate the kernel Gram matrix K[i,j] = k(x_i, y_j)
    (reference: detail/kernels/gram_matrix.cuh ``evaluate``)."""
    k = _gram(x, y)
    if params.kernel == KernelType.LINEAR:
        return k
    if params.kernel == KernelType.POLYNOMIAL:
        return (params.gamma * k + params.coef0) ** params.degree
    if params.kernel == KernelType.TANH:
        return jnp.tanh(params.gamma * k + params.coef0)
    if params.kernel == KernelType.RBF:
        xs = jnp.sum(x.astype(jnp.float32) ** 2, 1)
        ys = jnp.sum(y.astype(jnp.float32) ** 2, 1)
        d2 = jnp.maximum(xs[:, None] + ys[None, :] - 2.0 * k, 0.0)
        return jnp.exp(-params.gamma * d2)
    raise ValueError(f"unknown kernel {params.kernel}")
