"""Distance metric taxonomy (reference: distance/distance_types.hpp:23-67).

The full reference metric set, with the same expanded/unexpanded split:
*expanded* metrics decompose into a Gram matmul plus a norm epilogue and run
on the MXU; *unexpanded* metrics need per-element accumulation and run
through the generic tiled pairwise engine (see pairwise.py).
"""

from __future__ import annotations

import enum


class DistanceType(enum.Enum):
    """All metrics of the reference (distance/distance_types.hpp:23-67)."""

    L2Expanded = "l2_expanded"
    L2SqrtExpanded = "l2_sqrt_expanded"
    L2Unexpanded = "l2_unexpanded"
    L2SqrtUnexpanded = "l2_sqrt_unexpanded"
    CosineExpanded = "cosine"
    L1 = "l1"
    InnerProduct = "inner_product"
    Linf = "linf"
    Canberra = "canberra"
    LpUnexpanded = "lp"
    CorrelationExpanded = "correlation"
    JaccardExpanded = "jaccard"
    HellingerExpanded = "hellinger"
    Haversine = "haversine"
    BrayCurtis = "braycurtis"
    JensenShannon = "jensenshannon"
    HammingUnexpanded = "hamming"
    KLDivergence = "kl_divergence"
    RusselRaoExpanded = "russelrao"
    DiceExpanded = "dice"
    Precomputed = "precomputed"


# Friendly-name aliases accepted by the Python API (mirrors pylibraft's
# DISTANCE_TYPES mapping, pylibraft/distance/pairwise_distance.pyx).
METRIC_ALIASES = {
    "euclidean": DistanceType.L2SqrtExpanded,
    "sqeuclidean": DistanceType.L2Expanded,
    "l2": DistanceType.L2SqrtExpanded,
    "l2_expanded": DistanceType.L2Expanded,
    "l2_sqrt_expanded": DistanceType.L2SqrtExpanded,
    "l2_unexpanded": DistanceType.L2Unexpanded,
    "l2_sqrt_unexpanded": DistanceType.L2SqrtUnexpanded,
    "cosine": DistanceType.CosineExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "inner_product": DistanceType.InnerProduct,
    "chebyshev": DistanceType.Linf,
    "linf": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "lp": DistanceType.LpUnexpanded,
    "minkowski": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "kldivergence": DistanceType.KLDivergence,
    "russelrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
    "precomputed": DistanceType.Precomputed,
}

#: Metrics where smaller is better (distances). InnerProduct is a similarity.
SELECT_MIN = {m: True for m in DistanceType}
SELECT_MIN[DistanceType.InnerProduct] = False


def resolve_metric(metric) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    key = str(metric).lower()
    if key in METRIC_ALIASES:
        return METRIC_ALIASES[key]
    raise ValueError(f"unknown metric {metric!r}")
