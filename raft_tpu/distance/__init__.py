"""raft_tpu.distance — pairwise distances, fused L2 argmin, Gram kernels.

Counterpart of the reference distance layer (cpp/include/raft/distance).
"""

from raft_tpu.distance.types import DistanceType, SELECT_MIN, resolve_metric  # noqa: F401
from raft_tpu.distance.pairwise import distance, pairwise_distance  # noqa: F401
from raft_tpu.distance.fused_l2_nn import (  # noqa: F401
    fused_l2_nn_argmin,
    masked_l2_nn_argmin,
)
from raft_tpu.distance.kernels import KernelParams, KernelType, gram_matrix  # noqa: F401
