"""ANN benchmark runner — JSON config → build/search sweeps → CSV export.

TPU-native counterpart of the reference's bench harness
(cpp/bench/ann/src/common/benchmark.hpp gbench driver + JSON conf.hpp;
python/raft-ann-bench run/__main__.py orchestration and
data_export/__main__.py QPS/recall CSV).  One process, no subprocesses:
XLA jit-caching plays the role of the reference's per-algo executables.

Config schema (mirrors run/conf/*.json)::

    {
      "dataset": {"name": "...", "n": 10000, "dim": 128, "n_queries": 1000,
                   "metric": "sqeuclidean"},
      "k": 10,
      "batch_size": 10000,
      "index": [
        {"name": "ivf_flat.n1024", "algo": "ivf_flat",
         "build_param": {"n_lists": 1024},
         "search_params": [{"n_probes": 32}, {"n_probes": 64}]},
        ...
      ]
    }

Per-row search_param extras (popped before the algo sees them):
``batch_size``/``n_queries``/``fence_per_call`` (the reference's batch
1/10 latency protocol), ``filter_selectivity`` (ISSUE 12: pre-filter
the search with a seeded bitset at that set-bit fraction; recall is
measured against EXACT filtered groundtruth shared per selectivity),
and ``leg_env`` (env overrides held for the row's measurement +
diagnostics — how a config pins a dispatch tier for an honest
fused-vs-forced-fallback comparison). All of these stay in the
recorded ``search_param`` so benchdiff's join key distinguishes the
legs.
"""

from __future__ import annotations

import contextlib
import csv
import json
import os
import time

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.obs.spans import env_flag as _env_flag

from . import dataset as ds_mod


@dataclass
class BenchResult:
    """One (algo, build_param, search_param) measurement row — the
    reference's gbench JSON record (qps = items_per_second)."""

    algo: str
    index_name: str
    dataset: str
    k: int
    batch_size: int
    build_s: float
    search_s: float
    qps: float
    recall: float
    build_param: Dict[str, Any] = field(default_factory=dict)
    search_param: Dict[str, Any] = field(default_factory=dict)
    # observability extras (RAFT_TPU_BENCH_OBS=1): per-stage span seconds
    # for one diagnostic batch, and the allocator's PROCESS-LIFETIME
    # peak-HBM high-water mark read at capture time — PJRT has no reset,
    # so this includes the build and all earlier rows (None on backends
    # that don't report, e.g. CPU). stage_path names the program the
    # breakdown decomposed (the staged per_query f32-LUT path), which
    # may DIFFER from the scan mode the timed QPS loop auto-selected —
    # the breakdown attributes stages, it does not re-measure the row
    stage_breakdown: Optional[Dict[str, float]] = None
    stage_path: Optional[str] = None
    peak_hbm_bytes: Optional[int] = None
    # p50/p99 of the diagnostic batches' end-to-end search latency
    # (bucket-interpolated Histogram.quantile over OBS_REPS synced
    # calls) — an estimate for tail triage, not the timed QPS protocol
    latency_quantiles: Optional[Dict[str, float]] = None
    # True when the row was measured under the fenced LATENCY protocol
    # (reduced-batch legs): qps includes the per-call host round-trip
    fence_per_call: bool = False
    # roofline cost attribution (RAFT_TPU_BENCH_OBS=1, obs.prof): the
    # XLA cost model of the row's whole compiled search program —
    # flops / bytes_accessed / arith_intensity / memory-vs-compute
    # bound vs the device peak table, plus achieved_bw_frac from the
    # diagnostic batches' p50 latency. None when the search closure
    # can't be traced end-to-end (host-gather paths)
    cost: Optional[Dict[str, Any]] = None
    # environment provenance (jax/jaxlib/libtpu versions, device kind
    # and count, mesh shape) — benchdiff refuses cross-environment
    # comparisons instead of reporting phantom regressions
    env: Optional[Dict[str, Any]] = None


# ---------------------------------------------------------------------------
# algorithm wrappers (reference: bench/ann/src/raft/*_wrapper.h)
# ---------------------------------------------------------------------------

def _algo_brute_force(dsx, build_param, metric):
    from ..neighbors import brute_force

    index = brute_force.build(dsx, metric=build_param.get("metric", metric))

    def search(q, k, sp):
        return brute_force.knn(index, q, k)

    return search, index


def _algo_ivf_flat(dsx, build_param, metric):
    from ..neighbors import ivf_flat

    p = ivf_flat.IndexParams(**{"metric": metric, **build_param})
    index = ivf_flat.build(dsx, p)

    def search(q, k, sp):
        # refine_ratio rides the API's own refined path (SearchParams.
        # refine="f32_regen"): oversample the scan — recovering what
        # the approx hardware top-k trades away — then re-rank exactly
        # through neighbors.refine's dispatch tier, residency-routed by
        # ivf_flat._route_refined (device → fused gather-refine kernel
        # on TPU oversampled shapes; memmap base → host gather)
        sp = dict(sp)
        fb = sp.pop("filter_bitset", None)
        ratio = sp.pop("refine_ratio", 1)
        if ratio > 1:
            return ivf_flat.search(
                index, q, k,
                ivf_flat.SearchParams(**sp, refine="f32_regen",
                                      refine_ratio=float(ratio)),
                filter_bitset=fb, dataset=dsx)
        return ivf_flat.search(index, q, k, ivf_flat.SearchParams(**sp),
                               filter_bitset=fb)

    return search, index


def _algo_ivf_pq(dsx, build_param, metric):
    from ..neighbors import ivf_pq, refine

    bp = dict(build_param)
    refine_ratio = bp.pop("refine_ratio", 1)
    chunked = bp.pop("chunked_build", False)
    chunk_rows = bp.pop("chunk_rows", 1 << 18)
    p = ivf_pq.IndexParams(**{"metric": metric, **bp})
    if chunked:  # streaming build: O(chunk) working set (memmap-friendly)
        base = dsx if isinstance(dsx, np.ndarray) else np.asarray(dsx)
        index = ivf_pq.build_chunked(base, p, chunk_rows=chunk_rows)
    else:
        index = ivf_pq.build(dsx, p)

    host_base = dsx if isinstance(dsx, np.ndarray) else None

    def search(q, k, sp):
        sp = dict(sp)
        fb = sp.pop("filter_bitset", None)
        ratio = sp.pop("refine_ratio", refine_ratio)
        if ratio > 1:
            # the oversampled scan already excludes filtered candidates
            # (the fused tiers stream the mask), so i0 is filter-clean
            # entering the re-rank
            d0, i0 = ivf_pq.search(index, q, k * int(ratio),
                                   ivf_pq.SearchParams(**sp),
                                   filter_bitset=fb)
            if host_base is not None:
                # memmapped base: gather only candidate rows on the host —
                # jitted refine would materialize the whole base in HBM
                return refine.refine_gathered(host_base, q, i0, k,
                                              metric=index.metric)
            return refine.refine(dsx, q, i0, k, metric=index.metric)
        return ivf_pq.search(index, q, k, ivf_pq.SearchParams(**sp),
                             filter_bitset=fb)

    return search, index


def _algo_cagra(dsx, build_param, metric):
    from ..neighbors import cagra

    p = cagra.IndexParams(**{"metric": metric, **build_param})
    index = cagra.build(dsx, p)

    def search(q, k, sp):
        return cagra.search(index, q, k, cagra.SearchParams(**sp))

    return search, index


ALGO_REGISTRY: Dict[str, Callable] = {
    "brute_force": _algo_brute_force,
    "ivf_flat": _algo_ivf_flat,
    "ivf_pq": _algo_ivf_pq,
    "cagra": _algo_cagra,
}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_ENV_STAMP: Optional[Dict[str, Any]] = None


def environment_stamp() -> Dict[str, Any]:
    """Environment provenance for bench rows (cached per process):
    jax/jaxlib/libtpu versions, backend, device kind/count, local
    device count, and the (flat single-process) mesh shape. Two
    records whose stamps differ are measuring different hardware or
    different compilers — ``tools/benchdiff.py`` refuses to compare
    them instead of reporting phantom regressions. Every field
    degrades to None rather than raising (the stamp must never cost a
    row)."""
    global _ENV_STAMP
    if _ENV_STAMP is not None:
        return _ENV_STAMP
    env: Dict[str, Any] = {}
    try:
        env["jax"] = jax.__version__
    except Exception:
        env["jax"] = None
    try:
        import jaxlib

        env["jaxlib"] = jaxlib.__version__
    except Exception:
        env["jaxlib"] = None
    libtpu = None
    try:  # libtpu ships under several distribution names
        import importlib.metadata as _md

        for dist in ("libtpu", "libtpu-nightly"):
            try:
                libtpu = _md.version(dist)
                break
            except Exception:
                continue
    except Exception:
        pass
    env["libtpu"] = libtpu
    try:
        env["backend"] = jax.default_backend()
        devs = jax.devices()
        env["device_kind"] = getattr(devs[0], "device_kind", None)
        env["device_count"] = len(devs)
        env["local_device_count"] = jax.local_device_count()
        env["process_count"] = getattr(jax, "process_count", lambda: 1)()
        # flat single-process mesh; multichip records stamp their own
        env["mesh_shape"] = [len(devs)]
    except Exception:
        env.setdefault("backend", None)
        env.setdefault("device_kind", None)
        env.setdefault("device_count", None)
    _ENV_STAMP = env
    return env


def _obs_capture(search_fn, queries, k, sp, batch_size, context):
    """RAFT_TPU_BENCH_OBS=1: run a few diagnostic batches under the
    observability layer (sync + stage mode → ivf_pq dispatches
    coarse_quantize/lut/scan as separate synced programs; refine and the
    other searches report whole-API spans) and return
    (stage_seconds_by_span, path, peak_hbm_bytes, latency_quantiles).
    Runs AFTER the timed measurement so the staged dispatch never
    pollutes QPS. Stage values are PER-BATCH means over the reps; the
    quantiles (p50/p99, ``Histogram.quantile`` bucket interpolation)
    come from a ``bench.search_latency_s`` histogram of each rep's
    end-to-end synced call. RAFT_TPU_BENCH_OBS_REPS overrides the rep
    count (default 5). With RAFT_TPU_BENCH_OBS_JSONL set, the captured
    registry is appended to that file, one JSON line per series,
    stamped with ``context``."""
    from raft_tpu import obs
    from raft_tpu.obs import spans as _spans

    try:
        reps = max(1, int(os.environ.get("RAFT_TPU_BENCH_OBS_REPS", "5")))
    except ValueError:
        reps = 5
    reg = obs.MetricsRegistry()
    qb = queries[: min(batch_size, queries.shape[0])]
    prev = _spans._state()  # a RAFT_TPU_OBS=1 enable must survive this
    try:
        # warm-up: the timed QPS loop ran the FUSED search, so the staged
        # programs are still uncompiled — the first staged call pays
        # trace+compile and would report seconds of "stage time". Burn it
        # into a throwaway registry; measure the later calls.
        obs.enable(sync=True, stages=True, registry=obs.MetricsRegistry())
        jax.block_until_ready(search_fn(qb, k, dict(sp)))
        obs.enable(sync=True, stages=True, registry=reg)
        # denser-than-default buckets: the quantile estimate is bucket-
        # interpolated, and the default decade buckets would clamp a
        # handful of similar reps straight to min/max
        lat = reg.histogram(
            "bench.search_latency_s",
            buckets=[1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2,
                     2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                     10.0, 30.0, 60.0])
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(search_fn(qb, k, dict(sp)))
            lat.observe(time.perf_counter() - t0)
    finally:
        _spans._restore(prev)
    quantiles = {"p50": round(lat.quantile(0.5), 6),
                 "p99": round(lat.quantile(0.99), 6),
                 "samples": lat.count}
    # roofline cost attribution (obs.prof): trace+compile the SAME
    # search closure the timed loop dispatched as one whole program and
    # read XLA's cost model — flops, bytes accessed, arithmetic
    # intensity, memory-vs-compute bound vs the device peak table.
    # elapsed = the diagnostic p50, so achieved_bw_frac compares the
    # row's realized bandwidth against the chip ceiling. analyze_jit
    # returns None (row kept, columns null) when the closure can't
    # trace end-to-end — e.g. host-gather refine paths.
    cost_row = None
    try:
        from raft_tpu.obs import prof as _prof

        cost = _prof.analyze_jit(lambda q: search_fn(q, k, dict(sp)), qb,
                                 elapsed_s=quantiles["p50"])
        if cost is not None:
            _prof.record(cost, registry=reg, program=context)
            cost_row = cost.as_row()
    except Exception as e:  # diagnostics must never cost a row
        print(f"[bench] prof capture failed ({e!r}) — "
              "row kept without cost columns")
    snap = reg.snapshot()
    stages = {name[len("span."):]: round(h["mean"], 6)
              for name, h in snap["histograms"].items()
              if name.startswith("span.")}
    # which program the breakdown decomposed: ivf_pq with stage spans
    # means the staged per_query f32-LUT path ran (possibly different
    # from the scan mode the timed loop used); otherwise spans wrapped
    # the same whole-API calls the timed loop dispatched
    path = ("staged_per_query_f32lut"
            if any(n.count(".") >= 2 for n in stages) else "whole_api")
    peak = snap["gauges"].get("hbm.peak_bytes")
    jsonl = os.environ.get("RAFT_TPU_BENCH_OBS_JSONL")
    if jsonl:
        reg.dump_jsonl(jsonl, extra={"context": context})
    return stages, path, (int(peak) if peak else None), quantiles, cost_row


def _xprof_capture(search_fn, queries, k, sp, batch_size, xprof_dir):
    """RAFT_TPU_XPROF_DIR: bracket one measured batch in a programmatic
    profiler capture (``obs.prof.capture`` — the start/stop
    generalization of the old inline ``jax.profiler.trace`` block) for
    offline XProf/Perfetto analysis."""
    from raft_tpu.obs import prof as _prof

    qb = queries[: min(batch_size, queries.shape[0])]
    cap = _prof.capture(xprof_dir).start()
    try:
        out = search_fn(qb, k, dict(sp))
        jax.block_until_ready(out)
    finally:
        cap.stop()
    if cap.error is not None:
        print(f"[bench] xprof capture unavailable ({cap.error!r})")
    else:
        print(f"[bench] xprof capture written under {xprof_dir}")


@contextlib.contextmanager
def _scoped_env(overrides: Optional[Dict[str, Any]]):
    """Apply a leg's env overrides for the duration of its measurement
    (timed loop + diagnostic captures), restoring prior values — unset
    variables are removed again — even when the leg dies."""
    if not overrides:
        yield
        return
    saved = {name: os.environ.get(name) for name in overrides}
    try:
        for name, val in overrides.items():
            os.environ[name] = str(val)
        yield
    finally:
        for name, prev in saved.items():
            if prev is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = prev


def _filter_leg(data: ds_mod.Dataset, selectivity: float, k: int):
    """Deterministic filtered-search leg state (ISSUE 12): a seeded
    keep mask at ``selectivity``, its packed bitset, and EXACT filtered
    groundtruth (brute force over only the kept rows, kept-row ids
    mapped back to global). Cached on the Dataset per selectivity —
    the fused and forced-fallback rows of one sweep share the mask and
    the GT, so their recall columns are comparable."""
    from raft_tpu.core import bitset as _bitset
    from ..neighbors import brute_force

    cache = getattr(data, "_filter_legs", None)
    if cache is None:
        cache = {}
        data._filter_legs = cache
    key = round(float(selectivity), 6)
    if key in cache:
        return cache[key]
    rng = np.random.default_rng(981_000 + int(key * 1_000_000))
    keep = rng.random(data.n) < key
    if keep.sum() < k:  # degenerate tiny-selectivity guard
        keep[rng.permutation(data.n)[:k]] = True
    bits = _bitset.from_mask(jnp.asarray(keep))
    kept_rows = np.where(keep)[0].astype(np.int64)
    base_kept = jnp.asarray(np.ascontiguousarray(data.base[kept_rows],
                                                 dtype=np.float32))
    index = brute_force.build(base_kept, metric=data.metric)
    # impl="sort": guaranteed-exact GT, same contract as the unfiltered
    # groundtruth above
    _, ids = brute_force.knn(index, jnp.asarray(data.queries), k,
                             impl="sort")
    gt = kept_rows[np.clip(np.asarray(ids), 0, len(kept_rows) - 1)]
    gt = np.where(np.asarray(ids) >= 0, gt, -1).astype(np.int64)
    del index, base_kept
    cache[key] = (bits, gt)
    return cache[key]


def _bench_search(search_fn, queries, k, sp, batch_size, iters=5,
                  fence_per_call=False):
    m = queries.shape[0]
    # pre-split batches ONCE: eager slicing inside the timed loop costs a
    # per-op dispatch round-trip on remote-device (tunnelled) backends
    batches = [queries[start : start + batch_size]
               for start in range(0, m, batch_size)]
    jax.block_until_ready(batches)
    ids_all = []
    # warmup/compile + correctness capture
    for qb in batches:
        d, i = search_fn(qb, k, sp)
        ids_all.append(np.asarray(jax.device_get(i)))
    ids = np.concatenate(ids_all, axis=0)
    t0 = time.perf_counter()
    if fence_per_call:
        # LATENCY protocol (the reference's batch-1/10 legs): every call
        # is fenced to the host before the next one dispatches, so the
        # reported rate includes the full per-call round-trip — the
        # number a single-request serving loop would see. Pipelining
        # here would report throughput mislabeled as latency.
        for _ in range(iters):
            for qb in batches:
                jax.device_get(search_fn(qb, k, sp)[1])
    else:
        # timed THROUGHPUT protocol: dispatch all iterations, then fetch
        # every result as the sync fence (gbench's stream-pipelined
        # items_per_second measures the same way). Blocking per call
        # instead adds the full per-call transport round-trip
        # (~70-100 ms on a tunnelled device) to every iteration — that
        # is the fenced LATENCY protocol above. device_get is the fence
        # because block_until_ready alone does not reliably synchronize
        # on remote-device backends.
        outs = [search_fn(qb, k, sp)[1]
                for _ in range(iters) for qb in batches]
        jax.device_get(outs)  # FULL results cross to the host, pipelined
    dt = (time.perf_counter() - t0) / iters
    return ids, dt, m / dt


def run_config(config: Dict[str, Any],
               data: Optional[ds_mod.Dataset] = None,
               verbose: bool = True,
               on_row: Optional[Callable[[BenchResult], None]] = None,
               deadline: Optional[float] = None) -> List[BenchResult]:
    """Run one benchmark config; returns a result row per
    (index, search_param) combination.

    ``on_row`` fires after every completed measurement — callers that
    must survive an external timeout (the driver protocol) persist rows
    incrementally instead of waiting for the full sweep (the
    reference's per-algo subprocess isolation serves the same purpose,
    run/__main__.py:48-103). ``deadline`` (time.time() scale) skips
    remaining index builds / search params once passed."""
    k = int(config.get("k", 10))
    batch_size = int(config.get("batch_size", 10_000))

    mmap_mode = False
    if data is None:
        dcfg = config["dataset"]
        if "dir" in dcfg:  # on-disk .fbin/.ibin dataset directory
            mmap_mode = bool(dcfg.get("mmap", False))
            data = ds_mod.load_dataset(
                dcfg["dir"], dcfg["name"],
                metric=dcfg.get("metric", "sqeuclidean"),
                max_rows=int(dcfg.get("max_rows", -1)), mmap=mmap_mode)
        else:
            data = ds_mod.make_synthetic(
                dcfg.get("name", "synthetic"),
                int(dcfg["n"]), int(dcfg["dim"]), int(dcfg["n_queries"]),
                metric=dcfg.get("metric", "sqeuclidean"),
                seed=int(dcfg.get("seed", 0)),
                hard=bool(dcfg.get("hard", False)),
            )
    # memmapped bases stay host-side: chunked builds page them in; only
    # algos that genuinely need the full matrix pull it to device
    dsx = data.base if mmap_mode else jnp.asarray(data.base)
    if data.groundtruth is None:
        ds_mod.compute_groundtruth(
            data, k=max(k, 10),
            device_base=None if mmap_mode else dsx)
    queries = jnp.asarray(data.queries)
    # config errors fail loudly BEFORE any work; runtime failures of one
    # algo keep the other algos' completed rows
    for index_cfg in config["index"]:
        if index_cfg["algo"] not in ALGO_REGISTRY:
            raise ValueError(f"unknown algo {index_cfg['algo']!r} "
                             f"(have {sorted(ALGO_REGISTRY)})")
    results: List[BenchResult] = []
    for index_cfg in config["index"]:
        if deadline is not None and time.time() > deadline:
            print(f"[bench] leg budget exhausted — skipping "
                  f"{index_cfg.get('name')} and later indexes")
            break
        try:
            _run_one_index(index_cfg, index_cfg["algo"], dsx, data,
                           queries, k, batch_size, results, verbose,
                           on_row=on_row, deadline=deadline)
        except Exception as e:  # keep completed rows if one algo dies
            import traceback

            traceback.print_exc()
            print(f"[bench] {index_cfg.get('name')} failed: {e}")
    return results


def _run_one_index(index_cfg, algo, dsx, data, queries, k, batch_size,
               results, verbose, on_row=None, deadline=None):
    bp = dict(index_cfg.get("build_param", {}))
    t0 = time.perf_counter()
    search_fn, index_obj = ALGO_REGISTRY[algo](dsx, dict(bp), data.metric)
    # block on the *index* arrays, not the input: async dispatch would
    # otherwise let the build overlap the first search timing
    jax.block_until_ready(
        [leaf for leaf in jax.tree_util.tree_leaves(index_obj)
         if hasattr(leaf, "block_until_ready")])
    build_s = time.perf_counter() - t0
    for sp in index_cfg.get("search_params", [{}]):
        if deadline is not None and time.time() > deadline:
            print(f"[bench] leg budget exhausted — skipping remaining "
                  f"search params of {index_cfg.get('name')}")
            break
        # per-search-param batch/query overrides: the reference ANN
        # protocol measures batch 1/10/10000 (raft_ann_benchmarks), so a
        # search_param may carry "batch_size" (and a trimmed "n_queries"
        # — small batches measure latency, they don't need the full
        # query set) while sharing the dataset, groundtruth and built
        # index with the big-batch rows
        sp = dict(sp)
        row_bs = int(sp.pop("batch_size", batch_size))
        row_nq = sp.pop("n_queries", None)
        # reduced-batch legs default to the fenced LATENCY protocol
        # (that is what batch 1/10 measures); override with
        # "fence_per_call": false to pipeline anyway
        fenced = bool(sp.pop("fence_per_call", row_bs < batch_size))
        # filtered-search legs (ISSUE 12): "filter_selectivity": 0.1
        # pre-filters the search with a seeded bitset at that set-bit
        # fraction; recall is measured against EXACT filtered
        # groundtruth shared across the sweep's rows at the same
        # selectivity (fused vs forced-fallback rows stay comparable)
        fsel = sp.pop("filter_selectivity", None)
        leg_fn, gt = search_fn, data.groundtruth
        if fsel is not None:
            fbits, gt = _filter_leg(data, float(fsel), k)

            def leg_fn(q, kk, s, _fb=fbits, _fn=search_fn):
                return _fn(q, kk, {**s, "filter_bitset": _fb})
        # "leg_env": env overrides scoped to this row's measurement —
        # how a config pins a dispatch tier for an honest fused-vs-
        # forced-fallback comparison (e.g. RAFT_TPU_PALLAS_LUTSCAN=
        # "never" reproduces the pre-ISSUE-12 filtered fallback tier).
        # Held through the obs/xprof captures (they must describe the
        # same program the timed loop ran), restored after the row;
        # recorded in search_param (part of the benchdiff join key).
        leg_env = sp.pop("leg_env", None)
        q_leg = queries if row_nq is None else \
            queries[: min(int(row_nq), queries.shape[0])]
        with _scoped_env(leg_env):
            ids, dt, qps = _bench_search(leg_fn, q_leg, k, sp, row_bs,
                                         fence_per_call=fenced)
            rec = ds_mod.recall(ids, gt[: q_leg.shape[0]])
            stages = stage_path = peak_hbm = latency_q = cost_row = None
            if _env_flag("RAFT_TPU_BENCH_OBS"):
                try:
                    stages, stage_path, peak_hbm, latency_q, cost_row = \
                        _obs_capture(
                            leg_fn, q_leg, k, sp, row_bs,
                            context=f"{index_cfg.get('name', algo)} {sp}")
                except Exception as e:  # diagnostics never cost a row
                    print(f"[bench] obs capture failed ({e!r}) — "
                          "row kept without stage breakdown")
            xprof_dir = os.environ.get("RAFT_TPU_XPROF_DIR")
            if xprof_dir:
                _xprof_capture(leg_fn, q_leg, k, sp, row_bs, xprof_dir)
        # the recorded search_param keeps filter_selectivity + leg_env —
        # the join key benchdiff matches rows by must distinguish
        # filtered and env-pinned legs
        sp_rec = dict(sp)
        if fsel is not None:
            sp_rec["filter_selectivity"] = float(fsel)
        if leg_env:
            sp_rec["leg_env"] = dict(leg_env)
        row = BenchResult(
            algo=algo, index_name=index_cfg.get("name", algo),
            dataset=data.name, k=k, batch_size=row_bs,
            build_s=build_s, search_s=dt, qps=qps, recall=rec,
            build_param=bp, search_param=sp_rec,
            stage_breakdown=stages, stage_path=stage_path,
            peak_hbm_bytes=peak_hbm, latency_quantiles=latency_q,
            fence_per_call=fenced, cost=cost_row,
            env=environment_stamp(),
        )
        results.append(row)
        if on_row is not None:
            on_row(row)
        if verbose:
            bs_note = f" b={row_bs}" if row_bs != batch_size else ""
            print(f"[bench] {row.index_name} {sp}{bs_note}: "
                  f"qps={qps:,.0f} recall={rec:.4f} build={build_s:.1f}s")
            if stages:
                parts = ", ".join(f"{n}={v * 1e3:.1f}ms"
                                  for n, v in sorted(stages.items()))
                hbm = (f"; peak_hbm={peak_hbm / 2**30:.2f}GiB"
                       if peak_hbm else "")
                lat = (f"; p50={latency_q['p50'] * 1e3:.1f}ms "
                       f"p99={latency_q['p99'] * 1e3:.1f}ms"
                       if latency_q else "")
                print(f"[bench]   stages: {parts}{hbm}{lat}")
            if cost_row and cost_row.get("flops") is not None \
                    and cost_row.get("bytes_accessed") is not None:
                bw = cost_row.get("achieved_bw_frac")
                bw_s = f" bw_frac={bw:.3f}" if bw is not None else ""
                print(f"[bench]   roofline: flops={cost_row['flops']:.3g} "
                      f"bytes={cost_row['bytes_accessed']:.3g} "
                      f"bound={cost_row['bound']}{bw_s}")


def run_config_file(path: str, **kw) -> List[BenchResult]:
    with open(path) as f:
        return run_config(json.load(f), **kw)


def export_csv(results: List[BenchResult], path: str) -> None:
    """QPS/recall CSV (reference: data_export/__main__.py:54-55)."""
    cols = ["algo", "index_name", "dataset", "k", "batch_size", "build_s",
            "search_s", "qps", "recall", "build_param", "search_param"]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for r in results:
            w.writerow([r.algo, r.index_name, r.dataset, r.k, r.batch_size,
                        f"{r.build_s:.4f}", f"{r.search_s:.6f}", f"{r.qps:.1f}",
                        f"{r.recall:.4f}", json.dumps(r.build_param),
                        json.dumps(r.search_param)])


def pareto_frontier(results: List[BenchResult]) -> List[BenchResult]:
    """QPS/recall pareto points (the reference's plot module draws
    exactly this frontier)."""
    rows = sorted(results, key=lambda r: (-r.recall, -r.qps))
    front, best_qps = [], -1.0
    for r in rows:
        if r.qps > best_qps:
            front.append(r)
            best_qps = r.qps
    return list(reversed(front))
