"""Real-dataset ingestion — ann-benchmarks hdf5 → .fbin/.ibin dataset
directories, plus big-ann groundtruth splitting.

TPU-native counterpart of the reference's dataset tooling
(python/raft-ann-bench get_dataset/__main__.py:34 convert_hdf5_to_fbin +
hdf5_to_fbin.py; split_groundtruth/__main__.py + split_groundtruth.pl).
Re-designed host-side: one streaming pass per file (h5py chunk reads →
appended fbin payload), no subprocess/perl helpers.

ann-benchmarks hdf5 layout: datasets ``train`` [n, d] f32, ``test``
[m, d] f32, ``neighbors`` [m, k] int, ``distances`` [m, k] f32.
Angular sets are L2-normalized on conversion (``normalize=True``) so
inner-product search is exact cosine — the reference's ``-n`` flag.

big-ann groundtruth binary (split_groundtruth.pl's input): header
``[n, k] u32`` then ``n·k`` int32 neighbor ids then ``n·k`` float32
distances; :func:`split_groundtruth` splits it into the
``groundtruth.ibin`` / ``groundtruth_dist.fbin`` pair the bench loader
reads.
"""

from __future__ import annotations

import os
import struct
from typing import Optional

import numpy as np

from .. import native

_CHUNK_ROWS = 1 << 18


def _write_fbin_streaming(path: str, src, dtype, normalize: bool = False):
    """Stream ``src`` (h5py dataset / array-like) into a .fbin/.ibin
    file in row chunks — billion-scale trains never materialize."""
    n, d = src.shape
    with open(path, "wb") as f:
        f.write(struct.pack("<ii", n, d))
        for start in range(0, n, _CHUNK_ROWS):
            block = np.asarray(src[start:start + _CHUNK_ROWS], dtype=dtype)
            if normalize:
                norms = np.linalg.norm(block, axis=1, keepdims=True)
                block = block / np.maximum(norms, 1e-30)
            f.write(np.ascontiguousarray(block, dtype).tobytes())


def convert_hdf5(hdf5_path: str, out_dir: str,
                 normalize: bool = False) -> str:
    """Convert one ann-benchmarks hdf5 file into a dataset directory
    (reference: hdf5_to_fbin.py driven by get_dataset/__main__.py:34).

    Writes ``base.fbin``, ``query.fbin``, ``groundtruth.ibin`` and
    (when present) ``groundtruth_dist.fbin`` under
    ``out_dir/<dataset-name>``; returns that directory. ``normalize``
    L2-normalizes base and queries (angular → inner-product search),
    matching the reference's convention of renaming *-angular to
    *-inner."""
    import h5py

    name = os.path.splitext(os.path.basename(hdf5_path))[0]
    if normalize and "angular" in name:
        name = name.replace("angular", "inner")
    d = os.path.join(out_dir, name)
    os.makedirs(d, exist_ok=True)
    with h5py.File(hdf5_path, "r") as f:
        _write_fbin_streaming(os.path.join(d, "base.fbin"), f["train"],
                              np.float32, normalize)
        _write_fbin_streaming(os.path.join(d, "query.fbin"), f["test"],
                              np.float32, normalize)
        if "neighbors" in f:
            _write_fbin_streaming(os.path.join(d, "groundtruth.ibin"),
                                  f["neighbors"], np.int32)
        if "distances" in f:
            _write_fbin_streaming(os.path.join(d, "groundtruth_dist.fbin"),
                                  f["distances"], np.float32)
    return d


def split_groundtruth(gt_path: str, out_dir: Optional[str] = None) -> str:
    """Split a big-ann-benchmarks groundtruth file (ids+distances in one
    binary) into ``groundtruth.ibin`` + ``groundtruth_dist.fbin``
    (reference: split_groundtruth/__main__.py + split_groundtruth.pl).
    Returns the output directory (defaults to the file's)."""
    out_dir = out_dir or os.path.dirname(os.path.abspath(gt_path))
    os.makedirs(out_dir, exist_ok=True)
    with open(gt_path, "rb") as f:
        n, k = struct.unpack("<ii", f.read(8))
        ids = np.frombuffer(f.read(n * k * 4), dtype=np.int32).reshape(n, k)
        rest = f.read(n * k * 4)
    native.bin_write(os.path.join(out_dir, "groundtruth.ibin"), ids)
    if len(rest) == n * k * 4:  # distances present
        dist = np.frombuffer(rest, dtype=np.float32).reshape(n, k)
        native.bin_write(os.path.join(out_dir, "groundtruth_dist.fbin"),
                         dist)
    return out_dir


def fetch(name: str, data_dir: str, normalize: bool = False) -> str:
    """Download an ann-benchmarks dataset by name and convert it
    (reference: get_dataset/__main__.py download). In an air-gapped
    environment place ``<name>.hdf5`` under ``data_dir`` yourself and
    this converts it without network access."""
    os.makedirs(data_dir, exist_ok=True)
    hdf5_path = os.path.join(data_dir, f"{name}.hdf5")
    if not os.path.exists(hdf5_path):
        from urllib.request import urlretrieve

        url = f"https://ann-benchmarks.com/{name}.hdf5"
        # download to a temp name and rename on success: a partial file
        # at the final path would be mistaken for complete on retry
        tmp = hdf5_path + ".part"
        try:
            urlretrieve(url, tmp)
            os.replace(tmp, hdf5_path)
        except Exception as e:  # air-gapped: point at the manual path
            if os.path.exists(tmp):
                os.remove(tmp)
            raise RuntimeError(
                f"cannot download {url} ({e}); place the file at "
                f"{hdf5_path} and re-run") from e
    return convert_hdf5(hdf5_path, data_dir, normalize=normalize)
