"""QPS-vs-recall plotting — the reference's ``plot`` module
(python/raft-ann-bench/src/raft-ann-bench/plot/__main__.py), re-designed
around this harness's BenchResult rows / CSV export.

One figure per call: each index's measurement points, its pareto
frontier drawn solid, non-frontier points faded — the shape every
raft-ann-bench README curve uses. X axis defaults to a logit-like
scale so the interesting 0.9..0.999 recall region is readable.
"""

from __future__ import annotations

import csv
import json
from typing import Iterable, List, Optional, Sequence

from .runner import BenchResult, pareto_frontier


def read_csv(path: str) -> List[BenchResult]:
    """Load rows written by runner.export_csv back into BenchResult."""
    out: List[BenchResult] = []
    with open(path) as f:
        for row in csv.DictReader(f):
            out.append(BenchResult(
                algo=row["algo"], index_name=row["index_name"],
                dataset=row["dataset"], k=int(row["k"]),
                batch_size=int(row["batch_size"]),
                build_s=float(row["build_s"]),
                search_s=float(row["search_s"]), qps=float(row["qps"]),
                recall=float(row["recall"]),
                build_param=json.loads(row["build_param"]),
                search_param=json.loads(row["search_param"])))
    return out


def plot_search(results: Iterable[BenchResult], out_path: str,
                title: Optional[str] = None,
                x_scale: str = "logit") -> str:
    """Write the QPS-vs-recall plot (reference: plot/__main__.py
    create_plot_search). Returns ``out_path``."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = list(results)
    if not rows:
        raise ValueError("no results to plot")
    fig, ax = plt.subplots(figsize=(10, 7))
    names = sorted({r.index_name for r in rows})
    cmap = plt.get_cmap("tab10")

    # logit(1.0) is non-finite: exact-recall points (brute force, or
    # 0.99995+ rounded to 1.0 by the CSV) must clamp INSIDE the open
    # interval or they silently vanish from the chart
    def rx(r):
        return min(r.recall, 1 - 2e-5) if x_scale == "logit" else r.recall

    for i, name in enumerate(names):
        mine = [r for r in rows if r.index_name == name]
        color = cmap(i % 10)
        ax.scatter([rx(r) for r in mine], [r.qps for r in mine],
                   color=color, alpha=0.35, s=24)
        front = pareto_frontier(mine)
        ax.plot([rx(r) for r in front], [r.qps for r in front],
                color=color, marker="o", label=name, linewidth=2)
    if x_scale == "logit":
        # readable 0.9..0.999 region; clamp into (0, 1) open interval
        ax.set_xscale("logit")
        lo = min(max(min(rx(r) for r in rows) - 0.05, 0.01), 0.5)
        hi = min(max(rx(r) for r in rows) + 1e-5, 1 - 1e-5)
        ax.set_xlim(lo, hi)
    ax.set_yscale("log")
    ax.set_xlabel(f"recall@{rows[0].k}")
    ax.set_ylabel("queries/s")
    ax.grid(True, which="both", alpha=0.3)
    ax.legend()
    ax.set_title(title or f"{rows[0].dataset} (batch={rows[0].batch_size})")
    fig.savefig(out_path, bbox_inches="tight", dpi=120)
    plt.close(fig)
    return out_path


def plot_build(results: Iterable[BenchResult], out_path: str,
               title: Optional[str] = None) -> str:
    """Build-time bar chart (reference: plot/__main__.py
    create_plot_build)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = list(results)
    best: dict = {}
    for r in rows:  # one bar per index: its build time
        best[r.index_name] = r.build_s
    fig, ax = plt.subplots(figsize=(8, 5))
    names = sorted(best)
    ax.bar(range(len(names)), [best[n] for n in names],
           color=[plt.get_cmap("tab10")(i % 10) for i in range(len(names))])
    ax.set_xticks(range(len(names)), names, rotation=20, ha="right")
    ax.set_ylabel("build time (s)")
    ax.grid(True, axis="y", alpha=0.3)
    ax.set_title(title or (rows[0].dataset if rows else "build times"))
    fig.savefig(out_path, bbox_inches="tight", dpi=120)
    plt.close(fig)
    return out_path
