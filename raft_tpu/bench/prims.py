"""Primitive micro-benchmarks — the ``cpp/bench/prims`` analog.

The reference ships gbench micro-benchmarks per primitive
(cpp/bench/prims: matrix/select_k.cu, distance, fused_l2_nn, kmeans...)
to ground kernel-choice heuristics in measurements. This module plays
that role for the TPU build: it times the competing implementations of
each hot primitive (XLA vs Pallas select_k; XLA-scan vs Pallas
fused_l2_nn; grouped vs per-query IVF scans) on the *current* backend,
so dispatch thresholds (`matrix/select_k.py` `_PALLAS_MIN_LEN`/
`_PALLAS_MAX_K`, `ivf_pq.search` scan_mode="auto") can be set
empirically rather than guessed.

CLI::

    python -m raft_tpu.bench.prims [select_k|fused_l2_nn|pairwise|
                                    kmeans|ivf_scan|all] [--csv out.csv]

Each row: {bench, params, impl, ms, throughput}.

Caveat on tunnelled/remote devices: times are end-to-end per call
(dispatch + execute + result fetch — ``block_until_ready`` alone does
not reliably synchronize there), so a per-call transport floor
(~100 ms over an HTTP device tunnel) can swamp sub-ms kernels. For
per-op device time in that setting, chain iterations inside one jit
with a data dependency and difference two iteration counts — see
docs/tpu_design_notes.md for measured examples.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class PrimResult:
    bench: str
    impl: str
    ms: float
    throughput: float      # bench-specific unit/s (rows, pairs, queries)
    unit: str
    params: Dict[str, Any] = field(default_factory=dict)


def _time(fn: Callable[[], Any], iters: int = 10, warmup: int = 2) -> float:
    """Median wall ms of ``fn``, synchronized by fetching the result —
    ``block_until_ready`` alone does not reliably synchronize on
    remote-device (tunnelled) backends (a 25-GFLOP matmul "measured"
    0.05 ms, 10× over hardware peak); ``device_get`` is the honest
    fence, matching bench/runner.py's end-to-end methodology."""
    for _ in range(warmup):
        jax.device_get(fn())
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.device_get(fn())
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


# ---------------------------------------------------------------------------
# select_k (reference: bench/prims/matrix/select_k.cu)
# ---------------------------------------------------------------------------

def bench_select_k(grid=None, iters: int = 10) -> List[PrimResult]:
    from raft_tpu.matrix import select_k as select_k_auto
    from raft_tpu.ops import select_k_pallas
    from raft_tpu.ops.pallas_kernels import _on_tpu

    if grid is None:
        grid = [(256, 2048, 10), (256, 16384, 10), (64, 65536, 10),
                (256, 16384, 64), (64, 65536, 64),
                # large-k tier (the reference's radix path covers
                # k ≤ 2048, select_radix.cuh): tiled two-phase vs the
                # full-sort fallback
                (64, 262144, 128), (64, 262144, 512), (256, 65536, 256)]
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for batch, length, k in grid:
        s = jnp.asarray(rng.random((batch, length), dtype=np.float32))
        p = {"batch": batch, "len": length, "k": k}
        impls = {
            "lax.top_k": lambda: jax.lax.top_k(-s, k),
            "select_k.auto": lambda: select_k_auto(s, k),
        }
        if k > 64 and length >= 4 * 16384:
            impls["tiled.16k"] = lambda: select_k_auto(s, k,
                                                       len_tile=16384)
        if _on_tpu() and k <= 64:
            impls["pallas"] = lambda: select_k_pallas(s, k)
        for name, fn in impls.items():
            ms = _time(fn, iters)
            rows.append(PrimResult("select_k", name, ms,
                                   batch * 1e3 / ms, "rows/s", p))
    return rows


# ---------------------------------------------------------------------------
# fused_l2_nn (reference: bench/prims/distance/fused_l2_nn.cu)
# ---------------------------------------------------------------------------

def bench_fused_l2_nn(grid=None, iters: int = 10) -> List[PrimResult]:
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
    from raft_tpu.ops.pallas_kernels import _on_tpu

    if grid is None:
        grid = [(10000, 1024, 64), (10000, 16384, 128), (100000, 1024, 128)]
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for m, n, d in grid:
        x = jnp.asarray(rng.random((m, d), dtype=np.float32))
        y = jnp.asarray(rng.random((n, d), dtype=np.float32))
        p = {"m": m, "n": n, "d": d}
        impls = {"xla": lambda: fused_l2_nn_argmin(x, y, impl="xla")}
        if _on_tpu():
            impls["pallas"] = lambda: fused_l2_nn_argmin(x, y, impl="pallas")
        for name, fn in impls.items():
            ms = _time(fn, iters)
            rows.append(PrimResult("fused_l2_nn", name, ms,
                                   m * 1e3 / ms, "rows/s", p))
    return rows


# ---------------------------------------------------------------------------
# pairwise distance (reference: bench/prims/distance/distance_*.cu)
# ---------------------------------------------------------------------------

def bench_pairwise(grid=None, iters: int = 10) -> List[PrimResult]:
    from raft_tpu.distance import pairwise_distance

    if grid is None:
        grid = [("sqeuclidean", 4096, 4096, 128), ("cosine", 4096, 4096, 128),
                ("l1", 2048, 2048, 128)]
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for metric, m, n, d in grid:
        x = jnp.asarray(rng.random((m, d), dtype=np.float32))
        y = jnp.asarray(rng.random((n, d), dtype=np.float32))
        # reduce INSIDE the measured program: the [m, n] output is tens
        # of MB, and the device_get fence would otherwise time the
        # host-transfer, not the kernel (the sum blocks DCE; XLA may
        # fuse away the final HBM write, which a real consumer often
        # does too)
        f = jax.jit(lambda x_, y_, _mt=metric: jnp.sum(
            pairwise_distance(x_, y_, metric=_mt)))
        ms = _time(lambda: f(x, y), iters)
        rows.append(PrimResult(
            "pairwise", metric, ms, m * n * 1e3 / ms, "pairs/s",
            {"m": m, "n": n, "d": d, "metric": metric}))
    return rows


# ---------------------------------------------------------------------------
# kmeans Lloyd step (reference: bench/prims/cluster/kmeans.cu)
# ---------------------------------------------------------------------------

def bench_kmeans(grid=None, iters: int = 5) -> List[PrimResult]:
    from raft_tpu.cluster import KMeansParams, kmeans

    if grid is None:
        grid = [(100000, 64, 256), (100000, 128, 1024)]
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for n, d, clusters in grid:
        x = jnp.asarray(rng.random((n, d), dtype=np.float32))
        p = KMeansParams(n_clusters=clusters, max_iter=5, seed=0)
        ms = _time(lambda: kmeans.fit(p, x), iters=iters, warmup=1)
        rows.append(PrimResult(
            "kmeans.fit5", "lloyd", ms, n * 5 * 1e3 / ms, "row-iters/s",
            {"n": n, "d": d, "clusters": clusters}))
    return rows


# ---------------------------------------------------------------------------
# IVF scan-mode crossover (grouped vs per-query; sets scan_mode="auto")
# ---------------------------------------------------------------------------

def bench_ivf_scan(batches=(16, 64, 256, 1024, 4096), n: int = 200_000,
                   d: int = 96, n_lists: int = 1024, n_probes: int = 20,
                   iters: int = 5) -> List[PrimResult]:
    from raft_tpu.neighbors import ivf_pq

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((n, d), dtype=np.float32))
    index = ivf_pq.build(x, ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=max(8, d // 2 // 8 * 8), seed=0))
    q_all = jnp.asarray(rng.random((max(batches), d), dtype=np.float32))
    rows: List[PrimResult] = []
    for b in batches:
        q = q_all[:b]
        for mode in ("grouped", "per_query"):
            sp = ivf_pq.SearchParams(n_probes=n_probes, scan_mode=mode)
            ms = _time(lambda: ivf_pq.search(index, q, 10, sp),
                       iters=iters, warmup=1)
            rows.append(PrimResult(
                "ivf_pq.scan", mode, ms, b * 1e3 / ms, "queries/s",
                {"batch": b, "n": n, "n_lists": n_lists,
                 "n_probes": n_probes}))
    return rows


# ---------------------------------------------------------------------------
# IVF-PQ scan kernels: XLA one-hot grouped scan vs fused Pallas LUT scan
# ---------------------------------------------------------------------------

def bench_pq_scan(grid=None, iters: int = 3) -> List[PrimResult]:
    """One-hot (XLA grouped) vs fused Pallas LUT-scan row per config —
    the measurement behind the ``scan_select="pallas"`` dispatch tier
    (reference: the compute_similarity kernel benches under
    cpp/bench/prims). The index is built WITHOUT the recon cache so the
    one-hot path actually pays its per-chunk decode, as the DEEP-100M
    regime does. Off-TPU the Pallas row runs in interpreter mode and its
    time is meaningless — it is kept tiny and flagged via params."""
    from raft_tpu.neighbors import ivf_common as ic
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.ivf_pq import packed_nbytes
    from raft_tpu.ops.pallas_kernels import (LUT_SCAN_BINS, _on_tpu,
                                             pallas_lut_scan_wanted)

    on_tpu = _on_tpu()
    if grid is None:
        # (n, d, n_lists, n_probes, k_cand, batch)
        grid = ([(200_000, 96, 512, 64, 400, 2000)] if on_tpu
                else [(4_000, 32, 16, 8, 40, 128)])
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for n, d, n_lists, n_probes, k_cand, batch in grid:
        x = jnp.asarray(rng.random((n, d), dtype=np.float32))
        index = ivf_pq.build(x, ivf_pq.IndexParams(
            n_lists=n_lists, pq_dim=max(8, d // 2 // 8 * 8), seed=0,
            cache_reconstruction="never"))
        q = x[:batch]
        p = {"n": n, "d": d, "n_lists": n_lists, "n_probes": n_probes,
             "k_cand": k_cand, "batch": batch, "on_tpu": on_tpu}
        impls = {"one_hot": ivf_pq.SearchParams(
            n_probes=n_probes, scan_mode="grouped", scan_select="exact")}
        # gate the pallas row with search()'s FULL dispatch condition —
        # a declined request silently falls back, which would mislabel
        # this row: kernel layout/VMEM check, bin capacity for k_cand,
        # the HBM guard, and the codebook kind
        n_seg = ic.n_segments(batch * n_probes, n_lists, ic.SEGMENT_SIZE)
        lut_ok = (n_probes * LUT_SCAN_BINS >= k_cand
                  and index.codebook_kind == "per_subspace"
                  and ic.lut_scan_mem_ok(n_seg, ic.SEGMENT_SIZE,
                                         index.rot_dim, batch * n_probes,
                                         LUT_SCAN_BINS)
                  and pallas_lut_scan_wanted(
                      index.pq_dim, index.pq_book_size, index.pq_len,
                      packed_nbytes(index.pq_dim, index.pq_bits),
                      index.packed_codes.shape[-1], index.max_list_size,
                      index.rot_dim, lut_dtype="bfloat16"))
        if lut_ok:
            impls["pallas_lut"] = ivf_pq.SearchParams(
                n_probes=n_probes, scan_mode="grouped",
                scan_select="pallas", lut_dtype="bfloat16")
        for name, sp in impls.items():
            ms = _time(lambda: ivf_pq.search(index, q, k_cand, sp),
                       iters=iters, warmup=1)
            rows.append(PrimResult("ivf_pq.lut_scan", name, ms,
                                   batch * 1e3 / ms, "queries/s", p))
        # FILTERED rows (ISSUE 12 acceptance): the fused filtered scan
        # vs the forced-fallback tier the same filtered shape used to
        # pay (grouped XLA under RAFT_TPU_PALLAS_LUTSCAN=never) at 10%
        # selectivity — the cliff this PR removes, as one prims pair
        from raft_tpu.core import bitset as _bitset

        keep = rng.random(n) < 0.1
        fb = _bitset.from_mask(jnp.asarray(keep))
        pf = {**p, "filter_selectivity": 0.1}
        # the filtered gate re-checks the kernel admission with
        # filtered=True — the filter-byte slots + unpack selection
        # matrix grow the VMEM model, so a shape that fits unfiltered
        # can still decline filtered (search() would silently run the
        # approx tier and this row would be mislabeled)
        filtered_ok = (lut_ok
                       and ic.filtered_scan_mem_ok(
                           n_lists, index.max_list_size)
                       and pallas_lut_scan_wanted(
                           index.pq_dim, index.pq_book_size,
                           index.pq_len,
                           packed_nbytes(index.pq_dim, index.pq_bits),
                           index.packed_codes.shape[-1],
                           index.max_list_size, index.rot_dim,
                           lut_dtype="bfloat16", filtered=True))
        if filtered_ok:
            sp_f = ivf_pq.SearchParams(
                n_probes=n_probes, scan_mode="grouped",
                scan_select="pallas", lut_dtype="bfloat16")
            ms = _time(lambda: ivf_pq.search(index, q, k_cand, sp_f,
                                             filter_bitset=fb),
                       iters=iters, warmup=1)
            rows.append(PrimResult("ivf_pq.lut_scan",
                                   "filtered_pallas_lut", ms,
                                   batch * 1e3 / ms, "queries/s", pf))
        else:
            rows.append(PrimResult("ivf_pq.lut_scan",
                                   "filtered_pallas_skipped", 0.0, 0.0,
                                   "queries/s",
                                   {**pf, "skipped": "outside the "
                                    "kernel/HBM gate"}))
        from raft_tpu.bench.runner import _scoped_env

        with _scoped_env({"RAFT_TPU_PALLAS_LUTSCAN": "never"}):
            sp_u = ivf_pq.SearchParams(n_probes=n_probes,
                                       scan_mode="grouped",
                                       scan_select="approx")
            ms = _time(lambda: ivf_pq.search(index, q, k_cand, sp_u,
                                             filter_bitset=fb),
                       iters=iters, warmup=1)
            rows.append(PrimResult("ivf_pq.lut_scan",
                                   "filtered_fallback", ms,
                                   batch * 1e3 / ms, "queries/s", pf))
    return rows


# ---------------------------------------------------------------------------
# refine: XLA einsum-gather vs fused Pallas gather-refine
# ---------------------------------------------------------------------------

def bench_refine(grid=None, iters: int = 3) -> List[PrimResult]:
    """Einsum-gather XLA refine vs the fused Pallas gather-refine tier —
    the measurement behind ``neighbors.refine``'s dispatch (reference:
    the refinement kernels' gbench rows under cpp/bench/prims). Each
    impl is forced through the ``RAFT_TPU_PALLAS_REFINE`` override so a
    silent dispatch fallback cannot mislabel a row. The einsum row
    materializes the ``[m, C, d]`` gather buffer, so it only runs where
    that buffer is survivable; the batch-10000 × k_cand-2000 acceptance
    shape runs fused-only (its skipped einsum twin is recorded in
    params — at 7.7 GB the buffer IS the reason the tier exists, and a
    deliberately-OOMing row would kill the whole sweep). Off-TPU the
    pallas row runs in interpreter mode and its time is meaningless —
    kept tiny and flagged via params."""
    import os

    from raft_tpu.neighbors import refine as refine_mod
    from raft_tpu.ops.pallas_kernels import (_on_tpu,
                                             pallas_gather_refine_wanted)

    on_tpu = _on_tpu()
    if grid is None:
        # (n, d, m, k_cand, k)
        grid = ([(200_000, 96, 2_500, 2000, 10),
                 (200_000, 96, 10_000, 2000, 10)] if on_tpu
                else [(2_000, 32, 64, 256, 8)])
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    prev = os.environ.get("RAFT_TPU_PALLAS_REFINE")
    try:
        for n, d, m, C, k in grid:
            x = jnp.asarray(rng.random((n, d), dtype=np.float32))
            q = jnp.asarray(rng.random((m, d), dtype=np.float32))
            cand = jnp.asarray(
                rng.integers(0, n, (m, C)).astype(np.int32))
            buf_gib = m * C * d * 4 / 2**30
            p = {"n": n, "d": d, "m": m, "k_cand": C, "k": k,
                 "gather_buffer_gib": round(buf_gib, 2), "on_tpu": on_tpu}
            impls = {}
            if buf_gib <= 2.5:
                impls["einsum_gather"] = "never"
            else:
                p["einsum_skipped"] = (f"[m, C, d] buffer "
                                       f"{buf_gib:.1f} GiB")
            # gate the pallas row under the SAME force it will run with
            # (off-TPU the auto gate always declines, and an env value
            # left over from the previous impl must not leak into this
            # decision); skips are recorded, not silent
            os.environ["RAFT_TPU_PALLAS_REFINE"] = "always"
            if pallas_gather_refine_wanted(m, C, d, k):
                impls["pallas_gather"] = "always"
            else:
                p["pallas_skipped"] = "shape outside the kernel gate"
            for name, force in impls.items():
                os.environ["RAFT_TPU_PALLAS_REFINE"] = force
                ms = _time(lambda: refine_mod.refine(x, q, cand, k),
                           iters=iters, warmup=1)
                rows.append(PrimResult("refine", name, ms,
                                       m * 1e3 / ms, "queries/s", p))
            if not impls:
                rows.append(PrimResult("refine", "skipped", 0.0, 0.0,
                                       "queries/s", p))
    finally:
        if prev is None:
            os.environ.pop("RAFT_TPU_PALLAS_REFINE", None)
        else:
            os.environ["RAFT_TPU_PALLAS_REFINE"] = prev
    return rows


# ---------------------------------------------------------------------------
# tiered refine: HBM-resident vs host-prefetched vs serialized (ISSUE 17)
# ---------------------------------------------------------------------------

def bench_tiered_refine(grid=None, iters: int = 3) -> List[PrimResult]:
    """The memory-tiered refined search, three residency legs per
    config (reference claim: the host→HBM candidate-row fetch hides
    under the LUT scan):

    - ``hbm_resident``: the raw vectors live on device — the refine
      dispatch tiers run without any transfer (the ceiling);
    - ``tiered_prefetch``: host-resident base, candidate rows fetched
      by the :class:`~raft_tpu.neighbors.tiered.RowPrefetcher` pipeline
      overlapped under the scan (``refine_transfer="tiered"``);
    - ``serialized_gather``: the same host base through the serialized
      host gather (``refine_transfer="serial"``) — what the fetch costs
      when nothing hides it.

    Params carry the roofline context: ``h2d_gib`` (candidate rows
    crossing host→HBM per search) and, for the host legs, the
    effective ``h2d_gibps`` that wall implies, plus the tiered leg's
    hit/stall split (hits ≫ stalls is the overlap working). A config
    the mem guard declines records a ``tiered_skipped`` param instead
    of a silent hole."""
    import dataclasses

    from raft_tpu import obs
    from raft_tpu.neighbors import ivf_pq, tiered
    from raft_tpu.ops.pallas_kernels import _on_tpu

    on_tpu = _on_tpu()
    if grid is None:
        # (n, d, m, k)
        grid = ([(500_000, 96, 1024, 10)] if on_tpu
                else [(20_000, 32, 256, 10)])
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for n, d, m, k in grid:
        x = rng.random((n, d), dtype=np.float32)
        x_dev = jnp.asarray(x)
        q = jnp.asarray(rng.random((m, d), dtype=np.float32))
        idx = ivf_pq.build(x_dev, ivf_pq.IndexParams(
            n_lists=64 if on_tpu else 16, pq_dim=min(d, 32), seed=0,
            cache_reconstruction="never"))
        base = ivf_pq.SearchParams(n_probes=16, refine="f32_regen",
                                   refine_ratio=4.0,
                                   lut_dtype="float32")
        k_cand = int(k * base.refine_ratio)
        h2d_gib = m * k_cand * d * 4 / 2**30
        p = {"n": n, "d": d, "m": m, "k": k, "k_cand": k_cand,
             "h2d_gib": round(h2d_gib, 4), "on_tpu": on_tpu,
             "pipeline_batch": tiered.pipeline_batch(m)}
        tiered_params = dataclasses.replace(base,
                                            refine_transfer="tiered")
        legs = [("hbm_resident", x_dev, base),
                ("serialized_gather", x,
                 dataclasses.replace(base, refine_transfer="serial"))]
        if tiered.tiered_refine_wanted(x, m, k_cand, d, tiered_params):
            legs.insert(1, ("tiered_prefetch", x, tiered_params))
        else:
            p["tiered_skipped"] = ("mem guard or shape declined the "
                                   "prefetch pipeline")
        for name, base_ds, params in legs:
            lp = dict(p)
            if name == "tiered_prefetch":
                # one un-timed pass with recording on: the hit/stall
                # split is the overlap evidence riding next to the wall
                reg = obs.MetricsRegistry()
                obs.enable(registry=reg, hbm=False)
                try:
                    ivf_pq.search(idx, q, k, params, dataset=base_ds)
                finally:
                    obs.disable()
                c = reg.snapshot()["counters"]
                lp["prefetch_hits"] = int(sum(
                    v for key, v in c.items()
                    if key.startswith("serve.prefetch.hit")))
                lp["prefetch_stalls"] = int(sum(
                    v for key, v in c.items()
                    if key.startswith("serve.prefetch.stall")))
            ms = _time(lambda: ivf_pq.search(idx, q, k, params,
                                             dataset=base_ds),
                       iters=iters, warmup=1)
            if name != "hbm_resident":
                lp["h2d_gibps"] = round(h2d_gib / (ms / 1e3), 3)
            rows.append(PrimResult("tiered_refine", name, ms,
                                   m * 1e3 / ms, "queries/s", lp))
    return rows


# ---------------------------------------------------------------------------
# build encode throughput: serial build_chunked vs the prefetch-
# overlapped distributed encode (ISSUE 13)
# ---------------------------------------------------------------------------

def bench_build_encode(grid=None, iters: int = 1) -> List[PrimResult]:
    """Serial ``build_chunked`` vs the distributed prefetch-overlapped
    encode — the measurement behind build-throughput (vectors/s/chip),
    ROADMAP item 2's first-class build metric. Three rows per config:

    - ``build_chunked``: the single-host serial walk (read → H2D →
      encode strictly in sequence), vectors/s;
    - ``distributed_serial``: the sharded walk with ``prefetch=False``
      (serialized copy-then-encode per shard) — the overlap baseline;
    - ``distributed_prefetch``: the same walk with the double-buffered
      host→HBM prefetcher — chunk N+1's read+transfer hidden under
      chunk N's encode. vectors/s/chip = n / wall / n_dev (the CPU-mesh
      emulation walks shards sequentially, so total wall ≈ n_dev × the
      per-shard wall a real pod would pay).

    The distributed rows need a ≥ 2-device mesh; a 1-device host
    records the skip instead of silently dropping the row. Each row
    carries the PR-9 roofline columns of the jitted per-chunk encode
    program (the pass's hot program), attributed from the measured
    per-chunk encode time."""
    import jax

    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.obs import prof as _prof

    n_dev = len(jax.devices())
    if grid is None:
        # (n, d, n_lists, chunk_rows)
        grid = [(60_000, 32, 16, 4096)]
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for n, d, n_lists, chunk_rows in grid:
        x = rng.random((n, d), dtype=np.float32)
        params = ivf_pq.IndexParams(n_lists=n_lists,
                                    pq_dim=max(8, d // 2 // 8 * 8),
                                    kmeans_n_iters=4, seed=0,
                                    cache_reconstruction="never")
        p = {"n": n, "d": d, "n_lists": n_lists,
             "chunk_rows": chunk_rows, "n_dev": n_dev}

        # roofline attribution of the per-chunk encode program (the
        # walk's hot program; cost columns describe what the rows time)
        idx0 = ivf_pq.build(jnp.asarray(x[:4096]), params)
        xb = jnp.asarray(x[:chunk_rows])
        lb = jnp.zeros((chunk_rows,), jnp.int32)
        t_enc0 = time.perf_counter()
        jax.block_until_ready(ivf_pq._encode_with_norms(
            xb @ idx0.rotation.T, idx0.centers_rot, lb, idx0.codebooks,
            "per_subspace"))
        enc_s = time.perf_counter() - t_enc0
        cost = _prof.analyze_jit(
            lambda xb_, lb_: ivf_pq._encode_with_norms(
                xb_ @ idx0.rotation.T, idx0.centers_rot, lb_,
                idx0.codebooks, "per_subspace"),
            xb, lb, elapsed_s=enc_s)
        if cost is not None:
            p.update(flops=cost.flops, bytes_accessed=cost.bytes_accessed,
                     arith_intensity=cost.arithmetic_intensity,
                     bound=cost.bound)

        # untimed warm pass over a prefix: the first build at a shape
        # pays the jit compiles — without a warm-up they land in
        # whichever row runs first and the serial-vs-prefetch (and
        # chunked-vs-distributed) comparison measures compile cost
        warm_n = min(n, 4 * chunk_rows)
        ivf_pq.build_chunked(x[:warm_n], params, chunk_rows=chunk_rows)
        t0 = time.perf_counter()
        ivf_pq.build_chunked(x, params, chunk_rows=chunk_rows)
        wall = time.perf_counter() - t0
        rows.append(PrimResult("build_encode", "build_chunked",
                               wall * 1e3, n / wall, "vectors/s", p))
        if n_dev < 2:
            rows.append(PrimResult(
                "build_encode", "distributed_skipped", 0.0, 0.0,
                "vectors/s/chip",
                {**p, "skipped": f"{n_dev} device(s): no mesh axis to "
                                 "shard the build over"}))
            continue
        from raft_tpu.parallel import make_mesh

        mesh = make_mesh()
        ivf_pq.build_distributed(x, params, mesh=mesh,
                                 chunk_rows=chunk_rows, prefetch=False)
        for impl, prefetch in (("distributed_serial", False),
                               ("distributed_prefetch", True)):
            t0 = time.perf_counter()
            ivf_pq.build_distributed(x, params, mesh=mesh,
                                     chunk_rows=chunk_rows,
                                     prefetch=prefetch)
            wall = time.perf_counter() - t0
            rows.append(PrimResult(
                "build_encode", impl, wall * 1e3, n / wall / n_dev,
                "vectors/s/chip", p))
    return rows


def measure_merge_tier(mesh, x, q, k: int, tier: str, iters: int = 3,
                       schedule: Optional[str] = None,
                       with_cost: bool = False, axis="shard",
                       per_axis: bool = False):
    """Measure ONE cross-shard merge tier through sharded kNN on
    ``mesh``: returns ``(median ms per call, merge-phase comms bytes,
    cost)`` where ``cost`` is the PR-9 roofline attribution of the
    measured ring/merge program (an ``obs.prof.ProgramCost``, or
    ``None`` when ``with_cost`` is off or the closure won't lower).
    The single harness behind both the prims `ring_merge`/`hier_merge`
    rows and the dryrun's MULTICHIP scaling rows — byte-model or
    dispatch changes land in one place. Jits once so timed calls hit
    the cache (a bare ``sharded_knn`` call rebuilds its shard_map
    closure and re-traces every call — that would time the tracer),
    and enables a private registry only around the tracing call so the
    per-trace comms counters attribute exactly one merge.

    ``axis`` is forwarded to ``sharded_knn`` — pass the ``(outer,
    inner)`` tuple of a 2-D hier mesh to measure the ``hier`` tier (or
    the flat-ring comparator over the same two axes). With
    ``per_axis=True`` the bytes slot becomes a ``{axis_name: bytes}``
    dict split over the PR-19 per-axis attribution instead of one sum
    — how the scaling rows prove DCN traffic is O(k·pods).

    ``schedule`` env-forces the ring kernel's hop schedule
    (``RAFT_TPU_RING_OVERLAP``: "overlap" → on, "serial" → off) around
    BOTH the trace and the timed calls — the dispatch is read at trace
    time, so the force must cover the jit."""
    import os

    from raft_tpu import obs
    from raft_tpu.obs import spans as _spans
    from raft_tpu.obs.metrics import MetricsRegistry
    from raft_tpu.parallel import sharded_knn

    ops = {"ring": ("ring_topk",), "allgather": ("allgather",),
           "hier": ("ring_topk", "alltoall")}[tier]
    prev_env = os.environ.get("RAFT_TPU_RING_OVERLAP")
    if schedule is not None:
        os.environ["RAFT_TPU_RING_OVERLAP"] = (
            "on" if schedule == "overlap" else "off")
    try:
        fn = jax.jit(
            lambda xx, qq: sharded_knn(xx, qq, k, mesh, merge=tier,
                                       axis=axis))
        reg = MetricsRegistry()
        prev = _spans._state()  # a RAFT_TPU_OBS=1 enable must survive
        try:
            obs.enable(registry=reg, hbm=False)
            # the ONE trace: per-trace comms counters attribute exactly
            # one merge, and the AOT-compiled program below is what the
            # timed loop AND the cost attribution both use (PR-9 rule:
            # cost columns describe the measured program) — no second
            # trace, no second XLA compile
            compiled = fn.lower(x, q).compile()
        finally:
            _spans._restore(prev)
        c = reg.snapshot()["counters"]
        matched = [
            (key, v) for key, v in c.items()
            if key.startswith("comms.bytes{")
            and any(f"op={o}" in key for o in ops)]
        if per_axis:
            merge_bytes: Dict[str, int] = {}
            for key, v in matched:
                labels = dict(kv.split("=", 1) for kv
                              in key[key.index("{") + 1:-1].split(","))
                ax = labels.get("axis", "")
                merge_bytes[ax] = merge_bytes.get(ax, 0) + int(v)
        else:
            merge_bytes = int(sum(v for _, v in matched))
        ms = _time(lambda: compiled(x, q)[0], iters=iters, warmup=1)
        cost = None
        if with_cost:
            from raft_tpu.obs import prof as _prof

            try:
                cost = _prof.analyze_compiled(compiled,
                                              elapsed_s=ms / 1e3)
            except Exception:
                cost = None
    finally:
        if schedule is not None:
            if prev_env is None:
                os.environ.pop("RAFT_TPU_RING_OVERLAP", None)
            else:
                os.environ["RAFT_TPU_RING_OVERLAP"] = prev_env
    return ms, merge_bytes, cost


def bench_ring_merge(grid=None, iters: int = 3) -> List[PrimResult]:
    """Allgather-and-select vs the ring top-k exchange behind sharded
    search (``parallel.merge``) — the measurement grounding the merge
    tier's dispatch and the MULTICHIP scaling rows. Each row runs
    sharded kNN over the full local mesh with the merge tier forced,
    and decomposes the merge's interconnect cost from the PR-5
    ``comms.bytes`` counters (allgather: the materialized table; ring:
    n_dev−1 surviving-block hops). The ring tier measures BOTH hop
    schedules (``ring_serial`` = the PR-8 bulk-synchronous exchange,
    ``ring_overlap`` = the half-pipelined compute/comms-overlapped
    schedule, env-forced per row) plus the PR-9 roofline attribution
    of the measured ring program (flops/bytes/bound columns). Off-TPU
    the ring rides the ppermute fallback — identical schedule and
    identical counted bytes; wall time is CPU-mesh-shaped and the two
    schedule rows measure the same fallback program (the overlap is a
    kernel-internal property), so the comparison column is only
    load-bearing on real TPU rows."""
    from raft_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    if n_dev < 2:
        return [PrimResult("ring_merge", "skipped", 0.0, 0.0, "queries/s",
                           {"reason": f"{n_dev} device(s): no mesh axis "
                                      "to merge across"})]
    if grid is None:
        # (n, d, m, k)
        grid = [(32_768, 64, 1024, 10), (32_768, 64, 1024, 64)]
    mesh = make_mesh()
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    legs = (("allgather", "allgather", None),
            ("ring", "ring_serial", "serial"),
            ("ring", "ring_overlap", "overlap"))
    for n, d, m, k in grid:
        x = jnp.asarray(rng.random((n, d), dtype=np.float32))
        q = jnp.asarray(rng.random((m, d), dtype=np.float32))
        for tier, impl, schedule in legs:
            ms, merge_bytes, cost = measure_merge_tier(
                mesh, x, q, k, tier, iters=iters, schedule=schedule,
                with_cost=True)
            p = {"n": n, "d": d, "m": m, "k": k, "n_dev": n_dev,
                 "merge_bytes": merge_bytes}
            if schedule is not None:
                p["schedule"] = schedule
            if cost is not None:
                p.update(flops=cost.flops,
                         bytes_accessed=cost.bytes_accessed,
                         arith_intensity=cost.arithmetic_intensity,
                         bound=cost.bound)
            rows.append(PrimResult(
                "ring_merge", impl, ms, m * 1e3 / ms, "queries/s", p))
    return rows


def bench_hier_merge(grid=None, iters: int = 3) -> List[PrimResult]:
    """Flat single-ring vs the two-level ICI→DCN merge (ISSUE 19) on a
    2×(n_dev/2) hier mesh carved from the local devices. Both rows run
    the SAME sharded kNN over the same two mesh axes — only the merge
    tier differs — and decompose the merge's interconnect traffic into
    per-axis ``dcn_bytes``/``ici_bytes`` columns from the PR-19
    per-axis ``comms.bytes`` attribution. The load-bearing comparison
    is the DCN column: the flat ring drags whole surviving blocks
    across every hop including the slow cross-pod edges, while the
    hier tier's survivor exchange moves O(k·pods) rows — ``dcn_bytes``
    must sit strictly below the flat row's. Wall time is only
    meaningful on real multi-pod hardware (a CPU host mesh has no slow
    axis); the byte columns are layout-independent."""
    from raft_tpu.parallel import hier_mesh

    n_dev = len(jax.devices())
    if n_dev < 4 or n_dev % 2:
        return [PrimResult(
            "hier_merge", "skipped", 0.0, 0.0, "queries/s",
            {"reason": f"{n_dev} device(s): need an even mesh of >= 4 "
                       "to carve into pods"})]
    n_outer, n_inner = 2, n_dev // 2
    mesh = hier_mesh(n_inner, n_outer)
    axis = ("dcn", "ici")
    if grid is None:
        # (n, d, m, k)
        grid = [(32_768, 64, 1024, 10), (32_768, 64, 1024, 64)]
    rows: List[PrimResult] = []
    rng = np.random.default_rng(0)
    for n, d, m, k in grid:
        x = jnp.asarray(rng.random((n, d), dtype=np.float32))
        q = jnp.asarray(rng.random((m, d), dtype=np.float32))
        for tier, impl in (("ring", "flat_ring"), ("hier", "hier")):
            ms, by_axis, _ = measure_merge_tier(
                mesh, x, q, k, tier, iters=iters, axis=axis,
                per_axis=True)
            p = {"n": n, "d": d, "m": m, "k": k, "n_dev": n_dev,
                 "mesh": f"{n_outer}x{n_inner}",
                 "dcn_bytes": by_axis.get("dcn", 0),
                 "ici_bytes": by_axis.get("ici", 0)}
            rows.append(PrimResult(
                "hier_merge", impl, ms, m * 1e3 / ms, "queries/s", p))
    return rows


BENCHES: Dict[str, Callable[[], List[PrimResult]]] = {
    "select_k": bench_select_k,
    "fused_l2_nn": bench_fused_l2_nn,
    "pairwise": bench_pairwise,
    "kmeans": bench_kmeans,
    "ivf_scan": bench_ivf_scan,
    "pq_scan": bench_pq_scan,
    "refine": bench_refine,
    "tiered_refine": bench_tiered_refine,
    "ring_merge": bench_ring_merge,
    "hier_merge": bench_hier_merge,
    "build_encode": bench_build_encode,
}


def run(names=("all",)) -> List[PrimResult]:
    picked = list(BENCHES) if "all" in names else list(names)
    rows: List[PrimResult] = []
    for name in picked:
        if name not in BENCHES:
            raise ValueError(f"unknown bench {name!r} (have {sorted(BENCHES)})")
        rows.extend(BENCHES[name]())
    return rows


def export_csv(rows: List[PrimResult], path: str) -> None:
    import csv
    import json as _json

    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["bench", "impl", "ms", "throughput", "unit", "params"])
        for r in rows:
            w.writerow([r.bench, r.impl, f"{r.ms:.4f}",
                        f"{r.throughput:.1f}", r.unit, _json.dumps(r.params)])


def export_json(rows: List[PrimResult], path: str) -> None:
    """Self-describing record: rows + the same environment-provenance
    stamp bench rows carry (``runner.environment_stamp``), so prim
    measurements from different chips/jax builds are never compared as
    if they were the same machine."""
    import json as _json
    import time as _time

    from raft_tpu.bench.runner import environment_stamp

    doc = {
        "schema": "raft_tpu.prims/1",
        "measured_at": _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      _time.gmtime()),
        "env": environment_stamp(),
        "rows": [{"bench": r.bench, "impl": r.impl, "ms": r.ms,
                  "throughput": r.throughput, "unit": r.unit,
                  "params": r.params} for r in rows],
    }
    with open(path, "w") as f:
        _json.dump(doc, f, indent=1)


def main(argv: Optional[List[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="raft_tpu prim micro-benchmarks")
    ap.add_argument("benches", nargs="*", default=["all"])
    ap.add_argument("--csv", default=None)
    ap.add_argument("--json", default=None,
                    help="write rows + environment-provenance stamp "
                         "as one JSON record")
    args = ap.parse_args(argv)
    from raft_tpu.bench.runner import environment_stamp

    env = environment_stamp()
    print(f"[prims] env: jax={env.get('jax')} backend={env.get('backend')} "
          f"{env.get('device_kind')} x{env.get('device_count')}")
    rows = run(args.benches or ["all"])
    for r in rows:
        print(f"{r.bench:14s} {r.impl:14s} {r.ms:10.3f} ms "
              f"{r.throughput:14,.0f} {r.unit:12s} {r.params}")
    if args.csv:
        export_csv(rows, args.csv)
    if args.json:
        export_json(rows, args.json)


if __name__ == "__main__":
    main()
