"""ANN benchmark harness — TPU-native counterpart of the reference's
cpp/bench/ann + python/raft-ann-bench (SURVEY.md §2.16)."""

from . import dataset, runner  # noqa: F401
