"""CLI: python -m raft_tpu.bench run <config.json> [--out results.csv]
(reference: the raft-ann-bench CLI, run/__main__.py + data_export)."""

import argparse
import sys


def main(argv=None):
    p = argparse.ArgumentParser(prog="raft_tpu.bench")
    sub = p.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run", help="run a benchmark config")
    runp.add_argument("config")
    runp.add_argument("--out", default=None, help="CSV output path")
    runp.add_argument("--pareto", action="store_true",
                      help="print the QPS/recall pareto frontier")
    primsp = sub.add_parser("prims",
                            help="primitive micro-benchmarks "
                                 "(reference: cpp/bench/prims)")
    primsp.add_argument("benches", nargs="*", default=["all"])
    primsp.add_argument("--csv", default=None)
    args = p.parse_args(argv)

    if args.cmd == "prims":
        from raft_tpu.bench import prims

        prims.main((args.benches or ["all"]) +
                   (["--csv", args.csv] if args.csv else []))
        return 0

    from raft_tpu.bench import runner

    results = runner.run_config_file(args.config)
    if args.out:
        runner.export_csv(results, args.out)
        print(f"[bench] wrote {args.out}")
    if args.pareto:
        for r in runner.pareto_frontier(results):
            print(f"[pareto] {r.index_name} {r.search_param} "
                  f"qps={r.qps:,.0f} recall={r.recall:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
