"""CLI: python -m raft_tpu.bench run <config.json> [--out results.csv]
(reference: the raft-ann-bench CLI, run/__main__.py + data_export)."""

import argparse
import sys


def main(argv=None):
    p = argparse.ArgumentParser(prog="raft_tpu.bench")
    sub = p.add_subparsers(dest="cmd", required=True)
    runp = sub.add_parser("run", help="run a benchmark config")
    runp.add_argument("config")
    runp.add_argument("--out", default=None, help="CSV output path")
    runp.add_argument("--pareto", action="store_true",
                      help="print the QPS/recall pareto frontier")
    primsp = sub.add_parser("prims",
                            help="primitive micro-benchmarks "
                                 "(reference: cpp/bench/prims)")
    primsp.add_argument("benches", nargs="*", default=["all"])
    primsp.add_argument("--csv", default=None)
    getp = sub.add_parser("get-dataset",
                          help="fetch/convert an ann-benchmarks hdf5 "
                               "dataset (reference: get_dataset)")
    getp.add_argument("--dataset", default=None,
                      help="dataset name, e.g. sift-128-euclidean")
    getp.add_argument("--hdf5", default=None,
                      help="convert a local .hdf5 instead of fetching")
    getp.add_argument("--out", default="datasets",
                      help="dataset root directory")
    getp.add_argument("--normalize", action="store_true",
                      help="L2-normalize rows (angular → inner product)")
    splitp = sub.add_parser("split-groundtruth",
                            help="split a big-ann groundtruth binary "
                                 "(reference: split_groundtruth)")
    splitp.add_argument("groundtruth")
    splitp.add_argument("--out", default=None)
    plotp = sub.add_parser("plot", help="QPS/recall + build-time plots "
                                        "(reference: plot)")
    plotp.add_argument("csv", help="results CSV from `run --out`")
    plotp.add_argument("--out", default="search.png")
    plotp.add_argument("--build-out", default=None,
                       help="also write a build-time bar chart")
    plotp.add_argument("--x-scale", default="logit",
                       choices=["logit", "linear"])
    args = p.parse_args(argv)

    if args.cmd == "get-dataset":
        from raft_tpu.bench import ingest

        if args.hdf5:
            d = ingest.convert_hdf5(args.hdf5, args.out,
                                    normalize=args.normalize)
        elif args.dataset:
            d = ingest.fetch(args.dataset, args.out,
                             normalize=args.normalize)
        else:
            p.error("get-dataset needs --dataset or --hdf5")
        print(f"[bench] dataset ready at {d}")
        return 0
    if args.cmd == "split-groundtruth":
        from raft_tpu.bench import ingest

        d = ingest.split_groundtruth(args.groundtruth, args.out)
        print(f"[bench] groundtruth written under {d}")
        return 0
    if args.cmd == "plot":
        from raft_tpu.bench import plot as plot_mod

        rows = plot_mod.read_csv(args.csv)
        out = plot_mod.plot_search(rows, args.out, x_scale=args.x_scale)
        print(f"[bench] wrote {out}")
        if args.build_out:
            print(f"[bench] wrote "
                  f"{plot_mod.plot_build(rows, args.build_out)}")
        return 0

    if args.cmd == "prims":
        from raft_tpu.bench import prims

        prims.main((args.benches or ["all"]) +
                   (["--csv", args.csv] if args.csv else []))
        return 0

    from raft_tpu.bench import runner

    results = runner.run_config_file(args.config)
    if args.out:
        runner.export_csv(results, args.out)
        print(f"[bench] wrote {args.out}")
    if args.pareto:
        for r in runner.pareto_frontier(results):
            print(f"[pareto] {r.index_name} {r.search_param} "
                  f"qps={r.qps:,.0f} recall={r.recall:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
