"""Benchmark dataset management — .fbin/.ibin files, synthetic sets,
groundtruth.

TPU-native counterpart of the reference's bench dataset layer
(cpp/bench/ann/src/common/dataset.hpp: BinFile header/read/subset;
python/raft-ann-bench get_dataset/split_groundtruth).  Binary IO goes
through the native C++ reader (raft_tpu.native) with a numpy fallback.

A dataset directory holds::

    <name>/base.fbin           # [n, d] float32 vectors
    <name>/query.fbin          # [m, d] float32 queries
    <name>/groundtruth.ibin    # [m, k_gt] int32 exact neighbor ids
    <name>/groundtruth_dist.fbin  # [m, k_gt] float32 (optional)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import native


@dataclass
class Dataset:
    name: str
    base: np.ndarray        # [n, d] f32
    queries: np.ndarray     # [m, d] f32
    groundtruth: Optional[np.ndarray] = None  # [m, k_gt] i32
    metric: str = "sqeuclidean"

    @property
    def n(self) -> int:
        return self.base.shape[0]

    @property
    def dim(self) -> int:
        return self.base.shape[1]


def write_dataset(root: str, ds: Dataset) -> str:
    d = os.path.join(root, ds.name)
    os.makedirs(d, exist_ok=True)
    native.bin_write(os.path.join(d, "base.fbin"), ds.base.astype(np.float32))
    native.bin_write(os.path.join(d, "query.fbin"), ds.queries.astype(np.float32))
    if ds.groundtruth is not None:
        native.bin_write(os.path.join(d, "groundtruth.ibin"),
                         ds.groundtruth.astype(np.int32))
    return d


def bin_memmap(path: str, dtype) -> np.ndarray:
    """Memory-map a .fbin/.ibin file's payload as [n, d] without reading
    it (the reference's mmap path for billion-scale files,
    cpp/bench/ann/src/common/dataset.hpp BinFile::map). Row chunks are
    paged in on access and reclaimable — host RSS stays O(touched)."""
    n, d = native.bin_header(path)
    return np.memmap(path, dtype=np.dtype(dtype), mode="r", offset=8,
                     shape=(n, d))


def load_dataset(root: str, name: str, metric: str = "sqeuclidean",
                 max_rows: int = -1, mmap: bool = False) -> Dataset:
    """Load a dataset directory; ``max_rows`` subsets the base file and
    ``mmap=True`` memory-maps it instead of reading it whole (the
    reference's subset/memmap path for billion-scale files)."""
    d = os.path.join(root, name)
    if mmap:
        base = bin_memmap(os.path.join(d, "base.fbin"), np.float32)
        if max_rows >= 0:
            base = base[:max_rows]
    else:
        base = native.bin_read(os.path.join(d, "base.fbin"), np.float32,
                               count=max_rows)
    queries = native.bin_read(os.path.join(d, "query.fbin"), np.float32)
    gt_path = os.path.join(d, "groundtruth.ibin")
    gt = native.bin_read(gt_path, np.int32) if os.path.exists(gt_path) else None
    if gt is not None and 0 <= max_rows < native.bin_header(
            os.path.join(d, "base.fbin"))[0]:
        # the on-disk groundtruth covers the FULL base; against a subset
        # it contains unreachable ids and would deflate recall silently —
        # drop it so callers recompute on the subset
        gt = None
    return Dataset(name=name, base=base, queries=queries, groundtruth=gt,
                   metric=metric)


def make_synthetic(name: str, n: int, dim: int, n_queries: int,
                   metric: str = "sqeuclidean", seed: int = 0,
                   clustered: bool = True, hard: bool = False) -> Dataset:
    """Synthetic benchmark set shaped like the reference's standard ones
    (SIFT-style clustered f32).

    ``hard=True`` selects :func:`make_synthetic_hard` — many tiny
    clusters whose top-k sets cross kmeans cells, so IVF recall curves
    bend like real SIFT's instead of saturating."""
    if hard:
        return make_synthetic_hard(name, n, dim, n_queries, metric=metric,
                                   seed=seed)
    rng = np.random.default_rng(seed)
    if clustered:
        n_centers = max(16, int(np.sqrt(n)))
        centers = rng.random((n_centers, dim), dtype=np.float32) * 10.0
        assign = rng.integers(0, n_centers, n)
        base = centers[assign] + 0.5 * rng.standard_normal((n, dim), dtype=np.float32)
        q_assign = rng.integers(0, n_centers, n_queries)
        queries = centers[q_assign] + 0.5 * rng.standard_normal(
            (n_queries, dim), dtype=np.float32)
    else:
        base = rng.random((n, dim), dtype=np.float32)
        queries = rng.random((n_queries, dim), dtype=np.float32)
    return Dataset(name=name, base=base, queries=queries, metric=metric)


def make_synthetic_hard(name: str, n: int, dim: int, n_queries: int,
                        metric: str = "sqeuclidean", seed: int = 0,
                        rows_per_cluster: int = 24,
                        sigma: float = 0.45) -> Dataset:
    """Hard clustered synthetic: MANY tiny clusters, so every query's
    top-k must cross cluster/cell boundaries.

    The default :func:`make_synthetic` places ~√n Gaussian balls ~8×
    farther apart than their radius — a kmeans partition separates them
    perfectly and IVF recall saturates at tiny n_probes (VERDICT r3:
    0.9991 at n_probes=16 where real SIFT-1M needs far more). Two
    harder designs measured FLAT recall-vs-probes curves and were
    rejected: low-LID manifold clusters (foreign clusters' subspace
    arms hold neighbors whose centers rank arbitrarily far — a fixed
    fraction is unreachable at any probe count) and heavier uniform
    overlap (same mechanism). What reproduces real datasets' RISING,
    bending curve (measured 0.37→0.86 over n_probes 4→64 on a 200K
    proxy) is ``n / rows_per_cluster`` tiny clusters: a query's own
    cluster holds only ~``rows_per_cluster`` of its top-k, the rest
    come from ADJACENT clusters whose kmeans cells are ranked by
    center distance — exactly the structure probe counts pay for.

    ``sigma``: cluster radius as a fraction of the nearest-other-center
    distance (difficulty knob — bigger = more boundary crossing).
    Queries are drawn from the same distribution (the ann-benchmarks
    convention).
    """
    rng = np.random.default_rng(seed)
    n_centers = max(64, n // rows_per_cluster)
    centers = rng.standard_normal((n_centers, dim)).astype(np.float32)
    # nearest-other-center distance sets the radius scale (sample-
    # estimate on a subset to stay O(C·S)). Self pairs are masked BY
    # INDEX and the matrix computed in f64: the f32 expanded form's
    # cancellation error (~1e-3 at |c|²≈128) dwarfs a value threshold,
    # and a center "nearest to itself" gets scale ≈ 0 — its whole
    # cluster collapses into a point mass of exact ties (measured:
    # recall pinned at 0.61 at every n_probes)
    sel = rng.choice(n_centers, min(n_centers, 256), replace=False)
    sub = centers[sel].astype(np.float64)
    c64 = centers.astype(np.float64)
    d2 = (np.sum(c64**2, 1)[:, None] + np.sum(sub**2, 1)[None, :]
          - 2.0 * c64 @ sub.T)
    np.clip(d2, 0, None, out=d2)
    d2[np.arange(n_centers)[:, None] == sel[None, :]] = np.inf
    nearest = np.sqrt(d2.min(axis=1)).astype(np.float32)  # [C]
    # per-dim σ so a point's distance to its center ≈ sigma · nearest
    s = (sigma * nearest / np.sqrt(dim)).astype(np.float32)

    def sample(m, assign):
        return (centers[assign] + s[assign][:, None]
                * rng.standard_normal((m, dim)).astype(np.float32))

    assign = rng.integers(0, n_centers, n)
    base = sample(n, assign)
    q_assign = rng.integers(0, n_centers, n_queries)
    queries = sample(n_queries, q_assign)
    return Dataset(name=name, base=base, queries=queries, metric=metric)


class DeviceSyntheticChunks:
    """Deterministic clustered synthetic dataset materialized ON DEVICE
    in row chunks.

    For tunnel-attached chips host↔device runs ~25 MB/s (measured):
    streaming a 38 GB base file through the tunnel costs ~25 min PER
    PASS, while regenerating the same rows on-chip costs ~3 s per 1M
    rows — so billion-scale *synthetic* benchmarks (the DEEP-100M
    protocol shape) generate each chunk from (seed, row offset) on the
    device instead of reading a file. Every chunk is a pure function of
    the seed, so label/encode/groundtruth passes all see identical
    data; ``write_int8`` persists an SQ8 copy for the host-side refine
    gather (4× smaller than f32).

    Duck-types the slices build_chunked/compute_groundtruth take:
    ``shape``, ``provider[a:b] -> jax.Array`` (device), and
    ``sample_rows(sorted_idx)`` for trainset subsampling.
    """

    def __init__(self, n: int, dim: int, n_centers: int = 10_000,
                 seed: int = 7, std: float = 0.5, scale: float = 10.0,
                 chunk_rows: int = 1 << 20):
        import jax
        import jax.numpy as jnp

        self.shape = (n, dim)
        self.dtype = np.float32
        self.nbytes = n * dim * 4  # logical size (never materialized)
        self.chunk_rows = chunk_rows
        self._n_centers = n_centers
        self._std = std
        key = jax.random.PRNGKey(seed)
        ckey, self._akey, self._qkey = jax.random.split(key, 3)
        self.centers = jax.jit(
            lambda k: jax.random.uniform(k, (n_centers, dim)) * scale)(ckey)

        import functools

        @functools.partial(jax.jit, static_argnames=("m",))
        def gen(centers, akey, start, m):
            kk = jax.random.fold_in(akey, start)
            k1, k2 = jax.random.split(kk)
            assign = jax.random.randint(k1, (m,), 0, n_centers)
            return (centers[assign]
                    + std * jax.random.normal(k2, (m, dim), jnp.float32))

        self._gen = gen

    def _block(self, bi: int):
        """Internal FIXED-size generation block ``bi`` — row content is a
        function of the block index alone, so consumers slicing with any
        chunk size see identical rows (a start-offset-keyed generator
        would silently give different data per chunking)."""
        a = bi * self.chunk_rows
        m = min(self.chunk_rows, self.shape[0] - a)
        return self._gen(self.centers, self._akey, a, m)

    def __getitem__(self, sl):
        import jax.numpy as jnp

        if not isinstance(sl, slice):
            raise TypeError("DeviceSyntheticChunks supports slice access only")
        a = sl.start or 0
        b = min(sl.stop if sl.stop is not None else self.shape[0],
                self.shape[0])
        c = self.chunk_rows
        parts = []
        for bi in range(a // c, -(-b // c)):
            blk = self._block(bi)
            lo = max(a - bi * c, 0)
            hi = min(b - bi * c, blk.shape[0])
            parts.append(blk[lo:hi])
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, 0)

    def queries(self, m: int):
        """Deterministic query set from a SEPARATE key branch of the
        root split — a fold_in of the row key at any offset can collide
        with a base block's key when chunk_rows divides it, silently
        making queries bit-identical to base rows (and recall trivial)."""
        return self._gen(self.centers, self._qkey, 0, m)

    def sample_rows(self, idx: np.ndarray):
        """Gather arbitrary (sorted) rows by regenerating the covering
        chunks on device — the trainset subsample path."""
        import jax.numpy as jnp

        import time as _t

        idx = np.asarray(idx)
        out = []
        c = self.chunk_rows
        t0 = _t.time()
        n_blocks = -(-self.shape[0] // c)
        for bi, a in enumerate(range(0, self.shape[0], c)):
            b = min(a + c, self.shape[0])
            local = idx[(idx >= a) & (idx < b)] - a
            if len(local):
                out.append(self[a:b][jnp.asarray(local)])
            if bi % 25 == 24:
                print(f"[sample_rows] block {bi + 1}/{n_blocks} "
                      f"({_t.time() - t0:.0f}s)", flush=True)
        return jnp.concatenate(out, axis=0)

    def write_int8(self, path: str, progress: bool = False):
        """Persist an SQ8 copy (for host-side refine gathers) +
        (scale, zero) dequant vectors. Returns (scale, zero)."""
        import struct

        import jax
        import jax.numpy as jnp

        n, d = self.shape
        # quantization range from one chunk (same distribution everywhere)
        x0 = self[0:min(n, self.chunk_rows)]
        mn = np.asarray(jnp.min(x0, axis=0))
        mx = np.asarray(jnp.max(x0, axis=0))
        zero = ((mn + mx) / 2).astype(np.float32)
        scale = np.maximum((mx - mn) / 254.0, 1e-12).astype(np.float32)
        zj, sj = jnp.asarray(zero), jnp.asarray(scale)

        @jax.jit
        def quant(x):
            return jnp.clip(jnp.round((x - zj) / sj), -127, 127
                            ).astype(jnp.int8)

        with open(path, "wb") as f:
            f.write(struct.pack("<ii", n, d))
            for a in range(0, n, self.chunk_rows):
                b = min(a + self.chunk_rows, n)
                f.write(np.asarray(jax.device_get(
                    quant(self[a:b]))).tobytes())
                if progress and a % (8 * self.chunk_rows) == 0:
                    print(f"[write_int8] {b}/{n}", flush=True)
        np.save(path + ".dequant.npy", np.stack([scale, zero]))
        return scale, zero


def compute_groundtruth(ds: Dataset, k: int = 100,
                        device_budget: int = 2 << 30,
                        chunk_rows: int = 1 << 18,
                        max_queries: int = 0,
                        device_base=None) -> Dataset:
    """Exact top-k groundtruth via the library's own brute force (the
    reference's split_groundtruth uses its GPU brute force the same way).

    Bases larger than ``device_budget`` bytes (memmapped billion-scale
    files) stream through the device in ``chunk_rows`` blocks with a
    running top-k merge — the base never materializes in HBM.
    ``max_queries`` bounds the GT query count (chunked GT costs one
    full-dataset pass; recall on a subset is standard at 10⁸ scale)."""
    import jax
    import jax.numpy as jnp

    queries = ds.queries
    if max_queries and queries.shape[0] > max_queries:
        queries = queries[:max_queries]
    if ds.base.nbytes <= device_budget or device_base is not None:
        from ..neighbors import brute_force

        # callers that already hold the base on device pass it in —
        # a second multi-GB copy has OOMed wide-dataset runs
        base_dev = (device_base if device_base is not None
                    else jnp.asarray(ds.base))
        index = brute_force.build(base_dev, metric=ds.metric)
        # impl="sort": groundtruth must be GUARANTEED exact — the default
        # strided-bin tile cut is only probabilistically exact (loses a
        # true neighbor when ≥3 top-k rows collide in one stride bin),
        # and every recall number in the bench is measured against this
        _, ids = brute_force.knn(index, jnp.asarray(queries), k,
                                 impl="sort")
        ds.groundtruth = np.asarray(ids, np.int32)
        del index
        return ds

    from ..core.errors import expects
    from ..distance.types import DistanceType, resolve_metric

    mt = resolve_metric(ds.metric)
    ip = mt == DistanceType.InnerProduct
    cos = mt == DistanceType.CosineExpanded
    expects(mt in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                   DistanceType.InnerProduct, DistanceType.CosineExpanded),
            "streaming groundtruth supports l2/ip/cosine, not %s",
            ds.metric)

    def _norm(v):
        return v / jnp.sqrt(jnp.maximum(
            jnp.sum(v * v, axis=-1, keepdims=True), 1e-30))

    q = jnp.asarray(np.asarray(queries, np.float32))
    if cos:  # cosine ranks as L2 on normalized rows
        q = _norm(q)
    m = q.shape[0]
    qt = 1024  # query tile: bounds the [qt, chunk] distance block

    n_rows = ds.base.shape[0]

    @jax.jit
    def merge_chunk(best_v, best_i, xb, base_id):
        x_sq = jnp.sum(xb * xb, axis=1)
        col_id = base_id + jnp.arange(xb.shape[0], dtype=jnp.int32)

        def tile(args):
            bv, bi, qv = args                       # [qt,k],[qt,k],[qt,d]
            s = jax.lax.dot_general(
                qv, xb, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)  # [qt, C]
            # rank key only: q² is dropped, so x² − 2qx is legitimately
            # negative near the query — no zero clamp here
            d2 = -s if ip else x_sq[None, :] - 2.0 * s
            d2 = jnp.where(col_id[None, :] < n_rows, d2, jnp.inf)
            v, p = jax.lax.top_k(-d2, k)
            ids = (base_id + p).astype(jnp.int32)
            mv = jnp.concatenate([bv, -v], axis=1)
            mi = jnp.concatenate([bi, ids], axis=1)
            vv, pp = jax.lax.top_k(-mv, k)
            return -vv, jnp.take_along_axis(mi, pp, axis=1)

        n_t = best_v.shape[0] // qt
        bv, bi = jax.lax.map(tile, (best_v.reshape(n_t, qt, k),
                                    best_i.reshape(n_t, qt, k),
                                    q_pad.reshape(n_t, qt, -1)))
        return bv.reshape(-1, k), bi.reshape(-1, k)

    # d2 drops q² (constant per query row — rank-safe); the candidate
    # x² term stays, it differs across base rows
    m_pad = -(-m // qt) * qt
    q_pad = jnp.pad(q, ((0, m_pad - m), (0, 0)))
    best_v = jnp.full((m_pad, k), np.inf, jnp.float32)
    best_i = jnp.full((m_pad, k), -1, jnp.int32)
    n = ds.base.shape[0]
    for a in range(0, n, chunk_rows):
        raw = ds.base[a:a + chunk_rows]
        if isinstance(raw, jax.Array):  # device-chunk provider
            xb = raw.astype(jnp.float32)
            if cos:
                xb = _norm(xb)
            if xb.shape[0] < chunk_rows:
                xb = jnp.pad(xb, ((0, chunk_rows - xb.shape[0]), (0, 0)),
                             constant_values=1e30)
        else:
            xbh = np.asarray(raw, np.float32)
            if cos:
                xbh = xbh / np.maximum(np.linalg.norm(
                    xbh, axis=1, keepdims=True), 1e-15)
            if xbh.shape[0] < chunk_rows:  # ragged tail: pad far away,
                xbh = np.pad(xbh, ((0, chunk_rows - xbh.shape[0]), (0, 0)),
                             constant_values=1e30)  # one compiled shape
            xb = jnp.asarray(xbh)
        best_v, best_i = merge_chunk(best_v, best_i, xb, jnp.int32(a))
    ds.groundtruth = np.asarray(jax.device_get(best_i))[:m]
    return ds


def recall(found_ids: np.ndarray, groundtruth: np.ndarray) -> float:
    """recall@k against groundtruth's first k columns (reference:
    data_export recall column) — delegates to stats.neighborhood_recall."""
    from ..stats.metrics import neighborhood_recall

    k = found_ids.shape[1]
    return float(neighborhood_recall(np.asarray(found_ids),
                                     np.asarray(groundtruth[:, :k])))
