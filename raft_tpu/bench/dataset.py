"""Benchmark dataset management — .fbin/.ibin files, synthetic sets,
groundtruth.

TPU-native counterpart of the reference's bench dataset layer
(cpp/bench/ann/src/common/dataset.hpp: BinFile header/read/subset;
python/raft-ann-bench get_dataset/split_groundtruth).  Binary IO goes
through the native C++ reader (raft_tpu.native) with a numpy fallback.

A dataset directory holds::

    <name>/base.fbin           # [n, d] float32 vectors
    <name>/query.fbin          # [m, d] float32 queries
    <name>/groundtruth.ibin    # [m, k_gt] int32 exact neighbor ids
    <name>/groundtruth_dist.fbin  # [m, k_gt] float32 (optional)
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

from .. import native


@dataclass
class Dataset:
    name: str
    base: np.ndarray        # [n, d] f32
    queries: np.ndarray     # [m, d] f32
    groundtruth: Optional[np.ndarray] = None  # [m, k_gt] i32
    metric: str = "sqeuclidean"

    @property
    def n(self) -> int:
        return self.base.shape[0]

    @property
    def dim(self) -> int:
        return self.base.shape[1]


def write_dataset(root: str, ds: Dataset) -> str:
    d = os.path.join(root, ds.name)
    os.makedirs(d, exist_ok=True)
    native.bin_write(os.path.join(d, "base.fbin"), ds.base.astype(np.float32))
    native.bin_write(os.path.join(d, "query.fbin"), ds.queries.astype(np.float32))
    if ds.groundtruth is not None:
        native.bin_write(os.path.join(d, "groundtruth.ibin"),
                         ds.groundtruth.astype(np.int32))
    return d


def bin_memmap(path: str, dtype) -> np.ndarray:
    """Memory-map a .fbin/.ibin file's payload as [n, d] without reading
    it (the reference's mmap path for billion-scale files,
    cpp/bench/ann/src/common/dataset.hpp BinFile::map). Row chunks are
    paged in on access and reclaimable — host RSS stays O(touched)."""
    n, d = native.bin_header(path)
    return np.memmap(path, dtype=np.dtype(dtype), mode="r", offset=8,
                     shape=(n, d))


def load_dataset(root: str, name: str, metric: str = "sqeuclidean",
                 max_rows: int = -1, mmap: bool = False) -> Dataset:
    """Load a dataset directory; ``max_rows`` subsets the base file and
    ``mmap=True`` memory-maps it instead of reading it whole (the
    reference's subset/memmap path for billion-scale files)."""
    d = os.path.join(root, name)
    if mmap:
        base = bin_memmap(os.path.join(d, "base.fbin"), np.float32)
        if max_rows >= 0:
            base = base[:max_rows]
    else:
        base = native.bin_read(os.path.join(d, "base.fbin"), np.float32,
                               count=max_rows)
    queries = native.bin_read(os.path.join(d, "query.fbin"), np.float32)
    gt_path = os.path.join(d, "groundtruth.ibin")
    gt = native.bin_read(gt_path, np.int32) if os.path.exists(gt_path) else None
    if gt is not None and 0 <= max_rows < native.bin_header(
            os.path.join(d, "base.fbin"))[0]:
        # the on-disk groundtruth covers the FULL base; against a subset
        # it contains unreachable ids and would deflate recall silently —
        # drop it so callers recompute on the subset
        gt = None
    return Dataset(name=name, base=base, queries=queries, groundtruth=gt,
                   metric=metric)


def make_synthetic(name: str, n: int, dim: int, n_queries: int,
                   metric: str = "sqeuclidean", seed: int = 0,
                   clustered: bool = True, hard: bool = False) -> Dataset:
    """Synthetic benchmark set shaped like the reference's standard ones
    (SIFT-style clustered f32).

    ``hard=True`` selects :func:`make_synthetic_hard` — overlapping
    low-intrinsic-dimension clusters calibrated so IVF recall curves
    bend like real SIFT's, instead of the near-separable default."""
    if hard:
        return make_synthetic_hard(name, n, dim, n_queries, metric=metric,
                                   seed=seed)
    rng = np.random.default_rng(seed)
    if clustered:
        n_centers = max(16, int(np.sqrt(n)))
        centers = rng.random((n_centers, dim), dtype=np.float32) * 10.0
        assign = rng.integers(0, n_centers, n)
        base = centers[assign] + 0.5 * rng.standard_normal((n, dim), dtype=np.float32)
        q_assign = rng.integers(0, n_centers, n_queries)
        queries = centers[q_assign] + 0.5 * rng.standard_normal(
            (n_queries, dim), dtype=np.float32)
    else:
        base = rng.random((n, dim), dtype=np.float32)
        queries = rng.random((n_queries, dim), dtype=np.float32)
    return Dataset(name=name, base=base, queries=queries, metric=metric)


def make_synthetic_hard(name: str, n: int, dim: int, n_queries: int,
                        metric: str = "sqeuclidean", seed: int = 0,
                        n_centers: int = 0, lid: int = 16,
                        overlap: float = 1.0) -> Dataset:
    """Hard clustered synthetic: overlapping low-LID clusters.

    The default :func:`make_synthetic` places ~1000 Gaussian balls ~8×
    farther apart than their radius — a kmeans partition separates them
    perfectly and IVF recall saturates at tiny n_probes (VERDICT r3:
    0.9991 at n_probes=16 where real SIFT-1M needs far more). Here:

    - each cluster lives on a random ``lid``-dimensional affine subspace
      (local intrinsic dimension matched to SIFT's ~12-16, which is what
      makes graph/IVF search meaningfully hard, not the ambient 128);
    - cluster radius ≈ ``overlap`` × the distance to the nearest other
      center, so every neighborhood near a partition boundary spans
      several clusters and true top-k sets cross kmeans cells;
    - queries are perturbed copies of held-out base-like points (the
      ann-benchmarks convention: queries come from the data
      distribution, not from cluster centers).
    """
    rng = np.random.default_rng(seed)
    if not n_centers:
        n_centers = max(64, int(np.sqrt(n)))
    centers = rng.standard_normal((n_centers, dim)).astype(np.float32)
    # nearest-other-center distance sets the radius scale
    # (sample-estimate on a subset to stay O(C·S))
    sub = centers[rng.choice(n_centers, min(n_centers, 256), replace=False)]
    d2 = (np.sum(centers**2, 1)[:, None] + np.sum(sub**2, 1)[None, :]
          - 2.0 * centers @ sub.T)
    np.clip(d2, 0, None, out=d2)
    d2[d2 < 1e-6] = np.inf                      # self pairs
    nearest = np.sqrt(d2.min(axis=1))           # [C]
    lid = min(lid, dim)
    bases = rng.standard_normal((n_centers, dim, lid)).astype(np.float32)
    bases /= np.linalg.norm(bases, axis=1, keepdims=True)
    scale = (overlap * nearest / np.sqrt(lid)).astype(np.float32)

    def sample(m, assign):
        z = rng.standard_normal((m, lid)).astype(np.float32)
        z *= scale[assign][:, None]
        pts = centers[assign]
        pts = pts + np.einsum("mdl,ml->md", bases[assign], z)
        # small full-dim noise so points are near, not on, the manifold
        pts += (0.05 * scale[assign][:, None]
                * rng.standard_normal((m, dim)).astype(np.float32))
        return pts.astype(np.float32)

    assign = rng.integers(0, n_centers, n)
    base = sample(n, assign)
    q_assign = rng.integers(0, n_centers, n_queries)
    queries = sample(n_queries, q_assign)
    return Dataset(name=name, base=base, queries=queries, metric=metric)


def compute_groundtruth(ds: Dataset, k: int = 100) -> Dataset:
    """Exact top-k groundtruth via the library's own brute force (the
    reference's split_groundtruth uses its GPU brute force the same way)."""
    import jax.numpy as jnp

    from ..neighbors import brute_force

    index = brute_force.build(jnp.asarray(ds.base), metric=ds.metric)
    _, ids = brute_force.knn(index, jnp.asarray(ds.queries), k)
    ds.groundtruth = np.asarray(ids, np.int32)
    return ds


def recall(found_ids: np.ndarray, groundtruth: np.ndarray) -> float:
    """recall@k against groundtruth's first k columns (reference:
    data_export recall column) — delegates to stats.neighborhood_recall."""
    from ..stats.metrics import neighborhood_recall

    k = found_ids.shape[1]
    return float(neighborhood_recall(np.asarray(found_ids),
                                     np.asarray(groundtruth[:, :k])))
