"""Combinatorial solvers — TPU-native counterpart of `raft/solver/`
(linear assignment; SURVEY.md §2.11)."""

from . import lap
from .lap import solve as lap_solve

__all__ = ["lap", "lap_solve"]
