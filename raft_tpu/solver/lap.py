"""Linear assignment problem (LAP) solver.

TPU-native counterpart of the reference's Hungarian/LAP solver
(solver/linear_assignment.cuh, raft/lap/ — the Date–Nagi GPU tree
variant).  The TPU re-think uses the **auction algorithm** with
ε-scaling instead: every round is a dense, batched bid/assign step
(row-max + segment-max over an [n, n] matrix — pure VPU/MXU work, no
per-thread tree walking), which is the natural fit for a lockstep SIMD
machine.  With ε < 1/n the result is provably optimal for integer
costs; for floats it is ε-optimal (tests pin integer costs for
exactness, mirroring the reference's int tests in cpp/test/lap/lap.cu).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("max_rounds",))
def _auction_phase(benefit: jnp.ndarray, prices: jnp.ndarray, eps: jnp.ndarray,
                   max_rounds: int):
    """Run Jacobi auction rounds at one ε until all persons assigned.

    benefit [n, n]: person×object value (maximization).  Returns
    (person→object assignment, prices)."""
    n = benefit.shape[0]
    neg = jnp.asarray(-1, jnp.int32)

    def cond(state):
        assign, owner, prices, rounds = state
        return (rounds < max_rounds) & jnp.any(assign < 0)

    def body(state):
        assign, owner, prices, rounds = state
        values = benefit - prices[None, :]  # [n persons, n objects]
        best_j = jnp.argmax(values, axis=1).astype(jnp.int32)
        v1 = jnp.max(values, axis=1)
        # second-best: mask out the best column
        masked = values.at[jnp.arange(n), best_j].set(-jnp.inf)
        v2 = jnp.max(masked, axis=1)
        bid = prices[best_j] + v1 - v2 + eps  # each person's price offer

        unassigned = assign < 0
        # per object: highest bid among unassigned bidders
        bid_masked = jnp.where(unassigned, bid, -jnp.inf)
        obj_best_bid = jax.ops.segment_max(bid_masked, best_j, num_segments=n)
        has_bid = obj_best_bid > -jnp.inf
        # winner: lowest person index among those placing the top bid
        big = jnp.asarray(np.iinfo(np.int32).max, jnp.int32)
        is_top = unassigned & (bid == obj_best_bid[best_j])
        winner = jax.ops.segment_min(
            jnp.where(is_top, jnp.arange(n, dtype=jnp.int32), big),
            best_j,
            num_segments=n,
        )
        take = has_bid & (winner < big)

        # evict previous owners of newly-won objects: person
        # prev_owner[j] loses object j (out-of-bounds scatters drop)
        prev_owner = jnp.where(take, owner, neg)
        evict_idx = jnp.where(take & (prev_owner >= 0), prev_owner, n)
        assign = assign.at[evict_idx].set(neg, mode="drop")
        # award object j to winner[j]
        win_idx = jnp.where(take, winner, n)
        obj_ids = jnp.arange(n, dtype=jnp.int32)
        assign = assign.at[win_idx].set(obj_ids, mode="drop")
        owner = jnp.where(take, winner, owner)
        prices = jnp.where(take, obj_best_bid, prices)
        return assign, owner, prices, rounds + 1

    init = (
        jnp.full((n,), neg, jnp.int32),  # person → object
        jnp.full((n,), neg, jnp.int32),  # object → person
        prices,
        jnp.asarray(0, jnp.int32),
    )
    assign, owner, prices, _ = jax.lax.while_loop(cond, body, init)
    return assign, prices


def solve(cost, maximize: bool = False) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Solve the square LAP: one object per person minimizing total cost —
    counterpart of ``raft::solver::LinearAssignmentProblem::solve``
    (solver/linear_assignment.cuh:77).

    ε-scaling runs down to ε ≤ 1/(n+1) — optimal for integer costs —
    floored at the f32 price resolution (span·2⁻²⁰): prices live near
    the cost magnitude, so a smaller ε is not representable and bids
    would stop moving.  Costs with span·(n+1) ≲ 2²⁰ are therefore
    solved exactly; wider ranges are ε-optimal (total within n·ε).
    Returns (row_assignment [n] mapping person→object, total_cost).
    """
    c = jnp.asarray(cost, jnp.float32)
    if c.ndim != 2 or c.shape[0] != c.shape[1]:
        raise ValueError(f"cost must be square, got {c.shape}")
    n = c.shape[0]
    benefit = c if maximize else -c
    span = float(jnp.max(jnp.abs(benefit)))
    prices = jnp.zeros((n,), jnp.float32)
    eps = max(span / 2.0, 1.0 / n)
    eps_min = max(1.0 / (n + 1), span * (2.0 ** -20))
    assign = None
    # 5× shrink per phase reaches eps_min from any f32 span within ~64
    # phases; the bound is a safety net, not a precision cap
    for _ in range(64):
        assign, prices = _auction_phase(
            benefit, prices, jnp.asarray(eps, jnp.float32), max_rounds=50 * n
        )
        if eps <= eps_min:
            break
        eps = max(eps / 5.0, eps_min)
    if bool(jnp.any(assign < 0)):
        raise RuntimeError(
            "auction did not converge (unassigned persons remain); "
            "cost matrix may be degenerate"
        )
    total = jnp.sum(c[jnp.arange(n), assign])
    return assign, total
