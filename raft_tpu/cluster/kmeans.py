"""K-means — Lloyd iterations on the fused L2 argmin.

TPU-native counterpart of ``raft::cluster::kmeans``
(cluster/kmeans.cuh:88 fit, :152 predict, :215 fit_predict, :244 transform,
:307 cluster_cost, detail/kmeans.cuh). Design mapping:

- assignment = :func:`raft_tpu.distance.fused_l2_nn_argmin` (the reference's
  hot loop, detail/kmeans_common.cuh min_cluster_and_distance);
- centroid update = ``jax.ops.segment_sum`` weighted means (the reference's
  reduce_rows_by_key + weighted mean);
- the whole fit loop is one ``lax.while_loop`` under jit — no host round
  trips between iterations;
- k-means++ init (reference: kmeans_plus_plus, detail/kmeans.cuh via
  ``init_plus_plus``) as a ``lax.fori_loop`` of Gumbel-sampled seeding;
- distributed fit: sample-sharded SPMD — each shard computes local sums,
  one ``psum`` merges them (see raft_tpu.parallel / cluster.distributed).

All fitting supports sample weights (zero weights = masked rows), which the
balanced variant and padded distributed shards rely on.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced, span
from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
from raft_tpu.distance.pairwise import l2_expanded
from raft_tpu.random.rng import RngState, _as_key


@dataclasses.dataclass
class KMeansParams:
    """reference: ``KMeansParams`` (cluster/kmeans_types.hpp)."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: str = "k-means++"  # "k-means++" | "random" | "array"
    seed: int = 0
    n_init: int = 1
    oversampling_factor: float = 2.0  # accepted for parity; ++ init is exact


# ---------------------------------------------------------------------------
# initialization
# ---------------------------------------------------------------------------

def init_plus_plus(key: jax.Array, x: jax.Array, n_clusters: int,
                   weights: Optional[jax.Array] = None) -> jax.Array:
    """k-means++ seeding (reference: cluster/kmeans.cuh:584
    ``init_plus_plus``): iteratively sample points w.p. ∝ weight·D²."""
    n, d = x.shape
    xf = x.astype(jnp.float32)
    w = jnp.ones((n,), jnp.float32) if weights is None else weights.astype(jnp.float32)
    k0, key = jax.random.split(key)
    logw = jnp.where(w > 0, jnp.log(jnp.maximum(w, 1e-30)), -jnp.inf)
    first = jnp.argmax(logw + jax.random.gumbel(k0, (n,)))
    centers = jnp.zeros((n_clusters, d), jnp.float32).at[0].set(xf[first])
    x_sq = jnp.sum(xf * xf, axis=1)

    def dist2_to(c):
        c_sq = jnp.sum(c * c)
        return jnp.maximum(x_sq + c_sq - 2.0 * (xf @ c), 0.0)

    min_d2 = dist2_to(xf[first])

    def body(i, carry):
        centers, min_d2 = carry
        ki = jax.random.fold_in(key, i)
        # Gumbel-max sample ∝ w·D²
        logits = jnp.log(jnp.maximum(w * min_d2, 1e-30))
        logits = jnp.where(w * min_d2 > 0, logits, -jnp.inf)
        nxt = jnp.argmax(logits + jax.random.gumbel(ki, (n,)))
        c = xf[nxt]
        centers = centers.at[i].set(c)
        min_d2 = jnp.minimum(min_d2, dist2_to(c))
        return centers, min_d2

    centers, _ = lax.fori_loop(1, n_clusters, body, (centers, min_d2))
    return centers


def init_random(key: jax.Array, x: jax.Array, n_clusters: int) -> jax.Array:
    n = x.shape[0]
    idx = jax.random.choice(key, n, (n_clusters,), replace=False)
    return x[idx].astype(jnp.float32)


# ---------------------------------------------------------------------------
# Lloyd core (weighted)
# ---------------------------------------------------------------------------

def _update_centroids(x, w, labels, n_clusters, old_centroids):
    """Weighted per-cluster sums/counts via tiled one-hot MXU
    contractions. ``jax.ops.segment_sum`` lowers to a scatter-add that
    SERIALIZES on TPU — measured ~12 s per update at 2M rows × 8192
    clusters, which made billion-scale coarse training minutes-per-
    sweep; the same reduction as a [tile, k]ᵀ×[tile, d] one-hot matmul
    runs on the MXU in ~0.1 s. One-hot entries are exact 0/1 and the
    accumulation type is f32, so counts are exact below 2²⁴."""
    n, d = x.shape
    # bound the [row_tile, n_clusters] one-hot block to ~512 MB
    row_tile = min(n, max(1024, (512 << 20) // max(4 * n_clusters, 1)))
    nt = -(-n // row_tile)
    if nt * row_tile != n:
        pad = nt * row_tile - n
        x = jnp.pad(x, ((0, pad), (0, 0)))
        w = jnp.pad(w, (0, pad))            # zero-weight pad rows
        labels = jnp.pad(labels, (0, pad))

    def tile(args):
        xt, lt, wt = args
        oh = jax.nn.one_hot(lt, n_clusters, dtype=jnp.float32) * wt[:, None]
        return (jnp.einsum("tk,td->kd", oh, xt,
                           preferred_element_type=jnp.float32),
                jnp.sum(oh, axis=0))

    if nt == 1:
        sums, counts = tile((x, labels, w))
    else:
        sums_t, counts_t = lax.map(
            tile, (x.reshape(nt, row_tile, d),
                   labels.reshape(nt, row_tile),
                   w.reshape(nt, row_tile)))
        sums = jnp.sum(sums_t, axis=0)
        counts = jnp.sum(counts_t, axis=0)
    return jnp.where(counts[:, None] > 0,
                     sums / jnp.maximum(counts[:, None], 1e-12),
                     old_centroids), counts


@partial(jax.jit, static_argnames=("n_clusters", "max_iter"))
def _lloyd(x, w, init_centroids, n_clusters: int, max_iter: int, tol: float):
    xf = x.astype(jnp.float32)
    wf = w.astype(jnp.float32)

    def cond(carry):
        _, shift2, it, _ = carry
        return (it < max_iter) & (shift2 > tol * tol)

    def body(carry):
        centroids, _, it, _ = carry
        d2, labels = fused_l2_nn_argmin(xf, centroids)
        new_c, _ = _update_centroids(xf, wf, labels, n_clusters, centroids)
        shift2 = jnp.sum((new_c - centroids) ** 2)
        inertia = jnp.sum(wf * d2)
        return new_c, shift2, it + 1, inertia

    init = (init_centroids.astype(jnp.float32), jnp.array(jnp.inf, jnp.float32),
            jnp.array(0, jnp.int32), jnp.array(jnp.inf, jnp.float32))
    centroids, _, n_iter, inertia = lax.while_loop(cond, body, init)
    return centroids, inertia, n_iter


@traced("raft_tpu.kmeans.fit")
def fit(
    params: KMeansParams,
    x: jax.Array,
    sample_weights: Optional[jax.Array] = None,
    init_centroids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fit k-means (reference: cluster/kmeans.cuh:88 ``fit``).

    Returns (centroids [k, d], inertia, n_iter).
    """
    n, d = x.shape
    k = params.n_clusters
    expects(k <= n, "n_clusters=%d > n_samples=%d", k, n)
    w = jnp.ones((n,), jnp.float32) if sample_weights is None else sample_weights

    key = RngState(params.seed).key()
    best = None
    for trial in range(max(params.n_init, 1)):
        kt = jax.random.fold_in(key, trial)
        with span("init") as _sp:
            if init_centroids is not None or params.init == "array":
                expects(init_centroids is not None,
                        "init='array' requires init_centroids")
                c0 = init_centroids
            elif params.init == "random":
                c0 = init_random(kt, x, k)
            else:
                c0 = init_plus_plus(kt, x, k, w)
            _sp.attach(c0)
        with span("lloyd") as _sp:
            centroids, inertia, n_iter = _lloyd(x, w, c0, k,
                                                params.max_iter, params.tol)
            _sp.attach(centroids, inertia)
        if best is None:
            best = (centroids, inertia, n_iter)
        else:
            # device-side running best: no host sync in the restart loop
            # (the old per-trial float(inertia) comparison serialized
            # every restart behind a round-trip — graftlint GL01), O(1)
            # extra memory, and a NaN inertia (diverged restart) never
            # beats a finite best (NaN < x is False) — while a NaN best
            # (trial 0 diverged) is always replaced
            better = (inertia < best[1]) | jnp.isnan(best[1])
            best = tuple(jnp.where(better, new, old)
                         for new, old in zip((centroids, inertia, n_iter),
                                             best))
    return best


def update_centroids(x: jax.Array, sample_weights: jax.Array,
                     centroids: jax.Array, labels: jax.Array
                     ) -> Tuple[jax.Array, jax.Array]:
    """One centroid-update step given fixed labels — the helper an
    external mini-batch loop drives (reference: kmeans::update_centroids,
    cluster/kmeans.cuh:385-411). Returns (weight_per_cluster [k],
    new_centroids [k, d]); empty clusters keep their old centroid."""
    k = centroids.shape[0]
    new_c, counts = _update_centroids(x.astype(jnp.float32),
                                      sample_weights.astype(jnp.float32),
                                      labels, k, centroids)
    return counts, new_c


@partial(jax.jit, static_argnames=("n_clusters", "batch_size", "n_iters"))
def _minibatch_loop(x, c0, key, n_clusters: int, batch_size: int,
                    n_iters: int):
    """Mini-batch Lloyd: each iteration assigns one random batch and
    moves its centroids by the per-cluster running learning rate
    1/count (Sculley 2010, the update cuML's MiniBatchKMeans applies
    through update_centroids). One ``fori_loop`` — no host round trips."""
    n = x.shape[0]
    xf = x.astype(jnp.float32)

    def body(i, carry):
        c, v = carry
        ki = jax.random.fold_in(key, i)
        rows = jax.random.randint(ki, (batch_size,), 0, n)
        xb = xf[rows]
        _, labels = fused_l2_nn_argmin(xb, c)
        oh = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)
        bcount = jnp.sum(oh, axis=0)                      # [k]
        bsum = jnp.einsum("bk,bd->kd", oh, xb,
                          preferred_element_type=jnp.float32)
        v = v + bcount
        # per-cluster EMA toward the batch mean with rate bcount/v
        lr = jnp.where(v > 0, bcount / jnp.maximum(v, 1.0), 0.0)
        bmean = bsum / jnp.maximum(bcount, 1.0)[:, None]
        c = c + lr[:, None] * (bmean - c)
        return c, v

    c, _ = lax.fori_loop(0, n_iters, body,
                         (c0.astype(jnp.float32),
                          jnp.zeros((n_clusters,), jnp.float32)))
    return c


@traced("raft_tpu.kmeans.fit_minibatch")
def fit_minibatch(params: KMeansParams, x: jax.Array,
                  batch_size: int = 1024,
                  n_iters: Optional[int] = None,
                  init_centroids: Optional[jax.Array] = None
                  ) -> Tuple[jax.Array, jax.Array, int]:
    """Mini-batch k-means fit — the TPU counterpart of the mini-batch
    helpers around ``update_centroids`` (cluster/kmeans.cuh:367-411 area;
    cuML MiniBatchKMeans drives them the same way). Statically shaped
    random batches keep the whole fit one compiled program; use for
    datasets too large for full-batch Lloyd sweeps.

    Returns (centroids [k, d], inertia over a final full pass, n_iters).
    """
    n, d = x.shape
    k = params.n_clusters
    expects(k <= n, "n_clusters=%d > n_samples=%d", k, n)
    batch_size = min(batch_size, n)
    if n_iters is None:
        # enough batches to see the data ~max_iter/10 times, bounded
        n_iters = max(20, min(params.max_iter, 10 * n // batch_size))
    key = RngState(params.seed).key()
    if init_centroids is not None or params.init == "array":
        expects(init_centroids is not None,
                "init='array' requires init_centroids")
        c0 = init_centroids
    elif params.init == "random":
        c0 = init_random(key, x, k)
    else:
        # ++ seeding on one batch: full-data D² seeding defeats the
        # point of mini-batching at scale
        sub = x[jax.random.randint(jax.random.fold_in(key, n_iters + 1),
                                   (min(n, max(batch_size, 4 * k)),), 0, n)]
        c0 = init_plus_plus(key, sub, k)
    centroids = _minibatch_loop(x, c0, key, k, batch_size, n_iters)
    return centroids, cluster_cost(centroids, x), n_iters


@traced("raft_tpu.kmeans.predict")
def predict(centroids: jax.Array, x: jax.Array) -> jax.Array:
    """Nearest-centroid labels (reference: kmeans.cuh:152 ``predict``)."""
    _, labels = fused_l2_nn_argmin(x.astype(jnp.float32), centroids)
    return labels


@traced("raft_tpu.kmeans.fit_predict")
def fit_predict(params: KMeansParams, x: jax.Array,
                sample_weights: Optional[jax.Array] = None):
    """reference: kmeans.cuh:215."""
    centroids, inertia, n_iter = fit(params, x, sample_weights)
    return centroids, predict(centroids, x), inertia, n_iter


@traced("raft_tpu.kmeans.transform")
def transform(centroids: jax.Array, x: jax.Array) -> jax.Array:
    """Distances to all centroids (reference: kmeans.cuh:244)."""
    return l2_expanded(x, centroids, sqrt=True)


def cluster_cost(centroids: jax.Array, x: jax.Array,
                 sample_weights: Optional[jax.Array] = None) -> jax.Array:
    """Total weighted inertia (reference: kmeans.cuh:307)."""
    d2, _ = fused_l2_nn_argmin(x.astype(jnp.float32), centroids)
    if sample_weights is not None:
        d2 = d2 * sample_weights
    return jnp.sum(d2)


def find_k(x: jax.Array, k_max: int = 20, params: Optional[KMeansParams] = None
           ) -> Tuple[int, jax.Array]:
    """Auto-select k by the inertia elbow (reference:
    detail/kmeans_auto_find_k.cuh). Returns (best_k, inertias[2..k_max])."""
    if params is None:
        params = KMeansParams(max_iter=50)
    ks = list(range(2, k_max + 1))
    inertias = []
    for k in ks:
        p = dataclasses.replace(params, n_clusters=k)
        _, inertia, _ = fit(p, x)
        inertias.append(float(inertia))
    # largest relative drop-off slope change (simple elbow criterion)
    inertias_a = jnp.asarray(inertias)
    if len(ks) < 3:
        return ks[int(jnp.argmin(inertias_a))], inertias_a
    drops = -jnp.diff(inertias_a)
    curvature = drops[:-1] - drops[1:]
    return ks[int(jnp.argmax(curvature)) + 1], inertias_a
