"""raft_tpu.cluster — kmeans, balanced kmeans, single-linkage HAC.

Counterpart of the reference cluster layer (cpp/include/raft/cluster).
"""

from raft_tpu.cluster import kmeans, kmeans_balanced  # noqa: F401
from raft_tpu.cluster.kmeans import KMeansParams  # noqa: F401
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams  # noqa: F401
from raft_tpu.cluster import single_linkage as single_linkage_mod  # noqa: F401
from raft_tpu.cluster.single_linkage import (  # noqa: F401
    SingleLinkageOutput,
    single_linkage,
)
