"""Balanced (hierarchical) k-means — the trainer behind every IVF index.

TPU-native counterpart of ``raft::cluster::kmeans_balanced``
(cluster/kmeans_balanced.cuh:76 fit, detail/kmeans_balanced.cuh — 1097 LoC:
mesocluster hierarchy :758, adjust_centers balancing). Same two-level
design, TPU-shaped execution:

1. fit ~√k *mesoclusters* with plain Lloyd;
2. partition each mesocluster's rows into fine clusters (count ∝ meso
   size), fitting per-meso Lloyd on padded, weight-masked row blocks
   (static shapes per meso — the TPU version of the reference's
   variable-size mesocluster kernels);
3. finish with joint Lloyd sweeps over all fine centers, re-seeding
   under-populated clusters from the fattest clusters' far points each
   sweep (the reference's ``adjust_centers`` balancing pass).

Balance matters doubly on TPU: IVF lists are padded blocks, so variance in
list size is wasted HBM *and* wasted scan FLOPs.

Supports metric="l2" and "cosine" (rows are L2-normalized first, as the
reference does for spherical kmeans).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced
from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
from raft_tpu.cluster.kmeans import _update_centroids, init_random
from raft_tpu.random.rng import RngState


@dataclasses.dataclass
class KMeansBalancedParams:
    """reference: ``kmeans_balanced_params`` (cluster/kmeans_balanced_types.hpp)."""

    n_iters: int = 20
    metric: str = "l2"  # "l2" | "cosine"
    seed: int = 0
    mesocluster_factor: float = 1.0  # n_meso = factor * sqrt(k)


def _maybe_normalize(x: jax.Array, metric: str) -> jax.Array:
    if metric == "cosine":
        n = jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=-1, keepdims=True), 1e-12))
        return x / n
    return x


@partial(jax.jit, static_argnames=("n_clusters", "n_iters"))
def _balanced_lloyd(x, w, c0, n_clusters: int, n_iters: int, key):
    """Lloyd sweeps with per-sweep re-seeding of starved clusters from the
    largest clusters' farthest points (reference: adjust_centers,
    detail/kmeans_balanced.cuh)."""
    xf = x.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    total_w = jnp.maximum(jnp.sum(wf), 1e-12)
    # a cluster is "starved" below this fraction of the average mass
    starve_thresh = 0.25 * total_w / n_clusters

    def body(i, centroids):
        d2, labels = fused_l2_nn_argmin(xf, centroids)
        new_c, counts = _update_centroids(xf, wf, labels, n_clusters, centroids)
        # re-seed starved clusters at the globally farthest (weighted) points
        starved = counts < starve_thresh
        n_starved_slots = jnp.minimum(n_clusters, xf.shape[0])
        far_score = jnp.where(wf > 0, d2, -jnp.inf)
        _, far_idx = lax.top_k(far_score, n_clusters)
        # rank starved clusters; the j-th starved cluster takes the j-th
        # farthest point as its new center
        starved_rank = jnp.cumsum(starved.astype(jnp.int32)) - 1
        take_idx = far_idx[jnp.clip(starved_rank, 0, n_clusters - 1)]
        reseeded = xf[take_idx]
        new_c = jnp.where(starved[:, None], reseeded, new_c)
        return new_c

    return lax.fori_loop(0, n_iters, body, c0.astype(jnp.float32))


def build_clusters(
    x: jax.Array,
    n_clusters: int,
    params: Optional[KMeansBalancedParams] = None,
    sample_weights: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-level balanced clustering → (centers, labels, sizes).

    Counterpart of ``kmeans_balanced::helpers::build_clusters``
    (cluster/kmeans_balanced.cuh) — used directly for PQ codebook training.
    """
    if params is None:
        params = KMeansBalancedParams()
    xn = _maybe_normalize(jnp.asarray(x, jnp.float32), params.metric)
    n = xn.shape[0]
    w = jnp.ones((n,), jnp.float32) if sample_weights is None else sample_weights
    key = RngState(params.seed).key()
    c0 = init_random(key, xn, n_clusters)
    centers = _balanced_lloyd(xn, w, c0, n_clusters, params.n_iters, key)
    centers = _maybe_normalize(centers, params.metric)
    _, labels = fused_l2_nn_argmin(xn, centers)
    sizes = jax.ops.segment_sum(jnp.ones_like(w), labels, num_segments=n_clusters)
    return centers, labels, sizes.astype(jnp.int32)


@traced("raft_tpu.kmeans_balanced.fit")
def fit(
    x: jax.Array,
    n_clusters: int,
    params: Optional[KMeansBalancedParams] = None,
) -> jax.Array:
    """Hierarchical balanced fit → centers [n_clusters, d]
    (reference: kmeans_balanced::fit, cluster/kmeans_balanced.cuh:76)."""
    if params is None:
        params = KMeansBalancedParams()
    x = jnp.asarray(x, jnp.float32)
    xn = _maybe_normalize(x, params.metric)
    n, d = xn.shape
    expects(n_clusters <= n, "n_clusters=%d > n_samples=%d", n_clusters, n)
    key = RngState(params.seed).key()

    n_meso = max(1, min(n_clusters,
                        int(params.mesocluster_factor * math.isqrt(n_clusters))))
    if n_meso <= 1 or n_clusters <= 8:
        c0 = init_random(key, xn, n_clusters)
        w = jnp.ones((n,), jnp.float32)
        centers = _balanced_lloyd(xn, w, c0, n_clusters, params.n_iters, key)
        return _maybe_normalize(centers, params.metric)

    # level 1: mesoclusters (reference: detail/kmeans_balanced.cuh:758)
    w = jnp.ones((n,), jnp.float32)
    meso_c0 = init_random(key, xn, n_meso)
    meso_centers = _balanced_lloyd(xn, w, meso_c0, n_meso, params.n_iters, key)
    _, meso_labels = fused_l2_nn_argmin(xn, meso_centers)
    meso_labels_h = np.asarray(meso_labels)
    sizes = np.bincount(meso_labels_h, minlength=n_meso)

    # fine cluster counts ∝ mesocluster size, summing exactly to n_clusters
    quota = sizes / max(sizes.sum(), 1) * n_clusters
    fine_k = np.maximum(1, np.floor(quota).astype(np.int64))
    # distribute the remainder by largest fractional part
    while fine_k.sum() > n_clusters:
        fine_k[np.argmax(fine_k)] -= 1
    rem = n_clusters - fine_k.sum()
    if rem > 0:
        order = np.argsort(-(quota - np.floor(quota)))
        for j in order[:rem]:
            fine_k[j] += 1

    # level 2: per-mesocluster fine clustering on padded, masked row blocks
    max_sz = int(sizes.max())
    pad_to = max(8, 1 << (max_sz - 1).bit_length())  # one compile per size pow2
    fine_centers = []
    for m in range(n_meso):
        rows = np.nonzero(meso_labels_h == m)[0]
        if len(rows) == 0:
            continue
        k_m = int(min(fine_k[m], len(rows)))
        sub = np.zeros((pad_to, d), np.float32)
        sub[:len(rows)] = np.asarray(xn)[rows]
        mask = np.zeros((pad_to,), np.float32)
        mask[:len(rows)] = 1.0
        sub_j = jnp.asarray(sub)
        c0 = jnp.asarray(np.asarray(xn)[rows[np.linspace(0, len(rows) - 1, k_m).astype(int)]])
        cm = _balanced_lloyd(sub_j, jnp.asarray(mask), c0, k_m,
                             params.n_iters, jax.random.fold_in(key, m + 1))
        fine_centers.append(np.asarray(cm))
    centers = jnp.asarray(np.concatenate(fine_centers, axis=0))
    if centers.shape[0] < n_clusters:  # lost slots to empty mesoclusters
        extra = init_random(jax.random.fold_in(key, 999), xn,
                            n_clusters - centers.shape[0])
        centers = jnp.concatenate([centers, extra], axis=0)

    # final joint balancing sweeps over the full data
    centers = _balanced_lloyd(xn, w, centers, n_clusters,
                              max(2, params.n_iters // 4), key)
    return _maybe_normalize(centers, params.metric)


def predict(centers: jax.Array, x: jax.Array,
            params: Optional[KMeansBalancedParams] = None) -> jax.Array:
    """Nearest balanced-center labels (reference: kmeans_balanced::predict)."""
    metric = params.metric if params is not None else "l2"
    xn = _maybe_normalize(jnp.asarray(x, jnp.float32), metric)
    _, labels = fused_l2_nn_argmin(xn, centers)
    return labels
