"""Balanced (hierarchical) k-means — the trainer behind every IVF index.

TPU-native counterpart of ``raft::cluster::kmeans_balanced``
(cluster/kmeans_balanced.cuh:76 fit, detail/kmeans_balanced.cuh — 1097 LoC:
mesocluster hierarchy :758, adjust_centers balancing). Same two-level
design, TPU-shaped execution:

1. fit ~√k *mesoclusters* with plain Lloyd;
2. partition each mesocluster's rows into fine clusters (count ∝ meso
   size), fitting per-meso Lloyd on padded, weight-masked row blocks
   (static shapes per meso — the TPU version of the reference's
   variable-size mesocluster kernels);
3. finish with joint Lloyd sweeps over all fine centers, re-seeding
   under-populated clusters from the fattest clusters' far points each
   sweep (the reference's ``adjust_centers`` balancing pass).

Balance matters doubly on TPU: IVF lists are padded blocks, so variance in
list size is wasted HBM *and* wasted scan FLOPs.

Supports metric="l2" and "cosine" (rows are L2-normalized first, as the
reference does for spherical kmeans).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import traced
from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
from raft_tpu.cluster.kmeans import _update_centroids, init_random
from raft_tpu.random.rng import RngState


@dataclasses.dataclass
class KMeansBalancedParams:
    """reference: ``kmeans_balanced_params`` (cluster/kmeans_balanced_types.hpp)."""

    n_iters: int = 20
    metric: str = "l2"  # "l2" | "cosine"
    seed: int = 0
    mesocluster_factor: float = 1.0  # n_meso = factor * sqrt(k)


def _maybe_normalize(x: jax.Array, metric: str) -> jax.Array:
    if metric == "cosine":
        n = jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=-1, keepdims=True), 1e-12))
        return x / n
    return x


@partial(jax.jit, static_argnames=("n_clusters", "n_iters"))
def _balanced_lloyd(x, w, c0, n_clusters: int, n_iters: int, key,
                    split_iters=0):
    """Lloyd sweeps with per-sweep re-seeding of starved clusters
    (reference: adjust_centers, detail/kmeans_balanced.cuh).

    ``split_iters`` (traced) picks the re-seed target per sweep: sweeps
    ``i < split_iters`` re-seed at random far-ish rows *inside the
    fattest clusters* (best cluster BALANCE — measured on clustered
    100K×1024 data it cuts the max list from ~45× the mean to ~2×,
    which is exactly what the padded-list IVF layout needs; the
    deterministic farthest rows would be the boundary ring, which
    leaves the dense core as one cluster); later sweeps re-seed at the
    globally farthest points (best cluster QUALITY — the
    kmeans++-flavored choice for codebook/meso fits). Traced rather
    than static so both phases share ONE compiled program — XLA
    compilation is tens of seconds per variant on remote devices."""
    xf = x.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    total_w = jnp.maximum(jnp.sum(wf), 1e-12)
    # a cluster is "starved" below this fraction of the average mass
    starve_thresh = 0.25 * total_w / n_clusters

    def body(i, centroids):
        d2, labels = fused_l2_nn_argmin(xf, centroids)
        new_c, counts = _update_centroids(xf, wf, labels, n_clusters, centroids)
        starved = counts < starve_thresh
        u = jax.random.uniform(jax.random.fold_in(key, i),
                               (xf.shape[0],), minval=1e-6)
        split_score = counts[labels] + u * d2 / (jnp.max(d2) + 1e-12)
        far_score = jnp.where(
            wf > 0, jnp.where(i < split_iters, split_score, d2), -jnp.inf)
        # re-seed candidates need no exact order — the hardware approx
        # top-k replaces a full [n] sort per sweep (measured ~20 s at
        # n=2M, k=8192: it dominated billion-scale coarse training)
        _, far_idx = lax.approx_max_k(far_score, n_clusters,
                                      recall_target=0.9)
        # rank starved clusters; the j-th starved cluster takes the j-th
        # farthest point as its new center
        starved_rank = jnp.cumsum(starved.astype(jnp.int32)) - 1
        take_idx = far_idx[jnp.clip(starved_rank, 0, n_clusters - 1)]
        reseeded = xf[take_idx]
        new_c = jnp.where(starved[:, None], reseeded, new_c)
        return new_c

    return lax.fori_loop(0, n_iters, body, c0.astype(jnp.float32))


@partial(jax.jit, static_argnames=("k", "n_iters"))
def _balanced_lloyd_batched(xs, ws, c0s, kmask, k: int, n_iters: int):
    """All mesoclusters' fine Lloyd fits in ONE compiled program: padded
    row blocks ``xs [M, T, d]``, weight masks ``ws [M, T]`` (0 = pad),
    inits ``c0s [M, k, d]``, active-center masks ``kmask [M, k]`` (a
    meso wanting fewer than ``k`` centers masks the rest — inactive
    slots are pinned far away so they never attract rows nor re-seed).
    One batched einsum assigns, a one-hot MXU contraction updates, and
    starved clusters re-seed per meso — the batched twin of
    :func:`_balanced_lloyd`. Batching matters doubly on a remote device:
    a per-meso Python loop would compile one program per distinct
    (size, k) AND round-trip the host each step."""
    xf = xs.astype(jnp.float32)
    wf = ws.astype(jnp.float32)
    km = kmask.astype(jnp.bool_)
    M, T, d = xf.shape
    k_active = jnp.maximum(jnp.sum(km, axis=1).astype(jnp.float32), 1.0)
    total_w = jnp.maximum(jnp.sum(wf, axis=1), 1e-12)        # [M]
    starve_thresh = 0.25 * total_w / k_active                # [M]
    x_sq = jnp.sum(xf * xf, axis=-1)                         # [M, T]
    FAR = jnp.float32(1e15)

    def body(i, cs):
        cs = jnp.where(km[..., None], cs, FAR)               # park inactive
        c_sq = jnp.sum(cs * cs, axis=-1)                     # [M, k]
        g = jnp.einsum("mtd,mkd->mtk", xf, cs,
                       preferred_element_type=jnp.float32)
        d2 = jnp.maximum(x_sq[..., None] + c_sq[:, None, :] - 2.0 * g, 0.0)
        labels = jnp.argmin(d2, axis=-1)                     # [M, T]
        dmin = jnp.min(d2, axis=-1)
        oh = jax.nn.one_hot(labels, k, dtype=jnp.float32) * wf[..., None]
        counts = jnp.sum(oh, axis=1)                         # [M, k]
        sums = jnp.einsum("mtk,mtd->mkd", oh, xf,
                          preferred_element_type=jnp.float32)
        new_c = jnp.where(counts[..., None] > 0,
                          sums / jnp.maximum(counts[..., None], 1e-12), cs)
        starved = (counts < starve_thresh[:, None]) & km
        far_score = jnp.where(wf > 0, dmin, -jnp.inf)
        _, far_idx = lax.top_k(far_score, k)                 # [M, k]
        starved_rank = jnp.cumsum(starved.astype(jnp.int32), axis=1) - 1
        take = jnp.take_along_axis(far_idx,
                                   jnp.clip(starved_rank, 0, k - 1), axis=1)
        reseeded = jnp.take_along_axis(
            xf, take[..., None].astype(jnp.int32), axis=1)   # [M, k, d]
        return jnp.where(starved[..., None], reseeded, new_c)

    return lax.fori_loop(0, n_iters, body, c0s.astype(jnp.float32))


def build_clusters(
    x: jax.Array,
    n_clusters: int,
    params: Optional[KMeansBalancedParams] = None,
    sample_weights: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-level balanced clustering → (centers, labels, sizes).

    Counterpart of ``kmeans_balanced::helpers::build_clusters``
    (cluster/kmeans_balanced.cuh) — used directly for PQ codebook training.
    """
    if params is None:
        params = KMeansBalancedParams()
    xn = _maybe_normalize(jnp.asarray(x, jnp.float32), params.metric)
    n = xn.shape[0]
    w = jnp.ones((n,), jnp.float32) if sample_weights is None else sample_weights
    key = RngState(params.seed).key()
    c0 = init_random(key, xn, n_clusters)
    centers = _balanced_lloyd(xn, w, c0, n_clusters, params.n_iters, key)
    centers = _maybe_normalize(centers, params.metric)
    _, labels = fused_l2_nn_argmin(xn, centers)
    sizes = jax.ops.segment_sum(jnp.ones_like(w), labels, num_segments=n_clusters)
    return centers, labels, sizes.astype(jnp.int32)


# Above this fraction of the trainset, level-2 sampling truncation is a
# visible clustering-bias source, not a rounding error — warn.
_LEVEL2_DROP_WARN_FRAC = 0.02


def _warn_level2_drop(n_drop: int, n: int, cap: int) -> None:
    """Surface level-2 sampling bias (ADVICE r5): a skew-hot mesocluster
    past the 2×-mean block cap trains its fine centers on a TRUNCATED
    sample. Tolerable when rare (the trainset is a subsample anyway);
    silently losing a meaningful fraction of the trainset is not."""
    frac = n_drop / max(n, 1)
    if frac > _LEVEL2_DROP_WARN_FRAC:
        from raft_tpu.core import logging as _log
        _log.warn("kmeans_balanced: level-2 sampling dropped %d/%d "
                  "training rows (%.1f%%) past the per-mesocluster cap "
                  "%d — fine clusters of hot mesoclusters train on "
                  "truncated samples", n_drop, n, 100.0 * frac, cap)


@traced("raft_tpu.kmeans_balanced.fit")
# the hierarchical fit partitions fine-cluster quotas on the host BY
# DESIGN (documented in the level-2 block below) — its syncs are the
# algorithm, not an accident
def fit(  # graftlint: disable-fn=GL01
    x: jax.Array,
    n_clusters: int,
    params: Optional[KMeansBalancedParams] = None,
) -> jax.Array:
    """Hierarchical balanced fit → centers [n_clusters, d]
    (reference: kmeans_balanced::fit, cluster/kmeans_balanced.cuh:76)."""
    if params is None:
        params = KMeansBalancedParams()
    x = jnp.asarray(x, jnp.float32)
    xn = _maybe_normalize(x, params.metric)
    n, d = xn.shape
    expects(n_clusters <= n, "n_clusters=%d > n_samples=%d", n_clusters, n)
    key = RngState(params.seed).key()

    n_meso = max(1, min(n_clusters,
                        int(params.mesocluster_factor * math.isqrt(n_clusters))))
    if n_meso <= 1 or n_clusters <= 8:
        c0 = init_random(key, xn, n_clusters)
        w = jnp.ones((n,), jnp.float32)
        centers = _balanced_lloyd(xn, w, c0, n_clusters, params.n_iters, key)
        return _maybe_normalize(centers, params.metric)

    # level 1: mesoclusters (reference: detail/kmeans_balanced.cuh:758)
    w = jnp.ones((n,), jnp.float32)
    meso_c0 = init_random(key, xn, n_meso)
    meso_centers = _balanced_lloyd(xn, w, meso_c0, n_meso, params.n_iters, key)
    _, meso_labels = fused_l2_nn_argmin(xn, meso_centers)
    meso_labels_h = np.asarray(meso_labels)
    sizes = np.bincount(meso_labels_h, minlength=n_meso)

    # fine cluster counts ∝ mesocluster size, summing exactly to n_clusters
    quota = sizes / max(sizes.sum(), 1) * n_clusters
    fine_k = np.maximum(1, np.floor(quota).astype(np.int64))
    # distribute the remainder by largest fractional part
    while fine_k.sum() > n_clusters:
        fine_k[np.argmax(fine_k)] -= 1
    rem = n_clusters - fine_k.sum()
    if rem > 0:
        order = np.argsort(-(quota - np.floor(quota)))
        for j in order[:rem]:
            fine_k[j] += 1

    # level 2: per-mesocluster fine clustering on padded, masked row
    # blocks, batched into ONE compiled program. The rows are
    # partitioned into per-meso blocks ON DEVICE (ivf_common.pack_lists
    # — the same sort+scatter the IVF packers use): the previous host
    # partition shipped the trainset to the host and the padded blocks
    # back, ~0.75 GB of tunnel traffic at 500K×128 (~30-60 s at
    # 25 MB/s) plus one compile per pow2 size bucket. Block capacity is
    # capped at 2× the mean meso size; overflow rows of a skewed meso
    # are dropped from ITS TRAINING SAMPLE only (the trainset is a
    # subsample anyway — balance matters, completeness doesn't).
    from raft_tpu.neighbors import ivf_common as _ic

    avg_meso = max(1, -(-n // n_meso))
    L_meso = max(8, -(-2 * avg_meso // 8) * 8)
    (subs,), _mids, _sd, _drop, _addr = _ic.pack_lists_jit(
        [xn], meso_labels, jnp.arange(n, dtype=jnp.int32),
        n_lists=n_meso, L=L_meso, fill_values=[jnp.zeros((), xn.dtype)])
    _warn_level2_drop(int(_drop), n, L_meso)
    masks = (_mids >= 0).astype(jnp.float32)            # [n_meso, L]
    # active center count per meso, capped by its AVAILABLE block rows
    # (a meso past the block cap has only L_meso rows to fit on; the
    # global shortfall is backfilled below like empty mesos)
    sizes_c = np.minimum(np.maximum(sizes, 1), L_meso)
    k_active = np.maximum(np.minimum(np.minimum(fine_k, sizes), L_meso), 1)
    k_pad = int(k_active.max())
    # init: strided member rows of each block, spread over the FULL
    # member range per meso with linspace-style endpoints (first AND
    # last row included — a global k_pad stride clustered a small-
    # fine_k meso's inits in its first rows, measured to cost balance)
    pos = np.minimum(np.arange(k_pad)[None, :] * (sizes_c[:, None] - 1)
                     // np.maximum(k_active[:, None] - 1, 1),
                     sizes_c[:, None] - 1).astype(np.int32)
    c0s = jnp.take_along_axis(subs, jnp.asarray(pos)[..., None], axis=1)
    kmask_h = (np.arange(k_pad)[None, :]
               < k_active[:, None]).astype(np.float32)
    cms = np.asarray(_balanced_lloyd_batched(
        subs, masks, c0s, jnp.asarray(kmask_h), k_pad, params.n_iters))
    fine_centers = [cms[m, :int(k_active[m])]
                    for m in range(n_meso) if sizes[m] > 0]
    centers = jnp.asarray(np.concatenate(fine_centers, axis=0))
    if centers.shape[0] < n_clusters:  # lost slots to empty mesoclusters
        extra = init_random(jax.random.fold_in(key, 999), xn,
                            n_clusters - centers.shape[0])
        centers = jnp.concatenate([centers, extra], axis=0)

    # final joint sweeps over the full data: fat-splitting sweeps drive
    # list sizes toward the mean, then two plain quality sweeps settle
    # the centers — one compiled program (split_iters is traced)
    sweeps = max(2, params.n_iters // 4)
    centers = _balanced_lloyd(xn, w, centers, n_clusters, sweeps + 2, key,
                              split_iters=sweeps)
    return _maybe_normalize(centers, params.metric)


@traced("raft_tpu.kmeans_balanced.predict")
def predict(centers: jax.Array, x: jax.Array,
            params: Optional[KMeansBalancedParams] = None) -> jax.Array:
    """Nearest balanced-center labels (reference: kmeans_balanced::predict)."""
    metric = params.metric if params is not None else "l2"
    xn = _maybe_normalize(jnp.asarray(x, jnp.float32), metric)
    _, labels = fused_l2_nn_argmin(xn, centers)
    return labels


@partial(jax.jit, static_argnames=("row_tile", "k"))
def _topk_labels(centers, xn, row_tile: int, k: int):
    c_sq = jnp.sum(centers * centers, axis=1)
    m, d = xn.shape
    n_tiles = -(-m // row_tile)
    xp = jnp.pad(xn, ((0, n_tiles * row_tile - m), (0, 0)))

    def tile(xt):
        g = lax.dot_general(xt, centers, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        d2 = c_sq[None, :] - 2.0 * g  # rank-equivalent (x² constant/row)
        _, topk = lax.top_k(-d2, k)
        return topk.astype(jnp.int32)

    out = lax.map(tile, xp.reshape(n_tiles, row_tile, d))
    return out.reshape(n_tiles * row_tile, k)[:m]


def predict_topk(centers: jax.Array, x: jax.Array, k: int = 2,
                 params: Optional[KMeansBalancedParams] = None) -> jax.Array:
    """``k`` nearest centers per row → [m, k] int32 — feeds the packers'
    spill-cascade capacity capping (ivf_common.spill_assignments).
    Row-tiled so the [tile, n_lists] distance block stays bounded."""
    metric = params.metric if params is not None else "l2"
    xn = _maybe_normalize(jnp.asarray(x, jnp.float32), metric)
    k = min(k, centers.shape[0])
    tile = max(1024, min(x.shape[0], (256 << 20) // max(4 * centers.shape[0], 1)))
    return _topk_labels(centers, xn, -(-tile // 8) * 8, k)


def predict2(centers: jax.Array, x: jax.Array,
             params: Optional[KMeansBalancedParams] = None) -> jax.Array:
    """Two nearest centers per row → [m, 2] int32 (see predict_topk)."""
    return predict_topk(centers, x, 2, params)
