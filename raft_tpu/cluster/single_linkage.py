"""Single-linkage agglomerative clustering (HAC).

TPU-native counterpart of the reference's
``raft::cluster::single_linkage`` (cluster/single_linkage.cuh:53;
detail/{connectivities,mst,agglomerative,single_linkage}.cuh; cuSLINK
paper README.md:334-341).  Pipeline:

  knn-graph  →  symmetrize  →  connect components (cross_component_nn
  rounds until one component)  →  Boruvka MST  →  dendrogram (host
  union-find over weight-sorted MST edges — O(n α(n)) scalar work, the
  TPU analog of the reference's host-side agglomerative relabeling)  →
  flat cut at n_clusters.

The knn-graph connectivity (``LinkageDistance::KNN_GRAPH``) is the
reference's scalable default; pass ``n_neighbors >= n-1`` for the exact
pairwise construction (``LinkageDistance::PAIRWISE``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.tracing import traced


@dataclass
class SingleLinkageOutput:
    """Reference: linkage_output (cluster/single_linkage_types.hpp)."""

    labels: jnp.ndarray  # [n] flat cluster assignment
    children: np.ndarray  # [n-1, 2] merged cluster ids per dendrogram step
    distances: np.ndarray  # [n-1] merge heights
    sizes: np.ndarray  # [n-1] merged cluster sizes
    n_clusters: int


def _dendrogram(src, dst, w, n):
    """Host union-find over ascending-weight MST edges → scipy-style
    linkage rows (reference: detail/agglomerative.cuh build_dendrogram_host)."""
    order = np.argsort(w, kind="stable")
    src, dst, w = src[order], dst[order], w[order]
    parent = np.arange(n)
    cluster_id = np.arange(n, dtype=np.int64)  # cluster id held at each root
    size = np.ones(n, dtype=np.int64)  # subtree size held at each root

    def find(x):
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    children = np.zeros((len(src), 2), dtype=np.int64)
    heights = np.zeros(len(src), dtype=np.float64)
    sizes = np.zeros(len(src), dtype=np.int64)
    for i in range(len(src)):
        a, b = find(src[i]), find(dst[i])
        ca, cb = cluster_id[a], cluster_id[b]
        children[i] = (min(ca, cb), max(ca, cb))
        heights[i] = w[i]
        parent[b] = a
        size[a] += size[b]
        sizes[i] = size[a]
        cluster_id[a] = n + i
    return children, heights, sizes


def _cut(children, n, n_clusters):
    """Flat labels from the first n - n_clusters merges
    (reference: detail/agglomerative.cuh extract_flattened_clusters)."""
    parent = np.arange(2 * n - 1)

    def find(x):
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    for i in range(n - n_clusters):
        a, b = children[i]
        new = n + i
        parent[find(a)] = new
        parent[find(b)] = new
    roots = np.array([find(v) for v in range(n)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels


@traced("raft_tpu.single_linkage")
def single_linkage(
    dataset,
    n_clusters: int,
    metric: str = "sqeuclidean",
    n_neighbors: int = 15,
) -> SingleLinkageOutput:
    """Fit single-linkage HAC and cut into ``n_clusters`` flat clusters —
    counterpart of ``raft::cluster::single_linkage``
    (cluster/single_linkage.cuh:53)."""
    from ..label import connected_components
    from ..sparse.neighbors import cross_component_nn, knn_graph
    from ..sparse.ops import symmetrize
    from ..sparse.solver import mst
    from ..sparse.types import csr_to_coo, make_coo

    x = jnp.asarray(dataset)
    n = int(x.shape[0])
    if not 1 <= n_clusters <= n:
        raise ValueError(f"n_clusters={n_clusters} out of range [1, {n}]")
    k = min(n_neighbors, n - 1)
    graph = knn_graph(x, k, metric=metric)
    sym = symmetrize(graph, mode="max")

    # stitch components until the graph is connected (each round links
    # every component to its nearest neighbor component — halves count)
    for _ in range(32):
        labels, n_comp = connected_components(sym)
        if n_comp == 1:
            break
        bridge = cross_component_nn(x, labels, metric=metric)
        merged = csr_to_coo(sym)
        rows = jnp.concatenate([merged.rows, bridge.rows])
        cols = jnp.concatenate([merged.cols, bridge.cols])
        data = jnp.concatenate([merged.data, bridge.data.astype(merged.data.dtype)])
        sym = symmetrize(make_coo(rows, cols, data, sym.shape), mode="max")

    tree = mst(sym)
    children, heights, sizes = _dendrogram(tree.src, tree.dst, tree.weights, n)
    labels = _cut(children, n, n_clusters)
    return SingleLinkageOutput(
        labels=jnp.asarray(labels, jnp.int32),
        children=children,
        distances=heights,
        sizes=sizes,
        n_clusters=n_clusters,
    )
