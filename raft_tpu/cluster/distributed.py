"""Distributed k-means — sample-sharded SPMD Lloyd.

The reference's MNMG kmeans pattern (SURVEY.md §3.5: each worker runs the
local fused-L2 assign + local centroid sums, then ``allreduce`` merges the
sums — cuML on raft-dask/NCCL). Here the whole loop is one SPMD program:
``shard_map`` over the sample axis, ``lax.psum`` over ICI for the merge.

**Role in the distributed index build** (``parallel.build``): the
chunked pod builders train their coarse quantizer in one of two modes —
``coarse="replicated"`` (default) runs the single-host balanced-kmeans
trainer over the allgatherv'd cross-shard trainset, which keeps the
built index bit-identical to the single-host ``build_chunked``;
``coarse="distributed"`` routes HERE (:func:`fit`'s psum Lloyd over the
*sharded* trainset) when even the trainset is too big to replicate —
centers then differ from the single-host build (a different, equally
valid optimum), trading the sha-parity guarantee for trainset scale.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
from raft_tpu.cluster.kmeans import KMeansParams, init_random
from raft_tpu.core.tracing import traced
from raft_tpu.random.rng import RngState


@traced("raft_tpu.distributed_kmeans.fit")
def fit(
    params: KMeansParams,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "shard",
    init_centroids: Optional[jax.Array] = None,
    weights: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Distributed Lloyd fit over a sample-sharded dataset.

    ``x`` is [n, d], sharded (or shardable) over ``axis``; rows are padded
    to the device count with zero weights. Returns replicated
    (centroids, inertia, n_iter).

    ``weights`` (optional, [n] f32) weight the samples — the MNMG
    sample-weight support the reference's cuML kmeans carries. The
    distributed build's ``coarse="distributed"`` mode uses zero weights
    to mask the pad rows of its stacked ragged per-shard sample, so the
    sample never has to be gathered/replicated: each shard's slice stays
    its own and only the [k, d] centroid sums ride the psum. Zero-weight
    rows contribute to no centroid and no inertia; random init draws
    from positive-weight rows only.
    """
    # deferred: parallel.ivf imports this module, so a top-level comms
    # import would be circular
    from raft_tpu.parallel.comms import Comms

    comms = Comms(axis)
    n, d = x.shape
    k = params.n_clusters
    n_dev = mesh.shape[axis]
    padded_n = -(-n // n_dev) * n_dev
    w = (jnp.ones((n,), jnp.float32) if weights is None
         else jnp.asarray(weights, jnp.float32))
    if padded_n != n:
        x = jnp.pad(x, ((0, padded_n - n), (0, 0)))
        w = jnp.pad(w, (0, padded_n - n))

    if init_centroids is None:
        key = RngState(params.seed).key()
        if weights is None:
            init_centroids = init_random(key, x[:n], k)
        else:
            # draw initial centroids from REAL rows only — a zero-weight
            # pad row picked as an init would seed a dead centroid at
            # the origin (weights are concrete here: this runs on the
            # host before the SPMD program)
            real = jnp.flatnonzero(w[:n] > 0)
            init_centroids = init_random(key, x[real], k)

    def step(x_shard, w_shard, centroids):
        """One Lloyd iteration: local assign + psum-merged update."""
        d2, labels = fused_l2_nn_argmin(x_shard, centroids)
        local_sums = jax.ops.segment_sum(x_shard * w_shard[:, None], labels,
                                         num_segments=k)
        local_counts = jax.ops.segment_sum(w_shard, labels, num_segments=k)
        local_inertia = jnp.sum(w_shard * d2)
        # the reference's allreduce (core/comms.hpp:344), via the Comms
        # facade so the merge traffic is counted per op × axis
        sums = comms.allreduce(local_sums)
        counts = comms.allreduce(local_counts)
        inertia = comms.allreduce(local_inertia)
        new_c = jnp.where(counts[:, None] > 0,
                          sums / jnp.maximum(counts[:, None], 1e-12), centroids)
        return new_c, inertia

    def fit_loop(x_shard, w_shard, c0):
        def cond(carry):
            _, shift2, it, _ = carry
            return (it < params.max_iter) & (shift2 > params.tol * params.tol)

        def body(carry):
            c, _, it, _ = carry
            new_c, inertia = step(x_shard, w_shard, c)
            return new_c, jnp.sum((new_c - c) ** 2), it + 1, inertia

        init = (c0, jnp.array(jnp.inf, jnp.float32), jnp.array(0, jnp.int32),
                jnp.array(jnp.inf, jnp.float32))
        c, _, n_iter, inertia = lax.while_loop(cond, body, init)
        return c, inertia, n_iter

    fn = shard_map(
        fit_loop, mesh=mesh,
        in_specs=(P(axis, None), P(axis), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return fn(x.astype(jnp.float32), w, init_centroids.astype(jnp.float32))


@traced("raft_tpu.distributed_kmeans.predict")
def predict(centroids: jax.Array, x: jax.Array, mesh: Mesh,
            axis: str = "shard") -> jax.Array:
    """Sharded nearest-centroid assignment; labels return sharded."""
    n = x.shape[0]
    n_dev = mesh.shape[axis]
    padded_n = -(-n // n_dev) * n_dev
    if padded_n != n:
        x = jnp.pad(x, ((0, padded_n - n), (0, 0)))

    fn = shard_map(
        lambda xs, c: fused_l2_nn_argmin(xs, c)[1], mesh=mesh,
        in_specs=(P(axis, None), P()), out_specs=P(axis),
        check_vma=False,
    )
    return fn(x.astype(jnp.float32), centroids)[:n]
