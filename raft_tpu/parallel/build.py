"""Distributed billion-scale index build — sharded assign+encode with
host→HBM prefetch overlap and allgatherv-lean comms.

The reference's MNMG build story (raft-dask/NCCL: each worker builds
over its slice, SURVEY.md §2.15) restructured for the TPU pod and for
datasets that live in host memmaps rather than device memory — the
missing half of BASELINE config 5 (sharded IVF-PQ, SIFT-1B on v5e-64)
next to PR-8's sharded search. Shape of the pass:

- **coarse + PQ quantizers replicated, trained once** — the SAME
  trainset sample, trainer (:func:`ivf_pq._train_quantizers` /
  ``kmeans_balanced.fit``) and keys as the single-host
  ``build_chunked``, so the distributed build is *bit-identical* to the
  single-host one after assembly (:func:`assemble_ivf_pq`; the CI mesh
  asserts sha equality). The trainset rows are gathered from the shards
  with ONE ``allgatherv`` (each shard contributes the sample rows it
  owns, ragged, packed to rank order); an opt-in ``coarse="distributed"``
  trades the parity guarantee for the psum-Lloyd MNMG trainer
  (:func:`cluster.distributed.fit`) when even the trainset gather is
  too big;
- **assignment + encode shard-parallel over the data axis** — each
  shard walks only its contiguous memmap slice ``[rank·shard_rows,
  …)`` in chunks, with a double-buffered host→HBM prefetcher
  (:class:`ChunkPrefetcher`: a background reader thread issues chunk
  N+1's host read + ``jax.device_put`` under chunk N's jitted
  assign/encode; reads retry under
  :data:`raft_tpu.robust.retry.IO_POLICY` at the ``build.chunk_read``
  fault point). ``build.prefetch.{hit,stall}`` counters and the
  ``span.<entry>.encode`` / ``span.<entry>.h2d`` decomposition prove
  the overlap in obs rows — ``h2d`` times only the *un-hidden* wait;
- **comms stay allgatherv-of-per-list-counts only** — after the train
  phase, the sole collective is one ``allgatherv`` of each shard's
  ``[n_lists]`` label histogram (it sizes the global list capacity
  ``L``); encoded codes, norms and id tables NEVER cross the
  interconnect. Every byte rides the :class:`~raft_tpu.parallel.comms.
  Comms` facade, so ``comms.bytes{op=allgatherv}`` is the build's whole
  comms story (the dryrun asserts exactly that);
- **per-shard output the ring searcher consumes directly** — each shard
  packs its lists host-side in global row order (the
  ``ivf_pq._stable_slots`` pack, cursor-chained across chunks) and the
  stacked result is a :class:`~raft_tpu.parallel.ivf.ShardedIvfPq` /
  ``ShardedIvfFlat`` with global ids stamped via the
  :mod:`raft_tpu.core.ids` policy (``rank·shard_rows + local``,
  int64 past 2³¹ pod rows) — ``search_ivf_pq`` (ring or allgather
  merge, fused scan-in-ring included) takes it as-is;
- **preemption-safe per shard** — with ``checkpoint_dir=`` the PR-7
  checkpoint layer records quantizers, per-shard label passes and one
  encoded shard per (shard, chunk) (``robust.checkpoint`` shard-axis
  naming); resume validates the dataset/params fingerprints
  (fingerprinted ONCE, the elapsed time stamped into the manifest) and
  replays completed chunks to a sha-identical sharded index.

Layout invariant (what makes the sha stable): shard ``s`` packs row
``g`` of list ``l`` at the slot equal to the number of shard-``s`` rows
of list ``l`` preceding ``g`` — so concatenating the shards' list
prefixes in rank order reproduces the single-host pack exactly
(:func:`assemble_ivf_pq`), because shard slices partition the row range
contiguously in rank order.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from raft_tpu.core.errors import expects
from raft_tpu.core.tracing import span
from raft_tpu.core import ids as _ids
from raft_tpu.obs import sanitize as _sanitize
from raft_tpu.obs import spans as _obs_spans
from raft_tpu.parallel.comms import Comms
from raft_tpu.robust import degrade as _degrade
from raft_tpu.robust import faults as _faults
from raft_tpu.robust import retry as _retry


# ---------------------------------------------------------------------------
# host→HBM chunk prefetcher
# ---------------------------------------------------------------------------

class ChunkPrefetcher:
    """Double-buffered host→HBM chunk pipeline.

    A background reader thread walks ``ranges`` in order, calling
    ``read_fn(lo, hi)`` (host read + dtype convert + ``device_put`` —
    the read retries/faults belong inside ``read_fn``) and parking up to
    ``depth`` finished device chunks in a bounded queue. The consumer's
    :meth:`get` then returns chunk N while the reader is already filling
    chunk N+1 — the host IO and H2D copy of the next chunk hide under
    the current chunk's jitted encode, which runs in XLA-land and
    releases the GIL.

    Accounting (the overlap's proof, recorded only when obs is on):

    - ``build.prefetch.hit{site=}`` — the chunk was already resident
      when requested (the read fully hid under compute);
    - ``build.prefetch.stall{site=}`` — the consumer had to wait; the
      wait itself runs under a ``span("h2d")`` so the *un-hidden*
      host→HBM time lands in ``span.<entry>.h2d`` next to
      ``span.<entry>.encode``.

    ``prefetch=False`` degenerates to a serial reader (every get is an
    inline read under the same span/counter names) — the bench's
    serialized-copy-then-encode comparison leg.

    Error contract: an exception in the reader thread (IO error past the
    retry budget, an injected fault) is re-raised at the consumer's next
    :meth:`get`; the reader exits after queueing it. :meth:`close` is
    idempotent, drains the queue and joins the thread — safe to call
    mid-stream (the ``finally`` of an interrupted build).
    """

    def __init__(self, read_fn: Callable[[int, int], jax.Array],
                 ranges: Sequence[Tuple[int, int]], depth: int = 2,
                 prefetch: bool = True, counter_site: str = "build"):
        self._read = read_fn
        self._ranges = list(ranges)
        self._site = counter_site
        self._prefetch = bool(prefetch) and len(self._ranges) > 0
        self._taken = 0
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self._prefetch:
            self._thread = threading.Thread(
                target=self._run, name="raft_tpu-chunk-prefetch",
                daemon=True)
            self._thread.start()

    def __len__(self) -> int:
        return len(self._ranges)

    def _count(self, name: str) -> None:
        if _obs_spans.enabled():
            _obs_spans.registry().inc(name, labels={"site": self._site})

    def _run(self) -> None:
        for i, (a, b) in enumerate(self._ranges):
            if self._stop.is_set():
                return
            try:
                item = (i, self._read(a, b), None)
            except BaseException as e:  # propagated at the next get()
                item = (i, None, e)
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.05)
                    break
                except queue.Full:
                    continue
            if item[2] is not None:
                return

    def get(self) -> jax.Array:
        """Next chunk as a device array (in ``ranges`` order). Raises
        the reader's exception if its read failed; ``IndexError`` past
        the end."""
        if self._taken >= len(self._ranges):
            raise IndexError("ChunkPrefetcher exhausted")
        if not self._prefetch:
            a, b = self._ranges[self._taken]
            self._count("build.prefetch.stall")
            with span("h2d"):
                x = self._read(a, b)
            self._taken += 1
            return x
        # benign race on empty(): a reader mid-put counts as a stall
        # with a ~zero-length wait — the conservative side
        if self._q.empty():
            self._count("build.prefetch.stall")
            with span("h2d"), _sanitize.blocking_region("queue.get"):
                i, x, exc = self._q.get()
        else:
            self._count("build.prefetch.hit")
            with _sanitize.blocking_region("queue.get"):
                i, x, exc = self._q.get()
        if exc is not None:
            self.close()
            raise exc
        self._taken += 1
        return x

    def close(self) -> None:
        """Stop the reader and release queue slots (idempotent). A
        reader stuck inside a slow retried read can outlive the join
        timeout — keep the handle (and say so) instead of dropping the
        reference, so the still-running thread is visible rather than
        silently issuing reads against a stage that moved on."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread is not None:
            with _sanitize.blocking_region("join"):
                self._thread.join(timeout=5.0)
            if self._thread.is_alive():
                from raft_tpu.core import logging as _log
                _log.warn("ChunkPrefetcher.close: reader thread still "
                          "inside a read after 5s (slow IO/retry "
                          "backoff) — it will exit at its next "
                          "stop-flag check")
            else:
                self._thread = None


# ---------------------------------------------------------------------------
# shard geometry + the two allgatherv programs
# ---------------------------------------------------------------------------

def shard_ranges(n: int, n_dev: int) -> Tuple[List[Tuple[int, int]], int]:
    """Contiguous per-shard row ranges ``[(lo, hi), ...]`` and the
    padded per-shard row count ``shard_rows = ceil(n / n_dev)`` — the
    global-id offset base (``rank · shard_rows + local``). The last
    shard may be ragged (``hi − lo < shard_rows``)."""
    shard_n = -(-n // n_dev)
    # tail shards past the row count are EMPTY (lo == hi), not negative
    # — a 5-row dataset on an 8-shard mesh builds 3 empty shards
    return ([(min(n, s * shard_n), min(n, (s + 1) * shard_n))
             for s in range(n_dev)], shard_n)


def _chunk_ranges(lo: int, hi: int, chunk_rows: int) -> List[Tuple[int, int]]:
    return [(a, min(hi, a + chunk_rows)) for a in range(lo, hi, chunk_rows)]


def gather_trainset_rows(stacked: jax.Array, counts: jax.Array,
                         n_rows: int, mesh: Mesh, axis: str) -> jax.Array:
    """Replicate the cross-shard trainset with ONE ``allgatherv``.

    ``stacked [n_dev, cap, d]`` holds each shard's owned sample rows
    (ragged, zero-padded to the fattest shard's count), ``counts
    [n_dev]`` the valid-row counts. The allgatherv packs valid rows to
    the front in rank order — and because the global sample indices are
    sorted and shard slices partition the row range contiguously in
    rank order, the packed result IS the sample in global index order:
    bit-equal to the single-host ``dataset[tr_idx]`` read. Counted as
    gather-family traffic on the facade (``comms.bytes{op=allgatherv}``,
    axis-size × payload)."""
    comms = Comms(axis)

    def body(xs, cs):
        g, _ = comms.allgatherv(xs[0], cs[0], compact=True)
        return g

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(axis, None, None), P(axis)),
                   out_specs=P(), check_vma=False)
    # host-side collective timing (ISSUE 15): the dispatch runs under a
    # comms.allgatherv span (sync mode blocks on the gathered result),
    # so per-host flight dumps carry timed collective events the fleet
    # aggregator's straggler table compares across the pod
    with span("comms.allgatherv", labels={"op": "allgatherv",
                                          "axis": axis}) as sp:
        out = fn(stacked, counts)[:n_rows]
        sp.attach(out)
    return out


def gather_list_counts(local_counts, mesh: Mesh, axis: str) -> jax.Array:
    """The build's ONE post-train collective: every shard's
    ``[n_lists]`` label histogram crosses the interconnect as a single
    ``allgatherv`` row (codes/ids/norms never do) and each shard gets
    the full ``[n_dev, n_lists]`` table back — it sizes the global list
    capacity ``L`` and the stacked per-shard capacity ``L_shard``.
    Returns the gathered (replicated) table; trace-safe, so the
    collective-schedule checker can walk it."""
    comms = Comms(axis)

    def body(c):
        g, _ = comms.allgatherv(c, jnp.int32(1), compact=False)
        return g

    fn = shard_map(body, mesh=mesh, in_specs=(P(axis, None),),
                   out_specs=P(), check_vma=False)
    # timed like gather_trainset_rows: the straggler table wants every
    # host-driven collective dispatch comparable across the pod
    with span("comms.allgatherv", labels={"op": "allgatherv",
                                          "axis": axis}) as sp:
        out = fn(jnp.asarray(local_counts, jnp.int32))
        sp.attach(out)
    return out


# ---------------------------------------------------------------------------
# shared host-side helpers
# ---------------------------------------------------------------------------

def _count_resume(site: str, name: str, value: float = 1.0) -> None:
    if _obs_spans.enabled():
        _obs_spans.registry().inc(name, value, labels={"site": site})


def _read_rows(dataset, idx_or_slice, site: str):
    """One host read under the shared IO retry policy + fault point —
    the same contract as ``build_chunked``'s ``read_chunk``."""
    def _do():
        _faults.faultpoint(site)
        if hasattr(dataset, "sample_rows") and not isinstance(
                idx_or_slice, slice):
            return np.asarray(dataset.sample_rows(idx_or_slice),
                              np.float32)
        return np.asarray(dataset[idx_or_slice], np.float32)
    return _retry.retry_call(_do, site=site, policy=_retry.IO_POLICY)


def _make_read_chunk(dataset, normalize: bool):
    """``read_fn(a, b)`` for the prefetcher: retried host read →
    ``float32`` → device, cosine rows normalized — bit-identical to
    ``build_chunked.to_device(read_chunk(a, b))``."""
    def read_chunk(a, b):
        x = jnp.asarray(_read_rows(dataset, slice(a, b),
                                   "build.chunk_read"))
        if normalize:
            x = x / jnp.sqrt(jnp.maximum(
                jnp.sum(x * x, -1, keepdims=True), 1e-12))
        return x
    return read_chunk


def _owned_sample(dataset, tr_idx: np.ndarray,
                  ranges: Sequence[Tuple[int, int]]
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Each shard's owned sample rows, stacked ragged: ``(stacked
    [n_dev, cap, d] f32 zero-padded, counts [n_dev])``. Reads retry at
    the ``build.train_sample`` fault point."""
    n_dev = len(ranges)
    owned = [tr_idx[(tr_idx >= lo) & (tr_idx < hi)] for lo, hi in ranges]
    cap = max(1, max(len(o) for o in owned))
    d = dataset.shape[1]
    stacked = np.zeros((n_dev, cap, d), np.float32)
    counts = np.zeros((n_dev,), np.int32)
    for s, o in enumerate(owned):
        if len(o):
            stacked[s, :len(o)] = _read_rows(dataset, o,
                                             "build.train_sample")
        counts[s] = len(o)
    return stacked, counts


def _gather_trainset(dataset, tr_idx: np.ndarray,
                     ranges: Sequence[Tuple[int, int]], mesh: Mesh,
                     axis: str, normalize: bool) -> jax.Array:
    """Each shard reads the sample rows it owns (retried at
    ``build.train_sample``), then :func:`gather_trainset_rows`
    replicates them; cosine normalization runs once on the replicated
    result, as the single-host trainer does."""
    stacked, counts = _owned_sample(dataset, tr_idx, ranges)
    tr = gather_trainset_rows(jnp.asarray(stacked), jnp.asarray(counts),
                              len(tr_idx), mesh, axis)
    if normalize:
        tr = tr / jnp.sqrt(jnp.maximum(
            jnp.sum(tr * tr, -1, keepdims=True), 1e-12))
    return tr


def _coarse_distributed(dataset, tr_idx: np.ndarray,
                        ranges: Sequence[Tuple[int, int]], mesh: Mesh,
                        axis: str, n_lists: int, n_iters: int, seed: int,
                        spherical: bool, normalize: bool) -> jax.Array:
    """``coarse="distributed"``'s trainer: psum-Lloyd MNMG kmeans
    (:func:`raft_tpu.cluster.distributed.fit`) over the SHARDED sample —
    each shard's owned rows stay its own slice (the stacked ragged
    sample shards contiguously over the axis; zero weights mask the pad
    rows), so the full trainset is never gathered/replicated: only the
    ``[k, d]`` centroid sums cross the interconnect per Lloyd step.
    This is the mode's reason to exist — the replicated default's
    trainset gather is the thing that stops scaling first."""
    from raft_tpu.cluster import KMeansParams
    from raft_tpu.cluster import distributed as dkm

    n_dev = len(ranges)
    stacked, counts = _owned_sample(dataset, tr_idx, ranges)
    cap = stacked.shape[1]
    x_flat = jnp.asarray(stacked.reshape(n_dev * cap, -1))
    if normalize:
        x_flat = x_flat / jnp.sqrt(jnp.maximum(
            jnp.sum(x_flat * x_flat, -1, keepdims=True), 1e-12))
    w = (np.arange(cap)[None, :] < counts[:, None]).reshape(-1)
    kmp = KMeansParams(n_clusters=n_lists, max_iter=n_iters, seed=seed)
    centers, _, _ = dkm.fit(kmp, x_flat, mesh, axis=axis,
                            weights=jnp.asarray(w, jnp.float32))
    if spherical:
        centers = centers / jnp.sqrt(jnp.maximum(
            jnp.sum(centers ** 2, -1, keepdims=True), 1e-12))
    return centers


def _shard_label_pass(dataset, lo: int, hi: int, chunk_rows: int,
                      predict_fn, prefetch: bool,
                      site: str, normalize: bool) -> np.ndarray:
    """One shard's streaming label pass: chunked walk of the shard's
    memmap slice through the prefetcher, nearest-center assignment per
    chunk under ``span("assign")``."""
    labels = np.empty(hi - lo, np.int32)
    pf = ChunkPrefetcher(_make_read_chunk(dataset, normalize),
                         _chunk_ranges(lo, hi, chunk_rows),
                         prefetch=prefetch, counter_site=site)
    try:
        for a, b in _chunk_ranges(lo, hi, chunk_rows):
            xb = pf.get()
            with span("assign"):
                labels[a - lo:b - lo] = np.asarray(predict_fn(xb))
    finally:
        pf.close()
    return labels


# ---------------------------------------------------------------------------
# IVF-PQ distributed build
# ---------------------------------------------------------------------------

def build_ivf_pq_distributed(dataset, params, mesh: Mesh,
                             axis: str = "shard",
                             chunk_rows: int = 1 << 18,
                             max_train_rows: int = 1 << 21,
                             prefetch: bool = True,
                             coarse: str = "replicated",
                             checkpoint_dir: Optional[str] = None,
                             resume=False,
                             progress: bool = False):
    """Distributed chunked IVF-PQ build (see the module docstring;
    public entry: :func:`raft_tpu.neighbors.ivf_pq.build_distributed`).
    Returns a :class:`~raft_tpu.parallel.ivf.ShardedIvfPq` that
    ``search_ivf_pq`` consumes directly."""
    from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.distance.types import DistanceType, resolve_metric
    from raft_tpu.neighbors import ivf_pq as _pq
    from raft_tpu.neighbors.ivf_flat import _fit_list_size, _lane_round
    from raft_tpu.parallel.ivf import ShardedIvfPq

    site = "ivf_pq.build_distributed"
    t0 = time.time()

    def _say(msg):
        if progress:
            print(f"[build_distributed +{time.time() - t0:7.0f}s] {msg}",
                  flush=True)

    mt = resolve_metric(params.metric)
    expects(params.codebook_kind == "per_subspace",
            "distributed build supports per_subspace codebooks")
    expects(4 <= params.pq_bits <= 8, "pq_bits must be in [4, 8]")
    expects(not params.spill,
            "distributed build does not support spill=True yet (the "
            "spill cascade needs the global histogram mid-pass)")
    expects(coarse in ("replicated", "distributed"),
            "coarse must be 'replicated' or 'distributed' (got %r)",
            coarse)
    expects(resume in (False, True, "auto"),
            "resume must be False, True, or 'auto' (got %r)", resume)
    expects(not resume or checkpoint_dir is not None,
            "resume=%r needs checkpoint_dir=", resume)
    n, dim = dataset.shape
    n_dev = mesh.shape[axis]
    ranges, shard_n = shard_ranges(n, n_dev)
    spherical = mt in (DistanceType.InnerProduct,
                       DistanceType.CosineExpanded)
    normalize = mt == DistanceType.CosineExpanded

    pq_dim = params.pq_dim or _pq._default_pq_dim(dim)
    pq_len = -(-dim // pq_dim)
    K = 1 << params.pq_bits
    key = jax.random.PRNGKey(params.seed)
    km = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                              metric="cosine" if spherical else "l2",
                              seed=params.seed)

    # checkpoint bootstrap: fingerprint ONCE (timed), validate on resume
    ck = manifest = None
    base_manifest = {}
    if checkpoint_dir is not None:
        import dataclasses as _dc
        import os

        from raft_tpu.robust import checkpoint as _ckpt

        ck = _ckpt.BuildCheckpoint(checkpoint_dir)
        # fingerprint ONCE for the whole pod build; every shard scope
        # below reuses the pair — shards never re-fingerprint
        ds_sha, p_sha, fp_s = _ckpt.fingerprints_once(
            dataset, {**_dc.asdict(params), "chunk_rows": chunk_rows,
                      "max_train_rows": max_train_rows,
                      "n_shards": n_dev, "coarse": coarse,
                      "build": "distributed"})
        base_manifest = {"dataset_sha": ds_sha, "params_sha": p_sha,
                         "fingerprint_s": round(fp_s, 6),
                         "n": int(n), "dim": int(dim),
                         "chunk_rows": int(chunk_rows),
                         "n_shards": int(n_dev),
                         "shard_rows": int(shard_n)}
        if resume is True or (resume == "auto"
                              and os.path.exists(ck.manifest_path)):
            manifest = ck.load_manifest()
            ck.validate_manifest(manifest, ds_sha, p_sha)
            _count_resume(site, "resume.attempts")
            _say(f"resuming from {ck.manifest_path} "
                 f"(phase {manifest.get('phase')}, shard chunks "
                 f"{manifest.get('shard_chunks_done')})")

    # 1. quantizers — the exact single-host trainer over the exact
    # single-host trainset sample, so the distributed build stays
    # bit-identical to build_chunked after assembly
    if manifest is not None:
        _say("resume: loading quantizer state")
        q = ck.load_arrays("quantizers")
        centers = jnp.asarray(q["centers"])
        rotation = jnp.asarray(q["rotation"])
        centers_rot = jnp.asarray(q["centers_rot"])
        codebooks = jnp.asarray(q["codebooks"])
    else:
        n_train = min(n, max_train_rows,
                      max(params.n_lists * 4, 4 * K,
                          int(n * params.kmeans_trainset_fraction)))
        rng = np.random.default_rng(params.seed)
        tr_idx = np.sort(rng.choice(n, n_train, replace=False))
        with span("train"):
            if coarse == "distributed":
                # the MNMG psum-Lloyd trainer over the SHARDED sample
                # (never replicated — see _coarse_distributed), at the
                # cost of bit-parity with the single-host build
                # (cluster/distributed.py documents the trade). Only
                # the SMALL codebook subsample (the same ≤ 2¹⁶-row
                # stride _train_quantizers would take) is gathered, and
                # the codebooks train on residuals to the DISTRIBUTED
                # centers — the centers the index actually encodes
                # against.
                _say(f"distributed coarse fit over the sharded "
                     f"{n_train}-row sample")
                centers = _coarse_distributed(
                    dataset, tr_idx, ranges, mesh, axis, params.n_lists,
                    params.kmeans_n_iters, params.seed, spherical,
                    normalize)
                stride = max(1, -(-n_train // (1 << 16)))
                cb_sample = _gather_trainset(dataset, tr_idx[::stride],
                                             ranges, mesh, axis,
                                             normalize)
                _, rotation, centers_rot, codebooks = \
                    _pq._train_quantizers(cb_sample, params, dim, pq_dim,
                                          pq_len, K, key, km,
                                          centers=centers)
                del cb_sample
            else:
                _say(f"gathering {n_train} train rows (one allgatherv)")
                trainset = _gather_trainset(dataset, tr_idx, ranges,
                                            mesh, axis, normalize)
                centers, rotation, centers_rot, codebooks = \
                    _pq._train_quantizers(trainset, params, dim, pq_dim,
                                          pq_len, K, key, km)
                del trainset
            jax.block_until_ready(codebooks)
        if ck is not None:
            ck.save_arrays("quantizers",
                           centers=np.asarray(centers),
                           rotation=np.asarray(rotation),
                           centers_rot=np.asarray(centers_rot),
                           codebooks=np.asarray(codebooks))
            ck.write_manifest({**base_manifest, "phase": "label"})
    _say("quantizers trained; per-shard label pass")

    # 2. per-shard streaming label pass (prefetched), then the build's
    # ONE collective: allgatherv of the per-shard label histograms
    have_labels = (manifest is not None
                   and manifest.get("phase") in ("encode", "done"))
    labels_by_shard: List[np.ndarray] = []
    if have_labels:
        _say("resume: loading per-shard label passes")
        for s, (lo, hi) in enumerate(ranges):
            lb = np.asarray(ck.load_arrays(f"labels_s{s:03d}")["labels"],
                            np.int32)
            expects(lb.shape[0] == hi - lo,
                    "resume label checkpoint for shard %d holds %d rows, "
                    "expected %d", s, lb.shape[0], hi - lo)
            labels_by_shard.append(lb)
        # L/L_shard come from the manifest; per-shard sizes re-derive
        # from the loaded labels in the pack loop below
        L = int(manifest["L"])
        L_shard = int(manifest["L_shard"])
    else:
        def predict_fn(xb):
            return kmeans_balanced.predict(centers, xb, km)

        local_counts = np.zeros((n_dev, params.n_lists), np.int64)
        for s, (lo, hi) in enumerate(ranges):
            lb = _shard_label_pass(dataset, lo, hi, chunk_rows,
                                   predict_fn, prefetch, site, normalize)
            labels_by_shard.append(lb)
            local_counts[s] = np.bincount(lb, minlength=params.n_lists)
            if ck is not None:
                ck.save_arrays(f"labels_s{s:03d}", labels=lb)
            _say(f"shard {s}: labeled {hi - lo} rows")
        counts_by_shard = np.asarray(
            gather_list_counts(local_counts, mesh, axis))
        counts = counts_by_shard.sum(axis=0)
        avg = max(1, n // params.n_lists)
        L = _fit_list_size(counts, avg, params.list_size_cap_factor)
        # the stacked per-shard capacity: big enough that no shard drops
        # a row the GLOBAL capacity would keep (a kept row's within-
        # shard slot is < min(L, its shard's fattest list)), small
        # enough that the [n_dev, n_lists, L_shard, ...] tables don't
        # pay the global capacity per shard
        L_shard = min(L, _lane_round(int(max(1, counts_by_shard.max()))))
        if ck is not None:
            ck.write_manifest({**base_manifest, "phase": "encode",
                               "L": int(L), "L_shard": int(L_shard),
                               "shard_chunks_done": [0] * n_dev})
    nbytes = _pq.packed_nbytes(pq_dim, params.pq_bits)
    n_total_pad = n_dev * shard_n  # id width follows the PADDED total
    id_dt = _ids.np_id_dtype(n_total_pad)

    # 3. per-shard encode + pack (prefetched; codes never leave the
    # shard). RESOURCE_EXHAUSTED on an encode chunk halves it in place —
    # each row's encode is independent.
    def encode_rows(xb, lb, lo, hi):
        try:
            codes, norms = _pq._encode_with_norms(
                xb @ rotation.T, centers_rot, lb, codebooks,
                params.codebook_kind)
            return (_pq.pack_bits_np(np.asarray(codes), params.pq_bits),
                    np.asarray(norms))
        except Exception as e:
            if not _degrade.is_resource_exhausted(e) or hi - lo <= 1024:
                raise
            _degrade.note_step(site, "chunk", "half_chunk",
                               "resource_exhausted")
            mid = (hi - lo) // 2
            c1, n1 = encode_rows(xb[:mid], lb[:mid], lo, lo + mid)
            c2, n2 = encode_rows(xb[mid:], lb[mid:], lo + mid, hi)
            return np.concatenate([c1, c2]), np.concatenate([n1, n2])

    chunks_done = (list(manifest.get("shard_chunks_done", [0] * n_dev))
                   if have_labels else [0] * n_dev)
    packed = np.zeros((n_dev, params.n_lists, L_shard, nbytes), np.uint8)
    ids = np.full((n_dev, params.n_lists, L_shard), -1, id_dt)
    pnorm = np.zeros((n_dev, params.n_lists, L_shard), np.float32)
    sizes = np.zeros((n_dev, params.n_lists), np.int32)
    dropped = 0
    with span("encode_pack"):
        for s, (lo, hi) in enumerate(ranges):
            labels_s = labels_by_shard[s]
            cursor = np.zeros(params.n_lists, np.int64)
            chunks = _chunk_ranges(lo, hi, chunk_rows)
            pf = ChunkPrefetcher(
                _make_read_chunk(dataset, normalize),
                # replayed chunks need no device work — don't read them
                chunks[chunks_done[s]:], prefetch=prefetch,
                counter_site=site)
            try:
                for ci, (a, b) in enumerate(chunks):
                    if ci < chunks_done[s]:
                        shard = ck.load_shard(ci, shard=s)
                        codes_h = np.asarray(shard["codes"], np.uint8)
                        norms_h = np.asarray(shard["norms"], np.float32)
                        expects(codes_h.shape[0] == b - a,
                                "resume shard (%d, chunk %d) holds %d "
                                "rows, expected %d — corrupt checkpoint",
                                s, ci, codes_h.shape[0], b - a)
                        _count_resume(site, "resume.chunks_replayed")
                    else:
                        xb = pf.get()
                        _faults.faultpoint("build.chunk_encode")
                        lb = jnp.asarray(labels_s[a - lo:b - lo])
                        with span("encode"):
                            codes_h, norms_h = encode_rows(xb, lb, a, b)
                        if ck is not None:
                            # shard first, then the manifest recording
                            # it (the build_chunked ordering)
                            ck.save_shard(ci, shard=s, codes=codes_h,
                                          norms=norms_h)
                            done = list(chunks_done)
                            done[s] = ci + 1
                            ck.write_manifest(
                                {**base_manifest, "phase": "encode",
                                 "L": int(L), "L_shard": int(L_shard),
                                 "shard_chunks_done": done})
                            chunks_done = done
                    lb_h = labels_s[a - lo:b - lo]
                    order, sorted_l, slot = _pq._stable_slots(
                        lb_h, params.n_lists, cursor)
                    keep = (slot < L_shard) & (sorted_l < params.n_lists)
                    dropped += int((~keep).sum())
                    rows = order[keep]
                    ls, sl = sorted_l[keep], slot[keep].astype(np.int64)
                    packed[s, ls, sl] = codes_h[rows]
                    # global ids through the one id-dtype policy:
                    # rank·shard_rows + local (= the global row number,
                    # because shard slices are contiguous in rank order)
                    ids[s, ls, sl] = (a + rows).astype(id_dt)
                    pnorm[s, ls, sl] = norms_h[rows]
                    cursor = np.minimum(
                        cursor + np.bincount(
                            lb_h, minlength=params.n_lists), L_shard)
            finally:
                pf.close()
            sizes[s] = np.minimum(
                np.bincount(labels_s, minlength=params.n_lists),
                L_shard).astype(np.int32)
            _say(f"shard {s}: encoded rows [{lo}, {hi})")
    if ck is not None:
        ck.write_manifest({**base_manifest, "phase": "done",
                           "L": int(L), "L_shard": int(L_shard),
                           "shard_chunks_done":
                               [len(_chunk_ranges(lo, hi, chunk_rows))
                                for lo, hi in ranges]})
    if dropped:
        from raft_tpu.core import logging as _log
        _log.warn("distributed ivf_pq build: dropped %d overflow vectors "
                  "(raise list_size_cap_factor)", dropped)
    return ShardedIvfPq(
        centers=centers, centers_rot=centers_rot, rotation=rotation,
        codebooks=codebooks, packed_codes=jnp.asarray(packed),
        packed_ids=jnp.asarray(ids), packed_norms=jnp.asarray(pnorm),
        list_sizes=jnp.asarray(sizes), metric=mt.value,
        pq_bits=params.pq_bits, pq_dim=pq_dim, shard_rows=shard_n,
        global_list_cap=int(L))


# ---------------------------------------------------------------------------
# IVF-Flat distributed build (the twin: raw rows instead of codes)
# ---------------------------------------------------------------------------

def build_ivf_flat_distributed(dataset, params, mesh: Mesh,
                               axis: str = "shard",
                               chunk_rows: int = 1 << 18,
                               max_train_rows: int = 1 << 21,
                               prefetch: bool = True,
                               coarse: str = "replicated",
                               progress: bool = False):
    """Distributed chunked IVF-Flat build — the raw-vector twin of
    :func:`build_ivf_pq_distributed` (public entry:
    ``ivf_flat.build_distributed``). Same shard walk and allgatherv-lean
    comms; the per-chunk "encode" is just the row norms, and each shard
    packs its raw f32 rows. Assembly parity with the single-host
    ``ivf_flat.build`` holds while the trainset stays under
    ``max_train_rows`` (the single-host build has no cap)."""
    from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.distance.types import DistanceType, resolve_metric
    from raft_tpu.neighbors import ivf_pq as _pq
    from raft_tpu.neighbors.ivf_flat import _fit_list_size, _lane_round
    from raft_tpu.parallel.ivf import ShardedIvfFlat

    site = "ivf_flat.build_distributed"
    t0 = time.time()

    def _say(msg):
        if progress:
            print(f"[build_distributed +{time.time() - t0:7.0f}s] {msg}",
                  flush=True)

    mt = resolve_metric(params.metric)
    expects(not params.spill,
            "distributed build does not support spill=True yet")
    expects(coarse in ("replicated", "distributed"),
            "coarse must be 'replicated' or 'distributed' (got %r)",
            coarse)
    n, dim = dataset.shape
    expects(params.n_lists <= n, "n_lists=%d > n=%d", params.n_lists, n)
    n_dev = mesh.shape[axis]
    ranges, shard_n = shard_ranges(n, n_dev)
    spherical = mt in (DistanceType.InnerProduct,
                       DistanceType.CosineExpanded)
    km = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                              metric="cosine" if spherical else "l2",
                              seed=params.seed)

    # 1. coarse centers: the exact single-host trainset + trainer
    # (ivf_flat.build's formula) over the allgatherv'd sample
    n_train = min(n, max_train_rows,
                  max(params.n_lists * 4,
                      int(n * params.kmeans_trainset_fraction)))
    rng = np.random.default_rng(params.seed)
    tr_idx = (np.sort(rng.choice(n, n_train, replace=False))
              if n_train < n else np.arange(n))
    with span("train"):
        if coarse == "distributed":
            # sharded psum-Lloyd sample, never replicated (see
            # _coarse_distributed); parity with ivf_flat.build waived
            _say(f"distributed coarse fit over the sharded "
                 f"{n_train}-row sample")
            centers = _coarse_distributed(
                dataset, tr_idx, ranges, mesh, axis, params.n_lists,
                params.kmeans_n_iters, params.seed, spherical,
                normalize=False)
        else:
            _say(f"gathering {n_train} train rows (one allgatherv)")
            trainset = _gather_trainset(dataset, tr_idx, ranges, mesh,
                                        axis, normalize=False)
            centers = kmeans_balanced.fit(trainset, params.n_lists, km)
            del trainset
        jax.block_until_ready(centers)
    _say("coarse centers trained; per-shard label pass")

    # 2. per-shard label pass + the one per-list-count allgatherv
    def predict_fn(xb):
        return kmeans_balanced.predict(centers, xb, km)

    labels_by_shard = []
    local_counts = np.zeros((n_dev, params.n_lists), np.int64)
    for s, (lo, hi) in enumerate(ranges):
        lb = _shard_label_pass(dataset, lo, hi, chunk_rows, predict_fn,
                               prefetch, site, normalize=False)
        labels_by_shard.append(lb)
        local_counts[s] = np.bincount(lb, minlength=params.n_lists)
        _say(f"shard {s}: labeled {hi - lo} rows")
    counts_by_shard = np.asarray(
        gather_list_counts(local_counts, mesh, axis))
    counts = counts_by_shard.sum(axis=0)
    avg = max(1, n // params.n_lists)
    L = _fit_list_size(counts, avg, params.list_size_cap_factor)
    L_shard = min(L, _lane_round(int(max(1, counts_by_shard.max()))))
    n_total_pad = n_dev * shard_n
    id_dt = _ids.np_id_dtype(n_total_pad)

    # 3. per-shard pack of raw rows (prefetched walk; rows never cross).
    # This pass is HOST-ONLY — the labels are already computed, the pack
    # is a host scatter — so the prefetcher's read_fn skips the device
    # round-trip a device chunk would pay for nothing: the reader thread
    # overlaps the raw memmap read (retried at build.chunk_read) under
    # the consumer's host pack of the previous chunk.
    def read_rows_host(a, b):
        return _read_rows(dataset, slice(a, b), "build.chunk_read")

    packed = np.zeros((n_dev, params.n_lists, L_shard, dim), np.float32)
    ids = np.full((n_dev, params.n_lists, L_shard), -1, id_dt)
    sizes = np.zeros((n_dev, params.n_lists), np.int32)
    dropped = 0
    with span("encode_pack"):
        for s, (lo, hi) in enumerate(ranges):
            labels_s = labels_by_shard[s]
            cursor = np.zeros(params.n_lists, np.int64)
            pf = ChunkPrefetcher(read_rows_host,
                                 _chunk_ranges(lo, hi, chunk_rows),
                                 prefetch=prefetch, counter_site=site)
            try:
                for a, b in _chunk_ranges(lo, hi, chunk_rows):
                    rows_h = pf.get()
                    lb_h = labels_s[a - lo:b - lo]
                    order, sorted_l, slot = _pq._stable_slots(
                        lb_h, params.n_lists, cursor)
                    keep = (slot < L_shard) & (sorted_l < params.n_lists)
                    dropped += int((~keep).sum())
                    rows = order[keep]
                    ls, sl = sorted_l[keep], slot[keep].astype(np.int64)
                    packed[s, ls, sl] = rows_h[rows]
                    ids[s, ls, sl] = (a + rows).astype(id_dt)
                    cursor = np.minimum(
                        cursor + np.bincount(
                            lb_h, minlength=params.n_lists), L_shard)
            finally:
                pf.close()
            sizes[s] = np.minimum(
                np.bincount(labels_s, minlength=params.n_lists),
                L_shard).astype(np.int32)
            _say(f"shard {s}: packed rows [{lo}, {hi})")
    if dropped:
        from raft_tpu.core import logging as _log
        _log.warn("distributed ivf_flat build: dropped %d overflow "
                  "vectors (raise list_size_cap_factor)", dropped)
    packed_j = jnp.asarray(packed)
    # norms from the PACKED table (pad slots 0) with the same reduction
    # shape as the single-host build — bit-parity by construction
    norms = jnp.sum(packed_j * packed_j, axis=-1)
    return ShardedIvfFlat(centers=centers, packed_data=packed_j,
                          packed_ids=jnp.asarray(ids),
                          packed_norms=norms,
                          list_sizes=jnp.asarray(sizes),
                          metric=mt.value, global_list_cap=int(L))


# ---------------------------------------------------------------------------
# assembly — the sha-identity bridge to the single-host builders
# ---------------------------------------------------------------------------

def _assemble_lists(sizes: np.ndarray, L_shard: int, L: int):
    """Slot plan for concatenating per-shard list prefixes in rank
    order: returns ``(shard, list, src_slot, dst_slot)`` index arrays,
    truncated at the global capacity ``L`` (the rows a single-host pack
    would have dropped)."""
    n_dev, n_lists = sizes.shape
    base = np.zeros((n_dev, n_lists), np.int64)
    np.cumsum(sizes[:-1], axis=0, out=base[1:])
    slot = np.arange(L_shard)[None, None, :]
    valid = slot < sizes[:, :, None]
    dst = base[:, :, None] + slot
    keep = valid & (dst < L)
    sh, li, src = np.nonzero(keep)
    return sh, li, src, dst[keep]


def assemble_ivf_pq(sharded, cache_reconstruction: str = "never"):
    """Merge a distributed-built :class:`ShardedIvfPq` into the
    single-host :class:`~raft_tpu.neighbors.ivf_pq.IvfPqIndex` —
    bit-identical to ``build_chunked`` over the same dataset/params
    (the layout invariant in the module docstring; the CI mesh asserts
    the sha). Useful when a pod build feeds a single-chip serving
    host."""
    from raft_tpu.neighbors import ivf_pq as _pq

    expects(sharded.global_list_cap > 0,
            "assemble needs a distributed-built index (global_list_cap "
            "is unset on hand-assembled shards)")
    L = int(sharded.global_list_cap)
    sizes = np.asarray(sharded.list_sizes)
    n_dev, n_lists, L_shard = np.asarray(sharded.packed_ids).shape
    nb = np.asarray(sharded.packed_codes).shape[-1]
    sh, li, src, dst = _assemble_lists(sizes, L_shard, L)
    s_codes = np.asarray(sharded.packed_codes)
    s_ids = np.asarray(sharded.packed_ids)
    s_norms = np.asarray(sharded.packed_norms)
    packed = np.zeros((n_lists, L, nb), np.uint8)
    ids = np.full((n_lists, L), -1, _ids.np_id_dtype_like(s_ids))
    pnorm = np.zeros((n_lists, L), np.float32)
    packed[li, dst] = s_codes[sh, li, src]
    ids[li, dst] = s_ids[sh, li, src]
    pnorm[li, dst] = s_norms[sh, li, src]
    list_sizes = np.minimum(sizes.sum(axis=0), L).astype(np.int32)
    # the single-host builder's lane-fold policy, reproduced
    fold = (nb < 128 and packed.nbytes > (1 << 30) and (L * nb) % 128 == 0)
    if fold:
        packed = packed.reshape(n_lists, -1, 128)
    index = _pq.IvfPqIndex(
        centers=sharded.centers, centers_rot=sharded.centers_rot,
        rotation=sharded.rotation, codebooks=sharded.codebooks,
        packed_codes=jnp.asarray(packed), packed_ids=jnp.asarray(ids),
        packed_norms=jnp.asarray(pnorm),
        list_sizes=jnp.asarray(list_sizes), metric=sharded.metric,
        codebook_kind="per_subspace", pq_bits=sharded.pq_bits,
        pq_dim_static=sharded.pq_dim, codes_folded=fold)
    if cache_reconstruction == "always":
        index = index.replace(packed_recon=_pq._build_recon_cache(index))
    return index


def assemble_ivf_flat(sharded):
    """Merge a distributed-built ``ShardedIvfFlat`` into the single-host
    :class:`~raft_tpu.neighbors.ivf_flat.IvfFlatIndex` (bit-identical to
    ``ivf_flat.build`` over the same dataset/params)."""
    from raft_tpu.neighbors import ivf_flat as _flat

    expects(sharded.global_list_cap > 0,
            "assemble needs a distributed-built index (global_list_cap "
            "is unset on hand-assembled shards)")
    L = int(sharded.global_list_cap)
    sizes = np.asarray(sharded.list_sizes)
    n_dev, n_lists, L_shard = np.asarray(sharded.packed_ids).shape
    d = np.asarray(sharded.packed_data).shape[-1]
    sh, li, src, dst = _assemble_lists(sizes, L_shard, L)
    s_data = np.asarray(sharded.packed_data)
    s_ids = np.asarray(sharded.packed_ids)
    packed = np.zeros((n_lists, L, d), s_data.dtype)
    ids = np.full((n_lists, L), -1, _ids.np_id_dtype_like(s_ids))
    packed[li, dst] = s_data[sh, li, src]
    ids[li, dst] = s_ids[sh, li, src]
    list_sizes = np.minimum(sizes.sum(axis=0), L).astype(np.int32)
    packed_j = jnp.asarray(packed)
    return _flat.IvfFlatIndex(
        centers=sharded.centers, packed_data=packed_j,
        packed_ids=jnp.asarray(ids),
        packed_norms=jnp.sum(packed_j.astype(jnp.float32) ** 2, axis=-1),
        list_sizes=jnp.asarray(list_sizes), metric=sharded.metric)


def index_sha16(index) -> str:
    """16-hex content sha over an index's arrays (field-name order) —
    the identity the chaos lane and the dryrun's distributed-vs-
    single-host assertion both hash."""
    import hashlib

    h = hashlib.sha256()
    for name in sorted(f.name for f in index.__dataclass_fields__.values()
                       if f.metadata.get("pytree_node", True)):
        v = getattr(index, name)
        if v is None:
            continue
        h.update(name.encode())
        h.update(np.ascontiguousarray(np.asarray(v)).tobytes())
    return h.hexdigest()[:16]
