"""Cross-shard top-k merge tiers — the single dispatch point behind every
distributed candidate merge (sharded kNN and both sharded IVF searches).

Two tiers return the same global top-k, with very different traffic
(reference: ``knn_merge_parts.cuh`` merged over NCCL in raft-dask):

- **allgather**: every device gathers the full ``[n_dev, m, k]``
  candidate tables over ICI and selects locally — O(n_dev·m·k) bytes
  materialized per device, the original merge. Result is replicated.
- **ring**: reduce-scatter-of-top-k. The query axis splits into n_dev
  chunks; each chunk's partial top-k travels the ring for n_dev−1 hops,
  merged against each device's local candidates on the way, landing
  fully merged at its owner — only the surviving ``[m/n_dev, k]`` block
  ever crosses a link, O(m·k) bytes per device total. Result is
  query-sharded (``P(axis)`` out-specs; callers slice the assembled
  array back to ``[m, k]``). On TPU the hops are the Pallas
  ``ring_topk_merge`` kernel's async remote DMAs; elsewhere (the
  8-device CPU CI mesh) and on sub-axis rings of a multi-axis mesh an
  identical-schedule ``ppermute`` fallback keeps semantics and
  ``comms.ops/bytes{op=ring_topk}`` accounting bit-for-bit comparable.

- **hier** (ISSUE 19): the two-level cross-POD merge for 2-D
  ``(outer=dcn, inner=ici)`` meshes. The inner (ICI) stage is the ring
  tier per pod, exactly as today — the Pallas persistent kernel where
  eligible (:func:`raft_tpu.ops.pallas_kernels.ring_topk_inner_ok`),
  the ppermute schedule elsewhere — leaving each device its pod's
  fully-merged ``[mc, k]`` survivor block. Then only those k survivors
  — never raw candidates — cross DCN once: each device owns a
  ``1/n_outer`` sub-chunk of its pod block and allgathers every pod's
  survivors FOR ITS OWNED ROWS over the outer axis (the sparse
  survivor exchange: one collective, no serial DCN hop chain), selects
  k of ``n_outer·k`` locally. DCN traffic is the k-survivor model —
  ``n_outer · mc_d · k`` entries per device, O(k·pods) — independent
  of how many devices scanned, vs the flat ring's whole
  ``(n_dev−1)·mc·k`` stream pacing on the slow links. Result is
  query-sharded over (inner, outer); callers slice ``[:m]``.

``RAFT_TPU_RING_TOPK`` (auto | on | off, :func:`raft_tpu.obs.env_tristate`)
picks the flat tier; ``RAFT_TPU_HIER_MERGE`` (auto | on | off) gates the
hier tier, auto-on when the caller's 2-D mesh has a DCN-labeled outer
axis (:func:`raft_tpu.parallel.mesh.is_dcn_axis`); explicit ``merge=``
arguments on the search entries override both. Every decision lands in
``parallel.merge.dispatch{impl=...}``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.core.compat import axis_size as _axis_size
from raft_tpu.core.errors import expects
from raft_tpu.core import ids as _ids
from raft_tpu.matrix import select_k as _select_k
from raft_tpu.obs import spans as _obs_spans
from raft_tpu.ops import pallas_kernels as _pk
from raft_tpu.parallel.comms import Comms
from raft_tpu.parallel.mesh import is_dcn_axis

MERGE_TIERS = ("allgather", "ring", "hier")

# (outer_axis, inner_axis, n_outer, n_inner) — the hier tier's static
# topology summary, built by the search entries from their mesh + a
# 2-tuple ``axis`` argument (outer DCN-labeled). None = 1-D exchange.
HierAxes = Tuple[str, str, int, int]


def resolve_exchange(mesh, axis: Union[str, Sequence[str]]
                     ) -> Tuple[int, bool, Optional[HierAxes]]:
    """Normalize a search entry's ``axis`` argument — one mesh axis name
    or a 2-tuple ``(outer, inner)`` — against its mesh. Returns
    ``(n_dev, whole_mesh, hier_axes)``: device count of the exchange,
    whether it spans the whole mesh as ONE named axis (the flat ring
    kernel's logical-id addressing requirement), and the hier topology
    summary when the tuple's outer axis is DCN-labeled (None otherwise —
    flat tiers still serve DCN-unlabeled tuples, they just never
    auto-escalate to hier)."""
    if isinstance(axis, str):
        n_dev = mesh.shape[axis]
        return n_dev, n_dev == mesh.devices.size, None
    names = tuple(axis)
    expects(len(names) == 2,
            "axis must be one mesh axis name or a 2-tuple "
            "(outer, inner), got %r", axis)
    outer, inner = names
    n_outer, n_inner = mesh.shape[outer], mesh.shape[inner]
    hier = (outer, inner, n_outer, n_inner) if is_dcn_axis(outer) else None
    return n_outer * n_inner, False, hier


def ring_auto_wanted(m: int, k: int, n_dev: int) -> bool:
    """Auto-mode shape gate: take the ring only where it actually wins.
    The ring ships (n_dev−1) sublane-padded ``[mc, k]`` blocks over
    n_dev−1 SERIAL hops vs the allgather's one collective of
    n_dev·[m, k]; for tiny query batches the mc=8 row padding makes the
    ring ship MORE bytes and the hop chain is pure added latency.
    Require the ring's counted bytes to be ≤ half the allgather's (the
    same ≥2× bar the scaling CI asserts) before auto prefers it."""
    mc = _pk.ring_chunk_rows(m, n_dev)
    return 2 * (n_dev - 1) * mc <= n_dev * m


def hier_chunk_rows(m: int, n_inner: int, n_outer: int) -> int:
    """Per-device query-chunk rows of the hier tier's inner (per-pod)
    ring: the flat ring's sublane-padded chunk for ``n_inner`` devices,
    padded up so the outer survivor exchange splits it into ``n_outer``
    even sub-chunks."""
    mc = _pk.ring_chunk_rows(m, n_inner)
    return -(-mc // n_outer) * n_outer


def merge_tier(n_dev: int, m: int, k: int,
               explicit: Optional[str] = None,
               whole_mesh: bool = True,
               hier_axes: Optional[HierAxes] = None) -> Tuple[str, str]:
    """Pick the merge tier + implementation for one sharded search call.

    ``explicit`` (a search entry's ``merge=`` argument, "auto" = defer)
    overrides the ``RAFT_TPU_RING_TOPK`` tri-state; auto mode takes the
    ring tier on TPU when the kernel can serve the shape AND the shape
    is bandwidth-bound enough to win (:func:`ring_auto_wanted` —
    small/latency-bound batches keep the single allgather). The kernel
    addresses neighbors by logical device id, so it needs the exchange
    axis to be the ``whole_mesh``; sub-axis rings and non-TPU backends
    ride the ppermute fallback. Returns ``(tier, impl)`` with impl ∈
    {allgather, ring_kernel, ring_ppermute, hier}; counted per decision
    under ``parallel.merge.dispatch{impl=...}``.

    ``hier_axes`` (set by a search entry called with a 2-tuple
    ``axis`` whose outer axis is DCN-labeled) enables the hier tier:
    taken on ``merge="hier"`` or, under auto, whenever present unless
    ``RAFT_TPU_HIER_MERGE=off`` — a topology honest enough to name its
    slow axis should never flat-merge across it by default."""
    hier_force = _obs_spans.env_tristate("RAFT_TPU_HIER_MERGE")
    if explicit == "hier":
        expects(hier_axes is not None,
                "merge='hier' needs a 2-D (outer, inner) exchange: call "
                "the search with axis=(dcn_axis, ici_axis) over a "
                "hier_mesh-shaped mesh (DCN-labeled outer axis)")
    if hier_axes is not None and (
            explicit == "hier"
            or (explicit in (None, "auto") and hier_force != "off")):
        _obs_spans.count_dispatch("parallel.merge", "hier")
        return "hier", "hier"
    if hier_axes is None and hier_force == "on" \
            and explicit in (None, "auto"):
        # env asked for hier but the exchange is 1-D — fall through to
        # the flat tiers, visibly
        _obs_spans.count_fallback("parallel.merge", "no_hier_axes")
    force = _obs_spans.env_tristate("RAFT_TPU_RING_TOPK")
    kernel_ok = (_pk._on_tpu() and whole_mesh
                 and _pk.ring_topk_kernel_ok(m, k, n_dev))
    if explicit is not None and explicit != "auto":
        expects(explicit in MERGE_TIERS,
                "unknown merge tier %r (supported: %s)", explicit,
                "/".join(MERGE_TIERS))
        tier = explicit
    elif force == "off":
        tier = "allgather"
    elif force == "on":
        tier = "ring"
    else:
        tier = ("ring" if kernel_ok and ring_auto_wanted(m, k, n_dev)
                else "allgather")
        if _pk._on_tpu() and tier == "allgather" and n_dev > 1:
            _obs_spans.count_fallback(
                "parallel.merge",
                "latency_bound" if kernel_ok else "kernel_ineligible")
    impl = "allgather"
    if tier == "ring":
        impl = "ring_kernel" if kernel_ok else "ring_ppermute"
    _obs_spans.count_dispatch("parallel.merge", impl)
    return tier, impl


def merge_out_spec(tier: str, axis: Union[str, Sequence[str]]) -> P:
    """shard_map out-spec for one merged output: the allgather tier
    replicates, the ring tier leaves results query-sharded, the hier
    tier leaves them sharded over (inner, outer) — device (d, i) owns
    sub-chunk d of inner chunk i, so the assembled padded query order
    is exactly the flat one and callers still slice ``[:m]``."""
    if tier == "hier":
        outer, inner = axis
        return P((inner, outer), None)
    return P() if tier == "allgather" else P(axis, None)


def merged_rows(tier: str, m: int, n_dev: int, n_outer: int = 1) -> int:
    """Global row count of the assembled merge result (the ring tier
    pads the query axis to n_dev chunks of sublane-tiled rows; pad rows
    sit at the END, so callers slice ``[:m]``). For the hier tier pass
    ``n_dev`` = the INNER axis size and ``n_outer`` = the pod count."""
    if tier == "allgather":
        return m
    if tier == "hier":
        return hier_chunk_rows(m, n_dev, n_outer) * n_dev
    return _pk.ring_chunk_rows(m, n_dev) * n_dev


def _merge_allgather(vals, ids, comms, m: int, k: int, n_dev: int,
                     select_min: bool):
    """All-gather the per-shard tables, select locally (the original
    merge; reference: knn_merge_parts.cuh)."""
    all_v = comms.allgather(vals)               # [n_dev, m, k]
    all_i = comms.allgather(ids)
    flat_v = jnp.transpose(all_v, (1, 0, 2)).reshape(m, n_dev * k)
    flat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(m, n_dev * k)
    return _select_k(flat_v, k, select_min=select_min, input_indices=flat_i)


def _ring_merge_fallback(vals, ids, comms, axis, m: int, k: int,
                         n_dev: int, select_min: bool,
                         mc: Optional[int] = None):
    """The ppermute ring — the kernel's schedule, collective by
    collective: device ``i`` launches chunk ``(i−1) mod n_dev``'s
    partial, ships its running block right each hop, and merges the
    incoming partial with its local block for that chunk; after
    n_dev−1 hops device ``i`` owns chunk ``i`` fully merged. ``mc``
    overrides the chunk rows (the hier tier's outer-divisible pad)."""
    if mc is None:
        mc = _pk.ring_chunk_rows(m, n_dev)
    m_pad = mc * n_dev
    big = jnp.inf if select_min else -jnp.inf
    v = vals.astype(jnp.float32)
    # id width rides the policy (core.ids): an int64 billion-scale id
    # table must not truncate through the merge
    i = ids.astype(_ids.id_dtype_like(ids))
    if m_pad > m:
        v = jnp.pad(v, ((0, m_pad - m), (0, 0)), constant_values=big)
        i = jnp.pad(i, ((0, m_pad - m), (0, 0)), constant_values=-1)
    v = jnp.where(i < 0, big, v)  # uniform invalid sentinel (kernel parity)
    v3 = v.reshape(n_dev, mc, k)
    i3 = i.reshape(n_dev, mc, k)
    rank = comms.get_rank()
    c0 = jax.lax.rem(rank + n_dev - 1, n_dev)
    run_v = jax.lax.dynamic_index_in_dim(v3, c0, 0, keepdims=False)
    run_i = jax.lax.dynamic_index_in_dim(i3, c0, 0, keepdims=False)
    for s in range(n_dev - 1):
        run_v, run_i = comms.ring_topk_hop(run_v, run_i)
        c = jax.lax.rem(rank + 2 * n_dev - s - 2, n_dev)
        loc_v = jax.lax.dynamic_index_in_dim(v3, c, 0, keepdims=False)
        loc_i = jax.lax.dynamic_index_in_dim(i3, c, 0, keepdims=False)
        cat_v = jnp.concatenate([run_v, loc_v], axis=1)
        cat_i = jnp.concatenate([run_i, loc_i], axis=1)
        run_v, run_i = _select_k(cat_v, k, select_min=select_min,
                                 input_indices=cat_i)
    return run_v, run_i


def _merge_hier(vals, ids, outer: str, inner: str, m: int, k: int,
                select_min: bool, interpret: bool = False):
    """Two-level merge (ISSUE 19) — per-pod ring over ``inner`` (ICI),
    then ONE sparse survivor allgather over ``outer`` (DCN).

    Inner stage: the flat ring tier confined to this pod — the Pallas
    persistent kernel when the inner axis is eligible
    (:func:`~raft_tpu.ops.pallas_kernels.ring_topk_inner_ok`), the
    identical-schedule ppermute fallback otherwise — leaving each
    device its pod's fully-merged ``[mc, k]`` survivor block for its
    owned query chunk, ``mc`` padded so ``n_outer`` divides it.

    Outer stage: counterpart devices across pods hold the SAME query
    chunk, so each device takes ownership of ``mc_d = mc/n_outer`` of
    those rows and ONE all-to-all over the DCN axis ships pod ``e``'s
    sub-chunk ``f`` to outer-rank ``f`` — after the exchange this
    device holds every pod's k survivors (never raw candidates) for
    its owned rows, and selects k of ``n_outer·k`` locally. Counted
    ``op=alltoall, axis=<outer>``: ``mc·k`` entries per device =
    ``n_outer · mc_d · k``, the O(k·pods) k-survivor byte model the
    scaling CI asserts against the flat ring's stream.

    Each stage rides its own single-axis sub-communicator, so the
    per-axis ``comms.bytes{axis=ici|dcn}`` attribution falls out of the
    facade with no special casing."""
    inner_c = Comms(inner)
    outer_c = Comms(outer)
    n_inner = int(_axis_size(inner))
    n_outer = int(_axis_size(outer))
    mc = hier_chunk_rows(m, n_inner, n_outer)
    kernel_ok = (_pk._on_tpu()
                 and _pk.ring_topk_inner_ok(m, k, n_inner)
                 and mc == _pk.ring_chunk_rows(m, n_inner)
                 and jnp.dtype(ids.dtype).itemsize < 8)
    if kernel_ok:
        inner_c.count_ring_topk(
            n_inner - 1,
            jax.ShapeDtypeStruct((mc, k), jnp.float32),
            jax.ShapeDtypeStruct((mc, k), jnp.int32))
        pv, pi = _pk.ring_topk_merge(vals, ids, k, inner, n_inner,
                                     select_min, interpret=interpret,
                                     outer_axis=outer)
    else:
        pv, pi = _ring_merge_fallback(vals, ids, inner_c, inner, m, k,
                                      n_inner, select_min, mc=mc)
    mc_d = mc // n_outer
    # survivor exchange: one all-to-all over DCN — pod e's sub-chunk f
    # moves to outer-rank f, so this device receives every pod's
    # survivors for ITS sub-chunk d (row-block e of the result = pod
    # e's rows [d·mc_d, (d+1)·mc_d) of the pod-merged chunk)
    ex_v = outer_c.alltoall(pv).reshape(n_outer, mc_d, k)
    ex_i = outer_c.alltoall(pi).reshape(n_outer, mc_d, k)
    flat_v = jnp.transpose(ex_v, (1, 0, 2)).reshape(mc_d, n_outer * k)
    flat_i = jnp.transpose(ex_i, (1, 0, 2)).reshape(mc_d, n_outer * k)
    return _select_k(flat_v, k, select_min=select_min, input_indices=flat_i)


def merge_topk(vals: jax.Array, ids: jax.Array,
               axis: Union[str, Sequence[str]], m: int, k: int,
               n_dev: int, select_min: bool, tier: str = "allgather",
               impl: Optional[str] = None, interpret: bool = False
               ) -> Tuple[jax.Array, jax.Array]:
    """Cross-shard candidate merge — runs INSIDE ``shard_map`` over
    ``axis``. ``vals``/``ids`` [m, k] are this shard's local top-k
    (global ids, -1 invalid, invalid keys at the select sentinel).

    The allgather tier returns the replicated [m, k] result; the ring
    tier returns this device's owned query chunk; the hier tier (2-D
    ``axis=(outer, inner)``) its owned (inner-chunk, outer-sub-chunk)
    block (pair with :func:`merge_out_spec` / :func:`merged_rows`).
    All traffic rides the ``Comms`` facade — allgather merges count the
    materialized table, ring merges count n_dev−1 surviving-block hops
    under ``op=ring_topk``, hier merges count the per-pod ring on the
    inner axis plus one survivor allgather on the outer — so the tiers'
    merge-phase bytes are directly comparable in ``comms.bytes`` (the
    dryrun's scaling assertions)."""
    expects(tier in MERGE_TIERS, "unknown merge tier %r", tier)
    expects(vals.shape == (m, k) and ids.shape == (m, k),
            "merge_topk expects [m, k] local tables (got %s/%s for "
            "m=%d k=%d)", vals.shape, ids.shape, m, k)
    if tier == "hier":
        expects(not isinstance(axis, str) and len(tuple(axis)) == 2,
                "hier merge needs axis=(outer, inner), got %r", axis)
        outer, inner = axis
        return _merge_hier(vals, ids, outer, inner, m, k, select_min,
                           interpret=interpret)
    comms = Comms(axis)
    if tier == "allgather":
        return _merge_allgather(vals, ids, comms, m, k, n_dev, select_min)
    if impl == "ring_kernel" and jnp.dtype(ids.dtype).itemsize >= 8:
        # the Pallas kernel is int32-only by construction; an int64
        # billion-scale id table rides the identical-schedule ppermute
        # fallback instead of silently truncating through the kernel
        _obs_spans.count_fallback("parallel.merge", "id_width")
        impl = "ring_ppermute"
    if impl == "ring_kernel":
        mc = _pk.ring_chunk_rows(m, n_dev)
        # the kernel's remote DMAs bypass lax: attribute its hop traffic
        # through the facade at trace time (GL10's telemetry invariant).
        # Counted at the LOGICAL [mc, k] block — the facade-wide
        # convention (every verb counts shape × itemsize): physically
        # the kernel ships lane-padded [mc, 128] buffers, exactly as
        # XLA's tiled layout pads the allgather tier's [m, k] tables,
        # so the tier-vs-tier comparison stays like-for-like
        comms.count_ring_topk(
            n_dev - 1,
            jax.ShapeDtypeStruct((mc, k), jnp.float32),
            jax.ShapeDtypeStruct((mc, k), jnp.int32))
        return _pk.ring_topk_merge(vals, ids, k, axis, n_dev, select_min,
                                   interpret=interpret)
    return _ring_merge_fallback(vals, ids, comms, axis, m, k, n_dev,
                                select_min)
